"""Physical executor: walks the logical plan and produces device Tables.

Role parity: reference RelConverter.convert (physical/rel/convert.py:39
there) driven by Context._compute_table_from_rel (context.py:874).  The
registry maps node-type strings to plugins exactly like the reference's
Pluggable registries; execution is eager per node (XLA async dispatch under
the hood), with the distributed path swapping sharded kernels in via
`parallel/`.
"""
from __future__ import annotations

from typing import Dict, Optional

from ..columnar.table import Table
from ..planner.plan import LogicalPlan
from ..serving.runtime import current_ticket
from .rel.base import BaseRelPlugin
from .rex.convert import RexConverter


class Executor:
    _plugins: Dict[str, BaseRelPlugin] = {}

    def __init__(self, context, trace: bool = False):
        self.context = context
        self.rex = RexConverter(self)
        self._memo: Dict[int, Table] = {}
        from ..tracing import Tracer

        self.tracer = Tracer()
        if trace:
            self.tracer.start()
        #: (schema, table) -> Table substitutions (streaming batch execution)
        self.table_overrides: Dict[tuple, Table] = {}
        #: id(streamable node) -> StreamDecision for THIS execution
        #: (streaming/): the admission gate's routing verdict travels here
        #: — per-execution state, never on the shared cached plan object,
        #: so concurrent executions under different budgets cannot race
        self.stream_decisions: Dict[int, object] = {}

    @classmethod
    def add_plugin_class(cls, plugin_class):
        plugin = plugin_class()
        cls._plugins[plugin.class_name] = plugin
        return plugin_class

    def execute_root(self, rel: LogicalPlan) -> Table:
        """Entry for the plan ROOT: the result goes straight to the host, so
        root select chains compile to one kernel + one packed transfer
        (physical/compiled_select.py) before the recursive converter runs.
        Compressed-domain scans (columnar/encodings.py) late-materialize
        here: the compiled paths keep DICT/FOR codes end-to-end and decode
        only survivors at the root / d2h boundary, while the interpreted
        walk below decodes once at its TableScan.

        Resilience (resilience/ladder.py): the compiled fast path is a
        degradation-ladder rung — a compile failure or device OOM inside it
        steps down to the interpreted walk (recorded in the metrics registry
        and gated by the per-plan circuit breaker) instead of failing the
        query; the interpreted walk itself carries one CPU-backend rung
        under it.  The `execute` fault-injection site fires here so the
        ServingRuntime's retry/backoff path is testable end to end."""
        from ..resilience import faults, ladder
        from ..spmd import try_spmd_select
        from .compiled_predict import root_has_predict, try_compiled_predict
        from .compiled_select import try_compiled_select

        ticket = current_ticket()
        if ticket is not None:  # checkpoint before the one-kernel fast path
            ticket.checkpoint()
        faults.maybe_inject("execute", self.config)
        # cheap pre-check (same gate AggregatePlugin uses): the SPMD rung is
        # only worth attempting — plan extraction, table lookups, sharding
        # probes — when the subtree actually scans a mesh-sharded table
        from ..parallel.dist_plan import plan_has_sharded_scan

        sharded = plan_has_sharded_scan(rel, self.context)
        # admission-routed streamed select (streaming/, this execution's
        # stream_decisions entry): a provably-oversize root chain serves as
        # N pipelined chunk launches instead of being shed — its own
        # (family, rung) breaker entity, stepping down to the single-launch
        # rungs below
        streamed_mark = id(rel) in self.stream_decisions
        # fused PREDICT (physical/compiled_predict.py): a root
        # PredictModelNode whose input is a compilable select chain runs
        # model inference in the SAME executable as the scan — its own
        # (family, compiled_predict) breaker entity, stepping down to the
        # host predict path (PredictModelPlugin) below
        predict_root = root_has_predict(rel)
        if self.config.get("resilience.ladder.enabled", True):
            if predict_root:
                out = ladder.attempt(
                    self, "compiled_predict",
                    lambda: try_compiled_predict(rel, self),
                    rel=rel, inject_site="predict")
                if out is not None:
                    return out
            if streamed_mark:
                from ..streaming import try_streamed_select

                out = ladder.attempt(
                    self, "streamed_select",
                    lambda: try_streamed_select(rel, self), rel=rel)
                if out is not None:
                    return out
            if sharded:
                # the SPMD rung sits above the single-chip one (which
                # declines sharded tables); its failures degrade and
                # breaker-charge per (family, spmd_select) without
                # poisoning the family's single-chip rung
                out = ladder.attempt(
                    self, "spmd_select",
                    lambda: try_spmd_select(rel, self),
                    rel=rel, inject_site="spmd")
                if out is not None:
                    return out
            out = ladder.attempt(
                self, "compiled_select",
                lambda: try_compiled_select(rel, self),
                rel=rel, inject_site="compile")
            if out is not None:
                return out
            return ladder.execute_interpreted(self, rel)
        # ladder disabled: injection sites still fire (a forced compile
        # fault must propagate here — that is what disabling proves)
        if predict_root:
            faults.maybe_inject("predict", self.config)
            out = try_compiled_predict(rel, self)
            if out is not None:
                return out
        if streamed_mark:
            from ..streaming import try_streamed_select

            out = try_streamed_select(rel, self)
            if out is not None:
                return out
        if sharded:
            faults.maybe_inject("spmd", self.config)
            out = try_spmd_select(rel, self)
            if out is not None:
                return out
        faults.maybe_inject("compile", self.config)
        out = try_compiled_select(rel, self)
        if out is not None:
            return out
        faults.maybe_inject("exec_oom", self.config)
        return self.execute(rel)

    def execute(self, rel: LogicalPlan) -> Table:
        # cooperative cancellation checkpoint: a query past its serving
        # deadline (or cancelled by the client) raises here, between plan
        # nodes, instead of holding a worker until the full plan finishes
        ticket = current_ticket()
        if ticket is not None:
            ticket.checkpoint()
        key = id(rel)
        if key in self._memo:
            return self._memo[key]
        plugin = self._plugins.get(rel.node_type)
        if plugin is None:
            raise NotImplementedError(f"No rel plugin for node type {rel.node_type!r}")
        if self.tracer.enabled:
            with self.tracer.node(rel) as ctx:
                out = plugin.convert(rel, self)
                ctx.rows = out.num_rows
        else:
            out = plugin.convert(rel, self)
        self._memo[key] = out
        return out

    # -- services for plugins ----------------------------------------------
    def eval_expr(self, expr, table: Table):
        return self.rex.convert(expr, table)

    def lookup_function(self, name: str):
        fd = self.context.lookup_function(name)
        if fd is None:
            raise KeyError(f"Function {name!r} not registered")
        return fd

    def get_table(self, schema_name: str, table_name: str) -> Table:
        return self.context.get_table_data(schema_name, table_name)

    @property
    def config(self):
        return self.context.config
