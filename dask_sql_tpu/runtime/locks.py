"""Runtime lock sanitizer: named, ranked locks with lockdep-style
acquisition-order tracking (ISSUE 19, runtime tier).

The static tier (analysis/concurrency.py, rules DSQL601-603) proves lock
ordering over the AST; this module proves the same invariant over the
*executed* schedule, the way the kernel's lockdep does: every sanitized
lock carries a stable NAME (a class of locks, not an instance — all
replicas' state locks share "fleet.replica.state") and an optional RANK,
each thread keeps a stack of the sanitized locks it holds, and every
blocking acquisition

- checks the declared ranks: taking a lock whose rank is LOWER than a
  lock already held is an inversion (`LockOrderError(kind="rank")`);
- checks the process-global order graph: if the name being acquired can
  already reach the innermost held name, the new edge would close a
  cycle (`LockOrderError(kind="cycle")`) — the error carries BOTH
  witness stacks: this thread's acquisition stack and the recorded
  stack of the first thread that took the edge the other way round;
- records the edge (innermost held -> acquired) with the first witness
  stack, so later reversals can be reported with evidence.

The check runs BEFORE the blocking acquire, so a deliberate inversion in
a test raises instead of deadlocking.  Violations also increment
``analysis.locks.order_violation`` (when a metrics registry is attached)
and record a ``lock.order_violation`` flight event, which the chaos
campaigns (resilience/chaos.py) assert stays at zero.

Deliberate non-checks, each load-bearing:

- **disabled by default** (config ``analysis.lock_sanitizer``; the test
  suite turns it on in tests/conftest.py) — when disabled a NamedLock is
  a plain pass-through with no per-acquire bookkeeping;
- **non-blocking acquires skip the checks** (they cannot deadlock, and
  ``threading.Condition``'s ``_is_owned`` fallback probes
  ``acquire(False)`` on a lock the thread already holds — that probe
  must return False, not raise); they still push/pop the held stack so
  nesting seen *through* them stays visible;
- **same-name pairs are skipped** in the edge/cycle logic: two replicas'
  "fleet.replica.state" locks are distinct objects whose nesting is
  ordered by the router, and a name-level self-edge would be a false
  positive.  Re-acquiring the SAME OBJECT is still caught: a reentrant
  NamedLock bumps its hold depth, a plain one raises
  ``LockOrderError(kind="self-deadlock")`` instead of hanging;
- **violation reporting is recursion-guarded**: flight/metrics use
  NamedLocks themselves, so while the sanitizer is reporting (or
  checking) the per-thread ``in_sanitizer`` flag makes inner acquires
  skip their own checks.

Declared rank order (lower = acquired first = outer; the full table with
the justification per edge lives in docs/analysis.md "Lock ranks"):

====  ==========================  ==========================================
rank  name                        owner
====  ==========================  ==========================================
 10   fleet.router.apply          fleet/router.py write fan-out + promote
 20   fleet.router.state          fleet/router.py membership/epochs
 30   fleet.replica.state         fleet/replica.py lifecycle state
 32   fleet.replica.write         fleet/replica.py write fence + apply
 40   serving.runtime.cv          serving/runtime.py scheduler condition
 45   serving.admission           serving/admission.py ledger
 50   families.batcher            families/batcher.py rendezvous
 55   context.plan_cache          context.py plan/catalog caches
 70   inference.registry          inference/registry.py publish lock
 90   serving.metrics             serving/metrics.py counters (leaf)
 95   observability.flight        observability/flight.py ring (leaf)
====  ==========================  ==========================================
"""
from __future__ import annotations

import sys
import threading
import traceback
from typing import Any, Dict, List, Optional, Tuple

#: The canonical rank table (outer/first-acquired = low).  ``named_lock``
#: and ``named_condition`` resolve ranks here so every call site shares
#: one source of truth; docs/analysis.md mirrors this table.
DECLARED_RANKS: Dict[str, int] = {
    "fleet.router.apply": 10,
    "fleet.router.state": 20,
    "fleet.replica.state": 30,
    "fleet.replica.write": 32,
    "serving.runtime.cv": 40,
    "serving.admission": 45,
    "families.batcher": 50,
    "context.plan_cache": 55,
    "inference.registry": 70,
    "serving.metrics": 90,
    "observability.flight": 95,
}

_MAX_VIOLATIONS_KEPT = 100
_STACK_LIMIT = 24


class LockOrderError(RuntimeError):
    """A lock-order violation caught before the acquire blocked.

    Attributes: ``kind`` ("rank" | "cycle" | "self-deadlock"),
    ``holding`` / ``acquiring`` (lock names), and ``witness`` — the
    formatted evidence: this thread's acquisition stack plus, for
    cycles, the recorded stack of the edge taken the other way.
    """

    def __init__(self, message: str, *, kind: str, holding: str,
                 acquiring: str, witness: str):
        super().__init__(message + "\n" + witness)
        self.kind = kind
        self.holding = holding
        self.acquiring = acquiring
        self.witness = witness


# ---------------------------------------------------------------------------
# module state
# ---------------------------------------------------------------------------
#: raw lock guarding the order graph / registry — deliberately NOT a
#: NamedLock (the sanitizer cannot sanitize itself) and never held while
#: calling out of this module
_state_lock = threading.Lock()
_ranks: Dict[str, Optional[int]] = {}
#: order graph: holder name -> acquired name -> first-witness record
_graph: Dict[str, Dict[str, Dict[str, Any]]] = {}
_violations: List[Dict[str, Any]] = []
_violation_total = 0
_enabled = False
_metrics = None
_tls = threading.local()


def set_enabled(flag: bool) -> None:
    """Turn the sanitizer on/off process-wide (config
    ``analysis.lock_sanitizer``; Context only ever turns it ON so one
    opted-in context cannot be disarmed by a later default one)."""
    global _enabled
    _enabled = bool(flag)


def enabled() -> bool:
    return _enabled


def attach_metrics(metrics) -> None:
    """Point violation counters at a MetricsRegistry (Context wires its
    own in __init__); last attach wins, which is what tests want."""
    global _metrics
    _metrics = metrics
    try:
        _metrics.gauge("analysis.locks.registered", float(len(_ranks)))
    except Exception:  # dsql: allow-broad-except — advisory gauge only
        pass


def violation_count() -> int:
    """Monotonic count of violations since process start (or `reset`) —
    the chaos campaigns snapshot this before/after a storm."""
    return _violation_total


def violations() -> List[Dict[str, Any]]:
    with _state_lock:
        return list(_violations)


def snapshot() -> Dict[str, Any]:
    """Debug/readout view: registered names+ranks, observed edges, and
    the violation tally."""
    with _state_lock:
        edges = [
            {"from": a, "to": b, "count": rec["count"]}
            for a, nbrs in sorted(_graph.items())
            for b, rec in sorted(nbrs.items())
        ]
        return {
            "enabled": _enabled,
            "locks": dict(sorted(_ranks.items())),
            "edges": edges,
            "violations": _violation_total,
        }


def reset() -> None:
    """Clear the order graph, registry, and violation tally (tests)."""
    global _violation_total
    with _state_lock:
        _ranks.clear()
        _graph.clear()
        _violations.clear()
        _violation_total = 0


def _held_stack() -> List[List[Any]]:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


def _in_sanitizer() -> bool:
    return getattr(_tls, "in_sanitizer", False)


def _register(name: str, rank: Optional[int]) -> None:
    with _state_lock:
        prev = _ranks.get(name, None)
        if name in _ranks and prev is not None and rank is not None \
                and prev != rank:
            raise ValueError(
                f"lock name {name!r} re-registered with rank {rank} "
                f"(already declared rank {prev}); ranks are per-NAME, "
                f"fix the DECLARED_RANKS table")
        if name not in _ranks or (prev is None and rank is not None):
            _ranks[name] = rank
    if _metrics is not None:
        try:
            _metrics.gauge("analysis.locks.registered", float(len(_ranks)))
        except Exception:  # dsql: allow-broad-except — advisory gauge only
            pass


def _caller_site() -> str:
    """file:line of the frame that called acquire (cheap single-frame
    capture for held-stack entries; full stacks only on first-seen edges
    and violations)."""
    f = sys._getframe(1)
    this = __file__
    while f is not None and f.f_code.co_filename == this:
        f = f.f_back
    if f is None:
        return "<unknown>"
    return f"{f.f_code.co_filename}:{f.f_lineno} in {f.f_code.co_name}"


def _format_stack() -> str:
    frames = traceback.format_stack(limit=_STACK_LIMIT)
    # drop the sanitizer's own frames from the tail for readable evidence
    return "".join(fr for fr in frames if __file__ not in fr) or \
        "".join(frames)


def _reachable(src: str, dst: str) -> bool:
    """True when dst is reachable from src in the order graph (caller
    holds _state_lock)."""
    seen = {src}
    frontier = [src]
    while frontier:
        node = frontier.pop()
        if node == dst:
            return True
        for nxt in _graph.get(node, ()):
            if nxt not in seen:
                seen.add(nxt)
                frontier.append(nxt)
    return False


def _cycle_path(src: str, dst: str) -> List[Tuple[str, str]]:
    """One witness path src -> ... -> dst as a list of edges (caller
    holds _state_lock); [] when none."""
    parent: Dict[str, str] = {}
    frontier = [src]
    seen = {src}
    while frontier:
        node = frontier.pop(0)
        if node == dst:
            path: List[Tuple[str, str]] = []
            while node != src:
                path.append((parent[node], node))
                node = parent[node]
            path.reverse()
            return path
        for nxt in _graph.get(node, ()):
            if nxt not in seen:
                seen.add(nxt)
                parent[nxt] = node
                frontier.append(nxt)
    return []


def _report(kind: str, holding: str, acquiring: str, witness: str,
            message: str) -> LockOrderError:
    """Record a violation (tally, bounded detail list, metric, flight
    event) and build the structured error for the caller to raise."""
    global _violation_total
    detail = {
        "kind": kind,
        "holding": holding,
        "acquiring": acquiring,
        "thread": threading.current_thread().name,
        "witness": witness,
    }
    with _state_lock:
        _violation_total += 1
        _violations.append(detail)
        del _violations[:-_MAX_VIOLATIONS_KEPT]
    if _metrics is not None:
        try:
            _metrics.inc("analysis.locks.order_violation")
        except Exception:  # dsql: allow-broad-except — reporting must not mask the violation
            pass
    try:
        from ..observability import flight

        flight.record("lock.order_violation", kind=kind, holding=holding,
                      acquiring=acquiring,
                      thread=threading.current_thread().name)
    except Exception:  # dsql: allow-broad-except — reporting must not mask the violation
        pass
    return LockOrderError(message, kind=kind, holding=holding,
                          acquiring=acquiring, witness=witness)


class NamedLock:
    """A ``threading.Lock``/``RLock`` wrapper registered with the
    sanitizer under a stable name (a lock *class*, lockdep-style) and an
    optional rank.  Context-manager protocol, ``acquire(blocking,
    timeout)`` and ``release()`` match the stdlib locks, so it drops in
    anywhere a raw lock lived — including as the underlying lock of a
    ``threading.Condition`` (see `named_condition`)."""

    __slots__ = ("name", "rank", "_inner", "_reentrant")

    def __init__(self, name: str, rank: Optional[int] = None,
                 reentrant: bool = False):
        self.name = name
        self.rank = rank
        self._reentrant = reentrant
        self._inner = threading.RLock() if reentrant else threading.Lock()
        _register(name, rank)

    def __repr__(self) -> str:
        kind = "RLock" if self._reentrant else "Lock"
        return f"<NamedLock {self.name!r} rank={self.rank} {kind}>"

    # ------------------------------------------------------------- acquire
    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if not _enabled:
            return self._inner.acquire(blocking, timeout)
        try:
            held = _tls.stack
        except AttributeError:
            held = _tls.stack = []
        if not held:
            # fast path: nothing held, nothing to check (a metrics/flight
            # leaf taken at top level — the overwhelmingly common case)
            ok = self._inner.acquire(blocking, timeout)
            if ok:
                held.append([self, 1, None])
            return ok
        entry = None
        for e in held:
            if e[0] is self:
                entry = e
                break
        if entry is not None:
            if self._reentrant:
                ok = self._inner.acquire(blocking, timeout)
                if ok:
                    entry[1] += 1
                return ok
            if blocking and not _in_sanitizer():
                _tls.in_sanitizer = True
                try:
                    raise self._self_deadlock(entry)
                finally:
                    _tls.in_sanitizer = False
            # non-blocking probe of a lock this thread holds (Condition's
            # _is_owned fallback): report False, never raise
            return self._inner.acquire(False)
        if blocking and not getattr(_tls, "in_sanitizer", False):
            _tls.in_sanitizer = True
            try:
                self._check(held)
            finally:
                _tls.in_sanitizer = False
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            held.append([self, 1, _caller_site()])
        return ok

    def _self_deadlock(self, entry) -> LockOrderError:
        witness = (f"first acquired at: {entry[2] or '<outermost>'}\n"
                   f"re-acquired at:\n{_format_stack()}")
        return _report(
            "self-deadlock", self.name, self.name, witness,
            f"thread {threading.current_thread().name!r} re-acquired "
            f"non-reentrant lock {self.name!r} it already holds")

    def _check(self, held) -> None:
        """Rank + cycle check against the held stack; raises
        LockOrderError BEFORE the blocking acquire on violation.  Called
        with the in_sanitizer flag set (so flight/metrics NamedLocks
        used while reporting skip their own checks)."""
        # one pass: filter same-name siblings (cross-instance fan-out),
        # rank-check each survivor, remember the innermost as `top`
        my_rank = self.rank
        top_entry = None
        for e in held:
            h = e[0]
            if h.name == self.name:
                continue
            top_entry = e
            if my_rank is not None and h.rank is not None \
                    and my_rank < h.rank:
                witness = (
                    f"held {h.name!r} (rank {h.rank}) acquired at: "
                    f"{e[2] or '<outermost>'}\n"
                    f"acquiring {self.name!r} (rank {my_rank}) "
                    f"at:\n{_format_stack()}")
                raise _report(
                    "rank", h.name, self.name, witness,
                    f"rank inversion: acquiring {self.name!r} "
                    f"(rank {my_rank}) while holding {h.name!r} "
                    f"(rank {h.rank}); declared order is "
                    f"low-rank-first")
        if top_entry is None:
            return  # only same-name siblings held
        top = top_entry[0]
        # optimistic lock-free fast path: this exact edge is already
        # recorded AND the acquiring name has no outgoing edges (so no
        # path back to any holder can exist) — bump the advisory count
        # without touching _state_lock.  GIL-atomic dict reads make the
        # probe safe; a NEW edge that could close a cycle always goes
        # through the locked slow path below and is checked there.
        if not _graph.get(self.name):
            nbrs = _graph.get(top.name)
            rec = nbrs.get(self.name) if nbrs is not None else None
            if rec is not None:
                rec["count"] += 1  # racy under-count is fine: advisory
                return
        stack_txt = None
        with _state_lock:
            cycle = _cycle_path(self.name, top.name) if _graph.get(
                self.name) else []
            if not cycle:
                rec = _graph.setdefault(top.name, {}).get(self.name)
                if rec is None:
                    need_stack = True
                else:
                    rec["count"] += 1
                    need_stack = False
        if cycle:
            with _state_lock:
                other = "\n".join(
                    f"-- recorded edge {a!r} -> {b!r} (thread "
                    f"{_graph[a][b]['thread']}):\n{_graph[a][b]['stack']}"
                    for a, b in cycle)
            witness = (
                f"-- this thread ({threading.current_thread().name}) "
                f"holds {top.name!r} and is acquiring {self.name!r}:\n"
                f"{_format_stack()}\n{other}")
            raise _report(
                "cycle", top.name, self.name, witness,
                f"lock-order cycle: {self.name!r} -> ... -> {top.name!r} "
                f"already recorded, and this thread is taking "
                f"{top.name!r} -> {self.name!r}")
        if need_stack:
            stack_txt = _format_stack()
            with _state_lock:
                _graph.setdefault(top.name, {}).setdefault(
                    self.name, {
                        "stack": stack_txt,
                        "thread": threading.current_thread().name,
                        "count": 1,
                    })

    # ------------------------------------------------------------- release
    def release(self) -> None:
        if _enabled:
            held = getattr(_tls, "stack", None)
            if held:
                for i in range(len(held) - 1, -1, -1):
                    if held[i][0] is self:
                        held[i][1] -= 1
                        if held[i][1] <= 0:
                            del held[i]
                        break
        self._inner.release()

    def locked(self) -> bool:
        inner = self._inner
        return inner.locked() if hasattr(inner, "locked") else False

    def __enter__(self) -> "NamedLock":
        self.acquire()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.release()


def named_lock(name: str, reentrant: bool = False) -> NamedLock:
    """A NamedLock with its rank resolved from `DECLARED_RANKS` — the
    constructor every production call site uses, so ranks have one
    source of truth."""
    return NamedLock(name, rank=DECLARED_RANKS.get(name),
                     reentrant=reentrant)


def named_condition(name: str) -> "threading.Condition":
    """A ``threading.Condition`` whose underlying lock is a sanitized
    NamedLock (rank from `DECLARED_RANKS`).  Condition's ``_is_owned``
    fallback probes ``acquire(False)`` — non-blocking acquires skip the
    sanitizer checks, so the probe behaves exactly as on a raw lock."""
    return threading.Condition(named_lock(name))
