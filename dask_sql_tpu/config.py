"""Configuration system.

Role parity: reference piggybacks on dask.config with `sql.yaml` defaults +
`sql-schema.yaml` docs (config.py:1-12 there).  Self-contained here: a
process-global nested config with the same `sql.*` keys, `set()` context
manager for per-query overrides (Context.sql(config_options=...)).
"""
from __future__ import annotations

import contextlib
import threading
from typing import Any, Dict, Optional

DEFAULTS: Dict[str, Any] = {
    # parity: dask_sql/sql.yaml keys
    "sql.aggregate.split_out": 1,
    "sql.aggregate.split_every": None,
    "sql.identifier.case_sensitive": True,
    "sql.join.broadcast": None,  # None=auto, False=never, number=row threshold
    "sql.limit.check-first-partition": True,
    "sql.optimize": True,
    "sql.predicate_pushdown": True,
    "sql.dynamic_partition_pruning": True,
    "sql.optimizer.verbose": False,
    "sql.optimizer.fact_dimension_ratio": 0.7,
    "sql.optimizer.max_fact_tables": 2,
    "sql.optimizer.preserve_user_order": True,
    "sql.optimizer.filter_selectivity": 1.0,
    "sql.sort.topk-nelem-limit": 1000000,
    "sql.mappings.decimal_support": "float64",
    # TPU-native additions
    "sql.backend.default": "tpu",
    "sql.shuffle.num_buckets": None,  # None = number of devices
    "sql.native.binder": "auto",  # C++ parse+bind (auto|on|off)
    "sql.compile": True,  # whole-pipeline jit for hot aggregation shapes
    "sql.compile.join": "auto",  # jit the shape-stable join probe phase
    "sql.compile.select": True,  # one-kernel root select chains
    "sql.compile.segsum": "auto",  # scatter | matmul | pallas segment sums
    "sql.streaming.enabled": True,  # out-of-core parquet batch aggregation
    "sql.streaming.batch_rows": 2_000_000,
    "sql.compile.join_pipeline": True,  # one-jit scan->joins->aggregate
    "sql.distributed.aggregate": "auto",  # collectives engine routing
    "sql.distributed.join": "auto",
    "sql.distributed.sort": "auto",  # range-partition sort over the mesh
}


class Config:
    def __init__(self):
        self._values: Dict[str, Any] = dict(DEFAULTS)
        self._lock = threading.RLock()

    def get(self, key: str, default: Any = None) -> Any:
        with self._lock:
            if key in self._values:
                return self._values[key]
            return DEFAULTS.get(key, default)

    def update(self, options: Optional[Dict[str, Any]]) -> None:
        if not options:
            return
        with self._lock:
            self._values.update(options)

    @contextlib.contextmanager
    def set(self, options: Optional[Dict[str, Any]] = None, **kwargs):
        options = dict(options or {})
        options.update(kwargs)
        with self._lock:
            saved = {k: self._values[k] for k in options if k in self._values}
            missing = [k for k in options if k not in self._values]
            self._values.update(options)
        try:
            yield self
        finally:
            with self._lock:
                self._values.update(saved)
                for k in missing:
                    self._values.pop(k, None)


#: process-global config (parity: dask.config global)
config = Config()


def get(key: str, default: Any = None) -> Any:
    return config.get(key, default)


def set(options: Optional[Dict[str, Any]] = None, **kwargs):
    return config.set(options, **kwargs)
