"""Parameterized plan families + inter-query batched execution.

A *family* is the set of queries that differ only in literal values:
``WHERE user_id = 17`` and ``WHERE user_id = 404`` are one family.  This
subsystem (ROADMAP item 1; Flare arXiv:1703.08219, TQP arXiv:2203.01877)
makes the family — not the literal-baked plan — the engine's unit of
compilation, caching, resilience and accounting:

- `parameterize` — the post-optimize pass: literals lift into a runtime
  parameter vector, yielding a literal-stripped *family fingerprint* plus
  this query's param tuple (`FamilyInfo`);
- the compiled pipelines (physical/compiled*.py) key their caches on the
  parameterized expressions and take the values as traced runtime
  arguments, so one XLA executable serves the whole family — the second
  query of a family pays ZERO foreground compiles;
- `batcher` — the ServingRuntime's family batcher: concurrently admitted
  same-family queries coalesce into a single stacked (vmapped) kernel
  launch with the literal vectors as a batched leading axis, sharing one
  scan;
- the family fingerprint keys the result cache (family + param values),
  the circuit breaker and degradation ladder (per family, rung), the
  estimator (one interval per family — its bounds are value-agnostic),
  and the per-family profiles that drive `SHOW PROFILES` and restart
  pre-warm.

``families.enabled`` (default on) switches the whole subsystem; off means
byte-identical behavior to the pre-family engine.
"""
from __future__ import annotations

import logging
from typing import Optional

from .batcher import FamilyBatcher
from .parameterize import (
    FamilyInfo,
    Parameterizer,
    StemInfo,
    compute_family,
    compute_stem,
    full_width_stem,
    normalize_in_values,
    pow2_bucket,
    stack_params,
    stem_of,
)

logger = logging.getLogger(__name__)

__all__ = [
    "FamilyBatcher",
    "FamilyInfo",
    "Parameterizer",
    "StemInfo",
    "batcher_of",
    "compute_family",
    "compute_stem",
    "enabled",
    "family_of",
    "full_width_stem",
    "normalize_in_values",
    "pipeline_parameterizer",
    "pow2_bucket",
    "stack_params",
    "stem_of",
]


def enabled(config) -> bool:
    mode = str(config.get("families.enabled", True)).lower()
    return mode not in ("off", "false", "0", "none")


def family_of(plan, config, metrics=None) -> Optional[FamilyInfo]:
    """The `FamilyInfo` of a planned query, computed once and memoized on
    the plan object (plans are cached per SQL text, so their literals —
    and therefore their param values — are fixed).  Returns None for DDL /
    custom statements, with families disabled, or if the pass fails
    (parameterization is advisory: a bug here must never block a query)."""
    from ..planner import plan as p

    if plan is None or isinstance(plan, p.CustomNode):
        return None
    if not enabled(config):
        return None
    info = getattr(plan, "_dsql_family", None)
    if info is not None:
        return info
    try:
        info = compute_family(plan)
        plan._dsql_family = info
        return info
    except Exception:  # dsql: allow-broad-except — advisory analysis: an
        # unparameterizable plan simply keeps its literal-baked identity
        if metrics is not None:
            metrics.inc("families.internal_error")
        logger.debug("family parameterization failed; using literal plan "
                     "identity", exc_info=True)
        return None


def pipeline_parameterizer(config) -> Parameterizer:
    """The rewrite pass the compiled pipelines run on their extracted
    expression lists (no subplan recursion — subquery expressions decline
    at trace time anyway)."""
    return Parameterizer(enabled=enabled(config), recurse_subplans=False)


def batcher_of(context) -> Optional[FamilyBatcher]:
    """The serving runtime's family batcher, when one is attached and
    batching is on — compiled pipelines consult this before launching."""
    runtime = getattr(context, "serving", None)
    batcher = getattr(runtime, "batcher", None)
    if batcher is None or batcher.max_queries <= 1:
        return None
    return batcher
