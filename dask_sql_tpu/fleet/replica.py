"""One fleet replica: a Context + ServingRuntime with a lifecycle.

On real deployments a replica is a separate server process (server/app.py
behind the `/v1/*` endpoints); for CPU tests and the chaos harness a
replica is this in-process runtime wrapping its own Context — same
catalog, same admission/scheduling/pressure machinery, same health
surface — so the router (fleet/router.py) exercises the exact decision
loop it would run against remote processes, minus the HTTP hop.

Lifecycle states:

- ``standby``  warm spare: ingests checkpoint snapshots + the persistent
               compile cache + profile store (fleet/replication.py) but
               takes no routed traffic until promoted;
- ``ready``    routable (health-gated: the warm-up pass must also be
               ready before the router picks it);
- ``draining`` SIGTERM / ``POST /v1/drain`` landed: health reports 503,
               in-flight queries finish (bounded by
               ``serving.shutdown.drain_timeout_s``), queued work is
               handed back to the router as retryable `ShutdownError`;
- ``dead``     killed (kill -9 semantics): nothing resolves; in-flight
               routed futures fail IMMEDIATELY with retryable
               `ReplicaFailedError` so the router re-dispatches instead
               of waiting out a timeout.

Write fencing: fleet-managed tables mutate ONLY through the router's
write fan-out, which stamps every write with the table delta epoch it
expects to find (`apply_write`).  A retried/replayed write whose epoch
already advanced is a detected duplicate and no-ops — the exactly-once
INSERT INTO guarantee under failover.
"""
from __future__ import annotations

import itertools
import logging
import threading
from concurrent.futures import TimeoutError as FutureTimeoutError
from typing import Any, Dict, Optional, Tuple

from ..resilience.errors import ReplicaFailedError
from ..runtime import locks

logger = logging.getLogger(__name__)

#: replica lifecycle states (surfaced by health() and SHOW REPLICAS)
STANDBY, READY, DRAINING, DEAD = "standby", "ready", "draining", "dead"


class Replica:
    """An in-process replica runtime around one Context."""

    def __init__(self, name: str, context, standby: bool = False):
        from ..serving.runtime import ServingRuntime

        self.name = name
        self.context = context
        self.runtime = ServingRuntime.from_config(
            context.config, metrics=context.metrics)
        context.serving = self.runtime
        # rank 30: lifecycle state, taken from under the router's apply
        # lock (rank 10) during promotion
        self._lock = locks.named_lock("fleet.replica.state")
        self._state = STANDBY if standby else READY
        #: serializes write application so fence-check + apply is atomic
        #: per replica (concurrent routed reads are unaffected).  rank 32:
        #: held across context.sql (plan cache rank 55, registry 70,
        #: metrics 90) — deliberate per-replica write serialization
        self._write_lock = locks.named_lock("fleet.replica.write")
        #: per-replica dispatch suffix: the router re-dispatches the SAME
        #: client qid across replicas/attempts, but each runtime submit
        #: needs its own scheduler identity
        self._attempts = itertools.count()

    # ---------------------------------------------------------------- state
    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    @property
    def routable(self) -> bool:
        """Health-gated routing eligibility: READY *and* past warm-up."""
        if self.state != READY:
            return False
        warm = getattr(self.context, "warmup", None)
        return warm is None or warm.ready

    def health(self) -> Dict[str, Any]:
        """The replica's one-probe health payload — warming status plus
        the pressure band and ledger headroom (the same shape the HTTP
        ``/v1/health`` endpoint serves), so the router's routing loop and
        a load balancer read identical facts."""
        state = self.state
        warm = getattr(self.context, "warmup", None)
        if warm is None:
            out: Dict[str, Any] = {"status": "ready", "warmed": 0,
                                   "total": 0}
        else:
            out = dict(warm.status())
        if state != READY:
            out["status"] = state
        try:
            psnap = self.context.pressure.snapshot()
            out["band"] = psnap["band"]
            out["headroomBytes"] = psnap["headroomBytes"]
        except Exception:  # dsql: allow-broad-except — advisory readout
            logger.debug("replica %s pressure read failed", self.name,
                         exc_info=True)
        return out

    def headroom_bytes(self) -> Optional[int]:
        """Ledger headroom (None when no device budget is configured —
        the router then treats every query as fitting)."""
        try:
            return self.context.ledger.snapshot().get("headroomBytes")
        except Exception:  # dsql: allow-broad-except — advisory readout
            return None

    def predicted_drain_s(self) -> Optional[float]:
        """The packing scheduler's backlog drain prediction — the router's
        tiebreak between replicas with comparable headroom."""
        try:
            return self.runtime._predicted_drain_s()
        except Exception:  # dsql: allow-broad-except — advisory readout
            return None

    # ---------------------------------------------------------------- reads
    def run(self, sql: str, qid: str, priority_class: str = "interactive",
            config_options: Optional[Dict[str, Any]] = None,
            cost=None, timeout: Optional[float] = None):
        """Execute one routed query through this replica's serving
        runtime; blocks for the result.  Raises `ReplicaFailedError` when
        the replica is not READY or the dispatch times out (the router
        re-dispatches), `QueueFullError` when this replica's admission
        queue is at bound (the router spills to a peer)."""
        with self._lock:
            if self._state != READY:
                raise ReplicaFailedError(
                    f"replica {self.name} is {self._state}", query_id=qid)
        if timeout is None:
            timeout = float(self.context.config.get(
                "fleet.result_timeout_s", 60.0) or 60.0)
        opts = dict(config_options or {})

        def job(ticket):
            return self.context.sql(sql, config_options=opts).compute()

        _, fut, ticket = self.runtime.submit(
            job, qid=f"{qid}@{self.name}.{next(self._attempts)}",
            priority_class=priority_class, cost=cost)
        try:
            return fut.result(timeout)
        except FutureTimeoutError:
            # the replica may be wedged: cancel cooperatively and hand the
            # query back to the router as a replica failure
            ticket.cancel()
            raise ReplicaFailedError(
                f"replica {self.name} did not answer {qid} within "
                f"{timeout:g}s", query_id=qid) from None

    # --------------------------------------------------------------- writes
    def validate_write(self, sql: str, stmt, table_key: Tuple[str, str],
                       qid: Optional[str] = None) -> None:
        """Bind a fanned-out write against this replica's catalog WITHOUT
        executing it: an unknown target table, unknown columns or type
        errors in the SELECT/VALUES body surface to the client here,
        BEFORE the router sequences the statement into the write log — a
        statement that cannot bind must never occupy a fence slot."""
        from ..resilience.errors import BindingError

        schema_name, table_name = table_key
        container = self.context.schema.get(schema_name)
        tables = container.tables if container is not None else {}
        if table_name not in tables:
            raise BindingError(
                f"Table {schema_name}.{table_name} not found", query_id=qid)
        self.context._get_ral(stmt, sql_text=sql)

    def apply_noop(self, table_key: Tuple[str, str], expected_epoch: int,
                   qid: Optional[str] = None) -> None:
        """Advance the table epoch past a TOMBSTONED write-log slot
        without executing anything, under the same fence semantics as
        `apply_write` — keeps this replica's epoch aligned with the
        router's sequence when a poisoned entry is skipped."""
        state = self.state
        if state not in (READY, STANDBY):
            raise ReplicaFailedError(
                f"replica {self.name} is {state}", query_id=qid)
        with self._write_lock:
            current = self.context.table_epoch(*table_key)
            if current > expected_epoch:
                return
            if current < expected_epoch:
                raise ReplicaFailedError(
                    f"replica {self.name} is behind on {table_key[0]}."
                    f"{table_key[1]} (epoch {current} < fence "
                    f"{expected_epoch}); replay required", query_id=qid)
            self.context._bump_table_epoch(*table_key)

    def apply_write(self, sql: str, table_key: Tuple[str, str],
                    expected_epoch: int, qid: Optional[str] = None):
        """Apply one fanned-out write iff the table's delta epoch equals
        ``expected_epoch`` (the router's global write sequence for this
        table).  Returns the write's result frame, or None when the fence
        detects the write already applied here (a failover retry /
        promotion replay racing the original) — the exactly-once no-op.
        Raises `ReplicaFailedError` when the replica is not live or its
        epoch is BEHIND the fence (missed writes: the router must replay
        them in order first)."""
        state = self.state
        if state not in (READY, STANDBY):
            raise ReplicaFailedError(
                f"replica {self.name} is {state}", query_id=qid)
        with self._write_lock:
            current = self.context.table_epoch(*table_key)
            if current > expected_epoch:
                self.context.metrics.inc("fleet.write.fenced")
                logger.info(
                    "replica %s fenced duplicate write on %s.%s "
                    "(epoch %d > expected %d)", self.name,
                    table_key[0], table_key[1], current, expected_epoch)
                return None
            if current < expected_epoch:
                raise ReplicaFailedError(
                    f"replica {self.name} is behind on {table_key[0]}."
                    f"{table_key[1]} (epoch {current} < fence "
                    f"{expected_epoch}); replay required", query_id=qid)
            result = self.context.sql(sql, return_futures=False)
            self.context.metrics.inc("fleet.write.applied")
            return result

    # ------------------------------------------------------------ lifecycle
    def promote(self) -> None:
        """STANDBY -> READY (router-driven; write replay happens first)."""
        with self._lock:
            if self._state == STANDBY:
                self._state = READY

    def kill(self) -> int:
        """Simulated ``kill -9``: the replica resolves nothing from here
        on.  Queued work fails with retryable `ShutdownError` (the
        shutdown drain), in-flight routed futures fail immediately with
        retryable `ReplicaFailedError` — the router re-dispatches both to
        survivors.  Worker threads unwind on their own (a real SIGKILL
        would take them with the process; in-process their late results
        no-op against the already-failed futures).  Returns how many
        in-flight futures were failed."""
        from ..observability import flight

        with self._lock:
            if self._state == DEAD:
                return 0
            self._state = DEAD
        flight.record("replica.kill", replica=self.name)
        self.context.metrics.inc("fleet.kill")
        self.runtime.shutdown(wait=False)
        return self.runtime.fail_inflight(
            lambda ticket: ReplicaFailedError(
                f"replica {self.name} killed mid-query",
                query_id=ticket.qid))

    def drain(self, wait: bool = True) -> None:
        """Graceful drain (SIGTERM / ``POST /v1/drain``): stop taking
        routed traffic, finish in-flight work (bounded by
        ``serving.shutdown.drain_timeout_s``), hand queued work back to
        the router as retryable `ShutdownError`."""
        from ..observability import flight

        with self._lock:
            if self._state in (DEAD, DRAINING):
                return
            self._state = DRAINING
        flight.record("fleet.drain", replica=self.name)
        self.context.metrics.inc("fleet.drain")
        self.runtime.shutdown(wait=wait)

    def shutdown(self) -> None:
        """Test/teardown convenience: drain quietly and mark dead."""
        state = self.state
        if state != DEAD:
            self.runtime.shutdown(wait=True)
            with self._lock:
                self._state = DEAD
