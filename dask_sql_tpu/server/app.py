"""Presto-wire-protocol HTTP server.

Role parity: reference server/app.py — POST /v1/statement (app.py:69-100),
async status polling GET /v1/statement/{id} (app.py:44-66), cancellation
DELETE /v1/cancel/{id} (app.py:28-41), /v1/empty, plus JDBC metadata tables
(server/presto_jdbc.py).  Built on the stdlib ThreadingHTTPServer (this image
ships no fastapi/uvicorn); queries run on a worker thread pool so polling
stays responsive — the analogue of the reference's distributed futures.
"""
from __future__ import annotations

import json
import logging
import threading
import uuid
from concurrent.futures import Future, ThreadPoolExecutor
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional

from . import responses

logger = logging.getLogger(__name__)


class _QueryRegistry:
    """Future registry (parity: the reference's app.future_list, app.py:20)."""

    def __init__(self, max_workers: int = 8):
        self.pool = ThreadPoolExecutor(max_workers=max_workers)
        self.futures: Dict[str, Future] = {}
        self.lock = threading.Lock()

    def submit(self, fn) -> str:
        qid = str(uuid.uuid4())
        with self.lock:
            self.futures[qid] = self.pool.submit(fn)
        return qid

    def get(self, qid: str) -> Optional[Future]:
        with self.lock:
            return self.futures.get(qid)

    def cancel(self, qid: str) -> bool:
        with self.lock:
            fut = self.futures.pop(qid, None)
        return fut.cancel() if fut is not None else False


def _make_handler(context, registry: _QueryRegistry, jdbc_meta: bool):
    class Handler(BaseHTTPRequestHandler):
        server_version = "dask-sql-tpu-presto"

        def log_message(self, fmt, *args):  # quiet
            logger.debug(fmt, *args)

        def _send(self, payload: Dict[str, Any], status: int = 200):
            body = json.dumps(payload).encode()
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _base(self) -> str:
            host = self.headers.get("Host", "localhost")
            return f"http://{host}"

        # ------------------------------------------------------------ POST
        def do_POST(self):
            if self.path.rstrip("/") != "/v1/statement":
                self._send({"error": "unknown endpoint"}, 404)
                return
            length = int(self.headers.get("Content-Length", 0))
            sql = self.rfile.read(length).decode()
            if jdbc_meta:
                # JDBC drivers query the unsupported `system` catalog
                from .presto_jdbc import adjust_for_presto_sql

                sql = adjust_for_presto_sql(sql)
            if not sql.strip():
                self._send(self._empty_results())
                return

            def run():
                result = context.sql(sql)
                return result.compute() if result is not None else None

            qid = registry.submit(run)
            self._send({
                "id": qid,
                "infoUri": f"{self._base()}/v1/info/{qid}",
                "nextUri": f"{self._base()}/v1/statement/{qid}",
                "stats": {**responses.query_stats(), "state": "QUEUED"},
                "warnings": [],
            })

        def _empty_results(self):
            qid = str(uuid.uuid4())
            return {"id": qid, "infoUri": "", "stats": responses.query_stats(),
                    "warnings": [], "columns": [], "data": []}

        # ------------------------------------------------------------- GET
        def do_GET(self):
            parts = self.path.strip("/").split("/")
            if len(parts) == 3 and parts[0] == "v1" and parts[1] == "statement":
                self._status(parts[2])
                return
            if self.path.rstrip("/") == "/v1/empty":
                self._send(self._empty_results())
                return
            self._send({"error": "unknown endpoint"}, 404)

        def _status(self, qid: str):
            fut = registry.get(qid)
            if fut is None:
                self._send({"error": f"unknown query {qid}"}, 404)
                return
            if not fut.done():
                self._send({
                    "id": qid,
                    "infoUri": f"{self._base()}/v1/info/{qid}",
                    "nextUri": f"{self._base()}/v1/statement/{qid}",
                    "stats": {**responses.query_stats(), "state": "RUNNING"},
                    "warnings": [],
                })
                return
            try:
                df = fut.result()
            except Exception as e:  # noqa: BLE001 - surfaced to the client
                self._send(responses.error_results(qid, None, e))
                return
            payload = {
                "id": qid,
                "infoUri": f"{self._base()}/v1/info/{qid}",
                "stats": responses.query_stats(),
                "warnings": [],
            }
            if df is not None:
                payload["columns"] = responses.columns_from_frame(df)
                payload["data"] = responses.data_from_frame(df)
            self._send(payload)

        # ---------------------------------------------------------- DELETE
        def do_DELETE(self):
            parts = self.path.strip("/").split("/")
            if len(parts) == 3 and parts[0] == "v1" and parts[1] == "cancel":
                ok = registry.cancel(parts[2])
                self._send({"cancelled": bool(ok)}, 200 if ok else 404)
                return
            self._send({"error": "unknown endpoint"}, 404)

    return Handler


class PrestoServer:
    def __init__(self, context=None, host: str = "0.0.0.0", port: int = 8080,
                 jdbc_metadata: bool = False):
        from ..context import Context

        self.context = context or Context()
        if jdbc_metadata:
            from .presto_jdbc import create_meta_data

            create_meta_data(self.context)
        self.registry = _QueryRegistry()
        handler = _make_handler(self.context, self.registry, jdbc_metadata)
        self.httpd = ThreadingHTTPServer((host, port), handler)
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self.httpd.server_address[1]

    def serve_forever(self):  # pragma: no cover - blocking entrypoint
        logger.info("Presto server listening on %s", self.httpd.server_address)
        self.httpd.serve_forever()

    def start_background(self) -> "PrestoServer":
        self._thread = threading.Thread(target=self.httpd.serve_forever, daemon=True)
        self._thread.start()
        return self

    def shutdown(self):
        self.httpd.shutdown()
        self.httpd.server_close()


def run_server(context=None, host: str = "0.0.0.0", port: int = 8080,
               startup: bool = False, log_level=None, blocking: bool = True,
               jdbc_metadata: bool = False):
    """Parity: reference run_server (server/app.py:210 entrypoint)."""
    server = PrestoServer(context, host=host, port=port, jdbc_metadata=jdbc_metadata)
    if blocking:  # pragma: no cover - blocking entrypoint
        try:
            server.serve_forever()
        except KeyboardInterrupt:
            server.shutdown()
        return None
    return server.start_background()


def main():  # pragma: no cover - console entrypoint (dask-sql-server parity)
    import argparse

    parser = argparse.ArgumentParser(description="Start the SQL server")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", default=8080, type=int)
    parser.add_argument("--jdbc-metadata", action="store_true")
    args = parser.parse_args()
    run_server(host=args.host, port=args.port, jdbc_metadata=args.jdbc_metadata)


if __name__ == "__main__":  # pragma: no cover
    main()
