"""IPython %%sql magic (parity: reference integrations/ipython.py — registers
a sql cell/line magic bound to a Context; with auto_include, dataframes from
the calling namespace are registered automatically, context.py:914-931)."""
from __future__ import annotations


def ipython_integration(context, auto_include: bool = False,
                        disable_highlighting: bool = True) -> None:  # pragma: no cover
    try:
        from IPython.core.magic import needs_local_scope, register_line_cell_magic
    except ImportError as e:
        raise ImportError("IPython is required for the %%sql magic") from e

    @needs_local_scope
    def sql(line, cell=None, local_ns=None):
        sql_statement = cell if cell is not None else line
        if auto_include and local_ns:
            import pandas as pd

            for name, value in list(local_ns.items()):
                if isinstance(value, pd.DataFrame) and not name.startswith("_"):
                    context.create_table(name, value)
        result = context.sql(sql_statement)
        return result.compute() if result is not None else None

    register_line_cell_magic(sql)

    if not disable_highlighting:
        # best-effort SQL syntax highlighting of %%sql cells in classic
        # notebooks (parity: the reference's codemirror magic_spec injection)
        try:
            from IPython.display import Javascript, display

            display(Javascript(
                "if (window.IPython && IPython.CodeCell) {"
                "IPython.CodeCell.options_default.highlight_modes"
                "['magic_text/x-sql'] = {'reg': [/^%%sql/]};}"))
        except Exception:  # dsql: allow-broad-except — notebook JS
            # injection is cosmetic; failing it must not break the magic
            pass
