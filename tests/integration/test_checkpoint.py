"""Session checkpoint/restore (failure recovery, SURVEY §5): a fresh
Context after `load_state` answers the same queries the crashed one did —
including NULL/type fidelity for numeric columns and atomic snapshots."""
import json
import os

import numpy as np
import pandas as pd

from dask_sql_tpu import Context


def _manifest(loc):
    cur = open(os.path.join(loc, "CURRENT")).read().strip()
    return json.load(open(os.path.join(loc, cur, "manifest.json"))), cur


def test_save_and_restore_roundtrip(tmp_path):
    rng = np.random.RandomState(6)
    df = pd.DataFrame({
        "g": rng.choice(["a", "b", None], 500),
        "v": rng.randn(500),
        "d": pd.to_datetime("2022-03-01")
        + pd.to_timedelta(rng.randint(0, 30, 500), "D"),
    })
    c1 = Context()
    c1.create_table("t", df)
    c1.create_schema("aux")
    c1.create_table("u", pd.DataFrame({"k": [1, 2], "w": [0.5, 1.5]}),
                    schema_name="aux")
    from sklearn.linear_model import LinearRegression

    m = LinearRegression().fit(df[["v"]].to_numpy(), np.arange(500))
    c1.register_model("lm", m, ["v"])
    q = ("SELECT g, COUNT(*) AS n, SUM(v) AS s FROM t "
         "GROUP BY g ORDER BY g NULLS LAST")
    before = c1.sql(q, return_futures=False)
    c1.save_state(str(tmp_path / "snap"))

    # "crash": brand-new Context, restore, re-ask
    c2 = Context()
    c2.load_state(str(tmp_path / "snap"))
    after = c2.sql(q, return_futures=False)
    assert list(before["g"].fillna("~")) == list(after["g"].fillna("~"))
    np.testing.assert_allclose(before["s"], after["s"], rtol=1e-12)
    assert list(before["n"]) == list(after["n"])
    r = c2.sql("SELECT SUM(w) AS sw FROM aux.u", return_futures=False)
    assert float(r["sw"][0]) == 2.0
    p = c2.sql("SELECT * FROM PREDICT(MODEL lm, SELECT v FROM t LIMIT 3)",
               return_futures=False)
    assert "target" in p.columns and len(p) == 3


def test_numeric_nulls_and_types_survive(tmp_path):
    # the hard case: nullable BIGINT must come back as BIGINT with real
    # NULLs (not DOUBLE with NaN values), nullable DOUBLE keeps NULL vs
    # value distinction, DATE/TIMESTAMP keep their SQL type
    df = pd.DataFrame({
        "i": pd.array([1, None, 3, None, 5], dtype="Int64"),
        "f": [1.5, np.nan, 2.5, 3.5, np.nan],
        "b": [True, False, True, False, True],
    })
    c1 = Context()
    c1.create_table("t", df)
    before = c1.sql(
        "SELECT COUNT(i) AS ci, COUNT(*) AS n, SUM(i) AS si, "
        "SUM(CASE WHEN i IS NULL THEN 1 ELSE 0 END) AS nulls_i, "
        "COUNT(f) AS cf FROM t", return_futures=False)
    c1.save_state(str(tmp_path / "s"))
    c2 = Context()
    c2.load_state(str(tmp_path / "s"))
    after = c2.sql(
        "SELECT COUNT(i) AS ci, COUNT(*) AS n, SUM(i) AS si, "
        "SUM(CASE WHEN i IS NULL THEN 1 ELSE 0 END) AS nulls_i, "
        "COUNT(f) AS cf FROM t", return_futures=False)
    assert list(before.iloc[0]) == list(after.iloc[0])
    assert int(after["ci"][0]) == 3 and int(after["nulls_i"][0]) == 2
    assert int(after["cf"][0]) == 3
    # type fidelity via DESCRIBE
    d1 = c1.sql("DESCRIBE t", return_futures=False)
    d2 = c2.sql("DESCRIBE t", return_futures=False)
    assert list(d1["Type"]) == list(d2["Type"])


def test_atomic_snapshots_and_pruning(tmp_path):
    loc = str(tmp_path / "s")
    c = Context()
    c.create_table("t", pd.DataFrame({"x": [1, 2]}))
    c.save_state(loc)
    m1, cur1 = _manifest(loc)
    c.create_table("t", pd.DataFrame({"x": [10, 20, 30]}))
    c.save_state(loc)
    m2, cur2 = _manifest(loc)
    assert cur1 != cur2
    assert not os.path.exists(os.path.join(loc, cur1)), "old snapshot pruned"
    c2 = Context()
    c2.load_state(loc)
    assert int(c2.sql("SELECT SUM(x) AS s FROM t",
                      return_futures=False)["s"][0]) == 60


def test_dotted_names_do_not_collide(tmp_path):
    c = Context()
    c.create_schema("a.b")
    c.create_table("c", pd.DataFrame({"x": [1]}), schema_name="a.b")
    c.create_schema("a")
    c.create_table("b.c", pd.DataFrame({"x": [2]}), schema_name="a")
    c.save_state(str(tmp_path / "s"))
    c2 = Context()
    c2.load_state(str(tmp_path / "s"))
    one = c2.schema["a.b"].tables["c"].table.to_pandas()
    two = c2.schema["a"].tables["b.c"].table.to_pandas()
    assert list(one["x"]) == [1] and list(two["x"]) == [2]


def test_views_reported_not_restored(tmp_path):
    c = Context()
    c.create_table("t", pd.DataFrame({"x": [1, 2]}))
    c.sql("CREATE VIEW v AS SELECT x FROM t")
    c.save_state(str(tmp_path / "s"))
    m, _ = _manifest(str(tmp_path / "s"))
    assert m["not_restored"]["root"]["views"] == ["v"]


def test_lazy_parquet_tables_reregister_by_path(tmp_path):
    df = pd.DataFrame({"x": np.arange(100), "y": np.arange(100) * 0.5})
    pqpath = str(tmp_path / "data.parquet")
    df.to_parquet(pqpath)
    c1 = Context()
    c1.create_table("lazy", pqpath, persist=False)
    c1.save_state(str(tmp_path / "snap"))
    m, _ = _manifest(str(tmp_path / "snap"))
    spec = m["schemas"]["root"]["tables"]["lazy"]
    assert spec["kind"] == "parquet" and spec["path"] == pqpath

    c2 = Context()
    c2.load_state(str(tmp_path / "snap"))
    r = c2.sql("SELECT SUM(x) AS s FROM lazy", return_futures=False)
    assert int(r["s"][0]) == int(df.x.sum())
