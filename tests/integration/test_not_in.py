"""Null-aware anti-join for `NOT IN (subquery)` (VERDICT r4 #6).

SQL 3VL: an empty subquery passes every probe row; any NULL in the subquery
passes none; a NULL probe arg never passes against a non-empty set.  The
reference rewrites this shape in decorrelate_where_in.rs:267; here the
optimizer emits Join(LEFTANTI null_aware) and the physical layer evaluates
one vectorized mask — cost O((n+m) log m), not the direct evaluator's O(n*m).
"""
import time

import numpy as np
import pandas as pd
import pytest

from tests.utils import assert_eq


def _plan_text(c, sql):
    return c.explain(sql)


def test_not_in_nullable_plans_anti_join(c):
    """The nullable case must rewrite, not fall back to direct evaluation."""
    c.create_table("na_l", pd.DataFrame({"a": [1.0, 2.0, None]}))
    c.create_table("na_r", pd.DataFrame({"b": [2.0, None]}))
    plan = _plan_text(c, "SELECT * FROM na_l WHERE a NOT IN (SELECT b FROM na_r)")
    assert "null_aware" in plan and "LEFTANTI" in plan
    assert "InSubquery" not in plan


def test_not_in_null_in_subquery_passes_nothing(c):
    c.create_table("na_l", pd.DataFrame({"a": [1.0, 2.0, None, 5.0]}))
    c.create_table("na_r", pd.DataFrame({"b": [2.0, None]}))
    result = c.sql("SELECT * FROM na_l WHERE a NOT IN (SELECT b FROM na_r)").compute()
    assert len(result) == 0


def test_not_in_null_arg_never_passes(c):
    c.create_table("na_l", pd.DataFrame({"a": [1.0, 2.0, None, 5.0]}))
    c.create_table("na_r", pd.DataFrame({"b": [2.0, 3.0]}))
    result = c.sql("SELECT * FROM na_l WHERE a NOT IN (SELECT b FROM na_r)").compute()
    assert sorted(result["a"].tolist()) == [1.0, 5.0]


def test_not_in_empty_subquery_passes_all(c):
    c.create_table("na_l", pd.DataFrame({"a": [1.0, None]}))
    c.create_table("na_r", pd.DataFrame({"b": [2.0, 3.0]}))
    result = c.sql(
        "SELECT * FROM na_l WHERE a NOT IN (SELECT b FROM na_r WHERE b > 100)"
    ).compute()
    # empty set: every row passes, including the NULL arg
    assert len(result) == 2


def test_not_in_non_nullable_still_anti(c, user_table_1, user_table_2):
    result = c.sql(
        "SELECT * FROM user_table_1 WHERE user_id NOT IN "
        "(SELECT user_id FROM user_table_2)"
    ).compute()
    expected = user_table_1[~user_table_1.user_id.isin(user_table_2.user_id)]
    assert_eq(result, expected, check_dtype=False, sort_results=True)


def test_not_in_correlated_per_group_3vl(c):
    """Correlated NOT IN: emptiness / has-NULL are per correlation group."""
    left = pd.DataFrame({"k": [1, 1, 2, 2, 3, 4], "a": [10.0, 99.0, 10.0, 99.0, 7.0, None]})
    # group 1: values {10, NULL} -> nothing passes
    # group 2: values {10}       -> a=99 passes, a=10 blocked
    # group 3: no rows (empty)   -> a=7 passes
    # group 4: values {1}        -> NULL arg never passes
    right = pd.DataFrame({"k": [1, 1, 2, 4], "b": [10.0, None, 10.0, 1.0]})
    c.create_table("cg_l", left)
    c.create_table("cg_r", right)
    result = c.sql(
        "SELECT k, a FROM cg_l WHERE a NOT IN "
        "(SELECT b FROM cg_r WHERE cg_r.k = cg_l.k)"
    ).compute()
    got = sorted(zip(result["k"].tolist(), result["a"].tolist()))
    assert got == [(2, 99.0), (3, 7.0)]


def test_not_in_correlated_matches_pandas_random(c):
    rng = np.random.RandomState(7)
    n = 2000
    left = pd.DataFrame({
        "k": rng.randint(0, 20, n),
        "a": np.where(rng.rand(n) < 0.1, np.nan, rng.randint(0, 30, n).astype(float)),
    })
    right = pd.DataFrame({
        "k": rng.randint(0, 25, 300),
        "b": np.where(rng.rand(300) < 0.1, np.nan, rng.randint(0, 30, 300).astype(float)),
    })
    c.create_table("rq_l", left)
    c.create_table("rq_r", right)
    result = c.sql(
        "SELECT k, a FROM rq_l WHERE a NOT IN "
        "(SELECT b FROM rq_r WHERE rq_r.k = rq_l.k)"
    ).compute()

    def truth(row):
        vals = right.loc[right.k == row.k, "b"]
        if len(vals) == 0:
            return True
        if pd.isna(row.a) or vals.isna().any():
            return False
        return row.a not in set(vals.dropna())

    expected = left[left.apply(truth, axis=1)]
    assert_eq(result, expected, check_dtype=False, sort_results=True)


def test_not_in_cost_does_not_scale_with_subquery(c):
    """1M-row probe: doubling |subquery| 100x must not blow up runtime
    (the old direct evaluator was O(rows * |subquery|))."""
    rng = np.random.RandomState(0)
    n = 1_000_000
    probe = pd.DataFrame({"a": np.where(rng.rand(n) < 0.01, np.nan,
                                        rng.randint(0, 1 << 20, n).astype(float))})
    c.create_table("perf_l", probe)
    times = {}
    for label, m in (("small", 1_000), ("large", 100_000)):
        sub = pd.DataFrame({"b": rng.randint(0, 1 << 20, m).astype(float)})
        c.create_table("perf_r", sub)
        t0 = time.perf_counter()
        res = c.sql("SELECT COUNT(*) AS n FROM perf_l WHERE a NOT IN "
                    "(SELECT b FROM perf_r)").compute()
        times[label] = time.perf_counter() - t0
        expected = probe[probe.a.notna() & ~probe.a.isin(sub.b)]
        assert int(res["n"][0]) == len(expected)
    # O(n log m): 100x the subquery may cost a small constant factor, never 100x
    assert times["large"] < 10 * times["small"] + 0.5, times
