"""Packed single-transfer host materialization (columnar/pack.py): the
accelerator-backend Table.to_pandas path must agree exactly with the
per-column path over every dtype family and NULL placement."""
import os

import numpy as np
import pandas as pd
import pytest

from dask_sql_tpu.columnar.column import Column
from dask_sql_tpu.columnar.table import Table


@pytest.fixture()
def mixed_table():
    rng = np.random.RandomState(0)
    n = 257
    f64 = rng.randn(n)
    f64[3] = np.nan  # becomes NULL at ingest
    f32 = rng.randn(n).astype(np.float32)
    i64 = rng.randint(-(2 ** 62), 2 ** 62, n)
    i32 = rng.randint(-100, 100, n).astype(np.int32)
    b = rng.rand(n) < 0.5
    s = rng.choice(["x", "yy", "zzz", None], n)
    d = (np.datetime64("2020-01-01") +
         rng.randint(0, 1000, n).astype("timedelta64[D]"))
    cols = {
        "f64": Column.from_numpy(f64),
        "f32": Column.from_numpy(f32),
        "i64": Column.from_numpy(i64),
        "i32": Column.from_numpy(i32),
        "b": Column.from_numpy(b),
        "s": Column.from_numpy(s),
        "d": Column.from_numpy(d),
    }
    return Table(cols, n)


def test_packed_path_matches_per_column(mixed_table, monkeypatch):
    plain = mixed_table.to_pandas()
    monkeypatch.setenv("DSQL_PACK_TO_PANDAS", "1")
    packed = mixed_table.to_pandas()
    assert list(plain.columns) == list(packed.columns)
    for col in plain.columns:
        a, b = plain[col], packed[col]
        assert str(a.dtype) == str(b.dtype), col
        if a.dtype.kind == "f":
            np.testing.assert_array_equal(np.isnan(a), np.isnan(b))
            np.testing.assert_array_equal(a[~np.isnan(a)], b[~np.isnan(b)])
        else:
            assert a.equals(b), col


def test_packed_helper_bit_exact():
    from dask_sql_tpu.columnar.pack import packed_host_arrays
    import jax.numpy as jnp

    rng = np.random.RandomState(1)
    f64 = rng.randn(100)
    f32 = rng.randn(100).astype(np.float32)
    i64 = np.array([np.iinfo(np.int64).min, -1, 0, np.iinfo(np.int64).max]
                   ).repeat(25)
    got = packed_host_arrays([jnp.asarray(f64), jnp.asarray(f32),
                              jnp.asarray(i64)])
    np.testing.assert_array_equal(got[0], f64)
    np.testing.assert_array_equal(got[1], f32)
    np.testing.assert_array_equal(got[2], i64)
    assert got[0].dtype == np.float64 and got[1].dtype == np.float32
    assert got[2].dtype == np.int64


def test_packed_helper_declines_mixed_lengths():
    from dask_sql_tpu.columnar.pack import packed_host_arrays
    import jax.numpy as jnp

    assert packed_host_arrays([jnp.zeros(3), jnp.zeros(4)]) is None
    assert packed_host_arrays([np.zeros(3), np.zeros(3)]) is None
    assert packed_host_arrays([jnp.zeros(3)]) is None
