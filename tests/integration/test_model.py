"""SQL ML layer tests (parity: reference test_model.py, 1076 LoC)."""
import os

import numpy as np
import pandas as pd
import pytest


@pytest.fixture
def training_df(c):
    np.random.seed(0)
    df = pd.DataFrame({
        "x": np.random.rand(100),
        "y": np.random.rand(100),
    })
    df["target"] = (df.x * 2 + df.y > 1.5).astype(np.int64)
    c.create_table("timeseries", df)
    return df


def test_create_model_tpu_native(c, training_df):
    c.sql(
        """CREATE MODEL my_model WITH (
               model_class = 'LinearRegression',
               target_column = 'target'
           ) AS (SELECT x, y, target FROM timeseries)"""
    )
    assert "my_model" in c.schema[c.schema_name].models
    result = c.sql(
        "SELECT * FROM PREDICT(MODEL my_model, SELECT x, y FROM timeseries)"
    ).compute()
    assert "target" in result.columns
    assert len(result) == 100

def test_create_model_sklearn(c, training_df):
    c.sql(
        """CREATE MODEL sk_model WITH (
               model_class = 'sklearn.linear_model.LogisticRegression',
               wrap_predict = True,
               target_column = 'target'
           ) AS (SELECT x, y, target FROM timeseries)"""
    )
    result = c.sql(
        "SELECT * FROM PREDICT(MODEL sk_model, SELECT x, y FROM timeseries)"
    ).compute()
    acc = (result["target"] == training_df["target"]).mean()
    assert acc > 0.8

def test_wrap_fit_incremental(c, training_df):
    c.sql(
        """CREATE MODEL inc_model WITH (
               model_class = 'sklearn.linear_model.SGDClassifier',
               wrap_fit = True,
               target_column = 'target'
           ) AS (SELECT x, y, target FROM timeseries)"""
    )
    result = c.sql(
        "SELECT * FROM PREDICT(MODEL inc_model, SELECT x, y FROM timeseries)"
    ).compute()
    assert len(result) == 100

def test_show_describe_drop_model(c, training_df):
    c.sql(
        """CREATE MODEL m1 WITH (
               model_class = 'LinearRegression', target_column = 'target'
           ) AS (SELECT x, y, target FROM timeseries)"""
    )
    models = c.sql("SHOW MODELS").compute()
    assert "m1" in list(models["Model"])
    desc = c.sql("DESCRIBE MODEL m1").compute()
    assert "training_columns" in list(desc["Params"])
    c.sql("DROP MODEL m1")
    assert "m1" not in c.schema[c.schema_name].models
    c.sql("DROP MODEL IF EXISTS m1")
    with pytest.raises(RuntimeError):
        c.sql("DROP MODEL m1")

def test_export_model(c, training_df, tmp_path):
    c.sql(
        """CREATE MODEL exp_model WITH (
               model_class = 'sklearn.linear_model.LinearRegression',
               target_column = 'target'
           ) AS (SELECT x, y, target FROM timeseries)"""
    )
    path = str(tmp_path / "model.pkl")
    c.sql(f"EXPORT MODEL exp_model WITH (format = 'pickle', location = '{path}')")
    import pickle

    with open(path, "rb") as f:
        model = pickle.load(f)
    assert hasattr(model, "predict")
    path2 = str(tmp_path / "model.joblib")
    c.sql(f"EXPORT MODEL exp_model WITH (format = 'joblib', location = '{path2}')")
    assert os.path.exists(path2)

def test_create_experiment(c, training_df):
    c.sql(
        """CREATE EXPERIMENT exp1 WITH (
               model_class = 'sklearn.linear_model.LogisticRegression',
               experiment_class = 'sklearn.model_selection.GridSearchCV',
               tune_parameters = (C = (0.1, 1.0)),
               target_column = 'target'
           ) AS (SELECT x, y, target FROM timeseries)"""
    )
    assert "exp1" in c.schema[c.schema_name].experiments
    assert "exp1" in c.schema[c.schema_name].models

def test_kmeans_unsupervised(c, training_df):
    c.sql(
        """CREATE MODEL km WITH (
               model_class = 'KMeans', n_clusters = 2
           ) AS (SELECT x, y FROM timeseries)"""
    )
    result = c.sql("SELECT * FROM PREDICT(MODEL km, SELECT x, y FROM timeseries)").compute()
    assert set(result["target"]) <= {0, 1}

def test_ml_metrics():
    from dask_sql_tpu.ml.metrics import (accuracy_score, log_loss,
                                         mean_squared_error, r2_score)

    y = np.array([0, 1, 1, 0])
    p = np.array([0, 1, 0, 0])
    assert accuracy_score(y, p) == 0.75
    proba = np.array([0.1, 0.9, 0.4, 0.2])
    assert log_loss(y, proba) > 0
    assert mean_squared_error([1.0, 2.0], [1.0, 3.0]) == 0.5
    assert r2_score([1.0, 2.0, 3.0], [1.0, 2.0, 3.0]) == 1.0
