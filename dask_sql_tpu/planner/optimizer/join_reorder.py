"""Join reordering (parity: reference src/sql/optimizer/join_reorder.rs — the
fact/dimension heuristic of "Improving Join Reordering for Large Scale
Distributed Computing").

Algorithm (join_reorder.rs:74-188):
- flatten a filter-free pure-INNER-join subtree into leaf relations + a set
  of column-equality join conditions (bushy trees supported),
- classify leaves by catalog row counts: `size/largest > fact_dimension_ratio`
  => fact table, else dimension (unknown stats assume 100 rows),
- bail when facts or dims are empty or #facts > `max_fact_tables`,
- order dimensions: filtered dims (scaled by `filter_selectivity`) sorted by
  size; unfiltered dims keep user order unless `preserve_user_order=False`
  (then size-sorted); the two lists merge greedily smallest-first,
- build a left-deep tree per fact table (dimension-first), join the fact
  trees, and bail to the original plan if any condition or dimension cannot
  be placed.

Positional note: our plan uses positional ColumnRefs, so the rebuilt tree is
wrapped in a Projection restoring the original column order.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from .. import plan as p
from ..expressions import ColumnRef, Expr


def _table_rows(node, catalog) -> Optional[float]:
    """Row-count bound for the base table feeding this subtree, if simple.

    Walks through every unary operator whose output row count is bounded by
    its input (Filter/Projection/Alias pass rows through; Aggregate, Window
    partitions, Limit, Distinct only shrink), so opaque leaves like a CTE's
    aggregate still get a real upper bound instead of the unknown-stats
    default."""
    while isinstance(node, (p.Filter, p.SubqueryAlias, p.Projection,
                            p.Aggregate, p.Window, p.Limit, p.Distinct)):
        node = node.inputs()[0]
    if isinstance(node, p.TableScan):
        try:
            t = catalog.schemas[node.schema_name].tables[node.table_name]
            return t.statistics.row_count
        except KeyError:
            return None
    return None


def _is_not_null_pred(e: Expr) -> bool:
    from ..expressions import ScalarFunc

    return isinstance(e, ScalarFunc) and e.op in ("is_not_null", "isnotnull")


def _has_real_filter(node) -> bool:
    """Filters beyond join-key IS NOT NULL guards (join_reorder.rs:217-238)."""
    from .rules import _conjuncts

    if isinstance(node, p.Filter):
        if any(not _is_not_null_pred(c) for c in _conjuncts(node.predicate)):
            return True
        return _has_real_filter(node.inputs()[0])
    if isinstance(node, p.TableScan):
        return any(not _is_not_null_pred(f) for f in node.filters)
    return any(_has_real_filter(k) for k in node.inputs())




def _single_col(e: Expr):
    """(column index, wrapper-or-None) when the join key is one column,
    bare or under casts (q64: ss_store_sk = CAST(s_store_sk AS DOUBLE));
    None when the key is a computed expression."""
    from ..expressions import Cast

    wrap = None
    x = e
    while isinstance(x, Cast):
        wrap = e
        x = x.arg
    if isinstance(x, ColumnRef) and type(x) is ColumnRef:
        return x.index, wrap
    return None


def _rewrap(wrap, ref: ColumnRef) -> Expr:
    """Re-point a (possibly nested) cast chain at a new column position."""
    from dataclasses import replace

    from ..expressions import Cast

    if wrap is None:
        return ref
    if isinstance(wrap, Cast):
        return replace(wrap, arg=_rewrap(wrap.arg, ref))
    return ref


@dataclass
class _Leaf:
    plan: object
    start: int       # column offset in the original flattened schema
    width: int
    size: float
    filtered: bool


def _flatten(node, base: int, leaves: List[_Leaf],
             conds: List[Tuple[int, int, object, object]], catalog) -> bool:
    """Collect leaves (in user order) and global-position equality conds.

    Single structural walk (the flatten-through test and the leaf test are
    one and the same): INNER equijoins and CrossJoins flatten — a CrossJoin
    is an INNER join whose conditions live higher in the chain (q64's d2/d3
    date_dim aliases) — and every other node becomes an opaque leaf,
    placeable only when join conditions connect it.  Each cond is
    (left_pos, right_pos, left_cast_wrapper, right_cast_wrapper).  Returns
    False when a join key is a computed expression (beyond a cast chain)."""
    if isinstance(node, p.Join) and node.join_type == "INNER" and node.filter is None:
        nleft = len(node.left.schema)
        if not _flatten(node.left, base, leaves, conds, catalog):
            return False
        if not _flatten(node.right, base + nleft, leaves, conds, catalog):
            return False
        for l, r in node.on:
            lc = _single_col(l)
            rc = _single_col(r)
            if lc is None or rc is None:
                return False
            conds.append((base + lc[0], base + rc[0], lc[1], rc[1]))
        return True
    if isinstance(node, p.CrossJoin):
        nleft = len(node.left.schema)
        return (_flatten(node.left, base, leaves, conds, catalog)
                and _flatten(node.right, base + nleft, leaves, conds, catalog))
    size = _table_rows(node, catalog)
    leaves.append(_Leaf(node, base, len(node.schema),
                        100.0 if size is None else float(size),
                        _has_real_filter(node)))
    return True


def maybe_reorder(plan, config, catalog):
    ratio = float(config.get("sql.optimizer.fact_dimension_ratio", 0.7))
    max_facts = int(config.get("sql.optimizer.max_fact_tables", 2))
    preserve = bool(config.get("sql.optimizer.preserve_user_order", True))
    selectivity = float(config.get("sql.optimizer.filter_selectivity", 1.0))

    def go(node, parent_is_chain: bool):
        # CrossJoin deliberately does NOT propagate in_chain: an INNER-join
        # subtree under a CrossJoin reorders as its own (well-conditioned)
        # chain first, and the outer chain then places it as one leaf —
        # measured faster on q64 than flattening the whole 18-table chain
        # into a single reorder problem over default-stat leaves
        in_chain = (isinstance(node, p.Join) and node.join_type == "INNER"
                    and node.filter is None)
        is_chain_head = in_chain and not parent_is_chain
        kids = [go(k, in_chain) for k in node.inputs()]
        node = node.with_inputs(kids) if kids else node
        if is_chain_head:
            new = _reorder_chain(node, ratio, max_facts, preserve, selectivity,
                                 catalog)
            if new is not None:
                return new
        return node

    return go(plan, False)


def _reorder_chain(join, ratio, max_facts, preserve, selectivity, catalog):
    leaves: List[_Leaf] = []
    conds: List[Tuple[int, int, object, object]] = []
    if not _flatten(join, 0, leaves, conds, catalog):
        return None
    if len(leaves) < 3:
        return None  # nothing to reorder; the executor picks the build side

    largest = max(l.size for l in leaves)
    facts = [i for i, l in enumerate(leaves) if l.size / max(largest, 1e-9) > ratio]
    dims = [i for i, l in enumerate(leaves) if i not in facts]
    if not facts or not dims or len(facts) > max_facts:
        return None

    # order the dimensions (join_reorder.rs:122-167)
    unfiltered = [i for i in dims if not leaves[i].filtered]
    if not preserve:
        unfiltered.sort(key=lambda i: leaves[i].size)
    filtered = sorted((i for i in dims if leaves[i].filtered),
                      key=lambda i: leaves[i].size * selectivity)
    ordered: List[int] = []
    fi = ui = 0
    while fi < len(filtered) or ui < len(unfiltered):
        if fi < len(filtered) and (
                ui >= len(unfiltered)
                or leaves[filtered[fi]].size * selectivity
                < leaves[unfiltered[ui]].size):
            ordered.append(filtered[fi]); fi += 1
        else:
            ordered.append(unfiltered[ui]); ui += 1

    # global position -> (leaf index, offset)
    pos_to_leaf: Dict[int, Tuple[int, int]] = {}
    for li, leaf in enumerate(leaves):
        for off in range(leaf.width):
            pos_to_leaf[leaf.start + off] = (li, off)
    remaining = [(pos_to_leaf[a] + (wa,), pos_to_leaf[b] + (wb,))
                 for a, b, wa, wb in conds]

    builder = _TreeBuilder(leaves, remaining)
    unused = list(ordered)
    trees = []
    for f in facts:
        builder.start(f)
        # two passes so snowflake dims can attach through other dims
        for _ in range(2):
            still = []
            for d in unused:
                if not builder.try_join(d):
                    still.append(d)
            unused = still
            if not unused:
                break
        trees.append(builder.finish())
    if unused:
        return None
    tree = trees[0]
    for t in trees[1:]:
        tree = builder.join_trees(tree, t)
        if tree is None:
            return None
    if builder.remaining:
        return None  # a condition could not be placed; keep the user plan

    # restore the original column order
    new_pos: Dict[Tuple[int, int], int] = {}
    off = 0
    for li in tree.leaf_order:
        for o in range(leaves[li].width):
            new_pos[(li, o)] = off + o
        off += leaves[li].width
    exprs = []
    out_fields = list(join.schema)
    for i, f in enumerate(out_fields):
        exprs.append(ColumnRef(new_pos[pos_to_leaf[i]], f.name, f.sql_type,
                               f.nullable))
    return p.Projection(tree.plan, exprs, out_fields)


class _Tree:
    def __init__(self, plan, leaf_order: List[int]):
        self.plan = plan
        self.leaf_order = leaf_order


class _TreeBuilder:
    def __init__(self, leaves: List[_Leaf], conds):
        self.leaves = leaves
        #: [((leaf, off, cast_wrap), (leaf, off, cast_wrap))]
        self.remaining = list(conds)
        self._cur: Optional[_Tree] = None

    # -- helpers ------------------------------------------------------------
    def _offset_of(self, tree: _Tree, leaf_idx: int) -> int:
        off = 0
        for li in tree.leaf_order:
            if li == leaf_idx:
                return off
            off += self.leaves[li].width
        raise KeyError(leaf_idx)

    def _conds_between(self, in_tree, leaf_set):
        found, rest = [], []
        for (la, oa, wa), (lb, ob, wb) in self.remaining:
            if la in in_tree and lb in leaf_set:
                found.append(((la, oa, wa), (lb, ob, wb)))
            elif lb in in_tree and la in leaf_set:
                found.append(((lb, ob, wb), (la, oa, wa)))
            else:
                rest.append(((la, oa, wa), (lb, ob, wb)))
        return found, rest

    def _make_join(self, tree: _Tree, other: _Tree, pairs) -> _Tree:
        lwidth = sum(self.leaves[li].width for li in tree.leaf_order)
        on = []
        for (ll, lo, lw), (rl, ro, rw) in pairs:
            lf = self.leaves[ll].plan.schema[lo]
            rf = self.leaves[rl].plan.schema[ro]
            lpos = self._offset_of(tree, ll) + lo
            rpos = lwidth + self._offset_of(other, rl) + ro
            on.append((
                _rewrap(lw, ColumnRef(lpos, lf.name, lf.sql_type, lf.nullable)),
                _rewrap(rw, ColumnRef(rpos, rf.name, rf.sql_type, rf.nullable)),
            ))
        fields = list(tree.plan.schema) + list(other.plan.schema)
        plan = p.Join(tree.plan, other.plan, "INNER", on, None, fields)
        return _Tree(plan, tree.leaf_order + other.leaf_order)

    # -- build API ----------------------------------------------------------
    def start(self, leaf_idx: int):
        self._cur = _Tree(self.leaves[leaf_idx].plan, [leaf_idx])

    def try_join(self, leaf_idx: int) -> bool:
        tree = self._cur
        pairs, rest = self._conds_between(set(tree.leaf_order), {leaf_idx})
        if not pairs:
            return False
        self.remaining = rest
        self._cur = self._make_join(tree, _Tree(self.leaves[leaf_idx].plan,
                                                [leaf_idx]), pairs)
        return True

    def finish(self) -> _Tree:
        t = self._cur
        self._cur = None
        return t

    def join_trees(self, a: _Tree, b: _Tree) -> Optional[_Tree]:
        pairs, rest = self._conds_between(set(a.leaf_order), set(b.leaf_order))
        if not pairs:
            return None
        self.remaining = rest
        return self._make_join(a, b, pairs)
