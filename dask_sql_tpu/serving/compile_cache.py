"""Persistent executable cache: XLA compiles that survive the process.

A process restart is the one fault the engine otherwise handles badly —
every rung of every hot query recompiles on the critical path of a
recovering fleet (ROADMAP item 3).  Flare (arXiv:1703.08219) and TQP
(arXiv:2203.01877) both argue the compiled artifact, not the plan, is the
unit of serving; this module applies that discipline by enabling the JAX
persistent compilation cache under ``serving.compile_cache.path``:

- executables are keyed by the lowered HLO (which embeds the plan-family
  shape, the pow2 bucket shapes, and the rung's kernel structure), so a
  restarted process that re-plans the same query family deserializes the
  executable from disk instead of re-running XLA;
- a half-written entry (crash mid-write) is a cache MISS, never an error:
  ``jax_raise_persistent_cache_errors`` stays False, so corruption degrades
  to a recompile (tests/unit/test_coldstart.py proves it);
- hit/miss attribution reaches the engine's own metrics: a jax monitoring
  listener feeds process-global counters, and `timed_jit_call`
  (observability/spans.py) snapshots them around each recorded compile to
  emit ``resilience.compile_cache.hit`` / ``.miss`` and stamp the
  ``persistent_hit`` attribute on the trace's ``compile:<rung>`` span.

The JAX cache directory is process-global state: one path per process.
`enable` is idempotent for the same path and logs (rather than flips) on a
conflicting second path — the first serving Context wins.
"""
from __future__ import annotations

import logging
import os
import threading
from typing import Any, Dict, Optional

logger = logging.getLogger(__name__)

CONFIG_PATH_KEY = "serving.compile_cache.path"
CONFIG_MIN_COMPILE_KEY = "serving.compile_cache.min_compile_time_s"

_HIT_EVENT = "/jax/compilation_cache/cache_hits"
_MISS_EVENT = "/jax/compilation_cache/cache_misses"

_lock = threading.Lock()
_state: Dict[str, Any] = {"path": None, "listener_registered": False}
_counters = {"hits": 0, "misses": 0}


def _listener(event: str, **kwargs) -> None:
    if event == _HIT_EVENT:
        with _lock:
            _counters["hits"] += 1
    elif event == _MISS_EVENT:
        with _lock:
            _counters["misses"] += 1


def enable(path: str, min_compile_time_s: float = 0.0) -> bool:
    """Point the JAX persistent compilation cache at `path` (idempotent).

    Returns True when the cache is active on `path` after the call.  The
    floor defaults to 0 seconds so even fast CPU-backend compiles persist
    (a restarted process pays trace+lower either way; the XLA compile is
    the part worth skipping)."""
    import jax

    with _lock:
        current = _state["path"]
        if current == path:
            return True
        if current is not None:
            # jax holds ONE cache dir per process; flipping it mid-flight
            # would orphan the first Context's entries silently
            logger.warning(
                "persistent compile cache already enabled at %r; "
                "ignoring second path %r", current, path)
            return False
        try:
            os.makedirs(path, exist_ok=True)
            # jax latches its cache-used decision at the FIRST compile of
            # the process: without a reset, enabling after any compile has
            # happened (earlier Context, notebook warm-up) silently never
            # persists anything
            from jax.experimental.compilation_cache import (
                compilation_cache as jax_cc,
            )

            jax_cc.reset_cache()
            jax.config.update("jax_compilation_cache_dir", path)
            jax.config.update("jax_persistent_cache_min_compile_time_secs",
                              float(min_compile_time_s))
            # -1 disables the entry-size floor (0 would auto-raise it to the
            # jax default and drop the small CPU-test executables)
            jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
            # a torn/corrupt cache entry must degrade to a recompile, never
            # fail the query that tripped over it
            jax.config.update("jax_raise_persistent_cache_errors", False)
        except Exception:  # dsql: allow-broad-except — the cache is an
            # optimization; a jax version without these knobs serves cold
            logger.warning("could not enable the persistent compile cache",
                           exc_info=True)
            return False
        if not _state["listener_registered"]:
            try:
                from jax._src import monitoring

                monitoring.register_event_listener(_listener)
                _state["listener_registered"] = True
            except Exception:  # dsql: allow-broad-except — hit/miss
                # attribution is best-effort; the cache itself still works
                logger.debug("jax monitoring listener unavailable",
                             exc_info=True)
        _state["path"] = path
        logger.info("persistent compile cache enabled at %s", path)
        return True


def disable() -> None:
    """Turn the persistent cache off (tests: undo process-global state).
    Resets jax's lazily-initialized cache object too — without that, a
    later enable() on a different path would keep writing to the old
    directory (jax binds the cache object on first use)."""
    import jax

    with _lock:
        if _state["path"] is None:
            return
        try:
            jax.config.update("jax_compilation_cache_dir", None)
            from jax.experimental.compilation_cache import (
                compilation_cache as jax_cc,
            )

            jax_cc.reset_cache()
        except Exception:  # dsql: allow-broad-except — best-effort teardown
            logger.debug("could not reset jax compilation cache",
                         exc_info=True)
        _state["path"] = None


def maybe_enable(config, metrics=None) -> bool:
    """Enable from the ``serving.compile_cache.*`` config keys; no-op when
    unconfigured.  Called from Context.__init__ so any serving process
    that sets the path gets restart-surviving executables."""
    path = config.get(CONFIG_PATH_KEY)
    if not path:
        return False
    ok = enable(str(path),
                float(config.get(CONFIG_MIN_COMPILE_KEY, 0.0) or 0.0))
    if ok and metrics is not None:
        metrics.gauge("resilience.compile_cache.enabled", 1.0)
    return ok


def enabled_path() -> Optional[str]:
    with _lock:
        return _state["path"]


def hit_count() -> int:
    """Cumulative persistent-cache hits this process (monitoring events).
    `timed_jit_call` snapshots this around a compile to attribute the hit
    to a specific rung/span — best-effort under concurrent compiles."""
    with _lock:
        return _counters["hits"]


def stats() -> Dict[str, int]:
    with _lock:
        return dict(_counters)
