"""Binder: AST -> typed LogicalPlan.

Role parity: DataFusion's SqlToRel as driven by the reference
(`logical_relational_algebra`, src/sql.rs:586 / statement_to_plan sql.rs:674),
including the custom-statement lowering of sql.rs:668-814 and the dialect
rewrites of src/dialect.rs (CEIL..TO, TIMESTAMPADD, FILTER(WHERE..) aggs).
Name resolution, type inference/coercion, aggregate/window extraction and
subquery binding all happen here, producing positional `ColumnRef`s.
"""
from __future__ import annotations

import re
from dataclasses import replace
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..columnar.dtypes import (
    DATETIME_TYPES,
    INTEGER_TYPES,
    INTERVAL_TYPES,
    NUMERIC_TYPES,
    STRING_TYPES,
    SqlType,
    parse_sql_type,
    promote,
    similar_type,
)
from . import plan as p
from . import sqlast as a
from .catalog import Catalog
from .expressions import (
    AggExpr,
    CaseExpr,
    Cast,
    ColumnRef,
    ExistsExpr,
    Expr,
    Field,
    InListExpr,
    InSubqueryExpr,
    Literal,
    ScalarFunc,
    ScalarSubqueryExpr,
    SortKey,
    UdfExpr,
    WindowExpr,
    WindowFrameBound,
    WindowSpec,
    transform,
    walk,
)
from .functions import (
    AGGREGATE_FUNCTIONS,
    SCALAR_FUNCTIONS,
    WINDOW_FUNCTIONS,
    resolve_type,
)
from ..resilience.errors import BindingError
from .parser import ParsingException


class BindError(BindingError):
    """Name/type resolution failure; taxonomy code BIND_ERROR (USER_ERROR),
    still a ValueError through BindingError for historical callers."""


_CMP_OPS = {"=": "eq", "<>": "ne", "<": "lt", "<=": "le", ">": "gt", ">=": "ge"}
_ARITH_OPS = {"+": "add", "-": "sub", "*": "mul", "/": "div", "%": "mod"}

_INTERVAL_NS = {
    "NANOSECOND": 1,
    "MICROSECOND": 1_000,
    "MILLISECOND": 1_000_000,
    "SECOND": 1_000_000_000,
    "MINUTE": 60 * 1_000_000_000,
    "HOUR": 3600 * 1_000_000_000,
    "DAY": 86400 * 1_000_000_000,
    "WEEK": 7 * 86400 * 1_000_000_000,
}
_INTERVAL_MONTHS = {"MONTH": 1, "QUARTER": 3, "YEAR": 12}


class Scope:
    """Name-resolution scope: (qualifier, field) pairs over a flat positional schema."""

    def __init__(self, entries: List[Tuple[Optional[str], Field]], parent: Optional["Scope"] = None,
                 case_sensitive: bool = True):
        self.entries = entries
        self.parent = parent
        self.case_sensitive = case_sensitive

    @property
    def fields(self) -> List[Field]:
        return [f for _, f in self.entries]

    def _match_name(self, a_: str, b: str) -> bool:
        return a_ == b if self.case_sensitive else a_.lower() == b.lower()

    def resolve(self, parts: List[str]) -> Optional[ColumnRef]:
        if len(parts) == 1:
            qualifier, name = None, parts[0]
        else:
            qualifier, name = parts[-2], parts[-1]
        matches = []
        for i, (q, f) in enumerate(self.entries):
            if not self._match_name(f.name, name):
                continue
            if qualifier is not None and (q is None or not self._match_name(q, qualifier)):
                continue
            matches.append((i, f))
        if not matches:
            return None
        if len(matches) > 1 and qualifier is None:
            # exact-case match disambiguates in case-insensitive mode
            exact = [(i, f) for i, f in matches if f.name == name]
            if len(exact) == 1:
                matches = exact
            else:
                raise BindError(f"Ambiguous column reference {'.'.join(parts)!r}")
        i, f = matches[0]
        return ColumnRef(i, f.name, f.sql_type, f.nullable)


class Binder:
    def __init__(self, catalog: Catalog, case_sensitive: bool = True):
        self.catalog = catalog
        self.case_sensitive = case_sensitive
        self._cte_stack: List[Dict[str, p.LogicalPlan]] = []

    # ------------------------------------------------------------------ API
    def bind_statement(self, stmt: a.Statement) -> p.LogicalPlan:
        if isinstance(stmt, a.QueryStatement):
            plan, _ = self.bind_query(stmt.query)
            return plan
        if isinstance(stmt, a.ExplainStatement):
            plan, _ = self.bind_query(stmt.query)
            lint = getattr(stmt, "lint", False)
            estimate = getattr(stmt, "estimate", False)
            fmt_json = getattr(stmt, "fmt_json", False)
            col = "LINT" if lint else "ESTIMATE" if estimate else "PLAN"
            return p.Explain(plan, [Field(col, SqlType.VARCHAR)],
                             stmt.analyze, lint, estimate, fmt_json)
        if isinstance(stmt, a.CreateTableWith):
            return p.CreateTableNode([], stmt.name, stmt.kwargs, stmt.if_not_exists, stmt.or_replace)
        if isinstance(stmt, a.CreateTableAs):
            inner, _ = self.bind_query(stmt.query)
            return p.CreateMemoryTableNode([], stmt.name, inner, stmt.persist,
                                           stmt.if_not_exists, stmt.or_replace)
        if isinstance(stmt, a.DropTable):
            return p.DropTableNode([], stmt.name, stmt.if_exists)
        if isinstance(stmt, a.CreateSchema):
            return p.CreateSchemaNode([], stmt.name, stmt.if_not_exists, stmt.or_replace)
        if isinstance(stmt, a.DropSchema):
            return p.DropSchemaNode([], stmt.name, stmt.if_exists)
        if isinstance(stmt, a.UseSchema):
            return p.UseSchemaNode([], stmt.name)
        if isinstance(stmt, a.AlterSchema):
            return p.AlterSchemaNode([], stmt.old_name, stmt.new_name)
        if isinstance(stmt, a.AlterTable):
            return p.AlterTableNode([], stmt.old_name, stmt.new_name, stmt.if_exists)
        if isinstance(stmt, a.ShowSchemas):
            return p.ShowSchemasNode([Field("Schema", SqlType.VARCHAR)], stmt.like)
        if isinstance(stmt, a.ShowTables):
            return p.ShowTablesNode([Field("Table", SqlType.VARCHAR)], stmt.schema)
        if isinstance(stmt, a.ShowColumns):
            fields = [Field("Column", SqlType.VARCHAR), Field("Type", SqlType.VARCHAR),
                      Field("Extra", SqlType.VARCHAR), Field("Comment", SqlType.VARCHAR)]
            return p.ShowColumnsNode(fields, stmt.table)
        if isinstance(stmt, a.ShowModels):
            return p.ShowModelsNode([Field("Model", SqlType.VARCHAR)], stmt.schema)
        if isinstance(stmt, a.ShowMetrics):
            return p.ShowMetricsNode(
                [Field("Metric", SqlType.VARCHAR), Field("Value", SqlType.VARCHAR)],
                stmt.like)
        if isinstance(stmt, a.ShowProfiles):
            return p.ShowProfilesNode(
                [Field("Fingerprint", SqlType.VARCHAR),
                 Field("Metric", SqlType.VARCHAR),
                 Field("Value", SqlType.VARCHAR)],
                stmt.like)
        if isinstance(stmt, a.ShowQueries):
            return p.ShowQueriesNode(
                [Field("Qid", SqlType.VARCHAR),
                 Field("Field", SqlType.VARCHAR),
                 Field("Value", SqlType.VARCHAR)],
                stmt.like)
        if isinstance(stmt, a.ShowMaterialized):
            return p.ShowMaterializedNode(
                [Field("Kind", SqlType.VARCHAR),
                 Field("Fingerprint", SqlType.VARCHAR),
                 Field("Table", SqlType.VARCHAR),
                 Field("Rows", SqlType.VARCHAR),
                 Field("Bytes", SqlType.VARCHAR),
                 Field("Hits", SqlType.VARCHAR),
                 Field("Epoch", SqlType.VARCHAR)],
                stmt.like)
        if isinstance(stmt, a.ShowReplicas):
            return p.ShowReplicasNode(
                [Field("Replica", SqlType.VARCHAR),
                 Field("State", SqlType.VARCHAR),
                 Field("Band", SqlType.VARCHAR),
                 Field("Headroom", SqlType.VARCHAR),
                 Field("Routed", SqlType.VARCHAR)],
                stmt.like)
        if isinstance(stmt, a.InsertInto):
            inner, _ = self.bind_query(stmt.query)
            return p.InsertIntoNode([Field("Inserted", SqlType.VARCHAR)],
                                    stmt.table, inner)
        if isinstance(stmt, a.CancelQuery):
            return p.CancelQueryNode(
                [Field("Qid", SqlType.VARCHAR),
                 Field("Cancelled", SqlType.VARCHAR)],
                stmt.qid)
        if isinstance(stmt, a.AnalyzeTable):
            return p.AnalyzeTableNode([], stmt.table, stmt.columns)
        if isinstance(stmt, a.CreateModel):
            inner, _ = self.bind_query(stmt.query)
            return p.CreateModelNode([], stmt.name, stmt.kwargs, inner,
                                     stmt.if_not_exists, stmt.or_replace)
        if isinstance(stmt, a.DropModel):
            return p.DropModelNode([], stmt.name, stmt.if_exists)
        if isinstance(stmt, a.DescribeModel):
            fields = [Field("Params", SqlType.VARCHAR), Field("Value", SqlType.VARCHAR)]
            return p.DescribeModelNode(fields, stmt.name)
        if isinstance(stmt, a.ExportModel):
            return p.ExportModelNode([], stmt.name, stmt.kwargs)
        if isinstance(stmt, a.CreateExperiment):
            inner, _ = self.bind_query(stmt.query)
            return p.CreateExperimentNode([], stmt.name, stmt.kwargs, inner,
                                          stmt.if_not_exists, stmt.or_replace)
        raise BindError(f"Cannot bind statement {type(stmt).__name__}")

    # ---------------------------------------------------------------- query
    def bind_query(self, q: a.Select, outer: Optional[Scope] = None) -> Tuple[p.LogicalPlan, Scope]:
        ctes = {}
        if q.ctes:
            for name, sub in q.ctes:
                self._cte_stack.append(ctes)
                try:
                    sub_plan, _ = self.bind_query(sub, outer)
                finally:
                    self._cte_stack.pop()
                ctes[name] = p.SubqueryAlias(sub_plan, name, [
                    Field(f.name, f.sql_type, f.nullable) for f in sub_plan.schema
                ])
        self._cte_stack.append(ctes)
        try:
            if q.set_op is None and q.values is None:
                plan, scope = self._bind_select_core(q, outer, order_by=q.order_by)
            else:
                plan, scope = self._bind_set_expr(q, outer)
                if q.order_by:
                    plan = self._bind_order_by_output(plan, q.order_by, scope)
            if q.limit is not None or q.offset is not None:
                plan = p.Limit(plan, q.offset or 0, q.limit, plan.schema)
            return plan, scope
        finally:
            self._cte_stack.pop()

    def _bind_set_expr(self, q: a.Select, outer: Optional[Scope]) -> Tuple[p.LogicalPlan, Scope]:
        left, scope = self._bind_select_core(q, outer)
        if q.set_op is None:
            return left, scope
        op, all_, rhs_ast = q.set_op
        right, _ = self.bind_query(rhs_ast, outer) if (rhs_ast.ctes or rhs_ast.order_by or rhs_ast.limit is not None) else self._bind_set_expr(rhs_ast, outer)
        if len(left.schema) != len(right.schema):
            raise BindError(f"{op} requires equal column counts "
                            f"({len(left.schema)} vs {len(right.schema)})")
        fields = []
        for lf, rf in zip(left.schema, right.schema):
            fields.append(Field(lf.name, promote(lf.sql_type, rf.sql_type),
                                lf.nullable or rf.nullable))
        if op == "UNION":
            out = p.Union([left, right], all_, fields)
            if not all_:
                out = p.Distinct(out, fields)
        elif op == "INTERSECT":
            out = p.Intersect(left, right, all_, fields)
        else:
            out = p.Except(left, right, all_, fields)
        return out, Scope([(None, f) for f in fields], outer, self.case_sensitive)

    # ---------------------------------------------------------- select core
    def _bind_select_core(self, q: a.Select, outer: Optional[Scope],
                          order_by: Optional[List[a.OrderItem]] = None) -> Tuple[p.LogicalPlan, Scope]:
        # named windows are per-SELECT; nested subquery binds must not clobber
        prev_windows = getattr(self, "_named_windows", {})
        try:
            return self._bind_select_core_inner(q, outer, order_by)
        finally:
            self._named_windows = prev_windows

    def _bind_select_core_inner(self, q: a.Select, outer: Optional[Scope],
                                order_by: Optional[List[a.OrderItem]] = None) -> Tuple[p.LogicalPlan, Scope]:
        if q.values is not None:
            return self._bind_values(q)
        # FROM
        if q.from_ is None:
            plan: p.LogicalPlan = p.EmptyRelation([], produce_one_row=True)
            scope = Scope([], outer, self.case_sensitive)
        else:
            plan, scope = self._bind_table_ref(q.from_, outer)
        # WHERE
        from .expressions import GroupingExpr

        if q.where is not None:
            pred = self._coerce_bool(self.bind_expr(q.where, scope))
            if any(isinstance(x, GroupingExpr) for x in walk(pred)):
                raise BindError("GROUPING is not allowed in WHERE")
            plan = p.Filter(plan, pred, plan.schema)
        self._named_windows = dict(q.named_windows or {})
        # select-alias ASTs, visible to GROUPING() arg binding (saved/restored
        # so nested subselects don't clobber the outer map)
        prev_alias_asts = getattr(self, "_select_alias_asts", None)
        self._select_alias_asts = {
            (item.alias if self.case_sensitive else item.alias.lower()): item.expr
            for item in q.projections
            if getattr(item, "alias", None) and not isinstance(item.expr, a.Wildcard)
        }
        # bind select items (pre-aggregate binding; aggs collected after)
        proj_exprs: List[Expr] = []
        proj_names: List[str] = []
        for item in q.projections:
            if isinstance(item.expr, a.Wildcard):
                wc: a.Wildcard = item.expr
                for i, (qual, f) in enumerate(scope.entries):
                    if wc.qualifier is not None and (qual is None or qual != wc.qualifier[-1]):
                        continue
                    proj_exprs.append(ColumnRef(i, f.name, f.sql_type, f.nullable))
                    proj_names.append(f.name)
                continue
            e = self.bind_expr(item.expr, scope)
            proj_exprs.append(e)
            if item.alias:
                proj_names.append(item.alias)
            elif isinstance(e, ColumnRef):
                # preserve the table's column spelling (matters when
                # identifiers are matched case-insensitively)
                proj_names.append(e.name)
            else:
                proj_names.append(self._derive_name(item.expr))
        having_ast = q.having
        if having_ast is not None:
            # HAVING may reference a select alias (commonly of an aggregate);
            # table columns win over aliases per engine convention
            alias_map = {}
            for item in q.projections:
                if getattr(item, "alias", None) and not isinstance(item.expr, a.Wildcard):
                    key = item.alias if self.case_sensitive else item.alias.lower()
                    alias_map.setdefault(key, item.expr)
            if alias_map:
                fold = (lambda s: s) if self.case_sensitive else str.lower
                having_ast = _subst_select_aliases(
                    having_ast, alias_map,
                    lambda ident: scope.resolve(ident.parts) is None, fold)
        having_expr = self.bind_expr(having_ast, scope) if having_ast is not None else None

        # ORDER BY items: positions / select aliases resolve to outputs, the
        # rest bind against the pre-projection scope (participating in the
        # aggregate rewrite below, so ORDER BY SUM(x) works).  Per SQL, a
        # bare identifier names the OUTPUT column first (a select alias wins
        # over a same-named source column — TPC-DS q33/q56/q60/q71 rely on
        # `SUM(total_sales) AS total_sales ... ORDER BY total_sales`); inside
        # larger ORDER BY expressions aliases substitute textually (q36/q70/
        # q86 use `CASE WHEN lochierarchy = 0 ...` over a GROUPING alias).
        fold_ident = (lambda s: s) if self.case_sensitive else str.lower
        order_specs: List[Tuple[str, object, a.OrderItem]] = []
        for item in order_by or []:
            e = item.expr
            if isinstance(e, a.Literal) and isinstance(e.value, int):
                idx = e.value - 1
                if idx < 0 or idx >= len(proj_exprs):
                    raise BindError(f"ORDER BY position {e.value} out of range")
                order_specs.append(("pos", idx, item))
                continue
            if isinstance(e, a.Identifier) and len(e.parts) == 1:
                # proj_names is alias-or-derived-name, aligned with proj_exprs
                # (wildcard-expanded, unlike q.projections)
                matches = [i for i, n in enumerate(proj_names)
                           if fold_ident(n) == fold_ident(e.parts[0])]
                if len(matches) == 1:
                    order_specs.append(("pos", matches[0], item))
                    continue
            if self._select_alias_asts:
                e = _subst_select_aliases(
                    e, self._select_alias_asts,
                    lambda ident: scope.resolve(ident.parts) is None, fold_ident)
            order_specs.append(("expr", self.bind_expr(e, scope), item))
        self._select_alias_asts = prev_alias_asts
        order_exprs = [s[1] for s in order_specs if s[0] == "expr"]

        # aggregate context?
        agg_calls: List[AggExpr] = []
        for e in proj_exprs + order_exprs + ([having_expr] if having_expr is not None else []):
            agg_calls.extend(x for x in walk(e) if isinstance(x, AggExpr))
        if q.group_by or agg_calls:
            plan, rewritten, having_expr, scope_post = self._bind_aggregate(
                q, plan, scope, proj_exprs + order_exprs, having_expr
            )
            proj_exprs = rewritten[: len(proj_exprs)]
            order_exprs = rewritten[len(proj_exprs):]
        else:
            all_post = proj_exprs + order_exprs + (
                [having_expr] if having_expr is not None else [])
            if any(isinstance(x, GroupingExpr) for e in all_post for x in walk(e)):
                raise BindError("GROUPING requires a GROUP BY context")
            scope_post = scope
        if having_expr is not None:
            plan = p.Filter(plan, self._coerce_bool(having_expr), plan.schema)
            having_expr = None

        # window functions (computed after grouping, SQL semantics)
        all_exprs = proj_exprs + order_exprs
        win_calls = [x for e in all_exprs for x in walk(e) if isinstance(x, WindowExpr)]
        if win_calls:
            plan, all_exprs = self._bind_window(plan, all_exprs)
            proj_exprs = all_exprs[: len(proj_exprs)]
            order_exprs = all_exprs[len(proj_exprs):]

        # final projection
        fields = [Field(n, e.sql_type, _nullable(e)) for n, e in zip(proj_names, proj_exprs)]
        # sort keys: reuse an output column when the order expr matches one
        sort_keys: List[SortKey] = []
        extra_exprs: List[Expr] = []
        it_order = iter(order_exprs)
        for kind, val, item in order_specs:
            if kind == "pos":
                idx = val
            else:
                bound = next(it_order)
                idx = None
                for i, pe in enumerate(proj_exprs):
                    if pe == bound:
                        idx = i
                        break
                if idx is None:
                    if q.distinct:
                        raise BindError(
                            "For SELECT DISTINCT, ORDER BY expressions must appear in the select list")
                    idx = len(fields) + len(extra_exprs)
                    extra_exprs.append(bound)
            f = (fields + [Field(f"__sort{j}", x.sql_type, _nullable(x))
                           for j, x in enumerate(extra_exprs)])[idx]
            sort_keys.append(SortKey(ColumnRef(idx, f.name, f.sql_type, f.nullable),
                                     item.ascending, item.nulls_first))

        if extra_exprs:
            ext_fields = fields + [Field(f"__sort{j}", x.sql_type, _nullable(x))
                                   for j, x in enumerate(extra_exprs)]
            plan = p.Projection(plan, proj_exprs + extra_exprs, ext_fields)
            plan = p.Sort(plan, sort_keys, ext_fields)
            final_refs = [ColumnRef(i, f.name, f.sql_type, f.nullable)
                          for i, f in enumerate(fields)]
            plan = p.Projection(plan, final_refs, fields)
        else:
            plan = p.Projection(plan, proj_exprs, fields)
            if q.distinct:
                plan = p.Distinct(plan, fields)
            if sort_keys:
                plan = p.Sort(plan, sort_keys, fields)
        scope_out = Scope([(None, f) for f in fields], outer, self.case_sensitive)
        if q.distribute_by:
            keys = [self.bind_expr(e, scope_out) for e in q.distribute_by]
            plan = p.DistributeBy(plan, keys, plan.schema)
        return plan, scope_out

    def _bind_values(self, q: a.Select) -> Tuple[p.LogicalPlan, Scope]:
        empty = Scope([], None, self.case_sensitive)
        rows = [[self.bind_expr(e, empty) for e in row] for row in q.values]
        ncols = len(rows[0])
        fields = []
        for i in range(ncols):
            t = rows[0][i].sql_type
            for r in rows[1:]:
                t = promote(t, r[i].sql_type)
            fields.append(Field(f"column{i + 1}", t))
        rows = [[e if e.sql_type == fields[i].sql_type else Cast(e, fields[i].sql_type)
                 for i, e in enumerate(r)] for r in rows]
        plan = p.Values(rows, fields)
        return plan, Scope([(None, f) for f in fields], None, self.case_sensitive)

    # ------------------------------------------------------------ FROM refs
    def _bind_table_ref(self, ref: a.TableRef, outer: Optional[Scope]) -> Tuple[p.LogicalPlan, Scope]:
        if isinstance(ref, a.NamedTable):
            plan, scope = self._bind_named_table(ref, outer)
            if ref.sample is not None:
                method, frac, seed = ref.sample
                plan = p.Sample(plan, method, frac, seed, plan.schema)
            return plan, scope
        if isinstance(ref, a.DerivedTable):
            sub, _ = self.bind_query(ref.subquery, outer)
            alias, col_aliases = _split_alias(ref.alias)
            fields = list(sub.schema)
            if col_aliases:
                fields = [Field(col_aliases[i] if i < len(col_aliases) else f.name,
                                f.sql_type, f.nullable) for i, f in enumerate(fields)]
            if alias:
                sub = p.SubqueryAlias(sub, alias, fields)
            scope = Scope([(alias, f) for f in fields], outer, self.case_sensitive)
            return sub, scope
        if isinstance(ref, a.TableFunction):
            sub, _ = self.bind_query(ref.subquery, outer)
            fields = list(sub.schema) + [Field("target", SqlType.DOUBLE)]
            node = p.PredictModelNode(fields, ref.model_name, sub)
            alias, _ = _split_alias(ref.alias)
            scope = Scope([(alias, f) for f in fields], outer, self.case_sensitive)
            return node, scope
        if isinstance(ref, a.Join):
            return self._bind_join(ref, outer)
        raise BindError(f"Unsupported table reference {type(ref).__name__}")

    def _bind_named_table(self, ref: a.NamedTable, outer) -> Tuple[p.LogicalPlan, Scope]:
        alias, col_aliases = _split_alias(ref.alias)
        # CTE lookup first (innermost wins)
        if len(ref.parts) == 1:
            for frame in reversed(self._cte_stack):
                if ref.parts[0] in frame:
                    sub = frame[ref.parts[0]]
                    fields = list(sub.schema)
                    if col_aliases:
                        fields = [Field(col_aliases[i] if i < len(col_aliases) else f.name,
                                        f.sql_type, f.nullable) for i, f in enumerate(fields)]
                    name = alias or ref.parts[0]
                    scope = Scope([(name, f) for f in fields], outer, self.case_sensitive)
                    return sub, scope
        table = self.catalog.resolve_table(ref.parts)
        fields = list(table.fields)
        scan = p.TableScan(table.schema_name, table.name, fields)
        if col_aliases:
            fields = [Field(col_aliases[i] if i < len(col_aliases) else f.name,
                            f.sql_type, f.nullable) for i, f in enumerate(fields)]
        name = alias or table.name
        scope = Scope([(name, f) for f in fields], outer, self.case_sensitive)
        return scan, scope

    def _bind_join(self, ref: a.Join, outer) -> Tuple[p.LogicalPlan, Scope]:
        left, lscope = self._bind_table_ref(ref.left, outer)
        right, rscope = self._bind_table_ref(ref.right, outer)
        nleft = len(lscope.entries)
        combined_entries = list(lscope.entries) + [
            (q, f) for q, f in rscope.entries
        ]
        jt = ref.join_type
        # outer joins make the other side nullable
        def _mk_fields():
            out = []
            for i, (q, f) in enumerate(combined_entries):
                nullable = f.nullable
                if jt in ("LEFT", "FULL") and i >= nleft:
                    nullable = True
                if jt in ("RIGHT", "FULL") and i < nleft:
                    nullable = True
                out.append(Field(f.name, f.sql_type, nullable))
            return out

        scope = Scope(combined_entries, outer, self.case_sensitive)
        if jt == "CROSS":
            fields = _mk_fields()
            plan = p.CrossJoin(left, right, fields)
            return plan, scope
        using = ref.using
        if using is not None and not using:  # NATURAL JOIN: shared names
            lnames = {f.name for _, f in lscope.entries}
            using = [f.name for _, f in rscope.entries if f.name in lnames]
        if using is not None:
            on = []
            for name in using:
                lref = lscope.resolve([name])
                rref = rscope.resolve([name])
                if lref is None or rref is None:
                    raise BindError(f"USING column {name!r} not present on both sides")
                on.append((lref, replace(rref, index=rref.index + nleft)))
            fields = _mk_fields()
            plan = p.Join(left, right, jt, on, None, fields)
            return plan, scope
        cond = self.bind_expr(ref.condition, scope) if ref.condition is not None else Literal(True, SqlType.BOOLEAN)
        on, residual = split_join_condition(cond, nleft)
        fields = _mk_fields()
        if jt in ("LEFTSEMI", "LEFTANTI"):
            fields = fields[:nleft]
            scope = Scope(combined_entries[:nleft], outer, self.case_sensitive)
        plan = p.Join(left, right, jt, on, residual, fields)
        return plan, scope

    # ------------------------------------------------------------ aggregate
    def _bind_aggregate(self, q, plan, scope, proj_exprs, having_expr):
        # GROUPING SETS / ROLLUP / CUBE expand into a union of aggregates
        # (parity: aggregate.rs getGroupSets — the reference surfaces group
        # sets from DataFusion; we lower them during binding)
        plain_asts: List[a.Expr] = []
        construct = None
        for ge in q.group_by:
            if isinstance(ge, (a.GroupingSets, a.Rollup, a.Cube)):
                construct = ge
            else:
                plain_asts.append(ge)
        sets: Optional[List[List[int]]] = None
        if construct is not None:
            n_plain = len(plain_asts)
            if isinstance(construct, a.Rollup):
                extra = list(construct.exprs)
                raw_sets = [list(range(k)) for k in range(len(extra), -1, -1)]
            elif isinstance(construct, a.Cube):
                extra = list(construct.exprs)
                m = len(extra)
                raw_sets = [[i for i in range(m) if mask & (1 << i)]
                            for mask in range(2 ** m - 1, -1, -1)]
            else:
                # GROUPING SETS: dedupe expressions structurally via binding
                extra = []
                raw_sets = []
                bound_cache = {}
                for s in construct.sets:
                    idxs = []
                    for e in s:
                        b = self.bind_expr(e, scope)
                        if b not in bound_cache:
                            bound_cache[b] = len(extra)
                            extra.append(e)
                        idxs.append(bound_cache[b])
                    raw_sets.append(idxs)
            q = a.Select(**{**q.__dict__, "group_by": plain_asts + extra})
            sets = [list(range(n_plain)) + [n_plain + i for i in s] for s in raw_sets]
        group_exprs: List[Expr] = []
        for ge in q.group_by:
            if isinstance(ge, a.Literal) and isinstance(ge.value, int):
                idx = ge.value - 1
                if idx < 0 or idx >= len(proj_exprs):
                    raise BindError(f"GROUP BY position {ge.value} out of range")
                group_exprs.append(proj_exprs[idx])
                continue
            if isinstance(ge, a.Identifier) and len(ge.parts) == 1 and scope.resolve(ge.parts) is None:
                # alias of a select item
                matched = False
                for item, bound in zip(q.projections, proj_exprs):
                    if item.alias == ge.parts[0]:
                        group_exprs.append(bound)
                        matched = True
                        break
                if matched:
                    continue
            group_exprs.append(self.bind_expr(ge, scope))
        # collect aggregates from all post-group expressions
        agg_calls: List[AggExpr] = []
        seen = {}
        def _collect(e):
            for x in walk(e):
                if isinstance(x, AggExpr) and x not in seen:
                    seen[x] = len(agg_calls)
                    agg_calls.append(x)
        for e in proj_exprs:
            _collect(e)
        if having_expr is not None:
            _collect(having_expr)

        group_fields = [Field(self._derive_name_expr(e, i), e.sql_type, _nullable(e))
                        for i, e in enumerate(group_exprs)]
        agg_fields = [Field(f"__agg{i}", x.sql_type, True) for i, x in enumerate(agg_calls)]
        out_fields = group_fields + agg_fields

        # GROUPING(...) markers: constant 0 for a plain GROUP BY; for
        # grouping sets, a per-branch bitmask materialized as extra union
        # output columns (leftmost arg = most significant bit)
        from .expressions import GroupingExpr

        grouping_exprs: List[GroupingExpr] = []
        for e in list(proj_exprs) + ([having_expr] if having_expr is not None else []):
            for x in walk(e):
                if isinstance(x, GroupingExpr) and x not in grouping_exprs:
                    grouping_exprs.append(x)
        # GROUPING may not hide where the post-agg rewrite can't reach it
        for ac in agg_calls:
            for x in list(ac.args) + ([ac.filter] if ac.filter is not None else []):
                if any(isinstance(s_, GroupingExpr) for s_ in walk(x)):
                    raise BindError("GROUPING cannot appear inside an aggregate")
        for ge_ in group_exprs:
            if any(isinstance(s_, GroupingExpr) for s_ in walk(ge_)):
                raise BindError("GROUPING cannot appear in GROUP BY")
        grouping_map: Dict[Expr, Expr] = {}

        def _grouping_value(g: GroupingExpr, s: List[int]) -> int:
            val = 0
            for arg in g.args:
                try:
                    gi = group_exprs.index(arg)
                except ValueError:
                    raise BindError(
                        "GROUPING argument must be a grouping expression")
                val = (val << 1) | (0 if gi in s else 1)
            return val

        if sets is None:
            for g in grouping_exprs:
                _grouping_value(g, list(range(len(group_exprs))))  # validate
                grouping_map[g] = Literal(0, SqlType.INTEGER)
            agg_plan = p.Aggregate(plan, group_exprs, agg_calls, out_fields)
        else:
            # union of one aggregate per grouping set, NULL-padded to the full
            # group layout
            out_fields = ([Field(f.name, f.sql_type, True) for f in group_fields]
                          + agg_fields
                          + [Field(f"__grouping{j}", SqlType.INTEGER, False)
                             for j in range(len(grouping_exprs))])
            branches = []
            for s in sets:
                sub_groups = [group_exprs[i] for i in s]
                sub_fields = ([group_fields[i] for i in s] + agg_fields)
                sub_agg = p.Aggregate(plan, sub_groups, agg_calls, sub_fields)
                proj = []
                for gi, gf in enumerate(group_fields):
                    if gi in s:
                        pos = s.index(gi)
                        proj.append(ColumnRef(pos, gf.name, gf.sql_type, True))
                    else:
                        proj.append(Cast(Literal(None, SqlType.NULL), gf.sql_type))
                for ai, af in enumerate(agg_fields):
                    proj.append(ColumnRef(len(s) + ai, af.name, af.sql_type, True))
                for g in grouping_exprs:
                    proj.append(Literal(_grouping_value(g, s), SqlType.INTEGER))
                branches.append(p.Projection(sub_agg, proj, out_fields))
            agg_plan = p.Union(branches, True, out_fields)
            base = len(group_fields) + len(agg_fields)
            for j, g in enumerate(grouping_exprs):
                grouping_map[g] = ColumnRef(base + j, f"__grouping{j}",
                                            SqlType.INTEGER, False)

        # rewrite post-agg expressions: replace group-expr / agg subtrees with refs
        mapping: Dict[Expr, ColumnRef] = {}
        for i, ge in enumerate(group_exprs):
            mapping.setdefault(ge, ColumnRef(i, group_fields[i].name, ge.sql_type, _nullable(ge)))
        for i, ac in enumerate(agg_calls):
            mapping[ac] = ColumnRef(len(group_exprs) + i, agg_fields[i].name, ac.sql_type, True)

        def _rewrite(e: Expr) -> Expr:
            if isinstance(e, GroupingExpr):
                return grouping_map[e]
            if e in mapping:
                return mapping[e]
            kids = e.children()
            if not kids:
                if isinstance(e, ColumnRef):
                    raise BindError(
                        f"Column {e.name!r} must appear in the GROUP BY clause or be used in an aggregate function"
                    )
                return e
            return e.with_children([_rewrite(c) for c in kids])

        proj_exprs = [_rewrite(e) for e in proj_exprs]
        if having_expr is not None:
            having_expr = _rewrite(having_expr)
        scope_post = Scope([(None, f) for f in out_fields], scope.parent, self.case_sensitive)
        return agg_plan, proj_exprs, having_expr, scope_post

    # -------------------------------------------------------------- window
    def _bind_window(self, plan, proj_exprs):
        win_calls: List[WindowExpr] = []
        seen = {}
        for e in proj_exprs:
            for x in walk(e):
                if isinstance(x, WindowExpr) and x not in seen:
                    seen[x] = len(win_calls)
                    win_calls.append(x)
        base = len(plan.schema)
        fields = list(plan.schema) + [
            Field(f"__win{i}", w.sql_type, True) for i, w in enumerate(win_calls)
        ]
        win_plan = p.Window(plan, win_calls, fields)
        mapping = {w: ColumnRef(base + i, f"__win{i}", w.sql_type, True)
                   for i, w in enumerate(win_calls)}

        def _rewrite(e: Expr) -> Expr:
            if e in mapping:
                return mapping[e]
            kids = e.children()
            if not kids:
                return e
            return e.with_children([_rewrite(c) for c in kids])

        return win_plan, [_rewrite(e) for e in proj_exprs]

    # ------------------------------------------------------------ ORDER BY
    def _bind_order_by_output(self, plan, order_by: List[a.OrderItem], scope: Scope):
        """ORDER BY over a set-operation result: positions and output names only."""
        keys: List[SortKey] = []
        fields = list(plan.schema)
        for item in order_by:
            e = item.expr
            if isinstance(e, a.Literal) and isinstance(e.value, int):
                idx = e.value - 1
                if idx < 0 or idx >= len(fields):
                    raise BindError(f"ORDER BY position {e.value} out of range")
                f = fields[idx]
                keys.append(SortKey(ColumnRef(idx, f.name, f.sql_type, f.nullable),
                                    item.ascending, item.nulls_first))
                continue
            bound = self.bind_expr(e, scope)
            keys.append(SortKey(bound, item.ascending, item.nulls_first))
        return p.Sort(plan, keys, plan.schema)

    # ---------------------------------------------------------- expressions
    def bind_expr(self, e: a.Expr, scope: Scope) -> Expr:
        if isinstance(e, a.Literal):
            return _bind_literal(e)
        if isinstance(e, a.IntervalLiteral):
            return _bind_interval(e)
        if isinstance(e, a.Identifier):
            ref = scope.resolve(e.parts)
            if ref is None:
                # fall back: maybe a no-paren function (CURRENT_TIMESTAMP)
                up = e.parts[-1].upper()
                if len(e.parts) == 1 and up in SCALAR_FUNCTIONS and SCALAR_FUNCTIONS[up][2] == 0:
                    op, rt, _, _ = SCALAR_FUNCTIONS[up]
                    return ScalarFunc(op, (), resolve_type(rt, []))
                outer_ref = scope.parent.resolve(e.parts) if scope.parent is not None else None
                if outer_ref is not None:
                    from .expressions import ColumnRef as CR

                    return _OuterRef(outer_ref.index, outer_ref.name, outer_ref.sql_type,
                                     outer_ref.nullable)
                raise BindError(f"Column {'.'.join(e.parts)!r} not found")
            return ref
        if isinstance(e, a.UnaryOp):
            arg = self.bind_expr(e.operand, scope)
            if e.op == "NOT":
                return ScalarFunc("not", (self._coerce_bool(arg),), SqlType.BOOLEAN)
            if e.op == "-":
                return ScalarFunc("neg", (arg,), arg.sql_type)
            return arg
        if isinstance(e, a.BinaryOp):
            return self._bind_binary(e, scope)
        if isinstance(e, a.Cast):
            arg = self.bind_expr(e.operand, scope)
            return Cast(arg, parse_sql_type(e.type_name), e.safe)
        if isinstance(e, a.Case):
            return self._bind_case(e, scope)
        if isinstance(e, a.FunctionCall):
            return self._bind_function(e, scope)
        if isinstance(e, a.Between):
            arg = self.bind_expr(e.operand, scope)
            low = self.bind_expr(e.low, scope)
            high = self.bind_expr(e.high, scope)
            if e.symmetric:
                # bounds may arrive in either order; bound exprs are shared so
                # embedded subquery plans stay single-execution (executor memo)
                t = promote(low.sql_type, high.sql_type)
                low, high = (ScalarFunc("least", (low, high), t),
                             ScalarFunc("greatest", (low, high), t))
            arg_l, low = self._coerce_pair(arg, low)
            arg_h, high = self._coerce_pair(arg, high)
            cond = ScalarFunc("and", (
                ScalarFunc("ge", (arg_l, low), SqlType.BOOLEAN),
                ScalarFunc("le", (arg_h, high), SqlType.BOOLEAN),
            ), SqlType.BOOLEAN)
            if e.negated:
                return ScalarFunc("not", (cond,), SqlType.BOOLEAN)
            return cond
        if isinstance(e, a.InList):
            arg = self.bind_expr(e.operand, scope)
            items = []
            for it in e.items:
                b = self.bind_expr(it, scope)
                _, b = self._coerce_pair(arg, b)
                items.append(b)
            return InListExpr(arg, tuple(items), e.negated)
        if isinstance(e, a.InSubquery):
            arg = self.bind_expr(e.operand, scope)
            sub, _ = self.bind_query(e.subquery, scope)
            if len(sub.schema) != 1:
                raise BindError("IN subquery must return exactly one column")
            return InSubqueryExpr(arg, sub, e.negated)
        if isinstance(e, a.Exists):
            sub, _ = self.bind_query(e.subquery, scope)
            return ExistsExpr(sub, e.negated)
        if isinstance(e, a.ScalarSubquery):
            sub, _ = self.bind_query(e.subquery, scope)
            if len(sub.schema) != 1:
                raise BindError("Scalar subquery must return exactly one column")
            return ScalarSubqueryExpr(sub, sub.schema[0].sql_type)
        if isinstance(e, a.Like):
            arg = self.bind_expr(e.operand, scope)
            pattern = self.bind_expr(e.pattern, scope)
            op = "similar" if e.similar else ("ilike" if e.case_insensitive else "like")
            args = (arg, pattern) if e.escape is None else (arg, pattern, Literal(e.escape, SqlType.VARCHAR))
            out = ScalarFunc(op, args, SqlType.BOOLEAN)
            if e.negated:
                return ScalarFunc("not", (out,), SqlType.BOOLEAN)
            return out
        if isinstance(e, a.IsNull):
            arg = self.bind_expr(e.operand, scope)
            return ScalarFunc("is_not_null" if e.negated else "is_null", (arg,), SqlType.BOOLEAN)
        if isinstance(e, a.IsBool):
            arg = self._coerce_bool(self.bind_expr(e.operand, scope))
            op = {(True, False): "is_true", (True, True): "is_not_true",
                  (False, False): "is_false", (False, True): "is_not_false"}[(e.value, e.negated)]
            return ScalarFunc(op, (arg,), SqlType.BOOLEAN)
        if isinstance(e, a.IsDistinctFrom):
            left = self.bind_expr(e.left, scope)
            right = self.bind_expr(e.right, scope)
            left, right = self._coerce_pair(left, right)
            op = "is_not_distinct_from" if e.negated else "is_distinct_from"
            return ScalarFunc(op, (left, right), SqlType.BOOLEAN)
        if isinstance(e, a.Extract):
            arg = self.bind_expr(e.operand, scope)
            return ScalarFunc(f"extract_{e.unit.lower()}", (arg,), SqlType.BIGINT)
        if isinstance(e, a.Substring):
            arg = self.bind_expr(e.operand, scope)
            start = self.bind_expr(e.start, scope) if e.start is not None else Literal(1, SqlType.BIGINT)
            args = [arg, start]
            if e.length is not None:
                args.append(self.bind_expr(e.length, scope))
            return ScalarFunc("substring", tuple(args), SqlType.VARCHAR)
        if isinstance(e, a.Trim):
            arg = self.bind_expr(e.operand, scope)
            op = {"BOTH": "btrim", "LEADING": "ltrim", "TRAILING": "rtrim"}[e.where]
            args = [arg]
            if e.chars is not None:
                args.append(self.bind_expr(e.chars, scope))
            return ScalarFunc(op, tuple(args), SqlType.VARCHAR)
        if isinstance(e, a.Position):
            needle = self.bind_expr(e.needle, scope)
            hay = self.bind_expr(e.haystack, scope)
            return ScalarFunc("position", (needle, hay), SqlType.INTEGER)
        if isinstance(e, a.Overlay):
            args = [self.bind_expr(e.operand, scope), self.bind_expr(e.replacement, scope),
                    self.bind_expr(e.start, scope)]
            if e.length is not None:
                args.append(self.bind_expr(e.length, scope))
            return ScalarFunc("overlay", tuple(args), SqlType.VARCHAR)
        if isinstance(e, a.CeilFloorTo):
            arg = self.bind_expr(e.operand, scope)
            op = "datetime_ceil" if e.func == "CEIL" else "datetime_floor"
            return ScalarFunc(op, (arg, Literal(e.unit, SqlType.VARCHAR)), arg.sql_type)
        if isinstance(e, a.Wildcard):
            raise BindError("Wildcard not allowed here")
        raise BindError(f"Cannot bind expression {type(e).__name__}")

    def _bind_binary(self, e: a.BinaryOp, scope: Scope) -> Expr:
        if e.op in ("AND", "OR"):
            left = self._coerce_bool(self.bind_expr(e.left, scope))
            right = self._coerce_bool(self.bind_expr(e.right, scope))
            return ScalarFunc(e.op.lower(), (left, right), SqlType.BOOLEAN)
        left = self.bind_expr(e.left, scope)
        right = self.bind_expr(e.right, scope)
        if e.op == "||":
            return ScalarFunc("concat", (left, right), SqlType.VARCHAR)
        if e.op in _CMP_OPS:
            left, right = self._coerce_pair(left, right)
            return ScalarFunc(_CMP_OPS[e.op], (left, right), SqlType.BOOLEAN)
        if e.op in _ARITH_OPS:
            return self._bind_arith(e.op, left, right)
        raise BindError(f"Unknown binary operator {e.op}")

    def _bind_arith(self, op: str, left: Expr, right: Expr) -> Expr:
        lt, rt = left.sql_type, right.sql_type
        # datetime arithmetic
        if lt in DATETIME_TYPES or rt in DATETIME_TYPES:
            if op == "-" and lt in DATETIME_TYPES and rt in DATETIME_TYPES:
                return ScalarFunc("datetime_sub", (left, right), SqlType.INTERVAL_DAY_TIME)
            if lt in DATETIME_TYPES and rt in INTERVAL_TYPES:
                return ScalarFunc("datetime_add" if op == "+" else "datetime_sub_interval",
                                  (left, right), lt)
            if rt in DATETIME_TYPES and lt in INTERVAL_TYPES and op == "+":
                return ScalarFunc("datetime_add", (right, left), rt)
            # Timestamp +- Int: reference preoptimizer datetime_coercion
            # (src/sql/preoptimizer.rs:10-21) treats the int as days
            if lt in DATETIME_TYPES and rt in INTEGER_TYPES:
                iv = ScalarFunc("int_to_interval_days", (right,), SqlType.INTERVAL_DAY_TIME)
                return ScalarFunc("datetime_add" if op == "+" else "datetime_sub_interval",
                                  (left, iv), lt)
            if rt in DATETIME_TYPES and lt in INTEGER_TYPES and op == "+":
                iv = ScalarFunc("int_to_interval_days", (left,), SqlType.INTERVAL_DAY_TIME)
                return ScalarFunc("datetime_add", (right, iv), rt)
        if lt in INTERVAL_TYPES or rt in INTERVAL_TYPES:
            if op in ("+", "-") and lt in INTERVAL_TYPES and rt in INTERVAL_TYPES:
                return ScalarFunc(_ARITH_OPS[op], (left, right), lt)
            if op == "*":
                return ScalarFunc("mul", (left, right), lt if lt in INTERVAL_TYPES else rt)
        left, right = self._coerce_pair(left, right)
        result = promote(left.sql_type, right.sql_type)
        if op == "/":
            # SQL division: int/int stays int (truncating) — reference
            # SQLDivisionOperator call.py:165
            return ScalarFunc("div", (left, right), result)
        return ScalarFunc(_ARITH_OPS[op], (left, right), result)

    def _bind_case(self, e: a.Case, scope: Scope) -> Expr:
        whens = []
        if e.operand is not None:
            operand = self.bind_expr(e.operand, scope)
            for cond, res in e.whens:
                c = self.bind_expr(cond, scope)
                o2, c2 = self._coerce_pair(operand, c)
                whens.append((ScalarFunc("eq", (o2, c2), SqlType.BOOLEAN),
                              self.bind_expr(res, scope)))
        else:
            for cond, res in e.whens:
                whens.append((self._coerce_bool(self.bind_expr(cond, scope)),
                              self.bind_expr(res, scope)))
        else_ = self.bind_expr(e.else_, scope) if e.else_ is not None else None
        # result type: promote all branches
        rtypes = [r.sql_type for _, r in whens] + ([else_.sql_type] if else_ is not None else [])
        rt = rtypes[0]
        for t in rtypes[1:]:
            rt = promote(rt, t)
        whens = tuple((c, r if r.sql_type == rt else Cast(r, rt)) for c, r in whens)
        if else_ is not None and else_.sql_type != rt:
            else_ = Cast(else_, rt)
        return CaseExpr(whens, else_, rt)

    def _bind_function(self, e: a.FunctionCall, scope: Scope) -> Expr:
        name = e.name.upper()
        if name == "GROUPING" and e.over is None:
            # bound before the generic arg loop so a select alias can serve
            # as a GROUPING argument (same leniency GROUP BY itself has)
            if not e.args or any(isinstance(x, a.Wildcard) for x in e.args):
                raise BindError("GROUPING requires column arguments")
            from .expressions import GroupingExpr

            bound = []
            amap = getattr(self, "_select_alias_asts", None) or {}
            for arg in e.args:
                try:
                    bound.append(self.bind_expr(arg, scope))
                except BindError:
                    if isinstance(arg, a.Identifier) and len(arg.parts) == 1:
                        key = (arg.parts[0] if self.case_sensitive
                               else arg.parts[0].lower())
                        ast2 = amap.get(key)
                        if ast2 is not None:
                            bound.append(self.bind_expr(ast2, scope))
                            continue
                    raise
            return GroupingExpr(tuple(bound), SqlType.INTEGER)
        args = []
        for arg in e.args:
            if isinstance(arg, a.Wildcard):
                args.append(None)  # COUNT(*)
            else:
                args.append(self.bind_expr(arg, scope))
        # window function?
        if e.over is not None:
            return self._bind_window_call(name, args, e, scope)
        # aggregate?
        if name in AGGREGATE_FUNCTIONS:
            return self._make_agg(name, args, e, scope)
        # UDF / user aggregation (reference call.py:1193-1199 fallback)
        fns = self.catalog.resolve_function(e.name) or self.catalog.resolve_function(e.name.lower())
        if fns:
            fd = _pick_overload(fns, args)
            if fd.aggregation:
                return AggExpr("udaf:" + fd.name, tuple(args), fd.return_type, e.distinct,
                               self._bind_filter(e, scope))
            cast_args = tuple(
                arg if i >= len(fd.parameters) or arg.sql_type == fd.parameters[i][1]
                else Cast(arg, fd.parameters[i][1])
                for i, arg in enumerate(args)
            )
            return UdfExpr(fd.name, cast_args, fd.return_type, fd.row_udf)
        if name in SCALAR_FUNCTIONS:
            op, rt, lo, hi = SCALAR_FUNCTIONS[name]
            if not (lo <= len(args) <= hi):
                raise BindError(f"{name} expects {lo}..{hi} args, got {len(args)}")
            return ScalarFunc(op, tuple(args), resolve_type(rt, [x.sql_type for x in args]))
        raise BindError(f"Unknown function {e.name!r}")

    def _bind_filter(self, e: a.FunctionCall, scope: Scope) -> Optional[Expr]:
        if e.filter is None:
            return None
        return self._coerce_bool(self.bind_expr(e.filter, scope))

    def _make_agg(self, name: str, args, e: a.FunctionCall, scope: Scope) -> AggExpr:
        op, rt = AGGREGATE_FUNCTIONS[name]
        filt = self._bind_filter(e, scope)
        if name == "COUNT" and (not args or args[0] is None):
            return AggExpr("count_star", (), SqlType.BIGINT, e.distinct, filt)
        if any(arg is None for arg in args):
            raise BindError(f"* argument only allowed in COUNT")
        arg_types = [x.sql_type for x in args]
        return AggExpr(op, tuple(args), resolve_type(rt, arg_types), e.distinct, filt)

    def _bind_window_call(self, name, args, e: a.FunctionCall, scope: Scope) -> WindowExpr:
        spec = e.over
        if isinstance(spec, str):
            named = getattr(self, "_named_windows", {})
            if spec in named:
                spec = named[spec]
            elif not self.case_sensitive and spec.lower() in {
                    k.lower() for k in named}:
                spec = next(v for k, v in named.items() if k.lower() == spec.lower())
            else:
                raise BindError(f"Unknown window name {spec!r}")
        partition = tuple(self.bind_expr(x, scope) for x in spec.partition_by)
        order = tuple(
            SortKey(self.bind_expr(it.expr, scope), it.ascending, it.nulls_first)
            for it in spec.order_by
        )
        if name in WINDOW_FUNCTIONS:
            rt = WINDOW_FUNCTIONS[name]
            func = name.lower()
            sql_type = resolve_type(rt, [x.sql_type for x in args if x is not None])
        elif name in AGGREGATE_FUNCTIONS:
            op, rt = AGGREGATE_FUNCTIONS[name]
            if name == "COUNT" and (not args or args[0] is None):
                func, sql_type = "count_star", SqlType.BIGINT
                args = []
            else:
                func = op
                sql_type = resolve_type(rt, [x.sql_type for x in args])
        else:
            raise BindError(f"Unknown window function {name!r}")
        if spec.frame is not None:
            units = spec.frame.units
            start = _bind_bound(spec.frame.start, units)
            end = _bind_bound(spec.frame.end, units)
            wspec = WindowSpec(partition, order, units, start, end, True)
        else:
            # default frame: RANGE UNBOUNDED PRECEDING..CURRENT ROW when ordered,
            # else the whole partition
            if order:
                wspec = WindowSpec(partition, order, "RANGE",
                                   WindowFrameBound("UNBOUNDED_PRECEDING"),
                                   WindowFrameBound("CURRENT_ROW"), False)
            else:
                wspec = WindowSpec(partition, order, "ROWS",
                                   WindowFrameBound("UNBOUNDED_PRECEDING"),
                                   WindowFrameBound("UNBOUNDED_FOLLOWING"), False)
        return WindowExpr(func, tuple(a_ for a_ in args if a_ is not None), wspec,
                          sql_type, e.ignore_nulls)

    # ------------------------------------------------------------- coercion
    def _coerce_bool(self, e: Expr) -> Expr:
        if e.sql_type == SqlType.BOOLEAN:
            return e
        if e.sql_type in NUMERIC_TYPES:
            return Cast(e, SqlType.BOOLEAN)
        if e.sql_type == SqlType.NULL:
            return Cast(e, SqlType.BOOLEAN)
        raise BindError(f"Expected boolean expression, got {e.sql_type}")

    def _coerce_pair(self, left: Expr, right: Expr) -> Tuple[Expr, Expr]:
        lt, rt = left.sql_type, right.sql_type
        if lt == rt:
            return left, right
        # string literal vs datetime/numeric: cast the literal
        if isinstance(right, Literal) and rt in STRING_TYPES and lt not in STRING_TYPES:
            return left, _cast_literal(right, lt)
        if isinstance(left, Literal) and lt in STRING_TYPES and rt not in STRING_TYPES:
            return _cast_literal(left, rt), right
        try:
            target = promote(lt, rt)
        except NotImplementedError:
            raise BindError(f"Cannot compare {lt} with {rt}")
        l2 = left if lt == target else Cast(left, target)
        r2 = right if rt == target else Cast(right, target)
        return l2, r2

    # ----------------------------------------------------------------- misc
    def _derive_name(self, e: a.Expr) -> str:
        if isinstance(e, a.Identifier):
            return e.parts[-1]
        if isinstance(e, a.FunctionCall):
            return e.name
        if isinstance(e, a.Cast):
            return self._derive_name(e.operand)
        if isinstance(e, a.Literal):
            return str(e.value)
        if isinstance(e, a.Extract):
            return "EXTRACT"
        if isinstance(e, a.Case):
            return "CASE"
        return "EXPR"

    def _derive_name_expr(self, e: Expr, i: int) -> str:
        if isinstance(e, ColumnRef):
            return e.name
        return f"__group{i}"


class _OuterRef(ColumnRef):
    """Correlated reference to the immediately-enclosing query's scope.

    Parity: the correlated columns DataFusion's decorrelation rules track
    (optimizer/decorrelate_where_*.rs in the reference).
    """


def _subst_select_aliases(node, alias_map, should_subst, fold=lambda s: s):
    """Rewrite single-part Identifiers matching a select alias with that
    item's AST expression (HAVING may reference select aliases of
    aggregates, as the reference planner's SqlToRel resolves — VERDICT r2
    missing #5).  `fold` case-folds lookups to match the binder's identifier
    matching mode.  Does not descend into subqueries (own scopes)."""
    import dataclasses

    if isinstance(node, a.Identifier):
        if len(node.parts) == 1:
            target = alias_map.get(fold(node.parts[0]))
            if target is not None and should_subst(node):
                return target
        return node
    if isinstance(node, a.Select) or not dataclasses.is_dataclass(node):
        return node

    def walk_val(v):
        if isinstance(v, a.Expr):
            return _subst_select_aliases(v, alias_map, should_subst, fold)
        if isinstance(v, list):
            return [walk_val(x) for x in v]
        if isinstance(v, tuple):
            return tuple(walk_val(x) for x in v)
        return v

    kw = {f.name: walk_val(getattr(node, f.name))
          for f in dataclasses.fields(node)}
    return type(node)(**kw)


def _pick_overload(fns, args):
    """Choose the registered overload whose arity matches (parity: the
    reference's DaskFunction signature map, function.rs)."""
    n = len(args)
    exact = [fd for fd in fns if len(fd.parameters) == n]
    if exact:
        # prefer type-compatible signatures
        for fd in exact:
            if all(similar_type(a.sql_type, p_[1]) for a, p_ in zip(args, fd.parameters)):
                return fd
        return exact[0]
    return fns[0]


def _split_alias(alias):
    if alias is None:
        return None, None
    if isinstance(alias, tuple):
        return alias[0], alias[1]
    return alias, None


def _bind_bound(bound, units: str) -> WindowFrameBound:
    kind, offset = bound
    off = None
    if offset is not None:
        if isinstance(offset, a.IntervalLiteral):
            if units != "RANGE":
                raise BindError("Interval frame offsets require RANGE frames")
            lit = _bind_interval(offset)
            if lit.sql_type == SqlType.INTERVAL_YEAR_MONTH:
                raise BindError(
                    "Year-month intervals are not supported as RANGE offsets; "
                    "use day-time intervals (e.g. INTERVAL '30' DAY)")
            off = lit.value  # day-time interval: nanoseconds
        elif isinstance(offset, a.Literal) and isinstance(offset.value, (int, float)):
            if units == "ROWS" and not isinstance(offset.value, int):
                raise BindError("ROWS frame offsets must be integer literals")
            off = offset.value
        else:
            raise BindError("Window frame offsets must be numeric or interval literals")
    return WindowFrameBound(kind, off)


def _bind_literal(e: a.Literal) -> Literal:
    v = e.value
    if e.type_name == "DATE":
        ns = np.datetime64(v, "ns").astype(np.int64)
        ns = (ns // 86_400_000_000_000) * 86_400_000_000_000
        return Literal(int(ns), SqlType.DATE)
    if e.type_name in ("TIMESTAMP", "TIME"):
        return Literal(int(np.datetime64(v, "ns").astype(np.int64)), SqlType.TIMESTAMP)
    if v is None:
        return Literal(None, SqlType.NULL)
    if isinstance(v, bool):
        return Literal(v, SqlType.BOOLEAN)
    if isinstance(v, int):
        t = SqlType.INTEGER if -(2**31) <= v < 2**31 else SqlType.BIGINT
        return Literal(v, t)
    if isinstance(v, float):
        return Literal(v, SqlType.DOUBLE)
    if isinstance(v, str):
        return Literal(v, SqlType.VARCHAR)
    raise BindError(f"Cannot bind literal {v!r}")


def _cast_literal(lit: Literal, target: SqlType) -> Literal:
    v = lit.value
    if target in DATETIME_TYPES:
        if lit.sql_type in DATETIME_TYPES:
            # already epoch nanoseconds
            ns = int(v)
        else:
            ns = int(np.datetime64(str(v).strip(), "ns").astype(np.int64))
        if target == SqlType.DATE:
            ns = (ns // 86_400_000_000_000) * 86_400_000_000_000
        return Literal(int(ns), target)
    if lit.sql_type in DATETIME_TYPES or lit.sql_type in INTERVAL_TYPES:
        if target in INTEGER_TYPES:
            return Literal(int(v), target)
        return lit
    if target in INTEGER_TYPES:
        return Literal(int(v), target)
    if target in (SqlType.FLOAT, SqlType.DOUBLE, SqlType.DECIMAL, SqlType.REAL):
        return Literal(float(v), target)
    if target == SqlType.BOOLEAN:
        return Literal(str(v).strip().lower() in ("true", "t", "1", "yes"), target)
    return lit


def _bind_interval(e: a.IntervalLiteral) -> Literal:
    unit = e.unit.split(" TO ")[0]
    text = e.value.strip()
    if unit in _INTERVAL_MONTHS and re.fullmatch(r"-?\d+", text):
        months = int(text) * _INTERVAL_MONTHS[unit]
        return Literal(months, SqlType.INTERVAL_YEAR_MONTH)
    # day-time intervals, possibly compound '1 02:03:04.5'
    total_ns = 0
    neg = text.startswith("-")
    if neg:
        text = text[1:]
    if re.fullmatch(r"\d+(\.\d+)?", text):
        total_ns = int(float(text) * _INTERVAL_NS.get(unit, 1_000_000_000))
    else:
        m = re.fullmatch(r"(?:(\d+)\s+)?(\d+):(\d+)(?::(\d+(?:\.\d+)?))?", text)
        if not m:
            raise BindError(f"Bad interval literal {e.value!r}")
        days = int(m.group(1) or 0)
        h, mi = int(m.group(2)), int(m.group(3))
        s = float(m.group(4) or 0)
        total_ns = int(((days * 24 + h) * 3600 + mi * 60 + s) * 1_000_000_000)
    if neg:
        total_ns = -total_ns
    return Literal(total_ns, SqlType.INTERVAL_DAY_TIME)


def _nullable(e: Expr) -> bool:
    if isinstance(e, Literal):
        return e.value is None
    if isinstance(e, ColumnRef):
        return e.nullable
    return True


# ---------------------------------------------------------------------------
# Join-condition analysis (parity: reference join.py:250 _split_join_condition)
# ---------------------------------------------------------------------------
def split_join_condition(cond: Expr, nleft: int):
    """Split a bound join condition into equi-key pairs + residual filter.

    Key pairs are (left_expr, right_expr) where left refers only to columns
    < nleft and right only to columns >= nleft (right exprs keep combined
    indices; the physical layer re-bases them).
    """
    from .expressions import referenced_columns

    conjuncts = _flatten_and(cond)
    on, residual = [], []
    for c in conjuncts:
        if isinstance(c, Literal) and c.value is True:
            continue
        if isinstance(c, ScalarFunc) and c.op == "eq":
            l, r = c.args
            lcols, rcols = referenced_columns(l), referenced_columns(r)
            if lcols and rcols:
                if max(lcols) < nleft and min(rcols) >= nleft:
                    on.append((l, r))
                    continue
                if max(rcols) < nleft and min(lcols) >= nleft:
                    on.append((r, l))
                    continue
        residual.append(c)
    resid = None
    if residual:
        resid = residual[0]
        for c in residual[1:]:
            resid = ScalarFunc("and", (resid, c), SqlType.BOOLEAN)
    return on, resid


def _flatten_and(e: Expr) -> List[Expr]:
    if isinstance(e, ScalarFunc) and e.op == "and":
        out = []
        for c in e.args:
            out.extend(_flatten_and(c))
        return out
    return [e]
