"""ServingRuntime: the worker pool queries actually run on.

Replaces the server's bare ThreadPoolExecutor (fixed workers, unbounded
submission) with class-aware scheduling over the admission controller:

- ``interactive`` work is always popped first;
- ``batch`` work runs only while fewer than ``batch_max_running`` batch
  queries are in flight, so a burst of reports cannot occupy every worker;
- a submit past the class queue bound raises `QueueFullError` *in the
  submitting thread* (the server turns it into a retry-after wire error);
- each admitted query carries a `QueryTicket`; while the query runs the
  ticket is installed in a thread-local that `physical/executor.py` polls
  at per-node cancellation checkpoints, so deadline expiry and client
  cancels take effect mid-plan instead of after the fact.

The GIL drops during device execution, so host-side parse/plan/decode of
one query overlaps device compute of another (the analogue of the
reference's overlapping distributed futures, reference server/app.py:89).
"""
from __future__ import annotations

import contextlib
import logging
import threading
import time
import uuid
from collections import deque
from concurrent.futures import Future, InvalidStateError
from typing import Callable, Dict, Optional, Tuple

from ..resilience.errors import ShutdownError
from ..runtime import locks
from ..resilience.retry import BackoffPolicy, retry_call
from .admission import (
    CLASSES,
    AdmissionController,
    DeadlineExceededError,
    QueryCancelledError,
    QueryTicket,
)
from .metrics import MetricsRegistry
from .scheduler import PackingScheduler, QueryCost

logger = logging.getLogger(__name__)

_tls = threading.local()


def _resolve(fut: Future, result=None, exc: Optional[BaseException] = None,
             ) -> bool:
    """Set a future's outcome, tolerating a future someone else already
    resolved — the bounded-drain deadline (shutdown) and a replica kill
    (fleet/replica.py) both fail in-flight futures from OUTSIDE the worker
    thread, and the worker's own completion must then be a no-op instead
    of an InvalidStateError crash.  Returns False when the future was
    already resolved."""
    try:
        if exc is not None:
            fut.set_exception(exc)
        else:
            fut.set_result(result)
        return True
    except InvalidStateError:
        return False


def current_ticket() -> Optional[QueryTicket]:
    """The ticket of the query running on this thread, if any — the
    executor's cancellation checkpoints poll this."""
    return getattr(_tls, "ticket", None)


@contextlib.contextmanager
def ticket_scope(ticket: QueryTicket):
    """Install ``ticket`` as this thread's current ticket for the dynamic
    extent.  The serving workers install tickets directly; this scope is
    for executions OUTSIDE the worker pool (the Context API path), so
    ``CANCEL QUERY`` on their live-registry entry reaches the executor's
    cooperative checkpoints too."""
    prev = getattr(_tls, "ticket", None)
    _tls.ticket = ticket
    try:
        yield ticket
    finally:
        _tls.ticket = prev


class ServingRuntime:
    def __init__(self, workers: int = 8,
                 bounds: Optional[Dict[str, int]] = None,
                 batch_max_running: Optional[int] = None,
                 retry_after_s: float = 1.0,
                 default_deadline_s: Optional[float] = None,
                 metrics: Optional[MetricsRegistry] = None,
                 retry_policy: Optional[BackoffPolicy] = None,
                 batch_queries: int = 8,
                 batch_window_ms: float = 2.0,
                 scheduler_enabled: bool = True,
                 scheduler_budget_bytes: Optional[int] = None,
                 tenant_rate: Optional[float] = None,
                 tenant_burst: float = 4.0,
                 fair_horizon_s: float = 30.0,
                 drain_timeout_s: float = 30.0):
        self.workers = max(1, int(workers))
        self.metrics = metrics if metrics is not None else MetricsRegistry()
        #: backoff policy for taxonomy-retryable failures (resilience/retry.py)
        self.retry_policy = retry_policy if retry_policy is not None \
            else BackoffPolicy()
        self.admission = AdmissionController(
            bounds or {"interactive": 32, "batch": 64}, self.workers,
            retry_after_s=retry_after_s, metrics=self.metrics)
        from ..families.batcher import FamilyBatcher

        #: family batcher (families/batcher.py): concurrently admitted
        #: same-family queries coalesce into one stacked kernel launch.
        #: The busy probe gates the leader's rendezvous window on OTHER
        #: queries actually being in flight, so idle traffic pays nothing.
        self.batcher = FamilyBatcher(
            max_queries=batch_queries, window_ms=batch_window_ms,
            metrics=self.metrics, busy=self._others_in_flight,
            mates=self._family_mates)
        # 0 is a legitimate setting (pause batch entirely), so only None
        # falls back to the workers-1 default
        self.batch_max_running = int(batch_max_running) \
            if batch_max_running is not None else max(1, self.workers - 1)
        self.default_deadline_s = default_deadline_s
        self._queues: Dict[str, deque] = {c: deque() for c in CLASSES}
        #: the packing scheduler (serving/scheduler.py) replaces the FIFO
        #: deques when enabled; its state is guarded by `_cv`, never a lock
        #: of its own.  Disabled (`serving.scheduler.enabled=false`) the
        #: deques above keep today's FIFO behavior byte-for-byte.
        self.scheduler: Optional[PackingScheduler] = PackingScheduler(
            budget_bytes=scheduler_budget_bytes,
            tenant_rate=tenant_rate, tenant_burst=tenant_burst,
            fair_horizon_s=fair_horizon_s,
            metrics=self.metrics) if scheduler_enabled else None
        # rank 40: held while calling admission.on_finish (rank 45) on
        # the shed path, so cv-before-admission is the declared order
        self._cv = locks.named_condition("serving.runtime.cv")
        #: batch queries popped-but-not-finished, owned by _cv (admission's
        #: running counter is updated later under its own lock, so checking
        #: it from _pop_locked would let a burst overshoot the cap)
        self._batch_in_flight = 0
        #: bound on shutdown(wait=True)'s drain: past it, still-running
        #: queries are cancelled and their futures failed with a retryable
        #: ShutdownError instead of the drain hanging forever
        self.drain_timeout_s = max(0.0, float(drain_timeout_s))
        #: in-flight (popped, running) work: qid -> (ticket, future),
        #: owned by _cv — what the bounded drain fails at its deadline and
        #: a replica kill (fleet/replica.py) fails immediately
        self._inflight: Dict[str, Tuple[QueryTicket, Future]] = {}
        self._shutdown = False
        #: auxiliary background workers (warm-up pass, background
        #: recompiler) that shutdown() must cancel and join — worker queues
        #: alone draining is not a full drain (ISSUE 7 regression)
        self._background: list = []
        self._threads = [
            threading.Thread(target=self._worker, daemon=True,
                             name=f"dsql-serving-{i}")
            for i in range(self.workers)
        ]
        for t in self._threads:
            t.start()

    @classmethod
    def from_config(cls, config, metrics=None) -> "ServingRuntime":
        """Build from the ``serving.*`` keys (see config.py docstrings)."""
        from ..config import parse_byte_budget

        # the packer's budget: its own key when set, else the admission
        # gate's byte budget (one budget is the common deployment; a
        # separate scheduler budget exists for packing tighter or looser
        # than the shed threshold)
        budget = parse_byte_budget(
            config.get("serving.scheduler.device_budget_bytes"))
        if budget is None:
            budget = parse_byte_budget(
                config.get("serving.admission.max_estimated_bytes"))
        rate = config.get("serving.tenant.rate_qps")
        return cls(
            workers=int(config.get("serving.workers", 8)),
            bounds={
                "interactive": int(config.get("serving.queue.interactive", 32)),
                "batch": int(config.get("serving.queue.batch", 64)),
            },
            batch_max_running=config.get("serving.batch.max_running"),
            retry_after_s=float(config.get("serving.retry_after_s", 1.0)),
            default_deadline_s=config.get("serving.deadline_s"),
            metrics=metrics,
            retry_policy=BackoffPolicy.from_config(config),
            batch_queries=int(config.get("serving.batch.max_queries", 8) or 1),
            batch_window_ms=float(
                config.get("serving.batch.window_ms", 2.0) or 0.0),
            scheduler_enabled=bool(
                config.get("serving.scheduler.enabled", True)),
            scheduler_budget_bytes=budget,
            tenant_rate=None if rate is None else float(rate),
            tenant_burst=float(config.get("serving.tenant.burst", 4.0)),
            fair_horizon_s=float(
                config.get("serving.scheduler.fair_horizon_s", 30.0)),
            drain_timeout_s=float(
                config.get("serving.shutdown.drain_timeout_s", 30.0)),
        )

    def _others_in_flight(self) -> bool:
        """True when any OTHER query is admitted right now (running on a
        worker or still waiting in a class queue) — the only situation
        where a batch leader's rendezvous window can pay off.  Waiting
        queries count: a burst submits faster than workers wake, so an
        early leader would otherwise see running == 1 and skip the window
        its own batch-mates are about to fill."""
        with self.admission._lock:
            return (sum(self.admission.running.values())
                    + sum(self.admission.waiting.values())) > 1

    def _family_mates(self) -> int:
        """How many OTHER admitted queries share the calling thread's plan
        family — the packer's co-scheduling knowledge, handed to the family
        batcher so a leader whose batch-mates were packed alongside it
        waits the rendezvous window with certainty instead of relying on
        the in-flight heuristic.  0 when the scheduler is off or the
        current query submitted without a family cost hint."""
        if self.scheduler is None:
            return 0
        ticket = current_ticket()
        cost = getattr(ticket, "cost", None) if ticket is not None else None
        if cost is None or not cost.family:
            return 0
        with self._cv:
            return self.scheduler.family_mates_locked(
                cost.family, exclude_qid=ticket.qid)

    # -------------------------------------------------------------- submit
    def submit(self, fn: Callable[[QueryTicket], object],
               qid: Optional[str] = None,
               priority_class: str = "interactive",
               deadline_s: Optional[float] = None,
               cost: Optional[QueryCost] = None,
               ) -> Tuple[str, Future, QueryTicket]:
        """Admit and enqueue `fn(ticket)`; raises `QueueFullError` when the
        class queue is at its bound (load shedding, never blocks).

        ``cost`` is the packing scheduler's view of the query (provable
        peak-byte floor, predicted exec, tenant, family); None degrades to
        the zero cost — FIFO-equivalent treatment, no reservation."""
        if self._shutdown:
            raise ShutdownError("serving runtime is shut down")
        from .admission import QueueFullError

        from ..observability import flight

        if priority_class == "batch" and self.batch_max_running == 0:
            # batch is paused: shed immediately instead of admitting work
            # that no worker would ever pop (client would hang in QUEUED)
            self.metrics.inc("serving.rejected")
            self.metrics.inc("serving.rejected.batch")
            flight.record("query.shed", qid=qid, reason="batch_paused")
            raise QueueFullError("batch", 0, self.admission.retry_after_s)
        qid = qid or str(uuid.uuid4())
        if deadline_s is None:
            deadline_s = self.default_deadline_s
        try:
            ticket = self.admission.admit(qid, priority_class, deadline_s)
        except QueueFullError as e:
            flight.record("query.shed", qid=qid, reason="queue_full",
                          cls=priority_class)
            drain = self._predicted_drain_s()
            if drain is not None and drain > e.retry_after_s:
                # the scheduler predicts the drain from running queries'
                # remaining predicted exec + the queued backlog — a better
                # hint than the admission controller's latency average
                from .admission import retry_after_cap

                raise QueueFullError(e.priority_class, e.bound,
                                     min(retry_after_cap(), drain)) from None
            raise
        try:
            ticket.cost = cost
            flight.record("query.admit", qid=qid, cls=priority_class,
                          tenant=(cost.tenant or None) if cost is not None
                          else None)
            fut: Future = Future()
            with self._cv:
                if self._shutdown:
                    # lost the race with a concurrent shutdown(): enqueueing
                    # now would strand the future (the drain already ran)
                    raise ShutdownError("serving runtime is shut down")
                if self.scheduler is not None:
                    self.scheduler.push_locked(ticket, fn, fut, cost)
                else:
                    self._queues[ticket.priority_class].append(
                        (ticket, fn, fut))
                self._cv.notify()
        except BaseException:
            # admitted but never reached the queue (push_locked validation,
            # the shutdown race, even a flight-recorder failure): undo the
            # admission charge exactly once, or depth/byte accounting leaks
            # until restart
            self.admission.on_finish(ticket, started=False)
            raise
        return qid, fut, ticket

    def _predicted_drain_s(self) -> Optional[float]:
        if self.scheduler is None:
            return None
        with self._cv:
            return self.scheduler.predicted_drain_s(self.workers)

    # -------------------------------------------------------------- workers
    def _pop_locked(self):
        if self.scheduler is not None:
            item = self.scheduler.pop_locked(
                batch_ok=self._batch_in_flight < self.batch_max_running)
            if item is not None and item[0].priority_class == "batch":
                self._batch_in_flight += 1
            return item
        q = self._queues["interactive"]
        if q:
            return q.popleft()
        q = self._queues["batch"]
        if q and self._batch_in_flight < self.batch_max_running:
            self._batch_in_flight += 1
            return q.popleft()
        return None

    def _worker(self):
        while True:
            with self._cv:
                # conditional pop: a None result acquires nothing; a
                # non-None item's reservation is released by the
                # try/finally below — path-correlated, which the CFG
                # proof cannot see
                # dsql: allow-unpaired-effect — released by _release below
                item = self._pop_locked()
                while item is None and not self._shutdown:
                    self._cv.wait()
                    # dsql: allow-unpaired-effect — same conditional pop
                    item = self._pop_locked()
                if item is None:  # shutdown with a drained queue
                    return
            ticket, fn, fut = item
            try:
                self._run_one(ticket, fn, fut)
            finally:
                # the batch slot and the packer's byte reservation are
                # freed on EVERY outcome — including a bug between pop and
                # execution, which previously leaked the reservation and
                # killed the worker thread
                self._release(ticket)

    def _run_one(self, ticket: QueryTicket, fn, fut: Future) -> None:
        """Run one popped item to a terminal state: admission accounting,
        cancellation/expiry checks, taxonomy-aware retry, future
        resolution.  The caller owns the scheduler reservation and calls
        `_release` whatever happens here."""
        if not fut.set_running_or_notify_cancel():
            # cancelled while queued through Future.cancel()
            self.admission.on_finish(ticket, started=False)
            self.metrics.inc("serving.cancelled")
            return
        if ticket.cancelled or ticket.expired():
            self.admission.on_finish(ticket, started=False)
            if ticket.cancelled:
                self.metrics.inc("serving.cancelled")
                _resolve(fut, exc=QueryCancelledError(
                    f"query {ticket.qid} cancelled"))
            else:
                self.metrics.inc("serving.timeouts")
                _resolve(fut, exc=DeadlineExceededError(
                    f"query {ticket.qid} expired while queued"))
            return
        if ticket.queue_reason is None:
            # the scheduler stamps byte_blocked/quota_throttled at
            # dispatch; anything else waited only for a free worker
            ticket.queue_reason = "workers_busy"
        self.admission.on_start(ticket)
        with self._cv:
            self._inflight[ticket.qid] = (ticket, fut)
        _tls.ticket = ticket
        try:
            # taxonomy-retryable failures (transient device/runtime
            # errors) are retried here with backoff, bounded by the
            # ticket's deadline; everything else surfaces on first throw
            result = retry_call(lambda: fn(ticket), self.retry_policy,
                                ticket=ticket, metrics=self.metrics)
        except QueryCancelledError as e:
            self.metrics.inc("serving.cancelled")
            _resolve(fut, exc=e)
        except DeadlineExceededError as e:
            self.metrics.inc("serving.timeouts")
            _resolve(fut, exc=e)
        except BaseException as e:  # dsql: allow-broad-except — surfaced via Future
            self.metrics.inc("serving.failed")
            _resolve(fut, exc=e)
        else:
            self.metrics.inc("serving.completed")
            _resolve(fut, result=result)
        finally:
            _tls.ticket = None
            with self._cv:
                self._inflight.pop(ticket.qid, None)
            self.admission.on_finish(ticket)
            if ticket.started_at is not None:
                self.metrics.observe(
                    "serving.latency_ms",
                    (time.monotonic() - ticket.admitted_at) * 1000.0)

    def _release(self, ticket: QueryTicket):
        """Return a popped item's scheduling slot: frees the batch
        running-cap (and the packer's byte reservation — on EVERY outcome,
        including a mid-pack failure) and wakes workers blocked on it."""
        with self._cv:
            if ticket.priority_class == "batch":
                self._batch_in_flight -= 1
            if self.scheduler is not None:
                # reconcile the reservation with the measured footprint
                # the executing thread recorded (None when it never ran)
                self.scheduler.release_locked(
                    ticket, getattr(ticket, "measured_bytes", None))
            self._cv.notify_all()

    # ------------------------------------------------------------ lifecycle
    def register_background(self, worker) -> None:
        """Track an auxiliary background worker (must expose ``cancel()``
        and ``join(timeout)``) so shutdown() drains it with the queues.
        Registering against an already-shut-down runtime cancels the
        worker immediately — shutdown() has drained its snapshot and will
        never see this one."""
        with self._cv:
            if not self._shutdown:
                self._background.append(worker)
                return
        try:
            worker.cancel()
        except Exception:  # dsql: allow-broad-except — same policy as the
            # shutdown drain: a worker's teardown bug must not propagate
            logger.warning("background worker cancel failed", exc_info=True)

    def fail_inflight(self, exc_factory) -> int:
        """Fail every in-flight (popped, running) query's future NOW with
        ``exc_factory(ticket)`` and cancel its ticket — the replica-kill
        path (fleet/replica.py): a killed process resolves nothing, so the
        router must see its dispatched futures fail immediately instead of
        waiting out a result timeout.  The worker threads still unwind
        their (now-orphaned) executions; their own completion attempts
        no-op through `_resolve`.  Returns how many futures were failed."""
        with self._cv:
            inflight = list(self._inflight.values())
        failed = 0
        for ticket, fut in inflight:
            ticket.cancel()
            if _resolve(fut, exc=exc_factory(ticket)):
                failed += 1
        return failed

    def shutdown(self, wait: bool = False, timeout: float = 5.0) -> None:
        """Stop accepting work and drain deterministically.

        Queued-but-not-started queries fail immediately with a structured
        (retryable) `ShutdownError` — another replica or a restart can take
        them — instead of hanging on futures no worker will ever pop.
        Registered background workers (the warm-up pass, the background
        recompiler) are cancelled too; ``wait=True`` joins the worker
        threads AND the background threads, so a drained runtime leaves no
        thread still compiling.

        The ``wait=True`` drain is BOUNDED by ``drain_timeout_s``
        (``serving.shutdown.drain_timeout_s``): an in-flight query that
        has not finished by the deadline — a stuck row-UDF, a wedged
        device call — has its ticket cancelled and its future failed with
        a retryable `ShutdownError`, so the drain (and every client
        blocked on a drained future) terminates instead of hanging
        forever on work that will never yield."""
        drained = []
        with self._cv:
            self._shutdown = True
            background = list(self._background)
            for cls in CLASSES:
                q = self._queues[cls]
                while q:
                    drained.append(q.popleft())
            if self.scheduler is not None:
                drained.extend(self.scheduler.drain_all_locked())
            self._cv.notify_all()
        for ticket, _fn, fut in drained:
            self.admission.on_finish(ticket, started=False)
            self.metrics.inc("serving.shutdown_shed")
            if fut.set_running_or_notify_cancel():
                fut.set_exception(ShutdownError(
                    f"query {ticket.qid} shed: serving runtime shutting down"))
        for worker in background:
            try:
                worker.cancel()
            except Exception:  # dsql: allow-broad-except — one worker's
                # teardown bug must not block the rest of the drain
                logger.warning("background worker cancel failed",
                               exc_info=True)
        if wait:
            deadline = time.monotonic() + self.drain_timeout_s
            for t in self._threads:
                t.join(max(0.0, deadline - time.monotonic()))
            expired = [t for t in self._threads if t.is_alive()]
            if expired:
                # deadline: cancel the stuck queries cooperatively AND
                # fail their futures — the cancel reaches well-behaved
                # work at its next checkpoint, the future resolution
                # unblocks clients from work that never checkpoints
                n = self.fail_inflight(lambda ticket: ShutdownError(
                    f"query {ticket.qid} shed: drain timeout "
                    f"({self.drain_timeout_s:g}s) expired at shutdown"))
                if n:
                    self.metrics.inc("serving.shutdown_shed", n)
                    logger.warning(
                        "shutdown drain timed out after %gs; failed %d "
                        "in-flight futures with retryable ShutdownError",
                        self.drain_timeout_s, n)
            for worker in background:
                worker.join(timeout)

    def snapshot(self) -> Dict[str, object]:
        adm = self.admission.snapshot()
        with self._cv:
            if self.scheduler is not None:
                queues = {c: self.scheduler.depth_locked(c) for c in CLASSES}
                sched = self.scheduler.snapshot_locked()
            else:
                queues = {c: len(self._queues[c]) for c in CLASSES}
                sched = None
        out = {
            "workers": self.workers,
            "batchMaxRunning": self.batch_max_running,
            "queues": queues,
            "admission": adm,
            "familyBatcher": self.batcher.snapshot(),
        }
        if sched is not None:
            out["scheduler"] = sched
        return out
