"""Fixed-shape row partitions of a registered columnar table.

The streamed rungs (streaming/aggregate.py, streaming/select.py) execute a
provably-oversize scan as N pipelined launches of ONE morsel-shaped
executable, so every chunk must present the *identical* array shapes to the
kernel — otherwise each partition would pay a fresh XLA trace and the
zero-recompile family guarantee (families/, PR 7) would not survive the
partition axis.  Two mechanisms keep the shape static without ever
allocating pad buffers:

- every chunk is an exact ``chunk_rows``-long positional slice of the
  stored column buffers (DICT/FOR codes slice like values, so the h2d /
  working-set bytes of a chunk are its ENCODED bytes — the compressed-wire
  argument of arXiv:2506.10092 applied to the time axis);
- the FINAL chunk, which would come up short, slides its window back to
  ``total - chunk_rows`` and masks the overlap with ``row_valid`` — the
  same padded-table mask the compiled kernels already fold into their
  selection (physical/compiled.py `_trace_prelude`), so overlap rows are
  provably never counted, never aggregated, never gathered.

RLE columns are run-aligned and do not slice positionally; eligibility
checks (streaming/plan.py) decline them before a partition is ever cut.
"""
from __future__ import annotations

from typing import List, Tuple

import jax.numpy as jnp

from ..columnar.table import Table


def partition_layout(total_rows: int, chunk_rows: int
                     ) -> List[Tuple[int, int]]:
    """``[(lo, hi)]`` logical row ranges covering ``[0, total_rows)`` in
    order, every range ``chunk_rows`` long except the last."""
    out: List[Tuple[int, int]] = []
    lo = 0
    while lo < total_rows:
        out.append((lo, min(lo + chunk_rows, total_rows)))
        lo += chunk_rows
    return out


def slice_chunk(table: Table, lo: int, chunk_rows: int) -> Table:
    """Rows ``[lo, lo + chunk_rows)`` of an UNPADDED table as an exactly
    ``chunk_rows``-row Table with a ``row_valid`` mask.

    The mask is always materialized (all-True for interior chunks): the
    morsel executable's signature must not alternate between mask and
    no-mask chunks, or the final chunk would re-trace.  The final chunk
    shifts its window back so the buffers stay full-length; rows before
    ``lo`` in the shifted window are masked out."""
    total = table.num_rows
    if chunk_rows > total:
        raise ValueError(
            f"chunk_rows {chunk_rows} exceeds table rows {total}")
    start = min(lo, total - chunk_rows)
    cols = {name: col.slice(start, start + chunk_rows)
            for name, col in table.columns.items()}
    valid = jnp.arange(chunk_rows) + start >= lo
    return Table(cols, chunk_rows, row_valid=valid)


