"""SPMD query execution (spmd/, ISSUE 11): device-sharded storage, sharded
compiled rungs, and mesh-aware serving — end to end on the virtual 8-device
mesh.

The acceptance bar: a sharded TPC-H q1-shaped query executes on the
``spmd_aggregate`` rung (trace span attr), returns results byte-identical
to the unsharded single-chip context, the second literal variant of the
family pays ZERO foreground compile spans, and an induced SPMD-rung
failure degrades cleanly to the single-chip compiled rung with the breaker
charged per (family, rung).
"""
import numpy as np
import pandas as pd
import pytest

import jax

from dask_sql_tpu import config as config_module

pytestmark = [
    pytest.mark.spmd,
    pytest.mark.skipif(len(jax.devices()) < 2,
                       reason="needs the virtual multi-device mesh"),
]


@pytest.fixture(autouse=True)
def _restore_global_config():
    keys = ("serving.cache.enabled", "resilience.inject",
            "parallel.auto_shard", "parallel.auto_shard.min_rows",
            "columnar.encoding.min_rows")
    before = {k: config_module.config.get(k) for k in keys}
    yield
    config_module.config.update(before)


def _df(n=100_003):
    """Deterministic frame whose float sums are EXACT in f64 (quarters of
    bounded ints), so per-shard partial sums psum to the same bits the
    single-chip scatter produces — the byte-identical bar is meaningful,
    not rounding luck.  `k` is low-cardinality so DICT encoding kicks in
    (the sharded table stays encoded: exchanges move codes)."""
    rng = np.random.RandomState(11)
    return pd.DataFrame({
        "g": rng.choice(["a", "b", "c", "d", "e"], n),
        "k": rng.randint(0, 40, n).astype(np.int64),
        "x": rng.randint(0, 4000, n) * 0.25,
        "q": rng.randint(1, 51, n).astype(np.int64),
    })


def _pair(df, **config):
    from dask_sql_tpu import Context

    cfg = {"serving.cache.enabled": False,
           # small enough that the test frame's columns encode
           "columnar.encoding.min_rows": 1024}
    cfg.update(config)
    sharded = Context()
    sharded.config.update(cfg)
    sharded.create_table("t", df, distributed=True)
    single = Context()
    single.config.update(cfg)
    single.create_table("t", df)
    return sharded, single


Q1_SHAPE = ("SELECT g, SUM(q) AS sum_qty, SUM(x) AS sum_price, "
            "AVG(x) AS avg_price, MIN(k) AS min_k, MAX(k) AS max_k, "
            "COUNT(*) AS cnt FROM t WHERE k < {lit} GROUP BY g")


def _compiles(ctx):
    tr = ctx.last_trace
    return [s.name for s in tr.spans if s.name.startswith("compile:")]


def _rung_spans(ctx):
    tr = ctx.last_trace
    return [(s.name, dict(s.attrs)) for s in tr.spans
            if s.name.startswith("rung:")]


def test_spmd_aggregate_end_to_end_byte_identical():
    df = _df()
    sharded, single = _pair(df)
    # the stored sharded table kept its encodings (codes move, not values)
    st = sharded.schema["root"].tables["t"].table
    assert st.has_encoded_columns(), "sharding must preserve DICT/FOR"

    got = sharded.sql(Q1_SHAPE.format(lit=33)).compute()
    # executed on the spmd_aggregate rung, visible as a trace span attr
    spans = _rung_spans(sharded)
    assert ("rung:spmd_aggregate",
            {"rung": "spmd_aggregate", "spmd": True}) in spans, spans
    assert sharded.metrics.counter("resilience.rung.spmd_aggregate") == 1
    assert sharded.metrics.counter("parallel.spmd.launches") == 1

    exp = single.sql(Q1_SHAPE.format(lit=33)).compute()
    assert single.metrics.counter("resilience.rung.spmd_aggregate") == 0
    g = got.sort_values("g").reset_index(drop=True)
    e = exp.sort_values("g").reset_index(drop=True)
    assert list(g.columns) == list(e.columns)
    for col in g.columns:
        a, b = g[col].to_numpy(), e[col].to_numpy()
        assert a.dtype == b.dtype, col
        assert (a == b).all(), f"column {col} differs: {a} vs {b}"


def test_second_literal_variant_zero_foreground_compiles():
    df = _df(40_003)
    sharded, _ = _pair(df)
    sharded.sql(Q1_SHAPE.format(lit=30)).compute()
    assert len(_compiles(sharded)) >= 1  # first variant pays the compile
    sharded.sql(Q1_SHAPE.format(lit=22)).compute()
    assert _compiles(sharded) == [], (
        "second literal variant must reuse the family's SPMD executable")
    assert sharded.metrics.counter("families.hit") >= 1


def test_spmd_select_filter_projection_matches():
    df = _df(40_003)
    sharded, single = _pair(df)
    q = "SELECT g, x * 2 AS x2 FROM t WHERE k < 7 LIMIT 11"
    got = sharded.sql(q).compute()
    exp = single.sql(q).compute()
    pd.testing.assert_frame_equal(got.reset_index(drop=True),
                                  exp.reset_index(drop=True))
    assert sharded.metrics.counter("resilience.rung.spmd_select") == 1


def test_induced_spmd_failure_degrades_to_single_chip():
    df = _df(40_003)
    sharded, _ = _pair(df)
    sharded.config.update({"resilience.inject": "spmd:always"})
    got = sharded.sql(Q1_SHAPE.format(lit=25)).compute()
    # served, on the single-chip compiled rung, with the SPMD rung charged
    assert len(got) == 5
    m = sharded.metrics
    assert m.counter("resilience.degraded.spmd_aggregate") == 1
    assert m.counter("resilience.rung.compiled_aggregate") == 1
    assert m.counter("resilience.rung.spmd_aggregate") == 0
    # the breaker key is (family, rung): three strikes skip ONLY the spmd
    # rung — the single-chip rung keeps serving the family
    sharded.sql(Q1_SHAPE.format(lit=24)).compute()
    sharded.sql(Q1_SHAPE.format(lit=23)).compute()
    sharded.sql(Q1_SHAPE.format(lit=21)).compute()
    assert m.counter("resilience.breaker.skip.spmd_aggregate") >= 1
    assert m.counter("resilience.rung.compiled_aggregate") == 4


def test_auto_shard_policy_shards_registration():
    from dask_sql_tpu import Context
    from dask_sql_tpu.parallel.dist_plan import table_is_sharded

    df = _df(40_003)
    c = Context()
    c.config.update({"serving.cache.enabled": False,
                     "parallel.auto_shard": "on",
                     "parallel.auto_shard.min_rows": 1024})
    c.create_table("t", df)
    assert table_is_sharded(c.schema["root"].tables["t"].table)
    assert c.metrics.counter("parallel.auto_shard.tables") == 1
    # below the row floor: stays single-device
    c.create_table("tiny", df.head(100))
    assert not table_is_sharded(c.schema["root"].tables["tiny"].table)
    # an EXPLICIT distributed=False is a per-table opt-out the policy
    # must respect (None, the default, leaves the policy in charge)
    c.create_table("optout", df, distributed=False)
    assert not table_is_sharded(c.schema["root"].tables["optout"].table)


def test_create_table_with_distributed_passthrough(tmp_path):
    from dask_sql_tpu import Context
    from dask_sql_tpu.parallel.dist_plan import table_is_sharded

    df = _df(8_003)
    path = tmp_path / "t.csv"
    df.to_csv(path, index=False)
    c = Context()
    # the WITH (distributed=...) kwarg passes through CREATE TABLE to
    # create_table and shards the registration
    c.sql(f"CREATE TABLE dist_t WITH (location = '{path}', format = 'csv', "
          "distributed = true)")
    assert table_is_sharded(c.schema["root"].tables["dist_t"].table)
    # SQL literals may arrive as strings; 'false' must NOT shard
    c.create_table("dist2", df, distributed="true")
    assert table_is_sharded(c.schema["root"].tables["dist2"].table)
    c.create_table("dist3", df, distributed="false")
    assert not table_is_sharded(c.schema["root"].tables["dist3"].table)


def test_estimator_budgets_per_device():
    from dask_sql_tpu.analysis.estimator import estimate_plan

    df = _df(40_003)
    sharded, single = _pair(df)
    q = "SELECT g, SUM(x) AS s FROM t GROUP BY g"
    est_sharded = estimate_plan(sharded.sql(q).plan, context=sharded)
    est_single = estimate_plan(single.sql(q).plan, context=single)
    ndev = len(jax.devices())
    assert est_sharded.devices == min(ndev, 8)
    assert est_single.devices == 1
    # the provable per-chip floor divides by the mesh width
    assert est_sharded.peak_bytes.lo < est_single.peak_bytes.lo
    rows = est_sharded.format_rows()
    assert any(r.startswith("mesh: devices=") for r in rows), rows


def test_explain_lint_spmd_advisory():
    df = _df(40_003)
    sharded, single = _pair(df)
    rows = list(sharded.sql("EXPLAIN LINT SELECT g, SUM(x) FROM t GROUP BY g",
                            return_futures=False)["LINT"])
    spmd_rows = [r for r in rows if "[spmd]" in r]
    assert len(spmd_rows) == 1, rows
    assert "devices=" in spmd_rows[0]
    assert "per_device_bytes=" in spmd_rows[0]
    assert "eligible" in spmd_rows[0]
    # unsharded scans lint unchanged
    rows = list(single.sql("EXPLAIN LINT SELECT g, SUM(x) FROM t GROUP BY g",
                           return_futures=False)["LINT"])
    assert not [r for r in rows if "[spmd]" in r]


def test_family_batched_stacked_spmd_launch():
    """The family batcher's stacked launch vmaps over the leading
    parameter axis of the SAME SPMD program: member results equal their
    solo runs."""
    from dask_sql_tpu.spmd import aggregate as sa
    from dask_sql_tpu.spmd import select as ss

    df = _df(40_003)
    sharded, _ = _pair(df)
    sharded.sql(Q1_SHAPE.format(lit=20)).compute()  # build + cache
    # project every stored column so the cached pipeline's scan arity
    # matches the stored table we re-run it against below
    sharded.sql("SELECT g, k, x, q FROM t WHERE k < 4").compute()
    table = sharded.schema["root"].tables["t"].table

    aobj = list(sa._cache.values())[-1]  # most recent (module LRU persists)
    params_list = [(np.int64(20),), (np.int64(10),), (np.int64(5),)]
    outs = aobj.run_batched(table, params_list)
    for p, out in zip(params_list, outs):
        exp = aobj.run(table, p).to_pandas()
        got = out.to_pandas()
        for col in got.columns:
            assert (got[col].to_numpy() == exp[col].to_numpy()).all(), col

    sobj = list(ss._cache.values())[-1]
    params_list = [(np.int64(4),), (np.int64(2),)]
    outs = sobj.run_batched(table, params_list)
    for p, out in zip(params_list, outs):
        exp = sobj.run(table, p).to_pandas()
        got = out.to_pandas()
        for col in got.columns:
            assert (got[col].to_numpy() == exp[col].to_numpy()).all(), col


def test_shard_table_threads_existing_row_valid():
    """Regression (ISSUE 11 satellite): a table that ALREADY carries a
    row_valid mask keeps it through shard_table — the pre-fix code
    silently replaced a pre-masked table's mask whenever padding occurred
    (and dropped it when none did)."""
    import jax.numpy as jnp

    from dask_sql_tpu.columnar.column import Column
    from dask_sql_tpu.columnar.dtypes import SqlType
    from dask_sql_tpu.columnar.table import Table
    from dask_sql_tpu.parallel.distribute import shard_table
    from dask_sql_tpu.parallel.mesh import make_mesh

    ndev = min(8, len(jax.devices()))
    mesh = make_mesh(ndev)
    phys = 16 * ndev  # divisible: the pre-fix code would DROP the mask
    n_logical = phys - 5
    data = jnp.arange(phys, dtype=jnp.int64)
    mask = jnp.arange(phys) < n_logical
    t = Table({"a": Column(data, SqlType.BIGINT)}, n_logical, row_valid=mask)
    sharded = shard_table(t, mesh)
    assert sharded.num_rows == n_logical
    assert sharded.row_valid is not None
    np.testing.assert_array_equal(np.asarray(sharded.row_valid),
                                  np.asarray(mask))
    # and with fresh padding on top: the pre-masked rows stay invalid
    phys2 = 16 * ndev + 3  # non-divisible physical length
    n2 = phys2 - 7
    data2 = jnp.arange(phys2, dtype=jnp.int64)
    mask2 = jnp.arange(phys2) < n2
    t2 = Table({"a": Column(data2, SqlType.BIGINT)}, n2, row_valid=mask2)
    sharded2 = shard_table(t2, mesh)
    target = ((phys2 + ndev - 1) // ndev) * ndev
    rv = np.asarray(sharded2.row_valid)
    assert rv.shape[0] == target
    np.testing.assert_array_equal(rv[:phys2], np.asarray(mask2))
    assert not rv[phys2:].any()
    # the sharded mask is what aggregation sees: invalid rows never count
    total = int(np.asarray(
        jnp.sum(jnp.where(sharded2.row_valid,
                          sharded2.columns["a"].data, 0))))
    assert total == int(np.arange(n2).sum())
