"""Direct kernel tests (parity: reference tests/unit/test_call.py — exercising
the op layer without SQL)."""
import numpy as np
import pandas as pd
import pytest

import jax.numpy as jnp


def _col(arr, mask=None):
    from dask_sql_tpu.columnar import Column

    return Column.from_numpy(np.asarray(arr), mask)


class TestGrouping:
    def test_factorize_matches_pandas(self):
        from dask_sql_tpu.ops.grouping import factorize, key_arrays

        keys = np.array([3, 1, 3, 2, 1, 3])
        gid, order, num = factorize(key_arrays([_col(keys)]))
        assert num == 3
        # same partition structure as pandas
        expected = pd.Series(keys).groupby(keys).ngroup()
        codes = np.asarray(gid)
        mapping = {}
        for c, e in zip(codes, pd.factorize(np.sort(np.unique(keys)))[0][np.searchsorted(np.sort(np.unique(keys)), keys)]):
            mapping.setdefault(c, e)
        assert len(set(codes)) == 3

    def test_segment_sum_null_skip(self):
        from dask_sql_tpu.ops.grouping import seg_count, seg_sum

        vals = jnp.asarray([1.0, 2.0, 3.0, 4.0])
        valid = jnp.asarray([True, False, True, True])
        gid = jnp.asarray([0, 0, 1, 1])
        s, ok = seg_sum(vals, valid, gid, 2)
        assert list(np.asarray(s)) == [1.0, 7.0]
        assert list(np.asarray(seg_count(valid, gid, 2))) == [1, 2]

    def test_seg_var_matches_numpy(self):
        from dask_sql_tpu.ops.grouping import seg_var

        rng = np.random.RandomState(0)
        vals = rng.rand(100)
        gid = jnp.asarray(np.repeat([0, 1], 50))
        v, ok = seg_var(jnp.asarray(vals), jnp.ones(100, dtype=bool), gid, 2, 1)
        np.testing.assert_allclose(np.asarray(v), [vals[:50].var(ddof=1), vals[50:].var(ddof=1)], rtol=1e-9)

    def test_radix_gid_int_keys(self):
        from dask_sql_tpu.ops.grouping import radix_gid

        col = _col(np.array([10, 12, 10, 11], dtype=np.int64))
        out = radix_gid([col])
        assert out is not None
        gid, domain, decode = out
        assert domain == 4  # span 3 + null slot
        decoded = decode(jnp.asarray([0, 1, 2]))[0]
        assert list(np.asarray(decoded.data)) == [10, 11, 12]


class TestJoinKernels:
    def test_inner_indices(self):
        from dask_sql_tpu.ops.join import inner_join_indices, join_key_gids

        l = _col(np.array([1, 2, 3, 2], dtype=np.int64))
        r = _col(np.array([2, 2, 4], dtype=np.int64))
        lg, rg = join_key_gids([l], [r])
        li, ri = inner_join_indices(lg, rg)
        pairs = sorted(zip(np.asarray(li).tolist(), np.asarray(ri).tolist()))
        assert pairs == [(1, 0), (1, 1), (3, 0), (3, 1)]

    def test_left_indices_pad(self):
        from dask_sql_tpu.ops.join import join_key_gids, left_join_indices

        l = _col(np.array([1, 5], dtype=np.int64))
        r = _col(np.array([1], dtype=np.int64))
        lg, rg = join_key_gids([l], [r])
        li, ri = left_join_indices(lg, rg)
        assert np.asarray(li).tolist() == [0, 1]
        assert np.asarray(ri).tolist() == [0, -1]

    def test_null_keys_never_match(self):
        from dask_sql_tpu.ops.join import inner_join_indices, join_key_gids

        l = _col(np.array([1.0, np.nan]))
        r = _col(np.array([1.0, np.nan]))
        lg, rg = join_key_gids([l], [r])
        li, ri = inner_join_indices(lg, rg)
        assert np.asarray(li).tolist() == [0]

    def test_string_keys_merge_dicts(self):
        from dask_sql_tpu.ops.join import inner_join_indices, join_key_gids

        l = _col(np.array(["a", "b", "c"], dtype=object))
        r = _col(np.array(["c", "a"], dtype=object))
        lg, rg = join_key_gids([l], [r])
        li, ri = inner_join_indices(lg, rg)
        got = sorted(zip(np.asarray(li).tolist(), np.asarray(ri).tolist()))
        assert got == [(0, 1), (2, 0)]


class TestDatetimeKernels:
    def test_extract_fields(self):
        from dask_sql_tpu.ops import datetime as dt

        ts = pd.date_range("1999-12-28", periods=10, freq="37h")
        ns = jnp.asarray(np.asarray(ts, dtype="datetime64[ns]").view(np.int64))
        for unit, expect in [
            ("year", ts.year), ("month", ts.month), ("day", ts.day),
            ("hour", ts.hour), ("minute", ts.minute), ("second", ts.second),
            ("quarter", ts.quarter), ("doy", ts.dayofyear),
        ]:
            got = np.asarray(dt.extract(unit, ns))
            assert list(got) == list(expect), unit

    def test_iso_week(self):
        from dask_sql_tpu.ops import datetime as dt

        ts = pd.to_datetime(["2020-01-01", "2021-01-01", "2015-12-31", "2016-01-04"])
        got = np.asarray(dt.extract("week", jnp.asarray(np.asarray(ts, dtype="datetime64[ns]").view(np.int64))))
        expected = ts.isocalendar().week.to_numpy()
        assert list(got) == list(expected)

    def test_truncate_and_ceil(self):
        from dask_sql_tpu.ops import datetime as dt

        ts = pd.to_datetime(["2020-03-15 13:45:10", "2020-01-01 00:00:00"])
        ns = jnp.asarray(np.asarray(ts, dtype="datetime64[ns]").view(np.int64))
        got_m = pd.to_datetime(np.asarray(dt.truncate("MONTH", ns)))
        assert list(got_m) == list(ts.to_period("M").start_time)
        got_c = pd.to_datetime(np.asarray(dt.ceil_to("DAY", ns)))
        assert list(got_c) == list(ts.ceil("D"))

    def test_add_months_clamps(self):
        from dask_sql_tpu.ops import datetime as dt

        ts = pd.to_datetime(["2020-01-31"])
        out = pd.to_datetime(np.asarray(dt.add_months(jnp.asarray(np.asarray(ts, dtype="datetime64[ns]").view(np.int64)), 1)))
        assert out[0] == pd.Timestamp("2020-02-29")

    def test_timestampdiff(self):
        from dask_sql_tpu.ops import datetime as dt

        a = jnp.asarray(np.asarray(pd.to_datetime(["2020-01-31"]), dtype="datetime64[ns]").view(np.int64))
        b = jnp.asarray(np.asarray(pd.to_datetime(["2020-03-01"]), dtype="datetime64[ns]").view(np.int64))
        assert int(np.asarray(dt.timestampdiff("MONTH", a, b))[0]) == 1


class TestStringsKernels:
    def test_like_regex(self):
        from dask_sql_tpu.ops.strings import like_to_regex

        assert like_to_regex("a%b_c") == "^a.*b.c$"
        assert like_to_regex("50%%", escape=None) == "^50.*.*$"
        assert like_to_regex(r"50\%", escape="\\") == "^50%$"

    def test_map_unary_dictionary_only(self):
        from dask_sql_tpu.ops.strings import map_unary

        col = _col(np.array(["aa", "bb", "aa"], dtype=object))
        out = map_unary(col, str.upper)
        assert list(out.to_numpy()) == ["AA", "BB", "AA"]
        assert len(out.dictionary) == 2  # transformed uniques only

    def test_binary_string_op_pairs(self):
        from dask_sql_tpu.ops.strings import binary_string_op

        a = _col(np.array(["x", "y", "x"], dtype=object))
        b = _col(np.array(["1", "1", "2"], dtype=object))
        out = binary_string_op(a, b, lambda p, q: p + q)
        assert list(out.to_numpy()) == ["x1", "y1", "x2"]


class TestSortKernels:
    def test_sort_permutation_mixed(self):
        from dask_sql_tpu.ops.sorting import sort_permutation

        a = _col(np.array([1, 1, 2, 2]))
        b = _col(np.array([9.0, 1.0, 8.0, 2.0]))
        perm = sort_permutation([a, b], [True, False], [False, False])
        assert np.asarray(perm).tolist() == [0, 1, 2, 3]
        perm = sort_permutation([a, b], [True, True], [False, False])
        assert np.asarray(perm).tolist() == [1, 0, 3, 2]

    def test_topk(self):
        from dask_sql_tpu.ops.sorting import topk_permutation

        col = _col(np.array([5.0, 1.0, 9.0, 3.0]))
        idx = topk_permutation(col, ascending=True, k=2)
        assert sorted(np.asarray(idx).tolist()) == [1, 3]
