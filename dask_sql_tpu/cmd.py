"""Interactive REPL CLI.

Role parity: reference cmd.py — prompt-toolkit REPL with SQL highlighting,
psql-style meta commands (\\l \\dt \\df \\dm \\de \\dss \\dsc, cmd.py:79-146),
and the `dask-sql` console entrypoint (cmd.py:233).
"""
from __future__ import annotations

import sys
from typing import Optional


META_COMMANDS_HELP = """
\\l             list schemas
\\dt            list tables in the current schema
\\df            list user-defined functions
\\dm            list models
\\de            list experiments
\\dss <schema>  switch schema
\\dsc <schema>  show tables of a schema
\\conf [key]    show configuration
\\q             quit
"""


def _handle_meta(context, text: str) -> bool:
    """Handle a psql-style meta command; returns True when handled."""
    import pandas as pd

    cmd, _, arg = text.strip().partition(" ")
    arg = arg.strip()
    schema = context.schema[context.schema_name]
    if cmd == "\\l":
        print(pd.DataFrame({"Schema": list(context.schema.keys())}))
    elif cmd == "\\dt":
        print(pd.DataFrame({"Table": list(schema.tables.keys())}))
    elif cmd == "\\df":
        print(pd.DataFrame({"Function": list(schema.function_lists.keys())}))
    elif cmd == "\\dm":
        print(pd.DataFrame({"Model": list(schema.models.keys())}))
    elif cmd == "\\de":
        print(pd.DataFrame({"Experiment": list(schema.experiments.keys())}))
    elif cmd == "\\dss":
        if arg in context.schema:
            context.schema_name = arg
            print(f"Schema switched to {arg}")
        else:
            print(f"Schema {arg!r} not found")
    elif cmd == "\\dsc":
        if arg in context.schema:
            print(pd.DataFrame({"Table": list(context.schema[arg].tables.keys())}))
        else:
            print(f"Schema {arg!r} not found")
    elif cmd == "\\conf":
        from . import config as cfg

        items = {k: context.config.get(k) for k in cfg.DEFAULTS if not arg or arg in k}
        print(pd.DataFrame({"Key": list(items.keys()), "Value": [str(v) for v in items.values()]}))
    elif cmd in ("\\q", "quit", "exit"):
        raise EOFError
    elif cmd in ("\\?", "help"):
        print(META_COMMANDS_HELP)
    else:
        return False
    return True


def _run_query(context, sql: str):
    import time

    t0 = time.perf_counter()
    try:
        result = context.sql(sql)
        if result is not None:
            print(result.compute())
        elapsed = time.perf_counter() - t0
        print(f"({elapsed:.3f}s)")
    except Exception as e:  # dsql: allow-broad-except — REPL surfaces all errors
        print(f"ERROR: {e}", file=sys.stderr)


def cmd_loop(context=None, client=None, startup: bool = False,
             log_level=None):  # pragma: no cover - interactive
    """Parity: reference cmd_loop (cmd.py)."""
    from .context import Context

    context = context or Context()
    print("dask-sql-tpu — TPU-native SQL. Type \\? for help, \\q to quit.")
    try:
        from prompt_toolkit import PromptSession
        from prompt_toolkit.history import InMemoryHistory

        session = PromptSession(history=InMemoryHistory())
        read = lambda: session.prompt("(tpu-sql) > ")
    except ImportError:
        read = lambda: input("(tpu-sql) > ")

    while True:
        try:
            text = read()
        except (EOFError, KeyboardInterrupt):
            break
        if not text.strip():
            continue
        try:
            if text.strip().startswith("\\") or text.strip() in ("quit", "exit", "help"):
                if _handle_meta(context, text):
                    continue
            _run_query(context, text)
        except EOFError:
            break


def main():  # pragma: no cover - console entrypoint
    import argparse

    parser = argparse.ArgumentParser(description="TPU-native SQL REPL")
    parser.parse_args()
    cmd_loop()


if __name__ == "__main__":  # pragma: no cover
    main()
