"""Warm-standby replication: the cold-start machinery as a transport.

A standby replica is useful exactly insofar as promoting it costs
nothing: zero foreground compiles on its first routed family, catalog
already loaded, profiles already sharpening predictions.  This module
gets there by reusing the PR 6 cold-start pipeline verbatim as a
replication transport:

1. the primary's `Context.save_state` writes an atomic checkpoint
   snapshot (tables + models + statistics + profiles + breaker state +
   table delta epochs) into the replication directory;
2. the standby's `Context.load_state` rehydrates it and kicks the
   warm-up pass (`serving/warmup.py`), which replays the profile
   store's hot families through the compile cache in the background;
3. the persistent compile cache (``compile.cache.persist_path``) is the
   third leg: primaries and standbys pointed at one cache directory
   share lowered executables, so the standby's warm-up pass is
   cache-hits, not compiles.  (In-process fleets share the process
   compile cache and get this for free.)

Promotion then needs no data motion at all — the router replays any
writes sequenced after the last sync (epoch-fenced, fleet/router.py)
and flips the standby READY.  The epoch fencing is what makes syncing
and writing safely concurrent: the snapshot manifest carries the table
epochs it captured, so a standby restored from a snapshot taken BEFORE
an append can never serve a pre-append cached result — its epochs say
it is behind, and the router replays the tail before routing to it.
"""
from __future__ import annotations

import logging
import os
import tempfile
import time
from typing import Optional

from .replica import Replica

logger = logging.getLogger(__name__)


class StandbyReplicator:
    """Ships checkpoint snapshots from a primary to a warm standby."""

    def __init__(self, primary: Replica, standby: Replica,
                 directory: Optional[str] = None, metrics=None):
        self.primary = primary
        self.standby = standby
        self.directory = directory or tempfile.mkdtemp(prefix="dsql-fleet-")
        self.metrics = metrics if metrics is not None \
            else primary.context.metrics
        self.last_sync_ts: Optional[float] = None
        self.syncs = 0

    def sync(self, wait_warm: bool = True,
             warm_timeout_s: float = 60.0) -> str:
        """One replication round: snapshot the primary, restore the
        standby, and (by default) block until the standby's warm-up pass
        finishes — after which a promotion pays zero foreground compiles.
        Returns the snapshot directory used."""
        t0 = time.monotonic()
        os.makedirs(self.directory, exist_ok=True)
        self.primary.context.save_state(self.directory)
        self.standby.context.load_state(self.directory)
        if wait_warm:
            warm = getattr(self.standby.context, "warmup", None)
            if warm is not None:
                warm.join(timeout=warm_timeout_s)
        self.last_sync_ts = time.time()
        self.syncs += 1
        self.metrics.inc("fleet.sync")
        logger.info("standby %s synced from %s in %.0f ms (sync #%d)",
                    self.standby.name, self.primary.name,
                    (time.monotonic() - t0) * 1000.0, self.syncs)
        return self.directory
