"""Parameterized plan families + inter-query batched execution (families/).

Covers the family contract end to end: literal extraction (scalars,
optimizer-folded constants, date/interval literals, IN-list pow2 buckets,
LIMIT windows), the compile-once-run-many acceptance criterion (a second
same-family query produces ZERO foreground ``compile:<rung>`` spans), the
family keying of the result cache / breaker / estimator / profiles, the
serving batcher's stacked launch, and the ``families.enabled`` off-switch.
"""
import threading

import numpy as np
import pandas as pd
import pytest

from dask_sql_tpu import Context
from dask_sql_tpu import config as config_module
from dask_sql_tpu import families
from dask_sql_tpu.families.batcher import FamilyBatcher
from dask_sql_tpu.planner.expressions import (
    InListExpr,
    InParamExpr,
    Literal,
    ParamRef,
    ScalarFunc,
)
from dask_sql_tpu.columnar.dtypes import SqlType

pytestmark = pytest.mark.families


@pytest.fixture(autouse=True)
def _restore_global_config():
    """`Context.config` is process-global; _ctx() below disables the
    result cache for determinism — restore every key we touch so later
    test modules see the defaults."""
    keys = ("serving.cache.enabled", "families.enabled")
    before = {k: config_module.config.get(k) for k in keys}
    yield
    config_module.config.update(before)


def _ctx(n=512, name="ft"):
    c = Context()
    c.config.update({"serving.cache.enabled": False})
    rng = np.random.RandomState(7)
    df = pd.DataFrame({
        "a": np.arange(n, dtype=np.int64),
        "b": rng.rand(n),
        "k": rng.choice(["x", "y", "z"], n),
        "d": pd.to_datetime("1995-01-01")
        + pd.to_timedelta(rng.randint(0, 900, n), unit="D"),
    })
    c.create_table(name, df)
    return c, df


def _compiles(trace):
    return [s.name for s in trace.spans if s.name.startswith("compile:")]


# ------------------------------------------------------------ parameterize
def test_scalar_literal_parameterizes():
    pz = families.Parameterizer()
    e = ScalarFunc("gt", (Literal(5, SqlType.BIGINT),
                          Literal(2.5, SqlType.DOUBLE)), SqlType.BOOLEAN)
    out = pz.rewrite(e)
    assert isinstance(out.args[0], ParamRef)
    assert isinstance(out.args[1], ParamRef)
    assert [v.item() for v in pz.values] == [5, 2.5]
    # the stripped form is value-free: a different literal stringifies SAME
    pz2 = families.Parameterizer()
    e2 = ScalarFunc("gt", (Literal(99, SqlType.BIGINT),
                           Literal(0.125, SqlType.DOUBLE)), SqlType.BOOLEAN)
    assert str(pz2.rewrite(e2)) == str(out)


def test_string_null_and_pattern_literals_stay_baked():
    pz = families.Parameterizer()
    s = pz.rewrite(Literal("abc", SqlType.VARCHAR))
    n = pz.rewrite(Literal(None, SqlType.BIGINT))
    like = pz.rewrite(ScalarFunc(
        "like", (Literal(1, SqlType.BIGINT), Literal("a%", SqlType.VARCHAR)),
        SqlType.BOOLEAN))
    trunc = pz.rewrite(ScalarFunc(
        "datetime_floor", (Literal(7, SqlType.TIMESTAMP),
                           Literal("DAY", SqlType.VARCHAR)), SqlType.TIMESTAMP))
    assert isinstance(s, Literal) and isinstance(n, Literal)
    # LIKE arg 0 may parameterize; the pattern must not
    assert isinstance(like.args[1], Literal)
    assert isinstance(trunc.args[1], Literal)
    # the truncation VALUE argument also stays baked (static-tail op)
    assert isinstance(trunc.args[0], ParamRef) or isinstance(
        trunc.args[0], Literal)


def test_in_list_pow2_bucketing():
    pz = families.Parameterizer()
    arg = Literal(0, SqlType.BIGINT)  # stands in for a column-typed expr
    from dask_sql_tpu.planner.expressions import ColumnRef

    col = ColumnRef(0, "a", SqlType.BIGINT)
    for items, bucket in ((2, 2), (3, 4), (4, 4), (5, 8), (8, 8), (9, 16)):
        pz = families.Parameterizer()
        e = InListExpr(col, tuple(Literal(i, SqlType.BIGINT)
                                  for i in range(items)), False)
        out = pz.rewrite(e)
        assert isinstance(out, InParamExpr), (items, out)
        assert out.length == bucket
        assert len(pz.values[0]) == bucket
        # padding repeats the max: membership set unchanged
        assert set(pz.values[0].tolist()) == set(range(items))
    del arg


def test_in_list_with_null_member_stays_baked():
    """3VL regression (review finding): `x NOT IN (v, NULL)` is never TRUE
    while `x NOT IN (v)` can be — normalizing the NULL away would give both
    one family identity and ONE result-cache key.  A NULL member must keep
    the whole list baked so the NULL stays in the family repr."""
    from dask_sql_tpu.planner.expressions import ColumnRef

    col = ColumnRef(0, "a", SqlType.BIGINT)
    with_null = InListExpr(col, (Literal(2, SqlType.BIGINT),
                                 Literal(None, SqlType.BIGINT)), True)
    without = InListExpr(col, (Literal(2, SqlType.BIGINT),), True)
    pz1, pz2 = families.Parameterizer(), families.Parameterizer()
    out1, out2 = pz1.rewrite(with_null), pz2.rewrite(without)
    assert isinstance(out1, InListExpr) and not pz1.values
    assert isinstance(out2, InParamExpr)
    assert repr(out1) != repr(out2)


def test_in_list_with_string_or_computed_items_stays_baked():
    from dask_sql_tpu.planner.expressions import ColumnRef

    pz = families.Parameterizer()
    scol = ColumnRef(0, "k", SqlType.VARCHAR)
    e = InListExpr(scol, (Literal("x", SqlType.VARCHAR),), False)
    assert isinstance(pz.rewrite(e), InListExpr)
    icol = ColumnRef(0, "a", SqlType.BIGINT)
    computed = InListExpr(
        icol, (ScalarFunc("add", (Literal(1, SqlType.BIGINT),
                                  Literal(2, SqlType.BIGINT)), SqlType.BIGINT),),
        False)
    out = pz.rewrite(computed)
    assert isinstance(out, InListExpr)
    # and the kept items were NOT parameterized inside (trace evaluator
    # requires Literal items)
    assert not pz.values


# ------------------------------------- compile-once-run-many (acceptance)
def test_second_literal_variant_compiles_nothing_aggregate():
    c, df = _ctx()
    c.sql("SELECT k, SUM(b) AS s FROM ft WHERE a > 10 GROUP BY k",
          return_futures=False)
    t1 = c.last_trace
    c.sql("SELECT k, SUM(b) AS s FROM ft WHERE a > 250 GROUP BY k",
          return_futures=False)
    t2 = c.last_trace
    assert t1.fingerprint == t2.fingerprint
    assert len(_compiles(t1)) >= 1
    assert _compiles(t2) == []
    assert c.metrics.counter("families.hit") >= 1
    assert c.metrics.counter("families.estimate.hit") >= 1


def test_second_literal_variant_compiles_nothing_select():
    c, df = _ctx()
    # literals chosen so both queries land in the same pow2 survivor
    # bucket (the gather kernel's shape); the mask kernel is shared by
    # construction
    r1 = c.sql("SELECT a, b * 2 AS bb FROM ft WHERE b > 0.52 "
               "ORDER BY bb DESC LIMIT 10", return_futures=False)
    t1 = c.last_trace
    r2 = c.sql("SELECT a, b * 3 AS bb FROM ft WHERE b > 0.55 "
               "ORDER BY bb DESC LIMIT 10", return_futures=False)
    t2 = c.last_trace
    assert t1.fingerprint == t2.fingerprint
    assert _compiles(t2) == []
    exp = (df[df.b > 0.55].assign(bb=df.b * 3)
           .sort_values("bb", ascending=False).head(10))
    np.testing.assert_allclose(r2["bb"].to_numpy(), exp["bb"].to_numpy())
    assert len(r1) == 10


def test_optimizer_folded_constants_join_family():
    c, df = _ctx()
    r1 = c.sql("SELECT SUM(b) AS s FROM ft WHERE a > 1 + 1",
               return_futures=False)
    t1 = c.last_trace
    r2 = c.sql("SELECT SUM(b) AS s FROM ft WHERE a > 100",
               return_futures=False)
    t2 = c.last_trace
    assert t1.fingerprint == t2.fingerprint
    assert _compiles(t2) == []
    np.testing.assert_allclose(r1["s"][0], df[df.a > 2].b.sum())
    np.testing.assert_allclose(r2["s"][0], df[df.a > 100].b.sum())


def test_date_and_interval_literals_join_family():
    c, df = _ctx()
    # plain DATE literal comparisons: one family across date values
    r1 = c.sql("SELECT COUNT(*) AS n FROM ft WHERE d <= DATE '1996-01-01'",
               return_futures=False)
    t1 = c.last_trace
    r2 = c.sql("SELECT COUNT(*) AS n FROM ft WHERE d <= DATE '1996-09-02'",
               return_futures=False)
    t2 = c.last_trace
    assert r1["n"][0] == (df.d <= pd.Timestamp("1996-01-01")).sum()
    assert r2["n"][0] == (df.d <= pd.Timestamp("1996-09-02")).sum()
    assert t1.fingerprint == t2.fingerprint
    assert _compiles(t2) == []
    # date - interval arithmetic: both the date and the interval scalar
    # parameterize, so two (date, interval) pairs share one family
    r3 = c.sql("SELECT COUNT(*) AS n FROM ft "
               "WHERE d <= DATE '1997-01-01' - INTERVAL '90' DAY",
               return_futures=False)
    t3 = c.last_trace
    r4 = c.sql("SELECT COUNT(*) AS n FROM ft "
               "WHERE d <= DATE '1998-01-01' - INTERVAL '30' DAY",
               return_futures=False)
    t4 = c.last_trace
    for r, (date, days) in ((r3, ("1997-01-01", 90)),
                            (r4, ("1998-01-01", 30))):
        cutoff = pd.Timestamp(date) - pd.Timedelta(days=days)
        assert r["n"][0] == (df.d <= cutoff).sum()
    assert t3.fingerprint == t4.fingerprint
    assert _compiles(t4) == []


def test_in_list_buckets_split_families_and_stay_correct():
    c, df = _ctx()
    r3 = c.sql("SELECT SUM(b) AS s FROM ft WHERE a IN (1, 2, 3)",
               return_futures=False)
    t3 = c.last_trace
    r4 = c.sql("SELECT SUM(b) AS s FROM ft WHERE a IN (7, 8, 9, 10)",
               return_futures=False)
    t4 = c.last_trace
    r5 = c.sql("SELECT SUM(b) AS s FROM ft WHERE a IN (1, 2, 3, 4, 5)",
               return_futures=False)
    t5 = c.last_trace
    # 3 and 4 values share the 4-bucket => one family, no recompile
    assert t3.fingerprint == t4.fingerprint
    assert _compiles(t4) == []
    # 5 values cross into the 8-bucket => a new family, fresh compile
    assert t5.fingerprint != t3.fingerprint
    assert len(_compiles(t5)) >= 1
    np.testing.assert_allclose(r3["s"][0], df[df.a.isin([1, 2, 3])].b.sum())
    np.testing.assert_allclose(
        r4["s"][0], df[df.a.isin([7, 8, 9, 10])].b.sum())
    np.testing.assert_allclose(
        r5["s"][0], df[df.a.isin([1, 2, 3, 4, 5])].b.sum())


def test_limit_windows_are_family_boundaries():
    c, df = _ctx()
    c.sql("SELECT a FROM ft WHERE b > 0.9 LIMIT 5", return_futures=False)
    ta = c.last_trace
    c.sql("SELECT a FROM ft WHERE b > 0.8 LIMIT 5", return_futures=False)
    tb = c.last_trace
    c.sql("SELECT a FROM ft WHERE b > 0.9 LIMIT 6", return_futures=False)
    tc = c.last_trace
    # same LIMIT, different filter literal: one family
    assert ta.fingerprint == tb.fingerprint
    # different LIMIT window: its own family (static host slicing)
    assert tc.fingerprint != ta.fingerprint


# ------------------------------------------------- family-keyed consumers
def test_result_cache_distinguishes_param_values():
    c, df = _ctx()
    c.config.update({"serving.cache.enabled": True})
    try:
        r1 = c.sql("SELECT SUM(b) AS s FROM ft WHERE a > 100",
                   return_futures=False)
        r1b = c.sql("SELECT SUM(b) AS s FROM ft WHERE a > 100",
                    return_futures=False)
        r2 = c.sql("SELECT SUM(b) AS s FROM ft WHERE a > 300",
                   return_futures=False)
        # identical literals: second is a result-cache hit
        assert c.metrics.counter("query.cache.hit") >= 1
        # different literal, same family: MUST NOT serve the cached result
        np.testing.assert_allclose(r1["s"][0], df[df.a > 100].b.sum())
        np.testing.assert_allclose(r1b["s"][0], df[df.a > 100].b.sum())
        np.testing.assert_allclose(r2["s"][0], df[df.a > 300].b.sum())
    finally:
        c.config.update({"serving.cache.enabled": False})


def test_profiles_roll_up_by_family_and_show_family_column():
    c, df = _ctx()
    c.sql("SELECT SUM(b) AS s FROM ft WHERE a > 11", return_futures=False)
    c.sql("SELECT SUM(b) AS s FROM ft WHERE a > 22", return_futures=False)
    fp = c.last_trace.fingerprint
    prof = c.profiles.get(fp)
    assert prof is not None and prof["hits"] >= 2  # both variants rolled up
    assert prof["family"] == fp
    rows = c.sql("SHOW PROFILES", return_futures=False)
    assert list(rows.columns) == ["Fingerprint", "Family", "Metric", "Value"]
    assert fp in set(rows["Family"])


def test_warm_candidates_dedupe_by_family():
    from dask_sql_tpu.observability import ProfileStore

    store = ProfileStore()
    store.record_exec("fp1", sql="SELECT 1", family="famA")
    store.record_exec("fp2", sql="SELECT 2", family="famA")
    store.record_exec("fp3", sql="SELECT 3", family="famB")
    got = store.warm_candidates(10)
    fams = [store.get(fp)["family"] for fp, _ in got]
    assert sorted(fams) == ["famA", "famB"]  # one representative per family


def test_breaker_keys_by_family():
    """A rung verdict earned under one literal applies to the whole
    family: the breaker key is the family fingerprint."""
    c, df = _ctx()
    c.sql("SELECT SUM(b) AS s FROM ft WHERE a > 5", return_futures=False)
    fam = c.last_trace.fingerprint
    info = families.family_of(
        c.sql("SELECT SUM(b) AS s FROM ft WHERE a > 6").plan, c.config)
    assert info is not None and info.fingerprint == fam


# --------------------------------------------------------------- batcher
def test_batcher_coalesces_concurrent_same_family_queries():
    c, df = _ctx(n=4096)
    from dask_sql_tpu.serving.runtime import ServingRuntime

    rt = ServingRuntime(workers=8, metrics=c.metrics,
                        batch_queries=4, batch_window_ms=2000.0)
    c.serving = rt
    try:
        lits = [50, 150, 250, 350]
        sqls = {l: f"SELECT k, SUM(b) AS s FROM ft WHERE a > {l} GROUP BY k"
                for l in lits}
        for l in lits:
            c.sql(sqls[l])  # pre-plan so clients rendezvous at the executor

        def client(lit):
            def work(_t):
                return c.sql(sqls[lit]).execute()
            return work

        futs = [rt.submit(client(l))[1] for l in lits]
        for lit, fut in zip(lits, futs):
            got = fut.result(300).to_pandas()
            exp = df[df.a > lit].groupby("k").b.sum()
            gotmap = dict(zip([str(x) for x in got[got.columns[0]]],
                              got["s"]))
            for k in exp.index:
                np.testing.assert_allclose(gotmap[k], exp[k], rtol=1e-9)
        assert c.metrics.counter("serving.batch.launches") >= 1
        assert c.metrics.counter("serving.batch.queries") >= 2
    finally:
        rt.shutdown(wait=True)
        c.serving = None


def test_batcher_propagates_leader_failure_to_followers():
    batcher = FamilyBatcher(max_queries=4, window_ms=200.0)
    boom = RuntimeError("stacked launch died")
    outcomes = {}

    def member(i):
        def solo():
            return f"solo-{i}"

        def batched(members):
            raise boom

        try:
            outcomes[i] = batcher.run("key", (i,), solo, batched)
        except RuntimeError as e:
            outcomes[i] = e

    threads = [threading.Thread(target=member, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    assert all(outcomes[i] is boom for i in range(4))


def test_batcher_solo_when_alone():
    calls = []
    batcher = FamilyBatcher(max_queries=4, window_ms=1.0,
                            busy=lambda: False)
    out = batcher.run("k", (1,), solo=lambda: calls.append("solo") or 42,
                      batched=lambda m: calls.append("batched") or [0] * 4)
    assert out == 42 and calls == ["solo"]


def test_batcher_disabled_at_max_queries_one():
    batcher = FamilyBatcher(max_queries=1, window_ms=1000.0)
    assert batcher.run("k", (1,), solo=lambda: "s",
                       batched=lambda m: ["b"]) == "s"


# ------------------------------------------------------------- off-switch
def test_families_disabled_restores_literal_identity():
    c, df = _ctx()
    c.config.update({"families.enabled": False})
    try:
        c.sql("SELECT SUM(b) AS s FROM ft WHERE a > 10", return_futures=False)
        t1 = c.last_trace
        c.sql("SELECT SUM(b) AS s FROM ft WHERE a > 20", return_futures=False)
        t2 = c.last_trace
        # literal-baked identities again: different fingerprints, and the
        # second variant pays its own compile
        assert t1.fingerprint != t2.fingerprint
        assert len(_compiles(t2)) >= 1
        assert c.metrics.counter("families.parameterized") == 0
    finally:
        c.config.update({"families.enabled": True})


def test_family_fingerprint_is_deterministic():
    c, _ = _ctx(name="ft_det_a")
    c2, _ = _ctx(name="ft_det_a")
    c.sql("SELECT SUM(b) AS s FROM ft_det_a WHERE a > 10",
          return_futures=False)
    c2.sql("SELECT SUM(b) AS s FROM ft_det_a WHERE a > 999",
           return_futures=False)
    # separate Contexts/processes-worth of state, same statement shape:
    # same family fingerprint (the pre-warm/checkpoint contract)
    assert c.last_trace.fingerprint == c2.last_trace.fingerprint
