"""UDF / UDAF registration tests (parity: reference test_function.py)."""
import numpy as np
import pandas as pd
import pytest

from tests.utils import assert_eq


def test_scalar_udf(c, df):
    def f(x):
        return x ** 2

    c.register_function(f, "f", [("x", np.float64)], np.float64)
    result = c.sql("SELECT f(b) AS r FROM df").compute()
    np.testing.assert_allclose(result["r"], df.b ** 2)

def test_udf_two_args(c, df):
    def g(x, y):
        return x + 10 * y

    c.register_function(g, "g", [("x", np.float64), ("y", np.float64)], np.float64)
    result = c.sql("SELECT g(a, b) AS r FROM df").compute()
    np.testing.assert_allclose(result["r"], df.a + 10 * df.b)

def test_udf_in_where_and_groupby(c, df):
    def h(x):
        return x * 2

    c.register_function(h, "h", [("x", np.float64)], np.float64)
    result = c.sql("SELECT SUM(h(b)) AS s FROM df WHERE h(a) > 2").compute()
    sel = df[df.a * 2 > 2]
    np.testing.assert_allclose(result["s"][0], (sel.b * 2).sum())

def test_udf_replace_and_overload_guard(c):
    def f1(x):
        return x + 1

    c.register_function(f1, "dup", [("x", np.float64)], np.float64)
    with pytest.raises(ValueError):
        c.register_function(f1, "dup", [("x", np.float64)], np.float64)
    c.register_function(f1, "dup", [("x", np.float64)], np.float64, replace=True)

def test_row_udf(c, df):
    def row_f(row):
        return row["x"] + row["y"]

    c.register_function(row_f, "row_f", [("x", np.float64), ("y", np.float64)],
                        np.float64, row_udf=True)
    result = c.sql("SELECT row_f(a, b) AS r FROM df").compute()
    np.testing.assert_allclose(result["r"], df.a + df.b)

def test_udaf(c, df):
    def my_range(grouped):
        return grouped.max() - grouped.min()

    c.register_aggregation(my_range, "my_range", [("x", np.float64)], np.float64)
    result = c.sql("SELECT a, my_range(b) AS r FROM df GROUP BY a").compute()
    expected = (df.groupby("a").b.max() - df.groupby("a").b.min()).reset_index(name="r")
    assert_eq(result.sort_values("a").reset_index(drop=True),
              expected, check_dtype=False, check_names=False)

def test_jax_traceable_udf(c, df):
    import jax.numpy as jnp

    def smooth(x):
        return jnp.tanh(x / 10.0)

    c.register_function(smooth, "smooth", [("x", np.float64)], np.float64)
    result = c.sql("SELECT smooth(b) AS r FROM df").compute()
    np.testing.assert_allclose(result["r"], np.tanh(df.b / 10.0), rtol=1e-12)
