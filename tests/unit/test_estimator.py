"""Static cost & memory estimator: interval propagation, EXPLAIN ESTIMATE,
the pre-compile admission byte gate, and proof-driven ladder rung skips.

The acceptance-critical properties live here: the gate sheds provably
over-budget queries BEFORE any compilation (asserted through the `compile`
fault-injection site staying un-fired), rung proofs skip compiled
aggregates with ``resilience.degraded == 0``, the native and Python parser
paths produce the same ESTIMATE rows, and the upper bound dominates the
measured byte footprint on the q1/q3-shaped bench tables.
"""
import numpy as np
import pandas as pd
import pytest

from dask_sql_tpu import Context
from dask_sql_tpu import config as config_module
from dask_sql_tpu.analysis import estimator
from dask_sql_tpu.analysis.estimator import Interval
from dask_sql_tpu.columnar.dtypes import SqlType
from dask_sql_tpu.planner import plan as p
from dask_sql_tpu.planner.expressions import ColumnRef, Field, Literal
from dask_sql_tpu.planner.parser import parse_sql
from dask_sql_tpu.resilience import faults
from dask_sql_tpu.serving.admission import EstimatedBytesExceededError
from dask_sql_tpu.serving.cache import table_nbytes

pytestmark = pytest.mark.estimator


@pytest.fixture(autouse=True)
def _fresh_injector():
    faults.reset()
    yield
    faults.reset()


@pytest.fixture
def ctx():
    c = Context()
    c.create_table("t", pd.DataFrame({
        "a": np.arange(100, dtype=np.int64),
        "b": [f"k{i % 7}" for i in range(100)],
        "v": np.arange(100, dtype=np.float64),
    }))
    return c


def _estimate(ctx, sql):
    plan = ctx._get_ral(parse_sql(sql)[0], sql_text=sql)
    return estimator.estimate_plan(plan, context=ctx)


# ------------------------------------------------------- interval lattice
def test_interval_arithmetic_saturates_unbounded():
    a = Interval(2, 10)
    b = Interval(3, None)
    assert (a + b) == Interval(5, None)
    assert (a * b) == Interval(6, None)
    assert (a + Interval(1, 1)) == Interval(3, 11)
    assert a.clamp_hi(4) == Interval(2, 4)
    assert Interval(100, 100).clamp_hi(10) == Interval(10, 10)
    assert b.clamp_hi(7) == Interval(3, 7)
    assert a.drop_lo() == Interval(0, 10)
    assert Interval.exact(5).fmt() == "[5, 5]"
    assert b.fmt() == "[3, unbounded]"


# -------------------------------------------------- per-node propagation
def test_scan_rows_exact_from_statistics(ctx):
    est = _estimate(ctx, "SELECT * FROM t")
    assert est.rows == Interval(100, 100)
    # exact rows -> exact result bytes (lo == hi at the unpadded count is
    # not required, but lo must be positive and hi finite)
    assert est.result_bytes.lo > 0
    assert est.result_bytes.hi is not None


def test_filter_drops_lower_bound(ctx):
    est = _estimate(ctx, "SELECT a FROM t WHERE v > 50")
    assert est.rows.lo == 0
    assert est.rows.hi == 100


def test_limit_clamps_both_bounds(ctx):
    est = _estimate(ctx, "SELECT a FROM t LIMIT 7")
    assert est.rows == Interval(7, 7)
    est = _estimate(ctx, "SELECT a FROM t WHERE v > 50 LIMIT 7")
    assert est.rows == Interval(0, 7)


def test_cross_join_multiplies(ctx):
    est = _estimate(ctx, "SELECT t1.a FROM t t1, t t2")
    assert est.rows == Interval(100 * 100, 100 * 100)


def test_inner_join_zero_lower_bound(ctx):
    est = _estimate(ctx, "SELECT t1.a FROM t t1 JOIN t t2 ON t1.a = t2.a")
    assert est.rows.lo == 0
    assert est.rows.hi == 100 * 100


def test_outer_join_bound_survives_empty_side():
    """Regression: LEFT/RIGHT/FULL preserve their side even against an
    empty opposite input — the upper bound must not collapse to 0 below
    the actual row count (and the interval must stay well-formed)."""
    c = Context()
    c.create_table("l", pd.DataFrame({"k": np.array([1, 2, 3], dtype=np.int64)}))
    c.create_table("r", pd.DataFrame({"k": pd.Series([], dtype="int64"),
                                      "w": pd.Series([], dtype="float64")}))
    for jt, lo, hi in [("LEFT", 3, 3), ("RIGHT", 0, 0), ("FULL", 3, 3)]:
        sql = f"SELECT l.k FROM l {jt} JOIN r ON l.k = r.k"
        est = _estimate(c, sql)
        actual = len(c.sql(sql, return_futures=False))
        assert est.rows.lo == lo, jt
        assert est.rows.hi == hi, jt
        assert est.rows.lo <= actual <= est.rows.hi, jt


def test_aggregate_rows_clamped_by_radix_domain(ctx):
    # b has 7 distinct values -> dictionary size 7, +1 NULL sentinel = 8
    est = _estimate(ctx, "SELECT b, SUM(v) FROM t GROUP BY b")
    assert est.rows.lo == 1
    assert est.rows.hi == 8


def test_global_aggregate_is_exactly_one_row(ctx):
    est = _estimate(ctx, "SELECT SUM(v) FROM t")
    assert est.rows == Interval(1, 1)


def test_global_aggregate_scratch_not_charged_the_radix_gate(ctx):
    """Regression: a no-GROUP-BY aggregate has a known domain of exactly 1,
    so its packed-matrix upper bound must be slots*8 bytes — not the full
    ~33.5 MB 1<<22 gate cap."""
    est = _estimate(ctx, "SELECT SUM(v) FROM t")
    assert est.peak_bytes.hi is not None
    assert est.peak_bytes.hi < 1 << 20  # table is ~2.5 KB; gate cap is 2^25


def test_union_all_sums_and_distinct_drops_lo(ctx):
    est = _estimate(ctx, "SELECT a FROM t UNION ALL SELECT a FROM t")
    assert est.rows == Interval(200, 200)
    est = _estimate(ctx, "SELECT a FROM t UNION SELECT a FROM t")
    assert est.rows.lo == 1
    assert est.rows.hi == 200


def test_values_exact(ctx):
    est = _estimate(ctx, "SELECT * FROM (VALUES (1), (2), (3)) AS w(x)")
    assert est.rows == Interval(3, 3)


def test_direct_node_construction_sort_fetch():
    scan = p.TableScan("root", "t", [Field("a", SqlType.BIGINT)],
                       projection=["a"])
    srt = p.Sort(scan, [], [Field("a", SqlType.BIGINT)], fetch=5)
    est = estimator.estimate_plan(srt)
    # no context -> scan rows unknown, but the fetch still caps the top
    assert est.rows.hi == 5


def test_unknown_scan_is_unbounded():
    scan = p.TableScan("root", "missing", [Field("a", SqlType.BIGINT)],
                       projection=["a"])
    est = estimator.estimate_plan(scan)
    assert est.rows == Interval(0, None)
    assert est.peak_bytes.hi is None


def test_lower_bound_never_charges_validity_masks(ctx):
    """Regression: a nullable-declared column materializes a validity mask
    only when nulls occur, so the provable lower bound (which admission
    sheds on) must stay at or below the actual resident bytes of an
    all-valid table; the mask belongs in the upper bound only."""
    est = _estimate(ctx, "SELECT * FROM t")
    actual = table_nbytes(ctx.schema["root"].tables["t"].table)
    # lo = resident scan + materialized root; the root here aliases the
    # scan, so lo is exactly the scan's data buffers
    assert est.peak_bytes.lo <= actual
    assert est.peak_bytes.hi >= actual


def test_explain_analyze_estimate_is_bounded(ctx):
    """Regression: bind-time estimation of EXPLAIN ANALYZE must estimate
    the executing input plan, not the Explain text node (whose unknown
    render size used to force every bound to unbounded)."""
    from dask_sql_tpu.planner.parser import parse_sql

    sql = "EXPLAIN ANALYZE SELECT b, SUM(v) FROM t GROUP BY b"
    plan = ctx._get_ral(parse_sql(sql)[0], sql_text=sql)
    est = getattr(plan, "_dsql_estimate", None)
    assert est is not None
    assert est.rows.hi is not None
    assert est.peak_bytes.hi is not None


def test_peak_lower_bound_counts_resident_scans(ctx):
    est = _estimate(ctx, "SELECT a FROM t WHERE v > 1e9")
    # even a filter that keeps nothing cannot run below the resident base
    # table bytes: 100 rows x (int64 a + float64 v nullable)
    assert est.peak_bytes.lo >= 100 * 16
    # and the upper bound dominates the lower everywhere
    assert est.peak_bytes.hi >= est.peak_bytes.lo


# ------------------------------------------------------- EXPLAIN ESTIMATE
def test_explain_estimate_shape(ctx):
    out = ctx.sql("EXPLAIN ESTIMATE SELECT b, SUM(v) FROM t GROUP BY b",
                  return_futures=False)
    assert list(out.columns) == ["ESTIMATE"]
    head = out["ESTIMATE"][0]
    assert head.startswith("estimate: rows_lo=")
    for token in ("rows_lo=", "rows_hi=", "bytes_lo=", "bytes_hi="):
        assert token in head
    text = "\n".join(out["ESTIMATE"])
    assert "result: bytes=" in text
    assert "node " in text


def test_explain_estimate_native_python_parity(ctx):
    sql = "EXPLAIN ESTIMATE SELECT b, SUM(v) FROM t GROUP BY b"
    native = ctx.sql(sql, return_futures=False,
                     config_options={"sql.native.binder": "on"})
    python = ctx.sql(sql, return_futures=False,
                     config_options={"sql.native.binder": "off",
                                     "serving.cache.enabled": False})
    assert list(native.columns) == list(python.columns) == ["ESTIMATE"]
    # the headline interval must be identical across parser paths
    assert native["ESTIMATE"][0] == python["ESTIMATE"][0]


def test_explain_estimate_never_executes(ctx):
    """Executing the input would run its compiled aggregate and fire the
    armed `oom` site; EXPLAIN ESTIMATE only renders, so it never does."""
    with config_module.set({"resilience.inject": "oom:always"}):
        out = ctx.sql("EXPLAIN ESTIMATE SELECT b, SUM(v) FROM t GROUP BY b",
                      return_futures=False,
                      config_options={"serving.cache.enabled": False})
        inj = faults.get_injector(config_module.config)
        assert inj is not None and inj.fired("oom") == 0
    assert out["ESTIMATE"][0].startswith("estimate:")


def test_explain_estimate_reports_over_budget_instead_of_shedding(ctx):
    # EXPLAIN ESTIMATE of an over-budget query must REPORT, never shed
    out = ctx.sql(
        "EXPLAIN ESTIMATE SELECT t1.a FROM t t1, t t2",
        return_futures=False,
        config_options={"serving.admission.max_estimated_bytes": 1,
                        "serving.cache.enabled": False})
    assert out["ESTIMATE"][0].startswith("estimate:")


# --------------------------------------------------- admission byte gate
def test_gate_sheds_before_any_compile(ctx):
    """Acceptance: a synthetic over-budget query is shed with a taxonomy
    error while the `compile` fault-injection site proves zero compilation
    was attempted (an armed compile:always fault that never fires)."""
    spec = {"serving.admission.max_estimated_bytes": 1 << 16,
            "resilience.inject": "compile:always",
            "serving.cache.enabled": False}
    with config_module.set(spec):
        with pytest.raises(EstimatedBytesExceededError) as ei:
            ctx.sql("SELECT t1.a, t2.v FROM t t1, t t2",
                    return_futures=False)
        inj = faults.get_injector(config_module.config)
        assert inj is not None and inj.fired("compile") == 0
    err = ei.value
    assert err.code == "ESTIMATED_BYTES_EXCEEDED"
    assert err.retryable is False
    assert err.payload()["errorType"] == "INSUFFICIENT_RESOURCES"
    assert err.estimated_bytes_lo > err.budget_bytes == 1 << 16
    counters = ctx.metrics.snapshot()["counters"]
    assert counters.get("serving.shed_estimated_bytes", 0) >= 1
    assert counters.get("analysis.estimate.runs", 0) >= 1
    # nothing executed, nothing degraded
    assert counters.get("query.executed", 0) == 0
    assert counters.get("resilience.degraded", 0) == 0


def test_gate_admits_within_budget(ctx):
    out = ctx.sql(
        "SELECT b, SUM(v) AS s FROM t GROUP BY b", return_futures=False,
        config_options={"serving.admission.max_estimated_bytes": 1 << 30})
    assert len(out) == 7


def test_gate_disabled_by_default(ctx):
    out = ctx.sql("SELECT t1.a FROM t t1, t t2 LIMIT 5",
                  return_futures=False)
    assert len(out) == 5


def test_budget_string_zero_means_disabled(ctx):
    """Regression: config values arrive as strings through SET/env — a
    string "0" budget must disable the gate, not shed every query."""
    from dask_sql_tpu.config import parse_byte_budget

    for off in (None, "", 0, "0", " 0 ", "none", "OFF", "false", -1):
        assert parse_byte_budget(off) is None, off
    assert parse_byte_budget("1024") == 1024
    assert parse_byte_budget(1 << 20) == 1 << 20
    assert parse_byte_budget("64MB") == 64 << 20
    assert parse_byte_budget("2 GiB") == 2 << 30
    # malformed values disable with a warning instead of raising: a typo'd
    # budget must never fail every query at the execute boundary
    assert parse_byte_budget("sixty-four") is None
    for bad in ("0", "sixty-four"):
        out = ctx.sql(
            "SELECT a FROM t LIMIT 3", return_futures=False,
            config_options={"serving.admission.max_estimated_bytes": bad,
                            "serving.cache.enabled": False})
        assert len(out) == 3


def test_gate_error_wire_payload(ctx):
    from dask_sql_tpu.server.responses import error_results

    err = EstimatedBytesExceededError(10_000, 1_000)
    payload = error_results("q1", None, err)
    assert payload["error"]["errorName"] == "ESTIMATED_BYTES_EXCEEDED"
    assert payload["error"]["errorType"] == "INSUFFICIENT_RESOURCES"
    assert payload["error"]["retryable"] is False
    assert payload["error"]["estimatedBytesLow"] == 10_000
    assert payload["error"]["budgetBytes"] == 1_000


def test_result_cache_estimate_admission(ctx):
    """A result whose PROVABLE bytes exceed the per-entry cap is never
    inserted — no materialize-then-evict churn, no oversize reject."""
    with config_module.set({"serving.cache.max_entry_bytes": 64}):
        # rebuild the Context so the cache picks up the tiny cap
        c = Context()
        c.create_table("t", pd.DataFrame({
            "a": np.arange(100, dtype=np.int64),
            "v": np.arange(100, dtype=np.float64)}))
        out = c.sql("SELECT a, v FROM t", return_futures=False)
        assert len(out) == 100
        counters = c.metrics.snapshot()["counters"]
        assert counters.get("query.cache.estimate_skip", 0) >= 1
        # the estimator pre-empted the insert: no oversize reject happened
        assert c._result_cache.stats.oversize_rejects == 0
        assert c._result_cache.stats.inserts == 0


# ------------------------------------------------------- ladder rung proof
def test_rung_proof_preskips_compiled_aggregate(ctx):
    """Acceptance: an aggregate whose packed-matrix lower bound cannot fit
    the device budget runs via lower rungs with zero degradations — the
    compiled rungs are skipped by proof, not by failure."""
    out = ctx.sql(
        "SELECT b, SUM(v) AS s FROM t GROUP BY b", return_futures=False,
        config_options={"analysis.estimate.device_budget_bytes": 16,
                        "serving.cache.enabled": False})
    assert len(out) == 7
    counters = ctx.metrics.snapshot()["counters"]
    assert counters.get("analysis.estimate.rung_proof", 0) >= 1
    assert counters.get("analysis.rung_skip.compiled_aggregate", 0) >= 1
    assert counters.get("resilience.degraded", 0) == 0


def test_explain_estimate_renders_rung_proof(ctx):
    """Regression: EXPLAIN ESTIMATE must show the budget proof rows the
    execution path would act on (without marking the plan)."""
    out = ctx.sql(
        "EXPLAIN ESTIMATE SELECT b, SUM(v) FROM t GROUP BY b",
        return_futures=False,
        config_options={"analysis.estimate.device_budget_bytes": 16,
                        "serving.cache.enabled": False})
    text = "\n".join(out["ESTIMATE"])
    assert "rungs pre-skipped" in text
    assert "compiled_aggregate" in text


def test_rung_proof_absent_with_roomy_budget(ctx):
    ctx.sql("SELECT b, SUM(v) AS s FROM t GROUP BY b", return_futures=False,
            config_options={"analysis.estimate.device_budget_bytes": 1 << 34,
                            "serving.cache.enabled": False})
    counters = ctx.metrics.snapshot()["counters"]
    assert counters.get("analysis.estimate.rung_proof", 0) == 0


# ------------------------------------------- estimate-vs-actual soundness
def _bench_tables(n=20_000):
    from tests.tpch import generate

    return generate(scale_rows=n)


def test_upper_bound_dominates_actual_q1_shape():
    """q1-shaped bench query: measured result + resident input bytes never
    exceed the estimator's upper bound (soundness of the hi bound)."""
    import bench

    df = bench.gen_lineitem(50_000, seed=0)
    with config_module.set({"serving.cache.enabled": False}):
        c = Context()
        c.create_table("lineitem", df)
        plan = c._get_ral(parse_sql(bench.QUERY)[0], sql_text=bench.QUERY)
        est = estimator.estimate_plan(plan, context=c)
        frame = c.sql(bench.QUERY)
        result_table = frame.execute()
        result = frame.compute()
    assert len(result) > 0
    # resident inputs + materialized result coexist at query end: a true
    # peak lower bound the estimator's upper bound must dominate
    measured = sum(
        table_nbytes(dc.table)
        for dc in c.schema["root"].tables.values())
    measured += table_nbytes(result_table)
    assert est.peak_bytes.hi is not None
    # the provable lower bound must stay below the observed resident bytes
    # it claims (this is what admission sheds on), the upper bound above
    assert est.peak_bytes.lo <= measured <= est.peak_bytes.hi
    assert est.peak_bytes.hi >= est.peak_bytes.lo
    # the root cardinality bound holds for the actual result
    assert est.rows.lo <= len(result)
    assert est.rows.hi is None or len(result) <= est.rows.hi


@pytest.mark.slow
def test_upper_bound_dominates_actual_q3_shape():
    from tests.tpch import QUERIES

    tables = _bench_tables(20_000)
    with config_module.set({"serving.cache.enabled": False}):
        c = Context()
        for name, frame in tables.items():
            c.create_table(name, frame)
        sql = QUERIES[3]
        plan = c._get_ral(parse_sql(sql)[0], sql_text=sql)
        est = estimator.estimate_plan(plan, context=c)
        frame = c.sql(sql)
        result_table = frame.execute()
        result = frame.compute()
    # the estimate is plan-scoped: measure only the tables the plan scans,
    # plus the materialized result they coexist with at query end
    scanned = set()

    def _scans(node):
        if isinstance(node, p.TableScan):
            scanned.add(node.table_name)
        for child in node.inputs():
            _scans(child)

    _scans(plan)
    measured = sum(
        table_nbytes(c.schema["root"].tables[t].table) for t in scanned)
    measured += table_nbytes(result_table)
    assert est.peak_bytes.lo <= measured
    assert est.peak_bytes.hi is None or est.peak_bytes.hi >= measured
    assert est.rows.hi is None or len(result) <= est.rows.hi
    assert est.rows.lo <= len(result)


# ----------------------------------------------------------- metrics view
def test_estimate_metrics_visible_in_show_metrics(ctx):
    ctx.sql("SELECT b, SUM(v) FROM t GROUP BY b", return_futures=False)
    out = ctx.sql("SHOW METRICS LIKE 'analysis.estimate.%'",
                  return_futures=False)
    names = set(out[out.columns[0]])
    assert any(n.startswith("analysis.estimate.bytes_lo") for n in names)
    assert "analysis.estimate.runs" in names


# ------------------------------------------------------------ DSQL401 lint
def test_lint_flags_undocumented_metric_name():
    from dask_sql_tpu.analysis.selflint import lint_source

    src = 'def f(metrics):\n    metrics.inc("anaylsis.typo_counter")\n'
    assert [f.rule for f in lint_source(src, "f.py")] == ["DSQL401"]
    ok = 'def f(metrics):\n    metrics.inc("serving.admitted")\n'
    assert lint_source(ok, "f.py") == []
    fam = 'def f(metrics, r):\n    metrics.inc(f"resilience.rung.{r}")\n'
    assert lint_source(fam, "f.py") == []
    bad_fam = 'def f(metrics, r):\n    metrics.inc(f"resilience.wrung.{r}")\n'
    assert [f.rule for f in lint_source(bad_fam, "f.py")] == ["DSQL401"]
    sup = ('def f(metrics):\n'
           '    metrics.inc("oneoff.x")  # dsql: allow-metric-name\n')
    assert lint_source(sup, "f.py") == []
    # dynamic names make no claim
    dyn = 'def f(metrics, n):\n    metrics.inc(n)\n'
    assert lint_source(dyn, "f.py") == []
    # an exact literal that truncates a documented family prefix is DRIFT
    # (missing the per-rule suffix); only f-string prefixes get that slack
    trunc = 'def f(metrics):\n    metrics.inc("analysis.findings")\n'
    assert [f.rule for f in lint_source(trunc, "f.py")] == ["DSQL401"]
    short_fam = 'def f(metrics, r):\n    metrics.inc(f"analysis.fin{r}")\n'
    assert lint_source(short_fam, "f.py") == []
