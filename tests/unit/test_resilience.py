"""Resilience subsystem: error taxonomy, retry/backoff, circuit breaker,
degradation ladder, and the fault-injection harness that proves each of
them actually fires (ISSUE 3 acceptance criteria)."""
import threading
import time

import pandas as pd
import pytest

from dask_sql_tpu import Context
from dask_sql_tpu import config as config_module
from dask_sql_tpu.resilience import faults
from dask_sql_tpu.resilience.errors import (
    CompileError,
    DeadlineError,
    ExecutionError,
    ParseError,
    QueryError,
    ResourceExhaustedError,
    ShutdownError,
    TransientExecutionError,
    classify,
)
from dask_sql_tpu.resilience.faults import FaultInjector
from dask_sql_tpu.resilience.ladder import plan_fingerprint
from dask_sql_tpu.resilience.retry import BackoffPolicy, CircuitBreaker, retry_call


@pytest.fixture(autouse=True)
def _fresh_injector():
    """Every test starts with no armed faults and leaves none behind; the
    tests that must mutate the *global* config (serving worker threads do
    not see thread-local overlays) get it restored here."""
    saved = dict(config_module.config._values)
    faults.reset()
    yield
    config_module.config._values = saved
    faults.reset()


def _ctx():
    c = Context()
    c.create_table("t", pd.DataFrame({"a": [1, 2, 3], "b": [1.5, 2.5, 3.5]}))
    return c


# ---------------------------------------------------------------- taxonomy
def test_taxonomy_flags_and_codes():
    assert CompileError("x").degradable and not CompileError("x").retryable
    assert ResourceExhaustedError("x").degradable
    assert TransientExecutionError("x").retryable
    assert not DeadlineError("x").retryable
    assert ShutdownError("x").retryable
    p = ResourceExhaustedError("x").payload()
    assert p["code"] == "RESOURCE_EXHAUSTED"
    assert p["errorType"] == "INSUFFICIENT_RESOURCES"
    # instance overrides beat class defaults
    e = CompileError("known-permanent", degradable=False)
    assert not e.degradable


def test_taxonomy_exceptions_module_aliases():
    from dask_sql_tpu.exceptions import (
        BindError,
        LexError,
        OptimizationException,
        ParsingException,
    )

    # historical contracts: still ValueErrors / RuntimeErrors
    assert issubclass(ParsingException, ValueError)
    assert issubclass(BindError, ValueError)
    assert issubclass(LexError, ValueError)
    assert issubclass(OptimizationException, RuntimeError)
    # and now taxonomy members with stable codes
    assert issubclass(ParsingException, QueryError)
    assert ParsingException("x").code == "PARSE_ERROR"
    assert BindError("x").code == "BIND_ERROR"
    assert OptimizationException("x").code == "OPTIMIZATION_ERROR"


def test_parse_error_is_taxonomy_through_sql():
    c = _ctx()
    with pytest.raises(ParseError) as ei:
        c.sql("SELEC nope")
    assert ei.value.payload()["errorType"] == "USER_ERROR"


def test_classify_maps_oom_and_transients():
    assert isinstance(classify(RuntimeError("RESOURCE_EXHAUSTED: out of "
                                            "memory allocating 1GB")),
                      ResourceExhaustedError)
    assert isinstance(classify(MemoryError()), ResourceExhaustedError)
    assert isinstance(classify(ConnectionError("reset")),
                      TransientExecutionError)
    wrapped = classify(KeyError("ghost"))
    assert isinstance(wrapped, ExecutionError) and not wrapped.retryable
    # OOM matching is word-bounded: ROOM/ZOOM must not look like device OOM
    assert classify(KeyError("ROOM_ID")).code == "EXECUTION_ERROR"
    assert isinstance(classify(RuntimeError("device OOM")),
                      ResourceExhaustedError)
    # permanent filesystem errors are NOT retryable transients
    assert not classify(FileNotFoundError("gone.parquet")).retryable
    assert not classify(PermissionError("denied")).retryable
    # idempotent on taxonomy members
    e = CompileError("x")
    assert classify(e) is e


def test_executor_boundary_wraps_raw_failures():
    """A non-taxonomy crash inside execution leaves TpuFrame.execute as a
    structured QueryError (still a RuntimeError for old callers)."""
    c = _ctx()

    def boom(x):
        raise ValueError("kernel exploded")

    import numpy as np

    c.register_function(boom, "boom_udf", [("x", np.int64)], np.int64)
    with pytest.raises(QueryError) as ei:
        c.sql("SELECT boom_udf(a) AS v FROM t", return_futures=False)
    assert ei.value.code == "EXECUTION_ERROR"


# ------------------------------------------------------------------ faults
def test_fault_spec_parsing_and_budgets():
    inj = FaultInjector("compile:once,oom:2,execute:always")
    assert inj.arm("compile") and not inj.arm("compile")
    assert inj.arm("oom") and inj.arm("oom") and not inj.arm("oom")
    assert all(inj.arm("execute") for _ in range(5))
    assert not inj.arm("checkpoint")  # unlisted site never fires


def test_fault_probability_deterministic():
    i1 = FaultInjector("compile:0.5", seed=7)
    i2 = FaultInjector("compile:0.5", seed=7)
    seq = [i1.arm("compile") for _ in range(32)]
    assert seq == [i2.arm("compile") for _ in range(32)]
    assert any(seq) and not all(seq)  # p=0.5 really mixes outcomes


def test_fault_unknown_site_rejected():
    with pytest.raises(ValueError):
        FaultInjector("warpcore:once")


def test_fault_injector_keyed_on_spec_and_seed():
    with config_module.set({"resilience.inject": "compile:once"}):
        inj1 = faults.get_injector(config_module.config)
        assert inj1.arm("compile")
        assert faults.get_injector(config_module.config) is inj1  # state kept
    with config_module.set({"resilience.inject": "oom:once"}):
        inj2 = faults.get_injector(config_module.config)
        assert inj2 is not inj1
    # same spec, different seed -> fresh injector (fresh PRNG + budgets)
    with config_module.set({"resilience.inject": "compile:once",
                            "resilience.inject.seed": 9}):
        inj3 = faults.get_injector(config_module.config)
        assert inj3 is not inj1 and inj3.arm("compile")
    # alternating scopes do NOT reset each other's budgets
    with config_module.set({"resilience.inject": "compile:once"}):
        assert faults.get_injector(config_module.config) is inj1
        assert not inj1.arm("compile")  # still spent
    with config_module.set({"resilience.inject": None}):
        assert faults.get_injector(config_module.config) is None


# ----------------------------------------------------------------- retry
def test_backoff_schedule_deterministic_and_capped():
    p = BackoffPolicy(max_attempts=5, base_s=0.1, multiplier=2.0, max_s=0.3,
                      jitter=0.0, seed=0)
    assert p.delay_s(1) == pytest.approx(0.1)
    assert p.delay_s(2) == pytest.approx(0.2)
    assert p.delay_s(3) == pytest.approx(0.3)  # capped
    assert p.delay_s(4) == pytest.approx(0.3)
    j1 = BackoffPolicy(jitter=0.5, seed=42)
    j2 = BackoffPolicy(jitter=0.5, seed=42)
    assert [j1.delay_s(i) for i in (1, 2, 3)] == \
        [j2.delay_s(i) for i in (1, 2, 3)]


def test_retry_call_recovers_transient():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise TransientExecutionError("hiccup")
        return "ok"

    slept = []
    out = retry_call(flaky, BackoffPolicy(max_attempts=3, base_s=0.01,
                                          jitter=0.0),
                     sleep=slept.append)
    assert out == "ok" and len(calls) == 3 and len(slept) == 2


def test_retry_call_gives_up_after_max_attempts():
    def always_bad():
        raise TransientExecutionError("hiccup")

    with pytest.raises(TransientExecutionError):
        retry_call(always_bad, BackoffPolicy(max_attempts=2, base_s=0.0),
                   sleep=lambda s: None)


def test_retry_call_never_retries_permanent():
    calls = []

    def bad():
        calls.append(1)
        raise ExecutionError("broken plan")

    with pytest.raises(ExecutionError):
        retry_call(bad, BackoffPolicy(max_attempts=5, base_s=0.0),
                   sleep=lambda s: None)
    assert len(calls) == 1


def test_retry_call_respects_deadline():
    """A backoff sleep that would blow the deadline aborts immediately."""
    from dask_sql_tpu.serving import QueryTicket

    ticket = QueryTicket("q", deadline=time.monotonic() + 0.05)

    def flaky():
        raise TransientExecutionError("hiccup")

    t0 = time.monotonic()
    with pytest.raises(TransientExecutionError):
        retry_call(flaky, BackoffPolicy(max_attempts=10, base_s=5.0,
                                        jitter=0.0), ticket=ticket)
    assert time.monotonic() - t0 < 1.0  # did NOT sleep the 5s backoff


# ----------------------------------------------------------------- breaker
def test_breaker_trips_and_cools_down():
    now = [0.0]
    b = CircuitBreaker(threshold=2, cooldown_s=10.0, clock=lambda: now[0])
    key = ("fp", "compiled")
    assert b.allow(key)
    assert not b.record_failure(key)
    assert b.allow(key)  # one failure: still closed
    assert b.record_failure(key)  # trips now
    assert not b.allow(key)
    now[0] = 11.0
    assert b.allow(key)        # half-open trial admitted
    assert not b.allow(key)    # ...but only one
    b.record_success(key)
    assert b.allow(key)        # closed again


def test_breaker_unsettled_trial_does_not_stick_open():
    """A half-open trial that never settles (the rung *declined* — neither
    success nor failure recorded) must not leave the circuit open forever:
    the next cooldown admits another trial."""
    now = [0.0]
    b = CircuitBreaker(threshold=1, cooldown_s=10.0, clock=lambda: now[0])
    key = ("fp", "compiled")
    b.record_failure(key)  # trips (threshold 1)
    now[0] = 11.0
    assert b.allow(key)  # half-open trial; rung declines, nothing recorded
    assert not b.allow(key)
    now[0] = 22.0
    assert b.allow(key)  # another cooldown elapsed: trial re-admitted
    b.record_success(key)
    assert b.allow(key) and b.allow(key)  # fully closed


def test_breaker_success_resets_counter():
    b = CircuitBreaker(threshold=2, cooldown_s=10.0)
    key = ("fp", "r")
    b.record_failure(key)
    b.record_success(key)
    b.record_failure(key)
    assert b.allow(key)  # 1 consecutive failure, not 2


def test_plan_fingerprint_stable():
    c = _ctx()
    p1 = c.sql("SELECT SUM(a) AS s FROM t").plan
    p2 = c.sql("SELECT SUM(a) AS s FROM t").plan
    p3 = c.sql("SELECT SUM(b) AS s FROM t").plan
    assert plan_fingerprint(p1) == plan_fingerprint(p2)
    assert plan_fingerprint(p1) != plan_fingerprint(p3)


# ------------------------------------------------- ladder (fault-injected)
@pytest.mark.faults
def test_forced_compile_failure_degrades_and_matches():
    """Acceptance: a forced compile failure completes the query via a lower
    rung, the result matches the non-injected run, and resilience.* metrics
    recorded the degradation."""
    clean = _ctx().sql("SELECT SUM(a) AS s FROM t GROUP BY a > 1 "
                       "ORDER BY s", return_futures=False)
    c = _ctx()
    with config_module.set({"resilience.inject": "compile:always",
                            "serving.cache.enabled": False}):
        hurt = c.sql("SELECT SUM(a) AS s FROM t GROUP BY a > 1 "
                     "ORDER BY s", return_futures=False)
    pd.testing.assert_frame_equal(hurt, clean)
    assert c.metrics.counter("resilience.degraded") >= 1
    df = c.sql("SHOW METRICS LIKE 'resilience.%'", return_futures=False)
    rows = dict(zip(df["Metric"], df["Value"]))
    assert int(rows["resilience.degraded"]) >= 1


@pytest.mark.faults
def test_forced_oom_degrades_and_matches():
    """Acceptance: a forced device-OOM inside the compiled rung completes
    via the interpreted rung with an identical result."""
    clean = _ctx().sql("SELECT SUM(a) AS s FROM t", return_futures=False)
    c = _ctx()
    with config_module.set({"resilience.inject": "oom:once",
                            "serving.cache.enabled": False}):
        hurt = c.sql("SELECT SUM(a) AS s FROM t", return_futures=False)
    pd.testing.assert_frame_equal(hurt, clean)
    assert c.metrics.counter("resilience.degraded") == 1


@pytest.mark.faults
def test_forced_exec_oom_takes_cpu_rung():
    """Device ladder bottom: interpreted-path OOM re-executes on the CPU
    backend instead of failing."""
    c = _ctx()
    with config_module.set({"resilience.inject": "exec_oom:once",
                            "serving.cache.enabled": False,
                            "sql.compile": False}):
        out = c.sql("SELECT SUM(a) AS s FROM t", return_futures=False)
    assert int(out["s"][0]) == 6
    assert c.metrics.counter("resilience.rung.cpu") == 1
    assert c.metrics.counter("resilience.degraded.interpreted") == 1


@pytest.mark.faults
def test_ladder_disabled_propagates_failure():
    c = _ctx()
    with config_module.set({"resilience.inject": "compile:always",
                            "resilience.ladder.enabled": False,
                            "serving.cache.enabled": False}):
        with pytest.raises(CompileError):
            c.sql("SELECT SUM(a) AS s FROM t", return_futures=False)


@pytest.mark.faults
def test_breaker_skips_failing_rung_on_next_submission():
    """Acceptance: a repeatedly-failing plan fingerprint trips the breaker
    and the next submission skips the failing rung instead of re-failing."""
    c = _ctx()
    c.breaker.threshold = 2
    q = "SELECT SUM(a) AS s FROM t"
    with config_module.set({"resilience.inject": "compile:always",
                            "serving.cache.enabled": False}):
        c.sql(q, return_futures=False)
        c.sql(q, return_futures=False)
        assert c.metrics.counter("resilience.breaker.trip") >= 1
        degraded_before = c.metrics.counter(
            "resilience.degraded.compiled_select")
        out = c.sql(q, return_futures=False)
    assert int(out["s"][0]) == 6
    # third run skipped the compiled_select rung (breaker open): no new
    # degradation was paid for it
    assert c.metrics.counter("resilience.breaker.skip") >= 1
    assert c.metrics.counter(
        "resilience.degraded.compiled_select") == degraded_before


@pytest.mark.faults
def test_transient_execute_fault_retried_within_deadline():
    """Acceptance: a forced transient execute fault is retried with backoff
    at the serving worker and succeeds within the ticket deadline."""
    from dask_sql_tpu.resilience.retry import BackoffPolicy
    from dask_sql_tpu.serving import ServingRuntime

    c = _ctx()
    config_module.config.update({"resilience.inject": "execute:2",
                                 "serving.cache.enabled": False})
    rt = ServingRuntime(
        workers=1,
        retry_policy=BackoffPolicy(max_attempts=3, base_s=0.01, jitter=0.0))
    try:
        _, fut, _ = rt.submit(
            lambda t: c.sql("SELECT SUM(a) AS s FROM t",
                            return_futures=False),
            deadline_s=30.0)
        out = fut.result(30)
        assert int(out["s"][0]) == 6
        assert rt.metrics.counter("resilience.retry.attempts") == 2
        assert rt.metrics.counter("resilience.retry.recovered") == 1
        assert rt.metrics.counter("serving.completed") == 1
    finally:
        rt.shutdown(wait=True)
        config_module.config.update({"resilience.inject": None})


@pytest.mark.faults
def test_transient_fault_exhausts_attempts_surfaces_structured():
    from dask_sql_tpu.resilience.retry import BackoffPolicy
    from dask_sql_tpu.serving import ServingRuntime

    c = _ctx()
    config_module.config.update({"resilience.inject": "execute:always",
                                 "serving.cache.enabled": False})
    rt = ServingRuntime(
        workers=1,
        retry_policy=BackoffPolicy(max_attempts=2, base_s=0.0, jitter=0.0))
    try:
        _, fut, _ = rt.submit(
            lambda t: c.sql("SELECT SUM(a) AS s FROM t",
                            return_futures=False))
        with pytest.raises(TransientExecutionError):
            fut.result(30)
        assert rt.metrics.counter("resilience.retry.attempts") == 1
        assert rt.metrics.counter("serving.failed") == 1
    finally:
        rt.shutdown(wait=True)
        config_module.config.update({"resilience.inject": None})


# -------------------------------------------------------- wire integration
@pytest.mark.faults
def test_server_reports_structured_taxonomy_error():
    """The Presto wire payload carries the taxonomy code and retryable
    flag for an injected failure with the ladder disabled."""
    import json
    import urllib.request

    from dask_sql_tpu.server.app import run_server

    c = _ctx()
    server = run_server(context=c, host="127.0.0.1", port=0, blocking=False)
    try:
        config_module.config.update({"resilience.inject": "compile:always",
                                     "resilience.ladder.enabled": False,
                                     "serving.cache.enabled": False})
        base = f"http://127.0.0.1:{server.port}"
        req = urllib.request.Request(
            f"{base}/v1/statement",
            data=b"SELECT SUM(a) AS s FROM t", method="POST")
        with urllib.request.urlopen(req) as resp:
            submitted = json.loads(resp.read())
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            with urllib.request.urlopen(submitted["nextUri"]) as resp:
                status = json.loads(resp.read())
            if status.get("error") or "data" in status \
                    or status["stats"]["state"] == "FINISHED":
                break
            time.sleep(0.05)
        err = status["error"]
        assert err["errorName"] == "INJECTED_COMPILE_ERROR"
        assert err["retryable"] is False and err["degradable"] is True
    finally:
        config_module.config.update({"resilience.inject": None,
                                     "resilience.ladder.enabled": True})
        server.shutdown()


def test_error_results_payload_for_taxonomy_member():
    from dask_sql_tpu.server import responses

    payload = responses.error_results("q1", None, ResourceExhaustedError(
        "device OOM"))
    err = payload["error"]
    assert err["errorName"] == "RESOURCE_EXHAUSTED"
    assert err["errorType"] == "INSUFFICIENT_RESOURCES"
    assert err["degradable"] is True
    # raw exceptions get classified, not passed through unstructured
    payload = responses.error_results("q2", None, KeyError("ghost"))
    assert payload["error"]["errorName"] == "EXECUTION_ERROR"
