"""Model class name resolution (parity: reference physical/utils/ml_classes.py
short-name -> FQCN maps for sklearn/cuML/XGBoost/LightGBM).  TPU-native names
resolve to ml/jax_models.py; sklearn FQCNs import directly."""
from __future__ import annotations

import importlib
from typing import Any

TPU_CLASSES = {
    "LinearRegression": "dask_sql_tpu.ml.jax_models.LinearRegression",
    "LogisticRegression": "dask_sql_tpu.ml.jax_models.LogisticRegression",
    "KMeans": "dask_sql_tpu.ml.jax_models.KMeans",
}

SKLEARN_CLASSES = {
    "LinearRegression": "sklearn.linear_model.LinearRegression",
    "LogisticRegression": "sklearn.linear_model.LogisticRegression",
    "SGDClassifier": "sklearn.linear_model.SGDClassifier",
    "SGDRegressor": "sklearn.linear_model.SGDRegressor",
    "KMeans": "sklearn.cluster.KMeans",
    "RandomForestClassifier": "sklearn.ensemble.RandomForestClassifier",
    "RandomForestRegressor": "sklearn.ensemble.RandomForestRegressor",
    "GradientBoostingClassifier": "sklearn.ensemble.GradientBoostingClassifier",
    "GradientBoostingRegressor": "sklearn.ensemble.GradientBoostingRegressor",
    "DecisionTreeClassifier": "sklearn.tree.DecisionTreeClassifier",
    "GaussianNB": "sklearn.naive_bayes.GaussianNB",
    "StandardScaler": "sklearn.preprocessing.StandardScaler",
    "XGBClassifier": "xgboost.XGBClassifier",
    "XGBRegressor": "xgboost.XGBRegressor",
    "LGBMClassifier": "lightgbm.LGBMClassifier",
    "LGBMRegressor": "lightgbm.LGBMRegressor",
}


def get_model_class(name: str, backend: str = "tpu") -> Any:
    """Resolve a model_class string: FQCN, short TPU-native name, or sklearn
    short name (parity: create_model.py class resolution CPU/GPU)."""
    if "." not in name:
        if backend == "tpu" and name in TPU_CLASSES:
            name = TPU_CLASSES[name]
        elif name in SKLEARN_CLASSES:
            name = SKLEARN_CLASSES[name]
        else:
            raise ValueError(f"Unknown model class {name!r}")
    module_name, _, class_name = name.rpartition(".")
    module = importlib.import_module(module_name)
    return getattr(module, class_name)
