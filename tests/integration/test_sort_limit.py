"""Sort/limit tests (parity: reference test_sort.py + limit parts)."""
import numpy as np
import pandas as pd
import pytest

from tests.utils import assert_eq


def test_sort(c, user_table_1):
    result = c.sql("SELECT * FROM user_table_1 ORDER BY b, user_id DESC").compute()
    expected = user_table_1.sort_values(["b", "user_id"], ascending=[True, False]).reset_index(drop=True)
    assert_eq(result, expected, check_dtype=False)

def test_sort_desc(c, df):
    result = c.sql("SELECT * FROM df ORDER BY b DESC").compute()
    expected = df.sort_values("b", ascending=False).reset_index(drop=True)
    assert_eq(result, expected, check_dtype=False)

def test_sort_nulls(c):
    data = pd.DataFrame({"a": [1.0, None, 3.0, None, 2.0]})
    c.create_table("sn", data)
    result = c.sql("SELECT * FROM sn ORDER BY a").compute()
    assert list(result["a"].fillna(-1)) == [1.0, 2.0, 3.0, -1, -1]  # nulls last by default
    result = c.sql("SELECT * FROM sn ORDER BY a NULLS FIRST").compute()
    assert list(result["a"].fillna(-1)) == [-1, -1, 1.0, 2.0, 3.0]
    result = c.sql("SELECT * FROM sn ORDER BY a DESC").compute()
    assert list(result["a"].fillna(-1)) == [-1, -1, 3.0, 2.0, 1.0]  # desc: nulls first
    result = c.sql("SELECT * FROM sn ORDER BY a DESC NULLS LAST").compute()
    assert list(result["a"].fillna(-1)) == [3.0, 2.0, 1.0, -1, -1]

def test_sort_strings(c, string_table):
    result = c.sql("SELECT * FROM string_table ORDER BY a").compute()
    expected = string_table.sort_values("a").reset_index(drop=True)
    assert_eq(result, expected, check_dtype=False)

def test_limit(c, long_table):
    result = c.sql("SELECT * FROM long_table LIMIT 101").compute()
    assert_eq(result, long_table.head(101), check_dtype=False)
    result = c.sql("SELECT * FROM long_table LIMIT 101 OFFSET 99").compute()
    assert_eq(result, long_table.iloc[99 : 99 + 101].reset_index(drop=True), check_dtype=False)

def test_topk(c, df):
    result = c.sql("SELECT * FROM df ORDER BY b LIMIT 10").compute()
    expected = df.nsmallest(10, "b").reset_index(drop=True)
    assert_eq(result, expected, check_dtype=False)
    result = c.sql("SELECT * FROM df ORDER BY b DESC LIMIT 10").compute()
    expected = df.nlargest(10, "b").reset_index(drop=True)
    assert_eq(result, expected, check_dtype=False)

def test_sort_by_alias(c, df):
    result = c.sql("SELECT b AS my_column FROM df ORDER BY my_column LIMIT 5").compute()
    expected = df.sort_values("b").head(5).reset_index(drop=True)[["b"]]
    expected.columns = ["my_column"]
    assert_eq(result, expected, check_dtype=False)

def test_sort_with_limit_multi_key(c, user_table_1):
    result = c.sql("SELECT * FROM user_table_1 ORDER BY b DESC, user_id LIMIT 2").compute()
    expected = user_table_1.sort_values(["b", "user_id"], ascending=[False, True]).head(2).reset_index(drop=True)
    assert_eq(result, expected, check_dtype=False)
