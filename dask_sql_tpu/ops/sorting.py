"""Sort and top-k kernels (parity: reference physical/utils/sort.py).

Multi-key mixed-order sort is a single device `lexsort` over transformed keys
(descending = negated/flipped key; NULL ordering = a leading validity key) —
no per-partition mergesort tricks needed (reference sort_partition_func,
utils/sort.py:90-117 there).  Top-k uses `jax.lax.top_k` on the dominant key
when eligible (reference topk_sort utils/sort.py:78).
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp

from ..columnar.column import Column
from ..columnar.dtypes import STRING_TYPES
from ..planner.expressions import SortKey


def sort_permutation(cols: Sequence[Column], ascendings: Sequence[bool],
                     nulls_firsts: Sequence[bool]) -> jnp.ndarray:
    """Stable permutation ordering rows by the given keys.

    Host-resident inputs (tiny post-aggregate tables, see
    CompiledAggregate.run) sort via np.lexsort — no device round trip for
    a handful of group rows."""
    import numpy as np

    if all(isinstance(c.data, np.ndarray) for c in cols):
        nkeys: List[np.ndarray] = []
        for col, asc, nf in zip(cols, ascendings, nulls_firsts):
            if col.sql_type in STRING_TYPES:
                col = col.compact_dictionary()
            data = np.asarray(col.data)
            if data.dtype == np.bool_:
                data = data.astype(np.int32)
            if data.dtype.kind == "f":
                data = np.where(np.isnan(data), np.inf, data)
            if not asc:
                data = -data
            if col.validity is not None:
                valid = np.asarray(col.validity)
                nkeys.append(np.where(valid, 1, 0) if nf
                             else np.where(valid, 0, 1))
                nkeys.append(data)
            else:
                nkeys.append(data)
        return np.lexsort(tuple(reversed(nkeys)))
    keys: List[jnp.ndarray] = []
    for col, asc, nf in zip(cols, ascendings, nulls_firsts):
        if col.sql_type in STRING_TYPES:
            col = col.compact_dictionary()  # sorted dict => code order == lex order
        data = col.data
        if data.dtype == jnp.bool_:
            data = data.astype(jnp.int32)
        if jnp.issubdtype(data.dtype, jnp.floating):
            # make NaN sort last consistently, then handle direction
            nan = jnp.isnan(data)
            data = jnp.where(nan, jnp.inf, data)
        if not asc:
            data = -data
        valid = col.valid_mask() if col.validity is not None else None
        if valid is not None:
            # null indicator outranks the value within this sort key;
            # nulls-first => invalid rows get 0 which sorts before valid 1
            nullkey = jnp.where(valid, 1, 0) if nf else jnp.where(valid, 0, 1)
            keys.append(nullkey)
            keys.append(data)
        else:
            keys.append(data)
    # lexsort: last key is primary
    return jnp.lexsort(tuple(reversed(keys)))


def sort_table(table, keys: Sequence[SortKey], eval_key):
    """Sort a Table by SortKeys. `eval_key(expr) -> Column`."""
    cols = [eval_key(k.expr) for k in keys]
    perm = sort_permutation(
        cols,
        [k.ascending for k in keys],
        [k.nulls_first_resolved() for k in keys],
    )
    return table.take(perm)


def topk_permutation(col: Column, ascending: bool, k: int,
                     exact_ties: bool = False) -> Optional[jnp.ndarray]:
    """Top-k on a single numeric/ordered key via lax.top_k; None if ineligible.

    With ``exact_ties=True`` (needed when secondary sort keys exist), returns
    None unless every row tied with the boundary value made it into the top-k
    — otherwise a truncation by the primary key alone could drop rows that
    secondary keys would have ranked into the final fetch window.
    """
    if col.sql_type in STRING_TYPES and col.dictionary is not None:
        col = col.compact_dictionary()
    data = col.data
    if data.dtype == jnp.bool_:
        data = data.astype(jnp.int32)
    if col.validity is not None:
        return None  # nulls need full ordering semantics
    vals = data.astype(jnp.float64) if not jnp.issubdtype(data.dtype, jnp.floating) else data
    if ascending:
        vals = -vals
    n = int(data.shape[0])
    k = min(k, n)
    _, idx = jax.lax.top_k(vals, k)
    if exact_ties and 0 < k < n:
        boundary = vals[idx[-1]]  # top_k sorts descending: last kept = worst
        if int((vals == boundary).sum()) != int((vals[idx] == boundary).sum()):
            return None
    return idx
