"""TPC-DS q1-q99 runner: every runnable query is VALUE-CHECKED against a
sqlite oracle (not just executed).

Parity: the reference's coverage yardstick (reference
tests/unit/test_queries.py:5-44 — 99 TPC-DS-style queries with a 38-query
XFAIL list; 61 expected passes on CPU) plus its oracle strategy (reference
tests/integration/test_postgres.py:13-53 value-checks against live engines).
Here 99 standard TPC-DS queries run against generated in-memory tables and
compare full result multisets with tests/ds_oracle (sqlite + dialect
translation); the xfail list below is the honest record of what the engine
cannot do yet, grouped by root cause.
"""
import pandas as pd
import pytest

from tests.ds_oracle import (
    assert_same_result,
    cross_check,
    duckdb_available,
    duckdb_query,
    make_duckdb,
    make_sqlite,
    strip_top_limit,
    translate,
)
from tests.tpcds import generate
from tests.tpcds_queries import QUERIES

# Root causes (round 3 state; re-rooted after the r3 fixes: GROUPING(),
# HAVING/ORDER BY select-alias resolution, empty-frame robustness, and the
# r2 engine work that had already cured the CTE-reuse class).  The three
# remaining shapes — EXISTS under OR (q10/q35) and a correlated scalar
# COUNT whose correlation predicate sits under OR (q41) — are xfailed by
# the REFERENCE too (reference tests/unit/test_queries.py:5-39).
#: round 5: q10/q35 decorrelate via MARK joins (EXISTS under OR becomes a
#: boolean matched column) and q41's hidden correlation factors out of its
#: disjunction — all three of the REFERENCE'S OWN xfails now pass here
XFAIL_QUERIES = {
}
# round 4: the former SLOW skips (q23/q24/q64) are gone — the optimizer now
# descends into subquery-embedded plans and the join reorderer flattens
# through CrossJoin and cast-wrapped join keys, so they run in seconds
SLOW_QUERIES = {}

#: queries with no faithful sqlite translation — shape-checked only
NO_ORACLE = {
    67: "sqlite parser stack overflow on the 9-level ROLLUP expansion",
}
#: division by zero: engine yields +-inf (pandas parity, like the
#: reference's dask/pandas execution); sqlite yields NULL
INF_IS_NULL = {90}


@pytest.fixture(scope="module")
def tpcds_tables():
    return generate(scale_rows=1000)


@pytest.fixture(scope="module")
def tpcds_context(tpcds_tables):
    from dask_sql_tpu import Context

    c = Context()
    for name, df in tpcds_tables.items():
        c.create_table(name, df)
    return c


@pytest.fixture(scope="module")
def sqlite_oracle(tpcds_tables):
    conn = make_sqlite(tpcds_tables)
    yield conn
    conn.close()


@pytest.fixture(scope="module")
def duckdb_oracle(tpcds_tables):
    """Second independent oracle; None when duckdb isn't installed (this
    image).  Fills the reference's postgres-in-docker role and covers the
    shapes sqlite can't parse (q67's 9-level ROLLUP)."""
    if not duckdb_available():
        yield None
        return
    conn = make_duckdb(tpcds_tables)
    yield conn
    conn.close()


def _params():
    for qnum in sorted(QUERIES):
        marks = []
        if qnum in SLOW_QUERIES:
            marks.append(pytest.mark.skip(reason=f"q{qnum}: {SLOW_QUERIES[qnum]}"))
        elif qnum in XFAIL_QUERIES:
            # declarative xfail: the query still RUNS, so a query that starts
            # passing surfaces as XPASS instead of silently going stale
            marks.append(pytest.mark.xfail(
                reason=f"q{qnum}: {XFAIL_QUERIES[qnum]}", strict=False))
        yield pytest.param(qnum, marks=marks)


@pytest.mark.parametrize("qnum", _params())
def test_query(tpcds_context, sqlite_oracle, duckdb_oracle, qnum):
    # 1. the original query (LIMIT/top-k path) must execute
    result = tpcds_context.sql(QUERIES[qnum]).compute()
    assert result is not None
    assert len(result.columns) > 0
    if qnum in NO_ORACLE and duckdb_oracle is None:
        return  # no engine that can parse this shape is available
    # 2. value check on the LIMIT-stripped variant: when ORDER BY keys tie
    # at the cut, engines legitimately keep different rows, so the
    # well-defined comparand is the full multiset
    sql = strip_top_limit(QUERIES[qnum])
    if sql != QUERIES[qnum].rstrip():
        result = tpcds_context.sql(sql).compute()
    oracles = []
    if qnum not in NO_ORACLE:
        tsql = translate(sql)
        assert tsql is not None, f"q{qnum}: translator declined"
        oracles.append(
            ("sqlite", lambda s: pd.read_sql_query(tsql, sqlite_oracle)))
    if duckdb_oracle is not None:
        oracles.append(
            ("duckdb", lambda s: duckdb_query(duckdb_oracle, s)))
    cross_check(result, oracles, sql, qnum, inf_is_null=qnum in INF_IS_NULL)
