"""Virtual-mesh scaling curve: Q1 + Q3 throughput at 1/2/4/8 devices.

VERDICT r4 #2: nothing measured multi-chip throughput (the dryrun is
correctness-only).  Real ICI scaling needs real chips, but the virtual CPU
mesh pins the *collectives' scaling shape* — how the distributed kernels'
cost grows with device count on fixed data — which is what the sharding
design controls.  Run:
    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python benchmarks/bench_mesh.py
Emits one JSON line per (query, n_devices).
"""
from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, ".")
sys.path.insert(0, "tests")

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"

N_ROWS = 100_000  # virtual devices emulate on one CPU: keep configs fast


def run_query(c, sql, reps=2):
    c.sql(sql).compute()  # warm
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        c.sql(sql).compute()
        times.append(time.perf_counter() - t0)
    return min(times)


def main():
    import jax
    from jax.sharding import Mesh

    import numpy as np

    from bench import QUERY as Q1_QUERY, gen_lineitem
    from tpch import QUERIES, generate

    from dask_sql_tpu import Context
    from dask_sql_tpu.parallel import mesh as mesh_mod

    devices = jax.devices()
    print(json.dumps({"status": "generating data"}), flush=True)
    q1_df = gen_lineitem(N_ROWS)
    q3_tables = generate(scale_rows=N_ROWS // 4)
    results = []
    max_dev = int(os.environ.get("MESH_MAX_DEV", "4"))
    # 8-way in-process CPU collectives intermittently miss the rendezvous
    # window under load (xla rendezvous.cc watchdog); 4 is stable and pins
    # the same shape.  MESH_MAX_DEV=8 opts in.
    for ndev in (1, 2, 4, 8):
        if ndev > max_dev:
            break
        if ndev > len(devices):
            break
        print(json.dumps({"status": f"measuring ndev={ndev}"}), flush=True)
        sub = np.array(devices[:ndev])
        mesh = Mesh(sub, (mesh_mod.AXIS,))
        prev = mesh_mod._default_mesh if hasattr(mesh_mod, "_default_mesh") else None
        mesh_mod.set_default_mesh(mesh)
        try:
            c = Context()
            # result cache off: measure execution, not serving-cache lookups
            c.config.update({"serving.cache.enabled": False})
            c.create_table("lineitem", q1_df, distributed=ndev > 1)
            t1 = run_query(c, Q1_QUERY)
            c2 = Context()
            for name, df in q3_tables.items():
                c2.create_table(name, df, distributed=(
                    ndev > 1 and name == "lineitem"))
            t3 = run_query(c2, QUERIES[3])
            n3 = len(q3_tables["lineitem"])
        finally:
            mesh_mod.set_default_mesh(prev)
        for metric, t, n in (("q1", t1, N_ROWS), ("q3", t3, n3)):
            line = {"metric": f"mesh_{metric}_rows_per_sec", "devices": ndev,
                    "value": round(n / t, 1), "unit": "rows/s",
                    "ms": round(t * 1000, 1)}
            results.append(line)
            print(json.dumps(line), flush=True)
    return results


if __name__ == "__main__":
    main()
