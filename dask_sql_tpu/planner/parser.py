"""SQL parser: tokens -> AST.

Role parity: reference `src/parser.rs` (`DaskParser::parse_sql`, parser.rs:400) —
standard SQL plus the dask dialect statements (`CREATE MODEL/EXPERIMENT`,
`PREDICT`, `EXPORT MODEL`, `SHOW ...`, `DESCRIBE MODEL`, `ANALYZE TABLE`,
`ALTER`, `USE SCHEMA`, `CREATE TABLE ... WITH(...)`, parser.rs:552-1350) and the
dialect conveniences of `src/dialect.rs` (`CEIL(x TO DAY)`, `FILTER (WHERE ...)`
aggregates, `TIMESTAMPADD`, ...).  Hand-written recursive descent with Pratt
expression parsing.
"""
from __future__ import annotations

import logging
from typing import Any, Dict, List, Optional, Tuple

from . import sqlast as a
from ..resilience.errors import ParseError
from .lexer import Token, TokenType, tokenize

logger = logging.getLogger(__name__)


class ParsingException(ParseError):
    """Parity: reference DFParsingException (src/error.rs).  Based on the
    resilience taxonomy (code PARSE_ERROR, USER_ERROR, never retryable) so
    the server emits a structured wire payload; still a ValueError through
    ParseError for historical callers."""


RESERVED_STOP = {
    "FROM", "WHERE", "GROUP", "HAVING", "ORDER", "LIMIT", "OFFSET", "UNION",
    "INTERSECT", "EXCEPT", "ON", "USING", "JOIN", "INNER", "LEFT", "RIGHT",
    "FULL", "CROSS", "AS", "AND", "OR", "NOT", "WHEN", "THEN", "ELSE", "END",
    "BY", "ASC", "DESC", "NULLS", "SELECT", "SEMI", "ANTI", "DISTRIBUTE",
    "WITH", "TABLESAMPLE", "FETCH", "WINDOW", "OUTER", "NATURAL", "FILTER",
    "OVER", "CASE", "BETWEEN", "IN", "LIKE", "ILIKE", "SIMILAR", "IS", "ESCAPE",
    "VALUES", "TO", "FOR",
}

_DATETIME_UNITS = {
    "YEAR", "QUARTER", "MONTH", "WEEK", "DAY", "DOW", "DOY", "HOUR", "MINUTE",
    "SECOND", "MILLISECOND", "MICROSECOND", "NANOSECOND", "EPOCH", "CENTURY",
    "DECADE", "MILLENNIUM", "ISODOW", "ISOYEAR",
}


class Parser:
    def __init__(self, sql: str):
        self.sql = sql
        self.tokens = self._tokenize(sql)
        self.pos = 0

    @staticmethod
    def _tokenize(sql: str):
        # native (C++) lexer when built, identical-contract Python fallback
        try:
            from .native_bridge import native_tokenize

            tokens = native_tokenize(sql)
            if tokens is not None:
                return tokens
        except Exception:  # dsql: allow-broad-except — fall back on any native issue
            pass
        return tokenize(sql)

    # -- token helpers ------------------------------------------------------
    def peek(self, offset: int = 0) -> Token:
        i = min(self.pos + offset, len(self.tokens) - 1)
        return self.tokens[i]

    def next(self) -> Token:
        tok = self.tokens[self.pos]
        if tok.type != TokenType.EOF:
            self.pos += 1
        return tok

    def error(self, msg: str) -> ParsingException:
        tok = self.peek()
        ctx = self.sql[max(0, tok.pos - 30) : tok.pos + 30]
        return ParsingException(f"{msg} at position {tok.pos} (near {ctx!r})")

    def at_keyword(self, *kws: str) -> bool:
        tok = self.peek()
        return tok.type == TokenType.IDENT and tok.upper in kws

    def accept_keyword(self, *kws: str) -> bool:
        if self.at_keyword(*kws):
            self.next()
            return True
        return False

    def expect_keyword(self, kw: str) -> None:
        if not self.accept_keyword(kw):
            raise self.error(f"Expected {kw}")

    def accept(self, value: str) -> bool:
        tok = self.peek()
        if tok.type in (TokenType.OP, TokenType.PUNCT) and tok.value == value:
            self.next()
            return True
        return False

    def expect(self, value: str) -> None:
        if not self.accept(value):
            raise self.error(f"Expected {value!r}")

    def parse_identifier(self) -> str:
        tok = self.peek()
        if tok.type == TokenType.QUOTED_IDENT:
            self.next()
            return tok.value
        if tok.type == TokenType.IDENT:
            self.next()
            return tok.value
        raise self.error("Expected identifier")

    def parse_qualified_name(self) -> List[str]:
        parts = [self.parse_identifier()]
        while self.accept("."):
            parts.append(self.parse_identifier())
        return parts

    # -- statements ---------------------------------------------------------
    def parse_statements(self) -> List[a.Statement]:
        stmts = []
        while self.peek().type != TokenType.EOF:
            stmts.append(self.parse_statement())
            while self.accept(";"):
                pass
        return stmts

    def parse_statement(self) -> a.Statement:
        if self.at_keyword("SELECT", "WITH", "VALUES") or self.peek().value == "(":
            return a.QueryStatement(self.parse_query())
        if self.at_keyword("EXPLAIN"):
            self.next()
            analyze = self.accept_keyword("ANALYZE")
            lint = False if analyze else self.accept_keyword("LINT")
            estimate = False if (analyze or lint) \
                else self.accept_keyword("ESTIMATE")
            fmt_json = False
            if self.accept_keyword("FORMAT"):
                self.expect_keyword("JSON")
                if not analyze:
                    # reject now rather than silently return text a JSON
                    # client would choke on: only ANALYZE produces the
                    # Chrome-trace payload
                    raise self.error("FORMAT JSON requires EXPLAIN ANALYZE")
                fmt_json = True
            self.accept_keyword("VERBOSE")
            return a.ExplainStatement(self.parse_query(), analyze, lint,
                                      estimate, fmt_json)
        if self.at_keyword("CREATE"):
            return self.parse_create()
        if self.at_keyword("DROP"):
            return self.parse_drop()
        if self.at_keyword("SHOW"):
            return self.parse_show()
        if self.at_keyword("DESCRIBE", "DESC"):
            self.next()
            if self.accept_keyword("MODEL"):
                return a.DescribeModel(self.parse_qualified_name())
            return a.ShowColumns(self.parse_qualified_name())
        if self.at_keyword("ANALYZE"):
            self.next()
            self.expect_keyword("TABLE")
            table = self.parse_qualified_name()
            self.expect_keyword("COMPUTE")
            self.expect_keyword("STATISTICS")
            cols: List[str] = []
            if self.accept_keyword("FOR"):
                if self.accept_keyword("ALL"):
                    self.expect_keyword("COLUMNS")
                else:
                    self.expect_keyword("COLUMNS")
                    cols.append(self.parse_identifier())
                    while self.accept(","):
                        cols.append(self.parse_identifier())
            return a.AnalyzeTable(table, cols)
        if self.at_keyword("USE"):
            self.next()
            self.expect_keyword("SCHEMA")
            return a.UseSchema(self.parse_identifier())
        if self.at_keyword("ALTER"):
            return self.parse_alter()
        if self.at_keyword("CANCEL"):
            self.next()
            self.expect_keyword("QUERY")
            # the qid is a string literal ('uuid'); a bare identifier is
            # accepted too so copy-pasting an unquoted qid still works
            return a.CancelQuery(self.next().value)
        if self.at_keyword("EXPORT"):
            self.next()
            self.expect_keyword("MODEL")
            name = self.parse_qualified_name()
            self.expect_keyword("WITH")
            kwargs = self.parse_kwargs()
            return a.ExportModel(name, kwargs)
        if self.at_keyword("INSERT"):
            self.next()
            self.expect_keyword("INTO")
            name = self.parse_qualified_name()
            # the body is any query: VALUES (...), (...) or a full SELECT
            return a.InsertInto(name, self.parse_query())
        raise self.error("Unsupported statement")

    def parse_create(self) -> a.Statement:
        self.expect_keyword("CREATE")
        or_replace = False
        if self.accept_keyword("OR"):
            self.expect_keyword("REPLACE")
            or_replace = True
        if self.accept_keyword("SCHEMA"):
            ine = self._if_not_exists()
            return a.CreateSchema(self.parse_identifier(), ine, or_replace)
        if self.accept_keyword("MODEL"):
            ine = self._if_not_exists()
            name = self.parse_qualified_name()
            self.expect_keyword("WITH")
            kwargs = self.parse_kwargs()
            self.expect_keyword("AS")
            self.accept("(")
            query = self.parse_query()
            self.accept(")")
            return a.CreateModel(name, kwargs, query, ine, or_replace)
        if self.accept_keyword("EXPERIMENT"):
            ine = self._if_not_exists()
            name = self.parse_qualified_name()
            self.expect_keyword("WITH")
            kwargs = self.parse_kwargs()
            self.expect_keyword("AS")
            self.accept("(")
            query = self.parse_query()
            self.accept(")")
            return a.CreateExperiment(name, kwargs, query, ine, or_replace)
        is_view = self.accept_keyword("VIEW")
        if not is_view:
            self.expect_keyword("TABLE")
        ine = self._if_not_exists()
        name = self.parse_qualified_name()
        if self.accept_keyword("WITH"):
            kwargs = self.parse_kwargs()
            return a.CreateTableWith(name, kwargs, ine, or_replace)
        if self.accept_keyword("AS"):
            self.accept("(")
            query = self.parse_query()
            self.accept(")")
            return a.CreateTableAs(name, query, persist=not is_view,
                                   if_not_exists=ine, or_replace=or_replace)
        raise self.error("Expected WITH (...) or AS (...) in CREATE TABLE")

    def _if_not_exists(self) -> bool:
        if self.accept_keyword("IF"):
            self.expect_keyword("NOT")
            self.expect_keyword("EXISTS")
            return True
        return False

    def parse_drop(self) -> a.Statement:
        self.expect_keyword("DROP")
        if self.accept_keyword("SCHEMA"):
            ie = self._if_exists()
            return a.DropSchema(self.parse_identifier(), ie)
        if self.accept_keyword("MODEL"):
            ie = self._if_exists()
            return a.DropModel(self.parse_qualified_name(), ie)
        if self.accept_keyword("TABLE") or self.accept_keyword("VIEW"):
            ie = self._if_exists()
            return a.DropTable(self.parse_qualified_name(), ie)
        raise self.error("Expected TABLE, VIEW, SCHEMA or MODEL after DROP")

    def _if_exists(self) -> bool:
        if self.accept_keyword("IF"):
            self.expect_keyword("EXISTS")
            return True
        return False

    def parse_show(self) -> a.Statement:
        self.expect_keyword("SHOW")
        if self.accept_keyword("SCHEMAS"):
            like = None
            if self.accept_keyword("LIKE"):
                like = self.next().value
            return a.ShowSchemas(like)
        if self.accept_keyword("TABLES"):
            schema = None
            if self.accept_keyword("FROM") or self.accept_keyword("IN"):
                schema = self.parse_identifier()
            return a.ShowTables(schema)
        if self.accept_keyword("COLUMNS"):
            self.expect_keyword("FROM")
            return a.ShowColumns(self.parse_qualified_name())
        if self.accept_keyword("MODELS"):
            schema = None
            if self.accept_keyword("FROM") or self.accept_keyword("IN"):
                schema = self.parse_identifier()
            return a.ShowModels(schema)
        if self.accept_keyword("METRICS"):
            like = None
            if self.accept_keyword("LIKE"):
                like = self.next().value
            return a.ShowMetrics(like)
        if self.accept_keyword("PROFILES"):
            like = None
            if self.accept_keyword("LIKE"):
                like = self.next().value
            return a.ShowProfiles(like)
        if self.accept_keyword("QUERIES"):
            like = None
            if self.accept_keyword("LIKE"):
                like = self.next().value
            return a.ShowQueries(like)
        if self.accept_keyword("MATERIALIZED"):
            like = None
            if self.accept_keyword("LIKE"):
                like = self.next().value
            return a.ShowMaterialized(like)
        if self.accept_keyword("REPLICAS"):
            like = None
            if self.accept_keyword("LIKE"):
                like = self.next().value
            return a.ShowReplicas(like)
        raise self.error(
            "Expected SCHEMAS, TABLES, COLUMNS, MODELS, METRICS, PROFILES, "
            "QUERIES, MATERIALIZED or REPLICAS after SHOW")

    def parse_alter(self) -> a.Statement:
        self.expect_keyword("ALTER")
        if self.accept_keyword("SCHEMA"):
            old = self.parse_identifier()
            self.expect_keyword("RENAME")
            self.expect_keyword("TO")
            return a.AlterSchema(old, self.parse_identifier())
        self.expect_keyword("TABLE")
        ie = self._if_exists()
        old = self.parse_qualified_name()
        self.expect_keyword("RENAME")
        self.expect_keyword("TO")
        return a.AlterTable(old, self.parse_identifier(), ie)

    def parse_kwargs(self) -> Dict[str, Any]:
        """WITH ( key = value, ... ) — values: literal, ident, list, nested map."""
        self.expect("(")
        kwargs: Dict[str, Any] = {}
        if not self.accept(")"):
            while True:
                key = self.parse_identifier()
                self.expect("=")
                kwargs[key] = self.parse_kwarg_value()
                if not self.accept(","):
                    break
            self.expect(")")
        return kwargs

    def parse_kwarg_value(self):
        tok = self.peek()
        if tok.type == TokenType.STRING:
            self.next()
            return tok.value
        if tok.type == TokenType.NUMBER:
            self.next()
            return _parse_number(tok.value)
        if self.accept("("):  # nested map or list
            if self.peek(1).value == "=" and self.peek().type in (TokenType.IDENT, TokenType.QUOTED_IDENT):
                self.pos -= 1
                return self.parse_kwargs()
            items = []
            if not self.accept(")"):
                while True:
                    items.append(self.parse_kwarg_value())
                    if not self.accept(","):
                        break
                self.expect(")")
            return items
        if self.accept("["):
            items = []
            if not self.accept("]"):
                while True:
                    items.append(self.parse_kwarg_value())
                    if not self.accept(","):
                        break
                self.expect("]")
            return items
        if tok.type == TokenType.IDENT:
            self.next()
            up = tok.upper
            if up == "TRUE":
                return True
            if up == "FALSE":
                return False
            if up == "NULL":
                return None
            return tok.value
        raise self.error("Expected kwarg value")

    # -- queries ------------------------------------------------------------
    def parse_query(self) -> a.Select:
        ctes: List[Tuple[str, a.Select]] = []
        if self.accept_keyword("WITH"):
            while True:
                name = self.parse_identifier()
                self.expect_keyword("AS")
                self.expect("(")
                sub = self.parse_query()
                self.expect(")")
                ctes.append((name, sub))
                if not self.accept(","):
                    break
        query = self.parse_set_expr()
        query.ctes = ctes + query.ctes
        # trailing ORDER BY / LIMIT apply to the whole set expression
        if self.accept_keyword("ORDER"):
            self.expect_keyword("BY")
            query.order_by = self.parse_order_items()
        if self.accept_keyword("LIMIT"):
            tok = self.next()
            if tok.upper == "ALL":
                pass
            else:
                query.limit = int(_parse_number(tok.value))
        if self.accept_keyword("OFFSET"):
            query.offset = int(_parse_number(self.next().value))
            self.accept_keyword("ROW") or self.accept_keyword("ROWS")
        if self.accept_keyword("FETCH"):
            self.accept_keyword("FIRST") or self.accept_keyword("NEXT")
            query.limit = int(_parse_number(self.next().value))
            self.accept_keyword("ROW") or self.accept_keyword("ROWS")
            self.expect_keyword("ONLY")
        return query

    def parse_set_expr(self) -> a.Select:
        left = self.parse_select_core()
        while self.at_keyword("UNION", "INTERSECT", "EXCEPT"):
            op = self.next().upper
            all_ = self.accept_keyword("ALL")
            if not all_:
                self.accept_keyword("DISTINCT")
            right = self.parse_select_core()
            if left.set_op is not None:
                # chain: wrap the existing (A op B) as a derived table
                prev = left
                left = a.Select(projections=[a.SelectItem(a.Wildcard())],
                                from_=a.DerivedTable(prev, alias=None))
            left.set_op = (op, all_, right)
        return left

    def parse_select_core(self) -> a.Select:
        if self.accept("("):
            q = self.parse_query()
            self.expect(")")
            return q
        sel = a.Select()
        if self.accept_keyword("VALUES"):
            rows = []
            while True:
                self.expect("(")
                row = [self.parse_expr()]
                while self.accept(","):
                    row.append(self.parse_expr())
                self.expect(")")
                rows.append(row)
                if not self.accept(","):
                    break
            sel.values = rows
            return sel
        self.expect_keyword("SELECT")
        if self.accept_keyword("DISTINCT"):
            sel.distinct = True
        else:
            self.accept_keyword("ALL")
        sel.projections = self.parse_projections()
        if self.accept_keyword("FROM"):
            sel.from_ = self.parse_table_ref()
        if self.accept_keyword("WHERE"):
            sel.where = self.parse_expr()
        if self.at_keyword("GROUP"):
            self.next()
            self.expect_keyword("BY")
            sel.group_by = [self._parse_group_item()]
            while self.accept(","):
                sel.group_by.append(self._parse_group_item())
        if self.accept_keyword("HAVING"):
            sel.having = self.parse_expr()
        if self.at_keyword("WINDOW") and self.peek(1).type in (
                TokenType.IDENT, TokenType.QUOTED_IDENT) \
                and self.peek(2).upper == "AS":
            self.next()
            while True:
                wname = self.parse_identifier()
                self.expect_keyword("AS")
                sel.named_windows[wname] = self._parse_window_spec()
                if not self.accept(","):
                    break
        if self.at_keyword("DISTRIBUTE"):
            self.next()
            self.expect_keyword("BY")
            sel.distribute_by = [self.parse_expr()]
            while self.accept(","):
                sel.distribute_by.append(self.parse_expr())
        return sel

    def _parse_group_item(self) -> a.Expr:
        if self.at_keyword("GROUPING") and self.peek(1).upper == "SETS":
            self.next()
            self.next()
            self.expect("(")
            sets = []
            while True:
                if self.accept("("):
                    items = []
                    if not self.accept(")"):
                        items.append(self.parse_expr())
                        while self.accept(","):
                            items.append(self.parse_expr())
                        self.expect(")")
                    sets.append(items)
                else:
                    sets.append([self.parse_expr()])
                if not self.accept(","):
                    break
            self.expect(")")
            return a.GroupingSets(sets)
        if self.at_keyword("ROLLUP") and self.peek(1).value == "(":
            self.next()
            self.expect("(")
            exprs = [self.parse_expr()]
            while self.accept(","):
                exprs.append(self.parse_expr())
            self.expect(")")
            return a.Rollup(exprs)
        if self.at_keyword("CUBE") and self.peek(1).value == "(":
            self.next()
            self.expect("(")
            exprs = [self.parse_expr()]
            while self.accept(","):
                exprs.append(self.parse_expr())
            self.expect(")")
            return a.Cube(exprs)
        return self.parse_expr()

    def parse_projections(self) -> List[a.SelectItem]:
        items = [self.parse_select_item()]
        while self.accept(","):
            items.append(self.parse_select_item())
        return items

    def parse_select_item(self) -> a.SelectItem:
        expr = self.parse_expr()
        alias = None
        if self.accept_keyword("AS"):
            alias = self.parse_identifier()
        elif self.peek().type in (TokenType.IDENT, TokenType.QUOTED_IDENT) and self.peek().upper not in RESERVED_STOP:
            alias = self.parse_identifier()
        return a.SelectItem(expr, alias)

    def parse_order_items(self) -> List[a.OrderItem]:
        items = [self.parse_order_item()]
        while self.accept(","):
            items.append(self.parse_order_item())
        return items

    def parse_order_item(self) -> a.OrderItem:
        expr = self.parse_expr()
        asc = True
        if self.accept_keyword("ASC"):
            asc = True
        elif self.accept_keyword("DESC"):
            asc = False
        nulls_first = None
        if self.accept_keyword("NULLS"):
            if self.accept_keyword("FIRST"):
                nulls_first = True
            else:
                self.expect_keyword("LAST")
                nulls_first = False
        return a.OrderItem(expr, asc, nulls_first)

    # -- FROM clause --------------------------------------------------------
    def parse_table_ref(self) -> a.TableRef:
        left = self.parse_table_factor()
        while True:
            natural = self.accept_keyword("NATURAL")
            if self.accept_keyword("CROSS"):
                self.expect_keyword("JOIN")
                right = self.parse_table_factor()
                left = a.Join(left, right, "CROSS")
                continue
            join_type = None
            if self.accept_keyword("INNER"):
                join_type = "INNER"
            elif self.at_keyword("LEFT", "RIGHT", "FULL"):
                jt = self.next().upper
                if jt == "LEFT" and self.accept_keyword("SEMI"):
                    join_type = "LEFTSEMI"
                elif jt == "LEFT" and self.accept_keyword("ANTI"):
                    join_type = "LEFTANTI"
                else:
                    self.accept_keyword("OUTER")
                    join_type = jt
            elif self.at_keyword("JOIN"):
                join_type = "INNER"
            if join_type is None:
                if self.accept(","):
                    right = self.parse_table_factor()
                    left = a.Join(left, right, "CROSS")
                    continue
                break
            self.expect_keyword("JOIN")
            right = self.parse_table_factor()
            condition, using = None, None
            if self.accept_keyword("ON"):
                condition = self.parse_expr()
            elif self.accept_keyword("USING"):
                self.expect("(")
                using = [self.parse_identifier()]
                while self.accept(","):
                    using.append(self.parse_identifier())
                self.expect(")")
            elif natural:
                using = []  # natural join: resolved in binder
            left = a.Join(left, right, join_type, condition, using)
        return left

    def parse_table_factor(self) -> a.TableRef:
        if self.accept("("):
            inner = self.parse_query() if self.at_keyword("SELECT", "WITH", "VALUES") or self.peek().value == "(" else None
            if inner is None:
                ref = self.parse_table_ref()
                self.expect(")")
                return ref
            self.expect(")")
            alias = self._parse_table_alias()
            return a.DerivedTable(inner, alias)
        if self.at_keyword("PREDICT") and self.peek(1).value == "(":
            self.next()
            self.expect("(")
            self.expect_keyword("MODEL")
            model = self.parse_qualified_name()
            self.expect(",")
            query = self.parse_query()
            self.expect(")")
            alias = self._parse_table_alias()
            return a.TableFunction("PREDICT", model, query, alias)
        parts = self.parse_qualified_name()
        sample = None
        if self.accept_keyword("TABLESAMPLE"):
            method = "BERNOULLI"
            if self.accept_keyword("SYSTEM"):
                method = "SYSTEM"
            elif self.accept_keyword("BERNOULLI"):
                method = "BERNOULLI"
            self.expect("(")
            frac = float(_parse_number(self.next().value))
            self.expect(")")
            seed = None
            if self.accept_keyword("REPEATABLE"):
                self.expect("(")
                seed = int(_parse_number(self.next().value))
                self.expect(")")
            sample = (method, frac, seed)
        alias = self._parse_table_alias()
        return a.NamedTable(parts, alias, sample)

    def _parse_table_alias(self) -> Optional[str]:
        if self.accept_keyword("AS"):
            alias = self.parse_identifier()
        elif self.peek().type in (TokenType.IDENT, TokenType.QUOTED_IDENT) and self.peek().upper not in RESERVED_STOP:
            alias = self.parse_identifier()
        else:
            return None
        if self.accept("("):  # column aliases: t(a, b) — consumed, applied in binder
            cols = [self.parse_identifier()]
            while self.accept(","):
                cols.append(self.parse_identifier())
            self.expect(")")
            return (alias, cols)  # type: ignore[return-value]
        return alias

    # -- expressions (Pratt) ------------------------------------------------
    def parse_expr(self) -> a.Expr:
        return self.parse_or()

    def parse_or(self) -> a.Expr:
        left = self.parse_and()
        while self.accept_keyword("OR"):
            left = a.BinaryOp("OR", left, self.parse_and())
        return left

    def parse_and(self) -> a.Expr:
        left = self.parse_not()
        while self.accept_keyword("AND"):
            left = a.BinaryOp("AND", left, self.parse_not())
        return left

    def parse_not(self) -> a.Expr:
        if self.accept_keyword("NOT"):
            return a.UnaryOp("NOT", self.parse_not())
        return self.parse_predicate()

    def parse_predicate(self) -> a.Expr:
        left = self.parse_comparison()
        while True:
            negated = False
            save = self.pos
            if self.accept_keyword("NOT"):
                negated = True
            if self.accept_keyword("BETWEEN"):
                symmetric = self.accept_keyword("SYMMETRIC")
                low = self.parse_comparison()
                self.expect_keyword("AND")
                high = self.parse_comparison()
                left = a.Between(left, low, high, negated, symmetric)
                continue
            if self.accept_keyword("IN"):
                self.expect("(")
                if self.at_keyword("SELECT", "WITH"):
                    sub = self.parse_query()
                    self.expect(")")
                    left = a.InSubquery(left, sub, negated)
                else:
                    items = [self.parse_expr()]
                    while self.accept(","):
                        items.append(self.parse_expr())
                    self.expect(")")
                    left = a.InList(left, items, negated)
                continue
            if self.at_keyword("LIKE", "ILIKE"):
                ci = self.next().upper == "ILIKE"
                pattern = self.parse_comparison()
                escape = None
                if self.accept_keyword("ESCAPE"):
                    escape = self.next().value
                left = a.Like(left, pattern, negated, ci, False, escape)
                continue
            if self.accept_keyword("SIMILAR"):
                self.expect_keyword("TO")
                pattern = self.parse_comparison()
                escape = None
                if self.accept_keyword("ESCAPE"):
                    escape = self.next().value
                left = a.Like(left, pattern, negated, False, True, escape)
                continue
            if negated:
                self.pos = save
                break
            if self.accept_keyword("IS"):
                neg = self.accept_keyword("NOT")
                if self.accept_keyword("NULL"):
                    left = a.IsNull(left, neg)
                elif self.accept_keyword("TRUE"):
                    left = a.IsBool(left, True, neg)
                elif self.accept_keyword("FALSE"):
                    left = a.IsBool(left, False, neg)
                elif self.accept_keyword("UNKNOWN"):
                    left = a.IsNull(left, neg)
                elif self.accept_keyword("DISTINCT"):
                    self.expect_keyword("FROM")
                    right = self.parse_comparison()
                    left = a.IsDistinctFrom(left, right, neg)
                else:
                    raise self.error("Expected NULL/TRUE/FALSE/DISTINCT FROM after IS")
                continue
            break
        return left

    def parse_comparison(self) -> a.Expr:
        left = self.parse_additive()
        tok = self.peek()
        if tok.type == TokenType.OP and tok.value in ("=", "<>", "!=", "<", "<=", ">", ">="):
            op = self.next().value
            if op == "!=":
                op = "<>"
            # ANY/ALL subquery comparison
            if self.at_keyword("ANY", "SOME", "ALL"):
                quant = self.next().upper
                self.expect("(")
                sub = self.parse_query()
                self.expect(")")
                if op == "=" and quant in ("ANY", "SOME"):
                    return a.InSubquery(left, sub, False)
                if op == "<>" and quant == "ALL":
                    return a.InSubquery(left, sub, True)
                raise self.error(f"Unsupported quantified comparison {op} {quant}")
            right = self.parse_additive()
            return a.BinaryOp(op, left, right)
        return left

    def parse_additive(self) -> a.Expr:
        left = self.parse_multiplicative()
        while True:
            tok = self.peek()
            if tok.type == TokenType.OP and tok.value in ("+", "-", "||"):
                op = self.next().value
                left = a.BinaryOp(op, left, self.parse_multiplicative())
            else:
                break
        return left

    def parse_multiplicative(self) -> a.Expr:
        left = self.parse_unary()
        while True:
            tok = self.peek()
            if tok.type == TokenType.OP and tok.value in ("*", "/", "%"):
                op = self.next().value
                left = a.BinaryOp(op, left, self.parse_unary())
            else:
                break
        return left

    def parse_unary(self) -> a.Expr:
        tok = self.peek()
        if tok.type == TokenType.OP and tok.value in ("-", "+"):
            self.next()
            operand = self.parse_unary()
            if tok.value == "-":
                if isinstance(operand, a.Literal) and isinstance(operand.value, (int, float)):
                    return a.Literal(-operand.value)
                return a.UnaryOp("-", operand)
            return operand
        return self.parse_postfix()

    def parse_postfix(self) -> a.Expr:
        expr = self.parse_primary()
        while True:
            if self.accept("::"):
                type_name = self._parse_type_name()
                expr = a.Cast(expr, type_name)
                continue
            break
        return expr

    def _parse_type_name(self) -> str:
        name = self.parse_identifier().upper()
        # multi-word types
        while self.peek().type == TokenType.IDENT and self.peek().upper in (
            "PRECISION", "VARYING", "WITHOUT", "WITH", "TIME", "ZONE", "LOCAL",
        ):
            name += " " + self.next().upper
        if self.accept("("):
            args = [self.next().value]
            while self.accept(","):
                args.append(self.next().value)
            self.expect(")")
            name += f"({','.join(args)})"
        return name

    # -- primary expressions -------------------------------------------------
    def parse_primary(self) -> a.Expr:
        tok = self.peek()
        if tok.type == TokenType.NUMBER:
            self.next()
            return a.Literal(_parse_number(tok.value))
        if tok.type == TokenType.STRING:
            self.next()
            return a.Literal(tok.value)
        if tok.type == TokenType.PARAM:
            self.next()
            return a.Literal(None)
        if tok.value == "(":
            self.next()
            if self.at_keyword("SELECT", "WITH"):
                sub = self.parse_query()
                self.expect(")")
                return a.ScalarSubquery(sub)
            expr = self.parse_expr()
            if self.accept(","):  # row constructor — treat as function ROW
                items = [expr, self.parse_expr()]
                while self.accept(","):
                    items.append(self.parse_expr())
                self.expect(")")
                return a.FunctionCall("ROW", items)
            self.expect(")")
            return expr
        if tok.value == "*":
            self.next()
            return a.Wildcard()
        if tok.type == TokenType.QUOTED_IDENT:
            return self._parse_identifier_chain()
        if tok.type != TokenType.IDENT:
            raise self.error("Expected expression")
        up = tok.upper
        # keyword literals & special forms
        if up == "NULL":
            self.next()
            return a.Literal(None)
        if up == "TRUE":
            self.next()
            return a.Literal(True)
        if up == "FALSE":
            self.next()
            return a.Literal(False)
        if up in ("DATE", "TIMESTAMP", "TIME") and self.peek(1).type == TokenType.STRING:
            self.next()
            val = self.next().value
            return a.Literal(val, type_name=up)
        if up == "INTERVAL":
            self.next()
            neg = self.accept("-")
            val_tok = self.next()
            value = val_tok.value
            unit = "SECOND"
            if self.peek().type == TokenType.IDENT and self.peek().upper.rstrip("S") in _DATETIME_UNITS:
                unit = self.next().upper.rstrip("S")
                if self.accept_keyword("TO"):
                    unit += " TO " + self.next().upper.rstrip("S")
            return a.IntervalLiteral(("-" if neg else "") + value, unit)
        if up == "CASE":
            return self._parse_case()
        if up == "CAST" or up == "TRY_CAST":
            self.next()
            self.expect("(")
            operand = self.parse_expr()
            self.expect_keyword("AS")
            type_name = self._parse_type_name()
            self.expect(")")
            return a.Cast(operand, type_name, safe=(up == "TRY_CAST"))
        if up == "EXTRACT":
            self.next()
            self.expect("(")
            unit = self.next().upper if self.peek().type == TokenType.IDENT else self.next().value.upper()
            self.expect_keyword("FROM")
            operand = self.parse_expr()
            self.expect(")")
            return a.Extract(unit, operand)
        if up == "SUBSTRING" and self.peek(1).value == "(":
            self.next()
            self.expect("(")
            operand = self.parse_expr()
            start, length = None, None
            if self.accept_keyword("FROM"):
                start = self.parse_expr()
                if self.accept_keyword("FOR"):
                    length = self.parse_expr()
            elif self.accept(","):
                start = self.parse_expr()
                if self.accept(","):
                    length = self.parse_expr()
            self.expect(")")
            return a.Substring(operand, start, length)
        if up == "TRIM" and self.peek(1).value == "(":
            self.next()
            self.expect("(")
            where = "BOTH"
            if self.at_keyword("LEADING", "TRAILING", "BOTH"):
                where = self.next().upper
            chars = None
            if self.peek().type == TokenType.STRING:
                chars = a.Literal(self.next().value)
                if self.accept_keyword("FROM"):
                    operand = self.parse_expr()
                else:
                    operand, chars = chars, None
            elif self.accept_keyword("FROM"):
                operand = self.parse_expr()
            else:
                operand = self.parse_expr()
                if self.accept_keyword("FROM"):
                    chars, operand = operand, self.parse_expr()
            self.expect(")")
            return a.Trim(operand, where, chars)
        if up == "POSITION" and self.peek(1).value == "(":
            self.next()
            self.expect("(")
            needle = self.parse_additive()  # stop before IN (it's the separator here)
            self.expect_keyword("IN")
            haystack = self.parse_expr()
            self.expect(")")
            return a.Position(needle, haystack)
        if up == "OVERLAY" and self.peek(1).value == "(":
            self.next()
            self.expect("(")
            operand = self.parse_expr()
            self.expect_keyword("PLACING")
            repl = self.parse_expr()
            self.expect_keyword("FROM")
            start = self.parse_expr()
            length = None
            if self.accept_keyword("FOR"):
                length = self.parse_expr()
            self.expect(")")
            return a.Overlay(operand, repl, start, length)
        if up in ("CEIL", "CEILING", "FLOOR") and self.peek(1).value == "(":
            # possible CEIL(x TO DAY) form (reference dialect.rs:48)
            save = self.pos
            self.next()
            self.expect("(")
            operand = self.parse_expr()
            if self.accept_keyword("TO"):
                unit = self.next().upper
                self.expect(")")
                return a.CeilFloorTo("CEIL" if up != "FLOOR" else "FLOOR", operand, unit)
            self.expect(")")
            return a.FunctionCall("CEIL" if up != "FLOOR" else "FLOOR", [operand])
        if up in ("TIMESTAMPADD", "TIMESTAMPDIFF", "DATEDIFF") and self.peek(1).value == "(":
            # first argument is a bare datetime-unit keyword
            self.next()
            self.expect("(")
            unit_tok = self.next()
            unit = unit_tok.value if unit_tok.type == TokenType.STRING else unit_tok.upper
            self.expect(",")
            args = [a.Literal(unit), self.parse_expr()]
            self.expect(",")
            args.append(self.parse_expr())
            self.expect(")")
            return a.FunctionCall(up, args)
        if up == "EXISTS" and self.peek(1).value == "(":
            self.next()
            self.expect("(")
            sub = self.parse_query()
            self.expect(")")
            return a.Exists(sub)
        if self.peek(1).value == "(":
            return self._parse_function_call()
        return self._parse_identifier_chain()

    def _parse_identifier_chain(self) -> a.Expr:
        parts = [self.parse_identifier()]
        quoted = [self.tokens[self.pos - 1].type == TokenType.QUOTED_IDENT]
        while self.accept("."):
            if self.peek().value == "*":
                self.next()
                return a.Wildcard(qualifier=parts)
            parts.append(self.parse_identifier())
            quoted.append(self.tokens[self.pos - 1].type == TokenType.QUOTED_IDENT)
        return a.Identifier(parts, quoted)

    def _parse_case(self) -> a.Expr:
        self.expect_keyword("CASE")
        operand = None
        if not self.at_keyword("WHEN"):
            operand = self.parse_expr()
        whens = []
        while self.accept_keyword("WHEN"):
            cond = self.parse_expr()
            self.expect_keyword("THEN")
            result = self.parse_expr()
            whens.append((cond, result))
        else_ = None
        if self.accept_keyword("ELSE"):
            else_ = self.parse_expr()
        self.expect_keyword("END")
        return a.Case(operand, whens, else_)

    def _parse_function_call(self) -> a.Expr:
        name = self.parse_identifier()
        self.expect("(")
        distinct = False
        args: List[a.Expr] = []
        if not self.accept(")"):
            if self.accept_keyword("DISTINCT"):
                distinct = True
            else:
                self.accept_keyword("ALL")
            if self.peek().value == "*":
                self.next()
                args.append(a.Wildcard())
            else:
                args.append(self.parse_expr())
            while self.accept(","):
                args.append(self.parse_expr())
            self.expect(")")
        ignore_nulls = False
        if self.accept_keyword("IGNORE"):
            self.expect_keyword("NULLS")
            ignore_nulls = True
        elif self.accept_keyword("RESPECT"):
            self.expect_keyword("NULLS")
        if self.at_keyword("WITHIN"):
            # PERCENTILE_CONT(q) WITHIN GROUP (ORDER BY x) — rewrite to (x, q)
            self.next()
            self.expect_keyword("GROUP")
            self.expect("(")
            self.expect_keyword("ORDER")
            self.expect_keyword("BY")
            order_expr = self.parse_expr()
            desc = False
            if self.accept_keyword("DESC"):
                desc = True
            else:
                self.accept_keyword("ASC")
            self.expect(")")
            if args and isinstance(args[0], a.Literal) and isinstance(args[0].value, (int, float)):
                q = args[0].value
                if desc:
                    q = 1.0 - float(q)
                args = [order_expr, a.Literal(float(q))]
            else:
                raise ParsingException(
                    "WITHIN GROUP requires a numeric literal fraction, e.g. "
                    "PERCENTILE_CONT(0.5) WITHIN GROUP (ORDER BY x)")
        filter_expr = None
        if self.at_keyword("FILTER") and self.peek(1).value == "(":
            self.next()
            self.expect("(")
            self.expect_keyword("WHERE")
            filter_expr = self.parse_expr()
            self.expect(")")
        over = None
        if self.accept_keyword("OVER"):
            if self.peek().value == "(":
                over = self._parse_window_spec()
            else:
                over = self.parse_identifier()  # named window, resolved in binder
        return a.FunctionCall(name.upper(), args, distinct, filter_expr, over, ignore_nulls)

    def _parse_window_spec(self) -> a.WindowSpec:
        self.expect("(")
        spec = a.WindowSpec()
        if self.accept_keyword("PARTITION"):
            self.expect_keyword("BY")
            spec.partition_by.append(self.parse_expr())
            while self.accept(","):
                spec.partition_by.append(self.parse_expr())
        if self.accept_keyword("ORDER"):
            self.expect_keyword("BY")
            spec.order_by = self.parse_order_items()
        if self.at_keyword("ROWS", "RANGE"):
            units = self.next().upper
            if self.accept_keyword("BETWEEN"):
                start = self._parse_frame_bound()
                self.expect_keyword("AND")
                end = self._parse_frame_bound()
            else:
                start = self._parse_frame_bound()
                end = ("CURRENT_ROW", None)
            spec.frame = a.WindowFrame(units, start, end)
        self.expect(")")
        return spec

    def _parse_frame_bound(self) -> Tuple[str, Optional[a.Expr]]:
        if self.accept_keyword("UNBOUNDED"):
            if self.accept_keyword("PRECEDING"):
                return ("UNBOUNDED_PRECEDING", None)
            self.expect_keyword("FOLLOWING")
            return ("UNBOUNDED_FOLLOWING", None)
        if self.accept_keyword("CURRENT"):
            self.expect_keyword("ROW")
            return ("CURRENT_ROW", None)
        offset = self.parse_expr()
        if self.accept_keyword("PRECEDING"):
            return ("PRECEDING", offset)
        self.expect_keyword("FOLLOWING")
        return ("FOLLOWING", offset)


def _parse_number(text: str):
    try:
        if "." not in text and "e" not in text and "E" not in text:
            return int(text)
        return float(text)
    except ValueError:
        raise ParsingException(f"Bad number literal {text!r}")


def parse_sql(sql: str) -> List[a.Statement]:
    """Parse one or more ;-separated statements (reference DaskParser::parse_sql).

    Queries go through the native (C++) parser when the library is built
    (native/parser.cpp emits a flat AST buffer that decodes to the same
    sqlast objects); DDL/ML statements and any native miss fall back to the
    Python parser.  DSQL_NATIVE_PARSER=0 disables the native path.
    """
    import os

    if os.environ.get("DSQL_NATIVE_PARSER", "1") != "0":
        try:
            from .native_bridge import native_parse

            stmts = native_parse(sql)
            if stmts is not None:
                return stmts
        except ParsingException:
            raise
        except Exception:  # noqa: BLE001 - any native issue -> Python path
            logger.debug("native parse failed; using Python parser",
                         exc_info=True)
    return Parser(sql).parse_statements()
