"""Public exception types (parity: reference src/error.rs DaskPlannerError and
sql/exceptions.rs ParsingException/OptimizationException)."""
from __future__ import annotations

from .planner.binder import BindError
from .planner.lexer import LexError
from .planner.parser import ParsingException


class OptimizationException(RuntimeError):
    """Raised when optimization fails irrecoverably (the driver normally
    falls back to the unoptimized plan instead, context.py:857 parity)."""


__all__ = ["ParsingException", "OptimizationException", "BindError", "LexError"]
