"""Metrics registry: counters, gauges, and latency histograms for the
serving runtime.

Role parity: the reference points users at the dask dashboard for this;
an inference-serving stack needs its own registry (admissions, rejections,
timeouts, cache hit rate, queue-depth and latency percentiles) that both
``SHOW METRICS`` and the server's ``/v1/metrics`` endpoint can snapshot.
Aggregation from the per-node `Tracer` happens through `observe_trace`.
"""
from __future__ import annotations

from collections import deque
from typing import Any, Dict, Iterable, List, Optional, Tuple

from ..runtime import locks


# ---------------------------------------------------------------------------
# documented metric registry
# ---------------------------------------------------------------------------
#: Every exact metric name the engine emits via ``metrics.inc`` /
#: ``metrics.observe``.  This is the registry self-lint rule DSQL401
#: checks string-literal metric names against — an undocumented name in
#: code is name drift (a typo'd counter silently splits a time series) and
#: fails CI.  Add the name here (with the emitting site) when introducing
#: a metric; docs/serving.md and docs/analysis.md describe the families.
DOCUMENTED_METRICS = frozenset({
    # runtime/locks.py — lock sanitizer (ISSUE 19)
    "analysis.locks.order_violation",
    "analysis.locks.registered",
    # analysis/ — plan verifier + cost/memory estimator
    "analysis.verify.runs",
    "analysis.plan_error",
    "analysis.verifier_internal",
    "analysis.explain_lint",
    "analysis.explain_estimate",
    "analysis.rung_skip",
    "analysis.estimate.runs",
    "analysis.estimate.bytes_lo",
    "analysis.estimate.bytes_hi",
    "analysis.estimate.rows_hi",
    "analysis.estimate.rung_proof",
    "analysis.estimate.internal_error",
    "analysis.estimate.feedback",
    # columnar/ — compressed column encodings (encodings.py, docs/columnar.md)
    "columnar.encoding.encoded_columns",
    "columnar.encoding.encoded_bytes",
    "columnar.encoding.decoded_bytes",
    "columnar.encoding.codespace_pred",
    "columnar.encoding.late_rows",
    "columnar.encoding.decode",
    # inference/ — model lowering + fused PREDICT (docs/ml.md)
    "inference.model.registered",
    "inference.model.lowered",
    "inference.model.declined",
    "inference.model.swap",
    "inference.predict.compiled",
    "inference.predict.host",
    # families/ — parameterized plan families + inter-query batching
    "families.parameterized",
    "families.hit",
    "families.estimate.hit",
    "families.internal_error",
    "serving.batch.launches",
    "serving.batch.queries",
    "serving.batch.solo",
    "serving.batch.size",
    # parallel/ + spmd/ — sharded storage, SPMD rungs, collectives engine.
    # The parallel.dist.* names are the registry-visible counters of the
    # dist_* kernel launches that historically lived only in the module
    # STATS dict (predating the registry); parallel.spmd.* cover the
    # sharded compiled rungs and the auto-shard registration policy.
    "parallel.auto_shard.tables",
    "parallel.spmd.launches",
    "parallel.spmd.rows",
    "parallel.dist.agg_kernel",
    "parallel.dist.sort_kernel",
    "parallel.dist.join_kernel",
    "parallel.dist.broadcast_join",
    # observability/ — lifecycle tracing + slow-query log + flight recorder
    "observability.slow_query",
    "observability.flight.dumps",
    # observability/ — HBM ledger gauges (ledger.py, published on every
    # /v1/metrics scrape and SHOW METRICS)
    "serving.ledger.budget_bytes",
    "serving.ledger.reserved_bytes",
    "serving.ledger.inflight_measured_bytes",
    "serving.ledger.cache_bytes",
    "serving.ledger.table_bytes",
    "serving.ledger.headroom_bytes",
    "serving.ledger.model_bytes",
    "serving.ledger.materialized_bytes",
    "serving.ledger.reserve_drift_bytes",
    # observability/ — live query table (live.py, CANCEL QUERY)
    "serving.cancel_requested",
    # planner
    "planner.optimize.fallback",
    # query lifecycle (Context / TpuFrame)
    "query.executed",
    "query.execute_ms",
    "query.d2h_ms",
    "query.serialize_ms",
    "query.plan_cache.hit",
    "query.plan_cache.miss",
    "query.cache.hit",
    "query.cache.miss",
    "query.cache.oversize",
    "query.cache.evicted",
    "query.cache.estimate_skip",
    "query.cache.invalidated",
    # resilience/ — ladder, breaker, retry, watchdog, persistent cache
    "resilience.compile_cache.enabled",
    "resilience.compile_cache.hit",
    "resilience.compile_cache.miss",
    "resilience.watchdog.timeout",
    "resilience.watchdog.abandoned",
    "resilience.breaker.restored",
    "resilience.degraded",
    "resilience.degraded.interpreted",
    "resilience.rung.cpu",
    "resilience.fallback",
    "resilience.fallback.dist_aggregate",
    "resilience.fallback.dist_sort",
    "resilience.breaker.skip",
    "resilience.breaker.trip",
    "resilience.retry.attempts",
    "resilience.retry.recovered",
    "resilience.retry.deadline_abort",
    "resilience.retry.backoff_ms",
    # resilience/ + streaming/ — mid-stream partition fault handling
    # (streaming/runner.py, docs/resilience.md "Partition faults")
    "resilience.partition.oom",
    "resilience.partition.exhausted",
    # serving/ — admission, runtime
    "serving.admitted",
    "serving.rejected",
    "serving.rejected.batch",
    "serving.cancelled",
    "serving.completed",
    "serving.failed",
    "serving.timeouts",
    "serving.shutdown_shed",
    "serving.shed_estimated_bytes",
    "serving.latency_ms",
    "serving.queue_wait_ms",
    # serving/ — packing scheduler (scheduler.py, docs/serving.md
    # "Scheduling and multi-tenancy")
    "serving.scheduler.packed",
    "serving.scheduler.waited",
    "serving.scheduler.quota_throttled",
    "serving.scheduler.cost_rung_skip",
    "serving.scheduler.inflight_bytes",
    "serving.scheduler.running",
    "serving.scheduler.reserve_drift",
    # serving/ + streaming/ — streamed partitioned execution
    # (streaming/, docs/serving.md "Streaming execution")
    "serving.stream.admitted",
    "serving.stream.queries",
    "serving.stream.partitions",
    "serving.stream.repartitions",
    "serving.stream.rows",
    "serving.stream.chunk_rows",
    # liveness gauges: advancing = healthy long stream, stalled = hang
    "serving.stream.partitions_done",
    "serving.stream.rows_done",
    # serving/ — zero-cold-start: pre-warm + background recompile
    "serving.warmup.started",
    "serving.warmup.warmed",
    "serving.warmup.failed",
    "serving.warmup.skipped",
    "serving.warmup.cancelled",
    "serving.warmup.ms",
    "serving.bg_compile.submitted",
    "serving.bg_compile.completed",
    "serving.bg_compile.failed",
    "serving.bg_compile.dropped",
    "serving.bg_compile.deferred",
    "serving.bg_compile.ms",
    # serving/ + materialize/ — semantic reuse: sub-plan materialization,
    # subsumption answering, incremental maintenance (materialize/,
    # docs/serving.md "Semantic reuse and materialization")
    "serving.materialize.stored",
    "serving.materialize.hits",
    "serving.materialize.evicted",
    "serving.materialize.refreshed",
    "serving.materialize.declined",
    "serving.reuse.subsumption.hits",
    "serving.reuse.subsumption.declined",
    "serving.reuse.incremental.hits",
    "serving.reuse.incremental.folds",
    "serving.reuse.incremental.declined",
    "serving.reuse.append_rows",
    # resilience/pressure.py — coordinated HBM pressure response: band
    # gauge + transitions, YELLOW speculative-work suspensions, RED
    # cross-tier reclaim, OOM reclaim-then-retry on the SAME rung,
    # CRITICAL forced-stream/shed outcomes (docs/resilience.md
    # "Pressure hierarchy")
    "resilience.pressure.band",
    "resilience.pressure.transitions",
    "resilience.pressure.suspended",
    "resilience.pressure.reclaims",
    "resilience.pressure.reclaimed_bytes",
    "resilience.pressure.rung_retry",
    "resilience.pressure.rung_retry_ok",
    "resilience.pressure.critical_streamed",
    "resilience.pressure.critical_shed",
    # resilience/chaos.py — seeded randomized fault campaigns under
    # concurrent mixed load (bench.py --chaos, docs/resilience.md
    # "Chaos harness")
    "chaos.campaigns",
    "chaos.rounds",
    "chaos.queries",
    "chaos.violations",
    # fleet/ — router fronting N replicas: health-gated cost-aware
    # routing, mid-query failover, warm-standby promotion, graceful
    # drain, epoch-fenced write fan-out (docs/fleet.md)
    "fleet.replicas",
    "fleet.route",
    "fleet.route.spill",
    "fleet.failover",
    "fleet.promote",
    "fleet.drain",
    "fleet.kill",
    "fleet.write.applied",
    "fleet.write.fenced",
    "fleet.write.replayed",
    "fleet.write.poisoned",
    "fleet.write.unroutable",
    "fleet.sync",
})

#: Prefixes legitimizing *dynamic* metric families (f-string names keyed by
#: rung / rule / class / node type).  DSQL401 checks an f-string's static
#: prefix against these.
DOCUMENTED_METRIC_PREFIXES = (
    "analysis.findings.",       # per verifier rule id
    "analysis.rung_skip.",      # per pre-skipped ladder rung
    "resilience.degraded.",     # per degraded rung
    "resilience.rung.",         # per rung that answered
    "resilience.breaker.skip.",  # per breaker-skipped rung
    "resilience.compile_ms.",   # per-rung XLA compile wall time (observability/spans.py)
    "serving.admitted.",        # per admission class
    "serving.rejected.",        # per admission class
    "serving.scheduler.queue_depth.",    # per admission class (gauge)
    "serving.scheduler.cost_rung_skip.",  # per cost-skipped ladder rung
    "executor.node.",           # per plan-node type (Tracer aggregation)
    "fleet.routed.",            # per-replica routed-query counter (fleet/router.py)
)


def is_documented_metric(name: str, prefix_only: bool = False) -> bool:
    """True when ``name`` is covered by the documented registry.

    ``prefix_only`` means ``name`` is the static *prefix* of an f-string
    (the dynamic tail is unknown), so it also matches a documented family
    prefix it truncates (``f"resilience.rung.{r}"`` → ``"resilience.rung."``
    matching itself, or a shorter static run).  An exact literal gets no
    such slack — ``metrics.inc("analysis.findings")`` missing its per-rule
    suffix is exactly the drift DSQL401 exists to catch."""
    if name in DOCUMENTED_METRICS:
        return True
    if any(name.startswith(p) for p in DOCUMENTED_METRIC_PREFIXES):
        return True
    return prefix_only and any(p.startswith(name)
                               for p in DOCUMENTED_METRIC_PREFIXES)


def nearest_rank(data_sorted: List[float], q: float) -> float:
    """Nearest-rank percentile over pre-sorted data — THE quantile formula
    of the engine, shared by the serving histograms and the per-fingerprint
    profile store so SHOW METRICS and SHOW PROFILES can never report
    different p50s for the same samples."""
    if not data_sorted:
        return 0.0
    n = len(data_sorted)
    return data_sorted[min(n - 1, int(q * (n - 1) + 0.5))]


class Histogram:
    """Bounded-reservoir histogram: O(1) observe, percentile on snapshot.

    The reservoir keeps the most recent `window` observations — serving
    percentiles should reflect *current* traffic, not the process lifetime —
    while count/total stay exact cumulative aggregates."""

    __slots__ = ("window", "count", "total", "vmax", "_ring")

    def __init__(self, window: int = 2048):
        self.window = window
        self.count = 0
        self.total = 0.0
        self.vmax = 0.0
        self._ring: "deque[float]" = deque(maxlen=window)

    def observe(self, value: float) -> None:
        value = float(value)
        self.count += 1
        self.total += value
        if value > self.vmax:
            self.vmax = value
        self._ring.append(value)

    def percentiles(self, qs: Iterable[float] = (0.5, 0.95, 0.99)) -> List[float]:
        data = sorted(self._ring)
        return [nearest_rank(data, q) for q in qs]

    def snapshot(self) -> Dict[str, Any]:
        p50, p95, p99 = self.percentiles()
        return {
            "count": self.count,
            "sum": round(self.total, 3),
            "avg": round(self.total / self.count, 3) if self.count else 0.0,
            "p50": round(p50, 3),
            "p95": round(p95, 3),
            "p99": round(p99, 3),
            "max": round(self.vmax, 3),
        }


class MetricsRegistry:
    """Thread-safe named counters / gauges / histograms.

    Flat dotted names (``query.cache.hit``, ``serving.rejected``); the
    snapshot is JSON-ready for ``/v1/metrics`` and row-flattened for
    ``SHOW METRICS``."""

    def __init__(self):
        # leaf rank (90): counters are bumped from under every other
        # subsystem's lock, and nothing is acquired while this is held
        self._lock = locks.named_lock("serving.metrics")
        self._counters: Dict[str, int] = {}
        self._gauges: Dict[str, float] = {}
        self._hists: Dict[str, Histogram] = {}

    # ------------------------------------------------------------- writes
    def inc(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + n

    def gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = value

    def observe(self, name: str, value: float) -> None:
        with self._lock:
            hist = self._hists.get(name)
            if hist is None:
                hist = self._hists[name] = Histogram()
            hist.observe(value)

    def observe_trace(self, root) -> None:
        """Fold one executor `NodeTrace` tree into per-node-type wall-time
        histograms (``executor.node.<type>.ms``) and row counters."""
        if root is None:
            return
        stack = [root]
        while stack:
            t = stack.pop()
            self.observe(f"executor.node.{t.node_type}.ms", t.wall_ms)
            if t.rows >= 0:
                self.inc(f"executor.node.{t.node_type}.rows", t.rows)
            stack.extend(t.children)

    # -------------------------------------------------------------- reads
    def counter(self, name: str) -> int:
        with self._lock:
            return self._counters.get(name, 0)

    def hist_percentile(self, name: str, q: float = 0.5) -> Optional[float]:
        """One percentile of a histogram's rolling reservoir, or None when
        the histogram has no samples — the cost-based rung selector reads
        the per-rung compile-cost prior (``resilience.compile_ms.<rung>``)
        through this."""
        with self._lock:
            hist = self._hists.get(name)
            if hist is None or not hist._ring:
                return None
            return hist.percentiles([q])[0]

    def hit_rate(self, hit: str, miss: str) -> float:
        with self._lock:
            h = self._counters.get(hit, 0)
            m = self._counters.get(miss, 0)
        return h / (h + m) if (h + m) else 0.0

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            out: Dict[str, Any] = {
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "histograms": {k: h.snapshot() for k, h in self._hists.items()},
            }
        out["cacheHitRate"] = round(
            self.hit_rate("query.cache.hit", "query.cache.miss"), 4)
        return out

    def rows(self) -> List[Tuple[str, str]]:
        """Flatten the snapshot to (metric, value) string pairs, sorted by
        name — the ``SHOW METRICS`` result shape."""
        snap = self.snapshot()
        rows: List[Tuple[str, str]] = []
        for name, v in snap["counters"].items():
            rows.append((name, str(v)))
        for name, v in snap["gauges"].items():
            rows.append((name, _fmt(v)))
        for name, h in snap["histograms"].items():
            for stat in ("count", "avg", "p50", "p95", "p99", "max"):
                rows.append((f"{name}.{stat}", _fmt(h[stat])))
        rows.append(("query.cache.hit_rate", _fmt(snap["cacheHitRate"])))
        return sorted(rows)

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._hists.clear()


def _fmt(v) -> str:
    if isinstance(v, float) and v == int(v):
        return str(int(v))
    return str(v)
