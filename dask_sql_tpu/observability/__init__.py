"""Query observability: lifecycle tracing, per-fingerprint profiles,
Prometheus exposition, slow-query logging — and the live serving plane
(in-flight query table, HBM ledger, flight recorder).

The serving stack (admission, result cache, degradation ladder, breaker,
estimator) makes multi-stage decisions per query; this subsystem makes
every stage visible (docs/observability.md):

- `spans`     — the `QueryTrace` span model, contextvar activation, the
                bounded `TraceStore` behind ``/v1/trace/{qid}``,
                `timed_jit_call` per-rung compile timing, and cross-query
                flow links (Chrome-trace flow events);
- `profiles`  — `ProfileStore`: rolling per-fingerprint compile/exec/bytes
                profiles behind ``SHOW PROFILES``, persisted by the
                checkpoint subsystem;
- `prometheus`— text exposition of the MetricsRegistry for
                ``/v1/metrics?format=prometheus``;
- `slowlog`   — threshold-gated span-tree dumps of latency outliers;
- `live`      — `QueryRegistry`: the in-flight query table behind
                ``SHOW QUERIES`` / ``GET /v1/queries`` and the target of
                ``CANCEL QUERY``;
- `ledger`    — `DeviceLedger`: live HBM accounting (reservations,
                measured footprints, cache, at-rest tables vs. budget)
                as ``serving.ledger.*`` gauges;
- `flight`    — the always-on bounded flight recorder of structured
                engine events (``GET /v1/debug/events``), with a
                registered event vocabulary (self-lint DSQL501).
"""
from . import flight
from . import live
from .ledger import DeviceLedger
from .live import LiveQuery, QueryRegistry
from .profiles import ProfileStore
from .prometheus import CONTENT_TYPE as PROMETHEUS_CONTENT_TYPE
from .prometheus import render_prometheus
from .slowlog import maybe_log_slow
from .spans import (
    QueryTrace,
    Span,
    TraceStore,
    activate,
    compile_sink,
    current_trace,
    detail,
    merge_chrome_traces,
    stage,
    timed_jit_call,
    trace_event,
)

__all__ = [
    "DeviceLedger",
    "LiveQuery",
    "ProfileStore",
    "PROMETHEUS_CONTENT_TYPE",
    "QueryRegistry",
    "QueryTrace",
    "Span",
    "TraceStore",
    "activate",
    "compile_sink",
    "current_trace",
    "detail",
    "flight",
    "live",
    "maybe_log_slow",
    "merge_chrome_traces",
    "render_prometheus",
    "stage",
    "timed_jit_call",
    "trace_event",
]
