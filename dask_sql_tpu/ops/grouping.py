"""Group-id factorization and segment aggregation kernels.

TPU-first replacement for the reference's pandas `groupby().agg()` tree
(aggregate.py:575-581 there): keys are factorized to dense integer group ids
with a single device lexsort, and every aggregate lowers to an XLA segment
reduction (`jax.ops.segment_sum`/`_min`/`_max`) — embarrassingly parallel on
the VPU, and the same kernels serve as the partial-aggregation stage of the
distributed partial→final tree (see `parallel/collectives.py`).
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..columnar.column import Column
from ..columnar.dtypes import STRING_TYPES, SqlType


def key_arrays(cols: Sequence[Column]) -> List[jnp.ndarray]:
    """Device sort/group keys for columns: ints stay, floats stay, strings use
    *sorted-dictionary* codes so code order == lexicographic order."""
    out = []
    for c in cols:
        if c.sql_type in STRING_TYPES:
            c = c.compact_dictionary()
            data = c.data
        elif c.data.dtype == jnp.bool_:
            data = c.data.astype(jnp.int32)
        else:
            data = c.data
        valid = None
        if c.validity is not None:
            valid = c.valid_mask()
        if jnp.issubdtype(data.dtype, jnp.floating):
            # unconditional: a content check would be a device round trip;
            # an all-true mask keys identically
            nan = jnp.isnan(data)
            valid = ~nan if valid is None else (valid & ~nan)
        if valid is not None:
            # NULL forms its own single group (dropna=False semantics,
            # reference aggregate.py:575-577): zero the payload under NULL and
            # key on validity so all NULLs collide
            data = jnp.where(valid, data, jnp.zeros_like(data))
            out.append(data)
            out.append(valid.astype(jnp.int32))
        else:
            out.append(data)
    return out


#: mixed-radix group-id domain gate shared by every radix planner
#: (CompiledAggregate, compiled-join _plan_radix, radix_gid) and the
#: static plan verifier (analysis/verifier.py) — one constant so the
#: bind-time verdict and the compile-time gate can never drift
RADIX_DOMAIN_LIMIT = 1 << 22


def radix_gid(cols: Sequence[Column], max_domain: int = RADIX_DOMAIN_LIMIT):
    """Sort-free group ids for small-domain keys (dictionary codes / bools).

    When every key column is dictionary-encoded (or boolean), group ids are a
    mixed-radix combination of the codes — one fused multiply-add per column,
    no O(n log n) sort.  This is the hot path for TPC-H Q1-style aggregations.
    Returns (gid, domain, decode) or None when ineligible; `decode(gids)`
    maps group ids back to per-column Columns (for key materialization).
    """
    radices = []
    offsets = []
    # phase 1: classify columns, queueing every int key's min/max so ALL
    # bounds ride ONE device pull (phase 2) — one sync per node, not per key
    pending = []  # (slot, device min, device max)
    for c in cols:
        if c.sql_type in STRING_TYPES and c.dictionary is not None:
            radices.append(len(c.dictionary) + 1)  # +1 slot for NULL
            offsets.append(0)
        elif c.data.dtype == jnp.bool_:
            radices.append(3)
            offsets.append(0)
        elif jnp.issubdtype(c.data.dtype, jnp.integer) and len(c):
            pending.append((len(radices), jnp.min(c.data), jnp.max(c.data)))
            radices.append(None)
            offsets.append(None)
        else:
            return None
    spans = resolve_int_bounds(pending, max_domain)
    if spans is None:
        return None
    for slot, (span, lo) in spans.items():
        radices[slot] = span + 1
        offsets[slot] = lo
    domain = 1
    for r in radices:
        domain *= r
    if domain > max_domain:
        return None
    gid = None
    for c, r, off in zip(cols, radices, offsets):
        codes = c.data.astype(jnp.int64) - off
        codes = jnp.clip(codes, 0, r - 2)
        if c.validity is not None:
            codes = jnp.where(c.validity, codes, r - 1)  # NULL -> last slot
        gid = codes if gid is None else gid * r + codes

    def decode(gids: jnp.ndarray) -> List[Column]:
        out = []
        strides = []
        s = 1
        for r in reversed(radices):
            strides.append(s)
            s *= r
        strides = list(reversed(strides))
        # ONE device pull decides every column's NULL-group presence (a
        # per-column bool(any()) was a round trip each on a tunneled chip)
        null_masks = [(gids // stride) % r == (r - 1)
                      for r, stride in zip(radices, strides)]
        if null_masks:
            from ..utils import host_ints

            flags = host_ints(*[m.any() for m in null_masks])
        for ci, (c, r, off, stride) in enumerate(zip(cols, radices, offsets,
                                                     strides)):
            code = (gids // stride) % r
            is_null = null_masks[ci]
            validity = ~is_null if bool(flags[ci]) else None
            code = jnp.minimum(code, r - 2)
            if c.sql_type in STRING_TYPES:
                out.append(Column(code.astype(jnp.int32), c.sql_type, validity,
                                  c.dictionary))
            elif c.data.dtype == jnp.bool_:
                out.append(Column(code == 1, c.sql_type, validity))
            else:
                out.append(Column((code + off).astype(c.data.dtype), c.sql_type,
                                  validity))
        return out

    return gid.astype(jnp.int32) if domain < 2**31 else gid, domain, decode


def resolve_int_bounds(pending, max_domain):
    """Batch-resolve queued (slot, device_min, device_max) integer-key
    bounds in ONE device pull.  {slot: (span, lo)}, or None when any span
    blows the domain gate.  Shared by the three radix planners so the
    gate/backfill logic cannot drift."""
    if not pending:
        return {}
    from ..utils import host_ints

    flat = host_ints(*[v for _, mn, mx in pending for v in (mn, mx)])
    out = {}
    for j, (slot, _, _) in enumerate(pending):
        lo, hi = flat[2 * j], flat[2 * j + 1]
        span = hi - lo + 1
        if span <= 0 or span > max_domain:
            return None
        out[slot] = (span, lo)
    return out


def factorize(keys: Sequence[jnp.ndarray]) -> Tuple[jnp.ndarray, jnp.ndarray, int]:
    """Dense group ids for multi-column keys.

    Returns (group_ids per row, sorted-order permutation, num_groups).
    Group ids number the distinct keys in ascending lexicographic order.
    """
    n = int(keys[0].shape[0])
    if n == 0:
        return jnp.zeros(0, dtype=jnp.int32), jnp.zeros(0, dtype=jnp.int32), 0
    order = jnp.lexsort(tuple(reversed([k for k in keys])))
    changed = jnp.zeros(n, dtype=bool).at[0].set(True)
    for k in keys:
        ks = k[order]
        changed = changed.at[1:].set(changed[1:] | (ks[1:] != ks[:-1]))
    gid_sorted = jnp.cumsum(changed.astype(jnp.int32)) - 1
    gid = jnp.zeros(n, dtype=jnp.int32).at[order].set(gid_sorted)
    num_groups = int(gid_sorted[-1]) + 1
    return gid, order, num_groups


def group_first_indices(gid: jnp.ndarray, num_groups: int) -> jnp.ndarray:
    """Row index of the first occurrence of each group (for key materialization)."""
    n = gid.shape[0]
    big = jnp.full(num_groups, n, dtype=jnp.int64)
    first = big.at[gid].min(jnp.arange(n, dtype=jnp.int64))
    return first


# ---------------------------------------------------------------------------
# Segment aggregation kernels.  All take (values, valid, gid, num_groups) and
# return (agg_values, agg_valid).  `valid` is a bool mask; aggregates skip
# NULLs per SQL semantics (reference sum min_count=1, aggregate.py:486-493).
# ---------------------------------------------------------------------------
def seg_count(valid: jnp.ndarray, gid: jnp.ndarray, num_groups: int) -> jnp.ndarray:
    return jax.ops.segment_sum(valid.astype(jnp.int64), gid, num_groups)


def seg_sum(values, valid, gid, num_groups):
    contrib = jnp.where(valid, values, jnp.zeros_like(values))
    s = jax.ops.segment_sum(contrib, gid, num_groups)
    cnt = seg_count(valid, gid, num_groups)
    return s, cnt > 0


def seg_min(values, valid, gid, num_groups):
    fill = _extreme(values.dtype, maximum=True)
    contrib = jnp.where(valid, values, fill)
    m = jax.ops.segment_min(contrib, gid, num_groups)
    cnt = seg_count(valid, gid, num_groups)
    return jnp.where(cnt > 0, m, jnp.zeros_like(m)), cnt > 0


def seg_max(values, valid, gid, num_groups):
    fill = _extreme(values.dtype, maximum=False)
    contrib = jnp.where(valid, values, fill)
    m = jax.ops.segment_max(contrib, gid, num_groups)
    cnt = seg_count(valid, gid, num_groups)
    return jnp.where(cnt > 0, m, jnp.zeros_like(m)), cnt > 0


def seg_avg(values, valid, gid, num_groups):
    s, _ = seg_sum(values.astype(jnp.float64), valid, gid, num_groups)
    cnt = seg_count(valid, gid, num_groups)
    return s / jnp.maximum(cnt, 1), cnt > 0


def seg_var(values, valid, gid, num_groups, ddof: int):
    """Variance via the (count, sum, sumsq) triple — the same shape as the
    reference's tree-aggregation triple (aggregate.py:117-160)."""
    x = values.astype(jnp.float64)
    s, _ = seg_sum(x, valid, gid, num_groups)
    s2, _ = seg_sum(x * x, valid, gid, num_groups)
    cnt = seg_count(valid, gid, num_groups)
    denom = jnp.maximum(cnt - ddof, 1)
    mean = s / jnp.maximum(cnt, 1)
    var = (s2 - cnt * mean * mean) / denom
    var = jnp.maximum(var, 0.0)
    return var, cnt > ddof


def seg_bool_and(values, valid, gid, num_groups):
    contrib = jnp.where(valid, values.astype(jnp.int32), 1)
    m = jax.ops.segment_min(contrib, gid, num_groups)
    cnt = seg_count(valid, gid, num_groups)
    return m.astype(bool), cnt > 0


def seg_bool_or(values, valid, gid, num_groups):
    contrib = jnp.where(valid, values.astype(jnp.int32), 0)
    m = jax.ops.segment_max(contrib, gid, num_groups)
    cnt = seg_count(valid, gid, num_groups)
    return m.astype(bool), cnt > 0


def seg_bitwise(values, valid, gid, num_groups, op: str):
    """bit_and/bit_or/bit_xor per group via per-bit segment reductions.

    64 segment reductions over the bit planes — rarely-used ops, so clarity
    beats peak efficiency here (reference ReduceAggregation parity).
    """
    x = values.astype(jnp.int64)
    nbits = 64
    bits = (x[:, None] >> jnp.arange(nbits, dtype=jnp.int64)[None, :]) & 1
    if op == "bit_and":
        contrib = jnp.where(valid[:, None], bits, 1)
        red = jax.ops.segment_min(contrib, gid, num_groups)
    elif op == "bit_or":
        contrib = jnp.where(valid[:, None], bits, 0)
        red = jax.ops.segment_max(contrib, gid, num_groups)
    else:  # bit_xor
        contrib = jnp.where(valid[:, None], bits, 0)
        red = jax.ops.segment_sum(contrib, gid, num_groups) & 1
    out = jnp.sum(red << jnp.arange(nbits, dtype=jnp.int64)[None, :], axis=1)
    cnt = seg_count(valid, gid, num_groups)
    return out, cnt > 0


def seg_first(values, valid, gid, num_groups):
    """Value at the smallest row index with a valid value per group."""
    n = values.shape[0]
    idx = jnp.arange(n, dtype=jnp.int64)
    big = jnp.full(num_groups, n, dtype=jnp.int64)
    first = big.at[gid].min(jnp.where(valid, idx, n))
    cnt = seg_count(valid, gid, num_groups)
    safe = jnp.clip(first, 0, max(n - 1, 0))
    return values[safe], cnt > 0


def seg_last(values, valid, gid, num_groups):
    n = values.shape[0]
    idx = jnp.arange(n, dtype=jnp.int64)
    small = jnp.full(num_groups, -1, dtype=jnp.int64)
    last = small.at[gid].max(jnp.where(valid, idx, -1))
    cnt = seg_count(valid, gid, num_groups)
    safe = jnp.clip(last, 0, max(n - 1, 0))
    return values[safe], cnt > 0


def seg_percentile(values, valid, gid, num_groups, q: float):
    """Exact per-group quantile: one lexsort by (group, validity, value), then
    a linear-interpolated pick at the group offset (PERCENTILE_CONT rule).
    TPU-shaped: sort + gathers, no per-group loops."""
    n = values.shape[0]
    if n == 0:
        return (jnp.zeros(num_groups, dtype=jnp.float64),
                jnp.zeros(num_groups, dtype=bool))
    x = values.astype(jnp.float64)
    x = jnp.where(valid, x, jnp.inf)  # invalid (and NaN-masked) sort last
    order = jnp.lexsort((x, (~valid).astype(jnp.int32), gid))
    sorted_gid = gid[order]
    sorted_val = x[order]
    idx = jnp.arange(n, dtype=jnp.int64)
    starts = jnp.full(num_groups, n, dtype=jnp.int64).at[sorted_gid].min(idx)
    cnt = seg_count(valid, gid, num_groups)
    k = jnp.maximum(cnt - 1, 0).astype(jnp.float64) * q
    lo = jnp.floor(k).astype(jnp.int64)
    hi = jnp.ceil(k).astype(jnp.int64)
    frac = k - lo
    safe = lambda i: jnp.clip(starts + i, 0, max(n - 1, 0))
    v = sorted_val[safe(lo)] * (1.0 - frac) + sorted_val[safe(hi)] * frac
    return v, cnt > 0


def _extreme(dtype, maximum: bool):
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.array(jnp.inf if maximum else -jnp.inf, dtype=dtype)
    if dtype == jnp.bool_:
        return jnp.array(maximum, dtype=dtype)
    info = jnp.iinfo(dtype)
    return jnp.array(info.max if maximum else info.min, dtype=dtype)
