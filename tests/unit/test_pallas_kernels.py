"""Pallas / MXU segment-reduction kernel tests (interpret mode on CPU)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp


def _case(seed, n=1000, domain=37, k=3):
    rng = np.random.RandomState(seed)
    gid = rng.randint(0, domain, n).astype(np.int32)
    contribs = rng.rand(n, k).astype(np.float32)
    expected = np.zeros((domain, k), dtype=np.float64)
    for g, row in zip(gid, contribs):
        expected[g] += row
    return jnp.asarray(gid), jnp.asarray(contribs), expected


def test_segsum_onehot_jnp_matches_scatter():
    from dask_sql_tpu.ops.pallas_kernels import segsum_onehot_jnp

    gid, contribs, expected = _case(0)
    out = segsum_onehot_jnp(gid, contribs, 37)
    np.testing.assert_allclose(np.asarray(out), expected, rtol=1e-5)


def test_segsum_pallas_interpret():
    from dask_sql_tpu.ops.pallas_kernels import segsum_pallas

    gid, contribs, expected = _case(1, n=700, domain=19, k=2)
    out = segsum_pallas(gid, contribs, 19, block_rows=256, interpret=True)
    np.testing.assert_allclose(np.asarray(out), expected, rtol=1e-5)


def test_segsum_pallas_padding_edges():
    from dask_sql_tpu.ops.pallas_kernels import segsum_pallas

    # n not a multiple of the block, domain 1, single column
    gid, contribs, expected = _case(2, n=301, domain=1, k=1)
    out = segsum_pallas(gid, contribs, 1, block_rows=128, interpret=True)
    np.testing.assert_allclose(np.asarray(out), expected, rtol=1e-5)


def test_compiled_pipeline_matmul_mode(c):
    import pandas as pd

    # integer group key so the radix-compiled pipeline actually engages
    rng = np.random.RandomState(3)
    df = pd.DataFrame({"g": rng.randint(0, 5, 4000).astype(np.int64),
                       "v": rng.rand(4000) * 1e9})
    c.create_table("mmagg", df)
    q = "SELECT g, COUNT(*) AS n, SUM(v) AS s FROM mmagg GROUP BY g"
    got = c.sql(q, config_options={"sql.compile.segsum": "matmul"}).compute()
    ref = c.sql(q, config_options={"sql.compile.segsum": "scatter"}).compute()
    got = got.sort_values("g").reset_index(drop=True)
    ref = ref.sort_values("g").reset_index(drop=True)
    assert list(got["n"]) == list(ref["n"])
    # hi/lo double-float: representation-exact, f32-grade accumulation
    np.testing.assert_allclose(got["s"], ref["s"], rtol=1e-6)
    # and the compiled matmul path really ran (not an eager fallback)
    from dask_sql_tpu.physical import compiled as comp

    assert any(k[-1] == "matmul" and v.segsum_mode == "matmul"
               for k, v in comp._cache.items())


def test_segsum_double_float_accuracy():
    from dask_sql_tpu.ops.pallas_kernels import segsum_double_float

    rng = np.random.RandomState(4)
    gid = jnp.asarray(rng.randint(0, 4, 5000).astype(np.int32))
    vals = jnp.asarray(rng.rand(5000, 1) * 1e12 + 0.12345)
    out = segsum_double_float(gid, vals, 4)
    expected = np.zeros((4, 1))
    for g, v in zip(np.asarray(gid), np.asarray(vals)):
        expected[g] += v
    np.testing.assert_allclose(np.asarray(out), expected, rtol=1e-5)


def test_bad_segsum_config_rejected():
    from dask_sql_tpu import config
    from dask_sql_tpu.ops.pallas_kernels import choose_segsum_impl

    with config.set({"sql.compile.segsum": "scater"}):
        with pytest.raises(ValueError):
            choose_segsum_impl(config.config, 10)


def test_choose_impl():
    from dask_sql_tpu import config
    from dask_sql_tpu.ops import pallas_kernels
    from dask_sql_tpu.ops.pallas_kernels import choose_segsum_impl

    with config.set({"sql.compile.segsum": "pallas"}):
        # 'pallas' is availability-gated (axon remote-compile rejects pallas
        # lowering); where unavailable it degrades to the matmul path
        assert choose_segsum_impl(config.config, 100) in ("pallas", "matmul")
    with config.set({"sql.compile.segsum": "auto"}):
        # CPU backend in tests -> scatter
        assert choose_segsum_impl(config.config, 100) == "scatter"


def test_segsum_scan_blocked_accuracy_and_counts():
    from dask_sql_tpu.ops.pallas_kernels import (
        MATMUL_FLOAT_REL_ERR_BOUND,
        segsum_scan_blocked,
        split_hi_lo,
    )

    rng = np.random.RandomState(7)
    n, domain = 200_000, 16
    gid = jnp.asarray(rng.randint(0, domain, n).astype(np.int32))
    x64 = jnp.asarray(rng.rand(n) * 1e9 + 0.123456789)
    mask = jnp.asarray(rng.rand(n) < 0.8)
    hi, lo = split_hi_lo(jnp.where(mask, x64, 0.0))
    cols = [mask.astype(jnp.float32), hi, lo]
    out = segsum_scan_blocked(gid, cols, domain, block=8192)
    # counts: EXACT (integer-valued f32 block partials, f64 combine)
    cnt_exact = np.zeros(domain)
    np.add.at(cnt_exact, np.asarray(gid), np.asarray(mask).astype(np.float64))
    assert np.array_equal(np.asarray(out[:, 0]), cnt_exact)
    # float sums: within the stated bound of the exact f64 result
    s_exact = np.zeros(domain)
    np.add.at(s_exact, np.asarray(gid),
              np.where(np.asarray(mask), np.asarray(x64), 0.0))
    got = np.asarray(out[:, 1] + out[:, 2])
    rel = np.max(np.abs(got - s_exact) / np.maximum(np.abs(s_exact), 1e-30))
    assert rel < MATMUL_FLOAT_REL_ERR_BOUND, rel
