"""Serving runtime through the Presto server: admission rejection with
retry-after, deadlines, cooperative cancel, /v1/metrics counters, and
SHOW METRICS over the wire."""
import json
import time
import urllib.error
import urllib.request

import numpy as np
import pandas as pd
import pytest


def _post(port, sql, headers=None):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/statement", data=sql.encode(),
        method="POST")
    for k, v in (headers or {}).items():
        req.add_header(k, v)
    try:
        with urllib.request.urlopen(req) as resp:
            return resp.status, json.loads(resp.read()), dict(resp.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), dict(e.headers)


def _follow(port, payload, timeout=60):
    deadline = time.time() + timeout
    while "nextUri" in payload and time.time() < deadline:
        time.sleep(0.05)
        with urllib.request.urlopen(payload["nextUri"]) as resp:
            payload = json.loads(resp.read())
    return payload


def _metrics(port):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}/v1/metrics") as resp:
        return json.loads(resp.read())


@pytest.fixture
def server(c):
    from dask_sql_tpu.server.app import run_server

    srv = run_server(context=c, host="127.0.0.1", port=0, blocking=False)
    yield srv
    srv.shutdown()


@pytest.fixture
def tiny_server():
    """1 worker, interactive queue bound 1 — trivially saturated."""
    from dask_sql_tpu import Context
    from dask_sql_tpu.server.app import run_server

    c = Context()
    c.create_table("sleepy", pd.DataFrame({"a": np.arange(4, dtype=np.int64)}))

    def slow(row):
        time.sleep(0.3)
        return int(row["x"])

    c.register_function(slow, "slowid", [("x", np.int64)], np.int64,
                        row_udf=True)
    with c.config.set({"serving.workers": 1,
                       "serving.queue.interactive": 1,
                       "serving.retry_after_s": 2.0}):
        srv = run_server(context=c, host="127.0.0.1", port=0, blocking=False)
    yield srv
    srv.shutdown()


def test_rejection_past_queue_bound(tiny_server):
    port = tiny_server.port
    sqls = [f"SELECT slowid(a) + {i} AS v FROM sleepy" for i in range(3)]
    st1, p1, _ = _post(port, sqls[0])  # occupies the single worker
    deadline = time.time() + 10  # wait until it RUNS so the queue is empty
    while time.time() < deadline and _metrics(port)["running"] < 1:
        time.sleep(0.02)
    st2, p2, _ = _post(port, sqls[1])  # fills the queue (bound 1)
    assert st1 == 200 and st2 == 200
    st3, p3, h3 = _post(port, sqls[2])  # must shed, not queue unboundedly
    assert st3 == 429
    assert p3["error"]["errorName"] == "QUERY_QUEUE_FULL"
    assert p3["error"]["errorType"] == "INSUFFICIENT_RESOURCES"
    assert p3["error"]["retryAfterSeconds"] > 0
    assert int(h3["Retry-After"]) >= 1
    # the admitted queries still complete
    assert _follow(port, p1)["stats"]["state"] == "FINISHED"
    assert _follow(port, p2)["stats"]["state"] == "FINISHED"
    m = _metrics(port)
    assert m["rejected"] == 1
    assert m["completed"] == 2
    assert m["registry"]["counters"]["serving.rejected"] == 1


def test_deadline_header_cancels(tiny_server):
    port = tiny_server.port
    st, p, _ = _post(port, "SELECT slowid(a) AS v FROM sleepy",
                     headers={"X-Dsql-Deadline-Ms": "1"})
    assert st == 200
    payload = _follow(port, p)
    assert "error" in payload
    assert payload["error"]["errorName"] in ("EXCEEDED_TIME_LIMIT",
                                             "DeadlineExceededError")


def test_cancel_endpoint_cooperative(tiny_server):
    port = tiny_server.port
    st, p, _ = _post(port, "SELECT slowid(a) * 7 AS v FROM sleepy")
    qid = p["id"]
    time.sleep(0.1)
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/cancel/{qid}", method="DELETE")
    with urllib.request.urlopen(req) as resp:
        assert json.loads(resp.read())["cancelled"] is True
    payload = _follow(port, p)
    assert "error" in payload


def test_concurrent_queries_update_metrics(server):
    import concurrent.futures

    port = server.port
    before = _metrics(server.port)

    def run(i):
        payload = _follow(port, _post(
            port, f"SELECT {i} * a AS v FROM df_simple ORDER BY v")[1])
        assert payload["stats"]["state"] == "FINISHED", payload
        return [row[0] for row in payload["data"]]

    with concurrent.futures.ThreadPoolExecutor(max_workers=8) as pool:
        results = list(pool.map(run, range(1, 9)))
    for i, vals in enumerate(results, start=1):
        assert vals == [i * 1, i * 2, i * 3]
    m = _metrics(port)
    assert m["completed"] >= before["completed"] + 8
    assert m["queueDepth"] == 0 and m["running"] == 0
    reg = m["registry"]["counters"]
    assert reg["serving.admitted"] >= 8
    assert reg["serving.completed"] >= 8
    assert m["registry"]["histograms"]["serving.latency_ms"]["count"] >= 8
    assert m["serving"]["admission"]["waiting"] == {"interactive": 0,
                                                    "batch": 0}


def test_repeated_query_hits_cache_via_server(server):
    port = server.port
    sql = "SELECT a + 41 AS v FROM df_simple"
    r1 = _follow(port, _post(port, sql)[1])
    r2 = _follow(port, _post(port, sql)[1])
    assert r1["data"] == r2["data"]
    hits = int(_metrics(port)["resultCache"]["hits"])
    assert hits >= 1
    # the counter is also visible through SQL, per the acceptance criteria
    p = _follow(port, _post(port, "SHOW METRICS")[1])
    rows = {row[0]: row[1] for row in p["data"]}
    assert int(rows["query.cache.hit"]) >= 1
    # server-attached runtime state shows up too
    assert any(k.startswith("serving.runtime.") for k in rows)


def test_batch_class_header(server):
    port = server.port
    st, p, _ = _post(port, "SELECT 1 + 1 AS x",
                     headers={"X-Dsql-Class": "batch"})
    assert st == 200
    assert _follow(port, p)["data"][0][0] == 2
    reg = _metrics(port)["registry"]["counters"]
    assert reg.get("serving.admitted.batch", 0) >= 1
