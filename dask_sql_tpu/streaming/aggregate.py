"""streamed_aggregate: the morsel-shaped partial-state aggregation rung.

`CompiledAggregate` compiles a whole scan->filter->aggregate subtree into
one kernel whose output is the FINALIZED group table — which is exactly
wrong for partitioned execution: an avg/var finalized per chunk cannot be
combined.  This subclass keeps the parent's entire traced front half (the
shared `_trace_prelude` mask/gid body, the radix plan computed over the
FULL table so group ids are globally consistent across chunks, the same
`SegmentReducer` registrations) but emits the RAW segment reduction states
— hit counts, sums, counts, min/max contributions — as the kernel output.

Partition states then combine across the time axis with the same
elementwise sum/min/max algebra the SPMD rungs apply across the mesh axis
(spmd/aggregate.py psums/pmins/pmaxes the identical states): one combine
machinery, two axes.  The finalize arithmetic (avg = s/n, variance from
(n, s, s2), NULL = zero contributing rows) runs ONCE over the combined
global states and decodes through the parent's `_decode` — so a streamed
result is byte-identical to the single-launch rung whenever the partial
sums are exact (always for ints/counts/min/max; floats up to
addition-order rounding, the same caveat the SPMD rung carries).

One executable serves every chunk: chunks share a shape (partition.py), so
after the first launch every later launch — and every later query of the
family, ParamRefs included — replays the warm executable with zero
foreground compiles.  A repartition (halved chunks after an absorbed OOM)
re-specializes once per new shape.
"""
from __future__ import annotations

import logging
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..columnar.table import Table
from ..observability import trace_event
from ..physical.compiled import (
    CompiledAggregate,
    SegmentReducer,
    _extract_chain,
    _TableMeta,
    _TraceEval,
    _Unsupported,
    agg_argument,
    singleflight_get_or_build,
)
from ..planner import plan as p
from .partition import slice_chunk
from .plan import StreamDecision
from .runner import drive_partitions

logger = logging.getLogger(__name__)

#: elementwise combine per state kind — the time-axis twin of the SPMD
#: rung's psum/pmin/pmax collectives
_COMBINE = {"sum": jnp.add, "min": jnp.minimum, "max": jnp.maximum}


class StreamedAggregate(CompiledAggregate):
    """CompiledAggregate whose kernel emits combinable partial states.

    Constructed against the FULL table (the radix plan's integer-key
    bounds must cover every chunk), executed against fixed-shape chunks:
    `jax.jit` specializes the traced body per input shape, so all chunks
    of one partitioning share one executable."""

    def __init__(self, agg: p.Aggregate, table: Table, scan, filters,
                 group_exprs, agg_exprs):
        # combine ops / finalize plan are filled by _build (called from the
        # parent constructor); config=None pins segsum_mode "scatter" — the
        # only mode whose raw states combine elementwise, the same choice
        # the SPMD rung makes for its collectives
        self._combine_ops: List[str] = []
        self._finalize_plan: List[Tuple[str, List[int]]] = []
        super().__init__(agg, table, scan, filters, group_exprs, agg_exprs,
                         config=None)
        #: chunk shapes this executable already compiled for (the compile
        #: watchdog / zero-compile-span accounting hint)
        self._warm_shapes: set = set()

    def _build(self):
        ev = _TraceEval(_TableMeta(self.table))
        agg_exprs = self.agg_exprs
        domain = self.domain

        # static state layout: index 0 is the per-group hit count (group
        # presence across ALL partitions), then each aggregate's states in
        # order.  Decided before tracing so combine/finalize never depend
        # on trace-time objects.
        ops: List[str] = ["sum"]
        plan: List[Tuple[str, List[int]]] = []
        for a in agg_exprs:
            if a.func in ("count", "count_star"):
                plan.append((a.func, [_push(ops, "sum")]))
            elif a.func in ("sum", "avg"):
                plan.append((a.func, [_push(ops, "sum"),
                                      _push(ops, "sum")]))
            elif a.func in ("min", "max"):
                plan.append((a.func, [_push(ops, a.func),
                                      _push(ops, "sum")]))
            else:  # variance family: (s1, s2, count)
                plan.append((a.func, [_push(ops, "sum"), _push(ops, "sum"),
                                      _push(ops, "sum")]))
        self._combine_ops = ops
        self._finalize_plan = plan

        def fn(datas, valids, row_valid, params=()):
            slots, sel, gid, nr = self._trace_prelude(ev, datas, valids,
                                                      row_valid, params)
            reducer = SegmentReducer(gid, domain, "scatter", nr)
            arg_cache: Dict[Tuple, Tuple] = {}
            handles: List[Tuple[str, object]] = [
                ("cnt", reducer.count(sel))]
            for a in agg_exprs:
                ad, v = agg_argument(ev, slots, a, sel, arg_cache)
                cnt_h = reducer.count(v)
                if a.func in ("count", "count_star"):
                    handles.append(("cnt", cnt_h))
                    continue
                if a.func in ("sum", "avg"):
                    if ad.dtype == jnp.bool_:
                        h = reducer.sum_int(ad.astype(jnp.int32), v)
                    elif jnp.issubdtype(ad.dtype, jnp.integer):
                        h = reducer.sum_int(ad, v)
                    else:
                        h = reducer.sum_float(ad, v)
                    handles.append(("raw", h))
                    handles.append(("cnt", cnt_h))
                    continue
                if a.func in ("min", "max"):
                    if ad.dtype == jnp.bool_:
                        ad = ad.astype(jnp.int32)
                    if jnp.issubdtype(ad.dtype, jnp.floating):
                        fill = jnp.array(
                            jnp.inf if a.func == "min" else -jnp.inf,
                            dtype=ad.dtype)
                    else:
                        info = jnp.iinfo(ad.dtype)
                        fill = jnp.array(
                            info.max if a.func == "min" else info.min,
                            dtype=ad.dtype)
                    contrib = jnp.where(v, ad, fill)
                    h = (reducer.seg_min if a.func == "min"
                         else reducer.seg_max)(contrib)
                    handles.append(("raw", h))
                    handles.append(("cnt", cnt_h))
                    continue
                # variance family
                x = ad.astype(jnp.float64)
                handles.append(("raw", reducer.sum_float(x, v)))
                handles.append(("raw", reducer.sum_float(x * x, v)))
                handles.append(("cnt", cnt_h))
            reducer.finish()
            states = []
            for kind, h in handles:
                arr = reducer.get(h)
                if kind == "cnt":
                    # counts combine across an unbounded number of chunks:
                    # widen to int64 so the running total can never wrap
                    arr = arr.astype(jnp.int64)
                states.append(arr)
            return tuple(states)

        return fn

    # ----------------------------------------------------------- execution
    def run_partition(self, chunk: Table, params: Tuple = ()) -> Tuple:
        """Launch the morsel executable over one fixed-shape chunk; returns
        its raw partial-state tuple (device arrays, transfer-free)."""
        from ..observability import timed_jit_call

        datas = tuple(chunk.columns[n].data for n in chunk.column_names)
        valids = tuple(chunk.columns[n].validity
                       for n in chunk.column_names)
        shape = datas[0].shape[0] if datas else chunk.padded_rows
        states = timed_jit_call(
            "streamed_aggregate", self._fn, datas, valids, chunk.row_valid,
            tuple(params), may_compile=shape not in self._warm_shapes)
        self._warm_shapes.add(shape)
        return states

    def combine(self, acc: Optional[Sequence], states: Sequence) -> List:
        """Fold one partition's states into the running accumulator — the
        checkpointable partial-combine state a mid-stream recovery resumes
        from.  Elementwise on (domain,)-sized arrays: tiny, async, and
        identical in algebra to the SPMD collectives."""
        if acc is None:
            return list(states)
        return [_COMBINE[op](a, s)
                for op, a, s in zip(self._combine_ops, acc, states)]

    def finalize(self, acc: Sequence) -> Table:
        """Global finalize over the combined states: ONE host pull, the
        finalize arithmetic of `segment_agg_outputs` phase B in numpy, then
        the parent's `_decode` (group-key radix decode, output naming,
        zero-row global-aggregate semantics — literally shared code)."""
        from ..utils import count_d2h

        count_d2h()
        host = [np.asarray(x) for x in jax.device_get(tuple(acc))]
        hit = host[0]
        rows: List[np.ndarray] = [(hit != 0).astype(np.float64)]
        tags: List[Tuple[str, np.dtype]] = [("as", np.dtype(np.float64))]

        def emit(d: np.ndarray, v: np.ndarray) -> None:
            dt = np.dtype(d.dtype)
            if dt.kind in "iu" and dt.itemsize == 8:
                rows.append(np.ascontiguousarray(d).view(np.float64))
                tags.append(("bits", dt))
            else:
                rows.append(d.astype(np.float64))
                tags.append(("as", dt))
            rows.append(v.astype(np.float64))
            tags.append(("as", np.dtype(np.bool_)))

        for func, idxs in self._finalize_plan:
            # idxs are absolute state positions (index 0 is the hit count)
            st = [host[i] for i in idxs]
            if func in ("count", "count_star"):
                cnt = st[0]
                emit(cnt, np.ones_like(cnt, dtype=bool))
            elif func == "sum":
                s, cnt = st
                emit(s, cnt > 0)
            elif func == "avg":
                s, cnt = st
                emit(s.astype(np.float64) / np.maximum(cnt, 1), cnt > 0)
            elif func in ("min", "max"):
                red, cnt = st
                ok = cnt > 0
                emit(np.where(ok, red, np.zeros(1, dtype=red.dtype)), ok)
            else:  # variance family from (s1, s2, count)
                s1, s2, cnt = (st[0].astype(np.float64),
                               st[1].astype(np.float64), st[2])
                ddof = 1 if func.endswith("samp") else 0
                mean = s1 / np.maximum(cnt, 1)
                var = (np.maximum(s2 - cnt * mean * mean, 0.0)
                       / np.maximum(cnt - ddof, 1))
                out = np.sqrt(var) if func.startswith("stddev") else var
                emit(out, cnt > ddof)
        matrix = np.stack(rows, axis=0)
        present = np.nonzero(hit != 0)[0]
        return self._decode(matrix[:, present], present, tags)


def _push(ops: List[str], op: str) -> int:
    ops.append(op)
    return len(ops) - 1


# bounded cache of streamed morsel executables, keyed like the compiled
# aggregate cache plus nothing chunk-specific: ONE object serves every
# partitioning of a family (jit re-specializes per chunk shape), so the
# second streamed run of a family replays warm executables
_CACHE_CAP = 8
_cache: "OrderedDict[Tuple, StreamedAggregate]" = OrderedDict()


def reset_cache() -> None:
    """Tests: drop cached morsel executables (warm-shape state included)."""
    _cache.clear()


def try_streamed_aggregate(rel: p.Aggregate, executor) -> Optional[Table]:
    """The streamed_aggregate ladder rung: fires only for plans the
    admission layer routed to streaming (this execution's
    ``executor.stream_decisions`` entry); None declines down the ladder
    like every rung."""
    decision: Optional[StreamDecision] = \
        executor.stream_decisions.get(id(rel))
    if decision is None or decision.kind != "aggregate":
        return None
    config = executor.config
    if not config.get("serving.stream.enabled", True):
        return None
    if not config.get("sql.compile", True):
        return None
    chain = _extract_chain(rel)
    if chain is None:
        return None
    scan, filters, group_exprs, agg_exprs = chain
    ctx = executor.context
    # -- eligibility + morsel-executable build ----------------------------
    # construction-time ineligibility (a shape the static routing walk
    # could not rule out — e.g. an integer radix span only device data
    # reveals, or a trace-unsupported filter expression) RE-SHEDS with the
    # gate's 429: the alternative, declining down the ladder, runs the
    # full provably-over-budget working set single-launch
    try:
        dc = ctx.schema[scan.schema_name].tables.get(scan.table_name)
        if dc is None:
            return None
        table = executor.get_table(scan.schema_name, scan.table_name)
        if scan.projection is not None:
            table = table.select(scan.projection)
        if table.row_valid is not None:
            return None  # padded/sharded storage: not this rung's shape
        from .. import families

        pz = families.pipeline_parameterizer(config)
        filters = [pz.rewrite(f) for f in filters]
        agg_exprs = [pz.rewrite_agg(a) for a in agg_exprs]
        params = pz.params
        key = (
            "streamed_aggregate",
            dc.uid,
            scan.schema_name, scan.table_name,
            tuple(scan.projection or ()),
            tuple(str(f) for f in filters),
            tuple(str(e) for e in group_exprs),
            tuple(str(a) for a in agg_exprs),
            table.num_rows,
        )

        def build():
            obj = StreamedAggregate(rel, table, scan, filters, group_exprs,
                                    agg_exprs)
            obj.table = None  # never pin the construction table's HBM
            with ctx._plan_lock:
                _cache[key] = obj
                while len(_cache) > _CACHE_CAP:
                    _cache.popitem(last=False)
            return obj

        compiled, built_here = singleflight_get_or_build(ctx, _cache, key,
                                                         build)
    except (_Unsupported, ValueError, TypeError, NotImplementedError) as e:
        from .plan import shed_ineligible

        shed_ineligible(decision, ctx.metrics, reason=str(e))
        raise  # unreachable: shed_ineligible always raises
    if compiled is None:
        return None
    if not built_here and params:
        ctx.metrics.inc("families.hit")
        trace_event("family_hit", rung="streamed_aggregate",
                    params=len(params))
    ctx.metrics.inc("serving.stream.queries")
    # -- pipelined partition drive ----------------------------------------
    # failures in here keep the ladder's semantics: transient errors retry,
    # degradable OOM repartitions/resumes, exhaustion degrades the rung
    acc: List[Optional[List]] = [None]

    def launch(lo: int, chunk_rows: int) -> None:
        chunk = slice_chunk(table, lo, chunk_rows)
        states = compiled.run_partition(chunk, params)
        acc[0] = compiled.combine(acc[0], states)

    launches = drive_partitions(executor, decision, launch,
                                "streamed_aggregate")
    trace_event("rung:streamed_aggregate", rung="streamed_aggregate",
                partitions=launches, chunk_rows=decision.chunk_rows)
    return compiled.finalize(acc[0])
