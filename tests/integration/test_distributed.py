"""Multi-device collective tests on the virtual 8-device CPU mesh.

Parity: the analogue of the reference's DASK_SQL_DISTRIBUTED_TESTS switch
(tests/utils.py:8-12 there) — the same kernels the driver dry-runs multichip.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp


@pytest.fixture(scope="module")
def mesh():
    from dask_sql_tpu.parallel.mesh import make_mesh

    n = min(8, len(jax.devices()))
    if n < 2:
        pytest.skip("virtual multi-device mesh unavailable in this environment")
    return make_mesh(n)


def test_mesh_has_multiple_devices(mesh):
    assert mesh.devices.size >= 2


def test_dist_groupby(mesh):
    from dask_sql_tpu.parallel import collectives as coll
    from dask_sql_tpu.parallel.mesh import shard_rows

    ndev = mesh.devices.size
    rng = np.random.RandomState(0)
    n = 64 * ndev
    keys_np = rng.randint(0, 10, n).astype(np.int64)
    vals_np = rng.rand(n)
    keys = shard_rows(jnp.asarray(keys_np), mesh)
    vals = shard_rows(jnp.asarray(vals_np), mesh)
    valid = shard_rows(jnp.ones(n, dtype=bool), mesh)
    kernel = coll.make_dist_groupby(mesh, capacity=64)
    fk, fv, fstates, overflow = kernel(keys, vals, valid)
    assert not bool(np.asarray(overflow).any())
    k, cnt, s, mn, mx, mean, var = coll.finalize_states(fk, fv, fstates)
    # compare against numpy groupby
    exp_keys = np.unique(keys_np)
    assert list(k) == list(exp_keys)
    for i, key in enumerate(exp_keys):
        sel = vals_np[keys_np == key]
        assert cnt[i] == len(sel)
        np.testing.assert_allclose(s[i], sel.sum())
        np.testing.assert_allclose(mn[i], sel.min())
        np.testing.assert_allclose(mx[i], sel.max())


def test_hash_shuffle_routes_all_rows(mesh):
    from dask_sql_tpu.parallel import collectives as coll
    from dask_sql_tpu.parallel.mesh import shard_rows

    ndev = mesh.devices.size
    rng = np.random.RandomState(1)
    n = 32 * ndev
    keys_np = rng.randint(0, 1000, n).astype(np.int64)
    payload_np = np.stack([np.arange(n, dtype=np.float64)], axis=1)
    keys = shard_rows(jnp.asarray(keys_np), mesh)
    payload = shard_rows(jnp.asarray(payload_np), mesh)
    valid = shard_rows(jnp.ones(n, dtype=bool), mesh)
    shuffle = coll.make_hash_shuffle(mesh, capacity_per_peer=64)
    rk, rv, rp, overflow = shuffle(keys, payload, valid)
    assert not bool(np.asarray(overflow).any())
    rk_np = np.asarray(rk).reshape(ndev, -1)
    rv_np = np.asarray(rv).reshape(ndev, -1)
    # every row arrives exactly once, on the right device
    received = []
    for dev in range(ndev):
        got = rk_np[dev][rv_np[dev]]
        assert ((got % ndev) == dev).all()
        received.extend(got.tolist())
    assert sorted(received) == sorted(keys_np.tolist())
    # payload follows its key
    rp_np = np.asarray(rp).reshape(ndev, -1, 1)
    for dev in range(ndev):
        rows = rp_np[dev][rv_np[dev], 0].astype(int)
        for row_idx, key in zip(rows, rk_np[dev][rv_np[dev]]):
            assert keys_np[row_idx] == key


def test_dist_join_count(mesh):
    from dask_sql_tpu.parallel import collectives as coll
    from dask_sql_tpu.parallel.mesh import shard_rows

    ndev = mesh.devices.size
    rng = np.random.RandomState(2)
    nl, nr = 16 * ndev, 24 * ndev
    lk_np = rng.randint(0, 20, nl).astype(np.int64)
    rk_np = rng.randint(0, 20, nr).astype(np.int64)
    lk = shard_rows(jnp.asarray(lk_np), mesh)
    rk = shard_rows(jnp.asarray(rk_np), mesh)
    lv = shard_rows(jnp.ones(nl, dtype=bool), mesh)
    rv = shard_rows(jnp.ones(nr, dtype=bool), mesh)
    kernel = coll.make_dist_join_count(mesh, capacity_per_peer=256)
    counts, totals, overflow = kernel(lk, lv, rk, rv)
    assert not bool(np.asarray(overflow).any())
    expected_total = sum((rk_np == k).sum() for k in lk_np)
    assert int(np.asarray(totals).sum()) == expected_total


def test_broadcast_join_count(mesh):
    from dask_sql_tpu.parallel import collectives as coll
    from dask_sql_tpu.parallel.mesh import shard_rows

    ndev = mesh.devices.size
    rng = np.random.RandomState(3)
    n_probe, n_build = 64 * ndev, 8 * ndev
    pk_np = rng.randint(0, 30, n_probe).astype(np.int64)
    bk_np = rng.randint(0, 30, n_build).astype(np.int64)
    pk = shard_rows(jnp.asarray(pk_np), mesh)
    bk = shard_rows(jnp.asarray(bk_np), mesh)
    pv = shard_rows(jnp.ones(n_probe, dtype=bool), mesh)
    bv = shard_rows(jnp.ones(n_build, dtype=bool), mesh)
    kernel = coll.make_broadcast_join_count(mesh)
    counts = kernel(pk, pv, bk, bv)
    expected = np.array([(bk_np == k).sum() for k in pk_np])
    np.testing.assert_array_equal(np.asarray(counts), expected)
