"""JDBC metadata emulation.

Parity: reference server/presto_jdbc.py — a `system_jdbc` schema holding
`schemas`/`tables`/`columns` frames with the standard JDBC
DatabaseMetaData column sets (getSchemas/getTables/getColumns), so JDBC
drivers and DB tools (DBeaver) can introspect.  The driver queries
`system.jdbc`; the statement endpoint rewrites it to `system_jdbc`
(reference app.py:78-82) since catalogs aren't supported.
"""
from __future__ import annotations

import logging

import pandas as pd

logger = logging.getLogger(__name__)

SYSTEM_SCHEMA = "system_jdbc"


def adjust_for_presto_sql(sql: str) -> str:
    """Rewrites the unsupported `system` catalog to the metadata schema
    (parity: reference app.py:78-82)."""
    return sql.replace("system.jdbc", SYSTEM_SCHEMA)


def create_meta_data(context) -> None:
    if context is None:
        logger.warning("Context None: jdbc meta data not created")
        return
    catalog = ""
    context.create_schema(SYSTEM_SCHEMA)

    schema_rows = []
    table_rows = []
    column_rows = []
    for schema_name, schema in context.schema.items():
        schema_rows.append(create_schema_row(catalog, schema_name))
        for table_name, dc in schema.tables.items():
            table_rows.append(create_table_row(catalog, schema_name, table_name))
            for pos, (col, c) in enumerate(dc.table.columns.items(), start=1):
                column_rows.append(create_column_row(
                    catalog, schema_name, table_name, str(c.sql_type.value),
                    col, str(pos), "YES" if c.validity is not None else "NO"))

    schemas = (pd.DataFrame(schema_rows) if schema_rows
               else pd.DataFrame(create_schema_row(), index=[0]))
    context.create_table("schemas", schemas, schema_name=SYSTEM_SCHEMA)
    tables = (pd.DataFrame(table_rows) if table_rows
              else pd.DataFrame(create_table_row(), index=[0]))
    context.create_table("tables", tables, schema_name=SYSTEM_SCHEMA)
    columns = (pd.DataFrame(column_rows) if column_rows
               else pd.DataFrame(create_column_row(), index=[0]))
    context.create_table("columns", columns, schema_name=SYSTEM_SCHEMA)
    logger.info("jdbc meta data ready for %d tables", len(table_rows))


def create_catalog_row(catalog: str = ""):
    return {"TABLE_CAT": catalog}


def create_schema_row(catalog: str = "", schema: str = ""):
    return {"TABLE_CATALOG": catalog, "TABLE_SCHEM": schema}


def create_table_row(catalog: str = "", schema: str = "", table: str = ""):
    # the JDBC DatabaseMetaData.getTables() result-set columns
    return {
        "TABLE_CAT": catalog,
        "TABLE_SCHEM": schema,
        "TABLE_NAME": table,
        "TABLE_TYPE": "TABLE",
        "REMARKS": "",
        "TYPE_CAT": "",
        "TYPE_SCHEM": "",
        "TYPE_NAME": "",
        "SELF_REFERENCING_COL_NAME": "",
        "REF_GENERATION": "",
    }


def create_column_row(catalog: str = "", schema: str = "", table: str = "",
                      dtype: str = "", column: str = "", pos: str = "",
                      nullable: str = ""):
    # the JDBC DatabaseMetaData.getColumns() result-set columns
    return {
        "TABLE_CAT": catalog,
        "TABLE_SCHEM": schema,
        "TABLE_NAME": table,
        "COLUMN_NAME": column,
        "DATA_TYPE": dtype,
        "TYPE_NAME": dtype,
        "COLUMN_SIZE": "",
        "BUFFER_LENGTH": "",
        "DECIMAL_DIGITS": "",
        "NUM_PREC_RADIX": "",
        "NULLABLE": "",
        "REMARKS": "",
        "COLUMN_DEF": "",
        "SQL_DATA_TYPE": dtype,
        "SQL_DATETIME_SUB": "",
        "CHAR_OCTET_LENGTH": "",
        "ORDINAL_POSITION": pos,
        "IS_NULLABLE": nullable,
        "SCOPE_CATALOG": "",
        "SCOPE_SCHEMA": "",
        "SCOPE_TABLE": "",
        "SOURCE_DATA_TYPE": "",
        "IS_AUTOINCREMENT": "",
        "IS_GENERATEDCOLUMN": "",
    }
