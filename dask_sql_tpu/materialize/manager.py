"""MaterializationManager: the decide-and-act half of semantic reuse.

Three answering tiers sit above the exact-match result cache, all owned by
this manager (one per Context, ``context.materialize``):

1. **Sub-plan materialization** — the observe→decide→act loop over plan
   *prefixes*: every executed query's scan->filter stem is fingerprinted
   (`families.compute_stem`); a stem observed ``serving.materialize
   .min_hits`` times whose estimator byte floor fits the
   ``serving.materialize.max_bytes`` budget is pinned as a device-resident
   table (one interpreted pass, zero compiles).  Incoming plans whose stem
   matches are rewritten to scan the pinned table instead — the stem's
   filters never re-execute, the base table is never re-scanned, and every
   node of the rewritten copy carries ``_dsql_skip_rungs`` for ALL
   compiled rungs (the compiled pipelines resolve tables through the
   catalog and would silently compute over the UNFILTERED base table).
   Pinned bytes are charged to the HBM ledger's ``materialized`` component.
2. **Subsumption answering** — cached results register as candidates per
   family; a new query whose parameter intervals are provably contained in
   a candidate's (materialize/subsume.py over the estimator's interval
   algebra) is served by re-filtering the cached result.  The candidate's
   cache key must match the incoming key in every part except the
   parameter values — catalog epochs, table uids and config all live in
   the key, so a stale candidate can never serve.
3. **Incremental maintenance** — materialize/incremental.py: streamed
   combine states folded forward on `Context.append_rows`.

Everything here is advisory: any internal failure falls back to normal
execution (`try_*` returns None), never a wrong answer or a failed query.
"""
from __future__ import annotations

import dataclasses
import logging
import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

from ..columnar.table import Table
from ..observability import flight
from ..planner import plan as p
from . import subsume
from .incremental import IncrementalStates

logger = logging.getLogger(__name__)

#: every ladder rung that resolves tables through the catalog (or keys a
#: compiled executable on catalog state) instead of the executor's
#: `table_overrides`.  A stem-rewritten plan MUST skip all of them: its
#: TableScan carries stripped filters whose effect lives only in the
#: override table, so a catalog-resolving rung would compute over the
#: unfiltered base rows.  The interpreted walk honors overrides.
CATALOG_RESOLVING_RUNGS = frozenset({
    "compiled_predict", "streamed_select", "spmd_select", "compiled_select",
    "streamed_aggregate", "spmd_join_aggregate", "spmd_aggregate",
    "compiled_join_aggregate", "compiled_aggregate", "dist_aggregate",
    "dist_sort",
})

#: subsumption candidates retained per family (newest win: dashboards
#: re-issue the widest filters periodically, so recency tracks utility)
_CANDIDATES_PER_FAMILY = 8

#: stem hit counters retained (observation state, not pinned bytes)
_MAX_STEM_COUNTERS = 256


@dataclasses.dataclass
class _PinnedStem:
    """One device-resident materialized stem."""

    table: Table
    nbytes: int
    schema_name: str
    table_name: str
    uid: int                     # base DataContainer identity
    epoch: int                   # base table delta epoch at (re)build
    stem_plan: p.LogicalPlan     # literal-baked stem subtree (for refresh)
    fingerprint: str
    hits: int = 0


@dataclasses.dataclass
class _Candidate:
    """One cached result registered for subsumption answering."""

    key: Tuple                   # its exact result-cache key
    values: Tuple                # its parameter vector
    spec: subsume.SubsumeSpec
    deps: frozenset              # (schema, table) provenance


class MaterializationManager:
    """Per-Context semantic reuse: stems, subsumption, incremental."""

    def __init__(self, context):
        self.context = context
        self._lock = threading.RLock()
        #: (stem fingerprint, key_values) -> hit count (pre-pin observation)
        self._stem_hits: "OrderedDict[Tuple, int]" = OrderedDict()
        #: (stem fingerprint, key_values) -> pinned stem, LRU by last hit
        self._pinned: "OrderedDict[Tuple, _PinnedStem]" = OrderedDict()
        #: stems that failed the byte policy — never re-executed per query
        self._rejected: set = set()
        #: family fingerprint -> key_values -> candidate (LRU per family)
        self._subsume: Dict[str, "OrderedDict[Tuple, _Candidate]"] = {}
        self.incremental = IncrementalStates(context)

    # ------------------------------------------------------------- config
    def _cfg(self, key: str, default):
        return self.context.config.get(key, default)

    def enabled(self) -> bool:
        return bool(self._cfg("serving.materialize.enabled", True))

    def subsumption_enabled(self) -> bool:
        return bool(self._cfg("serving.reuse.subsumption", True))

    # -------------------------------------------------------- ledger input
    def pinned_bytes(self) -> int:
        """Device bytes of every pinned stem — the ledger's
        ``materialized`` component (observability/ledger.py)."""
        with self._lock:
            return sum(e.nbytes for e in self._pinned.values())

    def reclaim_bytes(self, bytes_needed: Optional[int] = None) -> int:
        """Pressure reclaim (resilience/pressure.py tier 2): evict
        LRU-coldest pinned stems until at least ``bytes_needed`` are freed
        (``None`` = drop every pin); returns bytes actually freed.  A
        dropped stem just re-pins once traffic re-earns its hit count."""
        freed = 0
        with self._lock:
            while self._pinned and (bytes_needed is None
                                    or freed < bytes_needed):
                key = next(iter(self._pinned))
                freed += self._pinned[key].nbytes
                self._evict_locked(key, "pressure")
        return freed

    # ===================================================== answering tiers
    def try_reuse(self, plan: p.LogicalPlan, family,
                  key: Optional[Tuple]) -> Optional[Tuple[Table, str]]:
        """The semantic answering tiers, tried after an exact-cache miss:
        (table, tier) or None.  `key` is the query's exact cache key."""
        if family is None or key is None:
            return None
        out = self._try_incremental(plan, family)
        if out is not None:
            return out, "incremental"
        out = self._try_subsumption(plan, family, key)
        if out is not None:
            return out, "subsumption"
        return None

    def _try_incremental(self, plan, family) -> Optional[Table]:
        try:
            out = self.incremental.answer(plan, family)
        except Exception:  # dsql: allow-broad-except — advisory reuse tier
            logger.debug("incremental answer failed", exc_info=True)
            return None
        if out is not None:
            self.context.metrics.inc("serving.reuse.incremental.hits")
            flight.record("materialize.hit", tier="incremental",
                          fingerprint=family.fingerprint)
        return out

    def _try_subsumption(self, plan, family, key) -> Optional[Table]:
        if not self.subsumption_enabled():
            return None
        metrics = self.context.metrics
        with self._lock:
            slot = self._subsume.get(family.fingerprint)
            candidates = list(reversed(slot.items())) if slot else []
        tried = False
        for values, cand in candidates:
            if values == family.key_values:
                continue  # identical query: the exact cache already missed
            # every key part except the parameter vector (slot 2) must
            # match — epochs, uids and config ride the key, so staleness
            # and config drift fail closed here
            if cand.key[:2] != key[:2] or cand.key[3:] != key[3:]:
                continue
            tried = True
            if not subsume.contains(cand.spec, values, family.key_values):
                continue
            cached = self.context._result_cache.get(cand.key)
            if cached is None:
                with self._lock:
                    slot = self._subsume.get(family.fingerprint)
                    if slot is not None:
                        slot.pop(values, None)
                continue
            try:
                served = subsume.serve(cached, cand.spec, family.key_values)
            except Exception:  # dsql: allow-broad-except — advisory tier
                logger.debug("subsumption serve failed", exc_info=True)
                served = None
            if served is None:
                continue
            metrics.inc("serving.reuse.subsumption.hits")
            flight.record("materialize.hit", tier="subsumption",
                          fingerprint=family.fingerprint)
            return served
        if tried:
            metrics.inc("serving.reuse.subsumption.declined")
        return None

    # ======================================================== stem rewrite
    def try_stem_rewrite(self, plan: p.LogicalPlan
                         ) -> Optional[Tuple[p.LogicalPlan, Dict]]:
        """(rewritten plan copy, executor table overrides) scanning a
        pinned stem instead of the base table, or None.  The copy's nodes
        all carry `_dsql_skip_rungs` = `CATALOG_RESOLVING_RUNGS` — the
        interpreted walk is the only path that honors the override."""
        if not self.enabled():
            return None
        from .. import families

        try:
            si = families.compute_stem(plan)
        except Exception:  # dsql: allow-broad-except — advisory analysis
            logger.debug("stem fingerprint failed", exc_info=True)
            return None
        if si is None:
            return None
        stem, scan, info = si.stem, si.scan, si.info
        key = (info.fingerprint, info.key_values)
        ctx = self.context
        with self._lock:
            entry = self._pinned.get(key)
            if entry is None:
                return None
            container = ctx.schema.get(entry.schema_name)
            dc = container.tables.get(entry.table_name) if container else None
            if dc is None or dc.uid != entry.uid or entry.epoch != \
                    ctx.table_epoch(entry.schema_name, entry.table_name):
                self._evict_locked(key, "stale")
                return None
            entry.hits += 1
            self._pinned.move_to_end(key)
            pinned_table = entry.table
        try:
            copy = _copy_replacing(plan, stem,
                                   dataclasses.replace(scan, filters=[]))
        except Exception:  # dsql: allow-broad-except — an uncopyable node
            # shape simply keeps the normal execution path
            logger.debug("stem plan rewrite failed", exc_info=True)
            return None
        self.context.metrics.inc("serving.materialize.hits")
        flight.record("materialize.hit", tier="stem",
                      fingerprint=info.fingerprint)
        return copy, {(scan.schema_name, scan.table_name): pinned_table}

    # ========================================================= observation
    def observe(self, plan: p.LogicalPlan, family, key: Optional[Tuple],
                deps, result: Table) -> None:
        """Post-execution hook (cache-miss path): count the stem, register
        the result as a subsumption candidate, register the aggregate for
        incremental capture.  Advisory — failures are swallowed."""
        if key is None:
            return  # volatile / uncacheable queries must never seed reuse
        try:
            if self.enabled():
                self._observe_stem(plan)
            if self.subsumption_enabled() and family is not None:
                spec = subsume.analyze(plan, family)
                if spec is not None:
                    with self._lock:
                        slot = self._subsume.setdefault(
                            family.fingerprint, OrderedDict())
                        slot.pop(family.key_values, None)
                        slot[family.key_values] = _Candidate(
                            key, family.key_values, spec,
                            frozenset(deps or ()))
                        while len(slot) > _CANDIDATES_PER_FAMILY:
                            slot.popitem(last=False)
            self.incremental.register(plan, family)
        except Exception:  # dsql: allow-broad-except — observation must
            # never fail the query that just succeeded
            logger.debug("materialize observation failed", exc_info=True)

    def _observe_stem(self, plan: p.LogicalPlan) -> None:
        from .. import families

        si = families.compute_stem(plan)
        if si is None:
            return
        key = (si.info.fingerprint, si.info.key_values)
        with self._lock:
            if key in self._pinned or key in self._rejected:
                return
            hits = self._stem_hits.get(key, 0) + 1
            self._stem_hits[key] = hits
            self._stem_hits.move_to_end(key)
            while len(self._stem_hits) > _MAX_STEM_COUNTERS:
                self._stem_hits.popitem(last=False)
            if hits < int(self._cfg("serving.materialize.min_hits", 2)):
                return
        pressure = getattr(self.context, "pressure", None)
        if pressure is not None and pressure.suspend_speculative():
            # YELLOW band (resilience/pressure.py): a new pin is
            # speculative HBM growth — skip it.  The earned hit count
            # stays, so the next observation under GREEN pins immediately.
            self.context.metrics.inc("resilience.pressure.suspended")
            return
        # the pin's ledger charge is custodied by the manager: pressure
        # reclaim and staleness eviction release it via _evict_locked
        # dsql: allow-unpaired-effect — policy-driven eviction custody
        self._pin(si, key)

    def _pin(self, si, key) -> None:
        """Decide-and-act: estimator floor gate, one interpreted execution
        of the FULL-WIDTH stem (every table column, so any sibling's
        projection serves from the pinned rows), byte policy, LRU
        admission."""
        from .. import families

        ctx = self.context
        metrics = ctx.metrics
        scan, info = si.scan, si.info
        max_bytes = int(self._cfg("serving.materialize.max_bytes",
                                  128 << 20))
        min_bytes = int(self._cfg("serving.materialize.min_bytes", 1024))
        container = ctx.schema.get(scan.schema_name)
        dc = container.tables.get(scan.table_name) if container else None
        if dc is None:
            return
        from ..datacontainer import LazyParquetContainer

        if isinstance(dc, LazyParquetContainer):
            return  # file-backed rows can change without a catalog bump
        if dc.table.row_valid is not None:
            return  # padded/sharded storage belongs to the SPMD rungs
        exec_stem = families.full_width_stem(si, dc.table)
        if exec_stem is None:
            metrics.inc("serving.materialize.declined")
            with self._lock:
                self._rejected.add(key)
            return
        # estimator floor: a stem whose PROVABLE result bytes already
        # exceed the budget must not even execute the pin pass
        try:
            from ..analysis.estimator import estimate_plan

            est = estimate_plan(exec_stem, context=ctx)
            if est.result_bytes.lo > max_bytes:
                metrics.inc("serving.materialize.declined")
                with self._lock:
                    self._rejected.add(key)
                return
        except Exception:  # dsql: allow-broad-except — the estimate is a
            # pre-gate; the post-execution byte check below still enforces
            logger.debug("stem estimate failed", exc_info=True)
        try:
            from ..physical.executor import Executor

            table = Executor(ctx).execute(exec_stem)
        except Exception:  # dsql: allow-broad-except — a failed pin pass
            # must never surface into the query that triggered it
            logger.debug("stem pin execution failed", exc_info=True)
            metrics.inc("serving.materialize.declined")
            return
        from ..serving.cache import table_nbytes

        nbytes = table_nbytes(table)
        if nbytes < min_bytes or nbytes > max_bytes:
            metrics.inc("serving.materialize.declined")
            with self._lock:
                self._rejected.add(key)
            return
        epoch = ctx.table_epoch(scan.schema_name, scan.table_name)
        with self._lock:
            self._stem_hits.pop(key, None)
            self._pinned[key] = _PinnedStem(
                table=table, nbytes=nbytes, schema_name=scan.schema_name,
                table_name=scan.table_name, uid=dc.uid, epoch=epoch,
                stem_plan=exec_stem, fingerprint=info.fingerprint)
            while sum(e.nbytes for e in self._pinned.values()) > max_bytes \
                    and len(self._pinned) > 1:
                old_key = next(iter(self._pinned))
                self._evict_locked(old_key, "pressure")
        metrics.inc("serving.materialize.stored")
        flight.record("materialize.store", fingerprint=info.fingerprint,
                      table=f"{scan.schema_name}.{scan.table_name}",
                      bytes=nbytes)

    def _evict_locked(self, key, reason: str) -> None:
        # caller holds the lock (self-lint DSQL201 *_locked convention)
        entry = self._pinned.pop(key, None)
        if entry is None:
            return
        self.context.metrics.inc("serving.materialize.evicted")
        flight.record("materialize.evict", fingerprint=entry.fingerprint,
                      reason=reason, bytes=entry.nbytes)

    # ======================================================== maintenance
    def on_append(self, schema_name: str, table_name: str, dc,
                  old_rows: int, epoch: int) -> None:
        """Append notification (Context.append_rows): refresh dependent
        pinned stems over ONLY the delta slice, fold incremental states."""
        tkey = (schema_name, table_name)
        new_rows = int(dc.table.num_rows)
        delta_rows = new_rows - old_rows
        with self._lock:
            targets = [(k, e) for k, e in self._pinned.items()
                       if (e.schema_name, e.table_name) == tkey]
            for key, entry in targets:
                if entry.uid != dc.uid or delta_rows < 0:
                    self._evict_locked(key, "append")
                    continue
                try:
                    if delta_rows > 0:
                        from ..physical.executor import Executor

                        ex = Executor(self.context)
                        ex.table_overrides[tkey] = \
                            dc.table.slice(old_rows, new_rows)
                        part = ex.execute(entry.stem_plan)
                        entry.table = Table.concat([entry.table, part])
                        from ..serving.cache import table_nbytes

                        entry.nbytes = table_nbytes(entry.table)
                    entry.epoch = epoch
                    self.context.metrics.inc("serving.materialize.refreshed")
                    flight.record("materialize.refresh",
                                  fingerprint=entry.fingerprint,
                                  table=f"{schema_name}.{table_name}",
                                  delta_rows=delta_rows)
                except Exception:  # dsql: allow-broad-except — a failed
                    # refresh evicts (the next query re-pins); it must not
                    # fail the append
                    logger.debug("stem refresh failed; evicting",
                                 exc_info=True)
                    self._evict_locked(key, "refresh_failed")
        self.incremental.on_append(schema_name, table_name, dc, old_rows,
                                   epoch)

    def invalidate_tables(self, tables) -> int:
        """Targeted invalidation (replace / drop / non-append DDL): evict
        exactly the state depending on these (schema, table) names."""
        targets = set(tables)
        n = 0
        with self._lock:
            for key in [k for k, e in self._pinned.items()
                        if (e.schema_name, e.table_name) in targets]:
                self._evict_locked(key, "invalidated")
                n += 1
            for fam, slot in list(self._subsume.items()):
                for values in [v for v, c in slot.items()
                               if c.deps & targets or not c.deps]:
                    del slot[values]
                    n += 1
                if not slot:
                    del self._subsume[fam]
        n += self.incremental.invalidate_tables(targets)
        return n

    def invalidate_all(self) -> int:
        with self._lock:
            n = len(self._pinned)
            for key in list(self._pinned):
                self._evict_locked(key, "invalidated")
            n += sum(len(s) for s in self._subsume.values())
            self._subsume.clear()
            self._stem_hits.clear()
            self._rejected.clear()
        n += self.incremental.invalidate_all()
        return n

    # ------------------------------------------------------------- surface
    def rows(self) -> List[Tuple[str, str, str, int, int, int, int]]:
        """``SHOW MATERIALIZED`` rows: (kind, fingerprint, table, rows,
        bytes, hits, epoch) — pinned stems then incremental states."""
        out: List[Tuple[str, str, str, int, int, int, int]] = []
        with self._lock:
            for entry in self._pinned.values():
                out.append(("stem", entry.fingerprint,
                            f"{entry.schema_name}.{entry.table_name}",
                            int(entry.table.num_rows), entry.nbytes,
                            entry.hits, entry.epoch))
        for fp, sname, tname, rows, epoch, hits in self.incremental.rows():
            out.append(("incremental", fp, f"{sname}.{tname}", rows, 0,
                        hits, epoch))
        return out


def _copy_replacing(node: p.LogicalPlan, target: p.LogicalPlan,
                    replacement: p.LogicalPlan) -> p.LogicalPlan:
    """Deep structural copy of ``node`` with the ``target`` subtree (by
    identity) swapped for ``replacement``, and EVERY copied node tagged to
    skip the catalog-resolving rungs.  Plans in the plan cache are shared
    across concurrent executions — the rewrite must never mutate or tag
    the original nodes."""
    if node is target:
        out = replacement
    else:
        kids = node.inputs()
        if kids:
            out = node.with_inputs([_copy_replacing(c, target, replacement)
                                    for c in kids])
        else:
            out = dataclasses.replace(node)
    if out is node:
        raise ValueError("plan node copy returned the shared original")
    out._dsql_skip_rungs = frozenset(
        getattr(node, "_dsql_skip_rungs", frozenset())
    ) | CATALOG_RESOLVING_RUNGS
    return out
