"""Join tests (parity: reference test_join.py)."""
import numpy as np
import pandas as pd
import pytest

from tests.utils import assert_eq


def test_join(c, user_table_1, user_table_2):
    result = c.sql(
        "SELECT lhs.user_id, lhs.b, rhs.c FROM user_table_1 AS lhs "
        "JOIN user_table_2 AS rhs ON lhs.user_id = rhs.user_id"
    ).compute()
    expected = user_table_1.merge(user_table_2, on="user_id")[["user_id", "b", "c"]]
    assert_eq(result, expected, check_dtype=False, sort_results=True)

def test_join_inner_sides(c, user_table_1, user_table_2):
    result = c.sql(
        "SELECT lhs.user_id, lhs.b, rhs.c FROM user_table_1 AS lhs "
        "INNER JOIN user_table_2 AS rhs ON lhs.user_id = rhs.user_id"
    ).compute()
    assert len(result) == 4  # user 1 x2, user 2 x2

def test_join_left(c, user_table_1, user_table_2):
    result = c.sql(
        "SELECT lhs.user_id, lhs.b, rhs.c FROM user_table_1 AS lhs "
        "LEFT JOIN user_table_2 AS rhs ON lhs.user_id = rhs.user_id"
    ).compute()
    expected = user_table_1.merge(user_table_2, on="user_id", how="left")[["user_id", "b", "c"]]
    assert_eq(result, expected, check_dtype=False, sort_results=True)

def test_join_right(c, user_table_1, user_table_2):
    result = c.sql(
        "SELECT rhs.user_id, lhs.b, rhs.c FROM user_table_1 AS lhs "
        "RIGHT JOIN user_table_2 AS rhs ON lhs.user_id = rhs.user_id"
    ).compute()
    expected = user_table_1.merge(user_table_2, on="user_id", how="right")[["user_id", "b", "c"]]
    assert_eq(result, expected, check_dtype=False, sort_results=True)

def test_join_full(c, user_table_1, user_table_2):
    result = c.sql(
        "SELECT lhs.user_id AS l_id, rhs.user_id AS r_id, lhs.b, rhs.c "
        "FROM user_table_1 AS lhs FULL JOIN user_table_2 AS rhs "
        "ON lhs.user_id = rhs.user_id"
    ).compute()
    # users 1(x2 right),2(x2 left),3 left-only,4 right-only
    assert len(result) == 4 + 1 + 1  # 1x2 + 2x2 matched = 4? recompute below
    expected = user_table_1.merge(user_table_2, on="user_id", how="outer")
    assert len(result) == len(expected)

def test_join_cross(c, user_table_1, df_simple):
    result = c.sql("SELECT * FROM user_table_1, df_simple").compute()
    assert len(result) == len(user_table_1) * len(df_simple)

def test_join_comma_filter(c, user_table_1, user_table_2):
    result = c.sql(
        "SELECT lhs.user_id, rhs.c FROM user_table_1 lhs, user_table_2 rhs "
        "WHERE lhs.user_id = rhs.user_id AND rhs.c > 1"
    ).compute()
    expected = user_table_1.merge(user_table_2, on="user_id")
    expected = expected[expected.c > 1][["user_id", "c"]]
    assert_eq(result, expected, check_dtype=False, sort_results=True)

def test_join_on_expression(c, user_table_1, user_table_2):
    result = c.sql(
        "SELECT lhs.user_id FROM user_table_1 lhs JOIN user_table_2 rhs "
        "ON lhs.user_id + 1 = rhs.user_id + 1"
    ).compute()
    expected = user_table_1.merge(user_table_2, on="user_id")[["user_id"]]
    assert_eq(result, expected, check_dtype=False, sort_results=True)

def test_join_non_equi_residual(c, user_table_1, user_table_2):
    result = c.sql(
        "SELECT lhs.user_id, lhs.b, rhs.c FROM user_table_1 lhs JOIN user_table_2 rhs "
        "ON lhs.user_id = rhs.user_id AND rhs.c > lhs.b"
    ).compute()
    merged = user_table_1.merge(user_table_2, on="user_id")
    expected = merged[merged.c > merged.b][["user_id", "b", "c"]]
    assert_eq(result, expected, check_dtype=False, sort_results=True)

def test_join_multiple_keys(c):
    left = pd.DataFrame({"k1": [1, 1, 2, 2], "k2": ["a", "b", "a", "b"], "v": [1, 2, 3, 4]})
    right = pd.DataFrame({"k1": [1, 2], "k2": ["a", "b"], "w": [10, 20]})
    c.create_table("ml", left)
    c.create_table("mr", right)
    result = c.sql(
        "SELECT ml.v, mr.w FROM ml JOIN mr ON ml.k1 = mr.k1 AND ml.k2 = mr.k2"
    ).compute()
    expected = left.merge(right, on=["k1", "k2"])[["v", "w"]]
    assert_eq(result, expected, check_dtype=False, sort_results=True)

def test_join_null_keys_dont_match(c):
    left = pd.DataFrame({"k": [1.0, None, 2.0], "v": [1, 2, 3]})
    right = pd.DataFrame({"k": [1.0, None], "w": [10, 20]})
    c.create_table("nl", left)
    c.create_table("nr", right)
    result = c.sql("SELECT nl.v, nr.w FROM nl JOIN nr ON nl.k = nr.k").compute()
    assert len(result) == 1
    assert result["v"][0] == 1 and result["w"][0] == 10

def test_in_subquery(c, user_table_1, user_table_2):
    result = c.sql(
        "SELECT * FROM user_table_1 WHERE user_id IN (SELECT user_id FROM user_table_2)"
    ).compute()
    expected = user_table_1[user_table_1.user_id.isin(user_table_2.user_id)]
    assert_eq(result, expected, check_dtype=False, sort_results=True)

def test_exists_correlated(c, user_table_1, user_table_2):
    result = c.sql(
        "SELECT * FROM user_table_1 u WHERE EXISTS "
        "(SELECT 1 FROM user_table_2 v WHERE v.user_id = u.user_id)"
    ).compute()
    expected = user_table_1[user_table_1.user_id.isin(user_table_2.user_id)]
    assert_eq(result, expected, check_dtype=False, sort_results=True)

def test_not_exists_correlated(c, user_table_1, user_table_2):
    result = c.sql(
        "SELECT * FROM user_table_1 u WHERE NOT EXISTS "
        "(SELECT 1 FROM user_table_2 v WHERE v.user_id = u.user_id)"
    ).compute()
    expected = user_table_1[~user_table_1.user_id.isin(user_table_2.user_id)]
    assert_eq(result, expected, check_dtype=False, sort_results=True)

def test_scalar_subquery(c, user_table_1, user_table_2):
    result = c.sql(
        "SELECT user_id, b - (SELECT MAX(c) FROM user_table_2) AS d FROM user_table_1"
    ).compute()
    expected = user_table_1.assign(d=user_table_1.b - user_table_2.c.max())[["user_id", "d"]]
    assert_eq(result, expected, check_dtype=False, sort_results=True)

def test_join_using(c, user_table_1, user_table_2):
    result = c.sql(
        "SELECT user_table_1.user_id, b, c FROM user_table_1 "
        "JOIN user_table_2 USING (user_id)"
    ).compute()
    expected = user_table_1.merge(user_table_2, on="user_id")[["user_id", "b", "c"]]
    assert_eq(result, expected, check_dtype=False, sort_results=True)

def test_self_join(c, user_table_1):
    result = c.sql(
        "SELECT a.user_id FROM user_table_1 a JOIN user_table_1 b ON a.user_id = b.user_id"
    ).compute()
    expected = user_table_1.merge(user_table_1, on="user_id")[["user_id"]]
    assert_eq(result, expected, check_dtype=False, sort_results=True)

def test_join_jit_probe_mode(c, user_table_1, user_table_2, monkeypatch):
    from dask_sql_tpu.ops import join as join_ops

    calls = []
    orig = join_ops._probe_phase_jit
    monkeypatch.setattr(join_ops, "_probe_phase_jit",
                        lambda *a: calls.append(1) or orig(*a))
    q = ("SELECT lhs.user_id, lhs.b, rhs.c FROM user_table_1 AS lhs "
         "JOIN user_table_2 AS rhs ON lhs.user_id = rhs.user_id")
    # pin the single-program path: in distributed-tests mode the collectives
    # kernel (dist_plan) would otherwise take the join, bypassing this probe
    ref = c.sql(q, config_options={"sql.compile.join": "off",
                                   "sql.distributed.join": "off"}).compute()
    assert not calls
    jit = c.sql(q, config_options={"sql.compile.join": "jit",
                                   "sql.distributed.join": "off"}).compute()
    assert calls  # the jitted phase really ran
    assert_eq(jit.sort_values(list(jit.columns)).reset_index(drop=True),
              ref.sort_values(list(ref.columns)).reset_index(drop=True),
              check_dtype=False)
    with pytest.raises(Exception):
        c.sql(q, config_options={"sql.compile.join": "bogus"}).compute()


def test_mark_join_exists_under_or(c, user_table_1, user_table_2):
    """Correlated EXISTS under OR decorrelates via a MARK join (the
    reference xfails this shape — TPC-DS q10/q35)."""
    result = c.sql(
        "SELECT * FROM user_table_1 u WHERE b > 0 AND "
        "(EXISTS (SELECT 1 FROM user_table_2 v WHERE v.user_id = u.user_id) "
        " OR u.b > 2)"
    ).compute()
    u1, u2 = user_table_1, user_table_2
    keep = (u1.b > 0) & (u1.user_id.isin(u2.user_id) | (u1.b > 2))
    expected = u1[keep]
    from tests.utils import assert_eq

    assert_eq(result, expected, check_dtype=False, sort_results=True)


def test_mark_join_not_exists_under_or(c, user_table_1, user_table_2):
    result = c.sql(
        "SELECT * FROM user_table_1 u WHERE "
        "(NOT EXISTS (SELECT 1 FROM user_table_2 v WHERE v.user_id = u.user_id) "
        " OR u.b = 3)"
    ).compute()
    u1, u2 = user_table_1, user_table_2
    keep = (~u1.user_id.isin(u2.user_id)) | (u1.b == 3)
    from tests.utils import assert_eq

    assert_eq(result, u1[keep], check_dtype=False, sort_results=True)
