"""Streaming partitioned execution: serve larger-than-budget working sets.

The vertical slice that turns the admission gate's ``shed:estimated_bytes``
into graceful degradation (ROADMAP item 4, docs/serving.md "Streaming
execution"):

- `plan.stream_decision` — admission-time routing: a provably-over-budget
  plan whose floor is dominated by ONE registered table's scan partitions
  along the row axis; shedding becomes the last resort;
- `partition` — fixed-shape encoded row chunks (one morsel shape = one
  executable, zero recompile across chunks);
- `runner.drive_partitions` — pipelined launches with per-partition
  retry/backoff, cooperative deadline checkpoints between launches, and
  mid-stream OOM recovery that halves the partition size and RESUMES from
  the checkpointable partial-combine state;
- `aggregate` / `select` — the streamed ladder rungs: partial aggregation
  states tree-reduced across the time axis with the same combine algebra
  the SPMD rungs use across the mesh axis, and survivor chunks
  concatenated in global row order.
"""
from .aggregate import StreamedAggregate, try_streamed_aggregate
from .plan import StreamDecision, stream_decision
from .select import try_streamed_select

__all__ = [
    "StreamDecision",
    "StreamedAggregate",
    "stream_decision",
    "try_streamed_aggregate",
    "try_streamed_select",
]
