"""Hive input plugin against a fake cursor (the in-image analogue of the
reference's dockerized Hive integration test, test_hive.py:39-70 there:
DESCRIBE FORMATTED metadata -> storage location -> registered files)."""
import os

import numpy as np
import pandas as pd
import pytest

from tests.utils import assert_eq


class FakeHiveCursor:
    """Scripted pyhive-like cursor: execute() + fetchall()."""

    def __init__(self, responses):
        self.responses = responses
        self._rows = []
        self.executed = []

    def execute(self, sql):
        self.executed.append(sql)
        for prefix, rows in self.responses.items():
            if sql.startswith(prefix):
                self._rows = rows
                return
        raise RuntimeError(f"unexpected hive query: {sql}")

    def fetchall(self):
        return self._rows


@pytest.fixture
def hive_parquet(tmp_path):
    df = pd.DataFrame({
        "i": np.arange(10, dtype=np.int64),
        "v": np.arange(10, dtype=np.float64) * 1.5,
    })
    loc = tmp_path / "warehouse" / "tbl"
    loc.mkdir(parents=True)
    df.to_parquet(loc / "part-000.parquet")
    return df, str(loc)


def test_hive_unpartitioned(hive_parquet):
    from dask_sql_tpu import Context

    df, loc = hive_parquet
    cursor = FakeHiveCursor({
        "DESCRIBE FORMATTED": [
            ("# col_name", "data_type", "comment"),
            ("i", "bigint", ""),
            ("v", "double", ""),
            ("Location:", f"file:{loc}", ""),
            ("InputFormat:", "org.apache.hadoop.hive.ql.io.parquet"
             ".MapredParquetInputFormat", ""),
        ],
        "SHOW PARTITIONS": [],
    })
    c = Context()
    c.create_table("t", cursor)
    result = c.sql("SELECT i, v FROM t", return_futures=False)
    assert_eq(result, df, check_dtype=False, sort_results=True)
    assert any(s.startswith("DESCRIBE FORMATTED") for s in cursor.executed)


def test_hive_partitioned(tmp_path):
    from dask_sql_tpu import Context

    loc = tmp_path / "warehouse" / "ptbl"
    frames = []
    for part in ("p=a", "p=b"):
        d = loc / part
        d.mkdir(parents=True)
        df = pd.DataFrame({"x": np.arange(3, dtype=np.int64)})
        df.to_parquet(d / "part-000.parquet")
        frames.append(df.assign(p=part.split("=")[1]))
    expected = pd.concat(frames, ignore_index=True)

    cursor = FakeHiveCursor({
        "DESCRIBE FORMATTED": [
            ("x", "bigint", ""),
            ("Location:", f"file:{loc}", ""),
            ("InputFormat:", "parquet", ""),
        ],
        "SHOW PARTITIONS": [("p=a",), ("p=b",)],
    })
    c = Context()
    c.create_table("pt", cursor)
    result = c.sql("SELECT x, p FROM pt", return_futures=False)
    assert_eq(result, expected, check_dtype=False, sort_results=True)


def test_hive_unsupported_format(hive_parquet):
    from dask_sql_tpu import Context

    _, loc = hive_parquet
    cursor = FakeHiveCursor({
        "DESCRIBE FORMATTED": [
            ("Location:", f"file:{loc}", ""),
            ("InputFormat:", "org.apache.hadoop.hive.ql.io.orc"
             ".OrcInputFormat", ""),
        ],
        "SHOW PARTITIONS": [],
    })
    c = Context()
    with pytest.raises(NotImplementedError):
        c.create_table("t", cursor)
