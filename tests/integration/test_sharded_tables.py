"""Distributed-table mode: SQL over row-sharded columns on the 8-device mesh.

The analogue of running the reference suite under a distributed Client
(DASK_SQL_DISTRIBUTED_TESTS parity): same queries, sharded execution.
"""
import numpy as np
import pandas as pd
import pytest

import jax

from tests.utils import assert_eq


needs_mesh = pytest.mark.skipif(len(jax.devices()) < 2, reason="needs multi-device mesh")


@pytest.fixture
def dist_c():
    from dask_sql_tpu import Context

    rng = np.random.RandomState(5)
    n = 800
    df = pd.DataFrame({
        "g": rng.choice(["a", "b", "c", "d"], n),
        "x": rng.randint(0, 100, n).astype(np.int64),
        "y": rng.rand(n),
    })
    small = pd.DataFrame({"g": ["a", "b", "c", "d"], "w": [1.0, 2.0, 3.0, 4.0]})
    c = Context()
    c.create_table("big", df, distributed=True)
    c.create_table("small", small)
    return c, df, small


@needs_mesh
def test_sharding_applied(dist_c):
    c, df, _ = dist_c
    table = c.schema["root"].tables["big"].table
    sh = table.columns["x"].data.sharding
    assert "shards" in str(sh) or len(sh.device_set) > 1


@needs_mesh
def test_sharded_groupby(dist_c):
    c, df, _ = dist_c
    result = c.sql("SELECT g, SUM(x) AS s, COUNT(*) AS n FROM big GROUP BY g").compute()
    expected = df.groupby("g").agg(s=("x", "sum"), n=("x", "count")).reset_index()
    assert_eq(result, expected, check_dtype=False, sort_results=True)


@needs_mesh
def test_sharded_filter_projection(dist_c):
    c, df, _ = dist_c
    result = c.sql("SELECT x + 1 AS x1 FROM big WHERE y > 0.5").compute()
    expected = pd.DataFrame({"x1": df[df.y > 0.5].x + 1})
    assert_eq(result, expected, check_dtype=False, sort_results=True)


@needs_mesh
def test_sharded_join_with_replicated(dist_c):
    c, df, small = dist_c
    result = c.sql(
        "SELECT big.g, SUM(big.y * small.w) AS r FROM big JOIN small ON big.g = small.g GROUP BY big.g"
    ).compute()
    m = df.merge(small, on="g")
    expected = (m.assign(r=m.y * m.w).groupby("g").r.sum().reset_index())
    assert_eq(result, expected, check_dtype=False, sort_results=True)


@needs_mesh
def test_sharded_sort_limit(dist_c):
    c, df, _ = dist_c
    result = c.sql("SELECT x, y FROM big ORDER BY y DESC LIMIT 5").compute()
    expected = df.nlargest(5, "y")[["x", "y"]].reset_index(drop=True)
    assert_eq(result, expected, check_dtype=False)
