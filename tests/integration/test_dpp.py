"""Dynamic partition pruning tests (parity: the reference's DPP optimizer,
dynamic_partition_pruning.rs — dim-side values collected at plan time and
injected into the fact scan, reaching pyarrow row-group filters)."""
import numpy as np
import pandas as pd
import pytest


@pytest.fixture
def dpp_setup(tmp_path):
    from dask_sql_tpu import Context

    rng = np.random.RandomState(0)
    n_fact = 20_000
    fact = pd.DataFrame({
        "f_key": np.repeat(np.arange(200), 100),
        "f_val": rng.rand(n_fact),
    })
    path = str(tmp_path / "fact.parquet")
    fact.to_parquet(path, row_group_size=1000)
    dim = pd.DataFrame({
        "d_key": np.arange(200),
        "d_cat": np.where(np.arange(200) < 5, "keep", "drop"),
    })
    c = Context()
    c.create_table("fact", path, persist=False)  # lazy: IO pruning visible
    c.create_table("dim", dim)
    return c, fact, dim


def test_dpp_injects_inlist(dpp_setup):
    c, fact, dim = dpp_setup
    q = ("SELECT SUM(f_val) AS s FROM fact JOIN dim ON f_key = d_key "
         "WHERE d_cat = 'keep'")
    plan_text = c.explain(q)
    assert "InArray" in plan_text, plan_text  # DPP filter landed on the fact scan
    result = c.sql(q).compute()
    keep = dim[dim.d_cat == "keep"].d_key
    expected = fact[fact.f_key.isin(keep)].f_val.sum()
    np.testing.assert_allclose(result["s"][0], expected, rtol=1e-9)


def test_dpp_io_pruning_reached(dpp_setup, monkeypatch):
    c, fact, dim = dpp_setup
    from dask_sql_tpu.datacontainer import LazyParquetContainer

    captured = {}
    orig = LazyParquetContainer.scan

    def spy(self, columns=None, filters=None):
        captured["filters"] = filters
        return orig(self, columns, filters)

    monkeypatch.setattr(LazyParquetContainer, "scan", spy)
    result = c.sql(
        "SELECT SUM(f_val) AS s FROM fact JOIN dim ON f_key = d_key "
        "WHERE d_cat = 'keep'").compute()
    assert captured.get("filters"), "DPP InList should reach pyarrow filters"
    ops = [f[1] for f in captured["filters"]]
    assert "in" in ops


def test_dpp_disabled_by_config(dpp_setup):
    c, fact, dim = dpp_setup
    q = ("SELECT SUM(f_val) AS s FROM fact JOIN dim ON f_key = d_key "
         "WHERE d_cat = 'keep'")
    res_on = c.sql(q).compute()
    res_off = c.sql(q, config_options={"sql.dynamic_partition_pruning": False}).compute()
    np.testing.assert_allclose(res_on["s"][0], res_off["s"][0])


def test_dpp_dim_on_left(dpp_setup):
    """Small filtered dim on the LEFT: the fact key (combined-plan space)
    must be rebased into the fact scan's schema.  Regression: the rebase
    offsets were swapped between the two injection sites, resolving the
    wrong fact column (or silently disabling DPP for left-dim joins)."""
    c, fact, dim = dpp_setup
    q = ("SELECT SUM(f_val) AS s FROM dim JOIN fact ON d_key = f_key "
         "WHERE d_cat = 'keep'")
    plan_text = c.explain(q)
    assert "InArray" in plan_text, plan_text  # DPP fired on the left-dim shape
    result = c.sql(q).compute()
    keep = dim[dim.d_cat == "keep"].d_key
    expected = fact[fact.f_key.isin(keep)].f_val.sum()
    np.testing.assert_allclose(result["s"][0], expected, rtol=1e-9)
