"""Post-optimize parameterization: lift literals out of a plan into a
runtime parameter vector.

Every query with a different literal used to be a different plan
fingerprint — its own XLA compile, its own result-cache / breaker /
estimator / profile entry — so a serving workload of `WHERE user_id = ?`
re-paid compilation per user id.  This pass rewrites eligible `Literal`
expressions to `ParamRef` placeholders (and all-literal ``IN`` lists to
`InParamExpr` vectors padded to a power-of-two bucket), producing

- a literal-stripped plan copy whose repr is the *family* identity
  (two queries differing only in parameterized literals stringify
  identically), and
- the ordered parameter values the stripped slots refer to.

The compiled pipelines (physical/compiled*.py) run the same rewrite on
their extracted expression lists, key their caches on the parameterized
strings, and take the values as traced runtime arguments — one XLA
executable per family, compile-once-run-many (Flare, arXiv:1703.08219;
TQP, arXiv:2203.01877).

Eligibility is deliberately conservative — a literal stays baked whenever
the compiled evaluators consume it at *trace* time:

- string literals (dictionary lookup tables are built per value at
  compile time), and NULL literals (validity shape is structural);
- LIKE / ILIKE / SIMILAR patterns and escapes (host-compiled regexes);
- DATE_TRUNC / CEIL unit arguments (static truncation unit);
- plan-node integer fields (LIMIT windows, sort fetch, sample fraction,
  window frames) — these change static shapes or host-side slicing, so
  each distinct value is its own family;
- IN lists keep their *bucket*: the value vector pads to the next power
  of two, so lists of 5..8 values share one family and one kernel while
  a 9th value starts a new bucket.

Numeric, boolean, datetime (int64 epoch-ns) and interval (int64
ns / months) scalars in filter predicates, projection expressions and
aggregate arguments all parameterize.
"""
from __future__ import annotations

import dataclasses
import hashlib
import logging
from typing import Any, List, Optional, Tuple

import numpy as np

from ..columnar.dtypes import (
    DATETIME_TYPES,
    INTERVAL_TYPES,
    NUMERIC_TYPES,
    SqlType,
    sql_to_np,
)
from ..planner import plan as p
from ..planner.expressions import (
    AggExpr,
    ExistsExpr,
    Expr,
    InListExpr,
    InParamExpr,
    InSubqueryExpr,
    Literal,
    ParamRef,
    ScalarFunc,
    ScalarSubqueryExpr,
)

logger = logging.getLogger(__name__)

#: SQL types whose literals are representable as runtime scalars of the
#: device dtype (strings need compile-time dictionaries; NULL is structural)
_PARAM_TYPES = frozenset(
    NUMERIC_TYPES | DATETIME_TYPES | INTERVAL_TYPES | {SqlType.BOOLEAN,
                                                       SqlType.DECIMAL})

#: ops whose TRAILING arguments the compiled evaluators read at trace time
#: (regex compilation, truncation units) — only args[0] may parameterize
_STATIC_TAIL_OPS = frozenset({"like", "ilike", "similar",
                              "datetime_floor", "datetime_ceil"})


def normalize_in_values(col_dtype: np.dtype,
                        values: List[Any]) -> Optional[np.ndarray]:
    """Host-normalize an IN value list to the comparison domain the kernel
    searches in: drop NULLs, reduce float lists against integer columns to
    their integral members (mirrors ops/membership.sorted_membership), sort.
    Returns None when the list is not parameterizable (empty, strings)."""
    vals = [v for v in values if v is not None]
    if not vals:
        return None
    try:
        arr = np.asarray(vals)
    except (ValueError, TypeError):
        return None
    if arr.dtype.kind not in "iufb":
        return None
    if col_dtype.kind in "iu" and arr.dtype.kind == "f":
        integral = arr == np.floor(arr)
        arr = arr[integral & (np.abs(arr) < 2.0 ** 63)].astype(np.int64)
        if not len(arr):
            return None
    cmp = np.result_type(col_dtype, arr.dtype)
    return np.sort(arr.astype(cmp, copy=False))


def pow2_bucket(n: int) -> int:
    return 1 << max(0, (int(n) - 1)).bit_length()


def stack_params(params_list) -> Tuple[Tuple[np.ndarray, ...], int]:
    """Stack per-member parameter tuples along a new leading axis for a
    batched (vmapped) launch, padded to the pow2 batch bucket by repeating
    the last member (padding work is discarded by the caller).  Returns
    (stacked params, bucket) — THE bucketing/padding policy, shared by
    every pipeline's `run_batched` so solo and batched variants cannot
    diverge."""
    n = len(params_list)
    bucket = pow2_bucket(n)
    padded = list(params_list) + [params_list[-1]] * (bucket - n)
    stacked = tuple(np.stack([np.asarray(p[i]) for p in padded])
                    for i in range(len(params_list[0])))
    return stacked, bucket


class Parameterizer:
    """One rewrite pass collecting parameter values as it strips literals.

    ``enabled=False`` makes every rewrite the identity (zero params), so
    call sites need no branching.  ``recurse_subplans`` is on for the
    plan-level family fingerprint (subquery literals join the family) and
    off for the compiled pipelines (subquery expressions decline at trace
    time anyway — their values would only bloat the kernel arguments)."""

    def __init__(self, enabled: bool = True, recurse_subplans: bool = False):
        self.enabled = enabled
        self.recurse_subplans = recurse_subplans
        #: jit-ready values, one per slot: 0-d numpy scalars of the slot's
        #: device dtype, or sorted padded vectors for IN buckets
        self.values: List[np.ndarray] = []
        #: hashable mirror of `values` for result-cache keys
        self.key_values: List[Any] = []

    @property
    def params(self) -> Tuple[np.ndarray, ...]:
        return tuple(self.values)

    # -------------------------------------------------------- expressions
    def rewrite(self, expr: Expr) -> Expr:
        if not self.enabled or expr is None:
            return expr
        return self._rewrite(expr)

    def _rewrite(self, e: Expr) -> Expr:
        if isinstance(e, Literal):
            return self._maybe_param(e)
        if isinstance(e, InListExpr):
            return self._rewrite_in_list(e)
        if isinstance(e, ScalarFunc) and e.op in _STATIC_TAIL_OPS and e.args:
            # pattern / unit arguments are compile-time constants
            return dataclasses.replace(
                e, args=(self._rewrite(e.args[0]),) + tuple(e.args[1:]))
        if isinstance(e, (ScalarSubqueryExpr, InSubqueryExpr, ExistsExpr)):
            if not self.recurse_subplans:
                return e
            out = e
            if getattr(e, "plan", None) is not None:
                out = dataclasses.replace(out, plan=self.rewrite_plan(e.plan))
            if isinstance(out, InSubqueryExpr):
                out = dataclasses.replace(out, arg=self._rewrite(out.arg))
            return out
        kids = e.children()
        if not kids:
            return e
        return e.with_children([self._rewrite(c) for c in kids])

    def _maybe_param(self, lit: Literal) -> Expr:
        if lit.value is None or lit.sql_type not in _PARAM_TYPES:
            return lit
        if isinstance(lit.value, str) or not isinstance(
                lit.value, (int, float, bool, np.integer, np.floating,
                            np.bool_)):
            return lit
        dtype = sql_to_np(lit.sql_type)
        try:
            value = np.asarray(lit.value, dtype=dtype)
        except (ValueError, TypeError, OverflowError):
            return lit
        index = len(self.values)
        self.values.append(value)
        self.key_values.append(value.item())
        return ParamRef(index, lit.sql_type)

    def _rewrite_in_list(self, e: InListExpr) -> Expr:
        from ..columnar.dtypes import STRING_TYPES

        arg = self._rewrite(e.arg)
        if e.arg.sql_type in STRING_TYPES \
                or not all(isinstance(it, Literal) for it in e.items):
            # string membership (dictionary LUT) and computed items stay
            # baked; items must remain Literals for the trace evaluator
            return dataclasses.replace(e, arg=arg)
        if any(it.value is None for it in e.items):
            # a NULL member changes the list's three-valued-logic semantics
            # on the eager path (`x NOT IN (v, NULL)` is never TRUE) —
            # normalizing it away would give `IN (v, NULL)` and `IN (v)`
            # one family identity and ONE result-cache key while their
            # results differ.  Keep the whole list baked: the NULL stays in
            # the family repr and the cache key.
            return dataclasses.replace(e, arg=arg)
        col_dtype = sql_to_np(e.arg.sql_type)
        norm = normalize_in_values(col_dtype, [it.value for it in e.items])
        if norm is None:
            return dataclasses.replace(e, arg=arg)
        bucket = pow2_bucket(len(norm))
        # pad by repeating the (sorted) maximum — membership is unchanged
        padded = np.concatenate(
            [norm, np.repeat(norm[-1:], bucket - len(norm))])
        index = len(self.values)
        self.values.append(padded)
        self.key_values.append(tuple(padded.tolist()))
        return InParamExpr(arg, index, bucket, str(padded.dtype), e.negated)

    # --------------------------------------------------------------- plans
    #: node type -> expression-bearing fields the pass rewrites.  Fields
    #: not listed (sort keys, window frames, VALUES rows, join keys, LIMIT
    #: windows) keep their literals: they steer static shapes, host-side
    #: slicing or converter-time decisions, so each value is its own family.
    _NODE_FIELDS = {
        "Filter": ("predicate",),
        "Projection": ("exprs",),
        "TableScan": ("filters",),
        "Aggregate": ("agg_exprs",),
        "Join": ("filter",),
    }

    def rewrite_plan(self, node: p.LogicalPlan) -> p.LogicalPlan:
        """Literal-stripped copy of `node` (bottom-up; the input plan is
        never mutated — placeholders exist only in the copy)."""
        if not self.enabled:
            return node
        kids = [self.rewrite_plan(c) for c in node.inputs()]
        if kids:
            node = node.with_inputs(kids)
        fields = self._NODE_FIELDS.get(node.node_type)
        if not fields:
            return node
        updates = {}
        for name in fields:
            v = getattr(node, name, None)
            if v is None:
                continue
            if isinstance(v, (list, tuple)):
                updates[name] = [self.rewrite_agg(x) if isinstance(x, AggExpr)
                                 else self._rewrite(x) for x in v]
            elif isinstance(v, Expr):
                updates[name] = self._rewrite(v)
        if not updates:
            return node
        return dataclasses.replace(node, **updates)

    def rewrite_agg(self, a: AggExpr) -> AggExpr:
        if not self.enabled:
            return a
        return dataclasses.replace(
            a, args=tuple(self._rewrite(x) for x in a.args),
            filter=self._rewrite(a.filter) if a.filter is not None else None)


# ---------------------------------------------------------------------------
# family identity
# ---------------------------------------------------------------------------
@dataclasses.dataclass(frozen=True)
class FamilyInfo:
    """The family identity of one planned query: the literal-stripped plan
    repr (collision-grade identity, same property the result cache's
    repr(plan) keys relied on), its 16-hex-char fingerprint, and this
    query's parameter values in slot order (hashable — IN vectors are
    tuples)."""

    fingerprint: str
    family_repr: str
    key_values: Tuple[Any, ...]
    n_params: int


def compute_family(plan: p.LogicalPlan) -> FamilyInfo:
    """Parameterize a copy of `plan` and derive its family identity.
    Deterministic: traversal order fixes slot numbering, so the same SQL
    shape always maps to the same fingerprint across processes."""
    pz = Parameterizer(enabled=True, recurse_subplans=True)
    stripped = pz.rewrite_plan(plan)
    family_repr = repr(stripped)
    fingerprint = hashlib.sha1(family_repr.encode()).hexdigest()[:16]
    return FamilyInfo(fingerprint, family_repr, tuple(pz.key_values),
                      len(pz.values))


# ---------------------------------------------------------------------------
# plan-prefix (stem) identity — sub-plan materialization
# ---------------------------------------------------------------------------
def stem_of(plan: p.LogicalPlan) -> Optional[p.LogicalPlan]:
    """The plan's materializable *stem*: the maximal contiguous Filter
    chain sitting directly on the plan's single TableScan (the shared
    scan->filter prefix a dashboard's sibling queries re-execute).  None
    when the plan scans zero or several tables, or when the scan carries
    no filtering work at all (materializing a bare scan would just copy
    the registered table).  Returns the topmost node of the stem subtree —
    the SAME object inside ``plan``, so callers can substitute it by
    identity."""
    scans = [n for n in p.walk_plan(plan) if isinstance(n, p.TableScan)]
    if len(scans) != 1:
        return None
    scan = scans[0]

    def find(node: p.LogicalPlan) -> Optional[p.LogicalPlan]:
        # preorder: the first Filter whose chain bottoms at the scan is the
        # topmost one — the maximal prefix
        if isinstance(node, p.Filter):
            cur: p.LogicalPlan = node.input
            while isinstance(cur, p.Filter):
                cur = cur.input
            if cur is scan:
                return node
        for child in node.inputs():
            got = find(child)
            if got is not None:
                return got
        return None

    stem = find(plan)
    if stem is None and scan.filters:
        # no Filter node, but pushed-down scan filters still do per-query
        # work a pinned stem would skip
        stem = scan
    return stem


@dataclasses.dataclass(frozen=True)
class StemInfo:
    """A plan's materializable scan->filter prefix and its identity.

    ``stem``/``scan`` are the ORIGINAL objects inside the plan (substitute
    by identity); ``preds`` are the Filter-chain predicates bottom-to-top
    (excluding the scan's pushed-down ``filters``); ``info`` is the
    PROJECTION-AGNOSTIC family identity — see `compute_stem`."""

    stem: p.LogicalPlan
    scan: p.TableScan
    preds: Tuple[Any, ...]
    info: FamilyInfo


def rewrite_column_indexes(expr, index_of) -> Any:
    """Structural copy of a (frozen-dataclass) expression tree with every
    `ColumnRef.index` replaced by ``index_of(name)``.  Raises ValueError
    for shapes whose identity or remapping is not trustworthy: exprs
    carrying nested plans (their column refs bind elsewhere) and
    `InArrayExpr` (ndarray reprs truncate, so repr is not identity-grade).
    Shared by the stem canonicalizer (``index_of`` = constant -1) and the
    full-width stem builder (``index_of`` = table column position)."""
    from ..planner.expressions import ColumnRef, InArrayExpr

    if isinstance(expr, ColumnRef):
        return dataclasses.replace(expr, index=int(index_of(expr.name)))
    if isinstance(expr, (InArrayExpr, ExistsExpr, InSubqueryExpr,
                         ScalarSubqueryExpr)) or hasattr(expr, "plan"):
        raise ValueError(f"unremappable expression {type(expr).__name__}")

    def value_of(v):
        if isinstance(v, Expr):
            return rewrite_column_indexes(v, index_of)
        if isinstance(v, tuple):
            return tuple(value_of(x) for x in v)
        return v

    if dataclasses.is_dataclass(expr) and isinstance(expr, Expr):
        kw = {f.name: value_of(getattr(expr, f.name))
              for f in dataclasses.fields(expr)}
        return dataclasses.replace(expr, **kw)
    return expr


def compute_stem(plan: p.LogicalPlan) -> Optional[StemInfo]:
    """The plan's materializable scan->filter prefix identity, or None.

    The identity must be PROJECTION-AGNOSTIC: column pruning bakes each
    sibling's projection (and the pruned column indexes) into its
    TableScan, so fingerprinting the literal stem subtree would give
    `SELECT a ...` and `SELECT b ...` over the same WHERE different stems.
    Instead the fingerprint is computed over a canonical form — projection
    and schemas stripped, every ColumnRef keyed by NAME (index -1) — so
    sibling queries sharing the prefix map to one stem fingerprint,
    whatever they project or aggregate above it.  A concrete
    materialization is keyed on ``(fingerprint, key_values)`` since pinned
    rows are literal-specific."""
    stem = stem_of(plan)
    if stem is None:
        return None
    preds: List[Any] = []
    cur = stem
    while isinstance(cur, p.Filter):
        preds.append(cur.predicate)
        cur = cur.input
    assert isinstance(cur, p.TableScan)
    scan = cur
    preds.reverse()
    try:
        nameize = lambda e: rewrite_column_indexes(e, lambda name: -1)
        node: p.LogicalPlan = dataclasses.replace(
            scan, schema=[], projection=None,
            filters=[nameize(f) for f in scan.filters])
        for pred in preds:
            node = p.Filter(node, nameize(pred), [])
    except (ValueError, TypeError):
        return None
    return StemInfo(stem, scan, tuple(preds), compute_family(node))


def full_width_stem(si: StemInfo, table) -> Optional[p.LogicalPlan]:
    """An EXECUTABLE copy of the stem reading every column of ``table``
    (a columnar Table) in registration order — the form a materialization
    pins, so any sibling's projection can be served from the pinned rows.
    Filter column indexes remap from the sibling's pruned scan schema to
    full-table positions by name; None when a referenced column is gone
    or an expression shape cannot be remapped."""
    from ..columnar.dtypes import SqlType
    from ..planner.expressions import Field

    pos = {name: i for i, name in enumerate(table.columns)}
    fields = [
        Field(name, col.sql_type,
              col.validity is not None
              or col.sql_type in (SqlType.FLOAT, SqlType.DOUBLE))
        for name, col in table.columns.items()
    ]
    try:
        remap = lambda e: rewrite_column_indexes(e, pos.__getitem__)
        node: p.LogicalPlan = p.TableScan(
            si.scan.schema_name, si.scan.table_name, fields,
            projection=None, filters=[remap(f) for f in si.scan.filters])
        for pred in si.preds:
            node = p.Filter(node, remap(pred), fields)
    except (KeyError, ValueError, TypeError):
        return None
    return node
