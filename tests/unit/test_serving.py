"""Serving subsystem: result cache, admission control, metrics registry,
SHOW METRICS, and executor cancellation checkpoints."""
import threading
import time

import numpy as np
import pandas as pd
import pytest

from dask_sql_tpu import Context
from dask_sql_tpu.serving import (
    DeadlineExceededError,
    Histogram,
    MetricsRegistry,
    QueryCancelledError,
    QueryTicket,
    QueueFullError,
    ResultCache,
    ServingRuntime,
)


# --------------------------------------------------------------- metrics
def test_histogram_percentiles():
    h = Histogram()
    for v in range(1, 101):
        h.observe(float(v))
    snap = h.snapshot()
    assert snap["count"] == 100
    assert snap["max"] == 100.0
    assert 45 <= snap["p50"] <= 55
    assert 90 <= snap["p95"] <= 100
    assert snap["avg"] == pytest.approx(50.5)


def test_registry_counters_and_rows():
    m = MetricsRegistry()
    m.inc("a.b", 2)
    m.observe("lat_ms", 5.0)
    m.gauge("depth", 3)
    snap = m.snapshot()
    assert snap["counters"]["a.b"] == 2
    assert snap["gauges"]["depth"] == 3
    rows = dict(m.rows())
    assert rows["a.b"] == "2"
    assert "lat_ms.p99" in rows


def test_registry_trace_aggregation():
    from dask_sql_tpu.tracing import NodeTrace, Tracer

    m = MetricsRegistry()
    root = NodeTrace("Projection", "Projection: x", 2.0, 10,
                     [NodeTrace("TableScan", "TableScan: t", 1.0, 10)])
    m.observe_trace(root)
    snap = m.snapshot()
    assert snap["histograms"]["executor.node.Projection.ms"]["count"] == 1
    assert snap["counters"]["executor.node.TableScan.rows"] == 10
    # Tracer.publish is the executor-side entry to the same aggregation
    t = Tracer()
    t.root = root
    t.publish(m)
    assert m.snapshot()["histograms"]["executor.node.Projection.ms"]["count"] == 2


# ----------------------------------------------------------- result cache
def test_result_cache_lru_by_bytes():
    c = ResultCache(max_bytes=100, max_entry_bytes=100, ttl_s=None)
    c.put("a", "va", nbytes=40)
    c.put("b", "vb", nbytes=40)
    assert c.get("a") == "va"  # bumps a to MRU
    c.put("c", "vc", nbytes=40)  # evicts b (LRU), not a
    assert c.get("b") is None
    assert c.get("a") == "va"
    assert c.get("c") == "vc"
    assert c.stats.evictions == 1
    assert c.stats.bytes <= 100


def test_result_cache_per_entry_cap():
    c = ResultCache(max_bytes=1000, max_entry_bytes=50, ttl_s=None)
    assert not c.put("big", "x", nbytes=51)
    assert c.get("big") is None
    assert c.stats.oversize_rejects == 1
    assert c.put("ok", "y", nbytes=50)


def test_result_cache_ttl():
    now = [0.0]
    c = ResultCache(max_bytes=100, max_entry_bytes=100, ttl_s=10.0,
                    clock=lambda: now[0])
    c.put("k", "v", nbytes=1)
    now[0] = 5.0
    assert c.get("k") == "v"
    now[0] = 16.0
    assert c.get("k") is None  # expired
    assert c.stats.expirations == 1


def test_result_cache_replace_accounting():
    c = ResultCache(max_bytes=100, max_entry_bytes=100, ttl_s=None)
    c.put("k", "v1", nbytes=30)
    c.put("k", "v2", nbytes=60)
    assert c.stats.bytes == 60 and c.stats.entries == 1
    assert c.get("k") == "v2"


def test_table_nbytes_counts_buffers():
    from dask_sql_tpu.columnar.table import Table
    from dask_sql_tpu.serving.cache import table_nbytes

    t = Table.from_pandas(pd.DataFrame({
        "i": np.arange(10, dtype=np.int64),
        "s": ["abc"] * 10,
    }))
    n = table_nbytes(t)
    assert n >= 10 * 8  # at least the int64 buffer


# ------------------------------------------- context-level result caching
def _ctx():
    c = Context()
    c.create_table("t", pd.DataFrame({"a": [1, 2, 3], "b": [1.5, 2.5, 3.5]}))
    return c


def test_repeated_query_hits_result_cache():
    c = _ctx()
    q = "SELECT SUM(a) AS s FROM t"
    r1 = c.sql(q, return_futures=False)
    assert c.metrics.counter("query.cache.hit") == 0
    r2 = c.sql(q, return_futures=False)
    assert c.metrics.counter("query.cache.hit") == 1
    assert int(r1["s"][0]) == int(r2["s"][0]) == 6


def test_ddl_invalidates_result_cache():
    c = _ctx()
    q = "SELECT SUM(a) AS s FROM t"
    assert int(c.sql(q, return_futures=False)["s"][0]) == 6
    c.create_table("t", pd.DataFrame({"a": [10, 20]}))  # replace = DDL
    assert int(c.sql(q, return_futures=False)["s"][0]) == 30
    # the replacement must NOT have been served from cache
    assert c.metrics.counter("query.cache.hit") == 0


def test_sql_ddl_invalidates_result_cache():
    c = _ctx()
    c.sql("CREATE VIEW v AS SELECT a FROM t")
    r1 = c.sql("SELECT SUM(a) AS s FROM v", return_futures=False)
    assert int(r1["s"][0]) == 6
    c.sql("DROP VIEW v")
    c.sql("CREATE VIEW v AS SELECT b FROM t")
    r2 = c.sql("SELECT SUM(b) AS s FROM v", return_futures=False)
    assert float(r2["s"][0]) == pytest.approx(7.5)


def test_config_options_partition_result_cache():
    c = _ctx()
    q = "SELECT SUM(a) AS s FROM t"
    c.sql(q, return_futures=False)
    c.sql(q, config_options={"sql.compile": False}, return_futures=False)
    # different config -> different key -> no hit
    assert c.metrics.counter("query.cache.hit") == 0
    c.sql(q, config_options={"sql.compile": False}, return_futures=False)
    assert c.metrics.counter("query.cache.hit") == 1


def test_result_cache_distinguishes_sort_null_order():
    c = Context()
    c.create_table("sn", pd.DataFrame({"a": [1.0, None, 3.0, None, 2.0]}))
    r1 = c.sql("SELECT * FROM sn ORDER BY a", return_futures=False)
    r2 = c.sql("SELECT * FROM sn ORDER BY a NULLS FIRST", return_futures=False)
    assert list(r1["a"].fillna(-1)) == [1.0, 2.0, 3.0, -1, -1]
    assert list(r2["a"].fillna(-1)) == [-1, -1, 1.0, 2.0, 3.0]


def test_result_cache_disabled_by_config():
    c = _ctx()
    q = "SELECT SUM(a) AS s FROM t"
    with c.config.set({"serving.cache.enabled": False}):
        c.sql(q, return_futures=False)
        c.sql(q, return_futures=False)
    assert c.metrics.counter("query.cache.hit") == 0


def test_volatile_functions_never_cached():
    c = _ctx()
    for q in ("SELECT RAND() AS r FROM t",
              "SELECT CURRENT_TIMESTAMP AS ts FROM t",
              # volatile call hiding inside a subquery plan
              "SELECT a FROM t WHERE a > (SELECT RAND() FROM t LIMIT 1)"):
        c.sql(q, return_futures=False)
        c.sql(q, return_futures=False)
    assert c.metrics.counter("query.cache.hit") == 0


def test_udf_queries_never_cached():
    c = _ctx()
    calls = []

    def sample(x):
        calls.append(1)
        return x

    c.register_function(sample, "sample_udf", [("x", np.int64)], np.int64)
    q = "SELECT sample_udf(a) AS v FROM t"
    r1 = c.sql(q, return_futures=False)
    r2 = c.sql(q, return_futures=False)
    assert list(r1["v"]) == list(r2["v"])
    assert c.metrics.counter("query.cache.hit") == 0
    assert len(calls) == 2  # really re-executed


def test_ddl_frees_cache_bytes():
    c = _ctx()
    q = "SELECT SUM(a) AS s FROM t"
    c.sql(q, return_futures=False)
    assert c._result_cache.stats.entries == 1
    # table DDL is epoch-scoped now: registering an UNRELATED table leaves
    # the entry over t valid — and still hittable
    c.create_table("t2", pd.DataFrame({"z": [1]}))
    assert c._result_cache.stats.entries == 1
    c.sql(q, return_futures=False)
    assert c.metrics.counter("query.cache.hit") == 1
    # replacing the REFERENCED table reclaims its entries eagerly, not
    # just unreferenced
    c.create_table("t", pd.DataFrame({"a": [7, 8]}))
    assert c._result_cache.stats.entries == 0
    assert c._result_cache.stats.bytes == 0


# ---------------------------------------------------------- SHOW METRICS
def test_show_metrics_statement():
    c = _ctx()
    q = "SELECT SUM(a) AS s FROM t"
    c.sql(q, return_futures=False)
    c.sql(q, return_futures=False)
    df = c.sql("SHOW METRICS", return_futures=False)
    assert list(df.columns) == ["Metric", "Value"]
    rows = dict(zip(df["Metric"], df["Value"]))
    assert rows["query.cache.hit"] == "1"
    assert "result_cache.bytes" in rows
    assert "plan_cache.entries" in rows


def test_show_metrics_like_filter():
    c = _ctx()
    df = c.sql("SHOW METRICS LIKE 'result_cache'", return_futures=False)
    assert len(df) > 0
    assert all(m.startswith("result_cache") for m in df["Metric"])
    # % switches to real SQL LIKE semantics
    df = c.sql("SHOW METRICS LIKE 'result_cache.%'", return_futures=False)
    assert len(df) > 0
    assert all(m.startswith("result_cache.") for m in df["Metric"])
    assert len(c.sql("SHOW METRICS LIKE 'nope.%'", return_futures=False)) == 0


# ------------------------------------------------------------- admission
def test_queue_full_rejection():
    rt = ServingRuntime(workers=1, bounds={"interactive": 1, "batch": 1})
    try:
        gate = threading.Event()
        started = threading.Event()

        def blocker(t):
            started.set()
            return gate.wait(10)

        _, f1, _ = rt.submit(blocker)
        assert started.wait(10)  # f1 occupies the worker, queue is empty
        _, f2, _ = rt.submit(lambda t: "queued")
        with pytest.raises(QueueFullError) as ei:
            rt.submit(lambda t: "shed")
        assert ei.value.retry_after_s > 0
        assert ei.value.priority_class == "interactive"
        gate.set()
        assert f2.result(10) == "queued"
        assert rt.metrics.counter("serving.rejected") == 1
        assert rt.metrics.counter("serving.admitted") == 2
    finally:
        rt.shutdown()


def test_interactive_scheduled_before_batch():
    rt = ServingRuntime(workers=1, bounds={"interactive": 8, "batch": 8})
    try:
        order = []
        gate = threading.Event()
        _, f0, _ = rt.submit(lambda t: gate.wait(10))  # occupy the worker
        _, fb, _ = rt.submit(lambda t: order.append("batch"),
                             priority_class="batch")
        _, fi, _ = rt.submit(lambda t: order.append("interactive"))
        gate.set()
        fb.result(10), fi.result(10)
        assert order == ["interactive", "batch"]
    finally:
        rt.shutdown()


def test_batch_running_cap_enforced():
    rt = ServingRuntime(workers=2, bounds={"interactive": 8, "batch": 8},
                        batch_max_running=1)
    try:
        gate = threading.Event()
        started = []

        def blocker(name):
            def fn(t):
                started.append(name)
                gate.wait(10)
                return name
            return fn

        _, f1, _ = rt.submit(blocker("b1"), priority_class="batch")
        _, f2, _ = rt.submit(blocker("b2"), priority_class="batch")
        time.sleep(0.3)
        assert started == ["b1"]  # cap 1: the burst must not overshoot
        _, fi, _ = rt.submit(lambda t: "i1")  # capped worker stays free
        assert fi.result(10) == "i1"
        gate.set()
        assert f1.result(10) == "b1" and f2.result(10) == "b2"
    finally:
        rt.shutdown()


def test_batch_paused_sheds_instead_of_stranding():
    rt = ServingRuntime(workers=2, bounds={"interactive": 8, "batch": 8},
                        batch_max_running=0)
    try:
        with pytest.raises(QueueFullError):
            rt.submit(lambda t: "never", priority_class="batch")
        # interactive traffic unaffected
        _, f, _ = rt.submit(lambda t: "ok")
        assert f.result(10) == "ok"
    finally:
        rt.shutdown()


def test_unknown_class_defaults_to_interactive():
    rt = ServingRuntime(workers=1)
    try:
        _, f, ticket = rt.submit(lambda t: "done", priority_class="realtime")
        assert ticket.priority_class == "interactive"
        assert f.result(10) == "done"
    finally:
        rt.shutdown()


def test_deadline_cancels_at_checkpoint():
    rt = ServingRuntime(workers=1)
    try:
        def ticking(t):
            for _ in range(200):
                time.sleep(0.01)
                t.checkpoint()
            return "never"

        _, f, _ = rt.submit(ticking, deadline_s=0.1)
        with pytest.raises(DeadlineExceededError):
            f.result(10)
        assert rt.metrics.counter("serving.timeouts") == 1
    finally:
        rt.shutdown()


def test_cooperative_cancel_mid_run():
    rt = ServingRuntime(workers=1)
    try:
        started = threading.Event()

        def spin(t):
            started.set()
            while True:
                time.sleep(0.01)
                t.checkpoint()

        _, f, ticket = rt.submit(spin)
        assert started.wait(10)
        ticket.cancel()
        with pytest.raises(QueryCancelledError):
            f.result(10)
        assert rt.metrics.counter("serving.cancelled") == 1
    finally:
        rt.shutdown()


def test_expired_while_queued():
    rt = ServingRuntime(workers=1)
    try:
        gate = threading.Event()
        started = threading.Event()
        # the blocker must be RUNNING before f2 is submitted: the packing
        # scheduler orders deadline-bearing queries first, so a still-queued
        # blocker would let f2 jump ahead and complete instead of expiring
        _, f1, _ = rt.submit(lambda t: (started.set(), gate.wait(10))[1])
        started.wait(5)
        _, f2, _ = rt.submit(lambda t: "x", deadline_s=0.05)
        time.sleep(0.2)
        gate.set()
        with pytest.raises(DeadlineExceededError):
            f2.result(10)
    finally:
        rt.shutdown()


def test_shutdown_drains_inflight_and_fails_queued():
    """shutdown(wait=True) regression: the in-flight query finishes, every
    queued future fails promptly with a structured ShutdownError (instead of
    hanging forever on futures no worker will pop), and later submits are
    rejected with the same error."""
    from dask_sql_tpu.serving import ShutdownError

    rt = ServingRuntime(workers=1, bounds={"interactive": 8, "batch": 8})
    gate = threading.Event()
    started = threading.Event()

    def inflight(t):
        started.set()
        gate.wait(10)
        return "inflight-done"

    _, f1, _ = rt.submit(inflight)
    assert started.wait(10)
    _, f2, _ = rt.submit(lambda t: "queued-1")
    _, f3, _ = rt.submit(lambda t: "queued-2", priority_class="batch")

    release = threading.Timer(0.2, gate.set)
    release.start()
    try:
        rt.shutdown(wait=True, timeout=10)
    finally:
        release.cancel()
        gate.set()
    assert f1.result(10) == "inflight-done"
    for fut in (f2, f3):
        with pytest.raises(ShutdownError) as ei:
            fut.result(1)  # already resolved: must not block
        assert ei.value.retryable  # clients may resubmit elsewhere
    with pytest.raises(ShutdownError):
        rt.submit(lambda t: "too-late")
    assert rt.metrics.counter("serving.shutdown_shed") == 2
    # admission gauges drained back to zero (no leaked waiting counts)
    snap = rt.admission.snapshot()
    assert snap["waiting"] == {"interactive": 0, "batch": 0}
    assert snap["running"] == {"interactive": 0, "batch": 0}


def test_deadline_cancels_executor_mid_plan():
    """The executor's per-node checkpoints observe the serving ticket."""
    from dask_sql_tpu.serving import runtime as rt_mod

    c = _ctx()
    ticket = QueryTicket("q1", deadline=time.monotonic() - 1.0)  # already past
    rt_mod._tls.ticket = ticket
    try:
        with pytest.raises(DeadlineExceededError):
            c.sql("SELECT SUM(a) AS s FROM t ORDER BY s", return_futures=False)
    finally:
        rt_mod._tls.ticket = None


# ------------------------------------------------- satellite: take_with_nulls
def test_take_with_nulls_debug_assertion():
    import jax.numpy as jnp

    from dask_sql_tpu import config as config_module
    from dask_sql_tpu.columnar.column import Column
    from dask_sql_tpu.ops.join import take_with_nulls

    col = Column.from_numpy(np.arange(4, dtype=np.int64))
    bad = jnp.array([0, -1, 2], dtype=jnp.int64)
    with config_module.set({"sql.debug.validate_take": True}):
        with pytest.raises(AssertionError):
            take_with_nulls(col, bad, may_pad=False)
        out = take_with_nulls(col, bad, may_pad=True)  # contract respected
        assert not bool(out.valid_mask()[1])
    # flag off: trust-based fast path unchanged (no device sync)
    out = take_with_nulls(col, jnp.array([0, 1], dtype=jnp.int64), may_pad=False)
    assert out.validity is None


# --------------------------------------- satellite: padded radix key bounds
def test_padded_int_bounds_masks_pad_rows():
    import jax.numpy as jnp

    from dask_sql_tpu.physical.compiled import padded_int_bounds

    # logical rows [100, 105, 103], pad rows are zero-filled
    data = jnp.array([100, 105, 103, 0, 0], dtype=jnp.int64)
    row_valid = jnp.array([True, True, True, False, False])
    lo, hi = padded_int_bounds(data, row_valid)
    assert int(lo) == 100 and int(hi) == 105  # pad zeros must not widen
    lo2, hi2 = padded_int_bounds(data, None)
    assert int(lo2) == 0  # unpadded: plain min/max
