"""Type mapping tests (parity: reference tests/unit/test_mapping.py)."""
import numpy as np
import pytest


def test_np_to_sql():
    from dask_sql_tpu.columnar.dtypes import SqlType, np_to_sql

    assert np_to_sql(np.dtype(np.int64)) == SqlType.BIGINT
    assert np_to_sql(np.dtype(np.int32)) == SqlType.INTEGER
    assert np_to_sql(np.dtype(np.float64)) == SqlType.DOUBLE
    assert np_to_sql(np.dtype(np.float32)) == SqlType.FLOAT
    assert np_to_sql(np.dtype(np.bool_)) == SqlType.BOOLEAN
    assert np_to_sql(np.dtype("datetime64[ns]")) == SqlType.TIMESTAMP
    assert np_to_sql(np.dtype("timedelta64[ns]")) == SqlType.INTERVAL_DAY_TIME
    assert np_to_sql(np.dtype(object)) == SqlType.VARCHAR


def test_python_to_sql():
    from dask_sql_tpu.columnar.dtypes import SqlType, python_to_sql_type

    assert python_to_sql_type(True) == SqlType.BOOLEAN
    assert python_to_sql_type(3) == SqlType.BIGINT
    assert python_to_sql_type(3.5) == SqlType.DOUBLE
    assert python_to_sql_type("x") == SqlType.VARCHAR


def test_parse_sql_type():
    from dask_sql_tpu.columnar.dtypes import SqlType, parse_sql_type

    assert parse_sql_type("BIGINT") == SqlType.BIGINT
    assert parse_sql_type("int") == SqlType.INTEGER
    assert parse_sql_type("VARCHAR(20)") == SqlType.VARCHAR
    assert parse_sql_type("DECIMAL(10,2)") == SqlType.DECIMAL
    assert parse_sql_type("timestamp without time zone") == SqlType.TIMESTAMP
    assert parse_sql_type("DOUBLE PRECISION") == SqlType.DOUBLE


def test_promotion():
    from dask_sql_tpu.columnar.dtypes import SqlType, promote

    assert promote(SqlType.INTEGER, SqlType.BIGINT) == SqlType.BIGINT
    assert promote(SqlType.BIGINT, SqlType.FLOAT) == SqlType.DOUBLE
    assert promote(SqlType.INTEGER, SqlType.DOUBLE) == SqlType.DOUBLE
    assert promote(SqlType.NULL, SqlType.VARCHAR) == SqlType.VARCHAR
    assert promote(SqlType.DATE, SqlType.TIMESTAMP) == SqlType.TIMESTAMP
    assert promote(SqlType.TIMESTAMP, SqlType.INTERVAL_DAY_TIME) == SqlType.TIMESTAMP


def test_similar_type():
    from dask_sql_tpu.columnar.dtypes import SqlType, similar_type

    assert similar_type(SqlType.INTEGER, SqlType.BIGINT)
    assert similar_type(SqlType.FLOAT, SqlType.DOUBLE)
    assert not similar_type(SqlType.INTEGER, SqlType.VARCHAR)


def test_cast_column_roundtrip():
    import jax.numpy as jnp

    from dask_sql_tpu.columnar import Column, SqlType

    col = Column.from_numpy(np.array([1.9, -2.9, 3.5]))
    as_int = col.cast(SqlType.BIGINT)
    assert list(np.asarray(as_int.data)) == [1, -2, 3]  # truncation toward zero
    back = as_int.cast(SqlType.DOUBLE)
    assert back.sql_type == SqlType.DOUBLE
    as_str = col.cast(SqlType.VARCHAR)
    assert as_str.sql_type == SqlType.VARCHAR
    as_bool = Column.from_numpy(np.array([0, 1, 2])).cast(SqlType.BOOLEAN)
    assert list(np.asarray(as_bool.data)) == [False, True, True]


def test_string_cast_to_number():
    from dask_sql_tpu.columnar import Column, SqlType

    col = Column.from_numpy(np.array(["1", "2.5", "bad"], dtype=object))
    as_f = col.cast(SqlType.DOUBLE)
    vals = as_f.to_numpy()
    assert vals[0] == 1.0 and vals[1] == 2.5 and np.isnan(vals[2])
