"""Distributed broadcast join + fused sharded join->aggregate.

VERDICT r3 #4/#5: the joined rows of a Q5-shaped query must NOT materialize
(host or device) between merge and groupby — the fused pipeline keeps the
probe row-sharded and probes replicated small-side LUTs per shard; and a
plain join under `sql.join.broadcast` must take the broadcast path (STATS
counter) instead of shuffling the big side.  Bar: the reference's
small-side broadcast merge (reference join.py:228-246)."""
import numpy as np
import pandas as pd
import pytest

import jax


pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 2, reason="needs the virtual multi-device mesh")


@pytest.fixture()
def q5_ctx():
    from dask_sql_tpu import Context

    rng = np.random.RandomState(5)
    n = 40_000
    nation = pd.DataFrame({"n_key": np.arange(8), "n_name": [f"N{i}" for i in range(8)]})
    customer = pd.DataFrame({
        "c_key": np.arange(400), "c_nkey": rng.randint(0, 8, 400)})
    orders = pd.DataFrame({
        "o_key": np.arange(2000), "o_ckey": rng.randint(0, 400, 2000)})
    lineitem = pd.DataFrame({
        "l_okey": rng.randint(0, 2000, n),
        "l_price": rng.rand(n) * 1e4,
        "l_disc": rng.rand(n) * 0.1,
    })
    c = Context()
    c.create_table("nation", nation)
    c.create_table("customer", customer)
    c.create_table("orders", orders)
    c.create_table("lineitem", lineitem, distributed=True)
    frames = dict(nation=nation, customer=customer, orders=orders,
                  lineitem=lineitem)
    return c, frames


def test_q5_shape_fused_no_materialization(q5_ctx):
    c, t = q5_ctx
    from dask_sql_tpu.parallel.dist_plan import STATS
    import dask_sql_tpu.physical.rel.logical.join as J

    materialized = []
    orig = J._materialize

    def spy(left, right, li, ri):
        materialized.append((left.num_rows, right.num_rows))
        return orig(left, right, li, ri)

    fused_before = STATS["sharded_join_agg"]
    J._materialize = spy
    try:
        got = c.sql(
            "SELECT n_name, SUM(l_price * (1 - l_disc)) AS revenue, "
            "COUNT(*) AS n FROM lineitem, orders, customer, nation "
            "WHERE l_okey = o_key AND o_ckey = c_key AND c_nkey = n_key "
            "GROUP BY n_name ORDER BY n_name",
            return_futures=False)
    finally:
        J._materialize = orig
    assert STATS["sharded_join_agg"] > fused_before, (
        "Q5 shape must run the fused sharded pipeline")
    assert materialized == [], (
        f"join output materialized (peak rows {materialized}) — the fused "
        "path must keep rows sharded with no merge->groupby gather")

    li, o, cu, na = t["lineitem"], t["orders"], t["customer"], t["nation"]
    m = (li.merge(o, left_on="l_okey", right_on="o_key")
         .merge(cu, left_on="o_ckey", right_on="c_key")
         .merge(na, left_on="c_nkey", right_on="n_key"))
    exp = (m.assign(rev=m.l_price * (1 - m.l_disc))
           .groupby("n_name", as_index=False)
           .agg(revenue=("rev", "sum"), n=("rev", "size"))
           .sort_values("n_name").reset_index(drop=True))
    assert list(got["n_name"]) == list(exp["n_name"])
    np.testing.assert_allclose(got["revenue"], exp["revenue"], rtol=1e-9)
    assert list(got["n"].astype(np.int64)) == list(exp["n"])


def test_plain_join_broadcast_path(q5_ctx):
    c, t = q5_ctx
    from dask_sql_tpu.parallel.dist_plan import STATS

    bc, jk = STATS["broadcast_join"], STATS["join_kernel"]
    got = c.sql("SELECT l_okey, o_ckey FROM lineitem "
                "JOIN orders ON l_okey = o_key", return_futures=False)
    assert STATS["broadcast_join"] > bc, "broadcast path not taken"
    assert STATS["join_kernel"] == jk, "big side was shuffled"
    exp = t["lineitem"].merge(t["orders"], left_on="l_okey", right_on="o_key")
    assert len(got) == len(exp)
    assert int(got["o_ckey"].sum()) == int(exp["o_ckey"].sum())


def test_broadcast_left_join_values(q5_ctx):
    c, t = q5_ctx
    # drop half the orders so some lineitems lose their match
    small = t["orders"].iloc[:1000]
    c.create_table("orders_half", small)
    got = c.sql("SELECT l_okey, o_ckey FROM lineitem "
                "LEFT JOIN orders_half ON l_okey = o_key",
                return_futures=False)
    exp = t["lineitem"].merge(small, how="left", left_on="l_okey",
                              right_on="o_key")
    assert len(got) == len(exp)
    assert got["o_ckey"].isna().sum() == exp["o_ckey"].isna().sum()


def test_broadcast_disabled_uses_shuffle(q5_ctx):
    c, t = q5_ctx
    from dask_sql_tpu.parallel.dist_plan import STATS

    jk = STATS["join_kernel"]
    got = c.sql(
        "SELECT l_okey, o_ckey FROM lineitem JOIN orders ON l_okey = o_key",
        config_options={"sql.join.broadcast": False}, return_futures=False)
    assert STATS["join_kernel"] > jk, "shuffle engine must run"
    exp = t["lineitem"].merge(t["orders"], left_on="l_okey", right_on="o_key")
    assert len(got) == len(exp)
