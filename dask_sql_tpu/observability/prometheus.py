"""Prometheus text exposition of the MetricsRegistry.

``/v1/metrics?format=prometheus`` renders the same snapshot the JSON
default serves, in the Prometheus `text exposition format 0.0.4` a scrape
job ingests directly — no client library dependency (the image ships
none), just the format:

- counters   -> ``dsql_<name>_total`` (TYPE counter)
- gauges     -> ``dsql_<name>`` (TYPE gauge)
- histograms -> ``dsql_<name>`` (TYPE summary): ``{quantile="0.5|0.95|
  0.99"}`` series from the registry's reservoir percentiles plus
  ``_sum``/``_count``, and a ``dsql_<name>_max`` gauge

Metric names are sanitized (``[^a-zA-Z0-9_:]`` -> ``_``), so the engine's
dotted names stay recognizable: ``query.cache.hit`` ->
``dsql_query_cache_hit_total``.  Output is sorted, making the format
golden-testable byte for byte.
"""
from __future__ import annotations

import re
from typing import Any, Dict, List, Optional

_PREFIX = "dsql_"
_SANITIZE = re.compile(r"[^a-zA-Z0-9_:]")

#: the content type a Prometheus scraper expects
CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _name(raw: str, suffix: str = "") -> str:
    return _PREFIX + _SANITIZE.sub("_", raw) + suffix


def _num(v: Any) -> str:
    f = float(v)
    if f == int(f):
        return str(int(f))
    return repr(f)


def render_prometheus(snapshot: Dict[str, Any],
                      extra_gauges: Optional[Dict[str, Any]] = None) -> str:
    """Render a `MetricsRegistry.snapshot()` (plus optional extra gauges,
    e.g. serving queue depths) to exposition text."""
    lines: List[str] = []

    for raw in sorted(snapshot.get("counters", {})):
        name = _name(raw, "_total")
        lines.append(f"# TYPE {name} counter")
        lines.append(f"{name} {_num(snapshot['counters'][raw])}")

    gauges = dict(snapshot.get("gauges", {}))
    if "cacheHitRate" in snapshot:
        gauges["query.cache.hit_rate"] = snapshot["cacheHitRate"]
    gauges.update(extra_gauges or {})
    for raw in sorted(gauges):
        name = _name(raw)
        lines.append(f"# TYPE {name} gauge")
        lines.append(f"{name} {_num(gauges[raw])}")

    for raw in sorted(snapshot.get("histograms", {})):
        h = snapshot["histograms"][raw]
        name = _name(raw)
        lines.append(f"# TYPE {name} summary")
        for q, key in (("0.5", "p50"), ("0.95", "p95"), ("0.99", "p99")):
            lines.append(f'{name}{{quantile="{q}"}} {_num(h[key])}')
        lines.append(f"{name}_sum {_num(h['sum'])}")
        lines.append(f"{name}_count {_num(h['count'])}")
        lines.append(f"# TYPE {name}_max gauge")
        lines.append(f"{name}_max {_num(h['max'])}")

    return "\n".join(lines) + "\n"
