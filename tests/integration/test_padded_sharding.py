"""Padded sharded-table representation (VERDICT r4 #5).

Non-divisible tables keep an exact row-block NamedSharding end-to-end: the
stored columns stay padded to a multiple of the device count with a sharded
row-validity mask, the compiled pipelines fold that mask into their
selection (pad rows never count, never aggregate, never join), and eager
paths take one `depad()` slice.
"""
import numpy as np
import pandas as pd
import pytest

import jax

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 2, reason="needs the virtual multi-device mesh")


@pytest.fixture()
def ctx7():
    """100_003 rows over 8 devices: maximally non-divisible."""
    from dask_sql_tpu import Context

    rng = np.random.RandomState(3)
    n = 100_003
    df = pd.DataFrame({
        "g": rng.randint(0, 5, n),
        "x": rng.rand(n),
        "k": rng.randint(0, 50, n),
    })
    c = Context()
    c.create_table("t", df, distributed=True)
    return c, df


def _stored_table(c, name="t"):
    return c.schema[c.schema_name].tables[name].table


def test_stored_columns_keep_exact_row_specs(ctx7):
    from jax.sharding import NamedSharding, PartitionSpec

    c, df = ctx7
    t = _stored_table(c)
    assert t.is_padded and t.num_rows == len(df)
    ndev = len(jax.devices())
    assert t.padded_rows % ndev == 0
    from dask_sql_tpu.parallel.mesh import AXIS

    for name, col in t.columns.items():
        sh = col.data.sharding
        assert isinstance(sh, NamedSharding), name
        assert sh.spec == PartitionSpec(AXIS), (
            f"column {name} lost its row-block spec: {sh.spec}")
    assert t.row_valid.sharding.spec == PartitionSpec(AXIS)


def test_padded_aggregate_values_exact(ctx7):
    c, df = ctx7
    got = c.sql("SELECT g, SUM(x) AS s, COUNT(*) AS n FROM t "
                "WHERE x > 0.25 GROUP BY g ORDER BY g", return_futures=False)
    sel = df[df.x > 0.25]
    exp = (sel.groupby("g", as_index=False)
           .agg(s=("x", "sum"), n=("x", "size")).sort_values("g"))
    np.testing.assert_allclose(got["s"], exp["s"], rtol=1e-9)
    assert list(got["n"].astype(np.int64)) == list(exp["n"])


def test_padded_global_aggregate(ctx7):
    c, df = ctx7
    got = c.sql("SELECT COUNT(*) AS n, SUM(x) AS s FROM t",
                return_futures=False)
    # pad rows must not inflate COUNT(*)
    assert int(got["n"][0]) == len(df)
    np.testing.assert_allclose(float(got["s"][0]), df.x.sum(), rtol=1e-9)


def test_padded_join_aggregate_pipeline(ctx7):
    c, df = ctx7
    dim = pd.DataFrame({"dk": np.arange(50), "w": np.arange(50) * 2.0})
    c.create_table("dim", dim)
    got = c.sql("SELECT g, SUM(w) AS sw FROM t JOIN dim ON k = dk "
                "GROUP BY g ORDER BY g", return_futures=False)
    m = df.merge(dim, left_on="k", right_on="dk")
    exp = m.groupby("g", as_index=False).agg(sw=("w", "sum")).sort_values("g")
    np.testing.assert_allclose(got["sw"], exp["sw"], rtol=1e-9)


def test_padded_eager_paths_depad(ctx7):
    c, df = ctx7
    # ORDER BY + LIMIT and plain selection go through eager operators
    got = c.sql("SELECT x FROM t ORDER BY x DESC LIMIT 5", return_futures=False)
    exp = df.x.nlargest(5).to_numpy()
    np.testing.assert_allclose(got["x"], exp, rtol=1e-9)
    assert len(c.sql("SELECT * FROM t", return_futures=False)) == len(df)


def test_divisible_tables_not_padded():
    from dask_sql_tpu import Context

    n = len(jax.devices()) * 1000
    c = Context()
    c.create_table("even", pd.DataFrame({"a": np.arange(n)}), distributed=True)
    t = _stored_table(c, "even")
    assert not t.is_padded and t.padded_rows == n


def test_padded_bare_count_star(ctx7):
    """Column-less aggregate: nr must come from the padded mask, not the
    logical count (review finding: shape mismatch crash)."""
    c, df = ctx7
    got = c.sql("SELECT COUNT(*) AS n FROM t", return_futures=False)
    assert int(got["n"][0]) == len(df)


def test_padded_to_arrow_depads(ctx7):
    c, df = ctx7
    at = _stored_table(c).to_arrow()
    assert at.num_rows == len(df)


def test_padded_assign_keeps_mask(ctx7):
    c, df = ctx7
    t = _stored_table(c)
    t2 = t.assign(extra=t.columns["x"])
    assert t2.is_padded and t2.num_rows == len(df)


def test_padded_checkpoint_roundtrip(ctx7, tmp_path):
    """save_state must persist logical rows only; restore re-shards."""
    from dask_sql_tpu import Context

    c, df = ctx7
    c.save_state(str(tmp_path / "snap"))
    c2 = Context()
    c2.load_state(str(tmp_path / "snap"))
    got = c2.sql("SELECT COUNT(*) AS n, SUM(x) AS s FROM t",
                 return_futures=False)
    assert int(got["n"][0]) == len(df)
    np.testing.assert_allclose(float(got["s"][0]), df.x.sum(), rtol=1e-9)


def test_padded_frame_filter_mask(ctx7):
    """A mask built from padded columns must never let pad rows through
    (review finding: zero-filled pad rows satisfying e.g. `x >= 0`)."""
    import jax.numpy as jnp

    c, df = ctx7
    t = _stored_table(c)
    mask = t.columns["x"].data >= 0.0  # padded length; pad rows are 0.0 -> True
    assert int(mask.shape[0]) == t.padded_rows
    out = t.filter(mask)
    assert out.num_rows == int((df.x >= 0).sum())
