"""Observability subsystem: query-lifecycle tracing, per-fingerprint
profiles (SHOW PROFILES + checkpoint persistence), Prometheus exposition,
the slow-query log, and trace isolation across concurrent server workers.
"""
import json
import threading
import time
import urllib.request

import numpy as np
import pandas as pd
import pytest

from dask_sql_tpu import Context
from dask_sql_tpu import config as config_module
from dask_sql_tpu.observability import (
    ProfileStore,
    QueryTrace,
    TraceStore,
    activate,
    current_trace,
    render_prometheus,
)
from dask_sql_tpu.serving.metrics import MetricsRegistry
from dask_sql_tpu.tracing import NodeTrace

pytestmark = pytest.mark.observability


def _ctx(rows=32, name="t"):
    c = Context()
    c.create_table(name, pd.DataFrame({
        "a": np.arange(rows, dtype=np.int64),
        "b": np.arange(rows, dtype=np.float64) * 1.5,
    }))
    return c


# ------------------------------------------------------------ span model
def test_lifecycle_stages_present_and_monotonic():
    c = _ctx()
    c.sql("SELECT a, b FROM t WHERE a > 3", return_futures=False)
    tr = c.last_trace
    assert tr is not None
    stages = tr.stage_spans()
    names = [s.name for s in stages]
    for required in ("parse", "bind", "verify", "estimate", "cache_lookup",
                     "execute", "d2h"):
        assert required in names, names
    # stages are sequential: each closes before the next opens
    for left, right in zip(stages, stages[1:]):
        assert left.t1 <= right.t0 + 1e-9, (left.name, right.name)


def test_plan_cache_hit_skips_parse_span():
    c = _ctx()
    sql = "SELECT SUM(a) AS s FROM t"
    c.sql(sql, return_futures=False)
    c.sql(sql, return_futures=False)
    tr = c.last_trace
    assert not tr.has_span("parse")
    assert any(s.name == "plan_cache_hit" for s in tr.spans)


def test_trace_disabled_by_config():
    c = _ctx()
    config_module.config.update({"observability.trace.enabled": False})
    try:
        c.last_trace = None
        c.sql("SELECT a FROM t", return_futures=False)
        assert c.last_trace is None
    finally:
        config_module.config.update({"observability.trace.enabled": True})


def test_compile_span_and_metric_recorded():
    c = Context()
    # unique column names => a plan shape no earlier test compiled, so the
    # jit cache MUST grow on first execution
    c.create_table("fresh_ct", pd.DataFrame({
        "zq_one": np.arange(40, dtype=np.int64),
        "zq_two": np.arange(40, dtype=np.float64),
    }))
    c.sql("SELECT zq_one FROM fresh_ct WHERE zq_one > 7",
          return_futures=False)
    tr = c.last_trace
    compiles = [s for s in tr.spans if s.name == "compile:compiled_select"]
    assert compiles, [s.name for s in tr.spans]
    assert all(s.parent == "execute" for s in compiles)
    snap = c.metrics.snapshot()
    assert "resilience.compile_ms.compiled_select" in snap["histograms"]
    # the profile store saw the compile under this plan's fingerprint
    prof = c.profiles.get(tr.fingerprint)
    assert prof is not None and "compiled_select" in prof["compile"]


def test_result_cache_hit_event_and_profile_hit():
    c = _ctx()
    sql = "SELECT MAX(b) AS m FROM t"
    c.sql(sql, return_futures=False)
    c.sql(sql, return_futures=False)
    tr = c.last_trace
    assert any(s.name == "result_cache_hit" for s in tr.spans)
    prof = c.profiles.get(tr.fingerprint)
    assert prof["hits"] == 2 and prof["cache_hits"] == 1


def test_chrome_trace_export_shape():
    tr = QueryTrace(sql="SELECT 1", metrics=None, profiles=None)
    with tr.span("parse"):
        pass
    tr.event("plan_cache_hit")
    payload = tr.to_chrome_trace()
    assert payload["displayTimeUnit"] == "ms"
    phases = {e["ph"] for e in payload["traceEvents"]}
    assert {"M", "X", "i"} <= phases
    x = [e for e in payload["traceEvents"] if e["ph"] == "X"][0]
    assert x["name"] == "parse" and x["dur"] >= 0
    assert payload["otherData"]["sql"] == "SELECT 1"


def test_activation_is_scoped_per_thread():
    seen = {}

    def worker(i):
        tr = QueryTrace(sql=f"q{i}")
        with activate(tr):
            time.sleep(0.01)
            seen[i] = current_trace().sql

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert seen == {i: f"q{i}" for i in range(8)}
    assert current_trace() is None


# ---------------------------------------------------------- NodeTrace fix
def test_node_trace_format_unknown_rows_and_events():
    root = NodeTrace("Projection", "Projection: x", 2.0, -1, [
        NodeTrace("Resilience", "degraded: compiled_select [OOM]", 0.0, -1),
        NodeTrace("TableScan", "TableScan: t", 1.0, 10),
    ])
    text = root.format()
    assert "? rows" in text
    assert "-1 rows" not in text
    assert "!! degraded: compiled_select [OOM]" in text
    assert "0.00 ms" not in text  # the event marker renders label-only
    assert "[1.00 ms, 10 rows]" in text


# --------------------------------------------------------- EXPLAIN ANALYZE
def test_explain_analyze_lifecycle_header():
    c = _ctx()
    rows = list(c.sql("EXPLAIN ANALYZE SELECT a FROM t WHERE a > 5",
                      return_futures=False)["PLAN"])
    header = [r for r in rows if r.startswith("-- query lifecycle")]
    assert header, rows
    assert any(r.strip().startswith("parse") for r in rows)
    assert any(r.strip().startswith("bind") for r in rows)
    assert any("TableScan" in r for r in rows)


def test_explain_format_json_without_analyze_rejected():
    """FORMAT JSON only pairs with ANALYZE — both parsers reject the
    combination instead of silently returning text a JSON client would
    choke on."""
    from dask_sql_tpu.planner.parser import ParsingException

    c = _ctx()
    for native in ("auto", "off"):
        config_module.config.update({"sql.native.binder": native})
        try:
            with pytest.raises(ParsingException):
                c.sql("EXPLAIN FORMAT JSON SELECT a FROM t",
                      return_futures=False)
        finally:
            config_module.config.update({"sql.native.binder": "auto"})


def test_repeated_compute_does_not_duplicate_d2h_stage():
    c = _ctx()
    frame = c.sql("SELECT a FROM t WHERE a > 4")
    frame.compute()
    frame.compute()
    tr = c.last_trace
    assert sum(1 for s in tr.spans if s.name == "d2h") == 1
    assert tr.finished


def test_d2h_metric_records_with_tracing_disabled():
    c = _ctx(name="d2h_t")
    config_module.config.update({"observability.trace.enabled": False})
    try:
        c.sql("SELECT a FROM d2h_t", return_futures=False)
        assert "query.d2h_ms" in c.metrics.snapshot()["histograms"]
    finally:
        config_module.config.update({"observability.trace.enabled": True})


def test_explain_analyze_format_json_both_parsers():
    c = _ctx()
    for native in ("auto", "off"):
        config_module.config.update({"sql.native.binder": native})
        try:
            out = c.sql(
                "EXPLAIN ANALYZE FORMAT JSON SELECT a FROM t WHERE a > 5",
                return_futures=False)
            payload = json.loads(out["PLAN"][0])
            assert payload["displayTimeUnit"] == "ms"
            names = [e["name"] for e in payload["traceEvents"]
                     if e.get("ph") == "X"]
            assert "parse" in names and "TableScan" in names
        finally:
            config_module.config.update({"sql.native.binder": "auto"})


# ------------------------------------------------------------ SHOW PROFILES
def test_show_profiles_statement_both_parsers():
    c = _ctx()
    c.sql("SELECT SUM(a) AS s FROM t", return_futures=False)
    for native in ("auto", "off"):
        config_module.config.update({"sql.native.binder": native})
        try:
            df = c.sql("SHOW PROFILES", return_futures=False)
            assert list(df.columns) == ["Fingerprint", "Family", "Metric",
                                        "Value"]
            metrics = set(df["Metric"])
            assert {"sql", "hits", "exec_ms.p50"} <= metrics
        finally:
            config_module.config.update({"sql.native.binder": "auto"})


def test_show_profiles_like_filters_fingerprint_and_metric():
    c = _ctx()
    c.sql("SELECT COUNT(*) AS n FROM t", return_futures=False)
    fp = c.last_trace.fingerprint
    by_fp = c.sql(f"SHOW PROFILES LIKE '{fp[:8]}%'", return_futures=False)
    assert set(by_fp["Fingerprint"]) == {fp}
    by_metric = c.sql("SHOW PROFILES LIKE 'hits'", return_futures=False)
    assert set(by_metric["Metric"]) == {"hits", "cache_hits"}


def test_profile_store_rolling_window():
    store = ProfileStore(window=4, keep=2)
    for i in range(10):
        store.record_exec("fp1", sql="q", exec_ms=float(i))
    assert store.get("fp1")["exec_ms"] == [6.0, 7.0, 8.0, 9.0]
    store.record_exec("fp2", exec_ms=1.0)
    store.record_exec("fp3", exec_ms=1.0)  # keep=2 evicts LRU fp1
    assert store.get("fp1") is None and len(store) == 2


def test_profile_store_snapshot_load_round_trip():
    store = ProfileStore(window=8)
    store.record_exec("abc123", sql="SELECT 1", exec_ms=5.5,
                      result_bytes=128)
    store.record_compile("abc123", "compiled_select", 42.0)
    restored = ProfileStore(window=8)
    assert restored.load(json.loads(json.dumps(store.snapshot()))) == 1
    assert restored.get("abc123") == store.get("abc123")
    assert restored.top_fingerprints(1) == ["abc123"]


def test_checkpoint_persists_profiles(tmp_path):
    c = _ctx(name="ckpt_src")
    c.sql("SELECT SUM(a) AS s FROM ckpt_src", return_futures=False)
    fp = c.last_trace.fingerprint
    manifest = c.save_state(str(tmp_path))
    assert manifest["profiles"] == "profiles.json"

    c2 = Context()
    c2.load_state(str(tmp_path))
    prof = c2.profiles.get(fp)
    assert prof is not None and prof["hits"] >= 1
    df = c2.sql("SHOW PROFILES", return_futures=False)
    assert fp in set(df["Fingerprint"])


# -------------------------------------------------------------- prometheus
def test_prometheus_exposition_golden():
    reg = MetricsRegistry()
    reg.inc("query.executed", 3)
    reg.gauge("serving.depth", 2.5)
    for v in (1.0, 2.0, 4.0):
        reg.observe("serving.latency_ms", v)
    text = render_prometheus(reg.snapshot())
    assert text == (
        "# TYPE dsql_query_executed_total counter\n"
        "dsql_query_executed_total 3\n"
        "# TYPE dsql_query_cache_hit_rate gauge\n"
        "dsql_query_cache_hit_rate 0\n"
        "# TYPE dsql_serving_depth gauge\n"
        "dsql_serving_depth 2.5\n"
        "# TYPE dsql_serving_latency_ms summary\n"
        'dsql_serving_latency_ms{quantile="0.5"} 2\n'
        'dsql_serving_latency_ms{quantile="0.95"} 4\n'
        'dsql_serving_latency_ms{quantile="0.99"} 4\n'
        "dsql_serving_latency_ms_sum 7\n"
        "dsql_serving_latency_ms_count 3\n"
        "# TYPE dsql_serving_latency_ms_max gauge\n"
        "dsql_serving_latency_ms_max 4\n"
    )


def test_prometheus_extra_gauges_and_sanitization():
    reg = MetricsRegistry()
    reg.inc("executor.node.TableScan.rows", 7)
    text = render_prometheus(reg.snapshot(),
                             extra_gauges={"serving.queue_depth": 1})
    assert "dsql_executor_node_TableScan_rows_total 7" in text
    assert "dsql_serving_queue_depth 1" in text


# ------------------------------------------------------------ slow queries
def test_slow_query_log_threshold(tmp_path):
    log = tmp_path / "slow.jsonl"
    c = _ctx(name="slow_t")
    config_module.config.update({
        "observability.slow_query_ms": 0,  # log every query
        "observability.slow_query_path": str(log),
    })
    try:
        c.sql("SELECT a FROM slow_t WHERE a > 1", return_futures=False)
        lines = log.read_text().strip().splitlines()
        assert len(lines) == 1
        record = json.loads(lines[0])
        assert record["sql"].startswith("SELECT a FROM slow_t")
        span_names = {s["name"] for s in record["spans"]}
        assert {"parse", "execute", "d2h"} <= span_names
        assert c.metrics.counter("observability.slow_query") == 1

        # far-above-threshold: nothing new is written
        config_module.config.update({"observability.slow_query_ms": 1e12})
        c.sql("SELECT a FROM slow_t WHERE a > 2", return_futures=False)
        assert len(log.read_text().strip().splitlines()) == 1
    finally:
        config_module.config.update({"observability.slow_query_ms": None,
                                     "observability.slow_query_path": None})


def test_failed_query_trace_finished_and_slow_logged(tmp_path):
    """A failing query's lifecycle must still finish and reach the
    slow-query log — timeouts and failures ARE the outliers worth
    debugging."""
    from dask_sql_tpu.resilience import faults
    from dask_sql_tpu.resilience.errors import QueryError

    log = tmp_path / "slow_fail.jsonl"
    c = _ctx(name="fail_t")
    faults.reset()
    config_module.config.update({
        "observability.slow_query_ms": 0,
        "observability.slow_query_path": str(log),
        "resilience.inject": "execute:always",
        "serving.cache.enabled": False,
    })
    try:
        with pytest.raises(QueryError):
            c.sql("SELECT a FROM fail_t", return_futures=False)
        tr = c.last_trace
        assert tr.finished
        execute = [s for s in tr.spans if s.name == "execute"]
        assert execute and execute[0].attrs.get("error")
        records = [json.loads(ln) for ln in
                   log.read_text().strip().splitlines()]
        assert any(r["sql"].startswith("SELECT a FROM fail_t")
                   for r in records)
    finally:
        faults.reset()
        config_module.config.update({
            "observability.slow_query_ms": None,
            "observability.slow_query_path": None,
            "resilience.inject": None,
            "serving.cache.enabled": True,
        })


def test_slow_query_config_options_gate_that_querys_failure(tmp_path):
    """Per-query config_options must still be in scope when a FAILING
    query runs its slow-query check (the finish hook fires inside the
    per-query config overlay, not after it pops)."""
    from dask_sql_tpu.resilience import faults
    from dask_sql_tpu.resilience.errors import QueryError

    log = tmp_path / "slow_opt.jsonl"
    c = _ctx(name="opt_t")
    faults.reset()
    try:
        with pytest.raises(QueryError):
            c.sql("SELECT a FROM opt_t", return_futures=False,
                  config_options={
                      "observability.slow_query_ms": 0,
                      "observability.slow_query_path": str(log),
                      "resilience.inject": "execute:always",
                      "resilience.ladder.enabled": False,
                      "serving.cache.enabled": False,
                  })
        assert log.exists() and log.read_text().strip()
    finally:
        faults.reset()


def test_compile_metrics_survive_tracing_disabled():
    """resilience.compile_ms.* and the profile store must record through
    the compile sink even when lifecycle tracing is off."""
    c = Context()
    c.create_table("notrace_ct", pd.DataFrame({
        "nt_col": np.arange(48, dtype=np.int64)}))
    config_module.config.update({"observability.trace.enabled": False})
    try:
        c.sql("SELECT nt_col FROM notrace_ct WHERE nt_col > 11",
              return_futures=False)
        assert c.last_trace is None
        snap = c.metrics.snapshot()
        assert "resilience.compile_ms.compiled_select" in snap["histograms"]
        rows = c.profiles.rows()
        assert any(m == "compile.compiled_select.count"
                   for _, _, m, _ in rows)
        assert any(m == "hits" for _, _, m, _ in rows)
    finally:
        config_module.config.update({"observability.trace.enabled": True})


def test_add_span_once_is_atomic():
    tr = QueryTrace(qid="q")
    results = []

    def add():
        results.append(tr.add_span_once("serialize", 0.0, 1.0))

    threads = [threading.Thread(target=add) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert results.count(True) == 1
    assert sum(1 for s in tr.spans if s.name == "serialize") == 1


def test_trace_store_lru_bound():
    store = TraceStore(keep=2)
    for i in range(4):
        store.put(f"q{i}", QueryTrace(qid=f"q{i}"))
    assert len(store) == 2
    assert store.get("q0") is None and store.get("q3") is not None


# ---------------------------------------------------------------- the wire
def _post(port, sql, headers=None):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/statement", data=sql.encode(),
        method="POST")
    for k, v in (headers or {}).items():
        req.add_header(k, v)
    with urllib.request.urlopen(req) as resp:
        return json.loads(resp.read())


def _follow(port, payload, timeout=60):
    deadline = time.time() + timeout
    while time.time() < deadline:
        with urllib.request.urlopen(payload["nextUri"]) as resp:
            status = json.loads(resp.read())
        if status.get("error") or "data" in status or "columns" in status:
            return status
        time.sleep(0.02)
    raise AssertionError("query did not finish")


def _get_json(port, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}") as resp:
        return json.loads(resp.read())


@pytest.fixture
def obs_server():
    from dask_sql_tpu.server.app import run_server

    c = Context()
    c.create_table("wire_t", pd.DataFrame({
        "wq_a": np.arange(128, dtype=np.int64),
        "wq_b": np.arange(128, dtype=np.float64) * 0.5,
    }))
    srv = run_server(context=c, host="127.0.0.1", port=0, blocking=False)
    yield c, srv
    srv.shutdown()


def test_wire_trace_acceptance(obs_server):
    """The acceptance criterion: a query served through the Presto wire
    yields a /v1/trace/{qid} Chrome trace containing queue-wait, parse,
    bind, verify, estimate, compile, execute and d2h spans with monotonic
    non-overlapping stage timestamps."""
    c, srv = obs_server
    payload = _post(srv.port, "SELECT wq_a, wq_b FROM wire_t WHERE wq_a > 9")
    status = _follow(srv.port, payload)
    assert "data" in status
    qid = payload["id"]
    trace = _get_json(srv.port, f"/v1/trace/{qid}")
    events = [e for e in trace["traceEvents"] if e.get("ph") == "X"]
    names = {e["name"] for e in events}
    for required in ("queue_wait", "parse", "bind", "verify", "estimate",
                     "execute", "d2h", "serialize"):
        assert required in names, names
    assert any(n.startswith("compile:") for n in names), names
    stages = sorted((e for e in events if e.get("cat") == "stage"),
                    key=lambda e: e["ts"])
    for left, right in zip(stages, stages[1:]):
        assert left["ts"] + left["dur"] <= right["ts"] + 1.0, (
            left["name"], right["name"])
    # compile spans nest inside the execute stage
    execute = next(e for e in stages if e["name"] == "execute")
    for e in events:
        if e["name"].startswith("compile:"):
            assert e["ts"] >= execute["ts"] - 1.0
            assert e["ts"] + e["dur"] <= execute["ts"] + execute["dur"] + 1.0
    # unknown qid -> 404
    try:
        urllib.request.urlopen(f"http://127.0.0.1:{srv.port}/v1/trace/ghost")
        raise AssertionError("expected 404")
    except urllib.error.HTTPError as e:
        assert e.code == 404


def test_wire_prometheus_endpoint(obs_server):
    c, srv = obs_server
    payload = _post(srv.port, "SELECT COUNT(*) AS n FROM wire_t")
    _follow(srv.port, payload)
    req = urllib.request.urlopen(
        f"http://127.0.0.1:{srv.port}/v1/metrics?format=prometheus")
    assert req.headers["Content-Type"].startswith(
        "text/plain; version=0.0.4")
    text = req.read().decode()
    assert "dsql_query_executed_total" in text
    assert 'dsql_query_execute_ms{quantile="0.5"}' in text
    assert "dsql_serving_queue_depth" in text
    # the JSON default is untouched
    assert "registry" in _get_json(srv.port, "/v1/metrics")


def test_concurrent_explain_analyze_trace_isolation(obs_server):
    """8 Presto worker threads running EXPLAIN ANALYZE simultaneously must
    not interleave span trees: each trace carries exactly one parse/bind/
    execute stage and references only its own table."""
    c, srv = obs_server
    for i in range(8):
        c.create_table(f"iso_{i}", pd.DataFrame({
            f"col_{i}": np.arange(64 + i, dtype=np.int64)}))
    payloads = {}
    errors = []

    def submit(i):
        try:
            payloads[i] = _post(
                srv.port,
                f"EXPLAIN ANALYZE SELECT col_{i} FROM iso_{i} "
                f"WHERE col_{i} > {i}")
        except Exception as e:  # surfaced via the errors list
            errors.append(e)

    threads = [threading.Thread(target=submit, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    for i in range(8):
        status = _follow(srv.port, payloads[i])
        rows = [r[0] for r in status["data"]]
        # the report's node tree references only this query's table
        assert any(f"iso_{i}" in r for r in rows), rows
        assert not any(f"iso_{(i + 1) % 8}" in r for r in rows)
        trace = _get_json(srv.port, f"/v1/trace/{payloads[i]['id']}")
        assert trace["otherData"]["sql"].endswith(
            f"col_{i} > {i}")
        stage_names = [e["name"] for e in trace["traceEvents"]
                       if e.get("cat") == "stage"]
        for stage in ("parse", "bind", "execute"):
            assert stage_names.count(stage) == 1, (i, stage_names)
        # this query's node-tree details landed on this trace only
        details = [e["args"].get("label", "") for e in trace["traceEvents"]
                   if e.get("cat") == "detail"]
        scans = [d for d in details if d.startswith("TableScan")]
        assert scans and all(f"iso_{i}" in d for d in scans), details
