"""Bounded retry with exponential backoff + jitter, and a per-plan-
fingerprint circuit breaker.

Retry runs at the ServingRuntime worker level (serving/runtime.py wraps each
admitted query in `retry_call`): only errors the taxonomy marks `retryable`
are retried, the backoff respects the ticket's deadline (never sleeps past
it) and its cancellation flag (a cancel during backoff aborts immediately).
Jitter is deterministic given (seed, attempt) so test runs reproduce.

The breaker protects the degradation ladder (resilience/ladder.py): a plan
fingerprint whose compiled rung failed `threshold` consecutive times skips
that rung for `cooldown_s` and goes straight to its known-good rung, instead
of paying the failure again on every submission.
"""
from __future__ import annotations

import logging
import random
import threading
import time
from typing import Callable, Optional, Tuple, TypeVar

from .errors import classify

logger = logging.getLogger(__name__)

T = TypeVar("T")


class BackoffPolicy:
    """Exponential backoff: base * multiplier^attempt, capped, jittered.

    `max_attempts` counts total tries (1 = no retry).  Jitter multiplies
    each delay by a factor drawn uniformly from [1-jitter, 1+jitter] using
    a PRNG seeded per-policy, so retries desynchronize across workers while
    a fixed seed reproduces the exact schedule."""

    def __init__(self, max_attempts: int = 3, base_s: float = 0.05,
                 multiplier: float = 2.0, max_s: float = 2.0,
                 jitter: float = 0.5, seed: Optional[int] = None):
        self.max_attempts = max(1, int(max_attempts))
        self.base_s = float(base_s)
        self.multiplier = float(multiplier)
        self.max_s = float(max_s)
        self.jitter = min(1.0, max(0.0, float(jitter)))
        self._rng = random.Random(seed)

    @classmethod
    def from_config(cls, config) -> "BackoffPolicy":
        # the jitter PRNG is pinned to the inject seed ONLY while fault
        # injection is active (reproducible tests); in production it must
        # stay unseeded, or every replica would draw the identical jitter
        # sequence and retries would re-synchronize instead of spreading
        seed = config.get("resilience.inject.seed") \
            if config.get("resilience.inject") else None
        return cls(
            max_attempts=int(config.get("resilience.retry.max_attempts", 3)),
            base_s=float(config.get("resilience.retry.base_s", 0.05)),
            multiplier=float(config.get("resilience.retry.multiplier", 2.0)),
            max_s=float(config.get("resilience.retry.max_s", 2.0)),
            jitter=float(config.get("resilience.retry.jitter", 0.5)),
            seed=seed,
        )

    def delay_s(self, attempt: int) -> float:
        """Backoff before retry number `attempt` (1-based)."""
        raw = min(self.max_s, self.base_s * (self.multiplier ** (attempt - 1)))
        if self.jitter:
            raw *= 1.0 + self.jitter * (2.0 * self._rng.random() - 1.0)
        return max(0.0, raw)


def retry_call(fn: Callable[[], T], policy: BackoffPolicy,
               ticket=None, metrics=None,
               sleep: Callable[[float], None] = time.sleep) -> T:
    """Run `fn`, retrying taxonomy-retryable failures with backoff.

    Non-retryable errors (user errors, cancels, deadline expiry, permanent
    execution failures) propagate on the first throw.  A retryable error is
    re-raised once attempts are exhausted or the ticket's deadline cannot
    absorb the next backoff sleep."""
    attempt = 1
    while True:
        try:
            result = fn()
        except BaseException as exc:  # dsql: allow-broad-except — classified below
            err = classify(exc)
            if not err.retryable or attempt >= policy.max_attempts:
                raise
            delay = policy.delay_s(attempt)
            if ticket is not None:
                remaining = ticket.remaining_s()
                if remaining is not None and delay >= remaining:
                    # the backoff alone would blow the deadline: surface the
                    # original failure now, with time left to report it
                    if metrics is not None:
                        metrics.inc("resilience.retry.deadline_abort")
                    raise
            if metrics is not None:
                metrics.inc("resilience.retry.attempts")
                metrics.observe("resilience.retry.backoff_ms", delay * 1000.0)
            logger.debug("retrying after %s (attempt %d/%d, backoff %.3fs)",
                         err.code, attempt, policy.max_attempts, delay)
            sleep(delay)
            if ticket is not None:
                ticket.checkpoint()  # cancel/deadline during backoff
            attempt += 1
            continue
        if attempt > 1 and metrics is not None:
            metrics.inc("resilience.retry.recovered")
        return result


class CircuitBreaker:
    """Per-key consecutive-failure breaker with cooldown.

    Keys are (plan fingerprint, rung name) tuples from the degradation
    ladder.  After `threshold` consecutive failures `allow` returns False
    until `cooldown_s` has elapsed, after which ONE trial is admitted
    (half-open); its outcome closes or re-opens the circuit.  Admitting the
    trial re-arms the cooldown clock rather than setting a sticky flag, so
    a trial that never settles (the rung *declines* instead of succeeding
    or failing) costs one more cooldown, not a permanently-open circuit."""

    def __init__(self, threshold: int = 3, cooldown_s: float = 30.0,
                 max_keys: int = 1024,
                 clock: Callable[[], float] = time.monotonic):
        self.threshold = max(1, int(threshold))
        self.cooldown_s = float(cooldown_s)
        self.max_keys = int(max_keys)
        self._clock = clock
        self._lock = threading.Lock()
        #: key -> [consecutive_failures, opened_at or None]
        self._state: dict = {}

    @classmethod
    def from_config(cls, config) -> "CircuitBreaker":
        return cls(
            threshold=int(config.get("resilience.breaker.threshold", 3)),
            cooldown_s=float(config.get("resilience.breaker.cooldown_s", 30.0)),
        )

    def allow(self, key: Tuple) -> bool:
        with self._lock:
            st = self._state.get(key)
            if st is None or st[1] is None:
                return True
            if self._clock() - st[1] >= self.cooldown_s:
                # admit one half-open trial and re-arm the cooldown: peers
                # stay blocked for another window, and a trial that never
                # settles (rung declined) simply waits out one more cooldown
                st[1] = self._clock()
                return True
            return False

    def record_failure(self, key: Tuple) -> bool:
        """Count a failure; returns True when this call TRIPS the breaker
        (transition closed -> open), so callers can emit the trip metric
        exactly once."""
        with self._lock:
            st = self._state.setdefault(key, [0, None])
            st[0] += 1
            tripped = st[1] is None and st[0] >= self.threshold
            if st[0] >= self.threshold:
                st[1] = self._clock()
            self._evict_locked()
            return tripped

    def record_success(self, key: Tuple) -> bool:
        """Clear the key's failure state; returns True when this success
        closed an OPEN circuit (the half-open trial passed), so callers
        can record the restore exactly once."""
        with self._lock:
            st = self._state.pop(key, None)
            return bool(st is not None and st[1] is not None)

    def is_open(self, key: Tuple) -> bool:
        with self._lock:
            st = self._state.get(key)
            return bool(st and st[1] is not None)

    def snapshot(self) -> dict:
        with self._lock:
            open_keys = sum(1 for st in self._state.values()
                            if st[1] is not None)
            return {"keys": len(self._state), "open": open_keys,
                    "threshold": self.threshold,
                    "cooldownSeconds": self.cooldown_s}

    # ------------------------------------------------------- persistence
    def snapshot_state(self) -> dict:
        """JSON-ready snapshot of the OPEN circuits (checkpoint.py writes
        this as breaker.json): a restarted process should not re-prove
        rungs this one already proved bad.  Only open verdicts persist —
        sub-threshold failure streaks are too cheap to be worth staleness.
        Ages are relative (monotonic clocks do not survive a process), and
        `saved_at` wall time lets the loader add the downtime on top."""
        now = self._clock()
        with self._lock:
            entries = [
                {"key": list(key), "failures": int(st[0]),
                 "open_age_s": round(now - st[1], 3)}
                for key, st in self._state.items() if st[1] is not None
            ]
        return {"version": 1, "saved_at": time.time(), "open": entries}

    def load_state(self, data: dict, ttl_s: float) -> int:
        """Restore open circuits younger than `ttl_s` (open age at save
        plus the wall-clock downtime since).  Bounded staleness: the data
        that tripped a breaker may be gone after a restart, so verdicts
        expire instead of sticking forever; a restored circuit whose
        cooldown already elapsed simply admits its half-open trial on
        first use.  Returns the number of circuits restored."""
        if not data:
            return 0
        stale_s = max(0.0, time.time() - float(data.get("saved_at") or 0.0))
        now = self._clock()
        restored = 0
        with self._lock:
            for e in data.get("open") or []:
                try:
                    key = tuple(e["key"])
                    age = float(e.get("open_age_s") or 0.0) + stale_s
                    failures = int(e.get("failures", self.threshold))
                except (KeyError, TypeError, ValueError):
                    continue  # malformed entry: skip, never fail the load
                if age >= ttl_s:
                    continue
                self._state[key] = [max(failures, self.threshold), now - age]
                restored += 1
            self._evict_locked()
        return restored

    def _evict_locked(self) -> None:
        # bounded memory: drop oldest entries past the cap (dict preserves
        # insertion order; breaker state is advisory, losing one is safe)
        while len(self._state) > self.max_keys:
            self._state.pop(next(iter(self._state)))
