"""Join converter.

Role parity: reference join.py:23 (equijoin extraction already done by the
binder/`split_join_condition`; NULL-key filtering join.py:202-213; leftanti
via indicator join.py:229-239; residual conditions as post-filter
join.py:170-181; cross join via constant column join.py:133-142).  TPU-first
mechanism: joint key factorization + sort/searchsorted probe
(ops/join.py), no hash shuffle needed on a single device; the distributed
path hash-shards both sides with collectives first (parallel/shuffle.py).
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ....columnar.table import Table
from ....ops import join as join_ops
from ....planner import plan as p
from ....planner.expressions import shift_columns
from ..base import BaseRelPlugin, unique_names
from ...executor import Executor


def _cross_indices(nl: int, nr: int):
    li = jnp.repeat(jnp.arange(nl, dtype=jnp.int64), nr)
    ri = jnp.tile(jnp.arange(nr, dtype=jnp.int64), nl)
    return li, ri


def _materialize(left: Table, right: Table, li, ri,
                 l_may_pad=None, r_may_pad=None) -> Table:
    """Gather a combined table from index pairs; -1 indices produce NULLs.

    `l_may_pad`/`r_may_pad` pass the static pad-possibility per side (inner
    matches never pad) so the per-column content sync in take_with_nulls is
    skipped; None keeps the dynamic check."""
    names = unique_names(list(left.column_names) + list(right.column_names))
    cols = {}
    for name, src in zip(names[: len(left.column_names)], left.column_names):
        cols[name] = join_ops.take_with_nulls(left.columns[src], li, l_may_pad)
    for name, src in zip(names[len(left.column_names):], right.column_names):
        cols[name] = join_ops.take_with_nulls(right.columns[src], ri, r_may_pad)
    return Table(cols, int(li.shape[0]))


@Executor.add_plugin_class
class JoinPlugin(BaseRelPlugin):
    class_name = "Join"

    def convert(self, rel: p.Join, executor) -> Table:
        left, right = self.assert_inputs(rel, 2, executor)
        nleft = len(rel.left.schema)
        jt = rel.join_type
        # jitted probe phase: 'auto' enables it on accelerator backends where
        # per-op dispatch round trips dominate
        mode = str(executor.config.get("sql.compile.join", "auto")).lower()
        if mode == "auto":
            import jax

            use_jit = jax.default_backend() not in ("cpu",)
        elif mode in ("jit", "true", "on"):
            use_jit = True
        elif mode in ("off", "false", "eager"):
            use_jit = False
        else:
            raise ValueError(
                f"sql.compile.join must be auto/jit/off, got {mode!r}")

        if rel.on:
            lkeys = [executor.eval_expr(l, left) for l, _ in rel.on]
            rkeys = [executor.eval_expr(shift_columns(r, -nleft), right) for _, r in rel.on]
            lgid, rgid = join_ops.join_key_gids(lkeys, rkeys)
        else:
            # no equi keys: every row matches every row (filtered below)
            lkeys = rkeys = []
            lgid = jnp.zeros(left.num_rows, dtype=jnp.int64)
            rgid = jnp.zeros(right.num_rows, dtype=jnp.int64)

        if jt == "LEFTANTI" and rel.null_aware:
            return self.fix_column_to_row_type(
                self._null_aware_anti(left, lkeys, rkeys, lgid, rgid,
                                      right.num_rows),
                rel.schema)

        # collectives-routed distributed join (all_to_all shuffle + local
        # probe) when an input is mesh-sharded; a small build side instead
        # stays replicated = broadcast join (`sql.join.broadcast` parity,
        # reference join.py:228)
        dist_pairs = None
        if rel.on:
            dist_pairs = self._maybe_dist_pairs(
                executor, left, right, lkeys, rkeys, lgid, rgid)
        if dist_pairs is not None:
            li, ri, lmatched = dist_pairs
            if jt == "LEFTMARK":
                # matched flag from the collectives probe — no local resort
                if rel.filter is None:
                    mask = jnp.asarray(lmatched)
                else:
                    mask = self._filtered_match_mask(rel, executor, left,
                                                     right, li, ri)
                return self.fix_column_to_row_type(
                    self._append_mark(rel, left, mask), rel.schema)
            if jt in ("LEFTSEMI", "LEFTANTI"):
                if rel.filter is None:
                    mask = jnp.asarray(lmatched)
                else:
                    mask = self._filtered_match_mask(rel, executor, left,
                                                     right, li, ri)
                if jt == "LEFTANTI":
                    mask = ~mask
                return self.fix_column_to_row_type(left.filter(mask),
                                                   rel.schema)
            if jt == "INNER":
                combined = _materialize(left, right, li, ri, False, False)
                if rel.filter is not None:
                    cond = executor.eval_expr(rel.filter, combined)
                    combined = combined.filter(cond.data & cond.valid_mask())
                return self.fix_column_to_row_type(combined, rel.schema)
            if jt in ("LEFT", "RIGHT", "FULL"):
                return self._outer_from_pairs(rel, executor, left, right, li, ri, jt)
            raise NotImplementedError(f"join type {jt}")

        if jt == "LEFTMARK":
            # semi-join as a boolean column: left rows pass through with an
            # appended matched flag (decorrelation of EXISTS under OR)
            if rel.filter is None:
                mask = join_ops.semi_join_mask(lgid, rgid)
            else:
                li, ri = join_ops.inner_join_indices(lgid, rgid, use_jit)
                mask = self._filtered_match_mask(rel, executor, left, right,
                                                 li, ri)
            return self.fix_column_to_row_type(
                self._append_mark(rel, left, mask), rel.schema)

        if jt in ("LEFTSEMI", "LEFTANTI"):
            if rel.filter is None:
                mask = join_ops.semi_join_mask(lgid, rgid, anti=(jt == "LEFTANTI"))
                return self.fix_column_to_row_type(left.filter(mask), rel.schema)
            li, ri = join_ops.inner_join_indices(lgid, rgid, use_jit)
            matched = self._filtered_match_mask(rel, executor, left, right,
                                                li, ri)
            if jt == "LEFTANTI":
                matched = ~matched
            return self.fix_column_to_row_type(left.filter(matched), rel.schema)

        if jt == "INNER":
            # probe from the bigger side so the build sort runs on the smaller
            # one (parity intent: reference broadcast-join small-side choice)
            if right.num_rows <= left.num_rows:
                li, ri = join_ops.inner_join_indices(lgid, rgid, use_jit)
            else:
                ri, li = join_ops.inner_join_indices(rgid, lgid, use_jit)
            combined = _materialize(left, right, li, ri, False, False)
            if rel.filter is not None:
                cond = executor.eval_expr(rel.filter, combined)
                combined = combined.filter(cond.data & cond.valid_mask())
            return self.fix_column_to_row_type(combined, rel.schema)

        if jt in ("LEFT", "RIGHT", "FULL"):
            li, ri = join_ops.inner_join_indices(lgid, rgid, use_jit)
            return self._outer_from_pairs(rel, executor, left, right, li, ri, jt)

        raise NotImplementedError(f"join type {jt}")

    def _filtered_match_mask(self, rel, executor, left, right, li, ri):
        """Per-left-row matched flag under the residual filter (shared by
        the semi/anti/mark variants on both probe paths)."""
        combined = _materialize(left, right, li, ri, False, False)
        cond = executor.eval_expr(rel.filter, combined)
        keep = cond.data & cond.valid_mask()
        matched = jnp.zeros(left.num_rows, dtype=bool)
        if int(li.shape[0]):
            matched = matched.at[li].max(keep)
        return matched

    @staticmethod
    def _append_mark(rel, left: Table, mask) -> Table:
        names = unique_names([f.name for f in rel.schema])
        cols = {n: left.columns[src]
                for n, src in zip(names[:-1], left.column_names)}
        from ....columnar.column import Column
        from ....columnar.dtypes import SqlType as _St

        cols[names[-1]] = Column(jnp.asarray(mask), _St.BOOLEAN)
        return Table(cols, left.num_rows)

    def _null_aware_anti(self, left: Table, lkeys, rkeys, lgid, rgid,
                         n_right: int) -> Table:
        """SQL `NOT IN (subquery)` as one vectorized mask — no per-row scan.

        3VL over build set S (grouped by the correlation keys when present):
          S empty            -> every probe row passes (even NULL args);
          any NULL in S      -> no probe row of that group passes;
          NULL probe arg     -> never passes (against non-empty S);
          else               -> passes iff no match.
        pass = empty | (arg_valid & ~has_null & ~match).  The reference gets
        here via decorrelate_where_in.rs:267; cost is O((n+m) log m) instead
        of the direct evaluator's O(n*m)."""
        if len(lkeys) == 1:  # uncorrelated: group scalars fold on the host
            # decide the scalar cases before dispatching the O((n+m) log m)
            # probe — an empty or NULL-containing set never needs it
            if n_right == 0:
                return left
            has_null = rkeys[0].validity is not None and \
                not bool(rkeys[0].valid_mask().all())
            if has_null:
                return left.filter(jnp.zeros(left.num_rows, dtype=bool))
        arg_valid = lkeys[0].valid_mask() if lkeys[0].validity is not None \
            else jnp.ones(left.num_rows, dtype=bool)
        match = join_ops.semi_join_mask(lgid, rgid)
        if len(lkeys) == 1:
            return left.filter(arg_valid & ~match)
        # correlated: emptiness / has-null are per correlation group
        cl, cr = join_ops.join_key_gids(lkeys[1:], rkeys[1:])
        empty_row = join_ops.semi_join_mask(cl, cr, anti=True)
        rnull = ~rkeys[0].valid_mask() if rkeys[0].validity is not None \
            else jnp.zeros(n_right, dtype=bool)
        has_null_row = join_ops.semi_join_mask(cl, cr[rnull])
        return left.filter(empty_row | (arg_valid & ~has_null_row & ~match))

    def _outer_from_pairs(self, rel, executor, left, right, li, ri, jt) -> Table:
        """Outer join from inner (li, ri) pairs: apply the residual to matched
        pairs, then pad outer rows that lost all their matches."""
        if rel.filter is not None and int(li.shape[0]):
            combined = _materialize(left, right, li, ri, False, False)
            cond = executor.eval_expr(rel.filter, combined)
            keep = cond.data & cond.valid_mask()
            li, ri = li[keep], ri[keep]
        li2, ri2 = li, ri
        if jt in ("LEFT", "FULL"):
            lm = jnp.zeros(left.num_rows, dtype=bool)
            if int(li.shape[0]):
                lm = lm.at[li].set(True)
            pad = jnp.nonzero(~lm)[0].astype(jnp.int64)
            li2 = jnp.concatenate([li2, pad])
            ri2 = jnp.concatenate([ri2, jnp.full(pad.shape[0], -1, dtype=jnp.int64)])
        if jt in ("RIGHT", "FULL"):
            rm = jnp.zeros(right.num_rows, dtype=bool)
            if int(ri.shape[0]):
                rm = rm.at[ri].set(True)
            pad = jnp.nonzero(~rm)[0].astype(jnp.int64)
            li2 = jnp.concatenate([li2, jnp.full(pad.shape[0], -1, dtype=jnp.int64)])
            ri2 = jnp.concatenate([ri2, pad])
        # pad-possibility is static per join type: LEFT/FULL pad the right
        # side, RIGHT/FULL the left
        combined = _materialize(left, right, li2, ri2,
                                jt in ("RIGHT", "FULL"), jt in ("LEFT", "FULL"))
        return self.fix_column_to_row_type(combined, rel.schema)

    def _maybe_dist_pairs(self, executor, left, right, lkeys, rkeys, lgid, rgid):
        """Collectives-routed equijoin matching, or None for the local path.

        Honors `sql.join.broadcast`: when the smaller side fits under the
        threshold it stays replicated (no shuffle at all) and the local
        sort/searchsorted probe runs per shard — the broadcast join."""
        from ....parallel import dist_plan

        mesh = dist_plan.should_distribute(
            executor, "sql.distributed.join", left, right)
        if mesh is None:
            return None
        lvalid = jnp.ones(left.num_rows, dtype=bool)
        for c in lkeys:
            if c.validity is not None:
                lvalid &= c.valid_mask()
        rvalid = jnp.ones(right.num_rows, dtype=bool)
        for c in rkeys:
            if c.validity is not None:
                rvalid &= c.valid_mask()

        # broadcast join: replicated small side probed in place, the big
        # side never shuffles (reference join.py:228-246).  True = always;
        # a number = row threshold; None/auto = small side well under the
        # big one and bounded
        broadcast = executor.config.get("sql.join.broadcast", None)
        small = min(left.num_rows, right.num_rows)
        big = max(left.num_rows, right.num_rows)
        explicit = (broadcast is True
                    or (broadcast not in (None, False)
                        and small <= float(broadcast)))
        auto = broadcast is None and small <= 65536 and small * 4 <= big
        metrics = executor.context.metrics
        if explicit or auto:
            # never declines: unique-dense keys take the LUT, everything
            # else (string-keyed, duplicate, sparse) the sorted probe
            metrics.inc("parallel.dist.broadcast_join")
            if right.num_rows <= left.num_rows:
                return dist_plan.broadcast_inner_pairs(lgid, lvalid,
                                                       rgid, rvalid)
            ri, li, _rmatch = dist_plan.broadcast_inner_pairs(
                rgid, rvalid, lgid, lvalid)
            lmatch = np.zeros(left.num_rows, dtype=bool)
            lmatch[np.asarray(li)] = True
            return li, ri, lmatch
        metrics.inc("parallel.dist.join_kernel")
        return dist_plan.dist_inner_pairs(mesh, lgid, lvalid, rgid, rvalid)


@Executor.add_plugin_class
class CrossJoinPlugin(BaseRelPlugin):
    """Parity: reference cross_join.py:15."""

    class_name = "CrossJoin"

    def convert(self, rel: p.CrossJoin, executor) -> Table:
        left, right = self.assert_inputs(rel, 2, executor)
        li, ri = _cross_indices(left.num_rows, right.num_rows)
        return self.fix_column_to_row_type(
            _materialize(left, right, li, ri, False, False), rel.schema)
