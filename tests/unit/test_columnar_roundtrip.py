"""Columnar interop round-trips and direct 3VL kernel checks."""
import numpy as np
import pandas as pd
import pytest


def test_arrow_roundtrip():
    import pyarrow as pa

    from dask_sql_tpu.columnar import Table

    df = pd.DataFrame({
        "i": [1, 2, 3],
        "f": [1.5, None, 3.5],
        "s": ["x", None, "z"],
        "b": [True, False, True],
        "t": pd.to_datetime(["2020-01-01", "2021-06-01", "2022-12-31"]),
    })
    table = Table.from_pandas(df)
    at = table.to_arrow()
    assert isinstance(at, pa.Table)
    back = Table.from_arrow(at).to_pandas()
    assert list(back["i"]) == [1, 2, 3]
    assert pd.isna(back["f"][1]) and back["f"][2] == 3.5
    assert back["s"][0] == "x" and pd.isna(back["s"][1])
    assert list(back["b"]) == [True, False, True]
    assert list(pd.to_datetime(back["t"])) == list(df["t"])


def test_arrow_dictionary_input():
    import pyarrow as pa

    from dask_sql_tpu.columnar import Table

    arr = pa.array(["a", "b", "a", None]).dictionary_encode()
    at = pa.table({"d": arr, "v": pa.array([1, 2, 3, 4])})
    t = Table.from_arrow(at)
    out = t.to_pandas()
    assert list(out["d"][:3]) == ["a", "b", "a"] and pd.isna(out["d"][3])


def test_three_valued_logic_kernels():
    import jax.numpy as jnp

    from dask_sql_tpu.columnar.column import Column
    from dask_sql_tpu.columnar.dtypes import SqlType
    from dask_sql_tpu.physical.rex.operations import OPERATION_MAPPING as OPS

    T, F, N = True, False, None  # truth table inputs

    def col(vals):
        data = jnp.asarray([bool(v) if v is not None else False for v in vals])
        validity = jnp.asarray([v is not None for v in vals])
        if bool(validity.all()):
            return Column(data, SqlType.BOOLEAN)
        return Column(data, SqlType.BOOLEAN, validity)

    def decode(c):
        out = []
        valid = np.asarray(c.valid_mask())
        data = np.asarray(c.data)
        for d, v in zip(data, valid):
            out.append(bool(d) if v else None)
        return out

    a = col([T, T, T, F, F, F, N, N, N])
    b = col([T, F, N, T, F, N, T, F, N])
    assert decode(OPS["and"](a, b)) == [T, F, N, F, F, F, N, F, N]
    assert decode(OPS["or"](a, b)) == [T, T, T, T, F, N, T, N, N]
    assert decode(OPS["not"](a)) == [F, F, F, T, T, T, N, N, N]
    assert decode(OPS["is_null"](a)) == [F, F, F, F, F, F, T, T, T]
    assert decode(OPS["is_true"](a)) == [T, T, T, F, F, F, F, F, F]
    assert decode(OPS["is_not_false"](a)) == [T, T, T, F, F, F, T, T, T]
