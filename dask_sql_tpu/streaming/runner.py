"""The partition drive loop: pipelined launches + mid-stream OOM recovery.

This is the robustness core of streamed partitioned execution.  One loop
serves both streamed rungs (aggregate and select); per iteration it

- polls the serving ticket's cooperative cancellation checkpoint, so a
  streamed batch scan stays responsive to ``X-Dsql-Deadline-Ms`` and
  client cancels BETWEEN launches (a single fused launch was never
  preemptible; N launches give N-1 preemption points);
- arms the ``partition`` fault-injection site and launches one partition
  under the engine's existing retry/backoff policy (resilience/retry.py)
  — taxonomy-*retryable* failures (transient runtime errors) retry in
  place, bounded by the ticket's deadline;
- absorbs a *degradable* ``RESOURCE_EXHAUSTED`` — a real mid-stream device
  OOM or the injected fault — by HALVING the partition size and RESUMING
  from the first row no completed partition covered: the checkpointable
  partial-combine state (the aggregate's running segment states, the
  select's survivor list) lives in the caller's accumulator, so completed
  partitions are never re-executed.  Only when halving would cross
  ``serving.stream.min_chunk_rows`` does the failure propagate, where the
  degradation ladder treats it like any rung failure: recorded, breaker-
  charged per (family, rung), stepped down.

Launches are pipelined, not synchronized: a partition launch enqueues
asynchronously on the device (XLA async dispatch) and the combine consumes
its output without a host round trip, so partition i+1's transfer overlaps
partition i's compute — the morsel-driven pipelining argument of TQP
(arXiv:2203.01877) on the time axis.
"""
from __future__ import annotations

import logging
from typing import Callable

from ..observability import detail, flight, live, trace_event
from ..resilience import faults
from ..resilience.errors import (
    ResourceExhaustedError,
    StreamLaunchTimeoutError,
    classify,
)
from ..resilience.retry import BackoffPolicy, retry_call
from ..resilience.watchdog import watched_call

logger = logging.getLogger(__name__)


def drive_partitions(executor, decision, launch: Callable[[int, int], None],
                     rung: str) -> int:
    """Run every partition of ``decision``; returns the number of launches.

    ``launch(lo, chunk_rows)`` executes ONE partition covering logical rows
    ``[lo, min(lo + chunk_rows, total))`` and folds its output into the
    caller's accumulator.  It is called with monotonically non-decreasing
    ``lo`` and may see ``chunk_rows`` shrink after an absorbed OOM; the
    caller's executable re-specializes per chunk shape (one extra compile
    per repartition — the cost of surviving instead of failing)."""
    config = executor.config
    metrics = executor.context.metrics
    from ..serving.runtime import current_ticket

    ticket = current_ticket()
    policy = BackoffPolicy.from_config(config)
    # per-chunk launch deadline (the compile-watchdog pattern extended to
    # streamed launches): a wedged mid-stream launch raises a degradable
    # StreamLaunchTimeoutError BETWEEN chunks instead of holding the
    # ticket's byte reservation forever.  None/non-positive = off.
    launch_timeout_ms = None
    raw_timeout = config.get("serving.stream.launch_timeout_ms")
    if raw_timeout is not None:
        try:
            launch_timeout_ms = float(raw_timeout)
        except (TypeError, ValueError):
            logger.warning("unparseable serving.stream.launch_timeout_ms=%r;"
                           " launch watchdog disabled", raw_timeout)
        if launch_timeout_ms is not None and launch_timeout_ms <= 0:
            launch_timeout_ms = None
    total = int(decision.total_rows)
    chunk_rows = min(int(decision.chunk_rows), total)
    min_rows = min(
        max(1, int(config.get("serving.stream.min_chunk_rows", 4096))),
        total)
    # recovery launch bound: halving must not multiply the admitted
    # partition count unboundedly — the config documents
    # serving.stream.max_partitions as a latency bound, so recovery may
    # at most DOUBLE it before the failure degrades down the ladder
    max_launches = 2 * max(1, int(
        config.get("serving.stream.max_partitions", 256)))
    rows_done = 0
    part_idx = 0
    launches = 0
    # live progress: the in-flight query table (SHOW QUERIES /
    # /v1/queries) shows partitions done/total so a long stream is
    # distinguishable from a hang while it runs
    live.update(stream_partitions_total=-(-total // chunk_rows),
                stream_partitions_done=0, stream_rows_total=total,
                stream_rows_done=0, stream_chunk_rows=chunk_rows)
    while rows_done < total:
        if ticket is not None:
            # deadline/cancel checkpoint between launches: a deadline that
            # expires mid-stream raises here, not after the full scan
            ticket.checkpoint()
        lo = rows_done
        hi = min(lo + chunk_rows, total)
        try:
            # a DETAIL span nested under the execute stage: the Chrome
            # trace shows every streamed partition as a child of execute
            with detail("stream_partition", rung=rung, index=part_idx,
                        row_lo=lo, rows=hi - lo, chunk_rows=chunk_rows):

                def attempt():
                    faults.maybe_inject("partition", config)
                    if launch_timeout_ms is not None:
                        watched_call(
                            f"{rung}[{part_idx}]", launch, (lo, chunk_rows),
                            deadline_ms=launch_timeout_ms,
                            hang_s=faults.hang_duration(
                                "compile_hang", config),
                            metrics=metrics,
                            error_cls=StreamLaunchTimeoutError)
                    else:
                        launch(lo, chunk_rows)

                retry_call(attempt, policy, ticket=ticket, metrics=metrics)
        except (KeyboardInterrupt, SystemExit):
            raise
        except BaseException as exc:  # dsql: allow-broad-except — classified
            # below; only degradable RESOURCE_EXHAUSTED is absorbed (that is
            # the repartition contract), everything else re-raises unchanged
            err = classify(exc)
            if not (err.degradable
                    and isinstance(err, ResourceExhaustedError)):
                raise
            metrics.inc("resilience.partition.oom")
            trace_event("stream_oom", rung=rung, row_lo=lo,
                        chunk_rows=chunk_rows)
            half = chunk_rows // 2
            projected = launches + (-(-(total - rows_done) // half)
                                    if half else 0)
            if half < min_rows or projected > max_launches:
                # recovery exhausted: the chunk floor was reached, or the
                # halving would blow the documented launch bound.  Surface
                # the OOM to the degradation ladder, which records/
                # breaker-charges (family, rung) and steps down —
                # completed partial state is discarded with the rung,
                # exactly like any other rung failure
                metrics.inc("resilience.partition.exhausted")
                trace_event("stream_exhausted", rung=rung,
                            chunk_rows=chunk_rows)
                flight.record("stream.exhausted",
                              qid=ticket.qid if ticket else None,
                              rung=rung, chunk_rows=chunk_rows)
                logger.warning(
                    "streamed %s: partition of %d rows still exhausts "
                    "resources at the %d-row floor; stepping down",
                    rung, chunk_rows, min_rows)
                raise
            chunk_rows = half
            metrics.inc("serving.stream.repartitions")
            trace_event("stream_repartition", rung=rung,
                        chunk_rows=chunk_rows, resume_row=rows_done)
            flight.record("stream.repartition",
                          qid=ticket.qid if ticket else None, rung=rung,
                          chunk_rows=chunk_rows, resume_row=rows_done)
            live.update(
                stream_chunk_rows=chunk_rows,
                stream_partitions_total=part_idx + (
                    -(-(total - rows_done) // chunk_rows)))
            logger.info(
                "streamed %s: mid-stream OOM at row %d; repartitioning to "
                "%d-row chunks and resuming from row %d (completed "
                "partitions kept)", rung, lo, chunk_rows, rows_done)
            continue  # rows_done unchanged: resume, never restart
        rows_done = hi
        part_idx += 1
        launches += 1
        metrics.inc("serving.stream.partitions")
        metrics.inc("serving.stream.rows", hi - lo)
        # liveness gauges: a stalled stream stops advancing these on
        # /v1/metrics, a healthy long stream keeps moving them
        metrics.gauge("serving.stream.partitions_done", part_idx)
        metrics.gauge("serving.stream.rows_done", rows_done)
        live.update(stream_partitions_done=part_idx,
                    stream_rows_done=rows_done)
    metrics.observe("serving.stream.chunk_rows", chunk_rows)
    return launches
