"""Flight recorder: an always-on bounded ring of structured engine events.

The slow-query log (slowlog.py) answers "why was THIS query slow" — but it
must be armed before the incident, and a failed query's context is often
another query's behavior (the batch scan that held the budget, the breaker
that tripped two minutes ago, the repartition storm that preceded the OOM).
The flight recorder is the postmortem tool that needs no pre-arming: every
engine decision that changes what runs — admits, sheds, packs, quota
throttles, ladder degradations, breaker trips/restores, stream
repartitions, compiles, cancellations — appends one structured event to a
process-global bounded ring buffer.

- ``GET /v1/debug/events`` dumps the ring (filterable by name/qid);
- on any query failure the ring is auto-flushed as one JSONL record to
  ``observability.flight.dump_path`` when configured (the in-memory ring
  stays dumpable either way — failures never require pre-arming);
- event *names* are a registered vocabulary (`EVENT_NAMES` /
  `EVENT_NAME_PREFIXES`): self-lint rule DSQL501 checks every literal name
  at a ``flight.record(...)`` call site against it, exactly like DSQL401
  does for metric names — a typo'd event name silently splits a postmortem
  timeline.

The recorder is process-global (`RECORDER`) because the layers that emit
events — scheduler, breaker, ladder, streaming loop — do not all hold a
Context; events carry the qid where one is known.  Recording is O(1)
(deque append under a lock) and always on: the ring costs bounded memory
and nothing else.
"""
from __future__ import annotations

import json
import logging
import threading
import time
from collections import deque

from ..runtime import locks
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)

#: Registered event-name vocabulary.  Self-lint rule DSQL501 checks every
#: string-literal name at a ``flight.record(...)`` call site against this
#: set (plus the prefixes below for f-string families) — add the name here
#: when introducing an event; docs/observability.md describes each.
EVENT_NAMES = frozenset({
    # query lifecycle (serving runtime / server / TpuFrame)
    "query.admit",
    "query.shed",
    "query.finish",
    "query.fail",
    "query.cancel",
    # packing scheduler (serving/scheduler.py)
    "sched.pack",
    "sched.quota_throttle",
    # degradation ladder + breaker (resilience/)
    "ladder.degrade",
    "breaker.trip",
    "breaker.restore",
    # streamed partitioned execution (streaming/runner.py)
    "stream.repartition",
    "stream.exhausted",
    # XLA compiles (observability/spans.py timed_jit_call)
    "compile.start",
    "compile.end",
    # family batching (families/batcher.py)
    "batch.lead",
    "batch.member",
    # background work (serving/background.py, serving/warmup.py)
    "bg.recompile",
    "warmup.replay",
    # model lowering + zero-recompile weight swaps (inference/registry.py)
    "model.lower",
    "model.swap",
    # semantic reuse: materialized stems + incremental refresh (materialize/)
    "materialize.store",
    "materialize.hit",
    "materialize.evict",
    "materialize.refresh",
    # coordinated HBM pressure response (resilience/pressure.py)
    "pressure.band",
    "pressure.reclaim",
    # chaos campaign harness (resilience/chaos.py)
    "chaos.arm",
    # runtime lock sanitizer (runtime/locks.py): a rank inversion or
    # order-graph cycle caught before the acquire blocked
    "lock.order_violation",
    # fleet tier (fleet/): routing, failover, promotion, drain, kill
    "fleet.route",
    "fleet.failover",
    "fleet.promote",
    "fleet.drain",
    "replica.kill",
})

#: prefixes legitimizing dynamic event families (none today; the slot
#: exists so DSQL501 shares the DSQL401 literal/prefix machinery)
EVENT_NAME_PREFIXES: tuple = ()


def is_registered_event(name: str, prefix_only: bool = False) -> bool:
    """True when ``name`` is covered by the registered vocabulary —
    DSQL501's oracle, mirroring `serving.metrics.is_documented_metric`."""
    if name in EVENT_NAMES:
        return True
    if any(name.startswith(p) for p in EVENT_NAME_PREFIXES):
        return True
    return prefix_only and any(p.startswith(name)
                               for p in EVENT_NAME_PREFIXES)


class FlightRecorder:
    """Bounded ring of ``{ts, event, qid?, **attrs}`` dicts."""

    def __init__(self, capacity: int = 4096):
        # leaf rank: nothing is acquired while the ring lock is held, so
        # any thread may record from under any other sanitized lock
        self._lock = locks.named_lock("observability.flight")
        self._ring: "deque[Dict[str, Any]]" = deque(
            maxlen=max(16, int(capacity)))
        self.recorded = 0

    def record(self, event: str, qid: Optional[str] = None,
               ts: Optional[float] = None, **attrs) -> None:
        rec: Dict[str, Any] = {
            "ts": time.time() if ts is None else float(ts),
            "event": event,
        }
        if qid is not None:
            rec["qid"] = qid
        for k, v in attrs.items():
            if v is not None:
                rec[k] = v
        with self._lock:
            self._ring.append(rec)
            self.recorded += 1

    def events(self, limit: Optional[int] = None,
               name: Optional[str] = None,
               qid: Optional[str] = None) -> List[Dict[str, Any]]:
        """Oldest-first dump, optionally filtered; ``limit`` keeps the
        newest N after filtering."""
        with self._lock:
            out = list(self._ring)
        if name is not None:
            out = [e for e in out if e["event"] == name]
        if qid is not None:
            out = [e for e in out if e.get("qid") == qid]
        if limit is not None and limit >= 0:
            out = out[-int(limit):]
        return out

    def resize(self, capacity: int) -> None:
        with self._lock:
            if self._ring.maxlen != max(16, int(capacity)):
                self._ring = deque(self._ring,
                                   maxlen=max(16, int(capacity)))

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)


#: THE process flight recorder — always on
RECORDER = FlightRecorder()


def record(event: str, qid: Optional[str] = None,
           ts: Optional[float] = None, **attrs) -> None:
    """Append one event to the process recorder (module-level convenience:
    ``from ..observability import flight; flight.record("query.admit",
    qid=qid)``).  ``event`` must be in the registered vocabulary — enforced
    statically by DSQL501, not at runtime (a hot path never pays a set
    lookup for an event nobody typo'd)."""
    RECORDER.record(event, qid=qid, ts=ts, **attrs)


#: serializes failure dumps so concurrent failing queries cannot
#: interleave JSONL lines mid-record
_dump_lock = threading.Lock()

#: characters allowed verbatim in a {qid} path substitution; anything
#: else (slashes, spaces, NULs from a hostile client qid) becomes "_"
_QID_SAFE = frozenset(
    "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789.-")


def expand_dump_path(path: str, qid: Optional[str] = None) -> str:
    """Expand ``{pid}`` / ``{qid}`` placeholders in the configured dump
    path.  Multiple replicas sharing one dump directory each write their
    own file (``flight-{pid}.jsonl``) instead of interleaving appends to
    a single JSONL — the ``_dump_lock`` below serializes writers within a
    process, but nothing serializes processes."""
    import os

    if "{pid}" in path:
        path = path.replace("{pid}", str(os.getpid()))
    if "{qid}" in path:
        safe = "".join(ch if ch in _QID_SAFE else "_"
                       for ch in (qid or "unknown"))
        path = path.replace("{qid}", safe or "unknown")
    return path


def flush_on_failure(qid: Optional[str], error_code: Optional[str],
                     config, metrics=None) -> bool:
    """Auto-flush hook run on any query failure: records the failure event
    and, when ``observability.flight.dump_path`` is configured, appends one
    JSONL record carrying the failure plus the entire current ring — the
    postmortem context of every engine decision leading up to it."""
    record("query.fail", qid=qid, code=error_code)
    path = None if config is None else config.get(
        "observability.flight.dump_path")
    if not path:
        return False
    path = expand_dump_path(path, qid=qid)
    rec = {
        "ts": time.time(),
        "qid": qid,
        "error": error_code,
        "events": RECORDER.events(),
    }
    try:
        with _dump_lock, open(path, "a", encoding="utf-8") as f:
            f.write(json.dumps(rec) + "\n")
    except OSError:
        logger.warning("flight-recorder dump to %r failed", path,
                       exc_info=True)
        return False
    if metrics is not None:
        metrics.inc("observability.flight.dumps")
    return True
