"""Datetime kernels: pure-jnp civil-calendar math over epoch nanoseconds.

The reference leans on pandas `.dt` accessors (call.py datetime ops there);
on TPU we keep timestamps as int64 ns and compute calendar fields with
branch-free integer arithmetic (Howard Hinnant's civil-from-days algorithm),
so EXTRACT/CEIL/FLOOR/TIMESTAMPADD all stay on device and fuse with
neighbouring kernels.
"""
from __future__ import annotations

import jax.numpy as jnp

NS_PER_SECOND = 1_000_000_000
NS_PER_MINUTE = 60 * NS_PER_SECOND
NS_PER_HOUR = 3600 * NS_PER_SECOND
NS_PER_DAY = 86_400 * NS_PER_SECOND


def _floordiv(a, b):
    return jnp.floor_divide(a, b)


def days_from_ns(ns):
    return _floordiv(ns, NS_PER_DAY)


def civil_from_days(days):
    """(year, month, day) from days since 1970-01-01 (proleptic Gregorian)."""
    z = days + 719468
    era = _floordiv(z, 146097)
    doe = z - era * 146097
    yoe = _floordiv(doe - _floordiv(doe, 1460) + _floordiv(doe, 36524) - _floordiv(doe, 146096), 365)
    y = yoe + era * 400
    doy = doe - (365 * yoe + _floordiv(yoe, 4) - _floordiv(yoe, 100))
    mp = _floordiv(5 * doy + 2, 153)
    d = doy - _floordiv(153 * mp + 2, 5) + 1
    m = mp + jnp.where(mp < 10, 3, -9)
    y = y + (m <= 2)
    return y, m, d


def days_from_civil(y, m, d):
    """Inverse of civil_from_days."""
    y = y - (m <= 2)
    era = _floordiv(y, 400)
    yoe = y - era * 400
    mp = m + jnp.where(m > 2, -3, 9)
    doy = _floordiv(153 * mp + 2, 5) + d - 1
    doe = yoe * 365 + _floordiv(yoe, 4) - _floordiv(yoe, 100) + doy
    return era * 146097 + doe - 719468


def extract(unit: str, ns):
    ns = ns.astype(jnp.int64)
    days = days_from_ns(ns)
    tod = ns - days * NS_PER_DAY  # time of day in ns, always >= 0
    if unit == "epoch":
        return _floordiv(ns, NS_PER_SECOND)
    if unit == "hour":
        return _floordiv(tod, NS_PER_HOUR)
    if unit == "minute":
        return _floordiv(tod, NS_PER_MINUTE) % 60
    if unit == "second":
        return _floordiv(tod, NS_PER_SECOND) % 60
    if unit == "millisecond":
        return _floordiv(tod, 1_000_000) % 1000
    if unit == "microsecond":
        return _floordiv(tod, 1000) % 1_000_000
    if unit == "nanosecond":
        return tod % NS_PER_SECOND
    y, m, d = civil_from_days(days)
    if unit == "year" or unit == "isoyear":
        return y
    if unit == "month":
        return m
    if unit == "day":
        return d
    if unit == "quarter":
        return _floordiv(m - 1, 3) + 1
    if unit == "week":
        # ISO week number
        doy = days - days_from_civil(y, jnp.ones_like(m), jnp.ones_like(d)) + 1
        dow_iso = _iso_dow(days)
        raw = _floordiv(doy - dow_iso + 10, 7)
        # weeks 0 / 53 belong to the neighbouring ISO year
        prev_weeks = 52 + _is_long_year(y - 1).astype(raw.dtype)
        this_weeks = 52 + _is_long_year(y).astype(raw.dtype)
        return jnp.where(raw < 1, prev_weeks, jnp.where(raw > this_weeks, 1, raw))
    if unit == "dow":
        # Calcite/reference convention: 1 = Sunday ... 7 = Saturday
        return (days + 4) % 7 + 1
    if unit == "isodow":
        return _iso_dow(days)
    if unit == "doy":
        jan1 = days_from_civil(y, jnp.ones_like(m), jnp.ones_like(d))
        return days - jan1 + 1
    if unit == "century":
        return _floordiv(y - 1, 100) + 1
    if unit == "decade":
        return _floordiv(y, 10)
    if unit == "millennium":
        return _floordiv(y - 1, 1000) + 1
    raise NotImplementedError(f"EXTRACT unit {unit}")


def _iso_dow(days):
    return (days + 3) % 7 + 1  # 1 = Monday ... 7 = Sunday


def _is_long_year(y):
    jan1 = days_from_civil(y, jnp.asarray(1), jnp.asarray(1))
    dec31 = days_from_civil(y, jnp.asarray(12), jnp.asarray(31))
    return (_iso_dow(jan1) == 4) | (_iso_dow(dec31) == 4)


_TRUNC_UNITS = ("YEAR", "QUARTER", "MONTH", "WEEK", "DAY", "HOUR", "MINUTE", "SECOND",
                "MILLISECOND", "MICROSECOND")


def truncate(unit: str, ns):
    """FLOOR(ts TO unit) (reference dialect.rs CEIL/FLOOR TO rewrites)."""
    unit = unit.upper()
    ns = ns.astype(jnp.int64)
    if unit == "SECOND":
        return _floordiv(ns, NS_PER_SECOND) * NS_PER_SECOND
    if unit == "MINUTE":
        return _floordiv(ns, NS_PER_MINUTE) * NS_PER_MINUTE
    if unit == "HOUR":
        return _floordiv(ns, NS_PER_HOUR) * NS_PER_HOUR
    if unit == "DAY":
        return _floordiv(ns, NS_PER_DAY) * NS_PER_DAY
    if unit == "MILLISECOND":
        return _floordiv(ns, 1_000_000) * 1_000_000
    if unit == "MICROSECOND":
        return _floordiv(ns, 1000) * 1000
    days = days_from_ns(ns)
    y, m, d = civil_from_days(days)
    one = jnp.ones_like(d)
    if unit == "WEEK":
        start = days - (_iso_dow(days) - 1)
        return start * NS_PER_DAY
    if unit == "MONTH":
        return days_from_civil(y, m, one) * NS_PER_DAY
    if unit == "QUARTER":
        qm = (_floordiv(m - 1, 3)) * 3 + 1
        return days_from_civil(y, qm, one) * NS_PER_DAY
    if unit == "YEAR":
        return days_from_civil(y, jnp.ones_like(m), one) * NS_PER_DAY
    raise NotImplementedError(f"truncate unit {unit}")


def ceil_to(unit: str, ns):
    ns = ns.astype(jnp.int64)
    fl = truncate(unit, ns)
    unit_u = unit.upper()
    if unit_u in ("SECOND", "MINUTE", "HOUR", "DAY", "WEEK", "MILLISECOND", "MICROSECOND"):
        step = {"SECOND": NS_PER_SECOND, "MINUTE": NS_PER_MINUTE, "HOUR": NS_PER_HOUR,
                "DAY": NS_PER_DAY, "WEEK": 7 * NS_PER_DAY,
                "MILLISECOND": 1_000_000, "MICROSECOND": 1000}[unit_u]
        return jnp.where(fl == ns, ns, fl + step)
    # month-based units: advance to next boundary
    nxt = add_months(fl, {"MONTH": 1, "QUARTER": 3, "YEAR": 12}[unit_u])
    return jnp.where(fl == ns, ns, nxt)


def add_months(ns, months):
    ns = ns.astype(jnp.int64)
    days = days_from_ns(ns)
    rem = ns - days * NS_PER_DAY
    y, m, d = civil_from_days(days)
    tot = y * 12 + (m - 1) + months
    ny = _floordiv(tot, 12)
    nm = tot - ny * 12 + 1
    # clamp day to target month length
    ml = month_length(ny, nm)
    nd = jnp.minimum(d, ml)
    return days_from_civil(ny, nm, nd) * NS_PER_DAY + rem


def month_length(y, m):
    lengths = jnp.asarray([31, 28, 31, 30, 31, 30, 31, 31, 30, 31, 30, 31], dtype=jnp.int64)
    base = lengths[jnp.clip(m - 1, 0, 11)]
    leap = ((y % 4 == 0) & (y % 100 != 0)) | (y % 400 == 0)
    return jnp.where((m == 2) & leap, 29, base)


def last_day(ns):
    days = days_from_ns(ns.astype(jnp.int64))
    y, m, _ = civil_from_days(days)
    return days_from_civil(y, m, month_length(y, m)) * NS_PER_DAY


def timestampadd(unit: str, n, ns):
    unit = unit.upper().rstrip("S")
    if unit in ("YEAR", "QUARTER", "MONTH"):
        mult = {"YEAR": 12, "QUARTER": 3, "MONTH": 1}[unit]
        return add_months(ns, n * mult)
    step = {"WEEK": 7 * NS_PER_DAY, "DAY": NS_PER_DAY, "HOUR": NS_PER_HOUR,
            "MINUTE": NS_PER_MINUTE, "SECOND": NS_PER_SECOND,
            "MILLISECOND": 1_000_000, "MICROSECOND": 1000, "NANOSECOND": 1}[unit]
    return ns.astype(jnp.int64) + n.astype(jnp.int64) * step


def timestampdiff(unit: str, a, b):
    """Full units from a to b (SQL TIMESTAMPDIFF argument order)."""
    unit = unit.upper().rstrip("S")
    a = a.astype(jnp.int64)
    b = b.astype(jnp.int64)
    if unit in ("YEAR", "QUARTER", "MONTH"):
        ya, ma, da = civil_from_days(days_from_ns(a))
        yb, mb, db = civil_from_days(days_from_ns(b))
        months = (yb * 12 + mb) - (ya * 12 + ma)
        # partial month does not count
        toda = a - days_from_ns(a) * NS_PER_DAY
        todb = b - days_from_ns(b) * NS_PER_DAY
        adjust = ((db < da) | ((db == da) & (todb < toda))) & (months > 0)
        adjust_neg = ((db > da) | ((db == da) & (todb > toda))) & (months < 0)
        months = months - adjust.astype(jnp.int64) + adjust_neg.astype(jnp.int64)
        if unit == "MONTH":
            return months
        if unit == "QUARTER":
            return _div_trunc(months, 3)
        return _div_trunc(months, 12)
    step = {"WEEK": 7 * NS_PER_DAY, "DAY": NS_PER_DAY, "HOUR": NS_PER_HOUR,
            "MINUTE": NS_PER_MINUTE, "SECOND": NS_PER_SECOND,
            "MILLISECOND": 1_000_000, "MICROSECOND": 1000, "NANOSECOND": 1}[unit]
    return _div_trunc(b - a, step)


def _div_trunc(a, b):
    """Integer division truncating toward zero (SQL semantics)."""
    q = jnp.floor_divide(jnp.abs(a), b)
    return jnp.where(a < 0, -q, q)
