"""Execution tracing.

Role parity: SURVEY.md §5 — the reference has no dedicated tracer (it points
users at the dask dashboard and logs per-rule optimizer traces).  Here the
executor records per-plan-node wall time and output rows, surfaced through
`EXPLAIN ANALYZE` and `Context.last_trace`.
"""
from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional


@dataclass
class NodeTrace:
    node_type: str
    label: str
    wall_ms: float
    rows: int
    children: List["NodeTrace"] = field(default_factory=list)
    #: start timestamp (time.perf_counter seconds) — real timeline position,
    #: so the observability layer can export the tree as Chrome-trace spans
    t0: float = 0.0

    def format(self, indent: int = 0) -> str:
        pad = "  " * indent
        if self.node_type == "Resilience":
            # zero-duration marker (ladder degradation step): the label IS
            # the information — "0.00 ms, -1 rows" was noise
            lines = [f"{pad}!! {self.label}"]
        else:
            # rows < 0 means "not observed" (e.g. a node that streamed its
            # output), not a literal row count
            rows = "? rows" if self.rows < 0 else f"{self.rows} rows"
            lines = [f"{pad}{self.label}  [{self.wall_ms:.2f} ms, {rows}]"]
        for child in self.children:
            lines.append(child.format(indent + 1))
        return "\n".join(lines)


class Tracer:
    def __init__(self):
        self.enabled = False
        self._stack: List[List[NodeTrace]] = [[]]
        self.root: Optional[NodeTrace] = None

    def start(self):
        self.enabled = True
        self._stack = [[]]
        self.root = None

    def publish(self, registry) -> None:
        """Fold the finished trace into a serving `MetricsRegistry` —
        per-node-type wall-time histograms and row counters.  The registry
        aggregates across queries; the trace tree itself stays per-query
        (EXPLAIN ANALYZE / `Context.last_trace`)."""
        if registry is not None and self.root is not None:
            registry.observe_trace(self.root)

    def event(self, label: str) -> None:
        """Record a zero-duration marker (e.g. a resilience-ladder
        degradation step) at the current tree position, so EXPLAIN ANALYZE
        shows *where* the engine stepped down a rung."""
        if self.enabled:
            self._stack[-1].append(
                NodeTrace("Resilience", label, 0.0, -1,
                          t0=time.perf_counter()))

    def node(self, rel):
        tracer = self

        class _Ctx:
            def __enter__(self):
                self.t0 = time.perf_counter()
                tracer._stack.append([])
                return self

            def __exit__(self, exc_type, exc, tb):
                elapsed = (time.perf_counter() - self.t0) * 1000.0
                children = tracer._stack.pop()
                trace = NodeTrace(rel.node_type, rel._label(), elapsed,
                                  getattr(self, "rows", -1), children,
                                  t0=self.t0)
                tracer._stack[-1].append(trace)
                tracer.root = trace
                return False

        return _Ctx()
