"""Benchmark: TPC-H Q1 (SF~1 lineitem, synthetic) through the full SQL path.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
value = rows/sec/chip through c.sql() end-to-end (plan + device execution),
vs_baseline = speedup over pandas executing the same query (the reference's
single-partition execution engine).
"""
from __future__ import annotations

import json
import time

import numpy as np


N_ROWS = 6_000_000  # ~SF1 lineitem row count
QUERY = """
SELECT
    l_returnflag,
    l_linestatus,
    SUM(l_quantity) AS sum_qty,
    SUM(l_extendedprice) AS sum_base_price,
    SUM(l_extendedprice * (1 - l_discount)) AS sum_disc_price,
    SUM(l_extendedprice * (1 - l_discount) * (1 + l_tax)) AS sum_charge,
    AVG(l_quantity) AS avg_qty,
    AVG(l_extendedprice) AS avg_price,
    AVG(l_discount) AS avg_disc,
    COUNT(*) AS count_order
FROM lineitem
WHERE l_shipdate <= DATE '1998-09-02'
GROUP BY l_returnflag, l_linestatus
ORDER BY l_returnflag, l_linestatus
"""


def gen_lineitem(n: int, seed: int = 0):
    import pandas as pd

    rng = np.random.RandomState(seed)
    start = np.datetime64("1992-01-01")
    return pd.DataFrame(
        {
            "l_returnflag": rng.choice(["A", "N", "R"], n),
            "l_linestatus": rng.choice(["F", "O"], n),
            "l_quantity": rng.randint(1, 51, n).astype(np.float32),
            "l_extendedprice": (rng.rand(n).astype(np.float32) * 100000.0),
            "l_discount": (rng.rand(n).astype(np.float32) * 0.1),
            "l_tax": (rng.rand(n).astype(np.float32) * 0.08),
            "l_shipdate": start + rng.randint(0, 2526, n).astype("timedelta64[D]"),
        }
    )


def run_pandas(df):
    cutoff = np.datetime64("1998-09-02")
    sel = df[df.l_shipdate <= cutoff]
    disc_price = sel.l_extendedprice * (1 - sel.l_discount)
    charge = disc_price * (1 + sel.l_tax)
    work = sel.assign(disc_price=disc_price, charge=charge)
    out = work.groupby(["l_returnflag", "l_linestatus"]).agg(
        sum_qty=("l_quantity", "sum"),
        sum_base_price=("l_extendedprice", "sum"),
        sum_disc_price=("disc_price", "sum"),
        sum_charge=("charge", "sum"),
        avg_qty=("l_quantity", "mean"),
        avg_price=("l_extendedprice", "mean"),
        avg_disc=("l_discount", "mean"),
        count_order=("l_quantity", "count"),
    ).reset_index().sort_values(["l_returnflag", "l_linestatus"])
    return out


def _ensure_backend():
    """Fall back to CPU when the configured accelerator backend is broken."""
    import os

    import jax

    try:
        jax.devices()
    except Exception:
        os.environ["JAX_PLATFORMS"] = "cpu"
        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass
        jax.devices()


def bench_q3_line(backend: str):
    """TPC-H Q3 (3-way join + topN) on the same chip — VERDICT r4 #2: the
    join path had no on-hardware number.  Emitted as its own JSON line
    before the headline metric."""
    import sys

    sys.path.insert(0, "tests")
    from tpch import QUERIES, generate

    from dask_sql_tpu import Context

    n = 1_000_000
    tables = generate(scale_rows=n)
    c = Context()
    # result cache off: measure execution, not serving-cache lookups
    c.config.update({"serving.cache.enabled": False})
    for name, frame in tables.items():
        c.create_table(name, frame)
    q3 = QUERIES[3]
    c.sql(q3).compute()  # warm-up
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        c.sql(q3).compute()
        times.append(time.perf_counter() - t0)
    print(json.dumps({
        "metric": "tpch_q3_sf1_rows_per_sec_per_chip",
        "value": round(n / min(times), 1),
        "unit": "rows/s",
        "backend": backend,
    }), flush=True)


def run_inject_smoke():
    """`bench.py --inject`: deterministic fault-injection smoke.

    Proves on real hardware (or CPU) that a forced compile failure and a
    forced device-OOM each complete the benchmark query via a lower ladder
    rung with the SAME result as the clean run, and prints one JSON line
    with the degradation counters.  Small and seed-pinned so CI can run it
    on every change without slowing the normal bench path.
    """
    import jax

    _ensure_backend()

    from dask_sql_tpu import Context
    from dask_sql_tpu import config as config_module
    from dask_sql_tpu.resilience import faults

    df = gen_lineitem(100_000, seed=0)
    c = Context()
    c.config.update({"serving.cache.enabled": False})
    c.create_table("lineitem", df)
    clean = c.sql(QUERY, return_futures=False)

    degradations = {}
    ok = True
    for spec in ("compile:always", "oom:once"):
        faults.reset()
        ctx = Context()
        ctx.config.update({"serving.cache.enabled": False})
        ctx.create_table("lineitem", df)
        with config_module.set({"resilience.inject": spec,
                                "resilience.inject.seed": 0}):
            hurt = ctx.sql(QUERY, return_futures=False)
        degraded = ctx.metrics.counter("resilience.degraded")
        degradations[spec] = degraded
        same = (len(hurt) == len(clean) and np.allclose(
            hurt["sum_qty"].to_numpy(np.float64),
            clean["sum_qty"].to_numpy(np.float64), rtol=1e-9))
        ok = ok and same and degraded >= 1
    faults.reset()
    print(json.dumps({
        "metric": "fault_injection_smoke",
        "backend": jax.default_backend(),
        "ok": bool(ok),
        "degradations": degradations,
    }), flush=True)
    if not ok:
        raise SystemExit(1)


def run_estimate_smoke():
    """`bench.py --estimate`: estimate-vs-actual bytes for the bench queries.

    Prints one JSON line per bench query with the estimator's
    (rows_lo, rows_hi, bytes_lo, bytes_hi) next to the measured resident
    bytes and result rows, and fails when a bound is violated (upper bound
    below measured, or measured rows outside the cardinality interval).
    Host + small-device work only — safe to run on every change.
    """
    _ensure_backend()

    from dask_sql_tpu import Context
    from dask_sql_tpu.analysis import estimator
    from dask_sql_tpu.planner.parser import parse_sql
    from dask_sql_tpu.serving.cache import table_nbytes

    # tests/ is a package and the script dir rides sys.path, so this works
    # from any cwd (the cwd-relative "tests" path hack would not)
    from tests.tpch import QUERIES, generate

    ok = True
    # q1 shape on synthetic lineitem; q3 shape on the tpch toolkit tables
    cases = []
    c1 = Context()
    c1.config.update({"serving.cache.enabled": False})
    c1.create_table("lineitem", gen_lineitem(100_000, seed=0))
    cases.append(("q1", c1, QUERY))
    c3 = Context()
    c3.config.update({"serving.cache.enabled": False})
    for name, frame in generate(scale_rows=100_000).items():
        c3.create_table(name, frame)
    cases.append(("q3", c3, QUERIES[3]))

    from dask_sql_tpu.planner import plan as plan_nodes

    def scanned_tables(node, seen):
        if isinstance(node, plan_nodes.TableScan):
            seen.add(node.table_name)
        for child in node.inputs():
            scanned_tables(child, seen)
        return seen

    for label, c, sql in cases:
        plan = c._get_ral(parse_sql(sql)[0], sql_text=sql)
        est = estimator.estimate_plan(plan, context=c)
        frame = c.sql(sql)
        result_table = frame.execute()
        result = frame.compute()
        # a true peak lower bound the hi bound must dominate: the tables
        # the PLAN references (plan-scoped — unreferenced catalog tables
        # are not its claim) plus the materialized result, both resident
        # simultaneously at query end.  Intermediate/scratch peaks are not
        # observable from the host here, so this check is partial.
        measured = sum(table_nbytes(c.schema["root"].tables[t].table)
                       for t in scanned_tables(plan, set()))
        measured += table_nbytes(result_table)
        rows_ok = (est.rows.lo <= len(result)
                   and (est.rows.hi is None or len(result) <= est.rows.hi))
        bytes_ok = est.peak_bytes.hi is None or est.peak_bytes.hi >= measured
        # the lower bound is what admission SHEDS on: it claims exactly
        # "resident scanned tables + materialized root", both of which
        # `measured` observes, so lo <= measured is a hard invariant
        lo_ok = est.peak_bytes.lo <= measured
        ok = ok and rows_ok and bytes_ok and lo_ok
        print(json.dumps({
            "metric": f"estimate_vs_actual_{label}",
            "rows_lo": est.rows.lo, "rows_hi": est.rows.hi,
            "bytes_lo": est.peak_bytes.lo, "bytes_hi": est.peak_bytes.hi,
            "measured_resident_bytes": measured,
            "actual_rows": len(result),
            "rows_ok": bool(rows_ok), "bytes_ok": bool(bytes_ok),
            "bytes_lo_ok": bool(lo_ok),
        }), flush=True)
    if not ok:
        raise SystemExit(1)


def run_profile_smoke():
    """`bench.py --profile`: query-lifecycle trace smoke.

    Runs the benchmark query once through the Context API with lifecycle
    tracing (observability/), asserts the trace is COMPLETE — every
    expected stage present, stage timestamps monotonic and non-overlapping,
    at least one per-rung compile span recorded — and writes the
    Chrome-trace JSON artifact so a CI run leaves a loadable profile
    behind.  Small input, safe to run on every change.
    """
    import json as _json
    import os

    _ensure_backend()
    from dask_sql_tpu import Context

    c = Context()
    c.config.update({"serving.cache.enabled": False})
    c.create_table("lineitem", gen_lineitem(100_000, seed=0))
    c.sql(QUERY, return_futures=False)
    tr = c.last_trace
    stages = tr.stage_spans()
    names = [s.name for s in stages]
    required = ["parse", "bind", "verify", "estimate", "execute", "d2h"]
    missing = [r for r in required if r not in names]
    # stages must be sequential: each one ends before the next begins
    monotonic = all(stages[i].t1 <= stages[i + 1].t0 + 1e-9
                    for i in range(len(stages) - 1))
    compiles = [s for s in tr.spans if s.name.startswith("compile:")]
    artifact = os.environ.get("DSQL_PROFILE_ARTIFACT",
                              "/tmp/dsql_q1_trace.json")
    with open(artifact, "w") as f:
        _json.dump(tr.to_chrome_trace(), f)
    ok = not missing and monotonic and len(compiles) >= 1
    print(_json.dumps({
        "metric": "lifecycle_profile_smoke",
        "ok": bool(ok),
        "stages": names,
        "missing_stages": missing,
        "monotonic": bool(monotonic),
        "compile_spans": len(compiles),
        "fingerprint": tr.fingerprint,
        "artifact": artifact,
    }), flush=True)
    if not ok:
        raise SystemExit(1)


def run_coldstart_smoke():
    """`bench.py --coldstart`: zero-cold-start restart smoke.

    Serves the benchmark query cold (foreground compiles, persistent
    executable cache filling), snapshots, then restarts the Context
    in-process: load_state restores tables + profiles and kicks the
    profile-driven warm-up.  Asserts the restart contract — the warm-up
    reaches ready, the pre-warmed fingerprint's first query shows ZERO
    foreground ``compile:<rung>`` spans in its lifecycle trace, and the
    persistent cache recorded at least one cross-"process" hit — and
    reports cold-vs-warm first-query latency.  Exit 1 on violation.
    """
    import json as _json
    import os
    import tempfile

    import jax

    _ensure_backend()
    from dask_sql_tpu import Context
    from dask_sql_tpu import config as config_module
    from dask_sql_tpu.serving import compile_cache

    work = tempfile.mkdtemp(prefix="dsql_coldstart_")
    config_module.config.update({
        "serving.cache.enabled": False,
        "serving.compile_cache.path": os.path.join(work, "compile-cache"),
    })
    df = gen_lineitem(100_000, seed=0)

    c1 = Context()
    c1.create_table("lineitem", df)
    t0 = time.perf_counter()
    cold = c1.sql(QUERY, return_futures=False)
    cold_ms = (time.perf_counter() - t0) * 1000.0
    c1.sql(QUERY).execute()  # second hit: the fingerprint is clearly hot
    snap = os.path.join(work, "snapshot")
    c1.save_state(snap)

    c2 = Context()  # the "restarted process"
    c2.load_state(snap)
    warm = c2.warmup
    warmed = ready = 0
    if warm is not None:
        warm.join(300)
        ready = int(warm.ready)
        warmed = warm.warmed
    t0 = time.perf_counter()
    out = c2.sql(QUERY, return_futures=False)
    warm_ms = (time.perf_counter() - t0) * 1000.0
    tr = c2.last_trace
    fg_compiles = [s.name for s in tr.spans if s.name.startswith("compile:")]
    same = len(out) == len(cold) and np.allclose(
        out["sum_qty"].to_numpy(np.float64),
        cold["sum_qty"].to_numpy(np.float64), rtol=1e-9)

    ok = bool(ready and warmed >= 1 and not fg_compiles and same)
    print(_json.dumps({
        "metric": "coldstart_smoke",
        "backend": jax.default_backend(),
        "ok": ok,
        "cold_first_query_ms": round(cold_ms, 2),
        "warm_first_query_ms": round(warm_ms, 2),
        "cold_over_warm": round(cold_ms / warm_ms, 2) if warm_ms else None,
        "warmed_fingerprints": warmed,
        "foreground_compile_spans": fg_compiles,
        "persistent_cache": compile_cache.stats(),
        "results_match": bool(same),
    }), flush=True)
    if not ok:
        raise SystemExit(1)


def run_families_smoke():
    """`bench.py --families`: parameterized plan families + batching smoke.

    Two checks, exit 1 on violation:

    1. *Compile-once-run-many*: two sequential queries differing only in a
       literal must share one family fingerprint, and the SECOND query's
       lifecycle trace must contain ZERO foreground ``compile:<rung>``
       spans (one executable serves the family).
    2. *Inter-query batching*: N concurrent clients issuing same-family
       queries with distinct literals through a ServingRuntime must be
       served with exactly ONE client paying a foreground compile (the
       batch leader) and at least one stacked launch serving >1 query
       (``serving.batch.launches`` / ``serving.batch.queries``), with
       every client's result matching pandas.
    """
    import json as _json

    _ensure_backend()
    import jax

    from dask_sql_tpu import Context
    from dask_sql_tpu.serving.runtime import ServingRuntime

    def q(disc):
        return ("SELECT l_returnflag, SUM(l_extendedprice) AS s, "
                "COUNT(*) AS n FROM lineitem "
                f"WHERE l_discount > {disc} GROUP BY l_returnflag")

    def compile_spans(tr):
        return [s.name for s in tr.spans if s.name.startswith("compile:")]

    df = gen_lineitem(100_000, seed=0)

    # -- phase 1: sequential family proof ---------------------------------
    c1 = Context()
    c1.config.update({"serving.cache.enabled": False})
    c1.create_table("lineitem", df)
    c1.sql(q(0.02), return_futures=False)
    tr_first = c1.last_trace
    c1.sql(q(0.05), return_futures=False)
    tr_second = c1.last_trace
    seq_same_family = tr_first.fingerprint == tr_second.fingerprint
    seq_second_compiles = compile_spans(tr_second)
    seq_ok = (seq_same_family and len(compile_spans(tr_first)) >= 1
              and not seq_second_compiles)

    # -- phase 2: concurrent clients, cold context, batched launch --------
    c2 = Context()
    c2.config.update({"serving.cache.enabled": False})
    c2.create_table("lineitem", df)
    discs = [0.01, 0.03, 0.05, 0.07]
    # batch bound == client count so the group closes the moment everyone
    # arrives; the window is an upper bound for stragglers (host-side
    # parse/bind of the members serializes under the GIL)
    runtime = ServingRuntime(workers=8, metrics=c2.metrics,
                             batch_queries=len(discs),
                             batch_window_ms=2000.0)
    c2.serving = runtime
    for d in discs:
        # pre-plan (no execution): the clients then hit the plan cache and
        # reach the executor together, so the phase measures EXECUTION
        # batching rather than GIL-serialized parse jitter
        c2.sql(q(d))
    frames = {}

    def client(disc):
        def work(_ticket):
            frame = c2.sql(q(disc))
            frame.execute()
            frames[disc] = frame
            return frame
        return work

    futures = [runtime.submit(client(d))[1] for d in discs]
    for fut in futures:
        fut.result(300)
    runtime.shutdown(wait=True)
    results_ok = True
    for disc in discs:
        got = frames[disc].execute().to_pandas().set_index(
            frames[disc].columns[0])
        exp = df[df.l_discount > disc].groupby("l_returnflag").agg(
            s=("l_extendedprice", "sum"), n=("l_extendedprice", "count"))
        # rtol: f32 sums of ~25k values differ by summation order alone
        results_ok = results_ok and len(got) == len(exp) and all(
            np.allclose(got.loc[k, "s"], exp.loc[k, "s"], rtol=1e-4)
            and got.loc[k, "n"] == exp.loc[k, "n"] for k in exp.index)
    compiling_clients = sum(
        1 for f in frames.values()
        if f._trace is not None and compile_spans(f._trace))
    launches = c2.metrics.counter("serving.batch.launches")
    batched_queries = c2.metrics.counter("serving.batch.queries")
    conc_ok = (compiling_clients == 1 and launches >= 1
               and batched_queries >= 2 and results_ok)

    ok = seq_ok and conc_ok
    print(_json.dumps({
        "metric": "plan_families_smoke",
        "backend": jax.default_backend(),
        "ok": bool(ok),
        "sequential_same_family": bool(seq_same_family),
        "sequential_second_query_compiles": seq_second_compiles,
        "concurrent_clients": len(discs),
        "clients_with_foreground_compile": compiling_clients,
        "batched_launches": launches,
        "queries_served_batched": batched_queries,
        "results_match": bool(results_ok),
        "family": tr_first.fingerprint,
    }), flush=True)
    if not ok:
        raise SystemExit(1)


def gen_lineitem_compressed(n: int, seed: int = 0):
    """Lineitem with the REAL TPC-H value domains the float32 bench
    generator flattens away: 11 distinct discounts, 9 taxes, 50 quantities,
    day-granular dates (DICT targets) and a stride-4 orderkey (FOR target);
    l_extendedprice stays continuous float64 (PLAIN control)."""
    import pandas as pd

    rng = np.random.RandomState(seed)
    start = np.datetime64("1992-01-01")
    return pd.DataFrame({
        "l_returnflag": rng.choice(["A", "N", "R"], n),
        "l_linestatus": rng.choice(["F", "O"], n),
        "l_orderkey": (rng.randint(0, 1_500_000, n) * 4).astype(np.int64),
        "l_linenumber": rng.randint(1, 8, n).astype(np.int64),
        "l_quantity": rng.randint(1, 51, n).astype(np.float64),
        "l_extendedprice": rng.rand(n) * 100000.0,
        "l_discount": rng.randint(0, 11, n) / 100.0,
        "l_tax": rng.randint(0, 9, n) / 100.0,
        "l_shipdate": start + rng.randint(0, 2526, n).astype("timedelta64[D]"),
    })


def run_compressed_smoke():
    """`bench.py --compressed`: compressed-domain execution smoke.

    Contracts, exit 1 on violation:

    1. *Byte reduction*: the registered lineitem stores DICT/FOR-encoded
       columns and its resident scan bytes are < 0.6x the decoded widths.
    2. *Compressed-domain execution*: TPC-H q1/q6-shape scans run on the
       COMPILED rungs with ZERO full-column decodes
       (``columnar.encoding.decode`` == 0) and at least one code-space
       predicate rewrite — predicates evaluate on codes, values
       materialize late.
    3. *Correctness*: every result is byte-identical to the same query on
       an encodings-off context, and matches pandas.
    4. *Estimator*: ``EXPLAIN ESTIMATE`` (estimate_plan) on the encoded
       context reports a strictly smaller ``peak_bytes.hi`` than with
       encodings off — encoded widths shrink the admission intervals.
    """
    import json as _json

    _ensure_backend()
    import jax

    from dask_sql_tpu import Context
    from dask_sql_tpu.analysis import estimator
    from dask_sql_tpu.columnar.encodings import Encoding, scan_bytes
    from dask_sql_tpu.planner.parser import parse_sql

    n = 200_000
    df = gen_lineitem_compressed(n, seed=0)

    c_enc = Context()
    c_enc.config.update({"serving.cache.enabled": False})
    c_enc.create_table("lineitem", df)
    c_off = Context()
    c_off.config.update({"serving.cache.enabled": False,
                         "columnar.encoding": "off"})
    c_off.create_table("lineitem", df)

    t = c_enc.schema["root"].tables["lineitem"].table
    encodings = {name: col.encoding.value for name, col in t.columns.items()}
    enc_b, dec_b = scan_bytes(t)
    ratio = enc_b / dec_b
    dict_for = any(v == "DICT" for v in encodings.values()) and \
        any(v == "FOR" for v in encodings.values())
    bytes_ok = dict_for and ratio < 0.6

    q6 = ("SELECT SUM(l_extendedprice * l_discount) AS revenue, COUNT(*) AS n "
          "FROM lineitem WHERE l_shipdate >= DATE '1994-01-01' "
          "AND l_shipdate < DATE '1995-01-01' "
          "AND l_discount BETWEEN 0.05 AND 0.07 AND l_quantity < 24")
    qg = ("SELECT l_linenumber, COUNT(*) AS n, SUM(l_quantity) AS s "
          "FROM lineitem GROUP BY l_linenumber ORDER BY l_linenumber")
    queries = {"q1": QUERY, "q6": q6, "qgroup": qg}

    results_identical = True
    for label, sql in queries.items():
        got = c_enc.sql(sql, return_futures=False)
        ref = c_off.sql(sql, return_futures=False)
        same = len(got) == len(ref) and all(
            np.array_equal(got[col].to_numpy(), ref[col].to_numpy())
            for col in got.columns)
        results_identical = results_identical and same

    # pandas cross-checks
    pd_ok = True
    exp1 = run_pandas(df)
    got1 = c_enc.sql(QUERY, return_futures=False)
    pd_ok &= len(got1) == len(exp1) and np.allclose(
        got1["sum_qty"].to_numpy(np.float64),
        exp1["sum_qty"].to_numpy(np.float64), rtol=1e-9)
    sel = df[(df.l_shipdate >= np.datetime64("1994-01-01"))
             & (df.l_shipdate < np.datetime64("1995-01-01"))
             & (df.l_discount >= 0.05) & (df.l_discount <= 0.07)
             & (df.l_quantity < 24)]
    got6 = c_enc.sql(q6, return_futures=False)
    pd_ok &= np.allclose(float(got6["revenue"][0]),
                         float((sel.l_extendedprice * sel.l_discount).sum()),
                         rtol=1e-9) and int(got6["n"][0]) == len(sel)

    decodes = c_enc.metrics.counter("columnar.encoding.decode")
    codespace = c_enc.metrics.counter("columnar.encoding.codespace_pred")
    compiled_runs = (c_enc.metrics.counter("resilience.rung.compiled_aggregate")
                     + c_enc.metrics.counter("resilience.rung.compiled_select")
                     + c_enc.metrics.counter(
                         "resilience.rung.compiled_join_aggregate"))
    compressed_ok = decodes == 0 and codespace >= 1 and compiled_runs >= 1

    est_enc = estimator.estimate_plan(
        c_enc._get_ral(parse_sql(q6)[0], sql_text=q6), context=c_enc)
    est_off = estimator.estimate_plan(
        c_off._get_ral(parse_sql(q6)[0], sql_text=q6), context=c_off)
    est_ok = (est_enc.peak_bytes.hi is not None
              and est_off.peak_bytes.hi is not None
              and est_enc.peak_bytes.hi < est_off.peak_bytes.hi)

    ok = bytes_ok and results_identical and pd_ok and compressed_ok and est_ok
    print(_json.dumps({
        "metric": "compressed_domain_smoke",
        "backend": jax.default_backend(),
        "ok": bool(ok),
        "encodings": encodings,
        "encoded_bytes": enc_b,
        "decoded_bytes": dec_b,
        "encoded_over_decoded": round(ratio, 3),
        "bytes_ok": bool(bytes_ok),
        "full_column_decodes": decodes,
        "codespace_predicates": codespace,
        "compiled_rung_runs": compiled_runs,
        "results_identical_to_decoded": bool(results_identical),
        "results_match_pandas": bool(pd_ok),
        "estimate_hi_encoded": est_enc.peak_bytes.hi,
        "estimate_hi_plain": est_off.peak_bytes.hi,
        "estimate_ok": bool(est_ok),
    }), flush=True)
    if not ok:
        raise SystemExit(1)


def run_spmd_smoke():
    """`bench.py --spmd`: SPMD sharded-execution smoke (ISSUE 11).

    Shards lineitem over the local mesh, runs the Q1 shape on the sharded
    and the single-chip context, and asserts: the spmd_aggregate rung
    fired (trace span attr), results match pandas, and — on >= 2 REAL
    devices — sharded rows/s is at least the single-chip run.  On the CPU
    backend the mesh is virtual (every "device" shares the same cores), so
    the perf bar is reported but not enforced.  Exit 1 on violation."""
    import os

    # the virtual mesh must exist BEFORE jax initializes
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8")
    _ensure_backend()
    import jax

    from dask_sql_tpu import Context

    ndev = len(jax.devices())
    if ndev < 2:
        print(json.dumps({"metric": "spmd_smoke", "ok": True,
                          "skipped": "single-device environment"}),
              flush=True)
        return

    n = min(N_ROWS, 2_000_000)
    df = gen_lineitem(n, seed=0)
    expected = run_pandas(df)

    def timed(ctx):
        ctx.sql(QUERY).compute()  # warm (compile)
        t0 = time.perf_counter()
        res = ctx.sql(QUERY).compute()
        return res, n / (time.perf_counter() - t0)

    single = Context()
    single.config.update({"serving.cache.enabled": False})
    single.create_table("lineitem", df)
    _, single_rate = timed(single)

    sharded = Context()
    sharded.config.update({"serving.cache.enabled": False})
    sharded.create_table("lineitem", df, distributed=True)
    res, spmd_rate = timed(sharded)

    tr = sharded.last_trace
    rung_spans = [s for s in tr.spans if s.name == "rung:spmd_aggregate"
                  and s.attrs.get("spmd")]
    rung_fired = bool(rung_spans) and \
        sharded.metrics.counter("resilience.rung.spmd_aggregate") >= 1

    res = res.sort_values(["l_returnflag", "l_linestatus"]).reset_index(
        drop=True)
    exp = expected.reset_index(drop=True)
    try:
        np.testing.assert_allclose(
            res["sum_qty"].to_numpy(np.float64),
            exp["sum_qty"].to_numpy(np.float64), rtol=1e-6)
        np.testing.assert_allclose(
            res["count_order"].to_numpy(np.float64),
            exp["count_order"].to_numpy(np.float64))
        pd_ok = list(res["l_returnflag"]) == list(exp["l_returnflag"])
    except AssertionError:
        pd_ok = False

    perf_enforced = jax.default_backend() != "cpu"
    perf_ok = (not perf_enforced) or spmd_rate >= single_rate
    ok = rung_fired and pd_ok and perf_ok
    print(json.dumps({
        "metric": "spmd_smoke",
        "backend": jax.default_backend(),
        "ok": bool(ok),
        "devices": ndev,
        "spmd_rung_fired": bool(rung_fired),
        "results_match_pandas": bool(pd_ok),
        "spmd_rows_per_sec": round(spmd_rate, 1),
        "single_chip_rows_per_sec": round(single_rate, 1),
        "speedup": round(spmd_rate / single_rate, 3) if single_rate else None,
        "perf_enforced": bool(perf_enforced),
    }), flush=True)
    if not ok:
        raise SystemExit(1)


def run_predict_smoke():
    """`bench.py --predict`: compiled in-plan inference smoke.

    Trains a gradient-boosted model on TPC-H-shaped data, then asserts
    (exit 1 on violation):

    1. *Fused rung*: the PREDICT query answers on ``compiled_predict``
       (the ``rung:compiled_predict`` span is present — model inference
       ran in the scan's executable, no mid-plan host round trip);
    2. *Correctness*: the fused predictions match ``model.predict`` over
       the pandas-filtered rows within float tolerance;
    3. *Zero recompile*: a second literal variant AND a retrained
       same-shape model both serve with ZERO foreground compile spans.
    """
    import json as _json

    _ensure_backend()
    import jax

    from dask_sql_tpu import Context

    df = gen_lineitem(100_000, seed=0)
    c = Context()
    c.config.update({"serving.cache.enabled": False})
    c.create_table("lineitem", df)

    def train(seed):
        c.sql("""CREATE OR REPLACE MODEL revenue WITH (
                 model_class = 'sklearn.ensemble.GradientBoostingRegressor',
                 target_column = 'l_extendedprice',
                 n_estimators = 10, max_depth = 3, random_state = {})
                 AS (SELECT l_quantity, l_discount, l_tax, l_extendedprice
                     FROM lineitem)""".format(seed), return_futures=False)

    def q(disc):
        return ("SELECT * FROM PREDICT(MODEL revenue, "
                "SELECT l_quantity, l_discount, l_tax FROM lineitem "
                f"WHERE l_discount > {disc})")

    def compile_spans(tr):
        return [s.name for s in tr.spans if s.name.startswith("compile:")]

    train(0)
    res1 = c.sql(q(0.02), return_futures=False)
    tr1 = c.last_trace
    fused = any(s.name == "rung:compiled_predict" for s in tr1.spans)
    model, cols = c.get_model(c.schema_name, "revenue")
    sub = df[df.l_discount > 0.02]
    expected = model.predict(sub[cols].to_numpy())
    correct = len(res1) == len(sub) and np.allclose(
        res1["target"].to_numpy(dtype=np.float64), expected, rtol=1e-6)
    # second literal variant: zero foreground compiles
    c.sql(q(0.021), return_futures=False)  # warm this survivor bucket
    res2 = c.sql(q(0.0215), return_futures=False)
    tr2 = c.last_trace
    variant_compiles = compile_spans(tr2)
    # retrain with the same hyper-shape: weights swap, zero compiles
    train(7)
    res3 = c.sql(q(0.0215), return_futures=False)
    tr3 = c.last_trace
    retrain_compiles = compile_spans(tr3)
    model2, _ = c.get_model(c.schema_name, "revenue")
    sub3 = df[df.l_discount > 0.0215]
    retrain_correct = np.allclose(
        res3["target"].to_numpy(dtype=np.float64),
        model2.predict(sub3[cols].to_numpy()), rtol=1e-6)
    swaps = c.metrics.counter("inference.model.swap")

    ok = (fused and correct and not variant_compiles
          and not retrain_compiles and retrain_correct and swaps >= 1)
    print(_json.dumps({
        "metric": "compiled_predict_smoke",
        "backend": jax.default_backend(),
        "fused_rung": bool(fused),
        "predictions_match": bool(correct),
        "variant_foreground_compiles": variant_compiles,
        "retrain_foreground_compiles": retrain_compiles,
        "retrain_predictions_match": bool(retrain_correct),
        "model_swaps": swaps,
        "rows": len(res1),
        "ok": bool(ok),
    }, indent=2), flush=True)
    if not ok:
        raise SystemExit(1)


def run_lint_smoke():
    """`bench.py --lint`: static + runtime concurrency-analysis smoke.

    Three gates, one JSON line, exit 1 on any failure:

    1. engine self-lint (all rules DSQL101-703, including the repo-wide
       lock-order pass and the CFG-based effect-lifecycle rules) must be
       clean — a per-rule findings table is printed either way;
    2. `EXPLAIN LINT` of the benchmark query must verify with zero errors;
    3. a 2-replica fleet booted with the runtime lock sanitizer ON serves
       concurrent reads plus a fanned-out INSERT INTO with ZERO
       ``lock.order_violation`` flight events — the dynamic counterpart
       of gate 1's DSQL601.

    Pure host work — safe to run on every change without touching devices.
    """
    from dask_sql_tpu.analysis import self_lint
    from dask_sql_tpu.analysis.selflint import RULES

    findings = self_lint()
    for f in findings:
        print(f.format(), flush=True)
    by_rule = {rule: 0 for rule in sorted(RULES)}
    for f in findings:
        by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
    width = max(len(r) for r in by_rule)
    print(f"  {'rule':<{width}}  findings  description", flush=True)
    for rule, count in sorted(by_rule.items()):
        desc = RULES.get(rule, "syntax error")
        print(f"  {rule:<{width}}  {count:>8}  {desc}", flush=True)

    _ensure_backend()
    from dask_sql_tpu import Context

    c = Context()
    c.create_table("lineitem", gen_lineitem(10_000, seed=0))
    rows = list(c.sql("EXPLAIN LINT " + QUERY, return_futures=False)["LINT"])
    errors = sum(1 for r in rows if r.startswith("error["))

    # gate 3: the sanitizer watching the full declared rank order
    # (router.apply 10 -> ... -> observability.flight 95) under a real
    # concurrent fleet workload
    from concurrent.futures import ThreadPoolExecutor

    from dask_sql_tpu import config as _config_module
    from dask_sql_tpu.fleet import build_fleet
    from dask_sql_tpu.observability import flight
    from dask_sql_tpu.runtime import locks as runtime_locks

    _config_module.config.update({"analysis.lock_sanitizer": True})
    lock_baseline = runtime_locks.violation_count()
    flight_baseline = len(flight.RECORDER.events(name="lock.order_violation"))
    df = gen_lineitem(5_000, seed=1)

    def factory():
        fc = Context()  # arms the sanitizer (analysis.lock_sanitizer)
        fc.create_table("lineitem", df)
        return fc

    router, members, _replicator = build_fleet(factory, replicas=2,
                                               standby=False)
    try:
        with ThreadPoolExecutor(max_workers=4,
                                thread_name_prefix="lint-fleet") as pool:
            futs = [pool.submit(router.execute, QUERY, f"lint-r{i}")
                    for i in range(6)]
            futs.append(pool.submit(
                router.execute,
                "INSERT INTO lineitem SELECT * FROM lineitem LIMIT 5",
                "lint-w0"))
            fleet_results = [f.result(300.0) for f in futs]
    finally:
        router.shutdown()
    lock_violations = runtime_locks.violation_count() - lock_baseline
    flight_violations = len(flight.RECORDER.events(
        name="lock.order_violation")) - flight_baseline
    fleet_ok = (all(r is not None for r in fleet_results)
                and lock_violations == 0 and flight_violations == 0)
    for v in runtime_locks.violations()[-max(lock_violations, 0):] \
            if lock_violations else []:
        print(f"  LOCK VIOLATION: {v['kind']}: holding {v['holding']} "
              f"acquiring {v['acquiring']} on {v['thread']}", flush=True)

    ok = not findings and errors == 0 and fleet_ok
    print(json.dumps({
        "metric": "static_analysis_smoke",
        "ok": bool(ok),
        "self_lint_findings": len(findings),
        "findings_by_rule": {r: n for r, n in sorted(by_rule.items()) if n},
        "explain_lint_errors": errors,
        "explain_lint_rows": len(rows),
        "fleet_queries": len(fleet_results),
        "lock_order_violations": int(lock_violations),
        "lock_sanitizer_edges": len(runtime_locks.snapshot()["edges"]),
    }), flush=True)
    if not ok:
        raise SystemExit(1)


def run_schedule_smoke():
    """`bench.py --schedule`: packing-scheduler smoke, exit 1 on violation.

    Mixed interactive+batch workload against a device budget that fits one
    batch working set plus three interactive ones (floors from the REAL
    estimator via `Context.cost_hint`):

    1. *FIFO baseline* — `serving.scheduler.enabled=false` with ONE worker:
       absent byte-aware packing, serial execution is the only provably
       safe concurrency under a device budget, so this is the conservative
       operator config the scheduler replaces.  Interactive queries queue
       behind the batch scan (head-of-line blocking).
    2. *Packing scheduler* — 4 workers, same budget: the batch scan and
       interactive queries run CONCURRENTLY (`serving.scheduler.packed`
       >= 1) because their floors fit, and interactive p95 latency must be
       strictly below the FIFO baseline measured in this same process.
    3. *Tenant quotas* — a greedy tenant flooding the queue must not starve
       a victim tenant (victim completes within the leading completions)
       while every greedy query still succeeds.
    """
    import json as _json

    _ensure_backend()
    import jax

    from dask_sql_tpu import Context
    from dask_sql_tpu.serving import QueryCost, ServingRuntime
    from dask_sql_tpu.serving.metrics import nearest_rank

    df = gen_lineitem(400_000, seed=0)
    c = Context()
    # result cache off: every interactive repeat must EXECUTE (the smoke
    # measures scheduling, not cache lookups)
    c.config.update({"serving.cache.enabled": False})
    c.create_table("lineitem", df)
    # the interactive working set is a small dimension table — the classic
    # mixed workload: dashboards hitting point lookups while one report
    # scans the fact table
    c.create_table("dim", gen_lineitem(20_000, seed=1))
    # the batch scan: a multi-branch report (UNION ALL of q1-shaped
    # aggregates) — many kernel launches, so packed interactive queries
    # interleave BETWEEN launches.  (A single fused kernel is
    # non-preemptible on any backend: packing overlaps queue wait and
    # host work, it cannot preempt a running launch.)
    batch_q = " UNION ALL ".join(
        f"SELECT l_returnflag, SUM(l_extendedprice * {1.0 + i / 10}) AS s, "
        f"AVG(l_quantity) AS q FROM lineitem "
        f"WHERE l_discount > 0.0{i} GROUP BY l_returnflag"
        for i in range(1, 9))
    inter_q = ("SELECT l_returnflag, l_extendedprice FROM dim "
               "WHERE l_extendedprice > 99000.0 LIMIT 20")
    # pre-warm: compile both families and populate plan cache + profiles
    # (cost_hint reads both; the smoke measures warm serving, not compiles)
    c.sql(batch_q, return_futures=False)
    c.sql(inter_q, return_futures=False)
    batch_cost = c.cost_hint(batch_q)
    inter_cost = c.cost_hint(inter_q)
    costs_ok = (batch_cost is not None and inter_cost is not None
                and batch_cost.bytes_lo > 0 and inter_cost.bytes_lo > 0)
    # the acceptance budget: one batch + three interactive provable floors
    budget = (batch_cost.bytes_lo + 3 * inter_cost.bytes_lo
              + (1 << 20)) if costs_ok else None

    def run_phase(runtime, n_inter=6):
        """One batch scan, then n interactive arrivals DURING it (the
        head-of-line shape: the report is already on the device when the
        dashboards land); returns interactive submit->completion seconds."""
        import threading as _threading

        done_at = {}
        batch_running = _threading.Event()

        def work(q, started=None):
            def fn(_t):
                if started is not None:
                    started.set()
                c.sql(q, return_futures=False)
                return q
            return fn

        futs = []
        _, bf, _ = runtime.submit(work(batch_q, batch_running),
                                  priority_class="batch", cost=batch_cost)
        batch_running.wait(60)
        t0s = []
        for i in range(n_inter):
            t0 = time.perf_counter()
            qid, f, _ = runtime.submit(work(inter_q), cost=inter_cost)
            f.add_done_callback(
                lambda _f, qid=qid: done_at.__setitem__(
                    qid, time.perf_counter()))
            t0s.append((qid, t0))
            futs.append(f)
        bf.result(300)
        for f in futs:
            f.result(300)
        return [done_at[qid] - t0 for qid, t0 in t0s]

    # -- phase 1: FIFO baseline (the byte-safe serial config) -------------
    rt_fifo = ServingRuntime(workers=1, metrics=c.metrics,
                             scheduler_enabled=False)
    fifo_lat = run_phase(rt_fifo)
    rt_fifo.shutdown(wait=True)
    fifo_p95 = nearest_rank(sorted(fifo_lat), 0.95)

    # -- phase 2: packing scheduler, same budget, same process ------------
    # workers exceed what the budget admits: concurrency is bounded by the
    # PACKER (batch + 3 interactive floors fit -> two packing waves for
    # the 6 interactive arrivals), not by the pool size
    rt_sched = ServingRuntime(workers=8, metrics=c.metrics,
                              scheduler_budget_bytes=budget)
    sched_lat = run_phase(rt_sched)
    rt_sched.shutdown(wait=True)
    sched_p95 = nearest_rank(sorted(sched_lat), 0.95)
    packed = c.metrics.counter("serving.scheduler.packed")

    # -- phase 3: tenant quotas under contention --------------------------
    import threading as _threading

    rt_q = ServingRuntime(workers=2, metrics=c.metrics,
                          tenant_rate=0.001, tenant_burst=1)
    completions = []
    # hold both workers until the whole mixed backlog is queued, so the
    # scheduler (not submission timing) decides the order
    hold = _threading.Event()
    held = _threading.Semaphore(0)
    holders = [rt_q.submit(
        lambda t: (held.release(), hold.wait(30)))[1] for _ in range(2)]
    held.acquire()
    held.acquire()
    greedy_futs = [rt_q.submit(
        lambda t, i=i: completions.append(f"greedy{i}") or i,
        cost=QueryCost(tenant="greedy", pred_exec_ms=1.0))[1]
        for i in range(6)]
    victim_fut = rt_q.submit(
        lambda t: completions.append("victim") or "v",
        cost=QueryCost(tenant="victim", pred_exec_ms=1.0))[1]
    hold.set()
    greedy_ok = all(f.result(60) == i
                    for i, f in enumerate(greedy_futs))
    victim_ok = victim_fut.result(60) == "v" \
        and "victim" in completions[:3]
    for f in holders:
        f.result(60)
    rt_q.shutdown(wait=True)

    ok = (costs_ok and packed >= 1 and sched_p95 < fifo_p95
          and greedy_ok and victim_ok)
    print(_json.dumps({
        "metric": "packing_scheduler_smoke",
        "backend": jax.default_backend(),
        "ok": bool(ok),
        "budget_bytes": budget,
        "batch_floor_bytes": None if batch_cost is None
        else batch_cost.bytes_lo,
        "interactive_floor_bytes": None if inter_cost is None
        else inter_cost.bytes_lo,
        "fifo_interactive_p95_ms": round(fifo_p95 * 1000, 2),
        "sched_interactive_p95_ms": round(sched_p95 * 1000, 2),
        "packed_dispatches": packed,
        "quota_throttled": c.metrics.counter(
            "serving.scheduler.quota_throttled"),
        "greedy_all_succeeded": bool(greedy_ok),
        "victim_not_starved": bool(victim_ok),
    }), flush=True)
    if not ok:
        raise SystemExit(1)


def run_stream_smoke():
    """`bench.py --stream`: streamed partitioned execution smoke, exit 1
    on violation (ISSUE 13 acceptance).

    1. *Streamed completion* — a working set whose provable resident floor
       is >2x the configured admission budget completes via N>1 pipelined
       partition launches of one morsel executable (instead of the 429 the
       gate used to return), with results matching pandas.
    2. *Mid-stream OOM recovery* — an injected ``partition:atK`` fault
       mid-sequence repartitions (halved chunks) and RESUMES from the last
       completed partition: the per-run processed-row counter equals the
       table rows exactly (a restart would re-count completed partitions),
       and results still match pandas.
    """
    import json as _json

    _ensure_backend()
    import jax
    import pandas as pd

    from dask_sql_tpu import Context
    from dask_sql_tpu.resilience import faults
    from dask_sql_tpu.serving.cache import table_nbytes

    n = 600_000
    df = gen_lineitem(n, seed=0)
    c = Context()
    c.config.update({"serving.cache.enabled": False})
    c.create_table("lineitem", df)
    resident = table_nbytes(c.schema["root"].tables["lineitem"].table)
    q = ("SELECT l_returnflag, SUM(l_quantity) AS sum_qty, "
         "COUNT(*) AS count_order, AVG(l_quantity) AS avg_qty "
         "FROM lineitem GROUP BY l_returnflag ORDER BY l_returnflag")
    # warm the plan cache, then size the budget from the query's PROVABLE
    # working-set floor (the estimator's peak_bytes.lo — what the gate
    # actually sheds on): the floor is > 2x the budget, so the single
    # launch is provably infeasible and only streaming can serve it
    c.sql(q, return_futures=False)
    cost = c.cost_hint(q)
    floor = int(cost.bytes_lo) if cost is not None else 0
    budget = floor // 2 - (1 << 10)
    expected = (df.groupby("l_returnflag").agg(
        sum_qty=("l_quantity", "sum"), count_order=("l_quantity", "size"),
        avg_qty=("l_quantity", "mean")).reset_index().sort_values(
            "l_returnflag").reset_index(drop=True))

    def matches(res) -> bool:
        got = res.sort_values("l_returnflag").reset_index(drop=True)
        try:
            assert list(got["l_returnflag"]) == list(
                expected["l_returnflag"])
            np.testing.assert_allclose(got["sum_qty"], expected["sum_qty"],
                                       rtol=1e-5)
            np.testing.assert_array_equal(got["count_order"],
                                          expected["count_order"])
            np.testing.assert_allclose(got["avg_qty"], expected["avg_qty"],
                                       rtol=1e-5)
            return True
        except AssertionError:
            return False

    opts = {"serving.admission.max_estimated_bytes": budget}
    # phase 1: streamed completion, N>1 launches, pandas-identical
    res1 = c.sql(q, return_futures=False, config_options=opts)
    parts1 = c.metrics.counter("serving.stream.partitions")
    rows1 = c.metrics.counter("serving.stream.rows")
    ok_stream = (budget > 0 and floor > 2 * budget
                 and c.metrics.counter("serving.stream.admitted") >= 1
                 and parts1 > 1 and rows1 == n
                 and c.metrics.counter("serving.shed_estimated_bytes") == 0
                 and matches(res1))

    # phase 2: induced mid-stream OOM -> repartition + resume (no restart)
    faults.reset()
    res2 = c.sql(q, return_futures=False, config_options={
        **opts, "resilience.inject": "partition:at2",
        "serving.stream.min_chunk_rows": 1024})
    rows2 = c.metrics.counter("serving.stream.rows") - rows1
    reparts = c.metrics.counter("serving.stream.repartitions")
    ooms = c.metrics.counter("resilience.partition.oom")
    # rows2 == n proves completed partitions were NOT re-executed: a
    # restart would re-process partition 0 and overshoot
    ok_recover = (ooms >= 1 and reparts >= 1 and rows2 == n
                  and c.metrics.counter("resilience.degraded") == 0
                  and matches(res2))

    ok = ok_stream and ok_recover
    print(_json.dumps({
        "metric": "streaming_partitioned_smoke",
        "backend": jax.default_backend(),
        "ok": bool(ok),
        "resident_bytes": resident,
        "working_set_floor_bytes": floor,
        "budget_bytes": budget,
        "partitions_first_run": parts1,
        "rows_processed_first_run": rows1,
        "streamed_completion_ok": bool(ok_stream),
        "midstream_oom_injected": ooms,
        "repartitions": reparts,
        "rows_processed_recovery_run": rows2,
        "resumed_without_restart": bool(rows2 == n),
        "recovery_ok": bool(ok_recover),
    }), flush=True)
    if not ok:
        raise SystemExit(1)


def run_live_smoke():
    """`bench.py --live`: live observability plane smoke, exit 1 on
    violation (ISSUE 14 acceptance).

    Starts a Presto server over a context whose admission budget forces a
    multi-partition streamed execution, submits the query over the wire,
    and while it is IN FLIGHT:

    1. polls ``GET /v1/queries`` asserting the entry is visible with
       ADVANCING partition progress and a NONZERO reserved-byte floor;
    2. cancels it with the ``CANCEL QUERY '<qid>'`` SQL statement
       (exercising the native parser path) and asserts the query
       terminates cooperatively between launches;
    3. asserts the flight recorder (``/v1/debug/events``) holds the
       cancel event and the HBM ledger returns to idle (zero reserved
       bytes) after the cancellation.
    """
    import json as _json
    import urllib.error
    import urllib.request

    _ensure_backend()
    import jax

    from dask_sql_tpu import Context
    from dask_sql_tpu.observability import flight
    from dask_sql_tpu.server.app import run_server

    n = 600_000
    df = gen_lineitem(n, seed=0)
    c = Context()
    c.config.update({"serving.cache.enabled": False})
    c.create_table("lineitem", df)
    q = ("SELECT l_returnflag, SUM(l_quantity) AS sum_qty, "
         "COUNT(*) AS count_order FROM lineitem GROUP BY l_returnflag")
    # size the budget below the provable floor so the gate routes the
    # query to a streamed rung; pin small chunks so the stream is long
    # enough to observe mid-flight over HTTP
    c.sql(q, return_futures=False)
    cost = c.cost_hint(q)
    floor = int(cost.bytes_lo) if cost is not None else 0
    budget = max(1 << 16, floor // 3)
    c.config.update({
        "serving.admission.max_estimated_bytes": budget,
        "serving.stream.chunk_rows": 4096,
        "serving.stream.max_partitions": 512,
    })
    # re-plan under the final config so the submit-time cost hint (keyed
    # on effective config) carries the streamed per-chunk floor
    c.sql(q, return_futures=False)
    srv = run_server(context=c, host="127.0.0.1", port=0, blocking=False)
    base = f"http://127.0.0.1:{srv.port}"

    def _get(path):
        return _json.load(urllib.request.urlopen(base + path))

    def _post(path, body=b""):
        req = urllib.request.Request(base + path, data=body,
                                     headers={"X-Dsql-Class": "batch",
                                              "X-Dsql-Tenant": "bench"})
        return _json.load(urllib.request.urlopen(req))

    flight.RECORDER.clear()
    qid = _post("/v1/statement", q.encode())["id"]
    # poll the live table until the entry streams, sampling progress
    samples, reserved_seen = [], 0
    deadline = time.perf_counter() + 30.0
    while time.perf_counter() < deadline:
        snap = _get("/v1/queries")
        entry = next((e for e in snap["queries"] if e["qid"] == qid), None)
        if entry is not None and entry["state"] in ("failed", "cancelled",
                                                    "done"):
            break
        if entry is not None and entry.get("stream"):
            samples.append(entry["stream"]["partitionsDone"])
            reserved_seen = max(reserved_seen,
                                int(entry.get("reservedBytes") or 0),
                                int(snap["ledger"]["reservedBytes"] or 0))
            if len(samples) >= 2 and samples[-1] > samples[0] \
                    and samples[-1] >= 2:
                break
        time.sleep(0.002)
    advancing = len(samples) >= 2 and samples[-1] > samples[0]
    # cancel through the SQL statement (native parser path) mid-flight
    cancel_df = None
    try:
        cancel_df = _post("/v1/statement",
                          f"CANCEL QUERY '{qid}'".encode())
    except urllib.error.HTTPError:
        pass
    # wait for the cooperative cancellation to land between launches
    final = None
    deadline = time.perf_counter() + 30.0
    while time.perf_counter() < deadline:
        entry = _get(f"/v1/queries/{qid}")
        if entry["state"] in ("failed", "cancelled", "done"):
            final = entry
            break
        time.sleep(0.01)
    cancelled = final is not None and final["state"] == "cancelled"
    events = _get("/v1/debug/events?name=query.cancel")["events"]
    cancel_recorded = any(e.get("qid") == qid for e in events)
    ledger = _get("/v1/queries")["ledger"]
    ledger_idle = int(ledger["reservedBytes"]) == 0 \
        and int(ledger["inflightMeasuredBytes"]) == 0
    srv.shutdown()
    ok = (advancing and reserved_seen > 0 and cancelled
          and cancel_recorded and ledger_idle)
    print(_json.dumps({
        "metric": "live_observability_smoke",
        "backend": jax.default_backend(),
        "ok": bool(ok),
        "budget_bytes": budget,
        "working_set_floor_bytes": floor,
        "progress_samples": samples[:16],
        "partitions_advancing": bool(advancing),
        "reserved_bytes_seen": reserved_seen,
        "cancel_submitted": cancel_df is not None,
        "cancelled_cooperatively": bool(cancelled),
        "final_state": None if final is None else final["state"],
        "flight_cancel_recorded": bool(cancel_recorded),
        "ledger_idle_after": bool(ledger_idle),
    }), flush=True)
    if not ok:
        raise SystemExit(1)


def run_reuse_smoke():
    """`bench.py --reuse`: semantic result reuse smoke, exit 1 on
    violation (ISSUE 16 acceptance).

    Replays a 20-query dashboard twice against one context:

    1. *Cold wave*: 20 distinct queries — sibling projections sharing
       scan->filter stems, filtered point-lookups, grouped aggregates —
       populate the exact-match cache, pin hot stems, register
       subsumption candidates and incremental aggregate states.
    2. *Warm wave*: the replay (exact repeats + TIGHTER int literals +
       a NEVER-SEEN sibling projection) must be served entirely by the
       reuse tiers: >=1 materialized-stem hit, >=1 subsumption answer,
       ZERO foreground compiles (no ``compile.start`` flight events) and
       ZERO base-table scan launches (every surviving TableScan reads a
       pinned stem, never the catalog).
    3. *Append*: ``INSERT INTO ... SELECT`` folds the delta through the
       pinned stems (refresh, not rescan) and the stored combine states;
       the re-queried aggregate matches pandas over base+delta and is
       served as an incremental hit.
    """
    import json as _json

    _ensure_backend()
    import jax

    from dask_sql_tpu import Context
    from dask_sql_tpu.observability import flight
    from dask_sql_tpu.physical.rel.logical import basic

    n = 200_000
    df = gen_lineitem(n, seed=0)
    rng = np.random.RandomState(1)
    # non-null int columns: the provable-interval domain for subsumption
    df["l_orderkey"] = (rng.randint(0, 1_500_000, n) * 4).astype(np.int64)
    df["l_linenumber"] = rng.randint(1, 8, n).astype(np.int64)

    ctx = Context()
    ctx.config.update({"serving.materialize.min_bytes": 1})
    ctx.create_table("lineitem", df)

    stem_where = "l_quantity < 30 AND l_discount < 0.05"
    wave1 = [
        # stem A siblings: pinned at the 2nd observation
        f"SELECT l_extendedprice FROM lineitem WHERE {stem_where}",
        f"SELECT l_quantity FROM lineitem WHERE {stem_where}",
        f"SELECT l_tax FROM lineitem WHERE {stem_where}",
        # subsumption families (int comparators, loose literals)
        "SELECT l_orderkey, l_quantity FROM lineitem WHERE l_orderkey < 5000000",
        "SELECT l_orderkey, l_linenumber FROM lineitem WHERE l_linenumber <= 6",
        # incremental aggregate states + cacheable aggregates
        "SELECT l_linenumber, SUM(l_quantity) AS s, COUNT(*) AS c "
        "FROM lineitem GROUP BY l_linenumber",
        "SELECT SUM(l_extendedprice) AS s FROM lineitem",
        "SELECT l_returnflag, COUNT(*) AS c FROM lineitem GROUP BY l_returnflag",
        "SELECT MAX(l_orderkey) AS m FROM lineitem",
        "SELECT AVG(l_discount) AS a FROM lineitem",
        # stem B siblings
        "SELECT l_returnflag FROM lineitem WHERE l_tax < 0.04",
        "SELECT l_discount FROM lineitem WHERE l_tax < 0.04",
        # assorted dashboard panels (exact repeats in wave 2)
        "SELECT l_linestatus, SUM(l_tax) AS s FROM lineitem GROUP BY l_linestatus",
        "SELECT COUNT(*) AS c FROM lineitem WHERE l_returnflag = 'A'",
        "SELECT COUNT(*) AS c FROM lineitem WHERE l_returnflag = 'R'",
        "SELECT SUM(l_quantity) AS s FROM lineitem WHERE l_linestatus = 'F'",
        "SELECT SUM(l_quantity) AS s FROM lineitem WHERE l_linestatus = 'O'",
        "SELECT l_orderkey FROM lineitem WHERE l_orderkey >= 5900000",
        "SELECT MIN(l_shipdate) AS d FROM lineitem",
        "SELECT MAX(l_shipdate) AS d FROM lineitem",
    ]
    assert len(wave1) == 20
    for q in wave1:
        ctx.sql(q).compute()

    # warm wave: exact repeats + tighter literals + a new stem sibling
    wave2 = list(wave1[5:])  # 15 exact repeats
    wave2 += [
        f"SELECT l_linestatus FROM lineitem WHERE {stem_where}",  # new sibling
        "SELECT l_orderkey, l_quantity FROM lineitem WHERE l_orderkey < 2000000",
        "SELECT l_orderkey, l_linenumber FROM lineitem WHERE l_linenumber <= 3",
        "SELECT l_orderkey FROM lineitem WHERE l_orderkey >= 5950000",
        f"SELECT l_quantity FROM lineitem WHERE {stem_where}",  # repeat
    ]
    assert len(wave2) == 20

    base_scans = {"n": 0}
    orig_convert = basic.TableScanPlugin.convert

    def counting_convert(self, rel, executor):
        if executor.table_overrides.get(
                (rel.schema_name, rel.table_name)) is None:
            base_scans["n"] += 1
        return orig_convert(self, rel, executor)

    m = ctx.metrics
    cache0 = ctx._result_cache.stats.hits
    sub0 = m.counter("serving.reuse.subsumption.hits")
    stem0 = m.counter("serving.materialize.hits")
    incr0 = m.counter("serving.reuse.incremental.hits")
    flight.RECORDER.clear()
    basic.TableScanPlugin.convert = counting_convert
    try:
        results2 = [ctx.sql(q).compute() for q in wave2]
    finally:
        basic.TableScanPlugin.convert = orig_convert
    compiles2 = len(flight.RECORDER.events(name="compile.start"))
    cache_d = ctx._result_cache.stats.hits - cache0
    sub_d = m.counter("serving.reuse.subsumption.hits") - sub0
    stem_d = m.counter("serving.materialize.hits") - stem0
    incr_d = m.counter("serving.reuse.incremental.hits") - incr0
    served = cache_d + sub_d + stem_d + incr_d
    ok_warm = (sub_d >= 1 and stem_d >= 1 and served >= len(wave2)
               and compiles2 == 0 and base_scans["n"] == 0)

    # spot-check the reuse-served answers against pandas
    sub_df = results2[16]
    ok_sub = len(sub_df) == int((df["l_orderkey"] < 2_000_000).sum())
    sel = (df["l_quantity"] < 30) & (df["l_discount"] < 0.05)
    ok_stem = len(results2[15]) == int(sel.sum())

    # append phase: INSERT INTO folds the delta, never rescans history
    refreshed0 = m.counter("serving.materialize.refreshed")
    folds0 = m.counter("serving.reuse.incremental.folds")
    ins = ctx.sql(
        "INSERT INTO lineitem SELECT * FROM lineitem "
        "WHERE l_orderkey < 40000").compute()
    delta = df[df["l_orderkey"] < 40000]
    ok_insert = int(ins["Inserted"][0]) == len(delta)
    agg = ctx.sql(wave1[5]).compute()
    incr_hit = m.counter("serving.reuse.incremental.hits") - incr0 - incr_d
    full = df if not len(delta) else \
        __import__("pandas").concat([df, delta], ignore_index=True)
    exp = (full.groupby("l_linenumber", as_index=False)
           .agg(s=("l_quantity", "sum"), c=("l_quantity", "count")))
    got = agg.sort_values("l_linenumber").reset_index(drop=True)
    exp = exp.sort_values("l_linenumber").reset_index(drop=True)
    ok_incr = (incr_hit >= 1
               and got["c"].tolist() == exp["c"].tolist()
               and np.allclose(got["s"].to_numpy(),
                               exp["s"].to_numpy(), rtol=1e-4))
    ok_append = (ok_insert and ok_incr
                 and m.counter("serving.materialize.refreshed") > refreshed0
                 and m.counter("serving.reuse.incremental.folds") > folds0)

    # ledger reconciliation: pinned bytes visible, idle after eviction
    pinned = ctx.materialize.pinned_bytes()
    ok_ledger = (pinned > 0
                 and ctx.ledger.snapshot()["materializedBytes"] == pinned)
    ctx.materialize.invalidate_all()
    ok_ledger = ok_ledger and ctx.ledger.snapshot()["materializedBytes"] == 0

    ok = ok_warm and ok_sub and ok_stem and ok_append and ok_ledger
    print(_json.dumps({
        "metric": "semantic_reuse_smoke",
        "backend": jax.default_backend(),
        "ok": bool(ok),
        "rows": n,
        "warm_wave": {
            "queries": len(wave2),
            "served_by_reuse": int(served),
            "cache_hits": int(cache_d),
            "subsumption_hits": int(sub_d),
            "stem_hits": int(stem_d),
            "incremental_hits": int(incr_d),
            "foreground_compiles": int(compiles2),
            "base_table_scans": int(base_scans["n"]),
            "ok": bool(ok_warm and ok_sub and ok_stem),
        },
        "append": {
            "rows_appended": int(ins["Inserted"][0]),
            "stem_refreshes": int(
                m.counter("serving.materialize.refreshed") - refreshed0),
            "incremental_folds": int(
                m.counter("serving.reuse.incremental.folds") - folds0),
            "aggregate_matches_pandas": bool(ok_incr),
            "ok": bool(ok_append),
        },
        "ledger": {"pinned_bytes_seen": int(pinned), "ok": bool(ok_ledger)},
    }), flush=True)
    if not ok:
        raise SystemExit(1)


def run_chaos_smoke():
    """`bench.py --chaos`: seeded chaos campaigns, exit 1 on any
    invariant violation (ISSUE 17 acceptance).

    Runs >= 5 seeds, each a deterministic fault storm of >= 40
    concurrent mixed queries (interactive aggregates, batch scans,
    streamed partitioned queries, PREDICT inference, exact repeats,
    mid-flight cancels) with rotating probability-armed subsets of
    every inject site.  Individual query outcomes are free under
    chaos; what must hold after every drain are the GLOBAL invariants
    (resilience/chaos.py): terminal live-table entries, idle
    reservations and ledger, restorable breakers, no zombie threads,
    causally consistent flight timelines.
    """
    import json as _json

    _ensure_backend()
    import jax

    from dask_sql_tpu.resilience.chaos import run_campaign

    seeds = [1, 2, 3, 4, 5]
    per_seed = []
    total_violations = 0
    for seed in seeds:
        t0 = time.perf_counter()
        report = run_campaign(seed=seed, queries=40, rounds=4, workers=4)
        elapsed = time.perf_counter() - t0
        print(report.summary(), flush=True)
        for v in report.violations:
            print(f"  VIOLATION: {v}", flush=True)
        total_violations += len(report.violations)
        per_seed.append({
            "seed": seed,
            "submitted": report.submitted,
            "completed": report.completed,
            "failed": report.failed,
            "cancelled": report.cancelled,
            "shed": report.shed,
            "rounds": report.rounds,
            "sites_armed": len(report.armed),
            "violations": len(report.violations),
            "seconds": round(elapsed, 2),
            "ok": report.ok,
        })
    ok = total_violations == 0
    print(_json.dumps({
        "metric": "chaos_campaign_smoke",
        "backend": jax.default_backend(),
        "ok": bool(ok),
        "seeds": len(seeds),
        "queries_per_seed": 40,
        "invariant_violations": int(total_violations),
        "campaigns": per_seed,
    }), flush=True)
    if not ok:
        raise SystemExit(1)


def run_fleet_smoke():
    """`bench.py --fleet`: fault-tolerant replica fleet smoke (ISSUE 18).

    Part 1 — failover + warm-standby promotion: a router over 3
    in-process replicas plus a warm standby serves a concurrent workload;
    one replica is killed (kill -9 semantics) mid-workload.  Asserts:

    - every routed query completes despite the kill (failover re-dispatch
      to survivors, dedupe through the result-cache idempotency key);
    - the standby is promoted into the serving set;
    - after the surviving original replicas drain, the PROMOTED standby
      serves its first routed query of the hot family with ZERO
      foreground ``compile:<rung>`` spans (the replication transport —
      checkpoint snapshot + profile store + shared compile cache — paid
      every compile off the serving path);
    - the promoted replica's result matches the pre-kill result.

    Part 2 — replica-kill chaos: `run_fleet_campaign` over 5 seeds
    (3 replicas, mixed concurrent workload, one kill per round): zero
    lost queries, INSERT INTO applied exactly once per survivor under
    failover (epoch fencing), ledgers idle after drain.

    Exit 1 on any violation.
    """
    import json as _json
    from concurrent.futures import ThreadPoolExecutor

    _ensure_backend()
    import jax

    from dask_sql_tpu import Context
    from dask_sql_tpu.fleet import READY, build_fleet
    from dask_sql_tpu.resilience.chaos import run_fleet_campaign

    df = gen_lineitem(50_000, seed=0)

    def factory():
        c = Context()
        c.create_table("lineitem", df)
        return c

    router, members, replicator = build_fleet(factory, replicas=3,
                                              standby=True)
    baseline = router.execute(QUERY, qid="fleet-cold")
    router.execute(QUERY, qid="fleet-hot")  # the family is clearly hot
    replicator.sync()  # standby: snapshot + profiles + warm-up, off-path

    with ThreadPoolExecutor(max_workers=4,
                            thread_name_prefix="fleet-smoke") as pool:
        futs = [pool.submit(router.execute, QUERY, f"fleet-w{i}")
                for i in range(8)]
        time.sleep(0.05)
        router.kill(members[1].name)  # kill -9 one replica mid-workload
        results = [f.result(300.0) for f in futs]
    all_complete = all(r is not None for r in results)

    promoted = router.find("standby")
    was_promoted = bool(promoted is not None and promoted.state == READY
                        and promoted in router.replicas)
    # drain the surviving originals so the next routed query can only
    # land on the promoted standby — ITS first serve of this family
    router.drain(members[0].name)
    router.drain(members[2].name)
    out = router.execute(QUERY, qid="fleet-promoted")
    tr = promoted.context.last_trace if promoted is not None else None
    fg_compiles = [] if tr is None else \
        [s.name for s in tr.spans if s.name.startswith("compile:")]
    match = out is not None and len(out) == len(baseline) and np.allclose(
        out["sum_qty"].to_numpy(np.float64),
        baseline["sum_qty"].to_numpy(np.float64), rtol=1e-9)
    router.shutdown()
    part1_ok = bool(all_complete and was_promoted and not fg_compiles
                    and match)

    seeds = [1, 2, 3, 4, 5]
    per_seed = []
    total_violations = 0
    for seed in seeds:
        t0 = time.perf_counter()
        report = run_fleet_campaign(seed=seed, queries=21, rounds=3,
                                    replicas=3, clients=4)
        elapsed = time.perf_counter() - t0
        print(report.summary(), flush=True)
        for v in report.violations:
            print(f"  VIOLATION: {v}", flush=True)
        total_violations += len(report.violations)
        per_seed.append({
            "seed": seed,
            "submitted": report.submitted,
            "completed": report.completed,
            "retried": report.retried,
            "failed": report.failed,
            "shed": report.shed,
            "kills": report.kills,
            "promoted": report.promoted,
            "inserts": report.inserts,
            "violations": len(report.violations),
            "seconds": round(elapsed, 2),
            "ok": report.ok,
        })

    ok = bool(part1_ok and total_violations == 0)
    print(_json.dumps({
        "metric": "fleet_smoke",
        "backend": jax.default_backend(),
        "ok": ok,
        "workload_completed": int(sum(1 for r in results if r is not None)),
        "workload_submitted": len(results),
        "standby_promoted": was_promoted,
        "promoted_foreground_compile_spans": fg_compiles,
        "results_match": bool(match),
        "chaos_seeds": len(seeds),
        "chaos_violations": int(total_violations),
        "campaigns": per_seed,
    }), flush=True)
    if not ok:
        raise SystemExit(1)


def main():
    import sys

    if "--fleet" in sys.argv:
        run_fleet_smoke()
        return
    if "--chaos" in sys.argv:
        run_chaos_smoke()
        return
    if "--live" in sys.argv:
        run_live_smoke()
        return
    if "--reuse" in sys.argv:
        run_reuse_smoke()
        return
    if "--lint" in sys.argv:
        run_lint_smoke()
        return
    if "--stream" in sys.argv:
        run_stream_smoke()
        return
    if "--inject" in sys.argv:
        run_inject_smoke()
        return
    if "--estimate" in sys.argv:
        run_estimate_smoke()
        return
    if "--profile" in sys.argv:
        run_profile_smoke()
        return
    if "--coldstart" in sys.argv:
        run_coldstart_smoke()
        return
    if "--families" in sys.argv:
        run_families_smoke()
        return
    if "--compressed" in sys.argv:
        run_compressed_smoke()
        return
    if "--spmd" in sys.argv:
        run_spmd_smoke()
        return
    if "--schedule" in sys.argv:
        run_schedule_smoke()
        return
    if "--predict" in sys.argv:
        run_predict_smoke()
        return

    import jax

    _ensure_backend()

    from dask_sql_tpu import Context
    from dask_sql_tpu.utils import TRANSFER_STATS

    df = gen_lineitem(N_ROWS)

    c = Context()
    # result cache off: measure execution, not serving-cache lookups
    c.config.update({"serving.cache.enabled": False})
    c.create_table("lineitem", df)

    # warm-up (compile caches, device transfer)
    frame = c.sql(QUERY)
    _ = frame.compute()

    # phase breakdown on THIS backend (the driver runs this on the chip):
    # cached-plan time, execute+decode, and device->host round trips
    t0 = time.perf_counter()
    plan_frame = c.sql(QUERY)
    t_plan = time.perf_counter() - t0
    TRANSFER_STATS["d2h"] = 0
    t0 = time.perf_counter()
    plan_frame.compute()
    t_exec = time.perf_counter() - t0
    print(json.dumps({
        "metric": "q1_phase_breakdown",
        "backend": jax.default_backend(),
        "plan_ms": round(t_plan * 1000, 2),
        "execute_ms": round(t_exec * 1000, 2),
        "d2h_round_trips": TRANSFER_STATS["d2h"],
    }), flush=True)

    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        res = c.sql(QUERY).compute()
        times.append(time.perf_counter() - t0)
    best = min(times)
    throughput = N_ROWS / best

    # root SELECT pipeline (filter+project+topk): two kernels, two round
    # trips, transfer sized by survivors (physical/compiled_select.py)
    sel_sql = ("SELECT l_returnflag, l_extendedprice * (1 - l_discount) AS rev "
               "FROM lineitem WHERE l_discount > 0.09 "
               "ORDER BY rev DESC LIMIT 100")
    c.sql(sel_sql).compute()
    TRANSFER_STATS["d2h"] = 0
    t0 = time.perf_counter()
    c.sql(sel_sql).compute()
    t_sel = time.perf_counter() - t0
    print(json.dumps({
        "metric": "select_topk_rows_per_sec",
        "value": round(N_ROWS / t_sel, 1),
        "unit": "rows/s",
        "backend": jax.default_backend(),
        "d2h_round_trips": TRANSFER_STATS["d2h"],
    }), flush=True)

    try:
        bench_q3_line(jax.default_backend())
    except Exception as e:  # Q3 must never sink the headline metric
        print(json.dumps({"metric": "tpch_q3_sf1_rows_per_sec_per_chip",
                          "error": f"{type(e).__name__}: {e}"}), flush=True)

    # pandas baseline (the reference's per-partition engine)
    t0 = time.perf_counter()
    expected = run_pandas(df)
    pandas_time = time.perf_counter() - t0
    t0 = time.perf_counter()
    expected = run_pandas(df)
    pandas_time = min(pandas_time, time.perf_counter() - t0)

    # correctness spot check
    assert len(res) == len(expected), (len(res), len(expected))
    np.testing.assert_allclose(
        res["sum_qty"].to_numpy(dtype=np.float64),
        expected["sum_qty"].to_numpy(dtype=np.float64), rtol=1e-2)

    print(json.dumps({
        "metric": "tpch_q1_sf1_rows_per_sec_per_chip",
        "value": round(throughput, 1),
        "unit": "rows/s",
        "vs_baseline": round((N_ROWS / pandas_time) and throughput / (N_ROWS / pandas_time), 3),
    }))


if __name__ == "__main__":
    main()
