"""TPC-DS q1-q99 runner with an explicit xfail list.

Parity: the reference's coverage yardstick (reference
tests/unit/test_queries.py:5-44 — 99 TPC-DS-style queries with a 38-query
XFAIL list; 61 expected passes on CPU).  Here 99 standard TPC-DS queries run
against generated in-memory tables; the xfail list below is the honest
record of what the engine cannot do yet, grouped by root cause.
"""
import pytest

from tests.tpcds import generate
from tests.tpcds_queries import QUERIES

# Root causes (round 3 state; re-rooted after the r3 fixes: GROUPING(),
# HAVING/ORDER BY select-alias resolution, empty-frame robustness, and the
# r2 engine work that had already cured the CTE-reuse class).  The three
# remaining shapes — EXISTS under OR (q10/q35) and a correlated scalar
# COUNT whose correlation predicate sits under OR (q41) — are xfailed by
# the REFERENCE too (reference tests/unit/test_queries.py:5-39).
XFAIL_QUERIES = {
    10: "decorrelate: EXISTS under OR (reference xfails q10 too)",
    35: "decorrelate: EXISTS under OR (reference xfails q35 too)",
    41: "decorrelate: correlation predicate under OR (reference xfails q41 too)",
}
# too slow at any scale without the compiled join pipeline — skipped, not xfail
SLOW_QUERIES = {23: "4 CTE scans x self-joins", 24: "ssales CTE x2",
                64: "18-table join at test scale"}


@pytest.fixture(scope="module")
def tpcds_context():
    from dask_sql_tpu import Context

    c = Context()
    for name, df in generate(scale_rows=1000).items():
        c.create_table(name, df)
    return c


def _params():
    for qnum in sorted(QUERIES):
        marks = []
        if qnum in SLOW_QUERIES:
            marks.append(pytest.mark.skip(reason=f"q{qnum}: {SLOW_QUERIES[qnum]}"))
        elif qnum in XFAIL_QUERIES:
            # declarative xfail: the query still RUNS, so a query that starts
            # passing surfaces as XPASS instead of silently going stale
            marks.append(pytest.mark.xfail(
                reason=f"q{qnum}: {XFAIL_QUERIES[qnum]}", strict=False))
        yield pytest.param(qnum, marks=marks)


@pytest.mark.parametrize("qnum", _params())
def test_query(tpcds_context, qnum):
    result = tpcds_context.sql(QUERIES[qnum]).compute()
    assert result is not None
    assert len(result.columns) > 0
