#!/bin/bash
# Keep retrying the on-chip Q1 phase profile until the axon tunnel grants a
# claim, then run the Q1 + Q3 benches on the chip.  Writes results under
# benchmarks/out/.  Run as THE single TPU-claiming process (everything else
# must use PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu).
set -u
cd "$(dirname "$0")/.."
mkdir -p benchmarks/out
for i in $(seq 1 40); do
    echo "[probe-loop] attempt $i $(date +%H:%M:%S)" >> benchmarks/out/probe_loop.log
    timeout 1200 python benchmarks/profile_q1.py > benchmarks/out/profile_tpu.jsonl 2> benchmarks/out/profile_tpu.err
    rc=$?
    if [ $rc -eq 0 ] && grep -q '"backend": "axon"' benchmarks/out/profile_tpu.jsonl \
            && grep -q rows_per_sec benchmarks/out/profile_tpu.jsonl; then
        echo "[probe-loop] profile OK" >> benchmarks/out/probe_loop.log
        timeout 1200 python bench.py > benchmarks/out/bench_tpu.json 2>> benchmarks/out/probe_loop.log
        timeout 1200 python benchmarks/bench_q3.py > benchmarks/out/bench_q3_tpu.json 2>> benchmarks/out/probe_loop.log
        echo "[probe-loop] done" >> benchmarks/out/probe_loop.log
        exit 0
    fi
    sleep 60
done
echo "[probe-loop] gave up" >> benchmarks/out/probe_loop.log
exit 1
