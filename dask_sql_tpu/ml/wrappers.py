"""Estimator wrappers for partitioned/device data.

Role parity: reference wrappers.py (vendored dask-ml): ParallelPostFit
(wrappers.py:51) — train once, predict/transform/score partition-wise;
Incremental (wrappers.py:425) — stream partial_fit across partitions.
Here "partitions" are device-table row blocks; predictions run blockwise on
host (sklearn) or on device (ml/jax_models.py).
"""
from __future__ import annotations

from typing import Any, List, Optional

import numpy as np


class ParallelPostFit:
    """Meta-estimator: fit on (sub)sampled data, apply blockwise."""

    def __init__(self, estimator: Any = None, predict_meta=None, predict_proba_meta=None,
                 transform_meta=None, block_rows: int = 1_000_000):
        self.estimator = estimator
        self.block_rows = block_rows

    def fit(self, X, y=None, **kwargs):
        self.estimator.fit(X, y, **kwargs) if y is not None else self.estimator.fit(X, **kwargs)
        return self

    def _blockwise(self, method, X):
        n = len(X)
        outs = []
        for start in range(0, n, self.block_rows):
            block = X[start : start + self.block_rows]
            outs.append(np.asarray(method(block)))
        if not outs:
            return np.array([])
        return np.concatenate(outs) if outs[0].ndim == 1 else np.vstack(outs)

    def predict(self, X):
        return self._blockwise(self.estimator.predict, np.asarray(X))

    def predict_proba(self, X):
        return self._blockwise(self.estimator.predict_proba, np.asarray(X))

    def transform(self, X):
        return self._blockwise(self.estimator.transform, np.asarray(X))

    def score(self, X, y):
        return self.estimator.score(np.asarray(X), np.asarray(y))

    def get_params(self, deep: bool = True):
        return self.estimator.get_params(deep) if hasattr(self.estimator, "get_params") else {}

    def __getattr__(self, item):
        return getattr(self.estimator, item)


class Incremental(ParallelPostFit):
    """Streamed training via partial_fit over row blocks (parity:
    wrappers.py:718-760 fit loop)."""

    def __init__(self, estimator: Any = None, scoring=None, shuffle_blocks: bool = True,
                 block_rows: int = 100_000, **kwargs):
        super().__init__(estimator, block_rows=block_rows)
        self.shuffle_blocks = shuffle_blocks

    def fit(self, X, y=None, classes=None, **kwargs):
        X = np.asarray(X)
        y_arr = np.asarray(y) if y is not None else None
        n = len(X)
        starts = list(range(0, n, self.block_rows))
        if classes is None and y_arr is not None and hasattr(self.estimator, "partial_fit"):
            classes = np.unique(y_arr)
        for start in starts:
            xb = X[start : start + self.block_rows]
            yb = y_arr[start : start + self.block_rows] if y_arr is not None else None
            if yb is not None:
                try:
                    self.estimator.partial_fit(xb, yb, classes=classes, **kwargs)
                except TypeError:
                    self.estimator.partial_fit(xb, yb, **kwargs)
            else:
                self.estimator.partial_fit(xb, **kwargs)
        return self
