"""Collectives-routed distributed execution: mechanism-pinning tests.

Round-1 asserted result equality only; a silent full-gather would have
passed.  These tests pin the mechanism itself:
- the compiled kernels contain `all-to-all` collectives,
- per-device output shards are ~1/ndev of the global shape,
- sharded-table SQL actually routes through the kernels (STATS counters),
- results match pandas for the full aggregate set, multi-key, and NULLs.
"""
import numpy as np
import pandas as pd
import pytest

import jax
import jax.numpy as jnp

from tests.utils import assert_eq

needs_mesh = pytest.mark.skipif(len(jax.devices()) < 2, reason="needs mesh")


@pytest.fixture
def mesh():
    from dask_sql_tpu.parallel.mesh import make_mesh

    if len(jax.devices()) < 2:
        pytest.skip("needs mesh")
    return make_mesh(len(jax.devices()))


# ---------------------------------------------------------------------------
# mechanism: explicit collectives in the compiled HLO
# ---------------------------------------------------------------------------
@needs_mesh
def test_agg_kernel_hlo_has_all_to_all(mesh):
    from dask_sql_tpu.parallel import dist_plan as dp

    ndev = mesh.devices.size
    fn = dp.get_agg_kernel(mesh, nk=1, nv=1, capacity=1024, cpeer=2048)
    n = 128 * ndev
    args = (
        jnp.zeros((1, n), jnp.int64), jnp.zeros((1, n), jnp.int64),
        jnp.zeros((1, n), jnp.float64), jnp.ones((1, n), bool),
        jnp.ones((n,), bool),
    )
    hlo = fn.lower(*args).compile().as_text()
    assert "all-to-all" in hlo, "aggregate kernel must shuffle via all_to_all"
    assert "all-gather" not in hlo, "no implicit full gather in the agg kernel"


@needs_mesh
def test_join_kernel_hlo_has_all_to_all(mesh):
    from dask_sql_tpu.parallel import dist_plan as dp

    ndev = mesh.devices.size
    fn = dp.get_join_kernel(mesh, cpeer=2048, out_cap=2048)
    n = 128 * ndev
    a = jnp.zeros((n,), jnp.int64)
    b = jnp.ones((n,), bool)
    hlo = fn.lower(a, a, b, a, a, b).compile().as_text()
    assert "all-to-all" in hlo
    assert "all-gather" not in hlo


@needs_mesh
def test_agg_kernel_output_is_sharded(mesh):
    """Per-device outputs are [1/ndev] shards: no device holds the world."""
    from dask_sql_tpu.parallel import dist_plan as dp

    ndev = mesh.devices.size
    cap = 1024
    fn = dp.get_agg_kernel(mesh, nk=1, nv=1, capacity=cap, cpeer=2048)
    n = 128 * ndev
    rng = np.random.RandomState(0)
    keys = jnp.asarray(rng.randint(0, 64, n).astype(np.int64))[None]
    vals = jnp.asarray(rng.rand(n))[None]
    out = fn(keys, keys, vals, jnp.ones((1, n), bool), jnp.ones((n,), bool))
    fk = out[0]
    assert fk.shape == (ndev, 1, cap)
    for shard in fk.addressable_shards:
        assert shard.data.shape == (1, 1, cap)  # 1/ndev of the global rows


# ---------------------------------------------------------------------------
# mechanism: SQL routes through the kernels
# ---------------------------------------------------------------------------
@pytest.fixture
def dist_ctx():
    from dask_sql_tpu import Context

    if len(jax.devices()) < 2:
        pytest.skip("needs mesh")
    rng = np.random.RandomState(3)
    n = 4000
    df = pd.DataFrame({
        "g": rng.choice(["a", "b", "c", None], n),
        "k": rng.randint(0, 150, n).astype(np.int64),
        "h": rng.randint(0, 8, n).astype(np.int64),
        "x": rng.randint(-50, 50, n).astype(np.int64),
        "y": rng.rand(n) * 100,
    })
    df.loc[rng.rand(n) < 0.1, "x"] = None
    dim = pd.DataFrame({
        "k": np.arange(0, 180, dtype=np.int64),
        "w": rng.rand(180),
        "lbl": [f"l{i % 7}" for i in range(180)],
    })
    c = Context()
    c.create_table("big", df, distributed=True)
    c.create_table("dim", dim, distributed=True)
    return c, df, dim


@needs_mesh
def test_sql_groupby_routes_spmd_compiled(dist_ctx):
    """Round 5: the no-join sharded groupby runs the whole-jit SPMD
    aggregate (filter/masks deferred, GSPMD collectives) — the eager
    partial->final kernel must NOT be needed for it, but still serves
    compiled-ineligible shapes (DISTINCT aggregates)."""
    from dask_sql_tpu.parallel import dist_plan as dp

    c, df, _ = dist_ctx
    before = dp.STATS["agg_kernel"]
    result = c.sql(
        "SELECT g, h, COUNT(*) AS n, SUM(x) AS sx, AVG(y) AS ay, "
        "MIN(y) AS mny, MAX(x) AS mxx, STDDEV(y) AS sy "
        "FROM big GROUP BY g, h").compute()
    assert dp.STATS["agg_kernel"] == before, (
        "plain sharded groupby must take the compiled SPMD aggregate")
    expected = (df.groupby(["g", "h"], dropna=False)
                .agg(n=("x", "size"), sx=("x", "sum"), ay=("y", "mean"),
                     mny=("y", "min"), mxx=("x", "max"), sy=("y", "std"))
                .reset_index())
    assert_eq(result, expected, check_dtype=False, sort_results=True)

    # a float group key defeats the compiled path's radix plan: those
    # shapes still route through the partial->final dist kernel
    before = dp.STATS["agg_kernel"]
    fk = c.sql("SELECT y, COUNT(*) AS n FROM big GROUP BY y").compute()
    assert dp.STATS["agg_kernel"] > before, (
        "float-key groupby still routes through the dist kernel")
    exp_f = df.groupby("y", dropna=False).size().reset_index(name="n")
    assert_eq(fk, exp_f, check_dtype=False, sort_results=True)

    # DISTINCT aggregates decline both compiled and dist kernels and fall
    # back to the single-program path — values must still be exact
    distinct = c.sql("SELECT g, COUNT(DISTINCT k) AS n FROM big "
                     "GROUP BY g").compute()
    exp_d = df.groupby("g", dropna=False).k.nunique().reset_index(name="n")
    assert_eq(distinct, exp_d, check_dtype=False, sort_results=True)


@needs_mesh
def test_sql_join_routes_through_join_kernel(dist_ctx):
    """Round 4: a small dim side takes the broadcast path by default; the
    all_to_all shuffle kernel remains the route when broadcast is off."""
    from dask_sql_tpu.parallel import dist_plan as dp

    c, df, dim = dist_ctx
    m = df[df.y > 50].merge(dim, on="k")
    expected = m[["k", "y", "w"]]

    before_bc = dp.STATS["broadcast_join"]
    result = c.sql(
        "SELECT big.k, big.y, dim.w FROM big JOIN dim ON big.k = dim.k "
        "WHERE big.y > 50").compute()
    assert dp.STATS["broadcast_join"] > before_bc, (
        "small-dim sharded join must take the broadcast path")
    assert_eq(result, expected, check_dtype=False, sort_results=True)

    before_jk = dp.STATS["join_kernel"]
    result = c.sql(
        "SELECT big.k, big.y, dim.w FROM big JOIN dim ON big.k = dim.k "
        "WHERE big.y > 50",
        config_options={"sql.join.broadcast": False}).compute()
    assert dp.STATS["join_kernel"] > before_jk, (
        "shuffle kernel must run when broadcast is disabled")
    assert_eq(result, expected, check_dtype=False, sort_results=True)


@needs_mesh
def test_sql_left_join_distributed(dist_ctx):
    c, df, dim = dist_ctx
    result = c.sql(
        "SELECT dim.k, big.x FROM dim LEFT JOIN big ON dim.k = big.k").compute()
    expected = dim.merge(df, on="k", how="left")[["k", "x"]]
    assert_eq(result, expected, check_dtype=False, sort_results=True)


@needs_mesh
def test_sql_semi_anti_distributed(dist_ctx):
    c, df, dim = dist_ctx
    result = c.sql(
        "SELECT k FROM dim WHERE EXISTS (SELECT 1 FROM big WHERE big.k = dim.k)"
    ).compute()
    expected = dim[dim.k.isin(df.k)][["k"]]
    assert_eq(result, expected, check_dtype=False, sort_results=True)
    result2 = c.sql(
        "SELECT k FROM dim WHERE NOT EXISTS (SELECT 1 FROM big WHERE big.k = dim.k)"
    ).compute()
    expected2 = dim[~dim.k.isin(df.k)][["k"]]
    assert_eq(result2, expected2, check_dtype=False, sort_results=True)


@needs_mesh
def test_broadcast_knob_skips_shuffle(dist_ctx):
    """sql.join.broadcast=True keeps the replicated small side un-shuffled."""
    from dask_sql_tpu.parallel import dist_plan as dp

    c, df, dim = dist_ctx
    before = dp.STATS["join_kernel"]
    result = c.sql(
        "SELECT big.k, dim.w FROM big JOIN dim ON big.k = dim.k",
        config_options={"sql.join.broadcast": True}).compute()
    assert dp.STATS["join_kernel"] == before, "broadcast join must not shuffle"
    expected = df.merge(dim, on="k")[["k", "w"]]
    assert_eq(result, expected, check_dtype=False, sort_results=True)


@needs_mesh
def test_distinct_count_falls_back_correctly(dist_ctx):
    """Non-decomposable aggregates fall back but stay correct."""
    c, df, _ = dist_ctx
    result = c.sql(
        "SELECT g, COUNT(DISTINCT h) AS dh FROM big GROUP BY g").compute()
    expected = (df.groupby("g", dropna=False).h.nunique()
                .reset_index().rename(columns={"h": "dh"}))
    assert_eq(result, expected, check_dtype=False, sort_results=True)


# ---------------------------------------------------------------------------
# kernel-level: capacity ladder + negative/NULL keys
# ---------------------------------------------------------------------------
@needs_mesh
def test_dist_pairs_capacity_retry(mesh):
    """Skewed keys overflow the first capacity rung; the ladder retries."""
    from dask_sql_tpu.parallel import dist_plan as dp

    rng = np.random.RandomState(1)
    n = 6000
    lg = jnp.asarray(np.zeros(n, dtype=np.int64))  # all one key: max skew
    rg = jnp.asarray(np.zeros(20, dtype=np.int64))
    ones_l = jnp.ones(n, bool)
    li, ri, lm = dp.dist_inner_pairs(mesh, lg, ones_l, rg, jnp.ones(20, bool))
    assert int(li.shape[0]) == n * 20
    assert lm.all()
