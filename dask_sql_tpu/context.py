"""The Context: the main user-facing object of the framework.

Role parity: reference `Context` (context.py:51 there) — create_table
(context.py:168), sql (context.py:482), explain (context.py:535),
register_function (context.py:324), register_aggregation (context.py:415),
register_model (context.py:626), schema DDL (context.py:580-613), run_server
(context.py:704), ipython magic (context.py:651), plus the per-query catalog
sync of _prepare_schemas (context.py:749-817) and plan driving of _get_ral
(context.py:819) / _compute_table_from_rel (context.py:874).

TPU-native differences: tables live in device HBM as columnar Tables
(`backend='tpu'`, with a CPU/pandas ingest path preserved); the planner is
in-process (planner/) instead of a PyO3 Rust module; execution lowers to
jax/XLA kernels through the physical plugin registries.
"""
from __future__ import annotations

import contextlib
import logging
import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

import numpy as np

from . import config as config_module
from . import observability
from .runtime import locks as runtime_locks
from .columnar.dtypes import SqlType, np_to_sql
from .columnar.table import Table
from .datacontainer import (
    ColumnContainer,
    DataContainer,
    FunctionDescription,
    SchemaContainer,
    Statistics,
)
from .input_utils import InputUtil
from .planner.binder import Binder, BindError
from .planner.catalog import Catalog, CatalogSchema, CatalogTable
from .planner.expressions import Field
from .planner.parser import ParsingException, parse_sql
from .planner import plan as plan_nodes

logger = logging.getLogger(__name__)


class TpuFrame:
    """Lazy query result: holds the optimized plan; executes on `.compute()`.

    Parity: the lazy dask DataFrame the reference returns from Context.sql
    (return_futures=True default, context.py:508).
    """

    def __init__(self, context: "Context", plan, field_names: List[str],
                 config_options: Optional[Dict[str, Any]] = None):
        self._context = context
        self._plan = plan
        self._field_names = field_names
        self._result: Optional[Table] = None
        #: per-query overrides re-applied at execution time (lazy compute
        #: happens after Context.sql's config scope has exited)
        self._config_options = dict(config_options or {})
        #: the lifecycle QueryTrace active when this frame was planned
        #: (observability/spans.py) — lazy execute/compute re-activate it so
        #: plan-time and run-time spans land on ONE trace
        self._trace: Optional[observability.QueryTrace] = None
        #: the FULL statement text (the trace's copy is display-truncated);
        #: recorded into the per-fingerprint profile so the pre-warm pass
        #: can replay it verbatim after a restart
        self._sql: Optional[str] = None
        #: cached plan fingerprint (resilience/ladder.py plan_fingerprint)
        self._fingerprint: Optional[str] = None

    @property
    def plan(self):
        return self._plan

    @property
    def columns(self) -> List[str]:
        return list(self._field_names)

    def execute(self) -> Table:
        """Run the plan to a device Table (cached).

        Serving integration: before executing, the context's result cache is
        consulted under a key of (plan fingerprint, parameter vector,
        per-referenced-table versions, config) — a repeated identical query
        returns the materialized Table without touching the executor; any
        DDL/DML on a referenced table changes the key (uid / delta-epoch
        versioning), so stale results can never be served.  On an exact
        miss the semantic reuse tiers (materialize/) get a shot: an
        incrementally-maintained aggregate state or a provably-subsuming
        cached sibling serves without executing, and a plan whose
        scan->filter stem is pinned executes against the materialized stem
        instead of the base table."""
        if self._result is None:
            from .physical.executor import Executor
            from .resilience.ladder import plan_fingerprint, wrap_boundary

            ctx = self._context
            tr = self._trace
            fp = self._fingerprint
            # family identity (families/): when the plan parameterized, its
            # literal-stripped family fingerprint keys the breaker, the
            # profiles and the warm-up — `user_id = 17` and `user_id = 404`
            # are one serving entity
            family = getattr(self._plan, "_dsql_family", None)
            family_fp = family.fingerprint if family is not None else None
            if fp is None:
                fp = self._fingerprint = family_fp or plan_fingerprint(
                    self._plan)
            sql_text = self._sql or (tr.sql if tr is not None else None)

            def _finish_on_error(exc_type, exc, tb):
                # a failing query's lifecycle ends HERE — the slowest, most
                # log-worthy queries (deadline expiries, OOM sheds, executor
                # failures) must reach the slow-query check too
                if exc is None or tr is None:
                    return False
                from .serving.runtime import current_ticket

                if getattr(exc, "retryable", False) \
                        and current_ticket() is not None:
                    # a serving worker may retry this attempt: leave the
                    # trace open — the registry's terminal done-callback
                    # finishes it on the FINAL outcome, not attempt 1
                    return False
                tr.finish(ctx.config, ctx.metrics)
                return False

            with contextlib.ExitStack() as stack:
                # entered before the finish hook is pushed, so the hook
                # (LIFO) still sees this query's config overrides — a
                # per-query slow_query_ms must gate its own failures
                stack.enter_context(ctx.config.set(self._config_options))
                stack.push(_finish_on_error)
                if tr is not None:
                    if observability.current_trace() is not tr:
                        # lazy compute outside Context.sql's scope (or on a
                        # different thread): re-install the plan-time trace
                        stack.enter_context(observability.activate(tr))
                    tr.fingerprint = fp
                # compile histograms + per-fingerprint profiles record
                # through the sink even with tracing disabled
                stack.enter_context(observability.compile_sink(
                    ctx.metrics, ctx.profiles, fp, sql_text,
                    family=family_fp))
                # in-flight query table (observability/live.py): the server
                # registered an entry at submit (found via the serving
                # ticket); a direct Context-API execution registers its own
                # here — WITH a cancellable ticket installed for the
                # executor's checkpoints, so CANCEL QUERY reaches it too
                from .serving.runtime import current_ticket, ticket_scope

                live_ticket = current_ticket()
                entry = None
                if live_ticket is not None:
                    entry = ctx.live_queries.get(live_ticket.qid)
                owned_entry = entry is None
                if owned_entry:
                    live_qid = tr.qid if tr is not None else None
                    if live_ticket is None:
                        from .serving.admission import QueryTicket
                        import uuid as _uuid

                        live_qid = live_qid or _uuid.uuid4().hex[:16]
                        live_ticket = QueryTicket(live_qid)
                        stack.enter_context(ticket_scope(live_ticket))
                    # dsql: allow-unpaired-effect — _finish_live ExitStack
                    entry = ctx.live_queries.begin(
                        live_qid or live_ticket.qid, sql=sql_text,
                        ticket=live_ticket, trace=tr,
                        priority_class=live_ticket.priority_class)
                ctx.live_queries.start(entry.qid)
                entry.family = family_fp
                entry.fingerprint = fp
                stack.enter_context(observability.live.activate(entry))

                def _finish_live(exc_type, exc, tb):
                    if exc is None:
                        if owned_entry:
                            ctx.live_queries.finish(entry.qid, "done")
                        return False
                    if not owned_entry:
                        # the server registry owns the terminal outcome
                        # (this attempt may be retried by the worker)
                        return False
                    from .serving.admission import QueryCancelledError

                    code = getattr(exc, "code", None) or exc_type.__name__
                    state = "cancelled" if isinstance(
                        exc, QueryCancelledError) else "failed"
                    ctx.live_queries.finish(entry.qid, state, code)
                    if state == "failed":
                        # cancels are user-initiated, not failures: they
                        # already recorded query.cancel at the request
                        # site and must not dump a failure postmortem
                        observability.flight.flush_on_failure(
                            entry.qid, code, ctx.config, ctx.metrics)
                    return False

                # pushed AFTER the trace hook so it runs first on unwind
                # (the live table should be terminal before the slow-query
                # check reads the trace)
                stack.push(_finish_live)
                with observability.stage("cache_lookup"):
                    key = ctx._result_cache_key(self._plan,
                                                self._config_options)
                    hit = ctx._result_cache.get(key) if key is not None \
                        else None
                if hit is not None:
                    if tr is not None:
                        tr.event("result_cache_hit")
                    ctx.profiles.record_exec(fp, sql=sql_text,
                                             cache_hit=True,
                                             family=family_fp)
                    self._result = hit
                    return self._result
                # semantic reuse tiers (materialize/): an incremental
                # aggregate state or a PROVABLY-subsuming cached sibling
                # answers the query without compiling or scanning anything
                reuse = ctx.materialize.try_reuse(self._plan, family, key)
                if reuse is not None:
                    served, tier = reuse
                    if tr is not None:
                        tr.event(f"semantic_reuse:{tier}")
                    ctx.profiles.record_exec(fp, sql=sql_text,
                                             cache_hit=True,
                                             family=family_fp)
                    if key is not None:
                        # promote to tier 0: an exact repeat of THIS query
                        # now hits the result cache directly
                        ctx._result_cache.put(
                            key, served,
                            deps=ctx._plan_table_deps(self._plan))
                    self._result = served
                    return self._result
                estimate = ctx._plan_estimate(self._plan)
                routed = None
                if estimate is not None:
                    # pre-compile OOM gate: a provable over-budget query is
                    # shed HERE — before the executor compiles anything —
                    # with a structured, non-retryable taxonomy error.
                    # Oversize-but-partitionable plans are routed to the
                    # streaming rungs instead (streaming/): shedding is the
                    # last resort, not the first.
                    from .serving.admission import check_estimated_bytes

                    routed = check_estimated_bytes(
                        estimate, ctx.config, ctx.metrics,
                        plan=self._plan, context=ctx)
                    # result-cache admission: a result whose PROVABLE bytes
                    # already exceed the per-entry cap is never cacheable;
                    # skip the insert instead of materializing-then-evicting
                    if key is not None and estimate.result_bytes.lo > \
                            ctx._result_cache.max_entry_bytes:
                        ctx.metrics.inc("query.cache.estimate_skip")
                        key = None
                trace = bool(ctx.config.get("serving.metrics.node_traces",
                                            False))
                executor = Executor(ctx, trace=trace)
                if routed is not None:
                    # per-EXECUTION streaming verdict: keyed by the
                    # streamable node's identity on THIS executor, so a
                    # concurrent execution of the same cached plan under a
                    # different budget cannot null it mid-flight
                    node, decision = routed
                    executor.stream_decisions[id(node)] = decision
                exec_plan = self._plan
                if routed is None:
                    # sub-plan materialization (materialize/manager.py):
                    # when this plan's scan->filter stem is pinned, execute
                    # a rewritten copy that scans the materialized stem —
                    # the base table is never touched and nothing compiles.
                    # Streamed executions keep the original plan: their
                    # routing decision is keyed on ITS node identity.
                    rewritten = ctx.materialize.try_stem_rewrite(self._plan)
                    if rewritten is not None:
                        exec_plan, stem_overrides = rewritten
                        executor.table_overrides.update(stem_overrides)
                        if tr is not None:
                            tr.event("materialized_stem_scan")
                t0 = time.perf_counter()
                # executor boundary: every failure leaves here as a taxonomy
                # QueryError (code/retryable/degradable), never a raw
                # device traceback (resilience/errors.py)
                with observability.stage("execute"):
                    self._result = wrap_boundary(
                        lambda: executor.execute_root(exec_plan))
                exec_ms = (time.perf_counter() - t0) * 1000.0
                ctx.metrics.observe("query.execute_ms", exec_ms)
                ctx.metrics.inc("query.executed")
                if trace:
                    executor.tracer.publish(ctx.metrics)
                    if tr is not None:
                        tr.attach_node_tree(executor.tracer.root)
                from .serving.cache import table_nbytes

                result_bytes = table_nbytes(self._result)
                ctx.profiles.record_exec(
                    fp, sql=sql_text, exec_ms=exec_ms,
                    result_bytes=result_bytes,
                    family=family_fp,
                    rows=self._result.num_rows)
                from .serving.runtime import current_ticket

                ticket = current_ticket()
                if ticket is not None:
                    # measured footprint for the packing scheduler's
                    # reservation reconciliation (release surfaces the
                    # drift as serving.scheduler.reserve_drift): result
                    # bytes + the MEASURED resident bytes of the scanned
                    # tables — table_nbytes accounting on both sides, so
                    # reserve-vs-measured comparisons cannot drift
                    ticket.measured_bytes = result_bytes \
                        + ctx._measured_scan_bytes(
                            self._plan,
                            routed[1] if routed is not None else None)
                    # the ledger's measured-vs-reserved reconciliation
                    # reads the same number off the live entry
                    entry.measured_bytes = ticket.measured_bytes
                est = getattr(self._plan, "_dsql_estimate", None)
                if est is not None:
                    # the "estimated" side of SHOW PROFILES' observed-vs-
                    # estimated pairing, recorded HERE because the entry
                    # now exists (record_estimate never creates entries)
                    ctx.profiles.record_estimate(fp, est.rows.hi,
                                                 family=family_fp)
                deps = ctx._plan_table_deps(self._plan)
                if key is not None:
                    # deps-tagged: append_rows/DDL invalidate exactly the
                    # entries reading the mutated tables (epoch-scoped)
                    ctx._result_cache.put(key, self._result, deps=deps)
                # semantic reuse observation (materialize/): stem hit
                # counting (pin at threshold), subsumption candidate
                # registration, incremental capture registration
                ctx.materialize.observe(self._plan, family, key, deps,
                                        self._result)
        return self._result

    def compute(self):
        """Materialize to a pandas DataFrame with the SQL output names."""
        table = self.execute()
        t0 = time.perf_counter()
        df = table.to_pandas()
        t1 = time.perf_counter()
        # every call transfers again, so every call observes — but the
        # metric must not go dark when tracing is off
        self._context.metrics.observe("query.d2h_ms", (t1 - t0) * 1e3)
        tr = self._trace
        if tr is not None:
            # add-once: a repeated compute() must not mutate a finished
            # (possibly already slow-logged) trace with duplicate stages
            tr.add_span_once("d2h", t0, t1, rows=table.num_rows,
                             cols=len(self._field_names))
            # the lifecycle ends here for the Context API (the server path
            # appends its serialize span post-finish): run the slow-query
            # check exactly once
            tr.finish(self._context.config, self._context.metrics)
        df.columns = self._disambiguated_names()
        return df

    def _disambiguated_names(self) -> List[str]:
        # parity: reference renames duplicate output fields with FQN hints
        # (context.py:890-906); we suffix duplicates positionally
        seen: Dict[str, int] = {}
        out = []
        for n in self._field_names:
            if n in seen:
                seen[n] += 1
                out.append(f"{n}{seen[n]}")
            else:
                seen[n] = 0
                out.append(n)
        return out

    def persist(self) -> "TpuFrame":
        self.execute()
        return self

    def head(self, n: int = 5):
        return self.compute().head(n)

    def __len__(self) -> int:
        return self.execute().num_rows

    def explain_str(self) -> str:
        return self._plan.explain()


class Context:
    DEFAULT_SCHEMA_NAME = "root"

    def __init__(self, logging_level=logging.INFO):
        # join the multi-host runtime if DSQL_COORDINATOR is set (parity:
        # the reference front-ends connecting a Client to the scheduler
        # address, reference server/app.py:249-252); no-op single-host
        from .parallel.bootstrap import initialize_from_env

        initialize_from_env()
        self.schema_name = self.DEFAULT_SCHEMA_NAME
        self.schema: Dict[str, SchemaContainer] = {
            self.DEFAULT_SCHEMA_NAME: SchemaContainer(self.DEFAULT_SCHEMA_NAME)
        }
        self._views: Dict[str, Dict[str, Any]] = {self.DEFAULT_SCHEMA_NAME: {}}
        self.config = config_module.config
        self.server = None
        #: bound+optimized plans for repeated SQL text (keyed on the catalog
        #: signature, so any table/view/function/config change re-plans)
        self._plan_cache: "OrderedDict[Tuple, List[Any]]" = OrderedDict()
        #: guards _plan_cache and _catalog_buf_cache: one Context serves
        #: every worker thread of the Presto server, and an unguarded
        #: OrderedDict move_to_end/popitem pair racing across threads
        #: corrupts the LRU order or KeyErrors (self-lint rule DSQL201).
        #: rank 55: nests inside replica write locks; planning/compiles
        #: happen OUTSIDE it (singleflight in physical/compiled.py)
        self._plan_lock = runtime_locks.named_lock("context.plan_cache")
        #: bumped on every view/function (re)definition or drop
        self._catalog_serial = 0
        from .serving.cache import ResultCache
        from .serving.metrics import MetricsRegistry

        #: serving metrics registry: query/cache/executor counters and
        #: latency histograms (SHOW METRICS, server /v1/metrics)
        self.metrics = MetricsRegistry()
        # arm the process-wide lock sanitizer when this context's config
        # asks for it (arming is one-way: a later default-config Context
        # must not disarm a suite that opted in), and point its
        # violation counters at this registry
        if self.config.get("analysis.lock_sanitizer", False):
            runtime_locks.set_enabled(True)
        runtime_locks.attach_metrics(self.metrics)
        #: materialized-result cache (serving/cache.py); keyed via
        #: _result_cache_key so DDL/DML versions entries out
        self._result_cache = ResultCache(
            max_bytes=int(self.config.get("serving.cache.max_bytes",
                                          256 << 20)),
            max_entry_bytes=int(self.config.get(
                "serving.cache.max_entry_bytes", 64 << 20)),
            ttl_s=self.config.get("serving.cache.ttl_s", 300.0),
            metrics=self.metrics)
        #: the ServingRuntime when a server front-end attached one (so
        #: SHOW METRICS can surface admission/queue state)
        self.serving = None
        #: per-fingerprint rolling profiles (compile/exec/bytes) behind
        #: SHOW PROFILES; persisted by checkpoint.save_state
        self.profiles = observability.ProfileStore(
            window=int(self.config.get("observability.profiles.window", 64)),
            keep=int(self.config.get("observability.profiles.keep", 512)))
        #: finished lifecycle traces, qid -> QueryTrace (/v1/trace/{qid})
        self.traces = observability.TraceStore(
            int(self.config.get("observability.trace.keep", 256)))
        #: the in-flight query table (observability/live.py) behind
        #: SHOW QUERIES / GET /v1/queries and the target of CANCEL QUERY
        self.live_queries = observability.QueryRegistry(
            keep_finished=int(self.config.get("observability.live.keep",
                                              64)))
        #: live HBM accounting (observability/ledger.py): scheduler
        #: reservations + measured in-flight footprints + result-cache +
        #: at-rest table bytes reconciled against the device budget
        self.ledger = observability.DeviceLedger(self)
        from .resilience.pressure import PressureController

        #: coordinated HBM pressure response (resilience/pressure.py):
        #: bands the ledger's headroom against the device budget, suspends
        #: speculative work at YELLOW, reclaims cross-tier at RED, forces
        #: streamed admission / sheds at CRITICAL
        self.pressure = PressureController(self)
        #: per-(schema, table) delta epoch: bumped by append_rows (and any
        #: create/drop of the name) WITHOUT replacing the container — the
        #: result-cache key and the semantic reuse tiers (materialize/)
        #: version on it, so an append invalidates exactly its dependents
        self._table_epochs: Dict[Tuple[str, str], int] = {}
        from .materialize import MaterializationManager

        #: semantic result reuse (materialize/): pinned sub-plan stems,
        #: subsumption answering over cached results, incremental
        #: maintenance of aggregate states across append_rows
        self.materialize = MaterializationManager(self)
        # the process flight recorder is always on; the capacity key only
        # resizes its ring
        observability.flight.RECORDER.resize(
            int(self.config.get("observability.flight.capacity", 4096)))
        #: the most recently started lifecycle trace (bench --profile and
        #: notebook introspection; per-query lookups go through `traces`)
        self.last_trace: Optional[observability.QueryTrace] = None
        from .resilience.retry import CircuitBreaker

        #: per-(plan fingerprint, ladder rung) circuit breaker: a query
        #: shape that repeatedly kills a compiled rung skips straight to
        #: its known-good rung (resilience/ladder.py consults this)
        self.breaker = CircuitBreaker.from_config(self.config)
        #: the active warm-up pass (serving/warmup.py) after load_state /
        #: server boot; /v1/health reports its warming->ready transition
        self.warmup = None
        #: lazily-created background recompiler (serving/background.py);
        #: guarded by _plan_lock — use background_compiler() to read
        self._bg_compiler = None
        #: plan family ((rung tag, key-minus-bucket) tuple) -> table bucket
        #: (uid, rows, padded_rows) last compiled by THIS context: a
        #: plugin-cache miss whose family maps to a DIFFERENT bucket means
        #: the table grew/was replaced — the background-recompile trigger
        #: (physical/compiled.py).  Guarded by _plan_lock.
        self._compiled_families: dict = {}
        #: (family fingerprint, catalog/config key) -> PlanEstimate: the
        #: estimator's intervals are literal-value-agnostic, so one
        #: estimate serves every member of a family (families/,
        #: docs/analysis.md).  Guarded by _plan_lock; cleared on DDL.
        self._family_estimates: dict = {}
        from .serving import compile_cache

        # persistent executable cache: when serving.compile_cache.path is
        # set, XLA executables survive the process (restart = deserialize,
        # not recompile; docs/serving.md "Cold starts")
        compile_cache.maybe_enable(self.config, self.metrics)
        logging.basicConfig(level=logging_level)

    _PLAN_CACHE_CAP = 128

    def _plan_cache_key(self, sql: str, config_options) -> Optional[Tuple]:
        """Cache key for a SQL text against the current catalog state, or
        None when the statement must be re-planned every time (plan-time
        data reads: DPP runs the dim side during optimization, so its
        inputs are pinned by the table uids in the signature)."""
        try:
            parts: List[Any] = [sql, self.schema_name]
            parts.extend(self._catalog_signature())
            # id()-free: view/function redefinitions bump _catalog_serial
            # (id reuse after a drop would silently replay a stale plan)
            parts.append(self._catalog_serial)
            parts.append(self.config.effective_items())
            if config_options:
                parts.append(tuple(sorted(config_options.items())))
            key = tuple(parts)
            hash(key)  # unhashable config values -> skip caching
            return key
        except TypeError:
            return None

    def _catalog_signature(self) -> List[Any]:
        """Versioned identity of the catalog: table uids, statistics row
        counts, view and function names per schema.  Shared by the plan
        cache and the result cache — any DDL/DML that replaces a table
        (fresh uid), redefines a view/function (`_catalog_serial` bump) or
        refreshes statistics changes the signature."""
        parts: List[Any] = []
        for schema_name in sorted(self.schema):
            container = self.schema[schema_name]
            parts.append(schema_name)
            parts.append(tuple(sorted(
                (name, dc.uid) for name, dc in container.tables.items())))
            stats = container.statistics
            parts.append(tuple(sorted(
                (name, s.row_count) for name, s in stats.items()
                if s is not None)))
            parts.append(tuple(sorted(self._views.get(schema_name, {}))))
            parts.append(tuple(sorted(container.function_lists)))
        return parts

    def table_epoch(self, schema_name: str, table_name: str) -> int:
        """The (schema, table) delta epoch — 0 until the first append or
        create/drop of the name.  Rides the result-cache key's per-table
        parts and the materialize/ validity checks."""
        return self._table_epochs.get((schema_name, table_name), 0)

    def _bump_table_epoch(self, schema_name: str, table_name: str) -> int:
        tkey = (schema_name, table_name)
        epoch = self._table_epochs.get(tkey, 0) + 1
        self._table_epochs[tkey] = epoch
        return epoch

    def _on_catalog_change(self, tables=None) -> None:
        """Called by every DDL-shaped mutation.  The result-cache keys
        embed per-referenced-table versions, so stale entries could never
        be *hit* — but unreachable entries would stay pinned in HBM until
        byte-pressure from new inserts; eager invalidation frees those
        buffers now.  With ``tables`` (a set of (schema, table) names) the
        invalidation is TARGETED: only cached results and materializations
        depending on those tables drop — results over other tables
        survive.  Without it (view/function/schema/model DDL, whose blast
        radius is not table-attributable) everything drops, as before."""
        if tables:
            n = self._result_cache.invalidate_tables(tables)
            n += self.materialize.invalidate_tables(tables)
            if n:
                self.metrics.inc("query.cache.invalidated", n)
        else:
            self._result_cache.invalidate_all()
            self.materialize.invalidate_all()
        with self._plan_lock:
            self._family_estimates.clear()

    def _result_cache_key(self, plan, config_options) -> Optional[Tuple]:
        """Result-cache key: (normalized plan fingerprint, parameter
        vector, per-referenced-table versions (uid, rows, delta epoch),
        config options) — or None when this result must not be cached
        (caching disabled, side-effecting/model statements, unhashable
        config).  Versioning only the REFERENCED tables (not the whole
        catalog signature) is what lets an append to one table leave every
        other table's cached results valid."""
        if not self.config.get("serving.cache.enabled", True):
            return None
        if isinstance(plan, plan_nodes.CustomNode):
            # DDL / ML statements: side effects or model-object state that
            # the catalog signature does not fully version
            return None
        if isinstance(plan, plan_nodes.Explain) and plan.analyze:
            # EXPLAIN ANALYZE must re-execute and re-profile every time —
            # serving a cached trace would report a run that never happened
            return None
        from .datacontainer import LazyParquetContainer

        table_parts: List[Tuple] = []
        stack = [plan]
        while stack:
            node = stack.pop()
            if isinstance(node, plan_nodes.Sample) and node.seed is None:
                # unseeded TABLESAMPLE draws fresh randomness per execution;
                # caching it would freeze the first draw for the TTL window
                return None
            if isinstance(node, plan_nodes.TableScan):
                dc = self.schema.get(node.schema_name, SchemaContainer(
                    node.schema_name)).tables.get(node.table_name)
                if isinstance(dc, LazyParquetContainer):
                    # file-backed scan: the files can change on disk without
                    # any catalog version bump, so the result is uncacheable
                    return None
                if dc is None:
                    view = self._views.get(node.schema_name, {}).get(
                        node.table_name)
                    if view is not None:
                        # the scan resolves through a view at execution
                        # time: the UNDERLYING tables must version this key
                        # (an append to one invalidates results over the
                        # view), so the view plan joins the walk
                        stack.append(view)
                # per-referenced-table version: identity (uid), size and
                # delta epoch — an append bumps the epoch, a replace the
                # uid, so exactly the dependent keys go stale while results
                # over OTHER tables keep their keys (and their entries)
                table_parts.append(
                    (node.schema_name, node.table_name,
                     None if dc is None else dc.uid,
                     None if dc is None else int(dc.table.num_rows),
                     self.table_epoch(node.schema_name, node.table_name)))
            # volatile calls (RAND / CURRENT_TIMESTAMP) and UDFs (arbitrary
            # host code) must re-evaluate per query; nested subquery plans
            # join the walk so nothing hides inside an expression
            nested, uncacheable = _scan_node_exprs(node)
            if uncacheable:
                return None
            stack.extend(nested)
            stack.extend(node.inputs())
        try:
            # repr() (not explain()) as the plan fingerprint: dataclass reprs
            # include every semantic field recursively, so two plans that
            # differ only in a detail the pretty-printer omits (e.g. sort
            # null ordering) can never collide.  With plan families enabled
            # the key splits into (literal-stripped family repr, parameter
            # values) — bijective with repr(plan), since substituting the
            # values back into the placeholder slots reconstructs it — so
            # family metrics and cache accounting see one family, while two
            # queries with different literals still get distinct entries.
            # INVARIANT: the parameter vector sits at index 2 in BOTH
            # shapes — subsumption answering (materialize/manager.py)
            # admits a candidate by comparing every part EXCEPT index 2.
            family = getattr(plan, "_dsql_family", None)
            if family is not None:
                parts: List[Any] = ["result", family.family_repr,
                                    family.key_values, self.schema_name]
            else:
                parts = ["result", repr(plan), (), self.schema_name]
            parts.extend(sorted(set(table_parts)))
            parts.append(self.config.effective_items())
            if config_options:
                parts.append(tuple(sorted(config_options.items())))
            key = tuple(parts)
            hash(key)
            return key
        except Exception:  # dsql: allow-broad-except — unhashable config /
            # unprintable plan just means this result is uncacheable
            return None

    def _plan_table_deps(self, plan) -> frozenset:
        """Every (schema, table) name a plan reads — nested subquery plans
        and view expansions included.  Tags result-cache entries and
        semantic-reuse state for targeted (epoch-scoped) invalidation."""
        deps = set()
        stack = [plan]
        while stack:
            node = stack.pop()
            if isinstance(node, plan_nodes.TableScan):
                deps.add((node.schema_name, node.table_name))
                if node.table_name not in self.schema.get(
                        node.schema_name,
                        SchemaContainer(node.schema_name)).tables:
                    view = self._views.get(node.schema_name, {}).get(
                        node.table_name)
                    if view is not None:
                        stack.append(view)
            nested, _ = _scan_node_exprs(node)
            stack.extend(nested)
            stack.extend(node.inputs())
        return frozenset(deps)

    # ------------------------------------------------------------ tables
    def create_table(
        self,
        table_name: str,
        input_table: Any,
        format: Optional[str] = None,
        persist: bool = False,
        schema_name: Optional[str] = None,
        statistics: Optional[Statistics] = None,
        backend: Optional[str] = None,
        gpu: bool = False,
        distributed: Optional[bool] = None,
        **kwargs,
    ) -> None:
        """Register a table (parity: context.py:168).  `backend='tpu'`
        (default) lands columns in device HBM; the reference's `gpu=` flag is
        accepted and treated as a backend hint.  `distributed=True` shards the
        column buffers row-wise over the default device mesh so kernels run
        SPMD with XLA-placed collectives; an EXPLICIT `distributed=False`
        also opts this table out of the `parallel.auto_shard` policy (None,
        the default, leaves the policy in charge)."""
        schema_name = schema_name or self.schema_name
        if schema_name not in self.schema:
            raise KeyError(f"Schema {schema_name} not found")
        dc = InputUtil.to_dc(input_table, table_name, format=format,
                             persist=persist, **kwargs)
        # normalize: the CREATE TABLE ... WITH (distributed=...) passthrough
        # delivers SQL literals, and a string 'false' must not shard
        from .spmd.storage import maybe_auto_shard, truthy_option

        if truthy_option(distributed):
            from .datacontainer import LazyParquetContainer
            from .parallel.distribute import shard_table

            if isinstance(dc, LazyParquetContainer):
                from .datacontainer import DataContainer

                dc = DataContainer(shard_table(dc.table))
            else:
                dc.table = shard_table(dc.table)
        elif distributed is None:
            # parallel.auto_shard policy (spmd/storage.py): eligible
            # registrations row-shard over the default mesh without
            # per-table opt-in, so the SPMD rungs serve plain create_table.
            # An EXPLICIT distributed=False (or WITH (distributed='false'))
            # is a per-table opt-out the policy must respect.
            dc = maybe_auto_shard(dc, self.config, self.metrics)
        self.schema[schema_name].tables[table_name] = dc
        from .datacontainer import LazyParquetContainer

        if statistics is None:
            if isinstance(dc, LazyParquetContainer):
                # footer row counts, no data scan (parity: context.py:281-289)
                if dc.statistics and dc.statistics.get("num-rows"):
                    statistics = Statistics(float(dc.statistics["num-rows"]))
            elif dc.table.num_rows:
                statistics = Statistics(float(dc.table.num_rows))
        if statistics is not None:
            self.schema[schema_name].statistics[table_name] = statistics
        filepath = getattr(dc, "filepath", None)
        if filepath:
            self.schema[schema_name].filepaths[table_name] = filepath
        # LazyParquetContainer.table is a LOADING property — peeking it here
        # would defeat lazy registration; lazy scans are PLAIN anyway
        table = None if isinstance(dc, LazyParquetContainer) \
            else getattr(dc, "table", None)
        if table is not None and table.has_encoded_columns():
            # compressed-encoding accounting (columnar/encodings.py):
            # encoded vs would-be-dense resident bytes of this registration
            from .columnar.encodings import Encoding, scan_bytes

            n_enc = sum(1 for c in table.columns.values()
                        if c.encoding is not Encoding.PLAIN)
            enc_b, dec_b = scan_bytes(table)
            self.metrics.inc("columnar.encoding.encoded_columns", n_enc)
            self.metrics.observe("columnar.encoding.encoded_bytes", enc_b)
            self.metrics.observe("columnar.encoding.decoded_bytes", dec_b)
        self._bump_table_epoch(schema_name, table_name)
        if self._views.setdefault(schema_name, {}).pop(table_name, None) is not None:
            # replacing a VIEW with a table: results over OTHER views may
            # reference this name through their plans — full invalidation
            self._catalog_serial += 1
            self._on_catalog_change()
        else:
            self._on_catalog_change(tables={(schema_name, table_name)})

    def drop_table(self, table_name: str, schema_name: Optional[str] = None) -> None:
        schema_name = schema_name or self.schema_name
        self.schema[schema_name].tables.pop(table_name, None)
        self.schema[schema_name].statistics.pop(table_name, None)
        self._bump_table_epoch(schema_name, table_name)
        if self._views.get(schema_name, {}).pop(table_name, None) is not None:
            self._catalog_serial += 1
            self._on_catalog_change()
        else:
            self._on_catalog_change(tables={(schema_name, table_name)})

    def alter_table(self, old_name: str, new_name: str,
                    schema_name: Optional[str] = None) -> None:
        schema_name = schema_name or self.schema_name
        tables = self.schema[schema_name].tables
        if old_name in tables:
            tables[new_name] = tables.pop(old_name)
        stats = self.schema[schema_name].statistics
        if old_name in stats:
            stats[new_name] = stats.pop(old_name)
        self._bump_table_epoch(schema_name, old_name)
        self._bump_table_epoch(schema_name, new_name)
        self._on_catalog_change(tables={(schema_name, old_name),
                                        (schema_name, new_name)})

    def append_rows(self, table_name: str, rows: Any,
                    schema_name: Optional[str] = None) -> int:
        """Append rows to a registered table IN PLACE — the engine behind
        ``INSERT INTO``.  Unlike create_table (replace), the container and
        its uid survive: only the per-table *delta epoch* bumps, so the
        result cache drops exactly the entries depending on this table
        (epoch-scoped keys) while results over other tables stay servable,
        and the semantic reuse tiers (materialize/) fold ONLY the appended
        chunk — pinned stems re-execute over the delta slice, stored
        streamed-combine states absorb it as one more time-axis partition —
        without rescanning history.

        ``rows`` is anything `create_table` accepts (DataFrame, dict of
        arrays, list of tuples...) with a column subset compatible with the
        existing table.  Lazy parquet registrations and row-sharded tables
        cannot concat in place and degrade to a replace (fresh uid,
        wholesale invalidation for this table).  Returns the number of
        appended rows."""
        schema_name = schema_name or self.schema_name
        container = self.schema.get(schema_name)
        dc = container.tables.get(table_name) if container else None
        if dc is None:
            raise KeyError(f"Table {schema_name}.{table_name} not found")
        delta_dc = InputUtil.to_dc(rows, table_name)
        delta = delta_dc.table
        appended = int(delta.num_rows)
        self.metrics.inc("serving.reuse.append_rows", appended)
        from .datacontainer import DataContainer, LazyParquetContainer

        tkey = (schema_name, table_name)
        if isinstance(dc, LazyParquetContainer) \
                or dc.table.row_valid is not None:
            # no in-place concat story for file-backed or padded/sharded
            # storage: degrade to a replace — fresh uid, so every reuse
            # tier fails closed on its identity checks
            base = dc.table
            merged = Table.concat(
                [base.slice(0, base.num_rows), delta])
            container.tables[table_name] = DataContainer(merged)
            container.statistics[table_name] = Statistics(
                float(merged.num_rows))
            self._bump_table_epoch(schema_name, table_name)
            self._on_catalog_change(tables={tkey})
            return appended
        old_rows = int(dc.table.num_rows)
        # same container, same uid: concat decodes + promotes as needed,
        # and raises on an incompatible column set before any state changes
        dc.table = Table.concat([dc.table, delta])
        container.statistics[table_name] = Statistics(
            float(dc.table.num_rows))
        epoch = self._bump_table_epoch(schema_name, table_name)
        # targeted: exactly the cached results reading this table drop
        # (their keys embed the old epoch and can never be hit again);
        # reuse state REFRESHES instead of dropping — that is the point
        n = self._result_cache.invalidate_tables({tkey})
        if n:
            self.metrics.inc("query.cache.invalidated", n)
        with self._plan_lock:
            self._family_estimates.clear()
        self.materialize.on_append(schema_name, table_name, dc, old_rows,
                                   epoch)
        return appended

    # ------------------------------------------------------------ schemas
    def create_schema(self, schema_name: str) -> None:
        self.schema[schema_name] = SchemaContainer(schema_name)
        self._views.setdefault(schema_name, {})
        self._on_catalog_change()

    def drop_schema(self, schema_name: str) -> None:
        if schema_name == self.schema_name:
            self.schema_name = self.DEFAULT_SCHEMA_NAME
        self.schema.pop(schema_name, None)
        if self._views.pop(schema_name, None):
            self._catalog_serial += 1
        self._on_catalog_change()

    def alter_schema(self, old_name: str, new_name: str) -> None:
        if old_name in self.schema:
            container = self.schema.pop(old_name)
            container.name = new_name
            self.schema[new_name] = container
            self._views[new_name] = self._views.pop(old_name, {})
            if self.schema_name == old_name:
                self.schema_name = new_name
            self._on_catalog_change()

    # ------------------------------------------------------------ functions
    def register_function(
        self,
        f: Callable,
        name: str,
        parameters: List[Tuple[str, Any]],
        return_type: Any,
        replace: bool = False,
        schema_name: Optional[str] = None,
        row_udf: bool = False,
    ) -> None:
        """Scalar UDF registration (parity: context.py:324).  Non-row UDFs
        receive jax arrays and should be jax-traceable for fusion."""
        self._register_callable(f, name, parameters, return_type, False,
                                replace, schema_name, row_udf)

    def register_aggregation(
        self,
        f: Callable,
        name: str,
        parameters: List[Tuple[str, Any]],
        return_type: Any,
        replace: bool = False,
        schema_name: Optional[str] = None,
    ) -> None:
        """Custom aggregation (parity: context.py:415): `f` is applied to a
        pandas GroupBy on the host fallback path."""
        self._register_callable(f, name, parameters, return_type, True,
                                replace, schema_name, False)

    def _register_callable(self, f, name, parameters, return_type, aggregation,
                           replace, schema_name, row_udf):
        schema_name = schema_name or self.schema_name
        schema = self.schema[schema_name]
        params = [(pname, _to_sql_type(ptype)) for pname, ptype in (parameters or [])]
        fd = FunctionDescription(name, f, params, _to_sql_type(return_type),
                                 aggregation, row_udf)
        lower = name.lower()
        existing = schema.function_lists.get(lower)
        if existing and not replace:
            # overload check (parity: context.py overload logic)
            for other in existing:
                if [t for _, t in other.parameters] == [t for _, t in params]:
                    raise ValueError(
                        f"Function {name} with signature already registered; "
                        f"use replace=True")
            existing.append(fd)
        else:
            schema.function_lists[lower] = [fd]
        schema.functions[lower] = fd
        self._catalog_serial += 1
        self._on_catalog_change()

    # ------------------------------------------------------------ checkpoint
    def save_state(self, location: str) -> dict:
        """Snapshot every schema (tables->parquet, models->pickle) so a new
        process can `load_state` after a crash — the TPU-native recovery
        story (SURVEY §5; the reference leans on dask worker recomputation,
        which multi-controller JAX does not have)."""
        from . import checkpoint

        return checkpoint.save_state(self, location)

    def load_state(self, location: str) -> dict:
        """Re-hydrate a `save_state` snapshot into this Context, then kick
        the profile-driven warm-up so the restored process compiles its hot
        query families before (or while) traffic arrives."""
        from . import checkpoint

        manifest = checkpoint.load_state(self, location)
        self.maybe_start_warmup()
        return manifest

    def maybe_start_warmup(self):
        """Start a background warm-up over the hottest profiled
        fingerprints (serving/warmup.py), when configured and there is
        anything to warm.  Idempotent while a pass is running; a finished
        pass is replaced (a second load_state re-warms).  Returns the
        `WarmupManager` or None."""
        if not self.config.get("serving.warmup.enabled", True):
            return None
        top_n = int(self.config.get("serving.warmup.top_n", 8) or 0)
        if top_n <= 0 or not len(self.profiles):
            return None
        if self.warmup is not None and not self.warmup.ready:
            return self.warmup  # a pass is already in flight
        from .serving.warmup import WarmupManager

        manager = WarmupManager(
            self, top_n=top_n,
            throttle_s=float(self.config.get(
                "serving.warmup.throttle_s", 0.0) or 0.0))
        self.warmup = manager
        self._register_background(manager)
        return manager.start()

    def background_compiler(self):
        """The bounded background recompiler (serving/background.py), or
        None when ``serving.bg_compile.enabled`` is off.  Created lazily so
        non-serving Contexts never start the thread."""
        if not self.config.get("serving.bg_compile.enabled", False):
            return None
        with self._plan_lock:
            bg = self._bg_compiler
            if bg is None:
                from .serving.background import BackgroundCompiler

                bg = self._bg_compiler = BackgroundCompiler.from_config(
                    self.config, metrics=self.metrics,
                    suspended=self.pressure.suspend_speculative)
            else:
                return bg
        self._register_background(bg)
        return bg

    def _register_background(self, worker) -> None:
        """Hand a cancellable/joinable background worker to the serving
        runtime (if one is attached) so shutdown(wait=True) drains it."""
        runtime = self.serving
        if runtime is not None:
            runtime.register_background(worker)

    # ------------------------------------------------------------ models
    def register_model(self, model_name: str, model: Any,
                       training_columns: List[str],
                       schema_name: Optional[str] = None) -> None:
        """Parity: context.py:626."""
        schema_name = schema_name or self.schema_name
        self.schema[schema_name].models[model_name] = (model, list(training_columns))
        self.metrics.inc("inference.model.registered")
        # the lowered-program cache is NOT invalidated here: it detects the
        # replaced object lazily (id mismatch -> re-lower), and the stale
        # entry is what lets inference/registry.py recognize a same-shape
        # retrain as a zero-recompile model.swap
        self._catalog_serial += 1
        self._on_catalog_change()

    # ------------------------------------------------------------ queries
    def sql(
        self,
        sql: Union[str, Any],
        return_futures: bool = True,
        dataframes: Optional[Dict[str, Any]] = None,
        config_options: Optional[Dict[str, Any]] = None,
    ):
        """Parse, plan, optimize and (lazily) execute a SQL string
        (parity: context.py:482)."""
        if dataframes is not None:
            for df_name, df in dataframes.items():
                self.create_table(df_name, df)
        with contextlib.ExitStack() as scope:
            scope.enter_context(self.config.set(config_options or {}))
            if not isinstance(sql, str):
                raise ValueError("sql must be a string (plans are internal here)")
            # lifecycle trace (observability/): reuse the active trace when
            # an outer scope (the Presto server's worker) already opened one
            # for this query, else open (and own) a fresh trace here
            tr = None
            owned = False
            if self._trace_enabled():
                tr = observability.current_trace()
                if tr is None:
                    tr = observability.QueryTrace(
                        sql=sql, metrics=self.metrics, profiles=self.profiles)
                    self.traces.put(tr.qid, tr)
                    owned = True
                    scope.enter_context(observability.activate(tr))
                self.last_trace = tr

            def _finish_owned_on_error(exc_type, exc, tb):
                # parse/bind/verify failures end the lifecycle of a trace
                # this call opened: close it so the slow-query check runs
                # and /v1/trace never serves a dangling open trace.  (A
                # trace the SERVER opened is not owned here — its registry
                # finishes it at the terminal outcome, after any retries.)
                if exc is not None and owned and tr is not None:
                    tr.finish(self.config, self.metrics)
                return False

            scope.push(_finish_owned_on_error)
            key = self._plan_cache_key(sql, config_options)
            plans = None
            if key is not None:
                with self._plan_lock:
                    plans = self._plan_cache.get(key)
                    if plans is not None:
                        self._plan_cache.move_to_end(key)
            result = None
            if plans is not None:
                self.metrics.inc("query.plan_cache.hit")
                if tr is not None:
                    tr.event("plan_cache_hit")
                for plan in plans:
                    result = self._run_plan(plan, config_options)
            else:
                self.metrics.inc("query.plan_cache.miss")
                with observability.stage("parse"):
                    statements = parse_sql(sql)
                plans = []
                # plan each statement right before running it: a later
                # statement may read what an earlier one created
                for stmt in statements:
                    plan = self._get_ral(
                        stmt, sql_text=sql if len(statements) == 1 else None)
                    plans.append(plan)
                    result = self._run_plan(plan, config_options)
                # only single-statement texts are cacheable — a script's later
                # plans were bound against mid-script catalog state
                if key is not None and len(plans) == 1:
                    with self._plan_lock:
                        self._plan_cache[key] = plans
                        while len(self._plan_cache) > self._PLAN_CACHE_CAP:
                            self._plan_cache.popitem(last=False)
            if result is None:
                # statement(s) with no result frame (DDL): the lifecycle
                # ends here for a trace this call opened
                if owned and tr is not None:
                    tr.finish(self.config, self.metrics)
                return None
            result._trace = tr
            result._sql = sql
            if return_futures:
                return result
            return result.compute()

    def _run_plan(self, plan, config_options=None) -> Optional[TpuFrame]:
        if isinstance(plan, plan_nodes.CustomNode) and not isinstance(
                plan, (plan_nodes.PredictModelNode,)):
            # DDL / side-effecting statements run eagerly (parity: reference
            # converts them immediately, create_memory_table.py etc.)
            from .physical.executor import Executor

            table = Executor(self).execute(plan)
            if not table.columns:
                return None
            frame = TpuFrame(self, plan, list(table.column_names), config_options)
            frame._result = table
            return frame
        return TpuFrame(self, plan, [f.name for f in plan.schema], config_options)

    def explain(self, sql: str, dataframes: Optional[Dict[str, Any]] = None,
                config_options: Optional[Dict[str, Any]] = None) -> str:
        """Return the optimized logical plan as a string (parity context.py:535)."""
        if dataframes is not None:
            for df_name, df in dataframes.items():
                self.create_table(df_name, df)
        with self.config.set(config_options or {}):
            statements = parse_sql(sql)
            plan = self._get_ral(
                statements[0], sql_text=sql if len(statements) == 1 else None)
        if isinstance(plan, plan_nodes.Explain):
            plan = plan.input
        return plan.explain()

    def visualize(self, sql: str, filename: str = "mydask.png") -> None:
        """Render the optimized plan tree to an image (parity: context.py:573
        there renders the dask task graph to png).  Falls back to a text dump
        next to the requested filename when no renderer is available."""
        statements = parse_sql(sql)
        plan = self._get_ral(
            statements[0], sql_text=sql if len(statements) == 1 else None)
        if isinstance(plan, plan_nodes.Explain):
            plan = plan.input
        try:
            self._render_plan_png(plan, filename)
        except Exception:  # dsql: allow-broad-except — no matplotlib /
            # headless issues: text fallback below renders instead
            logger.warning("plan image rendering unavailable; writing text",
                           exc_info=True)
            path = filename if filename.endswith(".txt") else filename + ".txt"
            with open(path, "w") as f:
                f.write(plan.explain())

    @staticmethod
    def _render_plan_png(plan, filename: str) -> None:
        """Layout the plan tree top-down and draw labeled boxes + edges."""
        import matplotlib

        matplotlib.use("Agg")
        import matplotlib.pyplot as plt

        # depth-first layout: x = leaf order, y = -depth
        positions: Dict[int, Tuple[float, float]] = {}
        labels: Dict[int, str] = {}
        edges: List[Tuple[int, int]] = []
        next_x = [0.0]

        def walk(node, depth):
            kids = node.inputs()
            xs = []
            for kid in kids:
                walk(kid, depth + 1)
                edges.append((id(node), id(kid)))
                xs.append(positions[id(kid)][0])
            x = sum(xs) / len(xs) if xs else next_x[0]
            if not xs:
                next_x[0] += 1.0
            positions[id(node)] = (x, -float(depth))
            label = node._label()
            labels[id(node)] = label if len(label) <= 42 else label[:39] + "..."

        walk(plan, 0)
        depth = -min(y for _, y in positions.values()) + 1
        width = max(x for x, _ in positions.values()) + 1
        fig, ax = plt.subplots(
            figsize=(max(6, 3.2 * width), max(3, 1.1 * depth)))
        for a, b in edges:
            (x1, y1), (x2, y2) = positions[a], positions[b]
            ax.plot([x1, x2], [y1, y2], "-", color="#888888", zorder=1)
        for nid, (x, y) in positions.items():
            ax.text(x, y, labels[nid], ha="center", va="center", fontsize=8,
                    zorder=2, bbox=dict(boxstyle="round,pad=0.35",
                                        facecolor="#eef3fb",
                                        edgecolor="#4a6fa5"))
        ax.set_axis_off()
        fig.tight_layout()
        fig.savefig(filename, dpi=120)
        plt.close(fig)

    # ------------------------------------------------------------ internals
    def _get_ral(self, stmt, sql_text: Optional[str] = None):
        """AST -> bound plan -> optimized plan (parity: context.py:819
        _get_ral driving parse/bind/optimize in the Rust planner).

        When the statement's source text is available, the whole parse+bind
        stage runs natively (native/binder.cpp, the analogue of the
        reference's compiled SqlToRel, src/sql.rs:586-674); the Python
        binder remains the fallback."""
        catalog = self._prepare_catalog()
        case_sensitive = bool(self.config.get("sql.identifier.case_sensitive", True))
        catalog.case_sensitive = case_sensitive
        plan = None
        core_optimized = False
        native_mode = str(self.config.get("sql.native.binder", "auto")).lower()
        want_opt = bool(self.config.get("sql.optimize", True))
        with observability.stage("bind") as bind_attrs:
            if sql_text is not None and native_mode in ("auto", "on", "true"):
                from .planner.native_bridge import native_bind, native_plan

                cat_buf = self._encoded_catalog(catalog)
                strict = native_mode != "auto"
                if want_opt:
                    # one native call runs parse+bind+the structural rule loop
                    # AND the stats-driven join reorder (the reference's
                    # compiled DataFusion pipeline analogue)
                    plan = native_plan(
                        sql_text, catalog, cat_buf=cat_buf,
                        predicate_pushdown=bool(
                            self.config.get("sql.predicate_pushdown", True)),
                        strict=strict,
                        fact_dimension_ratio=float(self.config.get(
                            "sql.optimizer.fact_dimension_ratio", 0.7)),
                        max_fact_tables=int(self.config.get(
                            "sql.optimizer.max_fact_tables", 2)),
                        preserve_user_order=bool(self.config.get(
                            "sql.optimizer.preserve_user_order", True)),
                        filter_selectivity=float(self.config.get(
                            "sql.optimizer.filter_selectivity", 1.0)))
                    core_optimized = plan is not None
                if plan is None:
                    plan = native_bind(sql_text, catalog, cat_buf=cat_buf,
                                       strict=strict)
            bind_attrs["native"] = plan is not None
            if plan is None:
                binder = Binder(catalog, case_sensitive=case_sensitive)
                plan = binder.bind_statement(stmt)
        if want_opt:
            from .planner.optimizer.driver import optimize_core, optimize_post
            from .resilience.errors import QueryError

            try:
                with observability.stage("optimize",
                                         core_native=core_optimized):
                    if not core_optimized:
                        plan = optimize_core(plan, self.config, catalog)
                    plan = optimize_post(plan, self.config, catalog,
                                         context=self,
                                         skip_reorder=core_optimized)
            except QueryError:
                # taxonomy errors (deadline expiry at a checkpoint, resource
                # exhaustion in a plan-time data read) carry policy upstream
                # layers act on — they must cross this boundary, not vanish
                # into a silent unoptimized-plan fallback
                raise
            except Exception:
                # parity: optimizer failure falls back to the unoptimized plan
                # (context.py:857-864), metric-counted so a lived-with
                # planner bug shows up in SHOW METRICS instead of only logs
                self.metrics.inc("planner.optimize.fallback")
                logger.warning("Optimization failed; using unoptimized plan",
                               exc_info=True)
        verify_mode = str(self.config.get("analysis.verify", "on")).lower()
        # plain EXPLAIN / EXPLAIN LINT never execute their input (the LINT
        # plugin runs its own verification walk), so only executing plans —
        # including EXPLAIN ANALYZE — pay the bind-time check
        wants_verify = not (isinstance(plan, plan_nodes.Explain)
                            and not plan.analyze)
        if wants_verify and not isinstance(plan, plan_nodes.CustomNode):
            # plan-family parameterization (families/, docs/serving.md):
            # literals lift into a runtime parameter vector, and the
            # literal-stripped fingerprint becomes the query's serving
            # identity — result-cache key, breaker/ladder key, estimator
            # memo, per-family profile/warm-up entry — while the compiled
            # pipelines share one executable across the whole family
            from . import families

            if families.enabled(self.config):
                with observability.stage("parameterize") as fam_attrs:
                    info = families.family_of(plan, self.config,
                                              metrics=self.metrics)
                    if info is not None:
                        fam_attrs["family"] = info.fingerprint
                        fam_attrs["params"] = info.n_params
                        if info.n_params:
                            self.metrics.inc("families.parameterized")
        if wants_verify and verify_mode not in ("off", "false", "0", "none"):
            from . import analysis

            # static plan verification (docs/analysis.md): schema/dtype
            # cross-check raises taxonomy PlanError here — at bind time —
            # and statically-doomed compiled rungs are marked on the plan
            # so the degradation ladder never attempts them
            with observability.stage("verify", mode=verify_mode):
                analysis.verify_and_apply(plan, self,
                                          strict=(verify_mode == "strict"))
        if wants_verify and not isinstance(plan, plan_nodes.CustomNode) \
                and self._estimate_enabled():
            # static cost & memory estimation (docs/analysis.md): the
            # verdict rides the plan (`_dsql_estimate`) for the admission
            # byte gate and result-cache admission, and compiled aggregate
            # rungs whose intermediate-buffer lower bound provably cannot
            # fit the device budget are pre-skipped for the ladder
            with observability.stage("estimate") as est_attrs:
                est = self._run_estimator(plan)
                if est is not None:
                    est_attrs["rows_hi"] = est.rows.hi
                    est_attrs["bytes_lo"] = est.peak_bytes.lo
        return plan

    def _estimate_enabled(self) -> bool:
        mode = str(self.config.get("analysis.estimate", "on")).lower()
        return mode not in ("off", "false", "0", "none")

    def _trace_enabled(self) -> bool:
        mode = str(self.config.get("observability.trace.enabled",
                                   True)).lower()
        return mode not in ("off", "false", "0", "none")

    def _run_estimator(self, plan):
        """Guarded `estimate_and_apply`: estimation is advisory, so an
        estimator bug must never block planning or execution — the query
        simply runs ungated, metric-counted.

        Family reuse (families/): the estimator's intervals never read
        literal *values* (filters drop the lower bound and keep the upper;
        IN buckets and LIMIT windows are part of the family), so a
        family's first estimate is exact for every member — later members
        reuse it instead of re-walking the plan.  When the device-budget
        rung proofs are armed the walk re-runs per plan, because proofs
        mark the concrete plan's nodes."""
        from .analysis import estimator

        try:
            fam = getattr(plan, "_dsql_family", None)
            key = None
            if fam is not None and estimator.device_budget_bytes(
                    self.config) is None:
                try:
                    key = (fam.fingerprint,
                           tuple(tuple(x) if isinstance(x, list) else x
                                 for x in self._catalog_signature()),
                           self._catalog_serial,
                           self.config.effective_items())
                    hash(key)
                except TypeError:
                    key = None
            if key is not None:
                with self._plan_lock:
                    cached = self._family_estimates.get(key)
                if cached is not None:
                    plan._dsql_estimate = cached
                    self.metrics.inc("families.estimate.hit")
                    return self._feedback_estimate(plan, cached, fam)
            est = estimator.estimate_and_apply(plan, self)
            if key is not None and est is not None:
                with self._plan_lock:
                    if len(self._family_estimates) >= 512:
                        self._family_estimates.clear()
                    self._family_estimates[key] = est
            return self._feedback_estimate(plan, est, fam)
        except Exception:  # dsql: allow-broad-except — advisory analysis
            self.metrics.inc("analysis.estimate.internal_error")
            logger.debug("plan estimation failed; query runs ungated",
                         exc_info=True)
            return None

    def cost_hint(self, sql: str, config_options=None):
        """Submit-time `QueryCost` for the packing scheduler
        (serving/scheduler.py): peek the plan cache for this SQL text — a
        hit carries the family's memoized estimate (the provable
        ``peak_bytes`` floor the packer reserves) and the family's observed
        exec profile (the predicted exec_ms behind drain hints and
        deadline ordering).  Never parses or plans: submit must stay cheap,
        so a cold SQL text returns None and the scheduler treats the query
        as zero-cost (FIFO-equivalent) until its first execution populates
        the plan cache and profile."""
        from .serving.scheduler import QueryCost

        try:
            # Context.sql computes the plan-cache key INSIDE its config
            # overlay scope (effective_items sees the per-query options);
            # the peek must mirror that or option-carrying submits never
            # hit the cache they populated
            with self.config.set(dict(config_options or {})):
                key = self._plan_cache_key(sql, config_options)
            if key is None:
                return None
            with self._plan_lock:
                plans = self._plan_cache.get(key)
            if not plans or len(plans) != 1:
                return None
            plan = plans[0]
            est = getattr(plan, "_dsql_estimate", None)
            fam = getattr(plan, "_dsql_family", None)
            fam_fp = fam.fingerprint if fam is not None else None
            fp = fam_fp
            if fp is None:
                from .resilience.ladder import plan_fingerprint

                fp = plan_fingerprint(plan)
            # streamed plans reserve only their per-chunk footprint: re-run
            # the (pure, read-only) routing decision under this submit's
            # effective config — never read from the shared plan object, so
            # the hint is always current with THIS submit's budget
            chunk = None
            if est is not None:
                chunk = self._stream_chunk_hint(plan, est, config_options)
            return QueryCost(
                bytes_lo=int(est.peak_bytes.lo) if est is not None else 0,
                pred_exec_ms=self.profiles.predicted_exec_ms(fp),
                family=fam_fp,
                chunk_bytes_lo=chunk)
        except Exception:  # dsql: allow-broad-except — advisory hint: a
            # lookup bug must degrade to FIFO treatment, never block submit
            logger.debug("cost hint failed for %r", sql, exc_info=True)
            return None

    def _stream_chunk_hint(self, plan, est, config_options):
        """The provable per-chunk floor a streamed execution of `plan`
        would reserve under this submit's effective config, or None (the
        query runs single-launch).  Mirrors the admission gate's routing
        exactly — same budget parse, same `stream_decision` — but purely
        read-only, so the submit path never mutates shared plan state."""
        with self.config.set(dict(config_options or {})):
            budget = config_module.parse_byte_budget(
                self.config.get("serving.admission.max_estimated_bytes"))
            if budget is None or int(est.peak_bytes.lo) <= budget:
                return None
            from .streaming import stream_decision

            routed = stream_decision(plan, est, self, self.config, budget)
        return int(routed[1].chunk_bytes_lo) if routed is not None else None

    def _measured_scan_bytes(self, plan, stream_decision=None) -> int:
        """MEASURED resident bytes of the registered tables `plan` scans
        (`serving/cache.table_nbytes` accounting — encoded widths, masks,
        dictionaries), the scan side of the scheduler's reserve-vs-measured
        reconciliation.  ``stream_decision`` is this execution's routing
        verdict (streaming/) when it streamed: the streamed table charges
        its PER-CHUNK share, because the reservation it reconciles against
        was the per-chunk floor.  Purely advisory — any failure means 0,
        never a failed query."""
        try:
            from .serving.cache import table_nbytes

            total = 0
            seen = set()
            for node in plan_nodes.walk_plan(plan):
                if not isinstance(node, plan_nodes.TableScan):
                    continue
                key = (node.schema_name, node.table_name)
                if key in seen:
                    continue
                seen.add(key)
                container = self.schema.get(node.schema_name)
                dc = container.tables.get(node.table_name) \
                    if container is not None else None
                if dc is None:
                    continue
                from .datacontainer import LazyParquetContainer

                if isinstance(dc, LazyParquetContainer):
                    continue
                nbytes = table_nbytes(dc.table)
                if stream_decision is not None \
                        and stream_decision.partitions > 1 \
                        and (stream_decision.schema_name,
                             stream_decision.table_name) == key:
                    nbytes = -(-nbytes // stream_decision.partitions)
                total += nbytes
            return total
        except Exception:  # dsql: allow-broad-except — advisory accounting
            logger.debug("measured scan bytes failed", exc_info=True)
            return 0

    def _feedback_estimate(self, plan, est, fam):
        """Close the profile-feedback loop on one freshly produced (or
        family-memoized) estimate: record the static rows upper bound into
        the family's profile (the "estimated" side SHOW PROFILES pairs with
        the observed rows), then tighten the estimate's upper bounds from
        the observed history (`estimator.apply_feedback` — bounded, never
        below the provable floors).  The memoized static verdict is never
        mutated, so every later family member re-applies feedback against
        its own, fresher history."""
        if est is None:
            return None
        try:
            from .analysis import estimator

            fam_fp = fam.fingerprint if fam is not None else None
            fp = fam_fp
            if fp is None:
                from .resilience.ladder import plan_fingerprint

                fp = plan_fingerprint(plan)
            self.profiles.record_estimate(fp, est.rows.hi, family=fam_fp)
            out = estimator.apply_feedback(est, self.profiles.get(fp),
                                           self.config, self.metrics)
            plan._dsql_estimate = out
            return out
        except Exception:  # dsql: allow-broad-except — feedback is an
            # advisory sharpening: a bug here must leave the static
            # verdict in force, never fail the query or EXPLAIN
            self.metrics.inc("analysis.estimate.internal_error")
            logger.debug("estimate feedback failed; static verdict kept",
                         exc_info=True)
            return est

    def _plan_estimate(self, plan):
        """The bind-time `PlanEstimate` riding a plan, or a fresh one when
        the gate is configured but the plan was never estimated (cached
        plans carry theirs; `analysis.estimate = off` disables both)."""
        est = getattr(plan, "_dsql_estimate", None)
        if est is not None:
            if not est.feedback:
                # a plan-cached query keeps its bind-time estimate; apply
                # feedback once history exists so repeated cached traffic
                # still benefits (one-time tightening — an already-fed-back
                # estimate is not re-ratcheted against a rolling window)
                est = self._feedback_estimate(
                    plan, est, getattr(plan, "_dsql_family", None))
            return est
        if config_module.parse_byte_budget(
                self.config.get("serving.admission.max_estimated_bytes")) \
                is None:
            return None
        if not self._estimate_enabled():
            return None
        if isinstance(plan, plan_nodes.CustomNode):
            return None
        if isinstance(plan, plan_nodes.Explain) and not plan.analyze:
            # plain EXPLAIN / LINT / ESTIMATE renders text, never executes
            # its input — it must report on an over-budget query, not be
            # shed by the gate
            return None
        return self._run_estimator(plan)

    def _encoded_catalog(self, catalog) -> Optional[bytes]:
        """Catalog bytes for the native binder, cached across queries until
        any table/view/function changes (keyed like the plan cache)."""
        try:
            # statistics row counts are serialized into the buffer for the
            # native join reorderer, so an in-place stats refresh (same uid,
            # same serial) must also invalidate (ADVICE r5)
            key = (self._catalog_serial, catalog.case_sensitive,
                   catalog.current_schema, tuple(
                       (sname, tname, dc.uid,
                        getattr(cont.statistics.get(tname), "row_count", None))
                       for sname, cont in sorted(self.schema.items())
                       for tname, dc in sorted(cont.tables.items())))
        except Exception:  # dsql: allow-broad-except — unhashable/odd stats
            # only disable caching for this call; encoding still runs
            key = None
        with self._plan_lock:
            cached = getattr(self, "_catalog_buf_cache", None)
        if key is not None and cached is not None and cached[0] == key:
            return cached[1]
        from .planner.native_bridge import encode_catalog

        try:
            buf = encode_catalog(catalog)
        except KeyError:
            buf = None
        if key is not None:
            with self._plan_lock:
                self._catalog_buf_cache = (key, buf)
        return buf

    def _prepare_catalog(self) -> Catalog:
        """Sync python-side schema containers into a planner catalog
        (parity: _prepare_schemas, context.py:749)."""
        catalog = Catalog(self.schema_name)
        catalog.current_schema = self.schema_name
        for schema_name, container in self.schema.items():
            catalog.add_schema(schema_name)
            cschema = catalog.schemas[schema_name]
            for table_name, dc in container.tables.items():
                from .datacontainer import LazyParquetContainer

                if isinstance(dc, LazyParquetContainer):
                    fields = list(dc.fields)
                else:
                    fields = [
                        Field(name, col.sql_type, col.validity is not None or
                              col.sql_type in (SqlType.FLOAT, SqlType.DOUBLE))
                        for name, col in dc.table.columns.items()
                    ]
                stats = container.statistics.get(table_name)
                from .planner.catalog import Statistics as PStats

                cschema.tables[table_name] = CatalogTable(
                    table_name, schema_name, fields,
                    PStats(stats.row_count if stats else None),
                    container.filepaths.get(table_name),
                )
            for view_name, view_plan in self._views.get(schema_name, {}).items():
                fields = list(view_plan.schema)
                ct = CatalogTable(view_name, schema_name, fields)
                ct.view_plan = view_plan
                cschema.tables[view_name] = ct
            for fname, fds in container.function_lists.items():
                cschema.functions[fname] = list(fds)
            cschema.models = container.models
        return catalog

    def _register_view(self, name: str, plan, schema_name: str) -> None:
        self._views.setdefault(schema_name, {})[name] = plan
        self._catalog_serial += 1
        self._on_catalog_change()

    def _table_schema_name(self, parts: List[str]) -> Tuple[str, str]:
        if len(parts) >= 2:
            return parts[-2], parts[-1]
        return self.schema_name, parts[0]

    def _table_fields(self, schema_name: str, table_name: str):
        dc = self.schema[schema_name].tables.get(table_name)
        if dc is not None:
            return [Field(n, c.sql_type, True) for n, c in dc.table.columns.items()]
        view = self._views.get(schema_name, {}).get(table_name)
        if view is not None:
            return list(view.schema)
        raise KeyError(f"Table {table_name} not found")

    # -- executor services ---------------------------------------------------
    def get_table_data(self, schema_name: str, table_name: str) -> Table:
        dc = self.schema[schema_name].tables.get(table_name)
        if dc is not None:
            return dc.assign()
        view = self._views.get(schema_name, {}).get(table_name)
        if view is not None:
            from .physical.executor import Executor

            return Executor(self).execute(view)
        raise KeyError(f"Table {schema_name}.{table_name} not found")

    def lookup_function(self, name: str) -> Optional[FunctionDescription]:
        schema = self.schema[self.schema_name]
        return schema.functions.get(name.lower()) or schema.functions.get(name)

    def get_model(self, schema_name: str, model_name: str):
        models = self.schema[schema_name].models
        if model_name not in models:
            raise KeyError(f"A model with the name {model_name} is not present.")
        return models[model_name]

    def cancel_query(self, qid: str) -> bool:
        """Cooperatively cancel an in-flight query by qid — the engine
        behind ``CANCEL QUERY '<qid>'`` and ``POST /v1/queries/{qid}/
        cancel``.  Resolves the live-registry entry's `QueryTicket` and
        flags it; the executor's per-node checkpoints (and the streaming
        loop's between-launch checkpoints) raise at the next poll, and a
        still-queued serving ticket is skipped by the worker that pops it.
        Returns False for an unknown or already-terminal qid."""
        ok = self.live_queries.cancel(qid)
        self.metrics.inc("serving.cancel_requested")
        observability.flight.record("query.cancel", qid=qid, ok=ok)
        return ok

    # ------------------------------------------------------------ front-ends
    def run_server(self, **kwargs):  # pragma: no cover - thin wrapper
        """Presto-protocol HTTP server (parity: context.py:704)."""
        from .server.app import run_server as _run

        return _run(context=self, **kwargs)

    def stop_server(self):  # pragma: no cover
        if self.server is not None:
            self.server.shutdown()
        self.server = None

    def ipython_magic(self, auto_include: bool = False):  # pragma: no cover
        from .integrations.ipython import ipython_integration

        ipython_integration(self, auto_include=auto_include)

    def fqn(self, parts) -> Tuple[str, str]:
        """Fully-qualified (schema, table) from a name (parity context helper)."""
        return self._table_schema_name(list(parts))


#: ops whose value changes between executions of the same plan (parity:
#: optimizer rules' _is_volatile, plus the clock functions)
_VOLATILE_OPS = frozenset(
    {"rand", "rand_integer", "current_timestamp", "current_date"})


def _scan_node_exprs(node) -> Tuple[List[Any], bool]:
    """Walk every expression hanging off one plan node.  Returns
    (nested subquery plans to keep walking, uncacheable) where uncacheable
    means a volatile builtin or any user-defined function was found — such
    results must never be served from the result cache."""
    import dataclasses

    from .planner.expressions import (
        ExistsExpr,
        Expr,
        InSubqueryExpr,
        ScalarFunc,
        ScalarSubqueryExpr,
        SortKey,
        UdfExpr,
    )
    from .planner.expressions import walk as expr_walk

    def exprs_of(v):
        if isinstance(v, Expr):
            yield v
        elif isinstance(v, SortKey):
            yield v.expr
        elif isinstance(v, (list, tuple)):
            for item in v:
                yield from exprs_of(item)

    nested: List[Any] = []
    if not dataclasses.is_dataclass(node):
        return nested, False
    for f in dataclasses.fields(node):
        for e in exprs_of(getattr(node, f.name, None)):
            for x in expr_walk(e):
                if isinstance(x, ScalarFunc) and x.op in _VOLATILE_OPS:
                    return nested, True
                if isinstance(x, UdfExpr):
                    # arbitrary host code: assume nondeterministic
                    return nested, True
                if isinstance(x, (ScalarSubqueryExpr, InSubqueryExpr,
                                  ExistsExpr)) and x.plan is not None:
                    nested.append(x.plan)
    return nested, False


def _to_sql_type(t) -> SqlType:
    if isinstance(t, SqlType):
        return t
    if isinstance(t, str):
        from .columnar.dtypes import parse_sql_type

        return parse_sql_type(t)
    try:
        return np_to_sql(np.dtype(t))
    except (TypeError, ValueError, KeyError):
        pass  # not a numpy dtype spec: try the python scalar mapping
    mapping = {int: SqlType.BIGINT, float: SqlType.DOUBLE, str: SqlType.VARCHAR,
               bool: SqlType.BOOLEAN}
    if t in mapping:
        return mapping[t]
    raise NotImplementedError(f"Cannot map {t!r} to a SQL type")
