"""Packed host transfer: N device buffers -> ONE device_get.

On a tunneled accelerator every dispatch/transfer costs a network round
trip; materializing a 10-column result as per-column `np.asarray` pays ~10+
of them.  This module bitcasts every 64-bit-encodable buffer into one
[n_buffers, n_rows] int64 matrix inside a single jitted kernel, pulls it
with one transfer, and recovers the original dtypes on host.

Lossless transport: f64 via bitcast, f32/f16 via exact widening to f64 then
bitcast (narrowing back is exact), ints/bools via sign-extending int64.

Trade-off: narrow buffers (bool masks, int32 dictionary codes) widen to 8B
for transport, so this path trades bytes for round trips — the right trade
on a latency-dominated tunnel, the wrong one on a bandwidth-starved link
with wide string-heavy results (the CPU backend skips it entirely).
Relationship to physical/compiled.py pack_flat/unpack_row: that pair packs
DOMAIN-sized aggregate outputs into f64 during kernel tracing; this packs
ROW-sized raw columns post-execution — both must stay independently
lossless for their dtype sets.
"""
from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

_jit_cache: dict = {}


def _build(sig):
    def fn(*bufs):
        cols = []
        for x, (kind, _) in zip(bufs, sig):
            if kind == "f64":
                cols.append(jax.lax.bitcast_convert_type(x, jnp.int64))
            elif kind == "f":
                cols.append(jax.lax.bitcast_convert_type(
                    x.astype(jnp.float64), jnp.int64))
            else:
                cols.append(x.astype(jnp.int64))
        return jnp.stack(cols)

    return jax.jit(fn)


def packed_host_arrays(bufs: List) -> Optional[List[np.ndarray]]:
    """All buffers as host numpy via one packed transfer; None if any
    buffer is host-resident or not 64-bit encodable (caller falls back)."""
    if len(bufs) < 2:
        return None
    sig = []
    n = None
    for x in bufs:
        if isinstance(x, np.ndarray) or not hasattr(x, "dtype"):
            return None
        dt = np.dtype(x.dtype)
        if x.ndim != 1:
            return None
        if n is None:
            n = x.shape[0]
        elif x.shape[0] != n:
            return None
        if dt == np.float64:
            sig.append(("f64", dt))
        elif dt.kind == "f":
            sig.append(("f", dt))
        elif dt.kind in "iub":
            sig.append(("i", dt))
        else:
            return None
    # keyed by signature only: jax.jit re-specializes per input shape
    # internally, so distinct row counts share one function object
    key = tuple(sig)
    fn = _jit_cache.get(key)
    if fn is None:
        fn = _build(sig)
        _jit_cache[key] = fn
    from ..config import config as _config
    from ..resilience import faults
    from ..utils import count_d2h

    # fault site ``d2h`` (resilience/faults.py): the packed transfer is
    # the one wire round trip a tunneled accelerator can drop — injected
    # here as a retryable TransientExecutionError so the serving worker's
    # backoff retry (never the rung breaker) absorbs it
    faults.maybe_inject("d2h", _config)
    count_d2h()
    packed = np.asarray(jax.device_get(fn(*bufs)))
    out = []
    for i, (kind, dt) in enumerate(sig):
        row = np.ascontiguousarray(packed[i])
        if kind == "f64":
            out.append(row.view(np.float64))
        elif kind == "f":
            out.append(row.view(np.float64).astype(dt))
        elif dt.kind == "b":
            out.append(row.astype(bool))
        else:
            out.append(row.astype(dt))
    return out
