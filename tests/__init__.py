# Package marker: tests/unit and tests/integration each ship a
# test_checkpoint.py; without package-qualified module names pytest's
# prepend import mode refuses the duplicate basename at collection time.
