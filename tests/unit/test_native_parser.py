"""Native (C++) parser: differential AST equality against the Python parser.

Parity: the reference's parser is compiled (src/parser.rs); here
native/parser.cpp emits a flat node buffer that must decode to EXACTLY the
sqlast objects the Python parser builds — checked structurally over the
TPC-H + TPC-DS corpora and targeted grammar cases.  TPC-H runs fallback-off:
a native miss on those queries is a failure, not a skip.
"""
import pytest

from dask_sql_tpu.planner.native_bridge import native_parse
from dask_sql_tpu.planner.parser import Parser, ParsingException

from tests.tpch import QUERIES as TPCH_QUERIES
from tests.tpcds_queries import QUERIES as TPCDS_QUERIES

native_available = native_parse("SELECT 1") is not None
needs_native = pytest.mark.skipif(not native_available,
                                  reason="native library not built")


@needs_native
@pytest.mark.parametrize("qnum", sorted(TPCH_QUERIES))
def test_tpch_parses_natively(qnum):
    """Fallback-off: every TPC-H query must go through the C++ parser."""
    sql = TPCH_QUERIES[qnum]
    nat = native_parse(sql)
    assert nat is not None, f"q{qnum} fell back to the Python parser"
    assert nat == Parser(sql).parse_statements(), f"q{qnum} AST mismatch"


@needs_native
def test_tpcds_corpus_differential():
    misses, mismatches = [], []
    for qnum, sql in sorted(TPCDS_QUERIES.items()):
        nat = native_parse(sql)
        if nat is None:
            misses.append(qnum)
        elif nat != Parser(sql).parse_statements():
            mismatches.append(qnum)
    assert not mismatches, f"AST mismatches: {mismatches}"
    assert not misses, f"native misses: {misses}"


GRAMMAR_CASES = [
    "SELECT a, b + 1 AS c FROM t WHERE x > 5 AND y LIKE 'a%' ESCAPE '!'",
    "SELECT DISTINCT t.a, s.* FROM t JOIN s ON t.k = s.k LEFT JOIN u USING (k)",
    "SELECT * FROM a NATURAL JOIN b, c CROSS JOIN d",
    "WITH c AS (SELECT 1 AS x) SELECT * FROM c WHERE x > (SELECT AVG(x) FROM c)",
    "SELECT CASE a WHEN 1 THEN 'x' ELSE 'y' END, TRY_CAST(a AS DECIMAL(10,2)) FROM t",
    "SELECT SUM(x) FILTER (WHERE y > 0) OVER (ORDER BY d RANGE BETWEEN "
    "UNBOUNDED PRECEDING AND 3 FOLLOWING) FROM t",
    "VALUES (1, 'a'), (2, NULL)",
    "SELECT PERCENTILE_CONT(0.25) WITHIN GROUP (ORDER BY y DESC) FROM t",
    "SELECT INTERVAL '1' MONTH, INTERVAL - '2' DAY, TIMESTAMP '2020-01-01 00:00:00' FROM t",
    "SELECT x NOT IN (SELECT y FROM s), a <> ALL (SELECT b FROM u) FROM t",
    "SELECT TRIM(TRAILING 'x' FROM s), TRIM(s), TRIM('c' FROM s) FROM t",
    "SELECT t.* FROM t TABLESAMPLE BERNOULLI (25.5) AS smp",
    "SELECT a FROM t GROUP BY CUBE (a, b)",
    "SELECT a FROM t GROUP BY GROUPING SETS ((a, b), b, ())",
    "SELECT f(x) OVER w, g() FROM t WINDOW w AS (PARTITION BY a ORDER BY b DESC)",
    "SELECT -x, +y, NOT z, a || b || c FROM t",
    "(SELECT a FROM t) UNION (SELECT b FROM s) INTERSECT SELECT c FROM u",
    "SELECT a FROM t ORDER BY 1 ASC NULLS LAST OFFSET 3 ROWS FETCH NEXT 7 ROWS ONLY",
    'SELECT x FROM "Tbl" AS "T"(c1, c2)',
    "SELECT TIMESTAMPDIFF(DAY, a, b), DATEDIFF('month', a, b) FROM t",
    "SELECT a IS UNKNOWN, b IS NOT FALSE, c IS TRUE FROM t",
    "EXPLAIN ANALYZE SELECT 1",
    "SELECT x FROM PREDICT(MODEL m, SELECT a FROM t) p",
]


@needs_native
@pytest.mark.parametrize("sql", GRAMMAR_CASES)
def test_grammar_case_differential(sql):
    nat = native_parse(sql)
    assert nat is not None, f"native miss: {sql}"
    assert nat == Parser(sql).parse_statements()


DDL_CASES = [
    # round 4: the native parser is fallback-off for the ENTIRE dialect
    # (VERDICT r3 #8; bar: reference src/parser.rs:552-1350)
    "SHOW SCHEMAS",
    "SHOW SCHEMAS LIKE 'oth%'",
    "SHOW TABLES",
    "SHOW TABLES FROM myschema",
    "SHOW COLUMNS FROM myschema.tbl",
    "SHOW MODELS",
    "DESCRIBE some_table",
    "DESCRIBE MODEL my_model",
    "USE SCHEMA other",
    "ANALYZE TABLE t COMPUTE STATISTICS FOR ALL COLUMNS",
    "ANALYZE TABLE s.t COMPUTE STATISTICS FOR COLUMNS a, b, c",
    "CREATE SCHEMA IF NOT EXISTS abc",
    "CREATE OR REPLACE SCHEMA abc",
    "DROP SCHEMA IF EXISTS abc",
    "ALTER SCHEMA old_s RENAME TO new_s",
    "ALTER TABLE IF EXISTS s.old_t RENAME TO new_t",
    "CREATE TABLE t WITH (location = 'x.parquet', format = 'parquet', "
    "persist = True, statistics = (row_count = 100))",
    "CREATE OR REPLACE TABLE t AS (SELECT a, SUM(b) FROM x GROUP BY a)",
    "CREATE TABLE IF NOT EXISTS t AS SELECT 1 AS one",
    "CREATE VIEW v AS (SELECT * FROM t WHERE a > 2)",
    "DROP TABLE IF EXISTS t",
    "DROP VIEW v",
    "CREATE MODEL my_model WITH (model_class = 'GradientBoostingClassifier',"
    " wrap_predict = True, target_column = 'target', "
    "fit_kwargs = (single_quoted = 'yes', number = 3.5, flag = False, "
    "list_arg = (1, 2, 'three'), arr = [4, 5], nothing = NULL)) AS ("
    "SELECT x, y, x*y > 0 AS target FROM timeseries LIMIT 100)",
    "CREATE OR REPLACE MODEL IF NOT EXISTS m WITH (model_class='c') AS SELECT 1",
    "DROP MODEL IF EXISTS my_model",
    "EXPORT MODEL my_model WITH (format = 'pickle', location = '/tmp/m.pkl')",
    "CREATE EXPERIMENT ex WITH (model_class = 'x', experiment_class = 'y',"
    " tune_parameters = (n_estimators = [16, 32], learning_rate = [0.1]))"
    " AS (SELECT * FROM train)",
    "CREATE TABLE t1 AS (SELECT 1); SELECT * FROM t1; DROP TABLE t1",
]


@needs_native
@pytest.mark.parametrize("sql", DDL_CASES)
def test_ddl_parses_natively(sql):
    """Fallback-off: every dialect statement goes through the C++ parser."""
    nat = native_parse(sql)
    assert nat is not None, "DDL statement fell back to the Python parser"
    assert nat == Parser(sql).parse_statements(), "DDL AST mismatch"


@needs_native
def test_native_errors_raise_parsing_exception():
    with pytest.raises(ParsingException) as ei:
        native_parse("SELECT FROM WHERE")
    assert "position" in str(ei.value)
    with pytest.raises(ParsingException):
        native_parse("SELECT a FROM t WHERE x BETWEEN 1")
    # same syntax errors through the public API
    from dask_sql_tpu.planner.parser import parse_sql

    with pytest.raises(ParsingException):
        parse_sql("SELECT (a FROM t")


@needs_native
def test_huge_int_literal_falls_back():
    # ints beyond int64 can't ride the flat buffer; Python handles them
    from dask_sql_tpu.planner.parser import parse_sql

    stmts = parse_sql("SELECT 99999999999999999999999999 AS x")
    assert stmts[0].query.projections[0].alias == "x"
