"""ctypes bridge to the native (C++) planner components.

Role parity: the reference embeds its whole planner as a native extension
(PyO3 cdylib, src/lib.rs).  Here the native library is loaded via ctypes —
no pybind11 needed — and each component keeps a pure-Python fallback so the
package works before `make` has run.  The library is built lazily (g++) on
first use and cached next to the sources.
"""
from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import List, Optional

logger = logging.getLogger(__name__)

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))), "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libdsql_native.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False

_TOKEN_TYPE_NAMES = ["IDENT", "QUOTED_IDENT", "NUMBER", "STRING", "OP", "PUNCT", "PARAM"]


def _build() -> bool:
    try:
        subprocess.run(["make", "-s"], cwd=_NATIVE_DIR, check=True,
                       capture_output=True, timeout=120)
        return os.path.exists(_LIB_PATH)
    except Exception as e:  # dsql: allow-broad-except — any failure means fallback
        logger.debug("native build failed: %s", e)
        return False


def get_lib() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(_LIB_PATH) and os.path.isdir(_NATIVE_DIR):
            _build()
        if not os.path.exists(_LIB_PATH):
            return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
            lib.dsql_tokenize.restype = ctypes.c_int64
            lib.dsql_tokenize.argtypes = [
                ctypes.c_char_p, ctypes.c_int64,
                ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int64),
                ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
            ]
            lib.dsql_tokenizer_abi_version.restype = ctypes.c_int32
            if lib.dsql_tokenizer_abi_version() != 1:
                return None
            _lib = lib
        except OSError:
            return None
        return _lib


def native_tokenize(sql: str):
    """Tokenize via the C++ lexer; returns a lexer.Token list or None."""
    from .lexer import Token, TokenType

    lib = get_lib()
    if lib is None:
        return None
    raw = sql.encode("utf-8")
    max_tokens = max(len(raw) // 2 + 16, 64)
    types = (ctypes.c_int32 * max_tokens)()
    starts = (ctypes.c_int64 * max_tokens)()
    lens = (ctypes.c_int64 * max_tokens)()
    count = lib.dsql_tokenize(raw, len(raw), types, starts, lens, max_tokens)
    if count < 0:
        from .lexer import LexError

        pos = -int(count) - 1
        raise LexError(f"Unexpected character at position {pos}")
    tokens: List[Token] = []
    for i in range(count):
        t = _TOKEN_TYPE_NAMES[types[i]]
        start, length = starts[i], lens[i]
        value = raw[start : start + length].decode("utf-8")
        if t == "STRING":
            value = value.replace("''", "'")
        elif t == "QUOTED_IDENT":
            value = value.replace('""', '"').replace("``", "`")
        tokens.append(Token(getattr(TokenType, t), value, start))
    end = len(raw)
    tokens.append(Token(TokenType.EOF, "", end))
    return tokens


# ---------------------------------------------------------------------------
# native parser (C++ parser.cpp) — flat node buffer -> sqlast objects
# ---------------------------------------------------------------------------
_parser_checked = False
_parser_ok = False

# kind constants (keep in sync with native/parser.cpp)
_K_STMT_LIST = 0; _K_QUERY_STMT = 1; _K_EXPLAIN_STMT = 2
_K_SELECT = 10; _K_PROJ_ITEM = 11; _K_FROM_CLAUSE = 12; _K_WHERE_CLAUSE = 13
_K_GROUP_ITEM = 14; _K_HAVING_CLAUSE = 15; _K_ORDER_ITEM = 16
_K_LIMIT_CLAUSE = 17; _K_OFFSET_CLAUSE = 18; _K_CTE = 19; _K_SETOP = 20
_K_DISTRIBUTE_ITEM = 21; _K_VALUES_ROW = 22; _K_NAMED_WINDOW = 23
_K_NAMED_TABLE = 30; _K_DERIVED_TABLE = 31; _K_TABLE_FUNC = 32; _K_JOIN = 33
_K_PART = 34; _K_ALIAS_COL = 35; _K_USING_COL = 36
_K_IDENT = 40; _K_WILDCARD = 41; _K_LIT_NULL = 42; _K_LIT_INT = 43
_K_LIT_FLOAT = 44; _K_LIT_STR = 45; _K_LIT_BOOL = 46; _K_LIT_TYPED = 47
_K_INTERVAL = 48; _K_UNARY = 49; _K_BINARY = 50; _K_CAST = 51; _K_CASE = 52
_K_FUNCALL = 53; _K_WINSPEC = 54; _K_FRAME = 55; _K_BETWEEN = 56
_K_INLIST = 57; _K_INSUBQ = 58; _K_EXISTS = 59; _K_SCALARSUBQ = 60
_K_LIKE = 61; _K_ISNULL = 62; _K_ISBOOL = 63; _K_ISDIST = 64; _K_EXTRACT = 65
_K_SUBSTRING = 66; _K_TRIM = 67; _K_POSITION = 68; _K_OVERLAY = 69
_K_CEILFLOORTO = 70; _K_GROUPING_SETS = 71; _K_SET_NODE = 72; _K_ROLLUP = 73
_K_CUBE = 74
_K_QNAME = 79; _K_CREATE_TABLE_WITH = 80; _K_CREATE_TABLE_AS = 81
_K_DROP_TABLE = 82; _K_CREATE_SCHEMA = 83; _K_DROP_SCHEMA = 84
_K_USE_SCHEMA = 85; _K_ALTER_SCHEMA = 86; _K_ALTER_TABLE = 87
_K_SHOW_SCHEMAS = 88; _K_SHOW_TABLES = 89; _K_SHOW_COLUMNS = 90
_K_SHOW_MODELS = 91; _K_ANALYZE_TABLE = 92; _K_CREATE_MODEL = 93
_K_DROP_MODEL = 94; _K_DESCRIBE_MODEL = 95; _K_EXPORT_MODEL = 96
_K_CREATE_EXPERIMENT = 97; _K_KWARGS = 98; _K_KV = 99; _K_KWLIST = 100
_K_SHOW_METRICS = 101; _K_SHOW_PROFILES = 102
_K_SHOW_QUERIES = 103; _K_CANCEL_QUERY = 104
_K_SHOW_MATERIALIZED = 105; _K_INSERT_INTO = 106
_K_SHOW_REPLICAS = 107

_FRAME_KINDS = ["UNBOUNDED_PRECEDING", "PRECEDING", "CURRENT_ROW",
                "FOLLOWING", "UNBOUNDED_FOLLOWING"]


def _get_parser_lib():
    global _parser_checked, _parser_ok
    lib = get_lib()
    if lib is None:
        return None
    if not _parser_checked:
        _parser_checked = True
        try:
            lib.dsql_parse.restype = ctypes.c_int32
            lib.dsql_parse.argtypes = [
                ctypes.c_char_p, ctypes.c_int64,
                ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
                ctypes.POINTER(ctypes.c_int64),
            ]
            lib.dsql_buf_free.argtypes = [ctypes.POINTER(ctypes.c_uint8)]
            lib.dsql_parser_abi_version.restype = ctypes.c_int32
            # grammar version 7 = SHOW REPLICAS (the fleet surface); a
            # stale .so predating it is rejected here so the Python parser
            # handles the syntax
            _parser_ok = lib.dsql_parser_abi_version() == 7
        except AttributeError:
            _parser_ok = False
    return lib if _parser_ok else None


class _FlatAst:
    __slots__ = ("nodes", "children", "strings", "root")

    MAGIC = 0x44535131

    def __init__(self, buf: bytes):
        import struct

        magic, n_nodes, n_children, n_strings, str_bytes, root, _ = \
            struct.unpack_from("<7i", buf, 0)
        if magic != self.MAGIC:
            raise ValueError("bad native buffer magic")
        self.nodes = []
        off = 28
        for _ in range(n_nodes):
            self.nodes.append(struct.unpack_from("<iiqdiiii", buf, off))
            off += 40
        self.children = struct.unpack_from(f"<{n_children}i", buf, off)
        off += 4 * n_children
        offs = struct.unpack_from(f"<{n_strings + 1}i", buf, off)
        off += 4 * (n_strings + 1)
        blob = buf[off : off + str_bytes]
        self.strings = [blob[offs[i]:offs[i + 1]].decode("utf-8")
                        for i in range(n_strings)]
        self.root = root

    def kids(self, nid):
        k = self.nodes[nid]
        return self.children[k[6] : k[6] + k[7]]

    def s(self, idx):
        return None if idx < 0 else self.strings[idx]


def _decode_expr(f: "_FlatAst", nid: int):
    from . import sqlast as a

    kind, flags, ival, dval, s0, s1, _, _ = f.nodes[nid]
    kids = f.kids(nid)
    if kind == _K_IDENT:
        parts, quoted = [], []
        for p in kids:
            pk = f.nodes[p]
            parts.append(f.s(pk[4]))
            quoted.append(bool(pk[1] & 1))
        return a.Identifier(parts, quoted)
    if kind == _K_WILDCARD:
        if flags & 1:
            return a.Wildcard([f.s(f.nodes[p][4]) for p in kids])
        return a.Wildcard()
    if kind == _K_LIT_NULL:
        return a.Literal(None)
    if kind == _K_LIT_INT:
        return a.Literal(ival)
    if kind == _K_LIT_FLOAT:
        return a.Literal(dval)
    if kind == _K_LIT_STR:
        return a.Literal(f.s(s0))
    if kind == _K_LIT_BOOL:
        return a.Literal(bool(ival))
    if kind == _K_LIT_TYPED:
        return a.Literal(f.s(s0), type_name=f.s(s1))
    if kind == _K_INTERVAL:
        return a.IntervalLiteral(f.s(s0), f.s(s1))
    if kind == _K_UNARY:
        return a.UnaryOp(f.s(s0), _decode_expr(f, kids[0]))
    if kind == _K_BINARY:
        return a.BinaryOp(f.s(s0), _decode_expr(f, kids[0]),
                          _decode_expr(f, kids[1]))
    if kind == _K_CAST:
        return a.Cast(_decode_expr(f, kids[0]), f.s(s0), safe=bool(flags & 1))
    if kind == _K_CASE:
        i = 0
        operand = None
        if flags & 1:
            operand = _decode_expr(f, kids[0])
            i = 1
        rest = kids[i:]
        n_when = (len(rest) - (1 if flags & 2 else 0)) // 2
        whens = [( _decode_expr(f, rest[2 * j]), _decode_expr(f, rest[2 * j + 1]))
                 for j in range(n_when)]
        else_ = _decode_expr(f, rest[-1]) if flags & 2 else None
        return a.Case(operand, whens, else_)
    if kind == _K_FUNCALL:
        args = [_decode_expr(f, k) for k in kids[:ival]]
        i = ival
        filt = None
        if flags & 4:
            filt = _decode_expr(f, kids[i])
            i += 1
        over = None
        if flags & 8:
            over = _decode_winspec(f, kids[i])
            i += 1
        elif flags & 16:
            over = f.s(s1)
        return a.FunctionCall(f.s(s0), args, bool(flags & 1), filt, over,
                              bool(flags & 2))
    if kind == _K_BETWEEN:
        return a.Between(_decode_expr(f, kids[0]), _decode_expr(f, kids[1]),
                         _decode_expr(f, kids[2]), bool(flags & 1),
                         bool(flags & 2))
    if kind == _K_INLIST:
        return a.InList(_decode_expr(f, kids[0]),
                        [_decode_expr(f, k) for k in kids[1:]],
                        bool(flags & 1))
    if kind == _K_INSUBQ:
        return a.InSubquery(_decode_expr(f, kids[0]),
                            _decode_select(f, kids[1]), bool(flags & 1))
    if kind == _K_EXISTS:
        return a.Exists(_decode_select(f, kids[0]), bool(flags & 1))
    if kind == _K_SCALARSUBQ:
        return a.ScalarSubquery(_decode_select(f, kids[0]))
    if kind == _K_LIKE:
        return a.Like(_decode_expr(f, kids[0]), _decode_expr(f, kids[1]),
                      bool(flags & 1), bool(flags & 2), bool(flags & 4),
                      f.s(s0) if flags & 8 else None)
    if kind == _K_ISNULL:
        return a.IsNull(_decode_expr(f, kids[0]), bool(flags & 1))
    if kind == _K_ISBOOL:
        return a.IsBool(_decode_expr(f, kids[0]), bool(flags & 2),
                        bool(flags & 1))
    if kind == _K_ISDIST:
        return a.IsDistinctFrom(_decode_expr(f, kids[0]),
                                _decode_expr(f, kids[1]), bool(flags & 1))
    if kind == _K_EXTRACT:
        return a.Extract(f.s(s0), _decode_expr(f, kids[0]))
    if kind == _K_SUBSTRING:
        start = _decode_expr(f, kids[1]) if flags & 1 else None
        length = _decode_expr(f, kids[2]) if flags & 2 else None
        return a.Substring(_decode_expr(f, kids[0]), start, length)
    if kind == _K_TRIM:
        chars = _decode_expr(f, kids[1]) if flags & 1 else None
        return a.Trim(_decode_expr(f, kids[0]), f.s(s0), chars)
    if kind == _K_POSITION:
        return a.Position(_decode_expr(f, kids[0]), _decode_expr(f, kids[1]))
    if kind == _K_OVERLAY:
        length = _decode_expr(f, kids[3]) if flags & 1 else None
        return a.Overlay(_decode_expr(f, kids[0]), _decode_expr(f, kids[1]),
                         _decode_expr(f, kids[2]), length)
    if kind == _K_CEILFLOORTO:
        return a.CeilFloorTo(f.s(s0), _decode_expr(f, kids[0]), f.s(s1))
    if kind == _K_GROUPING_SETS:
        return a.GroupingSets([[_decode_expr(f, e) for e in f.kids(sn)]
                               for sn in kids])
    if kind == _K_ROLLUP:
        return a.Rollup([_decode_expr(f, k) for k in kids])
    if kind == _K_CUBE:
        return a.Cube([_decode_expr(f, k) for k in kids])
    raise ValueError(f"unexpected native expr kind {kind}")


def _decode_order_item(f, nid):
    from . import sqlast as a

    _, flags, _, _, _, _, _, _ = f.nodes[nid]
    nulls_first = bool(flags & 4) if flags & 2 else None
    return a.OrderItem(_decode_expr(f, f.kids(nid)[0]), bool(flags & 1),
                       nulls_first)


def _decode_winspec(f, nid):
    from . import sqlast as a

    _, flags, npart, _, _, _, _, _ = f.nodes[nid]
    kids = list(f.kids(nid))
    has_frame = bool(flags & 1)
    frame_id = kids.pop() if has_frame else None
    spec = a.WindowSpec()
    spec.partition_by = [_decode_expr(f, k) for k in kids[:npart]]
    spec.order_by = [_decode_order_item(f, k) for k in kids[npart:]]
    if frame_id is not None:
        fk, fflags, fival, _, fs0, _, _, _ = f.nodes[frame_id]
        fkids = list(f.kids(frame_id))
        i = 0
        start_off = None
        if fflags & 1:
            start_off = _decode_expr(f, fkids[i]); i += 1
        end_off = None
        if fflags & 2:
            end_off = _decode_expr(f, fkids[i]); i += 1
        start = (_FRAME_KINDS[fival & 0xFF], start_off)
        end = (_FRAME_KINDS[(fival >> 8) & 0xFF], end_off)
        spec.frame = a.WindowFrame(f.s(fs0), start, end)
    return spec


def _decode_table_ref(f, nid):
    from . import sqlast as a

    kind, flags, ival, dval, s0, s1, _, _ = f.nodes[nid]
    kids = f.kids(nid)
    if kind == _K_NAMED_TABLE:
        parts = [f.s(f.nodes[k][4]) for k in kids
                 if f.nodes[k][0] == _K_PART]
        alias_cols = [f.s(f.nodes[k][4]) for k in kids
                      if f.nodes[k][0] == _K_ALIAS_COL]
        alias = f.s(s0)
        if alias_cols:
            alias = (alias, alias_cols)
        sample = None
        if flags & 1:
            sample = (f.s(s1), dval, None if ival < 0 else ival)
        return a.NamedTable(parts, alias, sample)
    if kind == _K_DERIVED_TABLE:
        alias_cols = [f.s(f.nodes[k][4]) for k in kids[1:]
                      if f.nodes[k][0] == _K_ALIAS_COL]
        alias = f.s(s0)
        if alias_cols:
            alias = (alias, alias_cols)
        return a.DerivedTable(_decode_select(f, kids[0]), alias)
    if kind == _K_TABLE_FUNC:
        parts = [f.s(f.nodes[k][4]) for k in kids
                 if f.nodes[k][0] == _K_PART]
        sel = next(k for k in kids if f.nodes[k][0] == _K_SELECT)
        return a.TableFunction(f.s(s0), parts, _decode_select(f, sel),
                               f.s(s1))
    if kind == _K_JOIN:
        left = _decode_table_ref(f, kids[0])
        right = _decode_table_ref(f, kids[1])
        jt = f.s(s0)
        condition = None
        using = None
        rest = kids[2:]
        if flags & 1:
            condition = _decode_expr(f, rest[0])
        elif flags & 2:
            using = [f.s(f.nodes[k][4]) for k in rest
                     if f.nodes[k][0] == _K_USING_COL]
        return a.Join(left, right, jt, condition, using)
    raise ValueError(f"unexpected native table-ref kind {kind}")


def _decode_select(f, nid):
    from . import sqlast as a

    kind, flags, _, _, _, _, _, _ = f.nodes[nid]
    if kind != _K_SELECT:
        raise ValueError(f"expected SELECT node, got {kind}")
    sel = a.Select()
    sel.distinct = bool(flags & 1)
    values_rows = []
    for k in f.kids(nid):
        ck, cflags, cival, cdval, cs0, cs1, _, _ = f.nodes[k]
        kk = f.kids(k)
        if ck == _K_PROJ_ITEM:
            sel.projections.append(
                a.SelectItem(_decode_expr(f, kk[0]), f.s(cs0)))
        elif ck == _K_FROM_CLAUSE:
            sel.from_ = _decode_table_ref(f, kk[0])
        elif ck == _K_WHERE_CLAUSE:
            sel.where = _decode_expr(f, kk[0])
        elif ck == _K_GROUP_ITEM:
            sel.group_by.append(_decode_expr(f, kk[0]))
        elif ck == _K_HAVING_CLAUSE:
            sel.having = _decode_expr(f, kk[0])
        elif ck == _K_ORDER_ITEM:
            sel.order_by.append(_decode_order_item(f, k))
        elif ck == _K_LIMIT_CLAUSE:
            sel.limit = cival
        elif ck == _K_OFFSET_CLAUSE:
            sel.offset = cival
        elif ck == _K_CTE:
            sel.ctes.append((f.s(cs0), _decode_select(f, kk[0])))
        elif ck == _K_SETOP:
            sel.set_op = (f.s(cs0), bool(cflags & 1),
                          _decode_select(f, kk[0]))
        elif ck == _K_DISTRIBUTE_ITEM:
            sel.distribute_by.append(_decode_expr(f, kk[0]))
        elif ck == _K_VALUES_ROW:
            values_rows.append([_decode_expr(f, e) for e in kk])
        elif ck == _K_NAMED_WINDOW:
            sel.named_windows[f.s(cs0)] = _decode_winspec(f, kk[0])
        else:
            raise ValueError(f"unexpected SELECT child kind {ck}")
    if values_rows:
        sel.values = values_rows
    return sel


def native_parse(sql: str):
    """Parse via the C++ parser; returns a list of sqlast.Statement or None
    when the native path is unavailable / the statement is unsupported.
    Raises ParsingException for genuine syntax errors (same format as the
    Python parser)."""
    lib = _get_parser_lib()
    if lib is None:
        return None
    raw = sql.encode("utf-8")
    out = ctypes.POINTER(ctypes.c_uint8)()
    out_len = ctypes.c_int64()
    rc = lib.dsql_parse(raw, len(raw), ctypes.byref(out),
                        ctypes.byref(out_len))
    if rc == 1:
        return None
    try:
        buf = ctypes.string_at(out, out_len.value) if out_len.value else b""
    finally:
        if out:
            lib.dsql_buf_free(out)
    if rc == 2:
        import struct

        from .parser import ParsingException

        pos = struct.unpack_from("<q", buf, 0)[0]
        msg = buf[8:].decode("utf-8", "replace")
        ctx = sql[max(0, pos - 30) : pos + 30]
        raise ParsingException(f"{msg} at position {pos} (near {ctx!r})")
    try:
        f = _FlatAst(buf)
    except Exception:  # dsql: allow-broad-except — corrupt buffer -> Python fallback
        logger.debug("native AST decode failed", exc_info=True)
        return None
    from . import sqlast as a

    stmts = []
    for sid in f.kids(f.root):
        stmt = _decode_statement(f, sid)
        if stmt is None:
            return None
        stmts.append(stmt)
    return stmts


def _decode_qname(f: "_FlatAst", nid: int):
    return [f.s(f.nodes[p][4]) for p in f.kids(nid)]


def _decode_kwarg_value(f: "_FlatAst", nid: int):
    kind, flags, ival, dval, s0, s1, _, _ = f.nodes[nid]
    if kind == _K_LIT_STR:
        return f.s(s0)
    if kind == _K_LIT_INT:
        return ival
    if kind == _K_LIT_FLOAT:
        return dval
    if kind == _K_LIT_BOOL:
        return bool(ival)
    if kind == _K_LIT_NULL:
        return None
    if kind == _K_KWLIST:
        return [_decode_kwarg_value(f, k) for k in f.kids(nid)]
    if kind == _K_KWARGS:
        return _decode_kwargs(f, nid)
    raise ValueError(f"bad kwarg value kind {kind}")


def _decode_kwargs(f: "_FlatAst", nid: int):
    out = {}
    for kv in f.kids(nid):
        _, _, _, _, s0, _, _, _ = f.nodes[kv]
        out[f.s(s0)] = _decode_kwarg_value(f, f.kids(kv)[0])
    return out


def _decode_statement(f: "_FlatAst", sid: int):
    """One statement node -> sqlast.Statement, or None for unknown kinds
    (the caller then falls back to the Python parser wholesale)."""
    from . import sqlast as a

    kind, flags, _, _, s0, s1, _, _ = f.nodes[sid]
    kids = f.kids(sid)
    ine = bool(flags & 1)
    orr = bool(flags & 2)
    if kind == _K_QUERY_STMT:
        return a.QueryStatement(_decode_select(f, kids[0]))
    if kind == _K_EXPLAIN_STMT:
        return a.ExplainStatement(_decode_select(f, kids[0]), bool(flags & 1),
                                  bool(flags & 2), bool(flags & 4),
                                  bool(flags & 8))
    if kind == _K_CREATE_TABLE_WITH:
        return a.CreateTableWith(_decode_qname(f, kids[0]),
                                 _decode_kwargs(f, kids[1]), ine, orr)
    if kind == _K_CREATE_TABLE_AS:
        return a.CreateTableAs(_decode_qname(f, kids[0]),
                               _decode_select(f, kids[1]),
                               persist=bool(flags & 4),
                               if_not_exists=ine, or_replace=orr)
    if kind == _K_DROP_TABLE:
        return a.DropTable(_decode_qname(f, kids[0]), bool(flags & 1))
    if kind == _K_CREATE_SCHEMA:
        return a.CreateSchema(f.s(s0), ine, orr)
    if kind == _K_DROP_SCHEMA:
        return a.DropSchema(f.s(s0), bool(flags & 1))
    if kind == _K_USE_SCHEMA:
        return a.UseSchema(f.s(s0))
    if kind == _K_ALTER_SCHEMA:
        return a.AlterSchema(f.s(s0), f.s(s1))
    if kind == _K_ALTER_TABLE:
        return a.AlterTable(_decode_qname(f, kids[0]), f.s(s0),
                            bool(flags & 1))
    if kind == _K_SHOW_SCHEMAS:
        return a.ShowSchemas(f.s(s0))
    if kind == _K_SHOW_TABLES:
        return a.ShowTables(f.s(s0))
    if kind == _K_SHOW_COLUMNS:
        return a.ShowColumns(_decode_qname(f, kids[0]))
    if kind == _K_SHOW_MODELS:
        return a.ShowModels(f.s(s0))
    if kind == _K_SHOW_METRICS:
        return a.ShowMetrics(f.s(s0))
    if kind == _K_SHOW_PROFILES:
        return a.ShowProfiles(f.s(s0))
    if kind == _K_SHOW_QUERIES:
        return a.ShowQueries(f.s(s0))
    if kind == _K_CANCEL_QUERY:
        return a.CancelQuery(f.s(s0) or "")
    if kind == _K_SHOW_MATERIALIZED:
        return a.ShowMaterialized(f.s(s0))
    if kind == _K_SHOW_REPLICAS:
        return a.ShowReplicas(f.s(s0))
    if kind == _K_INSERT_INTO:
        return a.InsertInto(_decode_qname(f, kids[0]),
                            _decode_select(f, kids[1]))
    if kind == _K_ANALYZE_TABLE:
        cols = [f.s(f.nodes[p][4]) for p in kids[1:]]
        return a.AnalyzeTable(_decode_qname(f, kids[0]), cols)
    if kind == _K_CREATE_MODEL:
        return a.CreateModel(_decode_qname(f, kids[0]),
                             _decode_kwargs(f, kids[1]),
                             _decode_select(f, kids[2]), ine, orr)
    if kind == _K_DROP_MODEL:
        return a.DropModel(_decode_qname(f, kids[0]), bool(flags & 1))
    if kind == _K_DESCRIBE_MODEL:
        return a.DescribeModel(_decode_qname(f, kids[0]))
    if kind == _K_EXPORT_MODEL:
        return a.ExportModel(_decode_qname(f, kids[0]),
                             _decode_kwargs(f, kids[1]))
    if kind == _K_CREATE_EXPERIMENT:
        return a.CreateExperiment(_decode_qname(f, kids[0]),
                                  _decode_kwargs(f, kids[1]),
                                  _decode_select(f, kids[2]), ine, orr)
    return None


# ---------------------------------------------------------------------------
# native binder (C++ binder.cpp) — catalog encode + flat plan buffer decode
# ---------------------------------------------------------------------------
_binder_checked = False
_binder_ok = False

# plan-buffer kinds (keep in sync with native/binder.cpp)
_P_TABLESCAN = 1; _P_PROJECTION = 2; _P_FILTER = 3; _P_JOIN = 4
_P_CROSSJOIN = 5; _P_AGGREGATE = 6; _P_WINDOW = 7; _P_SORT = 8; _P_LIMIT = 9
_P_UNION = 10; _P_INTERSECT = 11; _P_EXCEPT = 12; _P_DISTINCT = 13
_P_VALUES = 14; _P_EMPTY = 15; _P_SUBQUERY_ALIAS = 16; _P_SAMPLE = 17
_P_DISTRIBUTE_BY = 18; _P_EXPLAIN = 19
_P_CREATE_TABLE = 20; _P_CREATE_MEMORY_TABLE = 21; _P_DROP_TABLE = 22
_P_CREATE_SCHEMA = 23; _P_DROP_SCHEMA = 24; _P_USE_SCHEMA = 25
_P_ALTER_SCHEMA = 26; _P_ALTER_TABLE = 27; _P_SHOW_SCHEMAS = 28
_P_SHOW_TABLES = 29; _P_SHOW_COLUMNS = 30; _P_SHOW_MODELS = 31
_P_ANALYZE_TABLE = 32; _P_CREATE_MODEL = 33; _P_DROP_MODEL = 34
_P_DESCRIBE_MODEL = 35; _P_EXPORT_MODEL = 36; _P_CREATE_EXPERIMENT = 37
_P_PREDICT_MODEL = 38; _P_SHOW_METRICS = 39; _P_SHOW_PROFILES = 40
_P_SHOW_QUERIES = 41; _P_CANCEL_QUERY = 42
_P_FIELD = 50; _P_SORTKEY = 51; _P_ON_PAIR = 52; _P_VALUES_ROW = 53
_P_PART = 54; _P_KWARGS = 55; _P_KV = 56; _P_KWLIST = 57; _P_WINSPEC = 58
_P_FRAME_BOUND = 59
_P_KW_STR = 60; _P_KW_INT = 61; _P_KW_FLOAT = 62; _P_KW_BOOL = 63
_P_KW_NULL = 64
_E_COLREF = 70; _E_LITERAL = 71; _E_SCALARFN = 72; _E_AGG = 73
_E_WINDOW = 74; _E_CAST = 75; _E_CASE = 76; _E_INLIST = 77; _E_INSUBQ = 78
_E_EXISTS = 79; _E_SCALARSUBQ = 80; _E_UDF = 81; _E_OUTERREF = 82
_E_GROUPING = 83

_LT_NULL = 0; _LT_BOOL = 1; _LT_INT = 2; _LT_FLOAT = 3; _LT_STR = 4

_PLAN_FRAME_KINDS = ["UNBOUNDED_PRECEDING", "PRECEDING", "CURRENT_ROW",
                     "FOLLOWING", "UNBOUNDED_FOLLOWING"]


def _sql_type_ids():
    from ..columnar.dtypes import SqlType

    return list(SqlType)  # declaration order == C++ Ty enum order


def _get_binder_lib():
    global _binder_checked, _binder_ok
    lib = get_lib()
    if lib is None:
        return None
    if not _binder_checked:
        _binder_checked = True
        try:
            lib.dsql_bind.restype = ctypes.c_int32
            lib.dsql_bind.argtypes = [
                ctypes.c_char_p, ctypes.c_int64,
                ctypes.c_char_p, ctypes.c_int64,
                ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
                ctypes.POINTER(ctypes.c_int64),
            ]
            lib.dsql_binder_abi_version.restype = ctypes.c_int32
            # version 6 = P_SHOW_QUERIES + P_CANCEL_QUERY
            _binder_ok = lib.dsql_binder_abi_version() == 6
        except AttributeError:
            _binder_ok = False
    return lib if _binder_ok else None


def encode_catalog(catalog) -> bytes:
    """Serialize the planner catalog for dsql_bind (schemas/tables/columns +
    UDF signatures; see native/binder.cpp Catalog::load for the layout)."""
    import struct

    type_ids = {t: i for i, t in enumerate(_sql_type_ids())}
    out = bytearray()

    def w32(v):
        out.extend(struct.pack("<i", v))

    def wstr(s):
        raw = s.encode("utf-8")
        w32(len(raw))
        out.extend(raw)

    w32(0x44535143)
    w32(1 if catalog.case_sensitive else 0)
    wstr(catalog.current_schema)
    w32(len(catalog.schemas))
    for sname, schema in catalog.schemas.items():
        wstr(sname)
        w32(len(schema.tables))
        for tname, table in schema.tables.items():
            wstr(tname)
            rc = table.statistics.row_count if table.statistics else None
            out.extend(struct.pack("<d", -1.0 if rc is None else float(rc)))
            w32(len(table.fields))
            for f in table.fields:
                wstr(f.name)
                w32(type_ids[f.sql_type])
                w32(1 if f.nullable else 0)
        w32(len(schema.functions))
        for fname, fds in schema.functions.items():
            wstr(fname)
            w32(len(fds))
            for fd in fds:
                wstr(fd.name)
                w32(len(fd.parameters))
                for _, pt in fd.parameters:
                    w32(type_ids[pt])
                w32(type_ids[fd.return_type])
                w32(1 if fd.aggregation else 0)
                w32(1 if fd.row_udf else 0)
    return bytes(out)


class _FlatPlan(_FlatAst):
    """Same framing as the AST buffer, 'DSQB' magic."""

    MAGIC = 0x44535142


class _PlanDecoder:
    def __init__(self, f: _FlatPlan):
        self.f = f
        self.types = _sql_type_ids()
        self.plan_memo = {}  # node id -> plan object (preserves CTE sharing)

    # -------- aux --------
    def field(self, nid):
        from .expressions import Field

        _, flags, _, _, s0, _, _, _ = self.f.nodes[nid]
        return Field(self.f.s(s0), self.types[flags >> 8], bool(flags & 1))

    def fields(self, ids):
        return [self.field(i) for i in ids]

    def sortkey(self, nid):
        from .expressions import SortKey

        _, flags, _, _, _, _, _, _ = self.f.nodes[nid]
        nulls_first = bool(flags & 4) if flags & 2 else None
        return SortKey(self.expr(self.f.kids(nid)[0]), bool(flags & 1),
                       nulls_first)

    def winspec(self, nid):
        from .expressions import WindowFrameBound, WindowSpec

        _, flags, npart, _, s0, _, _, _ = self.f.nodes[nid]
        kids = list(self.f.kids(nid))
        end_b = kids.pop()
        start_b = kids.pop()
        partition = tuple(self.expr(k) for k in kids[:npart])
        order = tuple(self.sortkey(k) for k in kids[npart:])

        def bound(bid):
            _, bflags, bival, bdval, _, _, _, _ = self.f.nodes[bid]
            kind = _PLAN_FRAME_KINDS[bflags >> 4]
            off = None
            if bflags & 1:
                off = bdval if bflags & 2 else bival
            return WindowFrameBound(kind, off)

        return WindowSpec(partition, order, self.f.s(s0), bound(start_b),
                          bound(end_b), bool(flags & 1))

    def kwvalue(self, nid):
        kind, _, ival, dval, s0, _, _, _ = self.f.nodes[nid]
        if kind == _P_KW_STR:
            return self.f.s(s0)
        if kind == _P_KW_INT:
            return ival
        if kind == _P_KW_FLOAT:
            return dval
        if kind == _P_KW_BOOL:
            return bool(ival)
        if kind == _P_KW_NULL:
            return None
        if kind == _P_KWLIST:
            return [self.kwvalue(k) for k in self.f.kids(nid)]
        if kind == _P_KWARGS:
            return self.kwargs(nid)
        raise ValueError(f"bad kw kind {kind}")

    def kwargs(self, nid):
        out = {}
        for kv in self.f.kids(nid):
            _, _, _, _, s0, _, _, _ = self.f.nodes[kv]
            out[self.f.s(s0)] = self.kwvalue(self.f.kids(kv)[0])
        return out

    def parts(self, ids):
        return [self.f.s(self.f.nodes[i][4]) for i in ids]

    # -------- expressions --------
    def expr(self, nid):
        from ..columnar.dtypes import SqlType
        from .binder import _OuterRef
        from .expressions import (
            AggExpr, CaseExpr, Cast, ColumnRef, ExistsExpr, GroupingExpr,
            InListExpr, InSubqueryExpr, Literal, ScalarFunc,
            ScalarSubqueryExpr, UdfExpr, WindowExpr,
        )

        kind, flags, ival, dval, s0, s1, _, _ = self.f.nodes[nid]
        ty = self.types[flags >> 8]
        kids = self.f.kids(nid)
        if kind == _E_COLREF:
            return ColumnRef(ival, self.f.s(s0), ty, bool(flags & 1))
        if kind == _E_OUTERREF:
            return _OuterRef(ival, self.f.s(s0), ty, bool(flags & 1))
        if kind == _E_LITERAL:
            tag = flags & 0xFF
            if tag == _LT_NULL:
                v = None
            elif tag == _LT_BOOL:
                v = bool(ival)
            elif tag == _LT_INT:
                v = ival
            elif tag == _LT_FLOAT:
                v = dval
            else:
                v = self.f.s(s0)
            return Literal(v, ty)
        if kind == _E_SCALARFN:
            return ScalarFunc(self.f.s(s0),
                              tuple(self.expr(k) for k in kids), ty)
        if kind == _E_AGG:
            has_filter = bool(flags & 2)
            args = kids[:-1] if has_filter else kids
            filt = self.expr(kids[-1]) if has_filter else None
            return AggExpr(self.f.s(s0), tuple(self.expr(k) for k in args),
                           ty, bool(flags & 1), filt)
        if kind == _E_WINDOW:
            spec = self.winspec(kids[-1])
            return WindowExpr(self.f.s(s0),
                              tuple(self.expr(k) for k in kids[:-1]), spec,
                              ty, bool(flags & 1))
        if kind == _E_CAST:
            return Cast(self.expr(kids[0]), ty, bool(flags & 1))
        if kind == _E_CASE:
            has_else = bool(flags & 1)
            body = kids[:-1] if has_else else kids
            whens = tuple((self.expr(body[2 * i]), self.expr(body[2 * i + 1]))
                          for i in range(len(body) // 2))
            else_ = self.expr(kids[-1]) if has_else else None
            return CaseExpr(whens, else_, ty)
        if kind == _E_INLIST:
            return InListExpr(self.expr(kids[0]),
                              tuple(self.expr(k) for k in kids[1:]),
                              bool(flags & 1))
        if kind == _E_INSUBQ:
            return InSubqueryExpr(self.expr(kids[0]), self.plan(kids[1]),
                                  bool(flags & 1))
        if kind == _E_EXISTS:
            return ExistsExpr(self.plan(kids[0]), bool(flags & 1))
        if kind == _E_SCALARSUBQ:
            return ScalarSubqueryExpr(self.plan(kids[0]), ty)
        if kind == _E_UDF:
            return UdfExpr(self.f.s(s0), tuple(self.expr(k) for k in kids),
                           ty, bool(flags & 1))
        if kind == _E_GROUPING:
            return GroupingExpr(tuple(self.expr(k) for k in kids),
                                SqlType.INTEGER)
        raise ValueError(f"bad expr kind {kind}")

    # -------- plans --------
    def plan(self, nid):
        if nid in self.plan_memo:
            return self.plan_memo[nid]
        out = self._plan(nid)
        self.plan_memo[nid] = out
        return out

    def _split(self, ids, kind):
        """(of_kind, rest) preserving order."""
        of_kind = [i for i in ids if self.f.nodes[i][0] == kind]
        rest = [i for i in ids if self.f.nodes[i][0] != kind]
        return of_kind, rest

    def _plan(self, nid):
        from . import plan as p

        kind, flags, ival, dval, s0, s1, _, _ = self.f.nodes[nid]
        kids = list(self.f.kids(nid))
        F = self.f
        if kind == _P_TABLESCAN:
            # optimizer-extended scans: ival = nf when flags bit0 (projection
            # pushed) or bit1 (filters pushed); P_PART kids = projection
            # column names; remaining kids = pushed filter exprs
            if flags & 3:
                nf = ival
                fields = self.fields(kids[:nf])
                rest = kids[nf:]
                parts = [k for k in rest if F.nodes[k][0] == _P_PART]
                fexprs = [k for k in rest if F.nodes[k][0] != _P_PART]
                projection = self.parts(parts) if flags & 1 else None
                return p.TableScan(F.s(s0), F.s(s1), fields, projection,
                                   [self.expr(k) for k in fexprs])
            return p.TableScan(F.s(s0), F.s(s1), self.fields(kids))
        if kind == _P_PROJECTION:
            nf = ival
            return p.Projection(self.plan(kids[0]),
                                [self.expr(k) for k in kids[1 + nf:]],
                                self.fields(kids[1:1 + nf]))
        if kind == _P_FILTER:
            nf = ival
            return p.Filter(self.plan(kids[0]), self.expr(kids[-1]),
                            self.fields(kids[1:1 + nf]))
        if kind == _P_JOIN:
            nf = ival
            has_resid = bool(flags & 1)
            fields = self.fields(kids[2:2 + nf])
            rest = kids[2 + nf:]
            resid = self.expr(rest[-1]) if has_resid else None
            pairs_ids = rest[:-1] if has_resid else rest
            on = [(self.expr(F.kids(pi)[0]), self.expr(F.kids(pi)[1]))
                  for pi in pairs_ids]
            return p.Join(self.plan(kids[0]), self.plan(kids[1]), F.s(s0),
                          on, resid, fields, null_aware=bool(flags & 2))
        if kind == _P_CROSSJOIN:
            return p.CrossJoin(self.plan(kids[0]), self.plan(kids[1]),
                               self.fields(kids[2:]))
        if kind == _P_AGGREGATE:
            nf = ival
            ngroups = flags
            fields = self.fields(kids[1:1 + nf])
            rest = kids[1 + nf:]
            return p.Aggregate(self.plan(kids[0]),
                               [self.expr(k) for k in rest[:ngroups]],
                               [self.expr(k) for k in rest[ngroups:]], fields)
        if kind == _P_WINDOW:
            nf = ival
            return p.Window(self.plan(kids[0]),
                            [self.expr(k) for k in kids[1 + nf:]],
                            self.fields(kids[1:1 + nf]))
        if kind == _P_SORT:
            nf = ival
            fetch = int(dval) if flags & 1 else None
            return p.Sort(self.plan(kids[0]),
                          [self.sortkey(k) for k in kids[1 + nf:]],
                          self.fields(kids[1:1 + nf]), fetch)
        if kind == _P_LIMIT:
            fetch = ival if flags & 1 else None
            skip = int(F.s(s0))
            return p.Limit(self.plan(kids[0]), skip, fetch,
                           self.fields(kids[1:]))
        if kind == _P_UNION:
            nf = ival
            return p.Union([self.plan(k) for k in kids[nf:]], bool(flags & 1),
                           self.fields(kids[:nf]))
        if kind == _P_INTERSECT:
            return p.Intersect(self.plan(kids[0]), self.plan(kids[1]),
                               bool(flags & 1), self.fields(kids[2:]))
        if kind == _P_EXCEPT:
            return p.Except(self.plan(kids[0]), self.plan(kids[1]),
                            bool(flags & 1), self.fields(kids[2:]))
        if kind == _P_DISTINCT:
            return p.Distinct(self.plan(kids[0]), self.fields(kids[1:]))
        if kind == _P_VALUES:
            nf = ival
            rows = [[self.expr(c) for c in F.kids(r)] for r in kids[nf:]]
            return p.Values(rows, self.fields(kids[:nf]))
        if kind == _P_EMPTY:
            return p.EmptyRelation(self.fields(kids), bool(flags & 1))
        if kind == _P_SUBQUERY_ALIAS:
            return p.SubqueryAlias(self.plan(kids[0]), F.s(s0),
                                   self.fields(kids[1:]))
        if kind == _P_SAMPLE:
            seed = ival if flags & 1 else None
            return p.Sample(self.plan(kids[0]), F.s(s0), dval, seed,
                            self.fields(kids[1:]))
        if kind == _P_DISTRIBUTE_BY:
            nf = ival
            return p.DistributeBy(self.plan(kids[0]),
                                  [self.expr(k) for k in kids[1 + nf:]],
                                  self.fields(kids[1:1 + nf]))
        if kind == _P_EXPLAIN:
            return p.Explain(self.plan(kids[0]), self.fields(kids[1:]),
                             bool(flags & 1), bool(flags & 2),
                             bool(flags & 4), bool(flags & 8))
        # ---- DDL / ML custom nodes ----
        ine = bool(flags & 1)
        orr = bool(flags & 2)
        if kind == _P_CREATE_TABLE:
            part_ids, rest = self._split(kids, _P_PART)
            return p.CreateTableNode([], self.parts(part_ids),
                                     self.kwargs(rest[0]), ine, orr)
        if kind == _P_CREATE_MEMORY_TABLE:
            nparts = ival
            return p.CreateMemoryTableNode([], self.parts(kids[:nparts]),
                                           self.plan(kids[nparts]),
                                           bool(flags & 4), ine, orr)
        if kind == _P_DROP_TABLE:
            return p.DropTableNode([], self.parts(kids), bool(flags & 1))
        if kind == _P_CREATE_SCHEMA:
            return p.CreateSchemaNode([], F.s(s0), ine, orr)
        if kind == _P_DROP_SCHEMA:
            return p.DropSchemaNode([], F.s(s0), bool(flags & 1))
        if kind == _P_USE_SCHEMA:
            return p.UseSchemaNode([], F.s(s0))
        if kind == _P_ALTER_SCHEMA:
            return p.AlterSchemaNode([], F.s(s0), F.s(s1))
        if kind == _P_ALTER_TABLE:
            return p.AlterTableNode([], self.parts(kids), F.s(s0),
                                    bool(flags & 1))
        if kind == _P_SHOW_SCHEMAS:
            like = F.s(s0) if flags & 1 else None
            return p.ShowSchemasNode(self.fields(kids), like)
        if kind == _P_SHOW_TABLES:
            sc = F.s(s0) if flags & 1 else None
            return p.ShowTablesNode(self.fields(kids), sc)
        if kind == _P_SHOW_COLUMNS:
            nf = ival
            return p.ShowColumnsNode(self.fields(kids[:nf]),
                                     self.parts(kids[nf:]))
        if kind == _P_SHOW_MODELS:
            sc = F.s(s0) if flags & 1 else None
            return p.ShowModelsNode(self.fields(kids), sc)
        if kind == _P_SHOW_METRICS:
            like = F.s(s0) if flags & 1 else None
            return p.ShowMetricsNode(self.fields(kids), like)
        if kind == _P_SHOW_PROFILES:
            like = F.s(s0) if flags & 1 else None
            return p.ShowProfilesNode(self.fields(kids), like)
        if kind == _P_SHOW_QUERIES:
            like = F.s(s0) if flags & 1 else None
            return p.ShowQueriesNode(self.fields(kids), like)
        if kind == _P_CANCEL_QUERY:
            return p.CancelQueryNode(self.fields(kids), F.s(s0) or "")
        if kind == _P_ANALYZE_TABLE:
            table = [F.s(F.nodes[i][4]) for i in kids if F.nodes[i][1] == 0]
            columns = [F.s(F.nodes[i][4]) for i in kids if F.nodes[i][1] == 1]
            return p.AnalyzeTableNode([], table, columns)
        if kind == _P_CREATE_MODEL:
            nparts = ival
            return p.CreateModelNode([], self.parts(kids[:nparts]),
                                     self.kwargs(kids[nparts]),
                                     self.plan(kids[nparts + 1]), ine, orr)
        if kind == _P_DROP_MODEL:
            return p.DropModelNode([], self.parts(kids), bool(flags & 1))
        if kind == _P_DESCRIBE_MODEL:
            nf = ival
            return p.DescribeModelNode(self.fields(kids[:nf]),
                                       self.parts(kids[nf:]))
        if kind == _P_EXPORT_MODEL:
            nparts = ival
            return p.ExportModelNode([], self.parts(kids[:nparts]),
                                     self.kwargs(kids[nparts]))
        if kind == _P_CREATE_EXPERIMENT:
            nparts = ival
            return p.CreateExperimentNode([], self.parts(kids[:nparts]),
                                          self.kwargs(kids[nparts]),
                                          self.plan(kids[nparts + 1]), ine, orr)
        if kind == _P_PREDICT_MODEL:
            nf = ival
            return p.PredictModelNode(self.fields(kids[1:1 + nf]),
                                      self.parts(kids[1 + nf:]),
                                      self.plan(kids[0]))
        raise ValueError(f"bad plan kind {kind}")


def native_bind(sql: str, catalog, cat_buf: Optional[bytes] = None,
                strict: bool = False):
    """Parse + bind via the C++ binder; returns a LogicalPlan, or None when
    the native path is unavailable / declines (Python binder fallback).
    Raises BindError for genuine bind errors — same exception surface as the
    Python binder.  A native-parser rejection (the Python parser already
    accepted this text upstream) falls back unless `strict`, where it raises
    ParsingException."""
    lib = _get_binder_lib()
    if lib is None:
        return None
    raw = sql.encode("utf-8")
    try:
        if cat_buf is None:
            cat_buf = encode_catalog(catalog)
    except KeyError:  # exotic type in a table/function signature
        return None
    out = ctypes.POINTER(ctypes.c_uint8)()
    out_len = ctypes.c_int64()
    rc = lib.dsql_bind(raw, len(raw), cat_buf, len(cat_buf),
                       ctypes.byref(out), ctypes.byref(out_len))
    if rc == 1:
        return None
    try:
        buf = ctypes.string_at(out, out_len.value) if out_len.value else b""
    finally:
        if out:
            lib.dsql_buf_free(out)
    if rc == 2:
        from .binder import BindError

        msg = buf[1:].decode("utf-8", "replace")
        if buf[:1] == b"\x01":  # missing table/schema: KeyError surface
            raise KeyError(msg)
        raise BindError(msg)
    if rc == 3:
        if not strict:
            return None  # parser lockstep gap: Python binder handles it
        import struct

        from .parser import ParsingException

        pos = struct.unpack_from("<q", buf, 0)[0]
        msg = buf[8:].decode("utf-8", "replace")
        ctx = sql[max(0, pos - 30): pos + 30]
        raise ParsingException(f"{msg} at position {pos} (near {ctx!r})")
    try:
        f = _FlatPlan(buf)
        return _PlanDecoder(f).plan(f.root)
    except Exception:  # dsql: allow-broad-except — corrupt buffer -> Python fallback
        logger.debug("native plan decode failed", exc_info=True)
        return None


# ---------------------------------------------------------------------------
# native planner: parse + bind + structural-optimize in one call
# ---------------------------------------------------------------------------
_planner_checked = False
_planner_ok = False


def _get_planner_lib():
    global _planner_checked, _planner_ok
    lib = _get_binder_lib()
    if lib is None:
        return None
    if not _planner_checked:
        _planner_checked = True
        try:
            lib.dsql_plan.restype = ctypes.c_int32
            lib.dsql_plan.argtypes = [
                ctypes.c_char_p, ctypes.c_int64,
                ctypes.c_char_p, ctypes.c_int64, ctypes.c_int32,
                ctypes.c_int32, ctypes.c_double, ctypes.c_int32,
                ctypes.c_int32, ctypes.c_double,
                ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
                ctypes.POINTER(ctypes.c_int64),
            ]
            lib.dsql_optimizer_abi_version.restype = ctypes.c_int32
            _planner_ok = lib.dsql_optimizer_abi_version() == 6
        except AttributeError:
            _planner_ok = False
    return lib if _planner_ok else None


def native_plan(sql: str, catalog, cat_buf: Optional[bytes] = None,
                predicate_pushdown: bool = True, strict: bool = False,
                reorder: bool = True, fact_dimension_ratio: float = 0.7,
                max_fact_tables: int = 2, preserve_user_order: bool = True,
                filter_selectivity: float = 1.0):
    """Parse + bind + run the core optimizer rule pipeline natively
    (native/binder.cpp Optimizer — the analogue of the reference's compiled
    DataFusion rule loop, optimizer.rs:53-98).  Returns the optimized
    LogicalPlan or None for Python fallback; join reordering / DPP /
    embedded-subquery passes run in Python on the decoded plan."""
    lib = _get_planner_lib()
    if lib is None:
        return None
    raw = sql.encode("utf-8")
    try:
        if cat_buf is None:
            cat_buf = encode_catalog(catalog)
    except KeyError:
        return None
    if cat_buf is None:
        return None
    out = ctypes.POINTER(ctypes.c_uint8)()
    out_len = ctypes.c_int64()
    rc = lib.dsql_plan(raw, len(raw), cat_buf, len(cat_buf),
                       1 if predicate_pushdown else 0,
                       1 if reorder else 0,
                       float(fact_dimension_ratio), int(max_fact_tables),
                       1 if preserve_user_order else 0,
                       float(filter_selectivity),
                       ctypes.byref(out), ctypes.byref(out_len))
    if rc == 1:
        return None
    try:
        buf = ctypes.string_at(out, out_len.value) if out_len.value else b""
    finally:
        if out:
            lib.dsql_buf_free(out)
    if rc == 2:
        from .binder import BindError

        msg = buf[1:].decode("utf-8", "replace")
        if buf[:1] == b"\x01":
            raise KeyError(msg)
        raise BindError(msg)
    if rc == 3:
        if not strict:
            return None
        import struct

        from .parser import ParsingException

        pos = struct.unpack_from("<q", buf, 0)[0]
        msg = buf[8:].decode("utf-8", "replace")
        ctx = sql[max(0, pos - 30): pos + 30]
        raise ParsingException(f"{msg} at position {pos} (near {ctx!r})")
    try:
        f = _FlatPlan(buf)
        return _PlanDecoder(f).plan(f.root)
    except Exception:  # dsql: allow-broad-except — corrupt buffer -> Python fallback
        logger.debug("native plan decode failed", exc_info=True)
        return None
