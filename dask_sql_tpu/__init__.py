"""dask_sql_tpu: a TPU-native distributed SQL engine.

Public surface parity with the reference dask-sql package
(dask_sql/__init__.py there exports Context, run_server, cmd_loop,
Statistics).
"""
import jax as _jax

# SQL needs 64-bit ints/floats end-to-end; enable before any array is made.
_jax.config.update("jax_enable_x64", True)

from .context import Context, TpuFrame  # noqa: E402
from .datacontainer import Statistics  # noqa: E402


def run_server(context=None, **kwargs):  # pragma: no cover - thin wrapper
    from .server.app import run_server as _run

    return _run(context=context, **kwargs)


def cmd_loop(context=None, **kwargs):  # pragma: no cover - thin wrapper
    from .cmd import cmd_loop as _loop

    return _loop(context=context, **kwargs)


__version__ = "0.1.0"
__all__ = ["Context", "TpuFrame", "Statistics", "run_server", "cmd_loop", "__version__"]
