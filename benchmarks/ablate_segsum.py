"""On-chip ablation: where do Q1's kernel seconds go?

Times, on the current backend (axon TPU or CPU), the primitive variants the
compiled aggregate pipeline can be built from, so dtype/strategy choices are
measured rather than guessed:

  scatter segment_sum   x {f32, f64, int32, int64}
  one-hot matmul segsum x {f32, hi/lo double-float, blocked-f64-partials}
  gid radix computation x {int32, int64}
  full Q1-shaped kernel x {current-x64 shapes, int32/f32 shapes}

Run:  python benchmarks/ablate_segsum.py [n_rows]
"""
from __future__ import annotations

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_enable_x64", True)

N = int(sys.argv[1]) if len(sys.argv) > 1 else 6_000_000
DOMAIN = 12
REPS = 5


def timed(name, fn, *args):
    fn_j = jax.jit(fn)
    t0 = time.time()
    out = fn_j(*args)
    jax.block_until_ready(out)
    compile_s = time.time() - t0
    t0 = time.time()
    for _ in range(REPS):
        out = fn_j(*args)
    jax.block_until_ready(out)
    per = (time.time() - t0) / REPS
    print(f"{name:44s} {per*1e3:9.2f} ms   (compile {compile_s:.1f}s)", flush=True)
    return per


def main():
    print("backend:", jax.devices()[0].platform, jax.devices()[0], flush=True)
    rng = np.random.RandomState(0)
    gid_np = rng.randint(0, DOMAIN, N)
    x_np = rng.rand(N)

    gid64 = jnp.asarray(gid_np, dtype=jnp.int64)
    gid32 = jnp.asarray(gid_np, dtype=jnp.int32)
    xf32 = jnp.asarray(x_np, dtype=jnp.float32)
    xf64 = jnp.asarray(x_np, dtype=jnp.float64)
    xi32 = jnp.asarray((x_np * 100).astype(np.int32))
    xi64 = jnp.asarray((x_np * 100).astype(np.int64))
    jax.block_until_ready((gid64, gid32, xf32, xf64, xi32, xi64))

    # -- scatter segment_sum by dtype --------------------------------------
    for name, x, g in [("scatter f32/gid32", xf32, gid32),
                       ("scatter f32/gid64", xf32, gid64),
                       ("scatter f64/gid32", xf64, gid32),
                       ("scatter i32/gid32", xi32, gid32),
                       ("scatter i64/gid32", xi64, gid32),
                       ("scatter i64/gid64", xi64, gid64)]:
        timed(name, lambda a, b: jax.ops.segment_sum(a, b, DOMAIN), x, g)

    # -- one-hot matmul variants -------------------------------------------
    def onehot_f32(g, x):
        oh = jax.nn.one_hot(g, DOMAIN, dtype=jnp.float32)
        return oh.T @ x

    timed("onehot-matmul f32 [n,1]", onehot_f32, gid32, xf32[:, None])

    def onehot_hilo(g, x):
        hi = x.astype(jnp.float32)
        lo = (x - hi.astype(jnp.float64)).astype(jnp.float32)
        st = jnp.stack([hi, lo], axis=1)
        oh = jax.nn.one_hot(g, DOMAIN, dtype=jnp.float32)
        out = oh.T @ st
        return out[:, 0].astype(jnp.float64) + out[:, 1].astype(jnp.float64)

    timed("onehot-matmul hi/lo f64-in", onehot_hilo, gid32, xf64)

    def onehot_blocked(g, x, b=65536):
        npad = ((N + b - 1) // b) * b
        gp = jnp.zeros(npad, jnp.int32).at[:N].set(g)
        hp = jnp.zeros(npad, jnp.float32).at[:N].set(x.astype(jnp.float32))
        lp = jnp.zeros(npad, jnp.float32).at[:N].set(
            (x - x.astype(jnp.float32).astype(jnp.float64)).astype(jnp.float32))
        nb = npad // b
        gb = gp.reshape(nb, b)
        sb = jnp.stack([hp, lp], axis=1).reshape(nb, b, 2)
        oh = jax.nn.one_hot(gb, DOMAIN, dtype=jnp.float32)  # [nb, b, d]
        part = jax.lax.dot_general(
            oh, sb, dimension_numbers=(((1,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)  # [nb, d, 2]
        tot = part.astype(jnp.float64).sum(axis=0)
        return tot[:, 0] + tot[:, 1]

    timed("onehot-matmul blocked hi/lo", onehot_blocked, gid32, xf64)

    # accuracy of the variants vs exact f64 (numpy) -------------------------
    exact = np.zeros(DOMAIN)
    np.add.at(exact, gid_np, x_np)
    for name, fn in [("scatter f32", lambda: np.asarray(
                        jax.ops.segment_sum(xf32, gid32, DOMAIN), dtype=np.float64)),
                     ("scatter f64", lambda: np.asarray(
                        jax.ops.segment_sum(xf64, gid32, DOMAIN))),
                     ("onehot hi/lo", lambda: np.asarray(onehot_hilo(gid32, xf64))),
                     ("onehot blocked hi/lo", lambda: np.asarray(
                        jax.jit(onehot_blocked)(gid32, xf64)))]:
        got = fn()
        rel = np.max(np.abs(got - exact) / np.maximum(np.abs(exact), 1e-30))
        print(f"accuracy {name:32s} max-rel-err {rel:.3e}", flush=True)

    # -- gid radix computation ---------------------------------------------
    codes1 = jnp.asarray(rng.randint(0, 4, N), dtype=jnp.int64)
    codes2 = jnp.asarray(rng.randint(0, 3, N), dtype=jnp.int64)

    def gid_i64(a, b):
        return jnp.clip(a, 0, 3) * 3 + jnp.clip(b, 0, 2)

    def gid_i32(a, b):
        return (jnp.clip(a, 0, 3) * 3 + jnp.clip(b, 0, 2)).astype(jnp.int32)

    timed("gid radix int64", gid_i64, codes1, codes2)
    timed("gid radix int32->", gid_i32,
          codes1.astype(jnp.int32), codes2.astype(jnp.int32))

    # -- Q1-shaped kernels --------------------------------------------------
    ship = jnp.asarray(rng.randint(0, 2526, N) * 86_400_000_000_000, dtype=jnp.int64)
    qty = jnp.asarray(rng.randint(1, 51, N).astype(np.float32))
    price = jnp.asarray((rng.rand(N) * 1e5).astype(np.float32))
    disc = jnp.asarray((rng.rand(N) * 0.1).astype(np.float32))
    tax = jnp.asarray((rng.rand(N) * 0.08).astype(np.float32))
    cutoff = jnp.int64(2430 * 86_400_000_000_000)

    def q1_current(ship, qty, price, disc, tax, g1, g2):
        sel = ship <= cutoff
        gid = jnp.clip(g1.astype(jnp.int64), 0, 3) * 3 + jnp.clip(
            g2.astype(jnp.int64), 0, 2)
        dp = price * (1 - disc)
        ch = dp * (1 + tax)
        outs = [jax.ops.segment_sum(sel.astype(jnp.int32), gid, DOMAIN)]
        for col in (qty, price, dp, ch, disc):
            cnt = jax.ops.segment_sum(sel.astype(jnp.int64), gid, DOMAIN)
            s = jax.ops.segment_sum(jnp.where(sel, col, 0.0), gid, DOMAIN)
            outs.append(s)
            outs.append(cnt)
        return tuple(outs)

    def q1_lean(ship, qty, price, disc, tax, g1, g2):
        sel = ship <= cutoff
        gid = (jnp.clip(g1, 0, 3) * 3 + jnp.clip(g2, 0, 2)).astype(jnp.int32)
        dp = price * (1 - disc)
        ch = dp * (1 + tax)
        cnt = jax.ops.segment_sum(sel.astype(jnp.float32), gid, DOMAIN)
        outs = [cnt]
        for col in (qty, price, dp, ch, disc):
            s = jax.ops.segment_sum(jnp.where(sel, col, 0.0), gid, DOMAIN)
            outs.append(s)
        return tuple(outs)

    def q1_matmul(ship, qty, price, disc, tax, g1, g2):
        sel = ship <= cutoff
        gid = (jnp.clip(g1, 0, 3) * 3 + jnp.clip(g2, 0, 2)).astype(jnp.int32)
        dp = price * (1 - disc)
        ch = dp * (1 + tax)
        cols = jnp.stack([sel.astype(jnp.float32)]
                         + [jnp.where(sel, c, 0.0) for c in (qty, price, dp, ch, disc)],
                         axis=1)
        oh = jax.nn.one_hot(gid, DOMAIN, dtype=jnp.float32)
        return oh.T @ cols

    g1 = jnp.asarray(rng.randint(0, 3, N), dtype=jnp.int32)
    g2 = jnp.asarray(rng.randint(0, 2, N), dtype=jnp.int32)
    args = (ship, qty, price, disc, tax, g1.astype(jnp.int64), g2.astype(jnp.int64))
    args32 = (ship, qty, price, disc, tax, g1, g2)
    timed("Q1 kernel current (i64 cnt x5, i64 gid)", q1_current, *args)
    timed("Q1 kernel lean (f32 scatter, i32 gid)", q1_lean, *args32)
    timed("Q1 kernel matmul (one-hot, 6 cols)", q1_matmul, *args32)

    # -- pallas compile probe ----------------------------------------------
    try:
        sys.path.insert(0, ".")
        from dask_sql_tpu.ops.pallas_kernels import segsum_pallas

        t0 = time.time()
        out = segsum_pallas(gid32[:1 << 20], xf32[:1 << 20, None], DOMAIN)
        jax.block_until_ready(out)
        print(f"pallas segsum COMPILED+RAN in {time.time()-t0:.1f}s", flush=True)
    except Exception as e:  # noqa: BLE001
        print(f"pallas segsum FAILED: {type(e).__name__}: {str(e)[:300]}", flush=True)


if __name__ == "__main__":
    main()
