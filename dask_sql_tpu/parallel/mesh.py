"""Device mesh management.

Role parity: the reference's dask.distributed Client/cluster handle
(SURVEY.md §2.4) — here a `jax.sharding.Mesh` over TPU chips, with row-block
sharding of columnar tables.  Within a slice collectives ride ICI; across
slices XLA routes them over DCN — the comm backend is XLA itself, no NCCL/MPI
translation layer.
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXIS = "shards"

_default_mesh: Optional[Mesh] = None


def make_mesh(n_devices: Optional[int] = None, devices=None) -> Mesh:
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            devices = devices[:n_devices]
    return Mesh(np.array(devices), (AXIS,))


def default_mesh() -> Mesh:
    global _default_mesh
    if _default_mesh is None:
        _default_mesh = make_mesh()
    return _default_mesh


def set_default_mesh(mesh: Mesh) -> None:
    global _default_mesh
    _default_mesh = mesh


def row_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P(AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def pad_to_multiple(arr: jnp.ndarray, multiple: int, fill=0):
    """Pad a 1-D array so its length divides the shard count; returns
    (padded, valid_mask)."""
    n = arr.shape[0]
    target = ((n + multiple - 1) // multiple) * multiple
    if target == n:
        return arr, jnp.ones(n, dtype=bool)
    pad = target - n
    padded = jnp.concatenate([arr, jnp.full((pad,), fill, dtype=arr.dtype)])
    valid = jnp.concatenate([jnp.ones(n, dtype=bool), jnp.zeros(pad, dtype=bool)])
    return padded, valid


def shard_rows(arr: jnp.ndarray, mesh: Optional[Mesh] = None):
    """Place a row-padded array with row-block sharding over the mesh."""
    mesh = mesh or default_mesh()
    return jax.device_put(arr, row_sharding(mesh))
