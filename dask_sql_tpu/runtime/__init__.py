"""Process-runtime primitives shared by every layer.

This package sits BELOW observability/serving/resilience in the import
graph (stdlib-only imports at module scope), so the lock sanitizer can
wrap the flight recorder's and metrics registry's own locks without a
cycle.  `locks` is the runtime tier of the ISSUE 19 concurrency suite;
the static tier lives in analysis/concurrency.py.
"""
from . import locks  # noqa: F401
from .locks import (DECLARED_RANKS, LockOrderError, NamedLock,  # noqa: F401
                    named_condition, named_lock)
