"""sqlite-based differential oracle for the TPC-DS q1-q99 corpus.

Parity: the reference value-checks its feature corpus against live engines
(reference tests/integration/test_postgres.py:13-53 and
tests/integration/test_compatibility.py eq_sqlite) — this module does the
same for the flagship TPC-DS suite using the stdlib sqlite3 (>= 3.39:
window functions, FULL JOIN, INTERSECT/EXCEPT are native).

Dialect gap handling:
- dates are loaded as ISO text ('YYYY-MM-DD' when day-resolution), and
  ``cast('X' as date)`` folds to the text literal, so comparisons match;
- ``a + interval 'N' day`` becomes ``date(a, '+N days')``;
- STDDEV_SAMP is registered as a python aggregate;
- ``GROUP BY ROLLUP(c1..ck)`` expands to a UNION ALL of the k+1 grouping
  levels (grouped-out columns become NULL, ``GROUPING(c)`` becomes the
  level's 0/1 constant).  Window functions in those queries partition by
  the grouping level, so evaluating them per-branch is equivalent.
"""
from __future__ import annotations

import math
import re
import sqlite3
from typing import Dict, Optional

import numpy as np
import pandas as pd


# ----------------------------------------------------------- sqlite loading
class _Stddev:
    """Sample standard deviation aggregate (sqlite has none built in)."""

    def __init__(self):
        self.vals = []

    def step(self, v):
        if v is not None:
            self.vals.append(float(v))

    def finalize(self):
        n = len(self.vals)
        if n < 2:
            return None
        mean = sum(self.vals) / n
        var = sum((x - mean) ** 2 for x in self.vals) / (n - 1)
        return math.sqrt(var)


def make_sqlite(tables: Dict[str, pd.DataFrame]) -> sqlite3.Connection:
    conn = sqlite3.connect(":memory:")
    conn.create_aggregate("stddev_samp", 1, _Stddev)
    conn.create_aggregate("stddev", 1, _Stddev)
    for name, df in tables.items():
        out = df.copy()
        for col in out.columns:
            s = out[col]
            if s.dtype.kind == "M":
                day_res = s.dropna().eq(s.dropna().dt.normalize()).all()
                fmt = "%Y-%m-%d" if day_res else "%Y-%m-%d %H:%M:%S"
                out[col] = s.dt.strftime(fmt)
        out.to_sql(name, conn, index=False)
    return conn


# ----------------------------------------------------------- duckdb oracle
def duckdb_available() -> bool:
    """True when the optional second oracle can run (duckdb importable).

    The reference differentially tests against a live PostgreSQL container
    on top of sqlite (reference tests/integration/test_postgres.py:13-53);
    this image has no docker and no duckdb wheel, so the dual-oracle mode
    gates on import and activates wherever duckdb is present."""
    try:
        import duckdb  # noqa: F401

        return True
    except ImportError:
        return False


def make_duckdb(tables: Dict[str, pd.DataFrame]):
    """In-memory duckdb connection with every frame registered as a view.

    duckdb speaks the TPC-DS dialect natively (INTERVAL arithmetic, ROLLUP,
    GROUPING SETS, the shapes sqlite cannot parse), so no translation layer
    is needed — the query text runs as-is."""
    import duckdb

    conn = duckdb.connect(":memory:")
    for name, df in tables.items():
        conn.register(name, df)
    return conn


def duckdb_query(conn, sql: str) -> pd.DataFrame:
    return conn.execute(sql).df()


#: error substrings that mean the ORACLE ENGINE cannot run the query at
#: all — a test-infrastructure capability gap, not an engine result diff.
#: sqlite grew FULL/RIGHT OUTER JOIN only in 3.39 (2022-06); older images
#: (this container ships 3.34) refuse the q51/q97 shapes outright, so the
#: pre-PR-3 "q51/q97 sqlite-oracle diffs" were never engine bugs.
ORACLE_CAPABILITY_ERRORS = (
    "RIGHT and FULL OUTER JOINs are not currently supported",
)


def cross_check(got: pd.DataFrame, oracles, sql: str, qnum,
                rtol: float = 1e-4, inf_is_null: bool = False):
    """Assert `got` matches EVERY available oracle; an engine result that
    satisfies one oracle but not another surfaces as a failure naming the
    disagreeing oracle (VERDICT r4 #7 dual-oracle mode).

    An oracle that cannot PARSE/RUN the query (ORACLE_CAPABILITY_ERRORS)
    drops out instead of failing; if no capable oracle remains the test
    skips with the root cause — an xfail here would go stale the moment
    the image ships a newer sqlite, and the engine result is simply
    uncheckable, not wrong.

    `oracles` is a list of ("name", callable sql -> DataFrame) pairs."""
    import sqlite3

    failures = []
    incapable = []
    for name, run in oracles:
        try:
            expected = run(sql)
        except Exception as e:  # oracle itself failed: attribute, keep going
            msg = f"{type(e).__name__}: {e}"
            if any(cap in msg for cap in ORACLE_CAPABILITY_ERRORS):
                incapable.append(name)
                continue
            failures.append(f"[{name}] oracle errored: {msg}")
            continue
        try:
            assert_same_result(got, expected, qnum, rtol=rtol,
                               inf_is_null=inf_is_null)
        except AssertionError as e:
            failures.append(f"[{name}] {e}")
    if failures:
        raise AssertionError(
            f"q{qnum}: engine result disagrees with "
            f"{len(failures)}/{len(oracles)} oracles:\n" + "\n".join(failures))
    if incapable and len(incapable) == len(oracles):
        import pytest

        pytest.skip(
            f"q{qnum}: no capable oracle — {', '.join(incapable)} cannot run "
            f"this shape (sqlite {sqlite3.sqlite_version} predates FULL "
            f"OUTER JOIN support, added in 3.39); engine executed fine but "
            f"the result is uncheckable here")


# ----------------------------------------------------------- translation
def _depth0_positions(sql: str, word: str):
    """Start offsets of `word` occurring at paren depth 0."""
    out, depth = [], 0
    low = sql.lower()
    w = word.lower()
    i = 0
    while i < len(sql):
        ch = sql[i]
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
        elif depth == 0 and low.startswith(w, i) and (
                i == 0 or not low[i - 1].isalnum()) and (
                i + len(w) >= len(low) or not low[i + len(w)].isalnum()):
            out.append(i)
            i += len(w)
            continue
        i += 1
    return out


def _expand_rollup(sql: str) -> Optional[str]:
    m = re.search(r"group\s+by\s+rollup\s*\(([^)]*)\)", sql, re.I)
    if m is None:
        return sql
    cols = [c.strip() for c in m.group(1).split(",")]
    if not all(re.fullmatch(r"[A-Za-z_][A-Za-z0-9_.]*", c) for c in cols):
        return None
    # the rollup belongs to the last depth-0 SELECT before it (earlier ones
    # are WITH-clause CTEs, which stay in `prefix` untouched)
    sels = [p for p in _depth0_positions(sql, "select") if p < m.start()]
    if not sels:
        return None
    sel = sels[-1]
    froms = [p for p in _depth0_positions(sql, "from")
             if sel < p < m.start()]
    if not froms:
        return None
    prefix = sql[:sel]
    select_list = sql[sel + len("select"):froms[0]]
    body = sql[froms[0]:m.start()]
    tail = sql[m.end():]
    if re.search(r"group\s+by|rollup", tail, re.I):
        return None  # only the single-rollup shape is supported

    items = _split_top_level(select_list)
    branches = []
    for level in range(len(cols), -1, -1):
        kept, dropped = cols[:level], cols[level:]
        branch_items = []
        for item in items:
            expr, alias = _split_alias(item)
            for c in kept:
                expr = re.sub(r"grouping\s*\(\s*%s\s*\)" % re.escape(c),
                              "0", expr, flags=re.I)
            for c in dropped:
                expr = re.sub(r"grouping\s*\(\s*%s\s*\)" % re.escape(c),
                              "1", expr, flags=re.I)
                expr = re.sub(r"\b%s\b" % re.escape(c), "null", expr)
            if alias is None and expr.strip() == "null":
                alias = item.strip()  # bare rolled-out column keeps its name
            branch_items.append(expr + (f" as {alias}" if alias else ""))
        branch = "select " + ", ".join(branch_items) + " " + body
        if kept:
            branch += " group by " + ", ".join(kept)
        branches.append(branch)
    return (prefix + "select * from (" + " union all ".join(branches)
            + ") " + tail)


def _split_top_level(s: str):
    items, depth, cur = [], 0, []
    for ch in s:
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
        if ch == "," and depth == 0:
            items.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    items.append("".join(cur))
    return [i.strip() for i in items if i.strip()]


def _split_alias(item: str):
    m = re.search(r"\s+as\s+([A-Za-z_][A-Za-z0-9_]*)\s*$", item, re.I)
    if m:
        return item[: m.start()], m.group(1)
    return item, None


#: targeted dialect patches (applied before the generic rewrites):
#: sqlite refuses ORDER BY on an output alias that also names source columns
#: ("ambiguous column name") where the standard prefers the alias — use
#: ordinal positions for the affected queries.
_PATCHES = [
    ("order by item_id, ss_item_rev", "order by 1, 2"),
]


def translate(sql: str) -> Optional[str]:
    """TPC-DS dialect -> sqlite, or None when no faithful translation exists."""
    out = sql
    for old, new in _PATCHES:
        out = out.replace(old, new)
    # cast('X' as date) -> 'X'  (dates live as ISO text in the oracle db)
    out = re.sub(r"cast\s*\(\s*('[^']*')\s+as\s+date\s*\)", r"\1", out,
                 flags=re.I)
    # a + interval 'N' day -> date(a, '+N days')
    out = re.sub(
        r"([A-Za-z_][A-Za-z0-9_.]*)\s*\+\s*interval\s*'(\d+)'\s*day",
        r"date(\1, '+\2 days')", out, flags=re.I)
    if re.search(r"\binterval\b", out, re.I):
        return None
    if re.search(r"grouping\s+sets|\bcube\s*\(", out, re.I):
        return None
    # sqlite rejects parenthesized compound-select operands:
    # ((A) except (B)) -> ((A except B))
    out = re.sub(r"\)\s*(union\s+all|union|intersect|except)\s*\(",
                 r" \1 ", out, flags=re.I)
    out = _expand_rollup(out)
    return out


def strip_top_limit(sql: str) -> str:
    """Drop a trailing top-level LIMIT for value comparison: when ORDER BY
    keys tie at the cut, engines legitimately pick different rows — the
    un-limited multiset is the well-defined comparand."""
    return re.sub(r"\blimit\s+\d+\s*$", "", sql.rstrip(), flags=re.I)


# ----------------------------------------------------------- comparison
def _normalize(df: pd.DataFrame) -> pd.DataFrame:
    out = pd.DataFrame()
    for i, col in enumerate(df.columns):
        s = df[col]
        if s.dtype.kind == "M":
            s = s.dt.strftime("%Y-%m-%d")
        elif s.dtype == object:
            s = s.map(lambda v: None if v is None or (isinstance(v, float)
                                                      and np.isnan(v)) else str(v))
        out[i] = s
    return out


def assert_same_result(got: pd.DataFrame, exp: pd.DataFrame, qnum,
                       rtol: float = 1e-4, inf_is_null: bool = False):
    """Order-insensitive equality of two result frames.

    Both frames are normalized (datetimes to ISO text, objects to str) and
    sorted by every column; numerics compare with `rtol` (the matmul segsum
    path documents a ~5e-6 relative float bound).  `inf_is_null` folds ±inf
    to NULL first: division by zero is NULL in sqlite but ±inf in the
    engine (pandas parity, matching the reference's behavior)."""
    if inf_is_null:
        got = got.copy()
        for col in got.columns:
            if got[col].dtype.kind == "f":
                got[col] = got[col].replace([np.inf, -np.inf], np.nan)
    assert len(got.columns) == len(exp.columns), (
        f"q{qnum}: column count {len(got.columns)} != oracle {len(exp.columns)}")
    assert len(got) == len(exp), (
        f"q{qnum}: row count {len(got)} != oracle {len(exp)}")
    if len(got) == 0:
        return
    g = _normalize(got)
    e = _normalize(exp)

    def sortkey(df):
        key = df.copy()
        for c in key.columns:
            v = key[c]
            if v.dtype.kind == "f":
                key[c] = v.round(6)
            key[c] = key[c].map(lambda x: "\x00" if x is None or
                                (isinstance(x, float) and np.isnan(x)) else str(x))
        return df.loc[key.sort_values(list(key.columns)).index].reset_index(drop=True)

    g = sortkey(g)
    e = sortkey(e)
    for c in g.columns:
        gv, ev = g[c], e[c]
        g_num = pd.to_numeric(gv, errors="coerce")
        e_num = pd.to_numeric(ev, errors="coerce")
        if g_num.notna().equals(e_num.notna()) and g_num.notna().any():
            both = g_num.notna()
            np.testing.assert_allclose(
                g_num[both].astype(float), e_num[both].astype(float),
                rtol=rtol, atol=1e-6, err_msg=f"q{qnum} col#{c}")
            assert (list(gv[~both].map(_isnull))
                    == list(ev[~both].map(_isnull))), (
                f"q{qnum} col#{c}: NULL placement differs")
        else:
            assert list(gv.map(_nullstr)) == list(ev.map(_nullstr)), (
                f"q{qnum} col#{c}: values differ")


def _isnull(v) -> bool:
    return v is None or (isinstance(v, float) and np.isnan(v))


def _nullstr(v):
    return None if _isnull(v) else str(v)
