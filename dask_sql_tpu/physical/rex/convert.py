"""Rex converter: typed Expr -> device Column against an input Table.

Role parity: reference RexConverter plugin registry (physical/rex/convert.py
there, _REX_TYPE_TO_PLUGIN convert.py:16-22) with one plugin per expression
kind (core/input_ref.py, literal.py, call.py, alias.py, subquery.py).  Here
the registry is keyed by IR class; kernels come from OPERATION_MAPPING.
"""
from __future__ import annotations

from typing import Callable, Dict, Type

import jax.numpy as jnp
import numpy as np

from ...columnar.column import Column
from ...columnar.dtypes import STRING_TYPES, SqlType, sql_to_np
from ...columnar.table import Table
from ...planner.expressions import (
    CaseExpr,
    Cast,
    ColumnRef,
    ExistsExpr,
    Expr,
    InArrayExpr,
    InListExpr,
    InSubqueryExpr,
    Literal,
    ScalarFunc,
    ScalarSubqueryExpr,
    UdfExpr,
)
from ...ops.membership import (
    dictionary_membership,
    sorted_membership,
    vectorizable_literal_items,
)
from .operations import OPERATION_MAPPING, _and_validity, _merged_for_compare


def _bulk_membership(arg: Column, values) -> jnp.ndarray:
    """Vectorized `arg IN values` (bool device array; NULL handling is the
    caller's)."""
    if arg.sql_type in STRING_TYPES:
        return dictionary_membership(arg.data, arg.dictionary, values)
    return sorted_membership(arg.data, values)


class RexConverter:
    """Evaluates bound expressions over a Table.  `executor` supplies
    subquery execution and UDF lookup (the physical rel executor)."""

    def __init__(self, executor=None):
        self.executor = executor
        self._plugins: Dict[Type, Callable] = {
            ColumnRef: self._input_ref,
            Literal: self._literal,
            ScalarFunc: self._call,
            Cast: self._cast,
            CaseExpr: self._case,
            InListExpr: self._in_list,
            InArrayExpr: self._in_array,
            ScalarSubqueryExpr: self._scalar_subquery,
            InSubqueryExpr: self._in_subquery,
            ExistsExpr: self._exists,
            UdfExpr: self._udf,
        }

    def convert(self, expr: Expr, table: Table) -> Column:
        plugin = self._plugins.get(type(expr))
        if plugin is None:
            for klass, pl in self._plugins.items():
                if isinstance(expr, klass):
                    plugin = pl
                    break
        if plugin is None:
            raise NotImplementedError(f"No rex plugin for {type(expr).__name__}")
        return plugin(expr, table)

    # -- plugins ------------------------------------------------------------
    def _input_ref(self, expr: ColumnRef, table: Table) -> Column:
        # parity: core/input_ref.py — positional backend lookup
        if type(expr).__name__ == "_OuterRef":
            raise NotImplementedError(
                "Correlated subquery was not decorrelated; this shape is unsupported")
        name = table.column_names[expr.index]
        return table.columns[name]

    def _literal(self, expr: Literal, table: Table) -> Column:
        n = max(table.num_rows, 1) if table is not None else 1
        col = _literal_column(expr, table.num_rows if table is not None else 1)
        return col

    def _call(self, expr: ScalarFunc, table: Table) -> Column:
        fn = OPERATION_MAPPING.get(expr.op)
        if fn is None:
            raise NotImplementedError(f"No kernel for op {expr.op!r}")
        args = [self.convert(a, table) for a in expr.args]
        if not args:
            return fn(length=max(table.num_rows, 0))
        out = fn(*args)
        # trust the planner's result type when it differs benignly
        return out

    def _cast(self, expr: Cast, table: Table) -> Column:
        col = self.convert(expr.arg, table)
        return col.cast(expr.sql_type)

    def _case(self, expr: CaseExpr, table: Table) -> Column:
        target = expr.sql_type
        if expr.else_ is not None:
            out = self.convert(expr.else_, table).cast(target)
        else:
            out = Column.from_scalar(None, table.num_rows, target)
        if target in STRING_TYPES:
            # strings: materialize on host (dictionaries differ per branch)
            res = out.to_numpy()
            for cond, val in reversed(expr.whens):
                c = self.convert(cond, table)
                v = self.convert(val, table).cast(target).to_numpy()
                mask = np.asarray(c.data & c.valid_mask())
                res[mask] = v[mask]
            return Column.from_numpy(res)
        for cond, val in reversed(expr.whens):
            c = self.convert(cond, table)
            v = self.convert(val, table).cast(target)
            take = c.data & c.valid_mask()
            data = jnp.where(take, v.data, out.data)
            validity = jnp.where(take, v.valid_mask(), out.valid_mask())
            out = Column(data, target, None if bool(validity.all()) else validity)
        return out

    def _in_array(self, expr: InArrayExpr, table: Table) -> Column:
        arg = self.convert(expr.arg, table)
        hits = _bulk_membership(arg, expr.values)
        value = hits if not expr.negated else ~hits
        return Column(value, SqlType.BOOLEAN, arg.validity)

    def _in_list(self, expr: InListExpr, table: Table) -> Column:
        arg = self.convert(expr.arg, table)
        # bulk literal lists: one vectorized membership op instead of a
        # per-item comparison chain (which traces O(items) jnp ops)
        if vectorizable_literal_items(expr.items):
            vals = np.asarray([it.value for it in expr.items])
            hits = _bulk_membership(arg, vals)
            value = hits if not expr.negated else ~hits
            return Column(value, SqlType.BOOLEAN, arg.validity)
        hits = None
        any_null_item = False
        for item in expr.items:
            ic = self.convert(item, table)
            if isinstance(item, Literal) and item.value is None:
                any_null_item = True
                continue
            da, db = _merged_for_compare(arg, ic)
            h = (da == db) & ic.valid_mask()
            hits = h if hits is None else (hits | h)
        if hits is None:
            hits = jnp.zeros(len(arg), dtype=bool)
        # SQL 3VL: x IN (...) is NULL when no hit and (x is NULL or list has NULL)
        known = arg.valid_mask() & (hits | (not any_null_item))
        value = hits if not expr.negated else ~hits
        validity = None if bool(known.all()) else known
        return Column(value, SqlType.BOOLEAN, validity)

    def _scalar_subquery(self, expr: ScalarSubqueryExpr, table: Table) -> Column:
        sub = self.executor.execute(expr.plan)
        if sub.num_rows == 0:
            return Column.from_scalar(None, table.num_rows, expr.sql_type)
        col = sub.columns[sub.column_names[0]]
        first = col.slice(0, 1)
        # broadcast the scalar
        data = jnp.broadcast_to(first.data, (table.num_rows,))
        validity = None
        if first.validity is not None:
            validity = jnp.broadcast_to(first.validity, (table.num_rows,))
        return Column(data, col.sql_type, validity, col.dictionary)

    def _in_subquery(self, expr: InSubqueryExpr, table: Table) -> Column:
        from ...ops.join import join_key_gids, semi_join_mask

        arg = self.convert(expr.arg, table)
        sub = self.executor.execute(expr.plan)
        sub_col = sub.columns[sub.column_names[0]]
        lgid, rgid = join_key_gids([arg], [sub_col])
        mask = semi_join_mask(lgid, rgid)
        value = ~mask if expr.negated else mask
        # 3VL: NULL when not matched and (arg null or subquery contains null)
        sub_has_null = bool(sub_col.has_nulls)
        known = arg.valid_mask() & (mask | (not sub_has_null))
        return Column(value, SqlType.BOOLEAN, None if bool(known.all()) else known)

    def _exists(self, expr: ExistsExpr, table: Table) -> Column:
        sub = self.executor.execute(expr.plan)
        exists = sub.num_rows > 0
        val = (not exists) if expr.negated else exists
        return Column.from_scalar(val, table.num_rows, SqlType.BOOLEAN)

    def _udf(self, expr: UdfExpr, table: Table) -> Column:
        fd = self.executor.lookup_function(expr.name)
        args = [self.convert(a, table) for a in expr.args]
        if fd.row_udf:
            # row UDF: pandas-style row dicts on host (reference UDF wrapper,
            # datacontainer.py:234-270 there).  Row-wise host loops are the
            # longest single-node stretch of a plan, so the serving ticket is
            # polled per row — a cancel/deadline takes effect mid-UDF instead
            # of after the whole column is computed.
            import pandas as pd

            from ...serving.runtime import current_ticket

            ticket = current_ticket()

            def _call(row):
                if ticket is not None:
                    ticket.checkpoint()
                return fd.func(row)

            frame = pd.DataFrame({f"arg{i}": a.to_numpy() for i, a in enumerate(args)})
            frame.columns = [p[0] for p in fd.parameters][: len(args)]
            out = frame.apply(_call, axis=1).to_numpy()
            col = Column.from_numpy(np.asarray(out))
        else:
            out = fd.func(*[a.data for a in args])
            col = Column(jnp.asarray(out), fd.return_type, _and_validity(*args))
        return col.cast(fd.return_type) if col.sql_type != fd.return_type else col


def _literal_column(expr: Literal, length: int) -> Column:
    v = expr.value
    st = expr.sql_type
    length = max(length, 0)
    if v is None:
        col = Column.from_scalar(None, length, st if st != SqlType.NULL else SqlType.DOUBLE)
        return col
    if st in STRING_TYPES:
        col = Column(jnp.zeros(length, dtype=jnp.int32), st, None,
                     np.array([v], dtype=object))
    elif st in (SqlType.TIMESTAMP, SqlType.DATE, SqlType.TIME,
                SqlType.INTERVAL_DAY_TIME, SqlType.INTERVAL_YEAR_MONTH):
        col = Column(jnp.full(length, int(v), dtype=jnp.int64), st)
    elif st == SqlType.BOOLEAN:
        col = Column(jnp.full(length, bool(v), dtype=jnp.bool_), st)
    else:
        col = Column(jnp.full(length, v, dtype=sql_to_np(st)), st)
    object.__setattr__(col, "_lit_value", v)
    return col
