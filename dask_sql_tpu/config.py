"""Configuration system.

Role parity: reference piggybacks on dask.config with `sql.yaml` defaults +
`sql-schema.yaml` docs (config.py:1-12 there).  Self-contained here: a
process-global nested config with the same `sql.*` keys, `set()` context
manager for per-query overrides (Context.sql(config_options=...)).

The `serving.*` keys configure the serving runtime (serving/): worker-pool
size, per-class admission queue bounds and the batch running cap, default
query deadline + retry-after floor for load shedding, and the result cache
(enabled / byte budget / per-entry cap / TTL).  Each key's default below
carries an inline doc comment; docs/serving.md has the full semantics.
"""
from __future__ import annotations

import contextlib
import logging
import threading
from typing import Any, Dict, NamedTuple, Optional, Tuple

DEFAULTS: Dict[str, Any] = {
    # parity: dask_sql/sql.yaml keys
    "sql.aggregate.split_out": 1,  # dsql: allow-config-key — dask-sql parity key, reserved
    "sql.aggregate.split_every": None,  # dsql: allow-config-key — dask-sql parity key, reserved
    "sql.identifier.case_sensitive": True,
    "sql.join.broadcast": None,  # None=auto, False=never, number=row threshold
    "sql.limit.check-first-partition": True,  # dsql: allow-config-key — dask-sql parity key, reserved
    "sql.optimize": True,
    "sql.predicate_pushdown": True,
    "sql.dynamic_partition_pruning": True,
    "sql.optimizer.verbose": False,
    "sql.optimizer.fact_dimension_ratio": 0.7,
    "sql.optimizer.max_fact_tables": 2,
    "sql.optimizer.preserve_user_order": True,
    "sql.optimizer.filter_selectivity": 1.0,
    "sql.sort.topk-nelem-limit": 1000000,
    "sql.mappings.decimal_support": "float64",  # dsql: allow-config-key — dask-sql parity key, reserved
    # TPU-native additions
    "sql.backend.default": "tpu",  # dsql: allow-config-key — dask-sql parity key, reserved
    "sql.shuffle.num_buckets": None,  # None = number of devices; dsql: allow-config-key — dask-sql parity key, reserved
    "sql.native.binder": "auto",  # C++ parse+bind (auto|on|off)
    "sql.compile": True,  # whole-pipeline jit for hot aggregation shapes
    "sql.compile.join": "auto",  # jit the shape-stable join probe phase
    "sql.compile.select": True,  # one-kernel root select chains
    # fused PREDICT (inference/, physical/compiled_predict.py): run a
    # registered model's tensor program in the SAME executable as the
    # scan/filter feeding it (the compiled_predict ladder rung).  Off =
    # every PREDICT takes the host predict path (pull to pandas,
    # model.predict on numpy, re-upload).
    "sql.compile.predict": True,
    "sql.compile.segsum": "auto",  # scatter | matmul | pallas segment sums
    "sql.streaming.enabled": True,  # out-of-core parquet batch aggregation
    "sql.streaming.batch_rows": 2_000_000,
    "sql.compile.join_pipeline": True,  # one-jit scan->joins->aggregate
    "sql.distributed.aggregate": "auto",  # collectives engine routing
    "sql.distributed.join": "auto",
    "sql.distributed.sort": "auto",  # range-partition sort over the mesh
    # SPMD query execution (spmd/, docs/spmd.md): device-sharded storage +
    # sharded compiled rungs.
    #   parallel.auto_shard: row-shard eligible registrations over the
    #   default mesh at create_table/load time (same mechanism as the
    #   explicit `distributed=True` kwarg / CREATE TABLE WITH
    #   (distributed=...) passthrough).  "off" (default) preserves
    #   single-device registration; "on"/"auto" shards any non-lazy table
    #   with at least `min_rows` rows when the mesh has >= 2 devices.
    #   DICT/FOR encodings are preserved by sharding, so SPMD exchanges
    #   move codes, not values.
    "parallel.auto_shard": "off",
    "parallel.auto_shard.min_rows": 32768,  # smaller registrations stay single-device
    # the sharded compiled rungs (spmd_select / spmd_aggregate /
    # spmd_join_aggregate): explicit shard_map SPMD programs over
    # mesh-sharded scans, sitting ABOVE the single-chip compiled rungs in
    # the degradation ladder.  "auto" fires whenever the scanned table is
    # mesh-sharded; "off" keeps the pre-SPMD paths (GSPMD auto-layout /
    # dist_* collectives engine).
    "parallel.spmd": "auto",
    "parallel.spmd.select": True,  # spmd_select rung for root select chains
    "parallel.spmd.aggregate": True,  # spmd_aggregate rung (psum tree-reduce)
    "parallel.spmd.join_aggregate": True,  # spmd_join_aggregate rung (broadcast builds)
    # build sides up to this many rows broadcast (replicated LUT probe);
    # larger build sides decline to the all_to_all hash-shuffle engine
    "parallel.spmd.broadcast_rows": 1 << 20,
    "sql.debug.validate_take": False,  # assert gather-index invariants (host sync per gather)
    # Compressed column encodings (columnar/encodings.py, docs/columnar.md):
    # load-time auto-selection of DICT / FOR / RLE storage for
    # numeric/datetime columns at table registration.
    #   "auto" pick the smallest encoding per column (heuristics in
    #          encodings.maybe_encode); compiled pipelines then evaluate
    #          predicates in code space and decode late
    #   "off"  every column stays PLAIN (dense device buffers, pre-encoding
    #          behavior, byte-identical results)
    "columnar.encoding": "auto",
    # columns shorter than this stay PLAIN: tiny tables gain nothing and
    # the selection pass (host np.unique/gcd) isn't free
    "columnar.encoding.min_rows": 1024,
    # per-encoding toggles (all subject to the master switch above)
    "columnar.encoding.dict": True,  # sorted-dictionary codes (int16/int32)
    "columnar.encoding.for": True,  # frame-of-reference affine narrow ints
    "columnar.encoding.rle": True,  # run-length (storage-at-rest only)
    # DICT is only selected up to this cardinality (sorted host dictionary;
    # beyond it the per-predicate searchsorted constants stop paying off)
    "columnar.encoding.dict_max_card": 1 << 15,
    # Static plan verification (analysis/verifier.py, docs/analysis.md):
    #   "on"     cross-check every bound plan; error findings raise a
    #            taxonomy PlanError at bind time, doomed compiled rungs are
    #            skipped by the ladder (analysis.rung_skip.* metrics)
    #   "strict" warn findings (e.g. radix-domain overflow) also raise
    #   "off"    no verification
    "analysis.verify": "on",
    # Static cost & memory estimation (analysis/estimator.py, docs/analysis.md):
    #   "on"  estimate every freshly planned executing query at bind time
    #         (attaches the verdict for admission/cache/ladder consumers,
    #         records analysis.estimate.* metrics)
    #   "off" no estimation (EXPLAIN ESTIMATE still works on demand)
    "analysis.estimate": "on",
    # device byte budget the compiled-rung proofs compare against: an
    # Aggregate whose packed intermediate-buffer LOWER bound exceeds it has
    # compiled_aggregate/compiled_join_aggregate pre-skipped (no attempt,
    # no breaker charge).  None disables the proof.
    "analysis.estimate.device_budget_bytes": None,
    # Profile-feedback priors (estimator.apply_feedback): tighten a
    # family's estimate UPPER bounds from its observed output rows /
    # result bytes (margin x the observed max, after min_obs executions).
    # Lower bounds are never touched — they stay provable, so the
    # admission shed and rung proofs keep their soundness; the tightened
    # his are predictions that improve packing density and drain hints.
    "analysis.estimate.feedback": True,
    "analysis.estimate.feedback.margin": 2.0,  # safety multiple over the observed max
    "analysis.estimate.feedback.min_obs": 2,  # observed executions before feedback applies
    # Runtime lock sanitizer (runtime/locks.py, docs/analysis.md "Lock
    # ranks"): NamedLock rank + order-graph checking on every blocking
    # acquire, raising LockOrderError BEFORE a deadlock can form.  Off in
    # production (per-acquire bookkeeping on hot locks); the test suite
    # turns it on globally in tests/conftest.py, and a Context whose
    # config enables it arms the process-wide sanitizer (never disarms).
    "analysis.lock_sanitizer": False,
    # Parameterized plan families (families/, docs/serving.md "Plan
    # families and batching"): post-optimize literal extraction into a
    # runtime parameter vector.  One XLA executable then serves every
    # literal variant of a statement, and the family fingerprint keys the
    # result cache, the circuit breaker / degradation ladder, the
    # estimator memo, and the per-family profiles behind SHOW PROFILES and
    # restart pre-warm.  Off = literal-baked plan identity everywhere
    # (pre-family behavior, byte-identical).
    "families.enabled": True,
    # Inter-query family batching (families/batcher.py, ServingRuntime):
    # concurrently admitted same-family queries coalesce into ONE stacked
    # (vmapped) kernel launch sharing a single scan.  max_queries <= 1
    # disables coalescing; window_ms is how long a batch leader waits for
    # followers — only charged when other queries are already in flight.
    "serving.batch.max_queries": 8,
    "serving.batch.window_ms": 2.0,
    # Serving runtime (serving/) — admission control, result cache, metrics.
    # See docs/serving.md for semantics; all keys are read when the runtime
    # or Context is constructed (per-query config_options do not re-size
    # pools, but DO partition the result-cache key).
    "serving.workers": 8,  # query worker threads in the Presto server pool
    "serving.queue.interactive": 32,  # max WAITING interactive queries before shedding
    "serving.queue.batch": 64,  # max WAITING batch queries before shedding
    "serving.batch.max_running": None,  # concurrent batch cap (None = workers-1; 0 pauses batch)
    "serving.deadline_s": None,  # default per-query deadline, seconds (None = unbounded)
    "serving.retry_after_s": 1.0,  # floor of the retry-after hint on load shed
    # ceiling of EVERY Retry-After hint (queue-full backoff, the drain
    # predictor, CRITICAL-band pressure sheds): a pathological backlog
    # estimate must never tell a client to go away for an hour
    "serving.retry_after.cap_s": 60.0,
    # pre-compile OOM gate: shed queries whose statically PROVABLE peak
    # device bytes (estimator lower bound) exceed this budget, with a
    # non-retryable ESTIMATED_BYTES_EXCEEDED before any compilation.
    # None disables the gate.  Oversize-but-PARTITIONABLE plans are routed
    # to streamed execution first (serving.stream.* below); the shed is
    # the last resort.
    "serving.admission.max_estimated_bytes": None,
    # Streamed partitioned execution (streaming/, docs/serving.md
    # "Streaming execution"): a provably-over-budget scan splits into
    # fixed-size encoded row partitions and executes as N pipelined
    # launches of one morsel-shaped family executable, with partial
    # aggregate states combined across the time axis and mid-stream OOM
    # recovery (halve the partition, resume from the last completed one).
    "serving.stream.enabled": True,
    # explicit partition size in rows (0/None = derive from the estimate:
    # the smallest partition count whose provable per-chunk floor fits
    # serving.admission.max_estimated_bytes)
    "serving.stream.chunk_rows": None,
    # the repartition floor: an absorbed mid-stream OOM halves the chunk
    # until it would cross this, at which point the failure degrades down
    # the ladder (streamed -> interpreted) like any rung failure
    "serving.stream.min_chunk_rows": 4096,
    # admission cap on the partition count: a plan needing more launches
    # than this to fit is shed (bounded latency beats unbounded streaming)
    "serving.stream.max_partitions": 256,
    # per-chunk launch deadline, ms (None/0 = off): a wedged mid-stream
    # launch raises a degradable STREAM_LAUNCH_TIMEOUT between chunks —
    # the compile-watchdog pattern extended to streamed execution — so a
    # hung launch can never hold the ticket's byte reservation forever
    "serving.stream.launch_timeout_ms": None,
    # Zero-cold-start serving (docs/serving.md "Cold starts"): persistent
    # executable cache + profile-driven pre-warm + background recompile.
    "serving.compile_cache.path": None,  # dir for the persistent XLA executable cache (None = off)
    "serving.compile_cache.min_compile_time_s": 0.0,  # only persist compiles at least this slow
    "serving.warmup.enabled": True,  # pre-warm top profiled fingerprints after load_state / server boot
    "serving.warmup.top_n": 8,  # how many hot fingerprints the warm-up replays
    "serving.warmup.throttle_s": 0.0,  # pause between warm statements (rate-limit boot device load)
    "serving.bg_compile.enabled": False,  # recompile grown/replaced plan families off the critical path
    "serving.bg_compile.max_pending": 8,  # bounded background-compile queue (past it: foreground)
    # Estimator-driven packing scheduler (serving/scheduler.py,
    # docs/serving.md "Scheduling and multi-tenancy"): concurrently
    # admitted queries are packed against the device byte budget using each
    # family's PROVABLE peak-bytes floor, ordered deadline-first, with
    # per-tenant token-bucket quotas.  enabled=false restores the plain
    # FIFO class deques byte-for-byte (pre-scheduler behavior).
    "serving.scheduler.enabled": True,
    # device byte budget the packer reserves against; None falls back to
    # serving.admission.max_estimated_bytes (no budget anywhere = packing
    # inactive, ordering/quotas still apply)
    "serving.scheduler.device_budget_bytes": None,
    # anti-starvation bound on deadline-first ordering: a deadline-free
    # query sorts as if its deadline were admission + this many seconds,
    # so deadline-bearing traffic can delay it at most ~this long
    "serving.scheduler.fair_horizon_s": 30.0,
    # per-tenant token-bucket refill rate, queries/second (None = quotas
    # off).  Tenants come from the X-Dsql-Tenant header; an out-of-tokens
    # tenant is passed over only while OTHER tenants have runnable work
    # (work-conserving — quotas reorder, they never fail queries).
    "serving.tenant.rate_qps": None,
    "serving.tenant.burst": 4.0,  # token-bucket capacity (burst allowance) per tenant
    # Graceful drain (ServingRuntime.shutdown(wait=True), docs/fleet.md
    # "Drain protocol"): the drain is BOUNDED — in-flight queries that
    # have not finished within this many seconds have their tickets
    # cancelled and their futures failed with a retryable ShutdownError
    # (another replica or a restart can take them) instead of the drain
    # hanging forever on a stuck query.
    "serving.shutdown.drain_timeout_s": 30.0,
    # Fleet tier (fleet/, docs/fleet.md): a Router fronting N replicas
    # with health-gated cost-aware routing, mid-query failover and
    # warm-standby promotion.
    "fleet.failover.max_attempts": 3,  # total dispatch attempts per routed query across replicas
    "fleet.failover.base_s": 0.02,  # first failover backoff delay, seconds (doubles per attempt)
    "fleet.result_timeout_s": 60.0,  # per-dispatch wait before the router declares the replica failed
    "fleet.failover.suspect_cooldown_s": 5.0,  # a just-failed replica sorts last in candidate order this long
    "fleet.standby.auto_promote": True,  # promote a ready warm standby when a replica dies
    "serving.cache.enabled": True,  # result cache for repeated identical queries
    "serving.cache.max_bytes": 256 << 20,  # total resident bytes before LRU eviction
    "serving.cache.max_entry_bytes": 64 << 20,  # per-entry cap (huge results bypass the cache)
    "serving.cache.ttl_s": 300.0,  # entry time-to-live, seconds (None = no TTL)
    # Semantic reuse (materialize/, docs/serving.md "Semantic reuse and
    # materialization") — sub-plan stem materialization, subsumption
    # answering over cached results, incremental maintenance on append.
    "serving.materialize.enabled": True,  # pin hot scan->filter stems as device-resident tables
    "serving.materialize.min_hits": 2,  # stem family hit count before pinning (profile-driven)
    "serving.materialize.max_bytes": 128 << 20,  # total pinned bytes before LRU eviction
    "serving.materialize.min_bytes": 1024,  # floor: stems cheaper than this are not worth pinning
    "serving.reuse.subsumption": True,  # answer tighter-literal families by re-filtering cached results
    "serving.reuse.incremental": True,  # fold INSERT/append deltas through stored combine states
    "serving.metrics.node_traces": False,  # per-plan-node tracing folded into the registry
    # Observability (observability/, docs/observability.md) — query-lifecycle
    # tracing, per-fingerprint profiles, slow-query log.
    "observability.trace.enabled": True,  # lifecycle span trace per query (EXPLAIN ANALYZE header, /v1/trace/{qid})
    "observability.trace.keep": 256,  # finished traces retained for /v1/trace lookups (LRU)
    "observability.slow_query_ms": None,  # span-tree log threshold, ms (None = off; 0 logs every query)
    "observability.slow_query_path": None,  # JSONL sink for slow queries (None = python logger)
    "observability.profiles.window": 64,  # rolling samples kept per fingerprint (exec/compile/bytes)
    "observability.profiles.keep": 512,  # max fingerprints in the profile store (LRU)
    "observability.live.keep": 64,  # finished queries retained in the SHOW QUERIES / /v1/queries table
    "observability.flight.capacity": 4096,  # flight-recorder ring size (events; always on)
    "observability.flight.dump_path": None,  # JSONL sink auto-flushed with the full ring on any query failure (None = in-memory ring only)
    # Resilient execution (resilience/) — error taxonomy, degradation ladder,
    # retry/backoff, circuit breaker, fault injection.  docs/resilience.md.
    "resilience.ladder.enabled": True,  # degradable failures step down a rung instead of failing
    "resilience.ladder.cpu_fallback": True,  # last rung: re-execute the plan on the CPU backend
    # Cost-based rung selection (resilience/ladder.py cost_skip): skip a
    # compile-bearing rung whose predicted compile cost (observed per-rung
    # compile_ms p50) exceeds amortize_factor x the family's observed hits
    # x its observed exec_ms p50 — a choice, not a degradation (no breaker
    # charge, resilience.degraded untouched).  Evidence-gated: first-seen
    # families and already-compiled rungs are never skipped.
    "resilience.ladder.cost_based": True,
    "resilience.ladder.cost.amortize_factor": 4.0,
    "resilience.retry.max_attempts": 3,  # total tries per query at the serving worker (1 = no retry)
    "resilience.retry.base_s": 0.05,  # first backoff delay, seconds
    "resilience.retry.multiplier": 2.0,  # exponential backoff factor
    "resilience.retry.max_s": 2.0,  # backoff ceiling, seconds
    "resilience.retry.jitter": 0.5,  # +-fraction of jitter on each delay
    "resilience.breaker.enabled": True,  # per-plan-fingerprint circuit breaker on ladder rungs
    "resilience.breaker.threshold": 3,  # consecutive failures before a rung is skipped
    "resilience.breaker.cooldown_s": 30.0,  # seconds before a half-open trial is admitted
    "resilience.breaker.persist_ttl_s": 300.0,  # max age of checkpointed breaker verdicts restored on load_state (0 = never restore)
    "resilience.compile_timeout_ms": None,  # watchdog deadline on any XLA compile (None = off); expiry degrades the rung
    # Coordinated HBM pressure response (resilience/pressure.py,
    # docs/resilience.md "Pressure hierarchy"): tiered bands over the
    # ledger's headroom against serving.scheduler.device_budget_bytes
    # (STRICTLY that key — no device budget = banding off, GREEN always).
    # YELLOW suspends speculative work (warm-up, background recompiles,
    # new stem pins); RED reclaims cross-tier (cold result cache ->
    # unpinned stems -> idle model params) back to the YELLOW floor;
    # CRITICAL forces new admissions onto streamed rungs where eligible
    # and sheds the rest with a drain-predicted Retry-After.  enabled also
    # gates the ladder's reclaim-before-degrade OOM retry.
    "resilience.pressure.enabled": True,
    "resilience.pressure.yellow_frac": 0.25,  # headroom <= frac*budget enters YELLOW
    "resilience.pressure.red_frac": 0.10,  # headroom <= frac*budget enters RED
    "resilience.pressure.critical_frac": 0.05,  # headroom <= frac*budget enters CRITICAL
    "resilience.pressure.model_idle_s": 120.0,  # committed model params idle this long are reclaimable

    "resilience.inject": None,  # fault-injection spec, e.g. "compile:0.5,oom:once" (tests only)
    "resilience.inject.seed": 0,  # PRNG seed for probabilistic fault modes
    "resilience.inject.hang_s": 30.0,  # sleep modeled by HANG fault sites (compile_hang)

    # ---- static analysis (analysis/) -----------------------------------
    # warn (once per key) when config.get reads a key absent from
    # DOCUMENTED_KEYS; read by Config._note_unregistered in THIS module,
    # which the dead-key scan excludes
    "analysis.strict_config": False,  # dsql: allow-config-key — read here

}


class KeySpec(NamedTuple):
    """Registry row for one documented config key: its default and the
    value types a reader may hand to it.  The registry is what DSQL703
    (analysis/configkeys.py) checks every literal ``config.get`` site
    against — a typo'd key silently reads its fallback default forever,
    which is the config twin of a typo'd metric name splitting a time
    series (DSQL401)."""
    default: Any
    types: Tuple[type, ...]


#: value types for keys whose default is None (the default alone cannot
#: imply them); byte budgets accept strings ("64MB") via parse_byte_budget
_NULLABLE_KEY_TYPES: Dict[str, Tuple[type, ...]] = {
    "sql.aggregate.split_every": (int,),
    "sql.join.broadcast": (bool, int, float),
    "sql.shuffle.num_buckets": (int,),
    "analysis.estimate.device_budget_bytes": (int, str),
    "serving.batch.max_running": (int,),
    "serving.deadline_s": (float, int),
    "serving.admission.max_estimated_bytes": (int, str),
    "serving.stream.chunk_rows": (int,),
    "serving.stream.launch_timeout_ms": (float, int),
    "serving.compile_cache.path": (str,),
    "serving.scheduler.device_budget_bytes": (int, str),
    "serving.tenant.rate_qps": (float, int),
    "observability.slow_query_ms": (float, int),
    "observability.slow_query_path": (str,),
    "observability.flight.dump_path": (str,),
    "resilience.compile_timeout_ms": (float, int),
    "resilience.inject": (str,),
}


def _types_of(key: str, default: Any) -> Tuple[type, ...]:
    if default is None:
        return _NULLABLE_KEY_TYPES.get(key, (object,))
    if isinstance(default, bool):
        return (bool,)
    if isinstance(default, int):
        return (int,)
    if isinstance(default, float):
        return (float, int)
    return (type(default),)


#: every key a ``config.get("<literal>")`` site may read.  Built from
#: DEFAULTS so the inline doc comments above stay the single source of
#: truth; DSQL703 reports literal reads of unregistered keys, and
#: registered keys no source file ever mentions are reported as dead.
DOCUMENTED_KEYS: Dict[str, KeySpec] = {
    key: KeySpec(default, _types_of(key, default))
    for key, default in DEFAULTS.items()
}


def is_documented_key(key: str) -> bool:
    return key in DOCUMENTED_KEYS


def parse_byte_budget(value: Any) -> Optional[int]:
    """Normalize a byte-budget config value to ``int bytes`` or ``None``
    (disabled).  ``None`` / ``""`` / ``0`` / ``"0"`` / ``"none"`` /
    ``"off"`` / ``"false"`` (any case) and non-positive numbers all
    disable — config values arrive as strings through SET statements and
    environment overrides, and a string ``"0"`` must mean "off", never a
    zero-byte budget that sheds everything.  Shared by every budget gate
    (``serving.admission.max_estimated_bytes``,
    ``analysis.estimate.device_budget_bytes``) so the sites cannot drift.

    Malformed values (e.g. ``"sixty-four"``) disable with a logged warning
    rather than raise — a typo'd budget must never turn into a raw
    ValueError failing every query at the execute boundary.  Binary size
    suffixes (``"64MB"``, ``"2gib"``) are accepted."""
    if value is None:
        return None
    if isinstance(value, str):
        value = value.strip().lower()
        if value in ("", "0", "none", "off", "false"):
            return None
        scale = 1
        for suffix, mult in (("kib", 1 << 10), ("mib", 1 << 20),
                             ("gib", 1 << 30), ("tib", 1 << 40),
                             ("kb", 1 << 10), ("mb", 1 << 20),
                             ("gb", 1 << 30), ("tb", 1 << 40)):
            if value.endswith(suffix):
                value, scale = value[:-len(suffix)].strip(), mult
                break
        try:
            value = float(value) * scale
        except ValueError:
            logging.getLogger(__name__).warning(
                "unparseable byte budget %r; treating as disabled", value)
            return None
    try:
        n = int(value)
    except (TypeError, ValueError):
        logging.getLogger(__name__).warning(
            "unparseable byte budget %r; treating as disabled", value)
        return None
    return n if n > 0 else None


#: keys already warned about under analysis.strict_config — once per key
#: per process; plain set on purpose (a racing double-add only repeats
#: one log line)
_warned_unregistered: set = set()


class Config:
    """Process-global base values + thread-local scoped overlays.

    `update()` mutates the global base (visible everywhere).  `set()` pushes
    a scoped overlay onto THIS thread's stack only: concurrent queries on
    server worker threads each see their own per-query options, so one
    query's override can never leak into another's execution — or into the
    result-cache key it is stored under."""

    def __init__(self):
        self._values: Dict[str, Any] = dict(DEFAULTS)
        self._lock = threading.RLock()
        self._local = threading.local()

    def _overlay_stack(self):
        return getattr(self._local, "stack", None)

    def get(self, key: str, default: Any = None) -> Any:
        if key not in DOCUMENTED_KEYS:
            self._note_unregistered(key)
        stack = self._overlay_stack()
        if stack:
            for frame in reversed(stack):
                if key in frame:
                    return frame[key]
        with self._lock:
            if key in self._values:
                return self._values[key]
            return DEFAULTS.get(key, default)

    def _note_unregistered(self, key: str) -> None:
        """Runtime twin of DSQL703 for keys the static pass cannot see
        (computed names): under ``analysis.strict_config``, warn once per
        key.  The strict key itself is documented, so the recursive
        ``get`` below terminates after one level."""
        if key in _warned_unregistered:
            return
        if not self.get("analysis.strict_config", False):
            return
        _warned_unregistered.add(key)
        logging.getLogger(__name__).warning(
            "config.get(%r): key is not in config.DOCUMENTED_KEYS; "
            "register it with a default and type (analysis.strict_config)",
            key)

    def update(self, options: Optional[Dict[str, Any]]) -> None:
        if not options:
            return
        with self._lock:
            self._values.update(options)

    @contextlib.contextmanager
    def set(self, options: Optional[Dict[str, Any]] = None, **kwargs):
        options = dict(options or {})
        options.update(kwargs)
        stack = self._overlay_stack()
        if stack is None:
            stack = []
            self._local.stack = stack
        stack.append(options)
        try:
            yield self
        finally:
            stack.pop()

    def effective_items(self):
        """Sorted (key, value) pairs of the config THIS thread sees — base
        values merged with any active overlays; the cache-key ingredient."""
        with self._lock:
            merged = dict(self._values)
        for frame in self._overlay_stack() or ():
            merged.update(frame)
        return tuple(sorted(merged.items()))


#: process-global config (parity: dask.config global)
config = Config()


def get(key: str, default: Any = None) -> Any:
    return config.get(key, default)


def set(options: Optional[Dict[str, Any]] = None, **kwargs):
    return config.set(options, **kwargs)
