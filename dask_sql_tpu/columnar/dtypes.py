"""SQL type system and dtype mappings for the TPU columnar backend.

Role parity: reference `src/sql/types.rs` (SqlTypeName enum, types.rs:214) and
`dask_sql/mappings.py` (python<->sql type tables, mappings.py:17-90).  Re-designed for a
JAX/XLA backend: every SQL type maps onto a *device representation* — a jax/numpy dtype for
the data buffer plus an encoding tag (strings are dictionary-encoded int32 codes; datetimes
are int64 epoch values) — instead of pandas nullable extension dtypes.
"""
from __future__ import annotations

import datetime
import enum
from decimal import Decimal

import numpy as np


class SqlType(enum.Enum):
    """Calcite-style SQL type names (reference types.rs:214 SqlTypeName)."""

    NULL = "NULL"
    BOOLEAN = "BOOLEAN"
    TINYINT = "TINYINT"
    SMALLINT = "SMALLINT"
    INTEGER = "INTEGER"
    BIGINT = "BIGINT"
    FLOAT = "FLOAT"
    REAL = "REAL"
    DOUBLE = "DOUBLE"
    DECIMAL = "DECIMAL"
    VARCHAR = "VARCHAR"
    CHAR = "CHAR"
    DATE = "DATE"
    TIME = "TIME"
    TIMESTAMP = "TIMESTAMP"
    TIMESTAMP_WITH_LOCAL_TIME_ZONE = "TIMESTAMP_WITH_LOCAL_TIME_ZONE"
    INTERVAL_DAY_TIME = "INTERVAL_DAY_TIME"
    INTERVAL_YEAR_MONTH = "INTERVAL_YEAR_MONTH"
    BINARY = "BINARY"
    VARBINARY = "VARBINARY"
    ANY = "ANY"

    def __str__(self) -> str:  # pragma: no cover - repr sugar
        return self.value


# ---------------------------------------------------------------------------
# Device representation
# ---------------------------------------------------------------------------
# Strings live on device as int32 dictionary codes (+ a host-side array of unique
# values); datetimes as int64 nanoseconds since epoch; dates as int32 days since
# epoch; intervals as int64 (ns for day-time, months for year-month).

_SQL_TO_NP = {
    SqlType.BOOLEAN: np.dtype(np.bool_),
    SqlType.TINYINT: np.dtype(np.int8),
    SqlType.SMALLINT: np.dtype(np.int16),
    SqlType.INTEGER: np.dtype(np.int32),
    SqlType.BIGINT: np.dtype(np.int64),
    SqlType.FLOAT: np.dtype(np.float32),
    SqlType.REAL: np.dtype(np.float32),
    SqlType.DOUBLE: np.dtype(np.float64),
    SqlType.DECIMAL: np.dtype(np.float64),  # decimal policy: float64 (sql.yaml:33 analogue)
    SqlType.VARCHAR: np.dtype(np.int32),  # dictionary codes
    SqlType.CHAR: np.dtype(np.int32),
    SqlType.DATE: np.dtype(np.int64),  # ns since epoch (midnight)
    SqlType.TIME: np.dtype(np.int64),
    SqlType.TIMESTAMP: np.dtype(np.int64),  # ns since epoch
    SqlType.TIMESTAMP_WITH_LOCAL_TIME_ZONE: np.dtype(np.int64),
    SqlType.INTERVAL_DAY_TIME: np.dtype(np.int64),  # nanoseconds
    SqlType.INTERVAL_YEAR_MONTH: np.dtype(np.int64),  # months
    SqlType.NULL: np.dtype(np.float64),
    SqlType.ANY: np.dtype(np.object_),
}

_NP_TO_SQL = {
    np.dtype(np.bool_): SqlType.BOOLEAN,
    np.dtype(np.int8): SqlType.TINYINT,
    np.dtype(np.int16): SqlType.SMALLINT,
    np.dtype(np.int32): SqlType.INTEGER,
    np.dtype(np.int64): SqlType.BIGINT,
    np.dtype(np.uint8): SqlType.SMALLINT,
    np.dtype(np.uint16): SqlType.INTEGER,
    np.dtype(np.uint32): SqlType.BIGINT,
    np.dtype(np.uint64): SqlType.BIGINT,
    np.dtype(np.float16): SqlType.FLOAT,
    np.dtype(np.float32): SqlType.FLOAT,
    np.dtype(np.float64): SqlType.DOUBLE,
    np.dtype(np.object_): SqlType.VARCHAR,
    np.dtype(np.str_): SqlType.VARCHAR,
}

_PY_SCALAR_TO_SQL = {
    bool: SqlType.BOOLEAN,
    int: SqlType.BIGINT,
    float: SqlType.DOUBLE,
    str: SqlType.VARCHAR,
    bytes: SqlType.VARBINARY,
    Decimal: SqlType.DECIMAL,
    datetime.datetime: SqlType.TIMESTAMP,
    datetime.date: SqlType.DATE,
    datetime.timedelta: SqlType.INTERVAL_DAY_TIME,
    type(None): SqlType.NULL,
}

#: SQL types whose device buffer is an integer *encoding* rather than the value itself
STRING_TYPES = frozenset({SqlType.VARCHAR, SqlType.CHAR})
DATETIME_TYPES = frozenset(
    {SqlType.DATE, SqlType.TIME, SqlType.TIMESTAMP, SqlType.TIMESTAMP_WITH_LOCAL_TIME_ZONE}
)
INTERVAL_TYPES = frozenset({SqlType.INTERVAL_DAY_TIME, SqlType.INTERVAL_YEAR_MONTH})
INTEGER_TYPES = frozenset(
    {SqlType.TINYINT, SqlType.SMALLINT, SqlType.INTEGER, SqlType.BIGINT}
)
FLOAT_TYPES = frozenset({SqlType.FLOAT, SqlType.REAL, SqlType.DOUBLE, SqlType.DECIMAL})
NUMERIC_TYPES = INTEGER_TYPES | FLOAT_TYPES


def sql_to_np(sql_type: SqlType) -> np.dtype:
    """Device-buffer numpy dtype for a SQL type."""
    return _SQL_TO_NP[sql_type]


def np_to_sql(dtype) -> SqlType:
    """SQL type for a numpy/pandas dtype (datetime64/timedelta64 handled by kind)."""
    dtype = np.dtype(dtype) if not hasattr(dtype, "kind") else dtype
    kind = getattr(dtype, "kind", None)
    if kind == "M":
        return SqlType.TIMESTAMP
    if kind == "m":
        return SqlType.INTERVAL_DAY_TIME
    if kind in ("U", "S", "O"):
        return SqlType.VARCHAR
    try:
        return _NP_TO_SQL[np.dtype(dtype)]
    except (KeyError, TypeError):
        # pandas extension dtypes (Int64, boolean, string, ...)
        name = str(dtype).lower()
        for probe, st in (
            ("int8", SqlType.TINYINT),
            ("int16", SqlType.SMALLINT),
            ("int32", SqlType.INTEGER),
            ("int64", SqlType.BIGINT),
            ("float32", SqlType.FLOAT),
            ("float64", SqlType.DOUBLE),
            ("bool", SqlType.BOOLEAN),
            ("str", SqlType.VARCHAR),
            ("decimal", SqlType.DECIMAL),
            ("date", SqlType.TIMESTAMP),
        ):
            if probe in name:
                return st
        raise NotImplementedError(f"No SQL type known for dtype {dtype!r}")


def python_to_sql_type(value) -> SqlType:
    """SQL type of a python scalar (reference mappings.py:92 python_to_sql_type)."""
    if isinstance(value, np.generic):
        return np_to_sql(value.dtype)
    for py_type, st in _PY_SCALAR_TO_SQL.items():
        if isinstance(value, py_type) and type(value) is not bool or py_type is bool and isinstance(value, bool):
            # bool is a subclass of int; check bool first via the explicit clause
            if py_type is bool and not isinstance(value, bool):
                continue
            return st
    raise NotImplementedError(f"No SQL type known for python value {value!r}")


# Type-promotion lattice (reference mappings.py:264 `similar_type` — avoid needless casts).
_PROMOTION_ORDER = [
    SqlType.BOOLEAN,
    SqlType.TINYINT,
    SqlType.SMALLINT,
    SqlType.INTEGER,
    SqlType.BIGINT,
    SqlType.FLOAT,
    SqlType.REAL,
    SqlType.DOUBLE,
    SqlType.DECIMAL,
]


def promote(a: SqlType, b: SqlType) -> SqlType:
    """Least common supertype for arithmetic/comparison, SQL-style."""
    if a == b:
        return a
    if a == SqlType.NULL:
        return b
    if b == SqlType.NULL:
        return a
    if a in STRING_TYPES and b in STRING_TYPES:
        return SqlType.VARCHAR
    if a in DATETIME_TYPES and b in DATETIME_TYPES:
        return SqlType.TIMESTAMP
    # datetime +- interval keeps the datetime type
    if a in DATETIME_TYPES and b in INTERVAL_TYPES:
        return a
    if b in DATETIME_TYPES and a in INTERVAL_TYPES:
        return b
    if a in _PROMOTION_ORDER and b in _PROMOTION_ORDER:
        # int64 op float32 -> float64 to not lose precision (SQL semantics)
        ia, ib = _PROMOTION_ORDER.index(a), _PROMOTION_ORDER.index(b)
        hi = _PROMOTION_ORDER[max(ia, ib)]
        lo = _PROMOTION_ORDER[min(ia, ib)]
        if hi in (SqlType.FLOAT, SqlType.REAL) and lo in (SqlType.INTEGER, SqlType.BIGINT):
            return SqlType.DOUBLE
        return hi
    if a in DATETIME_TYPES and b in NUMERIC_TYPES:
        return a
    if b in DATETIME_TYPES and a in NUMERIC_TYPES:
        return b
    raise NotImplementedError(f"Cannot promote {a} and {b}")


def similar_type(a: SqlType, b: SqlType) -> bool:
    """True when a cast between the two types would be a no-op family-wise."""
    fams = (INTEGER_TYPES, FLOAT_TYPES, STRING_TYPES, DATETIME_TYPES, INTERVAL_TYPES,
            frozenset({SqlType.BOOLEAN}))
    for fam in fams:
        if a in fam and b in fam:
            return True
    return a == b


def parse_sql_type(name: str) -> SqlType:
    """Parse a SQL type name as written in queries (e.g. ``CAST(x AS BIGINT)``)."""
    name = name.strip().upper()
    base = name.split("(")[0].strip()
    aliases = {
        "INT": SqlType.INTEGER,
        "INT2": SqlType.SMALLINT,
        "INT4": SqlType.INTEGER,
        "INT8": SqlType.BIGINT,
        "LONG": SqlType.BIGINT,
        "STRING": SqlType.VARCHAR,
        "TEXT": SqlType.VARCHAR,
        "BOOL": SqlType.BOOLEAN,
        "NUMERIC": SqlType.DECIMAL,
        "FLOAT4": SqlType.FLOAT,
        "FLOAT8": SqlType.DOUBLE,
        "DOUBLE PRECISION": SqlType.DOUBLE,
        "TIMESTAMP WITHOUT TIME ZONE": SqlType.TIMESTAMP,
        "TIMESTAMP WITH TIME ZONE": SqlType.TIMESTAMP_WITH_LOCAL_TIME_ZONE,
        "DATETIME": SqlType.TIMESTAMP,
    }
    if base in aliases:
        return aliases[base]
    try:
        return SqlType[base.replace(" ", "_")]
    except KeyError:
        raise NotImplementedError(f"Unknown SQL type: {name}")
