"""ML statement converters: CREATE MODEL / PREDICT / EXPERIMENT / EXPORT.

Role parity (reference physical/rel/custom/): create_model.py:23 (WITH
options: model_class, target_column, wrap_predict, wrap_fit, fit_kwargs),
predict_model.py:15 (PREDICT(MODEL m, <select>) appends a `target` column),
create_experiment.py:22 (GridSearchCV-style tuning), export_model.py:15
(pickle/joblib/mlflow/onnx), describe_model.py, drop_model.py.
"""
from __future__ import annotations

import numpy as np

from ....columnar.column import Column
from ....columnar.table import Table
from ....planner import plan as p
from ..base import BaseRelPlugin, unique_names
from ...executor import Executor

_EMPTY = Table({}, 0)


def _split_xy(df, target_column):
    if target_column:
        X = df.drop(columns=[target_column])
        y = df[target_column]
    else:
        X, y = df, None
    return X, y


@Executor.add_plugin_class
class CreateModelPlugin(BaseRelPlugin):
    class_name = "CreateModelNode"

    def convert(self, rel: p.CreateModelNode, executor) -> Table:
        from ....ml.ml_classes import get_model_class
        from ....ml.wrappers import Incremental, ParallelPostFit

        ctx = executor.context
        schema_name, name = ctx._table_schema_name(rel.name)
        if name in ctx.schema[schema_name].models:
            if rel.if_not_exists:
                return _EMPTY
            if not rel.or_replace:
                raise RuntimeError(f"A model with the name {name} is already present.")
        kwargs = dict(rel.kwargs)
        model_class = kwargs.pop("model_class", None)
        if model_class is None:
            raise ValueError("CREATE MODEL requires a model_class parameter")
        experiment_class = kwargs.pop("experiment_class", None)
        target_column = kwargs.pop("target_column", "")
        wrap_predict = _boolish(kwargs.pop("wrap_predict", False))
        wrap_fit = _boolish(kwargs.pop("wrap_fit", False))
        fit_kwargs = kwargs.pop("fit_kwargs", {}) or {}
        backend = kwargs.pop("backend", "tpu")
        kwargs.pop("gpu", None)

        training_table = executor.execute(rel.input)
        df = training_table.to_pandas()
        X, y = _split_xy(df, target_column)

        ModelClass = get_model_class(str(model_class), backend=str(backend))
        model = ModelClass(**kwargs)
        if wrap_fit:
            model = Incremental(model)
        if y is not None:
            model.fit(X.to_numpy(), y.to_numpy(), **fit_kwargs)
        else:
            model.fit(X.to_numpy(), **fit_kwargs)
        if wrap_predict and not isinstance(model, (ParallelPostFit, Incremental)):
            model = ParallelPostFit(model)
        ctx.register_model(name, model, list(X.columns), schema_name=schema_name)
        return _EMPTY


@Executor.add_plugin_class
class PredictModelPlugin(BaseRelPlugin):
    class_name = "PredictModelNode"

    def convert(self, rel: p.PredictModelNode, executor) -> Table:
        ctx = executor.context
        schema_name, name = ctx._table_schema_name(rel.model_name)
        model, training_columns = ctx.get_model(schema_name, name)
        inp = executor.execute(rel.input)
        df = inp.to_pandas()
        pred = model.predict(df[training_columns].to_numpy())
        names = unique_names([f.name for f in rel.schema])
        cols = dict(zip(names[:-1], [inp.columns[c] for c in inp.column_names]))
        cols[names[-1]] = Column.from_numpy(np.asarray(pred))
        return Table(cols, inp.num_rows)


@Executor.add_plugin_class
class DropModelPlugin(BaseRelPlugin):
    class_name = "DropModelNode"

    def convert(self, rel: p.DropModelNode, executor) -> Table:
        ctx = executor.context
        schema_name, name = ctx._table_schema_name(rel.name)
        if name not in ctx.schema[schema_name].models:
            if rel.if_exists:
                return _EMPTY
            raise RuntimeError(f"A model with the name {name} is not present.")
        del ctx.schema[schema_name].models[name]
        return _EMPTY


@Executor.add_plugin_class
class DescribeModelPlugin(BaseRelPlugin):
    class_name = "DescribeModelNode"

    def convert(self, rel: p.DescribeModelNode, executor) -> Table:
        ctx = executor.context
        schema_name, name = ctx._table_schema_name(rel.name)
        model, training_columns = ctx.get_model(schema_name, name)
        params = model.get_params() if hasattr(model, "get_params") else {}
        params["training_columns"] = training_columns
        keys = np.array([str(k) for k in params.keys()], dtype=object)
        vals = np.array([str(v) for v in params.values()], dtype=object)
        return Table({"Params": Column.from_numpy(keys),
                      "Value": Column.from_numpy(vals)}, len(keys))


@Executor.add_plugin_class
class ExportModelPlugin(BaseRelPlugin):
    class_name = "ExportModelNode"

    def convert(self, rel: p.ExportModelNode, executor) -> Table:
        ctx = executor.context
        schema_name, name = ctx._table_schema_name(rel.name)
        model, training_columns = ctx.get_model(schema_name, name)
        kwargs = dict(rel.kwargs)
        fmt = str(kwargs.pop("format", "pickle")).lower()
        location = kwargs.pop("location", "tmp.pkl")
        if fmt in ("pickle", "pkl"):
            import pickle

            with open(location, "wb") as f:
                pickle.dump(model, f, **kwargs)
        elif fmt == "joblib":
            import joblib

            joblib.dump(model, location, **kwargs)
        elif fmt == "mlflow":
            try:
                import mlflow
            except ImportError as e:  # pragma: no cover
                raise RuntimeError("mlflow is not installed") from e
            mlflow.sklearn.save_model(model, location, **kwargs)
        elif fmt == "onnx":
            raise RuntimeError(
                "ONNX export requires skl2onnx, which is not installed here")
        else:
            raise NotImplementedError(f"EXPORT MODEL format {fmt!r}")
        return _EMPTY


@Executor.add_plugin_class
class CreateExperimentPlugin(BaseRelPlugin):
    class_name = "CreateExperimentNode"

    def convert(self, rel: p.CreateExperimentNode, executor) -> Table:
        from ....ml.ml_classes import get_model_class

        ctx = executor.context
        schema_name, name = ctx._table_schema_name(rel.name)
        if name in ctx.schema[schema_name].experiments:
            if rel.if_not_exists:
                return _EMPTY
            if not rel.or_replace:
                raise RuntimeError(f"An experiment with the name {name} is already present.")
        kwargs = dict(rel.kwargs)
        model_class = kwargs.pop("model_class", None)
        experiment_class = kwargs.pop("experiment_class", "sklearn.model_selection.GridSearchCV")
        tune_parameters = kwargs.pop("tune_parameters", {}) or {}
        target_column = kwargs.pop("target_column", "")
        automl_class = kwargs.pop("automl_class", None)
        experiment_kwargs = kwargs.pop("experiment_kwargs", {}) or {}
        kwargs.pop("gpu", None)

        training_table = executor.execute(rel.input)
        df = training_table.to_pandas()
        X, y = _split_xy(df, target_column)

        if automl_class:
            raise NotImplementedError(
                "AutoML (TPOT-style) experiments need the automl package installed")
        if model_class is None:
            raise ValueError("CREATE EXPERIMENT requires a model_class")
        ModelClass = get_model_class(str(model_class), backend="cpu")
        base = ModelClass()
        ExperimentClass = get_model_class(str(experiment_class), backend="cpu")
        tuner = ExperimentClass(base, {k: list(v) if isinstance(v, (list, tuple)) else [v]
                                       for k, v in tune_parameters.items()},
                                **experiment_kwargs)
        tuner.fit(X.to_numpy(), y.to_numpy() if y is not None else None)
        import pandas as pd

        results = pd.DataFrame(tuner.cv_results_)
        ctx.schema[schema_name].experiments[name] = results
        ctx.register_model(name, tuner.best_estimator_, list(X.columns),
                           schema_name=schema_name)
        out = Table.from_pandas(results.astype(str))
        return out


def _boolish(v) -> bool:
    if isinstance(v, bool):
        return v
    return str(v).lower() in ("true", "1", "yes")
