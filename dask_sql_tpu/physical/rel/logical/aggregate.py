"""Groupby-aggregate converter.

Role parity: reference aggregate.py:91 (AGGREGATION_MAPPING aggregate.py:117-231,
FILTER clauses aggregate.py:377-520, DISTINCT via pre-dedup aggregate.py:562-568,
NULL-preserving sum min_count=1 aggregate.py:486-493, dropna=False groupby
aggregate.py:575-577, no-groupby constant column aggregate.py:253-258).

TPU-first mechanism: one lexsort factorizes the keys to dense group ids, then
every aggregate is a masked XLA segment reduction (ops/grouping.py).  The
same (count,sum,sumsq)-style states serve as the *partial* stage of the
distributed partial->final tree (parallel/collectives.py), mirroring the
reference's dd.Aggregation chunk/agg/finalize triples.
"""
from __future__ import annotations

from typing import Callable, Dict

import jax
import jax.numpy as jnp
import numpy as np

from ....columnar.column import Column
from ....columnar.dtypes import SqlType, sql_to_np
from ....columnar.table import Table
from ....ops import grouping as g
from ....planner import plan as p
from ....planner.expressions import AggExpr
from ..base import BaseRelPlugin, unique_names
from ...executor import Executor


@Executor.add_plugin_class
class AggregatePlugin(BaseRelPlugin):
    class_name = "Aggregate"

    def convert(self, rel: p.Aggregate, executor) -> Table:
        from ....parallel import dist_plan
        from ....resilience import ladder
        from ...compiled import try_compiled_aggregate
        from ...streaming import try_streaming_aggregate

        from ...compiled_join import try_compiled_join_aggregate

        # Each fast path below is a degradation-ladder rung
        # (resilience/ladder.py): a rung that *declines* returns None as
        # before, and a rung that *fails degradably* (compile crash, device
        # OOM, capacity-ladder exhaustion) now also steps down — recorded as
        # resilience.degraded.<rung> and circuit-broken per plan fingerprint
        # — instead of sinking the query.
        def rung(name, fn, inject=None):
            return ladder.attempt(executor, name, fn, rel=rel,
                                  inject_site=inject)

        # mesh-sharded inputs: the one-jit join->aggregate pipeline runs
        # SPMD over the sharded probe (GSPMD turns its segment reductions
        # into partial-reduce + all-reduce; build-side LUT probes are local
        # gathers of the replicated small sides = broadcast joins).  The
        # joined rows NEVER materialize, on host or device — this is the
        # no-gather-between-merge-and-groupby path (VERDICT r3 #4/#5);
        # the explicit all_to_all shuffle engine remains the general path
        tried_join_pipeline = False
        tried_compiled = False
        if id(rel) in executor.stream_decisions:
            # admission-routed streamed aggregation (streaming/): the
            # provably-oversize scan executes as N pipelined morsel
            # launches with time-axis partial-state combines instead of
            # being shed.  Its OWN (family, streamed_aggregate) breaker
            # entity: an exhausted mid-stream recovery degrades to the
            # single-launch rungs below without poisoning them.
            from ....streaming import try_streamed_aggregate

            streamed = rung("streamed_aggregate",
                            lambda: try_streamed_aggregate(rel, executor))
            if streamed is not None:
                return streamed
        if dist_plan.plan_has_sharded_scan(rel.input, executor.context):
            from ....spmd import try_spmd_aggregate, try_spmd_join_aggregate

            # SPMD rungs first (spmd/, docs/spmd.md): explicit shard_map
            # programs with psum/pmin/pmax tree-reduced partial states and
            # broadcast build sides.  Each is its own (family, rung)
            # breaker entity — an induced SPMD failure degrades to the
            # single-chip compiled rungs below without poisoning them.
            spmd_joined = rung("spmd_join_aggregate",
                               lambda: try_spmd_join_aggregate(rel, executor),
                               inject="spmd")
            if spmd_joined is not None:
                return spmd_joined
            spmd_agg = rung("spmd_aggregate",
                            lambda: try_spmd_aggregate(rel, executor),
                            inject="spmd")
            if spmd_agg is not None:
                return spmd_agg
            joined = rung("compiled_join_aggregate",
                          lambda: try_compiled_join_aggregate(rel, executor),
                          inject="compile")
            tried_join_pipeline = True
            if joined is not None:
                return joined
            # no-join shapes: the whole-jit aggregate runs SPMD over the
            # sharded scan with the filter deferred as a mask — eagerly
            # compacting a sharded table first costs per-column resharding
            # gathers (measured ~1s/query on the Q1 shape, vs ~4ms fused)
            compiled = rung("compiled_aggregate",
                            lambda: try_compiled_aggregate(rel, executor),
                            inject="compile")
            if compiled is not None:
                return compiled
            tried_compiled = True
            (inp,) = self.assert_inputs(rel, 1, executor)
            # sharded -> single-device step-down: the collectives engine
            # raising ResourceExhaustedError (capacity ladder topped out)
            # falls through to the single-program path below
            dist = rung("dist_aggregate",
                        lambda: dist_plan.try_dist_aggregate(
                            rel, executor, inp))
            if dist is not None:
                return dist
        streamed = try_streaming_aggregate(rel, executor)
        if streamed is not None:
            return streamed
        if not tried_join_pipeline:
            joined = rung("compiled_join_aggregate",
                          lambda: try_compiled_join_aggregate(rel, executor),
                          inject="compile")
            if joined is not None:
                return joined
        if not tried_compiled:
            compiled = rung("compiled_aggregate",
                            lambda: try_compiled_aggregate(rel, executor),
                            inject="compile")
            if compiled is not None:
                return compiled
        (inp,) = self.assert_inputs(rel, 1, executor)
        n = inp.num_rows

        group_cols = [executor.eval_expr(e, inp) for e in rel.group_exprs]
        names = unique_names([f.name for f in rel.schema])
        out: Dict[str, Column] = {}
        present = None  # raw-domain compaction indices (radix fast path)
        if group_cols and n > 0:
            fast = g.radix_gid(group_cols)
            if fast is not None:
                # sort-free path: mixed-radix dictionary codes as segment ids
                gid, domain, decode = fast
                hit = jax.ops.segment_sum(jnp.ones(n, dtype=jnp.int32), gid, domain) > 0
                present = jnp.nonzero(hit)[0]
                num_groups = domain
                for name, col in zip(names, decode(present)):
                    out[name] = col
            else:
                gid, order, num_groups = g.factorize(g.key_arrays(group_cols))
                first = g.group_first_indices(gid, num_groups)
                for name, col in zip(names, group_cols):
                    out[name] = col.take(first)
        elif group_cols:
            gid = jnp.zeros(0, dtype=jnp.int32)
            num_groups = 0
            for name, col in zip(names, group_cols):
                out[name] = col.slice(0, 0)
        else:
            gid = jnp.zeros(n, dtype=jnp.int32)
            num_groups = 1  # global aggregate always yields one row

        agg_names = names[len(group_cols):]
        for name, agg in zip(agg_names, rel.agg_exprs):
            col = self._compute_agg(agg, inp, gid, num_groups, executor)
            if present is not None:
                col = col.take(present)
            out[name] = col
        nrows = int(present.shape[0]) if present is not None else num_groups
        return Table(out, nrows)

    # ------------------------------------------------------------------
    def _compute_agg(self, agg: AggExpr, inp: Table, gid, num_groups: int,
                     executor) -> Column:
        n = inp.num_rows
        func = agg.func

        # FILTER (WHERE ...) restricts contributing rows (validity-mask AND)
        fmask = None
        if agg.filter is not None:
            fc = executor.eval_expr(agg.filter, inp)
            fmask = fc.data & fc.valid_mask()

        if func == "count_star":
            valid = jnp.ones(n, dtype=bool) if fmask is None else fmask
            if agg.distinct:
                # COUNT(DISTINCT *) over all columns
                cols = [inp.columns[c] for c in inp.column_names]
                return self._count_distinct(cols, valid, gid, num_groups)
            cnt = g.seg_count(valid, gid, num_groups)
            return Column(cnt, SqlType.BIGINT)

        if func.startswith("udaf:"):
            return self._udaf(func[5:], agg, inp, gid, num_groups, executor, fmask)

        args = [executor.eval_expr(a, inp) for a in agg.args]
        col = args[0] if args else None
        if col is not None and col.dictionary is not None:
            # sorted dictionary => min/max over codes == lexicographic min/max
            col = col.compact_dictionary()
        valid = col.valid_mask() if col is not None else jnp.ones(n, dtype=bool)
        if fmask is not None:
            valid = valid & fmask
        if col is not None and col.sql_type in (SqlType.FLOAT, SqlType.DOUBLE, SqlType.DECIMAL):
            valid = valid & ~jnp.isnan(col.data)

        if agg.distinct and func not in ("min", "max"):
            # dedup (group, value) pairs before reducing — parity:
            # reference drop_duplicates pre-pass (aggregate.py:562-568)
            keys = [gid] + g.key_arrays([col])
            pair_gid, _, pair_num = g.factorize(keys)
            first = g.group_first_indices(pair_gid, pair_num) if n else jnp.zeros(0, jnp.int64)
            keep = jnp.zeros(n, dtype=bool)
            if n:
                keep = keep.at[first].set(True)
            valid = valid & keep

        values = col.data if col is not None else None

        if func == "count":
            if agg.distinct:
                pass  # already deduped above
            return Column(g.seg_count(valid, gid, num_groups), SqlType.BIGINT)
        if func == "sum":
            vals, ok = g.seg_sum(_as_acc(values, col), valid, gid, num_groups)
            return _mk(vals, ok, agg.sql_type)
        if func == "min":
            vals, ok = g.seg_min(values, valid, gid, num_groups)
            return _mk_like(vals, ok, col, agg.sql_type)
        if func == "max":
            vals, ok = g.seg_max(values, valid, gid, num_groups)
            return _mk_like(vals, ok, col, agg.sql_type)
        if func == "avg":
            vals, ok = g.seg_avg(_numeric(values), valid, gid, num_groups)
            return _mk(vals, ok, SqlType.DOUBLE)
        if func in ("var_samp", "var_pop", "stddev_samp", "stddev_pop"):
            ddof = 1 if func.endswith("samp") else 0
            vals, ok = g.seg_var(_numeric(values), valid, gid, num_groups, ddof)
            if func.startswith("stddev"):
                vals = jnp.sqrt(vals)
            return _mk(vals, ok, SqlType.DOUBLE)
        if func == "every":
            vals, ok = g.seg_bool_and(values, valid, gid, num_groups)
            return _mk(vals, ok, SqlType.BOOLEAN)
        if func == "bool_or":
            vals, ok = g.seg_bool_or(values, valid, gid, num_groups)
            return _mk(vals, ok, SqlType.BOOLEAN)
        if func in ("bit_and", "bit_or", "bit_xor"):
            vals, ok = g.seg_bitwise(values, valid, gid, num_groups, func)
            return _mk_like(vals.astype(col.data.dtype), ok, col, agg.sql_type)
        if func in ("single_value", "first_value"):
            vals, ok = g.seg_first(values, valid, gid, num_groups)
            return _mk_like(vals, ok, col, agg.sql_type)
        if func == "last_value":
            vals, ok = g.seg_last(values, valid, gid, num_groups)
            return _mk_like(vals, ok, col, agg.sql_type)
        if func == "percentile":
            # MEDIAN(x) / APPROX_PERCENTILE(x, q) / PERCENTILE_CONT..WITHIN GROUP
            q = 0.5
            if len(args) > 1:
                qv = np.asarray(args[1].data).reshape(-1)
                if qv.size:
                    q = float(qv[0])
            vals, ok = g.seg_percentile(_numeric(values), valid, gid, num_groups, q)
            return _mk(vals, ok, SqlType.DOUBLE)
        if func == "approx_count_distinct":
            cols = [col]
            return self._count_distinct(cols, valid, gid, num_groups)
        if func == "regr_count":
            y, x = args
            both = valid & x.valid_mask()
            return Column(g.seg_count(both, gid, num_groups), SqlType.BIGINT)
        if func in ("regr_syy", "regr_sxx"):
            y, x = args
            both = y.valid_mask() & x.valid_mask()
            if fmask is not None:
                both = both & fmask
            target = y if func == "regr_syy" else x
            vals, ok = g.seg_var(_numeric(target.data), both, gid, num_groups, 0)
            cnt = g.seg_count(both, gid, num_groups)
            return _mk(vals * cnt, ok, SqlType.DOUBLE)
        raise NotImplementedError(f"aggregate {func}")

    def _count_distinct(self, cols, valid, gid, num_groups) -> Column:
        n = int(valid.shape[0])
        keys = [gid] + g.key_arrays(cols)
        pair_gid, _, pair_num = g.factorize(keys)
        first = g.group_first_indices(pair_gid, pair_num) if n else jnp.zeros(0, jnp.int64)
        keep = jnp.zeros(n, dtype=bool)
        if n:
            keep = keep.at[first].set(True)
        allv = jnp.ones(n, dtype=bool)
        for c in cols:
            allv &= c.valid_mask()
        cnt = g.seg_count(keep & valid & allv, gid, num_groups)
        return Column(cnt, SqlType.BIGINT)

    def _udaf(self, name: str, agg: AggExpr, inp: Table, gid, num_groups,
              executor, fmask) -> Column:
        """User-registered aggregation: applied per group on host (parity:
        reference dd.Aggregation custom UDAFs, context.py:415)."""
        fd = executor.lookup_function(name)
        args = [executor.eval_expr(a, inp) for a in agg.args]
        col = args[0]
        import pandas as pd

        ser = pd.Series(col.to_numpy())
        gids = np.asarray(gid)
        if fmask is not None:
            keep = np.asarray(fmask)
            ser = ser[keep]
            gids = gids[keep]
        grouped = ser.groupby(gids)
        result = fd.func(grouped)
        out = np.full(num_groups, np.nan)
        out[np.asarray(result.index, dtype=int)] = result.to_numpy()
        res = Column.from_numpy(out)
        return res.cast(fd.return_type)


def _numeric(values):
    return values.astype(jnp.float64)


def _as_acc(values, col: Column):
    """Accumulate int sums in int64 (overflow safety)."""
    if jnp.issubdtype(values.dtype, jnp.integer) or values.dtype == jnp.bool_:
        return values.astype(jnp.int64)
    return values


def _mk(vals, ok, sql_type: SqlType) -> Column:
    target = sql_to_np(sql_type)
    vals = vals.astype(target) if vals.dtype != target else vals
    validity = None if bool(ok.all()) else ok
    return Column(vals, sql_type, validity)


def _mk_like(vals, ok, src: Column, sql_type: SqlType) -> Column:
    """Result keeping the source column's encoding (min/max of strings etc.)."""
    validity = None if bool(ok.all()) else ok
    return Column(vals, sql_type, validity, src.dictionary)
