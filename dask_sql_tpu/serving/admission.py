"""Admission control: bounded per-class queues, deadlines, load shedding.

Two concurrency classes — ``interactive`` (dashboards, point lookups:
scheduled first) and ``batch`` (reports, ETL: capped so it can never starve
interactive traffic).  Each class has a bounded *waiting* queue; a submit
past the bound is rejected immediately with a structured retry-after error
(`QueueFullError`) instead of queueing unbounded work — the Presto server
translates that into a wire-level error payload, so clients back off
instead of piling on.

Deadlines propagate as a `QueryTicket` that the executor polls at
cooperative cancellation checkpoints (`physical/executor.py` checks the
current ticket before every plan node): a query past its deadline or
cancelled by the client raises out of the next checkpoint rather than
holding a worker until completion.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from ..runtime import locks

from ..resilience.errors import (
    INSUFFICIENT_RESOURCES,
    CancelledError,
    DeadlineError,
    QueryError,
    ResourceExhaustedError,
)

#: scheduling order — lower runs first
CLASSES = ("interactive", "batch")

RETRY_AFTER_CAP_KEY = "serving.retry_after.cap_s"


def retry_after_cap(config=None) -> float:
    """The ceiling every Retry-After hint is clamped to
    (``serving.retry_after.cap_s``, default 60s): a pathological backlog
    estimate must never tell clients to go away for an hour.  ``config``
    defaults to the process config (thread-local overlays apply)."""
    if config is None:
        from ..config import config as process_config

        config = process_config
    try:
        cap = float(config.get(RETRY_AFTER_CAP_KEY, 60.0))
    except (TypeError, ValueError):
        return 60.0
    return cap if cap > 0 else 60.0


class QueueFullError(QueryError):
    """Load shed: the class queue is at its bound; retry after a delay.
    Taxonomy: retryable (the hint says when), INSUFFICIENT_RESOURCES."""

    code = "QUERY_QUEUE_FULL"
    error_type = INSUFFICIENT_RESOURCES
    retryable = True

    def __init__(self, priority_class: str, bound: int, retry_after_s: float):
        super().__init__(
            f"admission queue for class {priority_class!r} is full "
            f"({bound} waiting); retry after {retry_after_s:.1f}s")
        self.priority_class = priority_class
        self.bound = bound
        self.retry_after_s = retry_after_s


class EstimatedBytesExceededError(ResourceExhaustedError):
    """Pre-compile OOM gate: the static estimator's PROVABLE lower bound on
    peak device bytes exceeds the admission budget, so executing could only
    OOM — the query is shed before any compilation or device work.

    Taxonomy: non-retryable (the proof holds until the catalog or the
    query changes) and non-degradable (lower rungs share the same device;
    at serving scale, shedding beats a doomed attempt-and-degrade)."""

    code = "ESTIMATED_BYTES_EXCEEDED"
    error_type = INSUFFICIENT_RESOURCES
    retryable = False
    degradable = False

    def __init__(self, estimated_bytes_lo: int, budget_bytes: int):
        super().__init__(
            f"estimated peak device bytes >= {estimated_bytes_lo} "
            f"provably exceed the admission budget of {budget_bytes} bytes "
            f"(serving.admission.max_estimated_bytes); query shed before "
            f"compilation")
        self.estimated_bytes_lo = int(estimated_bytes_lo)
        self.budget_bytes = int(budget_bytes)

    def payload(self) -> dict:
        # clients/load balancers see the proof (estimator lower bound vs
        # budget) on the wire instead of a bare message
        out = super().payload()
        out["estimatedBytesLow"] = self.estimated_bytes_lo
        out["budgetBytes"] = self.budget_bytes
        return out


def check_estimated_bytes(estimate, config, metrics=None, plan=None,
                          context=None):
    """The ``serving.admission.max_estimated_bytes`` gate: raise
    `EstimatedBytesExceededError` when the estimate's *lower* bound on peak
    device bytes exceeds the budget.  Called by ``TpuFrame.execute`` after
    the result-cache lookup and before any executor/compiler work — only
    the lower bound sheds, because only it is provable (an upper-bound shed
    would reject feasible queries).

    Streaming escape hatch (streaming/, docs/serving.md "Streaming
    execution"): when ``plan`` and ``context`` are supplied, an over-budget
    plan that is *partitionable* — its floor dominated by one registered
    table's scan, its shape one a streamed rung serves, and its provable
    PER-CHUNK floor within the budget — returns ``(streamable node,
    StreamDecision)`` instead of shedding; the caller hands the pair to
    ITS executor (`Executor.stream_decisions`), so the verdict is
    per-execution state — a concurrent execution of the same cached plan
    under a different budget can never null it mid-flight.  Returns None
    when the query is simply admitted.  ``shed:estimated_bytes`` is the
    last resort: it fires only when even one chunk provably cannot fit.

    CRITICAL-band admission (resilience/pressure.py): when the pressure
    controller reports CRITICAL, even an under-budget plan is forced onto
    a streamed rung where eligible — browning out beats 429ing — and shed
    with a retryable, drain-predicted `PressureShedError` otherwise.
    This call is also the per-query observe->decide->act step: RED-band
    reclaim runs inside ``pressure.evaluate()`` before any verdict."""
    from ..config import parse_byte_budget

    budget = None if config is None else parse_byte_budget(
        config.get("serving.admission.max_estimated_bytes"))
    pressure = getattr(context, "pressure", None) if context is not None \
        else None
    critical = pressure is not None and pressure.evaluate() == "critical"
    if (budget is None and not critical) or estimate is None:
        return None
    lo = int(estimate.peak_bytes.lo)
    over = budget is not None and lo > budget
    if not over and not critical:
        return None
    from ..observability import trace_event

    stream_budget = budget
    if stream_budget is None and pressure is not None:
        stream_budget = pressure.budget_bytes()
    if plan is not None and context is not None \
            and stream_budget is not None:
        from ..streaming import stream_decision

        routed = stream_decision(plan, estimate, context, config,
                                 stream_budget)
        if routed is not None:
            _, decision = routed
            if metrics is not None:
                metrics.inc("serving.stream.admitted")
                if critical and not over:
                    metrics.inc("resilience.pressure.critical_streamed")
            trace_event("admit:streamed", bytes_lo=lo,
                        budget=stream_budget, critical=critical,
                        partitions=decision.partitions,
                        chunk_bytes_lo=decision.chunk_bytes_lo)
            return routed
    from ..observability import flight
    from .runtime import current_ticket

    ticket = current_ticket()
    if not over:
        # CRITICAL with no streamed rung to brown out onto: shed with a
        # drain-predicted Retry-After so clients back off past the spike
        from ..resilience.pressure import PressureShedError

        retry = 1.0 if config is None else float(
            config.get("serving.retry_after_s", 1.0) or 1.0)
        runtime = getattr(context, "serving", None)
        drain = runtime._predicted_drain_s() if runtime is not None else None
        if drain is not None and drain > retry:
            retry = drain
        retry = min(retry_after_cap(config), retry)
        if metrics is not None:
            metrics.inc("resilience.pressure.critical_shed")
        trace_event("shed:pressure", bytes_lo=lo, retry_after_s=retry)
        flight.record("query.shed",
                      qid=ticket.qid if ticket is not None else None,
                      reason="pressure", bytes_lo=lo)
        raise PressureShedError(
            f"device HBM pressure is CRITICAL and the plan has no "
            f"streamed rung; retry after {retry:.1f}s",
            retry_after_s=retry)
    if metrics is not None:
        metrics.inc("serving.shed_estimated_bytes")
    trace_event("shed:estimated_bytes", bytes_lo=lo, budget=budget)
    flight.record("query.shed",
                  qid=ticket.qid if ticket is not None else None,
                  reason="estimated_bytes", bytes_lo=lo, budget=budget)
    raise EstimatedBytesExceededError(lo, budget)


class DeadlineExceededError(DeadlineError):
    """The query ran past its deadline and was cancelled at a checkpoint."""


class QueryCancelledError(CancelledError):
    """The client cancelled the query; raised at the next checkpoint."""


class QueryTicket:
    """Per-admitted-query token: class, deadline, cooperative cancel flag.

    `checkpoint()` is the only method hot code calls — it is lock-free
    (reads a bool + the clock) so the executor can afford one per plan node.
    """

    __slots__ = ("qid", "priority_class", "deadline", "admitted_at",
                 "started_at", "_cancelled", "cost", "measured_bytes",
                 "queue_reason")

    def __init__(self, qid: str, priority_class: str = "interactive",
                 deadline: Optional[float] = None):
        self.qid = qid
        self.priority_class = priority_class
        #: absolute monotonic deadline (None = unbounded)
        self.deadline = deadline
        self.admitted_at = time.monotonic()
        self.started_at: Optional[float] = None
        self._cancelled = False
        #: why this query waited in the queue, stamped at dispatch by the
        #: packing scheduler (``byte_blocked`` / ``quota_throttled``) or
        #: defaulted to ``workers_busy`` — the queue_wait span's cause
        #: attribution the slow-query log surfaces
        self.queue_reason: Optional[str] = None
        #: the packing scheduler's `QueryCost` (serving/scheduler.py) when
        #: the submit carried one — rides the ticket so the executing
        #: thread (family batcher, metrics) can see its own cost view
        self.cost = None
        #: MEASURED footprint bytes of the finished execution (result +
        #: scanned-table resident bytes, `serving/cache.table_nbytes`
        #: accounting), recorded by TpuFrame.execute so the packing
        #: scheduler can reconcile its reservation on release
        #: (``serving.scheduler.reserve_drift``)
        self.measured_bytes = None

    def cancel(self) -> None:
        self._cancelled = True

    @property
    def cancelled(self) -> bool:
        return self._cancelled

    def expired(self) -> bool:
        return self.deadline is not None and time.monotonic() > self.deadline

    def remaining_s(self) -> Optional[float]:
        if self.deadline is None:
            return None
        return self.deadline - time.monotonic()

    def checkpoint(self) -> None:
        """Raise if this query should stop; called from executor hot paths."""
        if self._cancelled:
            raise QueryCancelledError(f"query {self.qid} cancelled")
        if self.expired():
            raise DeadlineExceededError(
                f"query {self.qid} exceeded its deadline")


class AdmissionController:
    """Bounded admission per concurrency class.

    Tracks waiting/running counts; `admit` either returns a ticket or
    sheds load with `QueueFullError`.  The retry-after hint scales with the
    observed average latency and current backlog so shed clients spread out
    instead of synchronizing their retries.
    """

    def __init__(self, bounds: Dict[str, int], workers: int,
                 retry_after_s: float = 1.0, metrics=None):
        self.bounds = {c: int(bounds.get(c, 32)) for c in CLASSES}
        self.workers = max(1, int(workers))
        self.retry_after_s = float(retry_after_s)
        self.metrics = metrics
        # rank 45: taken from under the runtime's cv (rank 40) on the
        # shed path; only leaf work (counter math, metrics) happens here
        self._lock = locks.named_lock("serving.admission")
        self.waiting = {c: 0 for c in CLASSES}
        self.running = {c: 0 for c in CLASSES}
        self._latency_sum = 0.0
        self._latency_n = 0

    # ------------------------------------------------------------ lifecycle
    def admit(self, qid: str, priority_class: str = "interactive",
              deadline_s: Optional[float] = None) -> QueryTicket:
        if priority_class not in self.bounds:
            # unknown class names (typo'd header, future class) fall back to
            # the documented default rather than silently demoting to batch
            priority_class = "interactive"
        with self._lock:
            bound = self.bounds[priority_class]
            if self.waiting[priority_class] >= bound:
                retry = self._retry_after_locked(priority_class)
                if self.metrics is not None:
                    self.metrics.inc("serving.rejected")
                    self.metrics.inc(f"serving.rejected.{priority_class}")
                raise QueueFullError(priority_class, bound, retry)
            self.waiting[priority_class] += 1
            if self.metrics is not None:
                self.metrics.inc("serving.admitted")
                self.metrics.inc(f"serving.admitted.{priority_class}")
        deadline = None if deadline_s is None \
            else time.monotonic() + float(deadline_s)
        return QueryTicket(qid, priority_class, deadline)

    def on_start(self, ticket: QueryTicket) -> None:
        ticket.started_at = time.monotonic()
        with self._lock:
            self.waiting[ticket.priority_class] -= 1
            self.running[ticket.priority_class] += 1
        if self.metrics is not None:
            self.metrics.observe(
                "serving.queue_wait_ms",
                (ticket.started_at - ticket.admitted_at) * 1000.0)

    def on_finish(self, ticket: QueryTicket, started: bool = True) -> None:
        now = time.monotonic()
        with self._lock:
            if started:
                self.running[ticket.priority_class] -= 1
                self._latency_sum += now - ticket.admitted_at
                self._latency_n += 1
            else:
                # never ran (cancelled / expired while queued)
                self.waiting[ticket.priority_class] -= 1

    # ------------------------------------------------------------- queries
    def depth(self, priority_class: Optional[str] = None) -> int:
        with self._lock:
            if priority_class is not None:
                return self.waiting[priority_class]
            return sum(self.waiting.values())

    def _retry_after_locked(self, priority_class: str) -> float:
        avg = self._latency_sum / self._latency_n if self._latency_n else 0.0
        backlog = sum(self.waiting.values()) + sum(self.running.values())
        est = avg * backlog / self.workers if avg else self.retry_after_s
        return min(retry_after_cap(), max(self.retry_after_s, est))

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            return {
                "bounds": dict(self.bounds),
                "waiting": dict(self.waiting),
                "running": dict(self.running),
                "avgLatencyMillis": int(
                    self._latency_sum / self._latency_n * 1000)
                if self._latency_n else 0,
            }
