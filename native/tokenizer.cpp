// SQL tokenizer — native component of the planner frontend.
//
// Role parity: the tokenizer under the reference's Rust DaskParser
// (src/parser.rs wraps sqlparser-rs).  Exposed through a C ABI consumed via
// ctypes (planner/native_bridge.py); the token-stream contract matches
// dask_sql_tpu/planner/lexer.py exactly (same types, same boundaries), so
// the Python lexer remains a drop-in fallback.
//
// Build: see native/Makefile (g++ -O2 -shared -fPIC).

#include <cstdint>
#include <cstring>

namespace {

enum TokenType : int32_t {
  TOK_IDENT = 0,
  TOK_QUOTED_IDENT = 1,
  TOK_NUMBER = 2,
  TOK_STRING = 3,
  TOK_OP = 4,
  TOK_PUNCT = 5,
  TOK_PARAM = 6,
};

inline bool is_space(char c) {
  return c == ' ' || c == '\t' || c == '\n' || c == '\r' || c == '\f' || c == '\v';
}
inline bool is_digit(char c) { return c >= '0' && c <= '9'; }
inline bool is_alpha(char c) {
  return (c >= 'a' && c <= 'z') || (c >= 'A' && c <= 'Z') ||
         static_cast<unsigned char>(c) >= 0x80;  // UTF-8 continuation-safe
}
inline bool is_ident_start(char c) { return is_alpha(c) || c == '_'; }
inline bool is_ident_part(char c) {
  return is_alpha(c) || is_digit(c) || c == '_' || c == '$';
}

inline bool is_one_char_op(char c) {
  switch (c) {
    case '+': case '-': case '*': case '/': case '%':
    case '<': case '>': case '=': case '~':
      return true;
    default:
      return false;
  }
}

inline bool is_punct(char c) {
  switch (c) {
    case '(': case ')': case ',': case '.': case ';':
    case '[': case ']': case '{': case '}': case ':': case '?':
      return true;
    default:
      return false;
  }
}

inline bool two_char_op(const char* s, int64_t n, int64_t i) {
  if (i + 1 >= n) return false;
  char a = s[i], b = s[i + 1];
  return (a == '<' && b == '=') || (a == '>' && b == '=') ||
         (a == '<' && b == '>') || (a == '!' && b == '=') ||
         (a == '|' && b == '|') || (a == ':' && b == ':') ||
         (a == '-' && b == '>');
}

}  // namespace

extern "C" {

// Tokenize `sql` (length n).  Writes up to `max_tokens` entries into the
// parallel arrays (type, byte offset of the token *content*, content length).
// For strings / quoted identifiers the offset+length cover the inner content
// (without quotes, escapes left in place for the wrapper to fold).
// Returns the token count, or -(errpos+1) on a lex error.
int64_t dsql_tokenize(const char* sql, int64_t n, int32_t* types,
                      int64_t* starts, int64_t* lens, int64_t max_tokens) {
  int64_t count = 0;
  int64_t i = 0;
  while (i < n) {
    char c = sql[i];
    if (is_space(c)) {
      ++i;
      continue;
    }
    if (c == '-' && i + 1 < n && sql[i + 1] == '-') {  // line comment
      while (i < n && sql[i] != '\n') ++i;
      continue;
    }
    if (c == '/' && i + 1 < n && sql[i + 1] == '*') {  // block comment
      int64_t j = i + 2;
      while (j + 1 < n && !(sql[j] == '*' && sql[j + 1] == '/')) ++j;
      if (j + 1 >= n) return -(i + 1);
      i = j + 2;
      continue;
    }
    if (count >= max_tokens) return -(i + 1);
    if (c == '\'') {  // string literal with '' escapes
      int64_t j = i + 1;
      while (true) {
        if (j >= n) return -(i + 1);
        if (sql[j] == '\'') {
          if (j + 1 < n && sql[j + 1] == '\'') {
            j += 2;
            continue;
          }
          break;
        }
        ++j;
      }
      types[count] = TOK_STRING;
      starts[count] = i + 1;
      lens[count] = j - (i + 1);
      ++count;
      i = j + 1;
      continue;
    }
    if (c == '"' || c == '`') {  // quoted identifier
      char quote = c;
      int64_t j = i + 1;
      while (true) {
        if (j >= n) return -(i + 1);
        if (sql[j] == quote) {
          if (j + 1 < n && sql[j + 1] == quote) {
            j += 2;
            continue;
          }
          break;
        }
        ++j;
      }
      types[count] = TOK_QUOTED_IDENT;
      starts[count] = i + 1;
      lens[count] = j - (i + 1);
      ++count;
      i = j + 1;
      continue;
    }
    if (is_digit(c) || (c == '.' && i + 1 < n && is_digit(sql[i + 1]))) {
      int64_t j = i;
      bool seen_dot = false, seen_exp = false;
      while (j < n) {
        char d = sql[j];
        if (is_digit(d)) {
          ++j;
        } else if (d == '.' && !seen_dot && !seen_exp) {
          seen_dot = true;
          ++j;
        } else if ((d == 'e' || d == 'E') && !seen_exp && j + 1 < n &&
                   (is_digit(sql[j + 1]) || sql[j + 1] == '+' || sql[j + 1] == '-')) {
          seen_exp = true;
          j += (sql[j + 1] == '+' || sql[j + 1] == '-') ? 2 : 1;
        } else {
          break;
        }
      }
      types[count] = TOK_NUMBER;
      starts[count] = i;
      lens[count] = j - i;
      ++count;
      i = j;
      continue;
    }
    if (is_ident_start(c)) {
      int64_t j = i;
      while (j < n && is_ident_part(sql[j])) ++j;
      types[count] = TOK_IDENT;
      starts[count] = i;
      lens[count] = j - i;
      ++count;
      i = j;
      continue;
    }
    if (two_char_op(sql, n, i)) {
      types[count] = TOK_OP;
      starts[count] = i;
      lens[count] = 2;
      ++count;
      i += 2;
      continue;
    }
    if (is_one_char_op(c)) {
      types[count] = TOK_OP;
      starts[count] = i;
      lens[count] = 1;
      ++count;
      ++i;
      continue;
    }
    if (c == '?') {
      types[count] = TOK_PARAM;
      starts[count] = i;
      lens[count] = 1;
      ++count;
      ++i;
      continue;
    }
    if (is_punct(c)) {
      types[count] = TOK_PUNCT;
      starts[count] = i;
      lens[count] = 1;
      ++count;
      ++i;
      continue;
    }
    return -(i + 1);
  }
  return count;
}

int32_t dsql_tokenizer_abi_version() { return 1; }

}  // extern "C"
