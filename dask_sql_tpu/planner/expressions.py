"""Typed relational expression IR.

Role parity: DataFusion `Expr` as exposed through the reference's `PyExpr`
(src/expression.rs: RexType classification expression.rs:318, operands/operator
expression.rs:333,458, result type expression.rs:511).  Bound, type-annotated,
and column references are positional — ready for the physical rex layer to
lower to jax kernels without name resolution.
"""
from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Any, List, Optional, Tuple

import numpy as np

from ..columnar.dtypes import SqlType


class RexType:
    REFERENCE = "RexType.Reference"
    CALL = "RexType.Call"
    LITERAL = "RexType.Literal"
    ALIAS = "RexType.Alias"
    SUBQUERY = "RexType.ScalarSubquery"


@dataclass(frozen=True)
class Field:
    name: str
    sql_type: SqlType
    nullable: bool = True


Schema = List[Field]


class Expr:
    sql_type: SqlType

    @property
    def rex_type(self) -> str:
        return RexType.CALL

    def children(self) -> List["Expr"]:
        return []

    def with_children(self, children: List["Expr"]) -> "Expr":
        return self


@dataclass(frozen=True)
class ColumnRef(Expr):
    index: int
    name: str
    sql_type: SqlType
    nullable: bool = True

    @property
    def rex_type(self) -> str:
        return RexType.REFERENCE

    def __str__(self):
        return f"#{self.index}:{self.name}"


@dataclass(frozen=True)
class Literal(Expr):
    value: Any
    sql_type: SqlType

    @property
    def rex_type(self) -> str:
        return RexType.LITERAL

    def __str__(self):
        return repr(self.value)


@dataclass(frozen=True)
class ParamRef(Expr):
    """A runtime query parameter: slot `index` of the per-query parameter
    vector (families/parameterize.py lifts eligible literals into these).

    Never produced by the binder — the parameterization pass creates them
    post-optimize so one compiled executable can serve a whole *family* of
    queries that differ only in literal values.  The repr/str carries the
    slot and the SQL type but NOT the value: two plans that differ only in
    parameterized literals stringify identically, which is exactly what
    keys the family fingerprint and the compiled-pipeline caches."""

    index: int
    sql_type: SqlType

    @property
    def rex_type(self) -> str:
        return RexType.LITERAL

    def __str__(self):
        return f"?{self.index}:{self.sql_type.name}"


@dataclass(frozen=True)
class InParamExpr(Expr):
    """Membership test against a runtime parameter *vector*: the
    parameterized form of an all-literal ``IN (...)`` list.

    The value list itself lives in the query's parameter vector (slot
    `index`), host-normalized to `cmp_dtype` and padded to the power-of-two
    `length` bucket — so IN lists of 5, 6 and 8 values share one compiled
    kernel (bucket 8) while a 9-value list is its own family.  Padding
    repeats an existing member, which cannot change membership."""

    arg: Expr
    index: int
    length: int  # pow2 value-vector length (the family's bucket)
    cmp_dtype: str  # numpy dtype name the comparison runs in
    negated: bool = False
    sql_type: SqlType = SqlType.BOOLEAN

    def children(self):
        return [self.arg]

    def with_children(self, children):
        return replace(self, arg=children[0])

    def __str__(self):
        neg = " negated" if self.negated else ""
        return (f"in_param({self.arg}, ?{self.index}x{self.length}"
                f":{self.cmp_dtype}{neg})")


@dataclass(frozen=True)
class ScalarFunc(Expr):
    """A call of a named kernel op — the unit the physical rex layer maps.

    Canonical op names are the keys of `physical.rex.operations.OPERATION_MAPPING`
    (parity: reference call.py:1047-1156).
    """

    op: str
    args: Tuple[Expr, ...]
    sql_type: SqlType

    def children(self):
        return list(self.args)

    def with_children(self, children):
        return replace(self, args=tuple(children))

    def __str__(self):
        return f"{self.op}({', '.join(map(str, self.args))})"


@dataclass(frozen=True)
class GroupingExpr(Expr):
    """GROUPING(e1, ...) — binder-internal marker, resolved during grouping
    sets expansion to a per-branch literal bitmask (leftmost arg = most
    significant bit, 1 = aggregated in this set).  Parity: the reference
    surfaces DataFusion's grouping-id through aggregate.rs getGroupSets;
    here the binder lowers it while expanding ROLLUP/CUBE/GROUPING SETS."""

    args: Tuple[Expr, ...]
    sql_type: SqlType

    def children(self):
        return list(self.args)

    def with_children(self, children):
        return replace(self, args=tuple(children))

    def __str__(self):
        return f"grouping({', '.join(map(str, self.args))})"


@dataclass(frozen=True)
class Cast(Expr):
    arg: Expr
    sql_type: SqlType
    safe: bool = False

    def children(self):
        return [self.arg]

    def with_children(self, children):
        return replace(self, arg=children[0])

    def __str__(self):
        return f"CAST({self.arg} AS {self.sql_type})"


@dataclass(frozen=True)
class CaseExpr(Expr):
    whens: Tuple[Tuple[Expr, Expr], ...]
    else_: Optional[Expr]
    sql_type: SqlType

    def children(self):
        out = []
        for c, r in self.whens:
            out += [c, r]
        if self.else_ is not None:
            out.append(self.else_)
        return out

    def with_children(self, children):
        n = len(self.whens)
        whens = tuple((children[2 * i], children[2 * i + 1]) for i in range(n))
        else_ = children[2 * n] if len(children) > 2 * n else None
        return replace(self, whens=whens, else_=else_)


@dataclass(frozen=True)
class InListExpr(Expr):
    arg: Expr
    items: Tuple[Expr, ...]
    negated: bool
    sql_type: SqlType = SqlType.BOOLEAN

    def children(self):
        return [self.arg, *self.items]

    def with_children(self, children):
        return replace(self, arg=children[0], items=tuple(children[1:]))


@dataclass(frozen=True, eq=False)
class InArrayExpr(Expr):
    """Membership test against a bulk host array (plan-time generated filters).

    Role parity: the reference's DynamicPartitionPruning injects `InList`
    filters with thousands of values (dynamic_partition_pruning.rs:1-8);
    carrying them as one numpy array keeps plan walks O(1) in the value
    count and lets the kernels evaluate membership with a single vectorized
    sorted-lookup instead of one comparison per value.

    `values` is already normalized to the comparison domain: numerics keep
    their numpy dtype, datetimes are int64 nanoseconds, strings are an
    object array.  Identity equality (eq=False) — the array payload makes
    structural equality both expensive and unnecessary.
    """

    arg: Expr
    values: Any  # np.ndarray, sorted unique, no nulls
    negated: bool = False
    sql_type: SqlType = SqlType.BOOLEAN

    def children(self):
        return [self.arg]

    def with_children(self, children):
        return replace(self, arg=children[0])

    def __repr__(self):
        # content digest: str(expr) keys compiled-plan caches, so two arrays
        # with equal length but different values must stringify differently
        import hashlib

        v = np.ascontiguousarray(self.values)
        digest = hashlib.sha1(v.tobytes() + str(v.dtype).encode()).hexdigest()[:12]
        return (f"InArray(arg={self.arg!r}, n={len(self.values)}, "
                f"digest={digest}, negated={self.negated})")


@dataclass(frozen=True)
class AggExpr(Expr):
    """Aggregate call inside an Aggregate plan node (parity aggregate.rs:24-58)."""

    func: str
    args: Tuple[Expr, ...]
    sql_type: SqlType
    distinct: bool = False
    filter: Optional[Expr] = None

    def children(self):
        return list(self.args) + ([self.filter] if self.filter is not None else [])

    def with_children(self, children):
        if self.filter is not None:
            return replace(self, args=tuple(children[:-1]), filter=children[-1])
        return replace(self, args=tuple(children))

    def __str__(self):
        inner = ", ".join(map(str, self.args))
        d = "DISTINCT " if self.distinct else ""
        return f"{self.func}({d}{inner})"


@dataclass(frozen=True)
class SortKey:
    expr: Expr
    ascending: bool = True
    nulls_first: Optional[bool] = None

    def nulls_first_resolved(self) -> bool:
        # SQL default: NULLS LAST for ASC, NULLS FIRST for DESC (Calcite/Postgres)
        if self.nulls_first is None:
            return not self.ascending
        return self.nulls_first


@dataclass(frozen=True)
class WindowFrameBound:
    kind: str  # UNBOUNDED_PRECEDING / PRECEDING / CURRENT_ROW / FOLLOWING / UNBOUNDED_FOLLOWING
    offset: Optional[int] = None


@dataclass(frozen=True)
class WindowSpec:
    partition_by: Tuple[Expr, ...]
    order_by: Tuple[SortKey, ...]
    units: str = "ROWS"  # ROWS | RANGE
    start: WindowFrameBound = WindowFrameBound("UNBOUNDED_PRECEDING")
    end: WindowFrameBound = WindowFrameBound("CURRENT_ROW")
    explicit_frame: bool = False


@dataclass(frozen=True)
class WindowExpr(Expr):
    func: str
    args: Tuple[Expr, ...]
    spec: WindowSpec
    sql_type: SqlType
    ignore_nulls: bool = False

    def children(self):
        return (list(self.args) + list(self.spec.partition_by)
                + [k.expr for k in self.spec.order_by])

    def with_children(self, children):
        na, np_ = len(self.args), len(self.spec.partition_by)
        args = tuple(children[:na])
        part = tuple(children[na : na + np_])
        order = tuple(
            replace(k, expr=children[na + np_ + i]) for i, k in enumerate(self.spec.order_by)
        )
        return replace(self, args=args, spec=replace(self.spec, partition_by=part, order_by=order))


@dataclass(frozen=True)
class ScalarSubqueryExpr(Expr):
    plan: Any  # LogicalPlan
    sql_type: SqlType

    @property
    def rex_type(self) -> str:
        return RexType.SUBQUERY


@dataclass(frozen=True)
class InSubqueryExpr(Expr):
    arg: Expr
    plan: Any  # LogicalPlan producing one column
    negated: bool
    sql_type: SqlType = SqlType.BOOLEAN

    def children(self):
        return [self.arg]

    def with_children(self, children):
        return replace(self, arg=children[0])


@dataclass(frozen=True)
class ExistsExpr(Expr):
    plan: Any
    negated: bool
    sql_type: SqlType = SqlType.BOOLEAN


@dataclass(frozen=True)
class UdfExpr(Expr):
    """Call of a user-registered function (context.register_function parity)."""

    name: str
    args: Tuple[Expr, ...]
    sql_type: SqlType
    row_udf: bool = False

    def children(self):
        return list(self.args)

    def with_children(self, children):
        return replace(self, args=tuple(children))


# ---------------------------------------------------------------------------
# Traversal helpers
# ---------------------------------------------------------------------------
def walk(expr: Expr):
    yield expr
    for c in expr.children():
        yield from walk(c)


def transform(expr: Expr, fn) -> Expr:
    """Bottom-up rewrite."""
    kids = [transform(c, fn) for c in expr.children()]
    return fn(expr.with_children(kids))


def referenced_columns(expr: Expr) -> set:
    return {e.index for e in walk(expr) if isinstance(e, ColumnRef)}


def shift_columns(expr: Expr, delta: int) -> Expr:
    def fn(e):
        if isinstance(e, ColumnRef):
            return replace(e, index=e.index + delta)
        return e

    return transform(expr, fn)


def remap_columns(expr: Expr, mapping: dict) -> Expr:
    def fn(e):
        if isinstance(e, ColumnRef):
            return replace(e, index=mapping[e.index])
        return e

    return transform(expr, fn)
