"""NYC-taxi-style workload (BASELINE config 5 shape): windowed hourly
aggregation + percentile UDAF over timestamps."""
import numpy as np
import pandas as pd
import pytest

from tests.utils import assert_eq


@pytest.fixture
def taxi(c):
    rng = np.random.RandomState(11)
    n = 5000
    start = np.datetime64("2015-01-01")
    pickup = start + rng.randint(0, 7 * 24 * 3600, n).astype("timedelta64[s]")
    df = pd.DataFrame({
        "pickup": pickup.astype("datetime64[ns]"),
        "fare": np.round(3 + rng.gamma(2.0, 6.0, n), 2),
        "distance": np.round(rng.gamma(1.5, 2.0, n), 2),
        "zone": rng.choice(["manhattan", "brooklyn", "queens", "bronx"], n),
    })
    c.create_table("taxi", df)
    return df


def test_hourly_aggregation(c, taxi):
    result = c.sql(
        """SELECT FLOOR(pickup TO HOUR) AS h, COUNT(*) AS trips,
                  AVG(fare) AS avg_fare, SUM(distance) AS total_dist
           FROM taxi GROUP BY FLOOR(pickup TO HOUR) ORDER BY h"""
    ).compute()
    expected = (taxi.assign(h=taxi.pickup.dt.floor("h"))
                .groupby("h").agg(trips=("fare", "count"), avg_fare=("fare", "mean"),
                                  total_dist=("distance", "sum")).reset_index())
    assert_eq(result, expected, check_dtype=False)


def test_percentile_udaf(c, taxi):
    c.register_aggregation(lambda g: g.quantile(0.9), "perc90",
                           [("x", np.float64)], np.float64)
    result = c.sql(
        "SELECT zone, perc90(fare) AS p90 FROM taxi GROUP BY zone"
    ).compute().sort_values("zone").reset_index(drop=True)
    expected = (taxi.groupby("zone").fare.quantile(0.9).reset_index(name="p90")
                .sort_values("zone").reset_index(drop=True))
    np.testing.assert_allclose(result["p90"], expected["p90"], rtol=1e-9)


def test_windowed_running_fare(c, taxi):
    result = c.sql(
        """SELECT zone, fare,
                  AVG(fare) OVER (PARTITION BY zone ORDER BY pickup
                                  ROWS BETWEEN 99 PRECEDING AND CURRENT ROW) AS run_avg
           FROM taxi"""
    ).compute()
    srt = taxi.sort_values(["zone", "pickup"])
    expected = srt.groupby("zone").fare.rolling(100, min_periods=1).mean()
    assert len(result) == len(taxi)
    # spot check one zone ordering
    zone = "queens"
    got = result[result.zone == zone]
    assert len(got) == (taxi.zone == zone).sum()


def test_hourly_window_rank(c, taxi):
    result = c.sql(
        """SELECT h, trips, RANK() OVER (ORDER BY trips DESC) AS r
           FROM (SELECT FLOOR(pickup TO HOUR) AS h, COUNT(*) AS trips
                 FROM taxi GROUP BY FLOOR(pickup TO HOUR)) AS hourly
           ORDER BY r LIMIT 10"""
    ).compute()
    assert list(result["r"])[:1] == [1]
    assert (result["trips"].diff().dropna() <= 0).all()


def test_determinism(c, taxi):
    q = "SELECT zone, SUM(fare) AS s FROM taxi GROUP BY zone ORDER BY zone"
    a = c.sql(q).compute()
    b = c.sql(q).compute()
    pd.testing.assert_frame_equal(a, b)
