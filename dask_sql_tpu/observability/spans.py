"""Query-lifecycle span model.

The executor's per-plan-node `Tracer` (tracing.py) answers "where inside
the plan did device time go?" — but a served query spends most of its
*lifecycle* outside the plan walk: queue wait, parse, bind, verify,
estimate, per-rung XLA compiles, d2h transfer, wire serialization.  TQP
(arXiv:2203.01877) and Flare (arXiv:1703.08219) both lean on staged
instrumentation of compiled pipelines to attribute tensor-runtime time;
this module is that instrumentation for the whole engine:

- `QueryTrace`: one trace per query (Context API or Presto server), a flat
  list of `Span`s — sequential lifecycle *stages* (queue_wait, cache_lookup,
  parse, bind, optimize, verify, estimate, execute, d2h, serialize),
  *detail* spans nested inside a stage (per-rung XLA compiles, the
  executor's per-node tree), and zero-duration *events* (resilience-ladder
  degradations, breaker skips, estimator rung-proof skips).
- A `contextvars` activation scope: `activate(trace)` installs the trace
  for the current thread of control, so the planner, the ladder and the
  compiled pipelines can attach spans without threading a handle through
  every signature — and 8 Presto worker threads each see only their own
  trace (contextvars are per-thread for `threading.Thread` workers).
- Chrome-trace export (`to_chrome_trace`): the JSON the `trace event
  profiling` format of chrome://tracing / Perfetto loads directly,
  downloadable at ``/v1/trace/{qid}`` and emitted by
  ``EXPLAIN ANALYZE FORMAT JSON``.
- `timed_jit_call`: wraps a `jax.jit` callable invocation and records a
  ``compile:<rung>`` span + ``resilience.compile_ms.<rung>`` histogram +
  per-fingerprint profile entry whenever the call triggered a fresh XLA
  compile (detected via the jit cache-size delta).  The recorded wall time
  is the first-call time — trace + lower + XLA compile + first dispatch —
  which is the cost a cold fingerprint actually pays; warm calls are never
  recorded.

Span clocks: `time.perf_counter()` (monotonic, process-wide comparable);
each trace also carries an epoch anchor so exported timestamps are
wall-clock meaningful.
"""
from __future__ import annotations

import contextlib
import contextvars
import threading
import time
import uuid
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

#: span kinds: "stage" spans are the sequential lifecycle phases (disjoint
#: by construction), "detail" spans nest inside a stage (compiles, plan
#: nodes), "event" spans are zero-duration markers
STAGE, DETAIL, EVENT = "stage", "detail", "event"


@dataclass
class Span:
    name: str
    t0: float  # perf_counter seconds
    t1: Optional[float] = None  # None while open
    kind: str = STAGE
    parent: Optional[str] = None  # enclosing stage name for detail spans
    attrs: Dict[str, Any] = field(default_factory=dict)

    @property
    def dur_ms(self) -> Optional[float]:
        return None if self.t1 is None else (self.t1 - self.t0) * 1000.0


class QueryTrace:
    """All spans of one query, id'd and exportable.

    Spans are appended under a lock: the HTTP status-poll thread appends
    the serialize span while the trace already lives in the store."""

    def __init__(self, sql: Optional[str] = None, qid: Optional[str] = None,
                 metrics=None, profiles=None):
        self.trace_id = uuid.uuid4().hex[:16]
        self.qid = qid or self.trace_id
        self.sql = (sql or "").strip()[:500]
        #: the context's MetricsRegistry / ProfileStore, so span recorders
        #: deep in the engine (timed_jit_call) reach them without a Context
        self.metrics = metrics
        self.profiles = profiles
        self.fingerprint: Optional[str] = None
        self.spans: List[Span] = []
        #: qids of causally linked queries (a batch member links its
        #: leader, the leader links its members): /v1/trace/{qid} merges
        #: linked traces into one multi-process Chrome export so the flow
        #: arrows have both endpoints loaded
        self.links: List[str] = []
        self._lock = threading.Lock()
        self.created_perf = time.perf_counter()
        #: epoch - perf offset: export wall-clock timestamps from perf spans
        self.epoch_offset = time.time() - self.created_perf
        self.finished = False
        self.slow_logged = False

    # ------------------------------------------------------------- writes
    def add_span(self, name: str, t0: float, t1: Optional[float],
                 kind: str = STAGE, parent: Optional[str] = None,
                 **attrs) -> Span:
        span = Span(name, t0, t1, kind, parent, dict(attrs))
        with self._lock:
            self.spans.append(span)
        return span

    @contextlib.contextmanager
    def span(self, name: str, kind: str = STAGE,
             parent: Optional[str] = None, **attrs):
        """Scoped span, appended OPEN at entry (t1=None) so a reader that
        renders mid-span — EXPLAIN ANALYZE reporting from inside its own
        execute stage — sees it as "(open)"; closed in the finally.  A
        failure inside is recorded on the span and re-raised unchanged."""
        span = self.add_span(name, time.perf_counter(), None, kind, parent,
                             **attrs)
        try:
            yield span.attrs  # callers may add attrs while the span is open
        except BaseException as exc:
            span.attrs["error"] = type(exc).__name__
            raise
        finally:
            span.t1 = time.perf_counter()

    def add_span_once(self, name: str, t0: float, t1: Optional[float],
                      kind: str = STAGE, parent: Optional[str] = None,
                      **attrs) -> bool:
        """Append unless a span of this name exists — one atomic
        check-and-add, so concurrent recorders (two status polls both
        serializing the same finished query) cannot duplicate a stage."""
        with self._lock:
            if any(s.name == name for s in self.spans):
                return False
            self.spans.append(Span(name, t0, t1, kind, parent, dict(attrs)))
            return True

    def event(self, name: str, **attrs) -> Span:
        t = time.perf_counter()
        return self.add_span(name, t, t, EVENT, **attrs)

    def link(self, qid: Optional[str]) -> None:
        """Record a causal link to another query's trace (idempotent)."""
        if not qid or qid == self.qid:
            return
        with self._lock:
            if qid not in self.links:
                self.links.append(qid)

    def finish(self, config=None, metrics=None) -> None:
        """Idempotent end-of-lifecycle hook: first call wins and runs the
        slow-query check (observability/slowlog.py)."""
        with self._lock:
            if self.finished:
                return
            self.finished = True
        if config is not None:
            from .slowlog import maybe_log_slow

            maybe_log_slow(self, config, metrics or self.metrics)

    # -------------------------------------------------------------- reads
    def has_span(self, name: str) -> bool:
        with self._lock:
            return any(s.name == name for s in self.spans)

    def stage_spans(self) -> List[Span]:
        """Closed lifecycle stages, sorted by start time."""
        with self._lock:
            out = [s for s in self.spans if s.kind == STAGE
                   and s.t1 is not None]
        return sorted(out, key=lambda s: s.t0)

    def total_ms(self) -> float:
        with self._lock:
            closed = [s for s in self.spans if s.t1 is not None]
        if not closed:
            return 0.0
        return (max(s.t1 for s in closed) - min(s.t0 for s in closed)) * 1e3

    def attach_node_tree(self, root, parent: str = "execute") -> None:
        """Fold an executor `NodeTrace` tree (tracing.py) in as detail
        spans — real timestamps (NodeTrace records its start), so the
        Chrome trace nests them inside the execute stage."""
        if root is None:
            return
        stack = [root]
        while stack:
            node = stack.pop()
            self.add_span(
                node.node_type, node.t0, node.t0 + node.wall_ms / 1e3,
                kind=DETAIL, parent=parent, label=node.label,
                rows=(node.rows if node.rows >= 0 else None))
            stack.extend(node.children)

    # ------------------------------------------------------------- export
    def chrome_events(self, pid: int = 1) -> List[Dict[str, Any]]:
        """This trace's Chrome-trace event list under process id ``pid``.
        Spans/events carrying ``flow_out`` / ``flow_in`` attrs (cross-query
        causality: batch member -> leader launch, background recompile ->
        trigger) additionally emit flow events (ph=s / ph=f) sharing a
        stable numeric id, so Perfetto draws the arrow — across processes
        when linked traces are merged into one export."""
        import zlib

        with self._lock:
            spans = list(self.spans)
        events: List[Dict[str, Any]] = [{
            "name": "process_name", "ph": "M", "pid": pid,
            "args": {"name": f"dask-sql-tpu query {self.qid}"},
        }, {
            "name": "thread_name", "ph": "M", "pid": pid, "tid": 1,
            "args": {"name": "query lifecycle"},
        }]
        for s in spans:
            ts = (s.t0 + self.epoch_offset) * 1e6
            args = {k: v for k, v in s.attrs.items() if v is not None}
            if s.parent:
                args["stage"] = s.parent
            if s.kind == EVENT:
                events.append({"name": s.name, "ph": "i", "ts": ts,
                               "pid": pid, "tid": 1, "s": "t", "args": args})
            else:
                dur = 0.0 if s.t1 is None else (s.t1 - s.t0) * 1e6
                events.append({"name": s.name, "ph": "X", "ts": ts,
                               "dur": dur, "cat": s.kind, "pid": pid,
                               "tid": 1, "args": args})
            for key, ph in (("flow_out", "s"), ("flow_in", "f")):
                flow = s.attrs.get(key)
                if flow is None:
                    continue
                ev = {"name": s.name, "cat": "dsql.flow", "ph": ph,
                      "id": zlib.crc32(str(flow).encode()), "ts": ts,
                      "pid": pid, "tid": 1}
                if ph == "f":
                    ev["bp"] = "e"  # bind to the enclosing slice
                events.append(ev)
        return events

    def to_chrome_trace(self) -> Dict[str, Any]:
        """The Chrome `trace event profiling` JSON object (ph=X complete
        events, microsecond timestamps) chrome://tracing and Perfetto load
        directly.  Stages and their nested details share tid 1 (nesting by
        containment); events become ph=i instants."""
        with self._lock:
            links = list(self.links)
        return {
            "displayTimeUnit": "ms",
            "traceEvents": self.chrome_events(),
            "otherData": {
                "traceId": self.trace_id,
                "qid": self.qid,
                "sql": self.sql,
                "fingerprint": self.fingerprint,
                "links": links,
            },
        }

    def format_lines(self) -> List[str]:
        """The lifecycle header EXPLAIN ANALYZE prints above the node
        tree: one line per stage in start order, events inline."""
        with self._lock:
            spans = sorted(self.spans, key=lambda s: s.t0)
        lines = [f"-- query lifecycle (trace {self.trace_id}"
                 + (f", fingerprint {self.fingerprint}" if self.fingerprint
                    else "") + ") --"]
        for s in spans:
            if s.kind == DETAIL and not s.name.startswith("compile:"):
                continue  # the node tree renders itself below the header
            if s.kind == EVENT:
                lines.append(f"  !! {s.name}")
                continue
            dur = "(open)" if s.t1 is None else f"{s.dur_ms:10.2f} ms"
            pad = "    " if s.kind == DETAIL else "  "
            lines.append(f"{pad}{s.name:<14} {dur}")
        return lines


def merge_chrome_traces(traces: List["QueryTrace"]) -> Dict[str, Any]:
    """One Chrome-trace JSON over several causally linked traces — each
    query its own process row, flow arrows crossing between them (the
    ``/v1/trace/{qid}`` export when the trace carries links)."""
    events: List[Dict[str, Any]] = []
    for i, tr in enumerate(traces):
        events.extend(tr.chrome_events(pid=i + 1))
    head = traces[0]
    return {
        "displayTimeUnit": "ms",
        "traceEvents": events,
        "otherData": {
            "traceId": head.trace_id,
            "qid": head.qid,
            "sql": head.sql,
            "fingerprint": head.fingerprint,
            "merged": [tr.qid for tr in traces],
        },
    }


class TraceStore:
    """Bounded qid -> QueryTrace LRU; the backing store of
    ``/v1/trace/{qid}`` and `Context.last_trace`."""

    def __init__(self, keep: int = 256):
        self.keep = max(1, int(keep))
        self._lock = threading.Lock()
        self._traces: "OrderedDict[str, QueryTrace]" = OrderedDict()

    def put(self, qid: str, trace: QueryTrace) -> None:
        with self._lock:
            self._traces[qid] = trace
            self._traces.move_to_end(qid)
            while len(self._traces) > self.keep:
                self._traces.popitem(last=False)

    def get(self, qid: str) -> Optional[QueryTrace]:
        with self._lock:
            return self._traces.get(qid)

    def __len__(self) -> int:
        with self._lock:
            return len(self._traces)


# ---------------------------------------------------------------------------
# activation scope
# ---------------------------------------------------------------------------
_current: "contextvars.ContextVar[Optional[QueryTrace]]" = \
    contextvars.ContextVar("dsql_query_trace", default=None)


def current_trace() -> Optional[QueryTrace]:
    """The QueryTrace of the query running on this thread, if any."""
    return _current.get()


@contextlib.contextmanager
def activate(trace: Optional[QueryTrace]):
    """Install `trace` as the current trace for the dynamic extent."""
    token = _current.set(trace)
    try:
        yield trace
    finally:
        _current.reset(token)


def stage(name: str, **attrs):
    """Scoped stage span on the active trace — a no-op context manager
    when no trace is active, so instrumented code never branches.  Also
    stamps the stage onto the in-flight query table (live.py), which works
    with tracing disabled too."""
    from . import live

    live.update(stage=name)
    tr = current_trace()
    if tr is None:
        return contextlib.nullcontext({})
    return tr.span(name, kind=STAGE, **attrs)


def detail(name: str, parent: str = "execute", **attrs):
    """Scoped DETAIL span nested under ``parent`` on the active trace —
    a no-op context manager without one.  The streaming drive loop uses
    this so each partition renders as a child of the execute stage."""
    tr = current_trace()
    if tr is None:
        return contextlib.nullcontext({})
    return tr.span(name, kind=DETAIL, parent=parent, **attrs)


def trace_event(name: str, **attrs) -> None:
    """Zero-duration marker on the active trace (ladder degradations,
    breaker skips, rung-proof skips, admission sheds); no-op without one."""
    tr = current_trace()
    if tr is not None:
        tr.event(name, **attrs)


# ---------------------------------------------------------------------------
# per-rung compile timing
# ---------------------------------------------------------------------------
#: (metrics, profiles, fingerprint, sql) of the executing query — installed
#: by TpuFrame.execute for EVERY execution, trace enabled or not, so
#: compile histograms and profiles never go dark when tracing is off
_sink: "contextvars.ContextVar[Optional[tuple]]" = \
    contextvars.ContextVar("dsql_compile_sink", default=None)


@contextlib.contextmanager
def compile_sink(metrics, profiles=None, fingerprint: Optional[str] = None,
                 sql: Optional[str] = None, family: Optional[str] = None):
    """Install the metric/profile destinations for `timed_jit_call` over
    the dynamic extent of one query execution.  `family` is the query's
    literal-stripped family fingerprint (families/), recorded on the
    profile entry so SHOW PROFILES can group and warm-up can dedupe."""
    token = _sink.set((metrics, profiles, fingerprint, sql, family))
    try:
        yield
    finally:
        _sink.reset(token)


def _jit_cache_size(fn) -> Optional[int]:
    try:
        return fn._cache_size()
    except Exception:  # dsql: allow-broad-except — jit internals are
        # version-dependent introspection; no size just means no timing
        return None


def timed_jit_call(rung: str, fn, *args, may_compile: Optional[bool] = None,
                   **kwargs):
    """Invoke a `jax.jit` callable, recording the call as a fresh XLA
    compile for `rung` when the jit's executable cache grew.

    Recorded (only on a compile): a ``resilience.compile_ms.<rung>``
    histogram observation and a per-fingerprint ProfileStore entry (via the
    installed `compile_sink` — independent of tracing, so SHOW METRICS and
    the pre-warm input stay populated with tracing disabled), plus a
    ``compile:<rung>`` detail span when a trace is active.  When the
    persistent executable cache (serving/compile_cache.py) is enabled, the
    span carries a ``persistent_hit`` flag and the compile is counted as
    ``resilience.compile_cache.hit`` / ``.miss``.

    ``may_compile`` is the caller's hint about whether THIS call can
    trigger a fresh compile (False = the shape is known-warm).  When a
    compile is possible and ``resilience.compile_timeout_ms`` is set, the
    call runs under the compile watchdog (resilience/watchdog.py): a hung
    or exploding compile raises a degradable `CompileTimeoutError` instead
    of wedging the serving worker."""
    metrics = profiles = fingerprint = sql = family = None
    sink = _sink.get()
    if sink is not None:
        metrics, profiles, fingerprint, sql, family = sink
    tr = current_trace()
    if tr is not None and metrics is None:
        metrics = tr.metrics
    before = _jit_cache_size(fn)
    pc_hits0 = None
    from ..serving import compile_cache

    if compile_cache.enabled_path() is not None:
        pc_hits0 = compile_cache.hit_count()
    t0 = time.perf_counter()
    deadline_ms = None
    if may_compile is not False:
        from ..config import config as _config
        from ..resilience import faults, watchdog

        deadline_ms = watchdog.timeout_ms(_config)
    if deadline_ms is not None:
        out = watchdog.watched_call(
            rung, fn, args, kwargs, deadline_ms=deadline_ms,
            hang_s=faults.hang_duration("compile_hang", _config),
            metrics=metrics)
    else:
        out = fn(*args, **kwargs)
    if before is None:
        return out
    after = _jit_cache_size(fn)
    if after is None or after <= before:
        return out
    t1 = time.perf_counter()
    ms = (t1 - t0) * 1000.0
    persistent_hit = None
    if pc_hits0 is not None:
        # best-effort attribution: a concurrent query's compile can land in
        # the same window, but a false positive only flips a trace flag
        persistent_hit = compile_cache.hit_count() > pc_hits0
        if metrics is not None:
            metrics.inc("resilience.compile_cache.hit" if persistent_hit
                        else "resilience.compile_cache.miss")
    if tr is not None:
        fingerprint = tr.fingerprint or fingerprint
        tr.add_span(f"compile:{rung}", t0, t1, kind=DETAIL, parent="execute",
                    rung=rung, fingerprint=fingerprint,
                    persistent_hit=persistent_hit)
        profiles = profiles if profiles is not None else tr.profiles
        sql = sql or tr.sql
    if metrics is not None:
        metrics.observe(f"resilience.compile_ms.{rung}", ms)
    if profiles is not None and fingerprint:
        profiles.record_compile(fingerprint, rung, ms, sql=sql,
                                family=family)
    from . import flight

    qid = tr.qid if tr is not None else None
    if qid is None:
        from ..serving.runtime import current_ticket

        ticket = current_ticket()
        qid = ticket.qid if ticket is not None else None
    # start/end pair stamped retrospectively — a compile is only known to
    # have happened once the jit cache grew, but the recorder accepts
    # explicit timestamps so the timeline still shows the true window
    wall_end = time.time()
    flight.record("compile.start", qid=qid, ts=wall_end - ms / 1e3,
                  rung=rung)
    flight.record("compile.end", qid=qid, ts=wall_end, rung=rung,
                  ms=round(ms, 3), persistent_hit=persistent_hit)
    return out
