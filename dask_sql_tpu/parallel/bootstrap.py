"""Multi-host process bootstrap: the coordination layer that replaces the
reference's external dask scheduler.

Role parity: the reference connects every front-end to a scheduler address
(`Client(scheduler_address)` in reference server/app.py:249-252 and
cmd.py:207-214) and lets dask.distributed coordinate workers.  The TPU-native
equivalent is JAX's multi-controller runtime: every host runs the SAME
program, `jax.distributed.initialize` wires them into one runtime, and
`jax.devices()` then spans all hosts — meshes built over it place collectives
on ICI within a slice and DCN across slices with no further engine changes
(SURVEY.md §2.4).

Environment contract (mirrors the reference's scheduler-address argument):

    DSQL_COORDINATOR   host:port of process 0 (e.g. "10.0.0.1:8476")
    DSQL_NUM_PROCESSES total process count
    DSQL_PROCESS_ID    this process's rank (0-based)

`initialize_from_env()` is idempotent and a no-op when the variables are
absent (single-host operation needs no coordinator, exactly like running the
reference without a scheduler address).
"""
from __future__ import annotations

import logging
import os
from typing import Optional

logger = logging.getLogger(__name__)

_initialized = False


def initialize_from_env(timeout_s: Optional[int] = None) -> bool:
    """Join the multi-host runtime described by DSQL_* env vars.

    Returns True when running multi-host (after initialize), False for
    single-host.  Safe to call repeatedly; only the first call acts."""
    global _initialized
    if _initialized:
        return True
    coordinator = os.environ.get("DSQL_COORDINATOR")
    if not coordinator:
        return False
    num_processes = int(os.environ.get("DSQL_NUM_PROCESSES", "1"))
    process_id = int(os.environ.get("DSQL_PROCESS_ID", "0"))
    import jax

    kwargs = {}
    if timeout_s is not None:
        kwargs["initialization_timeout"] = timeout_s
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
        **kwargs,
    )
    _initialized = True
    logger.info("joined multi-host runtime: process %d/%d via %s",
                process_id, num_processes, coordinator)
    return True


def is_multihost() -> bool:
    import jax

    return jax.process_count() > 1


def process_index() -> int:
    import jax

    return jax.process_index()


def make_global_array(host_arr, sharding):
    """Place a host array under a (possibly multi-host) NamedSharding.

    Single-host this is jax.device_put; multi-host every process holds the
    SAME full host array (SPMD ingest — each host generated or read identical
    input) and contributes only its addressable shards."""
    import jax
    import numpy as np

    if not is_multihost():
        return jax.device_put(host_arr, sharding)
    host_arr = np.asarray(host_arr)
    return jax.make_array_from_callback(
        host_arr.shape, sharding, lambda idx: host_arr[idx])


def host_read(arr):
    """numpy value of a (possibly multi-host sharded) device array.

    Single-host (or fully-addressable) arrays read directly; global arrays
    spanning other processes are first replicated with an XLA all-gather —
    every process then reads its local replica (SPMD: all processes call
    this at the same point)."""
    import jax
    import numpy as np

    from ..utils import count_d2h

    count_d2h()
    if not hasattr(arr, "sharding") or getattr(
            arr, "is_fully_addressable", True):
        return np.asarray(arr)
    from jax.sharding import NamedSharding, PartitionSpec

    sharding = arr.sharding
    rep = jax.jit(
        lambda x: x,
        out_shardings=NamedSharding(sharding.mesh, PartitionSpec()))(arr)
    return np.asarray(rep)


def all_processes_allgather(local_np):
    """Host-level allgather of small numpy arrays (result assembly on every
    host, e.g. pulling a replicated aggregate to the driver process)."""
    import jax

    if not is_multihost():
        return local_np
    from jax.experimental import multihost_utils

    return multihost_utils.process_allgather(local_np)
