"""Input conversion driver (parity: reference input_utils/convert.py:43-92)."""
from __future__ import annotations

from typing import Any, List, Optional

from ..datacontainer import DataContainer
from .base import BaseInputPlugin
from .plugins import (
    ArrowInputPlugin,
    DeviceTableInputPlugin,
    DictInputPlugin,
    HiveInputPlugin,
    IntakeCatalogInputPlugin,
    LocationInputPlugin,
    PandasLikeInputPlugin,
    SqlalchemyInputPlugin,
)


class InputUtil:
    _plugins: List[BaseInputPlugin] = [
        DeviceTableInputPlugin(),
        ArrowInputPlugin(),
        PandasLikeInputPlugin(),
        DictInputPlugin(),
        HiveInputPlugin(),
        IntakeCatalogInputPlugin(),
        SqlalchemyInputPlugin(),
        LocationInputPlugin(),  # last: strings are the most generic
    ]

    @classmethod
    def add_plugin_class(cls, plugin_class) -> None:
        cls._plugins.insert(0, plugin_class())

    @classmethod
    def to_dc(cls, input_item: Any, table_name: str, format: Optional[str] = None,
              persist: bool = False, **kwargs) -> DataContainer:
        filepath = input_item if isinstance(input_item, str) else None
        for plugin in cls._plugins:
            try:
                matches = plugin.is_correct_input(input_item, table_name, format=format, **kwargs)
            except Exception:  # dsql: allow-broad-except — a plugin probe
                # declining (or crashing) just means "not my input type"
                matches = False
            if matches:
                from ..columnar import encodings

                # registration is THE load boundary: host->device column
                # conversions inside the plugin may pick a compressed
                # encoding (columnar/encodings.py) per `columnar.encoding`
                with encodings.load_scope():
                    dc = plugin.to_dc(input_item, table_name, format=format,
                                      persist=persist, **kwargs)
                dc.filepath = filepath  # plan-time pruning hook (DaskTable.filepath parity)
                return dc
        raise ValueError(f"Do not understand the input type {type(input_item)}")
