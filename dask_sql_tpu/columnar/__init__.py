from .column import Column
from .dtypes import SqlType, np_to_sql, parse_sql_type, promote, python_to_sql_type, similar_type, sql_to_np
from .encodings import Encoding
from .table import Table

__all__ = [
    "Column",
    "Encoding",
    "Table",
    "SqlType",
    "np_to_sql",
    "parse_sql_type",
    "promote",
    "python_to_sql_type",
    "similar_type",
    "sql_to_np",
]
