"""Sharded storage at registration: the `parallel.auto_shard` policy.

`create_table(..., distributed=True)` (and the `CREATE TABLE ... WITH
(distributed=...)` passthrough) has always sharded explicitly; this module
adds the POLICY layer: with ``parallel.auto_shard`` on, every eligible
registration row-shards over the default mesh automatically, so the SPMD
rungs fire for plain `create_table` calls without per-table opt-in.

Eligibility: a device-resident (non-lazy) table of at least
``parallel.auto_shard.min_rows`` rows, on a process whose default mesh has
two or more devices, that is not already sharded.  `shard_table` preserves
DICT/FOR encodings, so sharded storage keeps the compressed-domain wins —
exchanges move codes, not values.
"""
from __future__ import annotations

import logging

logger = logging.getLogger(__name__)


def truthy_option(value) -> bool:
    """Normalize a create_table kwarg that may arrive as a SQL WITH literal
    (bool, number, or string) — a string ``'false'`` must not shard."""
    if isinstance(value, str):
        return value.strip().lower() in ("true", "1", "on", "yes")
    return bool(value)


def auto_shard_enabled(config) -> bool:
    mode = str(config.get("parallel.auto_shard", "off")).lower()
    return mode in ("on", "auto", "true", "1")


def maybe_auto_shard(dc, config, metrics=None):
    """Apply the auto-shard policy to a freshly built DataContainer;
    returns the (possibly sharded) container.  Never raises: a sharding
    failure keeps the single-device registration (policy, not contract)."""
    if not auto_shard_enabled(config):
        return dc
    from ..datacontainer import LazyParquetContainer

    if isinstance(dc, LazyParquetContainer):
        return dc  # lazy scans keep IO pushdown; shard on materialization
    table = getattr(dc, "table", None)
    if table is None:
        return dc
    min_rows = int(config.get("parallel.auto_shard.min_rows", 32768) or 0)
    if table.num_rows < min_rows:
        return dc
    try:
        from ..parallel.dist_plan import table_is_sharded
        from ..parallel.distribute import shard_table
        from ..parallel.mesh import default_mesh

        if table_is_sharded(table):
            return dc
        mesh = default_mesh()
        if mesh.devices.size < 2:
            return dc
        dc.table = shard_table(table, mesh)
        if metrics is not None:
            metrics.inc("parallel.auto_shard.tables")
        logger.debug("auto-sharded registration over %d devices",
                     mesh.devices.size)
    except Exception:  # dsql: allow-broad-except — policy layer: a backend
        # without a mesh (or a mid-teardown runtime) keeps the registration
        # single-device rather than failing CREATE TABLE
        logger.warning("auto_shard failed; keeping single-device table",
                       exc_info=True)
    return dc
