"""Native C++ tokenizer vs Python lexer contract tests."""
import pytest


QUERIES = [
    "SELECT a, b FROM t WHERE x >= 1.5e3 AND s <> 'it''s' -- comment\nORDER BY 1",
    'SELECT "quoted col", `tick` FROM t /* block\ncomment */ LIMIT 5',
    "SELECT x::DOUBLE, a || b, c -> d FROM t WHERE y BETWEEN .5 AND 2.",
    "INSERT-free ; ? , ( ) [ ] { } : % ~",
    "SELECT ünïcode_cöl FROM täble",
]


@pytest.fixture(scope="module")
def native_available():
    from dask_sql_tpu.planner.native_bridge import get_lib

    lib = get_lib()
    if lib is None:
        pytest.skip("native library not built (g++ unavailable?)")
    return lib


@pytest.mark.parametrize("sql", QUERIES)
def test_token_stream_matches_python(native_available, sql):
    from dask_sql_tpu.planner.lexer import tokenize
    from dask_sql_tpu.planner.native_bridge import native_tokenize

    py_tokens = tokenize(sql)
    c_tokens = native_tokenize(sql)
    assert c_tokens is not None
    assert len(c_tokens) == len(py_tokens)
    for pt, ct in zip(py_tokens, c_tokens):
        assert pt.type == ct.type, (pt, ct)
        assert pt.value == ct.value, (pt, ct)


def test_error_positions_match(native_available):
    from dask_sql_tpu.planner.lexer import LexError, tokenize
    from dask_sql_tpu.planner.native_bridge import native_tokenize

    bad = "SELECT 'unterminated"
    with pytest.raises(LexError):
        tokenize(bad)
    with pytest.raises(LexError):
        native_tokenize(bad)


def test_parser_uses_native(native_available):
    from dask_sql_tpu.planner.parser import parse_sql

    stmts = parse_sql("SELECT 1 AS x")
    assert len(stmts) == 1
