"""Optimizer rule driver (parity: reference optimizer.rs rule list + observe
tracing, optimizer.rs:132-138)."""
from __future__ import annotations

import logging

logger = logging.getLogger(__name__)

_RULES = None


def _load_rules():
    global _RULES
    if _RULES is None:
        from . import rules

        # Order matters (parity: optimizer.rs:53-98)
        _RULES = [
            rules.SimplifyExpressions(),
            rules.UnwrapCastInComparison(),
            rules.DecorrelateSubqueries(),
            rules.SimplifyExpressions(),
            rules.RewriteDisjunctivePredicate(),
            rules.EliminateCrossJoin(),
            rules.EliminateLimit(),
            rules.FilterNullJoinKeys(),
            rules.EliminateOuterJoin(),
            rules.PushDownLimit(),
            rules.PushDownFilter(),
            rules.SimplifyExpressions(),
            rules.UnwrapCastInComparison(),
            rules.PushDownProjection(),
            rules.PushDownLimit(),
        ]
    return _RULES


def optimize_core(plan, config, catalog):
    """The structural rule loop (2 x 15 slots).  The native planner
    (native/binder.cpp Optimizer) runs this same loop in C++; this Python
    twin is the fallback and the differential-test reference."""
    rules = _load_rules()
    verbose = bool(config.get("sql.optimizer.verbose", False))
    # two passes: pushdowns expose new opportunities (e.g. cross-join
    # elimination after filters sink) — parity with the reference pipeline
    # repeating SimplifyExpressions/PushDownLimit (optimizer.rs:53-98)
    for _ in range(2):
        for rule in rules:
            new_plan = rule.apply(plan, config, catalog)
            if new_plan is not None:
                if verbose and new_plan is not plan:
                    logger.info("After %s:\n%s", type(rule).__name__, new_plan.explain())
                plan = new_plan
    return plan


def optimize_post(plan, config, catalog, context=None, skip_reorder=False):
    """Statistics/data-driven passes after the structural loop: join
    reordering (needs row counts; skipped when the native planner already
    reordered), dynamic partition pruning (reads data at plan time), and
    the embedded-subquery pipeline."""
    from . import join_reorder, rules

    if not skip_reorder:
        plan = join_reorder.maybe_reorder(plan, config, catalog)
    if config.get("sql.dynamic_partition_pruning", True):
        from . import dpp

        plan = dpp.apply(plan, config, catalog, context)
    # reorder/DPP introduce projections and filters of their own — prune again
    plan = rules.PushDownProjection().apply(plan, config, catalog)
    plan = _optimize_embedded_subqueries(plan, config, catalog, context)
    return plan


def optimize_plan(plan, config, catalog, context=None):
    plan = optimize_core(plan, config, catalog)
    return optimize_post(plan, config, catalog, context)


def _optimize_embedded_subqueries(plan, config, catalog, context):
    """Run the full pipeline on plans embedded INSIDE expressions.

    Uncorrelated subqueries that decorrelation leaves as runtime expressions
    (scalar subquery broadcast, IN/EXISTS probes) carry whole plan trees the
    node-walking rules never see — q23's max_store_sales CTE executed as a
    three-way CROSS join (182M rows at 1000-row scale) because its equijoin
    predicates were never pushed.  Correlated remnants (carrying _OuterRef,
    the reference-xfail shapes) are left untouched: pushdown's column
    remapping must not rewrite outer indices."""
    from dataclasses import replace as _dc_replace

    from ..binder import _OuterRef
    from ..expressions import (
        ExistsExpr,
        InSubqueryExpr,
        ScalarSubqueryExpr,
        transform,
        walk,
    )
    from . import rules as R

    def subplan_correlated(sub) -> bool:
        found = [False]

        def check(e):
            for x in walk(e):
                if isinstance(x, _OuterRef):
                    found[0] = True
                # walk() stops at expression boundaries — a correlated
                # remnant one subquery level deeper must also fence off
                # this whole subtree (its outer refs point into OUR schema)
                if (isinstance(x, (ScalarSubqueryExpr, InSubqueryExpr,
                                   ExistsExpr))
                        and getattr(x, "plan", None) is not None
                        and subplan_correlated(x.plan)):
                    found[0] = True
            return e

        def go(node):
            R._map_node_exprs(node, check)
            for k in node.inputs():
                go(k)

        go(sub)
        return found[0]

    def fix_expr(e):
        def fn(x):
            if (isinstance(x, (ScalarSubqueryExpr, InSubqueryExpr, ExistsExpr))
                    and getattr(x, "plan", None) is not None
                    and not subplan_correlated(x.plan)):
                new = optimize_plan(x.plan, config, catalog, context)
                if new is not x.plan:
                    return _dc_replace(x, plan=new)
            return x

        return transform(e, fn)

    def go(node):
        node = R._rewrite_children(node, go)
        return R._map_node_exprs(node, fix_expr)

    return go(plan)
